"""Benchmark of record: ResNet-50 training throughput, images/sec/chip.

Baseline (BASELINE.md): reference MXNet ResNet-50 train bs32 on K80 =
45.52 img/s (docs/faq/perf.md:146-180).  This benchmark runs the same
workload TPU-natively: one fused XLA train step (fwd+bwd+SGD update,
donated buffers) via parallel.ShardedTrainer, data resident in HBM,
bfloat16 activations/params with fp32 BN statistics (the TPU-native
precision recipe; set BENCH_DTYPE=float32 for strict fp32).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 45.52  # reference K80 bs32 (docs/faq/perf.md)


def main():
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    iters = int(os.environ.get("BENCH_ITERS", "100"))

    import jax
    import jax.numpy as jnp
    import mxnet_tpu  # noqa: F401  (enables x64 config, registers ops)
    from mxnet_tpu.models.resnet import get_symbol
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    devices = jax.devices()
    n_dev = len([d for d in devices if d.platform != "cpu"]) or 1
    sym = get_symbol(num_classes=1000, num_layers=50,
                     image_shape="3,224,224", dtype=dtype)
    spec = MeshSpec(make_mesh((n_dev,), ("dp",)))
    trainer = ShardedTrainer(sym, spec, lr=0.1, momentum=0.9, wd=1e-4,
                             param_dtype=dtype if dtype != "float32" else None)

    global_batch = batch * n_dev
    shapes = {"data": (global_batch, 3, 224, 224),
              "softmax_label": (global_batch,)}
    params, mom, aux = trainer.init_state(shapes)

    # data generated on device — the tunnel must not be in the loop
    key = jax.random.PRNGKey(0)
    data = jax.device_put(
        jax.random.uniform(key, (global_batch, 3, 224, 224), jnp.float32),
        spec.batch_sharding())
    label = jax.device_put(
        jax.random.randint(key, (global_batch,), 0, 1000).astype(jnp.float32),
        spec.batch_sharding())
    batch_dict = {"data": data, "softmax_label": label}

    from mxnet_tpu.parallel.trainer import sgd_step_fn
    step = sgd_step_fn(trainer)
    keys = trainer._keys()

    for _ in range(warmup):
        params, mom, aux, loss = step(params, mom, aux, batch_dict, keys)
    float(loss)  # full sync: block_until_ready alone does not drain the
    # remote-execution tunnel, giving impossibly fast (fake) timings

    t0 = time.perf_counter()
    for _ in range(iters):
        params, mom, aux, loss = step(params, mom, aux, batch_dict, keys)
    float(loss)  # end-of-chain sync; one tunnel round-trip amortized
    dt = time.perf_counter() - t0

    img_s = global_batch * iters / dt
    img_s_chip = img_s / n_dev
    print(json.dumps({
        "metric": "resnet50_train_img_per_sec_per_chip",
        "value": round(img_s_chip, 2),
        "unit": "images/sec/chip (bs%d, %s, %d chip%s)" % (
            batch, dtype, n_dev, "s" if n_dev > 1 else ""),
        "vs_baseline": round(img_s_chip / BASELINE_IMG_S, 2),
    }))


if __name__ == "__main__":
    main()
