"""Benchmark of record: ResNet-50 training throughput, images/sec/chip.

Baseline (BASELINE.md): reference MXNet ResNet-50 train bs32 on K80 =
45.52 img/s (docs/faq/perf.md:146-180).  This benchmark runs the same
workload TPU-natively: one fused XLA train step (fwd+bwd+SGD update,
donated buffers) via parallel.ShardedTrainer, data resident in HBM,
bfloat16 activations/params with fp32 BN statistics (the TPU-native
precision recipe; set BENCH_DTYPE=float32 for strict fp32).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

BENCH_IO=1 switches to the end-to-end mode: batches come from a RecordIO
file through the native C++ decode pipeline (native/record_iter.cc), host
decode + host->device transfer overlapped with device compute — the analog
of the reference's train_imagenet.py with ImageRecordIter.  Payload crosses
the wire as uint8 NCHW (the TPU-native recipe: normalize on device, not on
host) and the train step casts on device.
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 45.52  # reference K80 bs32 (docs/faq/perf.md)


def _ensure_bench_rec(n_images, hw):
    """Synthesize (once) a RecordIO dataset of random JPEGs for BENCH_IO."""
    import io as pyio
    from PIL import Image
    from mxnet_tpu import recordio
    prefix = os.environ.get(
        "BENCH_REC_PREFIX",
        "/tmp/mxnet_tpu_bench_%dx%d_%d" % (hw, hw, n_images))
    if os.path.isfile(prefix + ".rec") and os.path.isfile(prefix + ".idx"):
        return prefix
    rs = np.random.RandomState(0)
    # write to temp names, rename when complete: an interrupted run must
    # not leave a truncated dataset that later runs silently reuse
    tmp = prefix + ".part"
    w = recordio.MXIndexedRecordIO(tmp + ".idx", tmp + ".rec", "w")
    for i in range(n_images):
        arr = rs.randint(0, 256, (hw, hw, 3), dtype=np.uint8)
        buf = pyio.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=90)
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 1000), i, 0), buf.getvalue()))
    w.close()
    os.rename(tmp + ".rec", prefix + ".rec")
    os.rename(tmp + ".idx", prefix + ".idx")
    return prefix


def _transformer_flops_per_step(batch, seq, layers, hidden, vocab):
    """One true FLOPs/MFU formula, loaded from tools/bench_ideal.py so
    framework and ideal MFU can never drift apart."""
    import importlib.util
    here = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "bench_ideal_flops", os.path.join(here, "tools", "bench_ideal.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.transformer_flops_per_step(batch, seq, layers, hidden, vocab)


def _attach_phases(result, step, n_dev, step_time_s, tag):
    """Attribution phases block: roofline shares + MFU + report path in
    the bench JSON line, so every BENCH_* artifact is self-describing
    (telemetry/perf.py; needs the AOT-compiled step — BENCH_AUTO_LAYOUT=0
    skips it).  Never fails the bench."""
    try:
        # ungated ledger extra (same deal as peak_hbm_bytes): total jit
        # compile time this process paid, from the compile/ span family
        # — attached even when attribution is skipped below
        from mxnet_tpu.telemetry import tracing as _tracing
        cs = _tracing.compile_summary()
        if cs["count"]:
            result["phases"] = {"compile_seconds": cs["total_seconds"],
                                "compile_by_name": cs["by_name"]}
    except Exception:
        pass
    try:
        if not hasattr(step, "as_text"):
            return
        from mxnet_tpu.telemetry import perf as _perf
        rep = _perf.attribute_compiled(step, "bench.%s" % tag,
                                       n_devices=n_dev,
                                       measured_step_s=step_time_s)
        path = os.environ.get(
            "BENCH_ATTRIBUTION_PATH",
            "/tmp/mxnet_tpu_bench_attr_%s_%d.json" % (tag, os.getpid()))
        rep.save(path)
        result["phases"] = _perf.phases_block(rep, path)
    except Exception as e:
        result["phases"] = {"error": str(e)[:200]}
    try:
        # ungated ledger extra (same deal as peak_hbm_bytes): total jit
        # compile time this process paid, from the compile/ span family
        from mxnet_tpu.telemetry import tracing as _tracing
        cs = _tracing.compile_summary()
        if cs["count"]:
            result.setdefault("phases", {})
            if isinstance(result["phases"], dict):
                result["phases"]["compile_seconds"] = cs["total_seconds"]
                result["phases"]["compile_by_name"] = cs["by_name"]
    except Exception:
        pass


def _maybe_ledger(result):
    """BENCH_LEDGER=path: append this run to the benchwatch trajectory
    ledger (tools/benchwatch.py gates it in CI)."""
    path = os.environ.get("BENCH_LEDGER")
    if not path:
        return
    try:
        import importlib.util
        here = os.path.dirname(os.path.abspath(__file__))
        spec = importlib.util.spec_from_file_location(
            "benchwatch_feed", os.path.join(here, "tools", "benchwatch.py"))
        bw = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bw)
        bw.append_entry(path, bw.extract_metrics(result),
                        source="bench.py",
                        extra=bw.extract_extra(result) or None)
    except Exception as e:
        print("bench: ledger append failed: %s" % e, file=sys.stderr)


def _transformer_main(as_dict=False, batch=None, iters=None):
    """BENCH_MODEL=transformer: decoder-only LM training tokens/sec —
    the attention-path number of record (GPT-2-small-ish geometry by
    default: 12 layers, 768 hidden, 12 heads, T=1024).  Reports MFU
    against BENCH_PEAK_TFLOPS (default 197, TPU v5e bf16 peak)."""
    batch = batch or int(os.environ.get("BENCH_BATCH", "8"))
    seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
    layers = int(os.environ.get("BENCH_LAYERS", "12"))
    hidden = int(os.environ.get("BENCH_HIDDEN", "768"))
    heads = int(os.environ.get("BENCH_HEADS", "12"))
    vocab = int(os.environ.get("BENCH_VOCAB", "32768"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    iters = iters or int(os.environ.get("BENCH_ITERS", "30"))
    peak = float(os.environ.get("BENCH_PEAK_TFLOPS", "197")) * 1e12

    import jax
    import jax.numpy as jnp
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu.models.transformer import get_symbol
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    n_dev = len([d for d in jax.devices() if d.platform != "cpu"]) or 1
    sym = get_symbol(vocab_size=vocab, seq_len=seq_len,
                     num_layers=layers, hidden=hidden, heads=heads)
    spec = MeshSpec(make_mesh((n_dev,), ("dp",)))
    trainer = ShardedTrainer(sym, spec, lr=1e-4, momentum=0.9, wd=0.0,
                             param_dtype=dtype if dtype != "float32" else None)
    gb = batch * n_dev
    shapes = {"data": (gb, seq_len), "softmax_label": (gb, seq_len)}
    params, mom, aux = trainer.init_state(shapes)
    if os.environ.get("BENCH_AUTO_LAYOUT", "1") != "0":
        step, params, mom, aux = trainer.build_step_auto_layout(
            params, mom, aux, shapes)
    else:
        from mxnet_tpu.parallel.trainer import sgd_step_fn
        step = sgd_step_fn(trainer)
    keys = trainer._keys()
    guard = trainer._guard_arrays()
    key = jax.random.PRNGKey(0)
    data = jax.device_put(
        jax.random.randint(key, (gb, seq_len), 0, vocab)
        .astype(jnp.float32), spec.batch_sharding())
    label = jax.device_put(
        jax.random.randint(key, (gb, seq_len), 0, vocab)
        .astype(jnp.float32), spec.batch_sharding())
    batch_dict = {"data": data, "softmax_label": label}
    for _ in range(warmup):
        params, mom, aux, loss, _ok, guard = step(params, mom, aux, batch_dict, keys, guard)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, mom, aux, loss, _ok, guard = step(params, mom, aux, batch_dict, keys, guard)
    float(loss)
    dt = time.perf_counter() - t0
    tok_s = gb * seq_len * iters / dt / n_dev
    mfu = _transformer_flops_per_step(gb, seq_len, layers, hidden,
                                      vocab) * iters / dt / (peak * n_dev)
    result = {
        "metric": "transformer_train_tokens_per_sec_per_chip",
        "value": round(tok_s, 2),
        "mfu": round(mfu, 4),
        "unit": "tokens/sec/chip (L%d H%d T%d bs%d, %s)" % (
            layers, hidden, seq_len, batch, dtype),
        "vs_baseline": None,
    }
    _attach_phases(result, step, n_dev, dt / iters, "transformer")
    if as_dict:
        return result
    print(json.dumps(result))


def _recommender_main(as_dict=False):
    """BENCH_MODEL=recommender: DLRM-style criteo-toy click predictor —
    the sparse-at-scale number of record.  Categorical features hit
    mesh-sharded embedding tables through the routed lookup
    (mxnet_tpu/sparse: all-to-all bytes ~ touched rows, tables
    row-sharded over dp), dense features run the MLP, and the tables
    take the touched-rows-only lazy SGD.  Geometry knobs:
    BENCH_REC_TABLES/VOCAB/EMBED_DIM/DENSE, batch via BENCH_BATCH.
    MXNET_TPU_PALLAS_EMBED picks the shard-local kernel backend (unset:
    the autotune-cache winner)."""
    batch = int(os.environ.get("BENCH_BATCH", "4096"))
    n_tables = int(os.environ.get("BENCH_REC_TABLES", "4"))
    vocab = int(os.environ.get("BENCH_REC_VOCAB", "100000"))
    dim = int(os.environ.get("BENCH_REC_EMBED_DIM", "16"))
    dense_dim = int(os.environ.get("BENCH_REC_DENSE", "13"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))

    import jax
    import jax.numpy as jnp
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.sparse import (ShardedEmbedding, make_recommender_step,
                                  recommender_state,
                                  step_alltoall_model_bytes)

    devices = jax.devices()
    n_dev = len([d for d in devices if d.platform != "cpu"]) or 1
    platform = devices[0].platform
    spec = MeshSpec(make_mesh((n_dev,), ("dp",)))
    gb = batch * n_dev
    embs = [ShardedEmbedding(vocab, dim, spec, name="table%d" % f)
            for f in range(n_tables)]
    state = recommender_state(embs, dense_dim=dense_dim,
                              hidden=(64, 32), seed=0)
    step = make_recommender_step(embs, lr=0.05, momentum=0.9)
    key = jax.random.PRNGKey(0)
    bat = spec.batch_sharding()
    from jax.sharding import NamedSharding, PartitionSpec as P
    ids = jax.device_put(
        jax.random.randint(key, (n_tables, gb), 0, vocab, jnp.int32),
        NamedSharding(spec.mesh, P(None, "dp")))
    dense = jax.device_put(
        jax.random.uniform(key, (gb, dense_dim), jnp.float32), bat)
    label = jax.device_put(
        (jax.random.uniform(key, (gb,)) > 0.5).astype(jnp.float32), bat)
    feed = {"ids": ids, "dense": dense, "label": label}
    for _ in range(warmup):
        state, loss = step(state, feed)
    float(loss)   # full sync (bench methodology: drain the tunnel)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step(state, feed)
    float(loss)
    dt = time.perf_counter() - t0
    ex_s = gb * iters / dt / n_dev
    a2a = n_tables * step_alltoall_model_bytes(gb, dim, n_dev)
    result = {
        "metric": "recommender_train_examples_per_sec_per_chip",
        "value": round(ex_s, 2),
        "unit": "examples/sec/chip (%d tables x %dx%d, dense %d, bs%d, "
                "%d %s dev%s)" % (n_tables, vocab, dim, dense_dim, batch,
                                  n_dev, platform,
                                  "s" if n_dev > 1 else ""),
        "vs_baseline": None,
        "embedding": {
            "tables": n_tables, "vocab": vocab, "dim": dim,
            "table_mb_total": round(
                sum(e.table_bytes for e in embs) / 1e6, 2),
            "alltoall_model_bytes_per_step": a2a,
            "backend": embs[0].backend or "auto",
        },
    }
    _attach_phases(result, step, n_dev, dt / iters, "recommender")
    if as_dict:
        return result
    print(json.dumps(result))


def _decode_main(as_dict=False):
    """BENCH_MODEL=decode: interactive decode steady-state — tokens/sec/
    chip of the paged-KV continuous-batching step (mxnet_tpu/serving/
    decode) with every slot occupied mid-sequence, the regime a loaded
    interactive fleet runs in.  Geometry knobs BENCH_DECODE_{LAYERS,
    HIDDEN,HEADS,VOCAB,SEQ,SLOTS,PAGE,QUANT}; MXNET_TPU_PALLAS_DECODE
    picks the attention backend.  The continuous-vs-static batching
    comparison lives in tools/servebench.py --decode."""
    layers = int(os.environ.get("BENCH_DECODE_LAYERS", "4"))
    hidden = int(os.environ.get("BENCH_DECODE_HIDDEN", "256"))
    heads = int(os.environ.get("BENCH_DECODE_HEADS", "8"))
    vocab = int(os.environ.get("BENCH_DECODE_VOCAB", "2048"))
    seq = int(os.environ.get("BENCH_DECODE_SEQ", "256"))
    slots = int(os.environ.get("BENCH_DECODE_SLOTS", "8"))
    page = int(os.environ.get("BENCH_DECODE_PAGE", "16"))
    quant = os.environ.get("BENCH_DECODE_QUANT") or None
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    iters = int(os.environ.get("BENCH_ITERS", "50"))

    import jax
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu.analysis.costmodel import decode_step_model
    from mxnet_tpu.serving.decode import (DecodeConfig, DecodeProgram,
                                          init_decode_params)

    devices = jax.devices()
    n_dev = len([d for d in devices if d.platform != "cpu"]) or 1
    platform = devices[0].platform
    cfg = DecodeConfig(vocab, layers, hidden, heads, seq, page_size=page,
                       max_seqs=slots, quantize=quant)
    prog = DecodeProgram(init_decode_params(cfg, seed=0), cfg,
                         name="bench")
    prog.ensure_compiled()
    kv = prog.fresh_cache()
    pp = cfg.pages_per_seq
    table = np.zeros((slots, pp), np.int32)
    for s in range(slots):
        table[s] = 1 + s * pp + np.arange(pp)
    rs = np.random.RandomState(0)
    # steady state: every slot mid-sequence (half the context cached)
    base = seq // 2
    toks = rs.randint(0, vocab, slots).astype(np.int32)
    t_host = 0.0

    def one(kv, pos):
        positions = np.full(slots, pos, np.int32)
        nxt, _lg, kv = prog.step(
            kv, toks, positions, positions + 1,
            table[np.arange(slots), pos // page],
            np.full(slots, pos % page, np.int32), table)
        return nxt, kv
    pos = base
    for _ in range(warmup):
        nxt, kv = one(kv, pos)
        pos += 1
    jax.block_until_ready(nxt)
    t0 = time.perf_counter()
    for _ in range(iters):
        nxt, kv = one(kv, pos)
        pos += 1
    jax.block_until_ready(nxt)
    dt = time.perf_counter() - t0
    tok_s = slots * iters / dt / n_dev
    model = decode_step_model(
        layers, hidden, vocab, slots, slots * base,
        quant_bits={"int8": 8, "int4": 4}.get(quant, 32))
    result = {
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(tok_s, 2),
        "unit": "tokens/sec/chip (L%d H%d heads%d V%d T%d S%d page%d%s, "
                "%d %s dev%s)" % (layers, hidden, heads, vocab, seq,
                                  slots, page,
                                  " %s" % quant if quant else "",
                                  n_dev, platform,
                                  "s" if n_dev > 1 else ""),
        "vs_baseline": None,
        "decode": {
            "step_ms": round(dt / iters * 1e3, 4),
            "cached_tokens": slots * base,
            "quantize": quant,
            "compiles": prog.trace_count,
            "model_hbm_bytes_per_step": int(model["hbm_bytes"]),
            "model_weight_bytes": int(model["weight_bytes"]),
        },
    }
    # the toy decode program's jit time deliberately does NOT ride the
    # phases block: phases.compile_seconds is the GATED trainer-compile
    # series, and a different program class would poison its trajectory
    try:
        from mxnet_tpu.telemetry import tracing as _tracing
        cs = _tracing.compile_summary()
        if cs["count"]:
            result["decode"]["compile_seconds"] = cs["total_seconds"]
    except Exception:
        pass
    if as_dict:
        return result
    print(json.dumps(result))


def main():
    model = os.environ.get("BENCH_MODEL", "resnet50")
    if model == "transformer":
        result = _transformer_main(as_dict=True)
        _maybe_ledger(result)
        print(json.dumps(result))
        return
    if model == "decode":
        result = _decode_main(as_dict=True)
        _maybe_ledger(result)
        print(json.dumps(result))
        return
    if model == "recommender":
        result = _recommender_main(as_dict=True)
        _maybe_ledger(result)
        print(json.dumps(result))
        return
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    iters = int(os.environ.get("BENCH_ITERS", "100"))
    layout = os.environ.get("BENCH_LAYOUT", "NCHW")  # NHWC: channels-last path
    if layout not in ("NCHW", "NHWC"):
        raise SystemExit("BENCH_LAYOUT must be NCHW or NHWC, got %r" % layout)
    if layout == "NHWC" and os.environ.get("BENCH_IO", "0") == "1":
        raise SystemExit("BENCH_IO=1 decodes NCHW batches; combine with "
                         "BENCH_LAYOUT=NCHW (default)")

    import jax
    import jax.numpy as jnp
    import mxnet_tpu  # noqa: F401  (enables x64 config, registers ops)
    from mxnet_tpu.models.resnet import get_symbol
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    devices = jax.devices()
    n_dev = len([d for d in devices if d.platform != "cpu"]) or 1
    sym = get_symbol(num_classes=1000, num_layers=50,
                     image_shape="3,224,224", dtype=dtype, layout=layout)
    spec = MeshSpec(make_mesh((n_dev,), ("dp",)))
    trainer = ShardedTrainer(sym, spec, lr=0.1, momentum=0.9, wd=1e-4,
                             param_dtype=dtype if dtype != "float32" else None)

    global_batch = batch * n_dev
    data_shape = (global_batch, 224, 224, 3) if layout == "NHWC" \
        else (global_batch, 3, 224, 224)
    shapes = {"data": data_shape, "softmax_label": (global_batch,)}
    params, mom, aux = trainer.init_state(shapes)

    io_mode = os.environ.get("BENCH_IO", "0") == "1"
    if os.environ.get("BENCH_AUTO_LAYOUT", "1") != "0":
        # compiler-chosen parameter layouts: kills the per-step layout
        # copies on NCHW/OIHW weights (see build_step_auto_layout).
        # The AOT executable is dtype-exact: the IO path feeds uint8.
        step, params, mom, aux = trainer.build_step_auto_layout(
            params, mom, aux, shapes,
            input_dtypes={"data": jnp.uint8} if io_mode else None)
    else:
        from mxnet_tpu.parallel.trainer import sgd_step_fn
        step = sgd_step_fn(trainer)
    keys = trainer._keys()
    guard = trainer._guard_arrays()
    if not io_mode:
        # data generated on device — the tunnel must not be in the loop
        key = jax.random.PRNGKey(0)
        data = jax.device_put(
            jax.random.uniform(key, data_shape, jnp.float32),
            spec.batch_sharding())
        label = jax.device_put(
            jax.random.randint(key, (global_batch,), 0,
                               1000).astype(jnp.float32),
            spec.batch_sharding())
        batch_dict = {"data": data, "softmax_label": label}
    if io_mode:
        # End-to-end RecordIO mode.  Tunnel characteristics (measured):
        # a device_put issued while compute is in flight drains the whole
        # dispatch queue (~200ms), and per-index python slicing recompiles.
        # So: feed in CHUNKS — decode K batches on the host (native OMP
        # pipeline, overlapped with device compute on the previous chunk),
        # sync once, ship ONE uint8 superbatch, then dole out batches with
        # a single jitted dynamic-slice program.
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from mxnet_tpu.io.native import NativeRecordIter
        n_images = int(os.environ.get("BENCH_IO_IMAGES", "2048"))
        prefix = _ensure_bench_rec(n_images, 224)
        threads = int(os.environ.get("BENCH_IO_THREADS",
                                     str(os.cpu_count() or 8)))
        chunk = int(os.environ.get("BENCH_IO_CHUNK", "16"))
        rec_iter = NativeRecordIter(
            prefix + ".rec", (3, 224, 224), global_batch,
            idx_path=prefix + ".idx", threads=threads, shuffle=True,
            rand_mirror=True, prefetch=chunk + 2)
        # superbatch layout (K, global_batch, ...): batch axis dp-sharded so
        # pick hands each step a batch already laid out like the synthetic
        # path (spec.batch_sharding())
        x_shard = NamedSharding(spec.mesh, P(None, "dp"))

        @jax.jit
        def pick(X, L, i):
            return (lax.dynamic_index_in_dim(X, i, 0, keepdims=False),
                    lax.dynamic_index_in_dim(L, i, 0, keepdims=False))

        def decode_chunk(n):
            ds, ls = [], []
            for _ in range(n):
                try:
                    d, l, _ = rec_iter.next()
                except StopIteration:
                    rec_iter.reset()
                    d, l, _ = rec_iter.next()
                ds.append(d.astype(np.uint8))
                ls.append(l[:, 0].copy())
            return np.stack(ds), np.stack(ls)

        def run_epochs(n_iters, params, mom, aux):
            # Double-buffered: while the device steps through chunk N, the
            # host decodes chunk N+1 (native OMP queue) and ships it.  On
            # this dev tunnel the shipping is the bottleneck (h2d collapses
            # to ~20MB/s once a large program has run — see PERF.md); on a
            # real TPU-VM host (PCIe DMA) the same loop is decode-bound.
            nonlocal guard
            if n_iters <= 0:
                return params, mom, aux
            done = 0
            host = decode_chunk(min(chunk, n_iters))
            loss = None
            while done < n_iters:
                if loss is not None:
                    float(loss)     # drain: puts contend badly with
                    # in-flight compute on the tunnel
                X = jax.device_put(host[0], x_shard)
                L = jax.device_put(host[1], x_shard)
                todo = host[0].shape[0]
                for i in range(todo):
                    d, l = pick(X, L, jnp.int32(i))
                    params, mom, aux, loss, _ok, guard = step(
                        params, mom, aux,
                        {"data": d, "softmax_label": l}, keys, guard)
                done += todo
                if done < n_iters:
                    # overlaps device compute
                    host = decode_chunk(min(chunk, n_iters - done))
            float(loss)
            return params, mom, aux

        params, mom, aux = run_epochs(warmup, params, mom, aux)
        t0 = time.perf_counter()
        params, mom, aux = run_epochs(iters, params, mom, aux)
        dt = time.perf_counter() - t0
    else:
        for _ in range(warmup):
            params, mom, aux, loss, _ok, guard = step(params, mom, aux, batch_dict, keys, guard)
        float(loss)  # full sync: block_until_ready alone does not drain the
        # remote-execution tunnel, giving impossibly fast (fake) timings

        t0 = time.perf_counter()
        for _ in range(iters):
            params, mom, aux, loss, _ok, guard = step(params, mom, aux, batch_dict, keys, guard)
        float(loss)  # end-of-chain sync; one tunnel round-trip amortized
        dt = time.perf_counter() - t0

    img_s = global_batch * iters / dt
    img_s_chip = img_s / n_dev
    result = {
        "metric": "resnet50_train_img_per_sec_per_chip" +
                  ("_io" if io_mode else ""),
        "value": round(img_s_chip, 2),
        "unit": "images/sec/chip (bs%d, %s, %s, %d chip%s%s)" % (
            batch, dtype, layout, n_dev, "s" if n_dev > 1 else "",
            ", RecordIO+native decode in loop" if io_mode else ""),
        "vs_baseline": round(img_s_chip / BASELINE_IMG_S, 2),
    }
    _attach_phases(result, step, n_dev, dt / iters, "resnet50")
    if not io_mode and os.environ.get("BENCH_TRANSFORMER", "1") != "0":
        # attention-path number of record, captured in the same artifact.
        # Runs in a fresh subprocess: HBM must start empty (the resident
        # ResNet state would skew or OOM the LM step), and the ResNet
        # BENCH_BATCH/BENCH_ITERS knobs must not leak into LM geometry.
        import subprocess
        env = dict(os.environ, BENCH_MODEL="transformer")
        # the LM subprocess must not inherit ResNet geometry knobs, the
        # parent's attribution path (it has its own), or the ledger (the
        # parent appends ONE entry carrying both metrics)
        for knob in ("BENCH_BATCH", "BENCH_ITERS", "BENCH_WARMUP",
                     "BENCH_ATTRIBUTION_PATH", "BENCH_LEDGER"):
            env.pop(knob, None)
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True,
                           timeout=1800)
        try:
            result["transformer"] = json.loads(
                r.stdout.strip().splitlines()[-1])
        except Exception:
            result["transformer"] = {
                "error": (r.stderr.strip().splitlines() or ["no output"])
                [-1][:200]}
    _maybe_ledger(result)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
