#include "image_decode.h"

#include <cstdio>
#include <jpeglib.h>

#include <algorithm>
#include <csetjmp>
#include <cstring>

namespace mxt {

namespace {
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

inline uint64_t xorshift(uint64_t* s) {
  uint64_t x = *s;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  *s = x;
  return x;
}
}  // namespace

bool DecodeJPEG(const uint8_t* data, size_t len, std::vector<uint8_t>* out,
                int* height, int* width, int* channels) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  int h = cinfo.output_height;
  int w = cinfo.output_width;
  int c = cinfo.output_components;
  out->resize((size_t)h * w * c);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() + (size_t)cinfo.output_scanline * w * c;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *height = h;
  *width = w;
  *channels = c;
  return true;
}

void ResizeBilinear(const uint8_t* src, int sh, int sw, int c, uint8_t* dst,
                    int dh, int dw) {
  const float sy = (float)sh / dh;
  const float sx = (float)sw / dw;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = std::max(0, (int)fy);
    int y1 = std::min(sh - 1, y0 + 1);
    float wy = fy - y0;
    if (wy < 0) wy = 0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = std::max(0, (int)fx);
      int x1 = std::min(sw - 1, x0 + 1);
      float wx = fx - x0;
      if (wx < 0) wx = 0;
      for (int ch = 0; ch < c; ++ch) {
        float v00 = src[((size_t)y0 * sw + x0) * c + ch];
        float v01 = src[((size_t)y0 * sw + x1) * c + ch];
        float v10 = src[((size_t)y1 * sw + x0) * c + ch];
        float v11 = src[((size_t)y1 * sw + x1) * c + ch];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[((size_t)y * dw + x) * c + ch] = (uint8_t)(v + 0.5f);
      }
    }
  }
}

bool DecodeAugment(const uint8_t* jpeg, size_t len, const AugmentParams& p,
                   float* out, uint64_t* rng_state) {
  std::vector<uint8_t> img;
  int h, w, c;
  if (!DecodeJPEG(jpeg, len, &img, &h, &w, &c)) return false;

  std::vector<uint8_t> resized;
  if (p.resize_short > 0) {
    int nh, nw;
    if (h < w) {
      nh = p.resize_short;
      nw = (int)((int64_t)w * p.resize_short / h);
    } else {
      nw = p.resize_short;
      nh = (int)((int64_t)h * p.resize_short / w);
    }
    resized.resize((size_t)nh * nw * c);
    ResizeBilinear(img.data(), h, w, c, resized.data(), nh, nw);
    img.swap(resized);
    h = nh;
    w = nw;
  }

  // crop to out_h x out_w (random or center); resize if too small
  int ch_ = p.out_h, cw_ = p.out_w;
  std::vector<uint8_t> crop((size_t)ch_ * cw_ * c);
  if (h < ch_ || w < cw_) {
    ResizeBilinear(img.data(), h, w, c, crop.data(), ch_, cw_);
  } else {
    int y0, x0;
    if (p.rand_crop) {
      y0 = (int)(xorshift(rng_state) % (uint64_t)(h - ch_ + 1));
      x0 = (int)(xorshift(rng_state) % (uint64_t)(w - cw_ + 1));
    } else {
      y0 = (h - ch_) / 2;
      x0 = (w - cw_) / 2;
    }
    for (int y = 0; y < ch_; ++y) {
      std::memcpy(crop.data() + (size_t)y * cw_ * c,
                  img.data() + ((size_t)(y + y0) * w + x0) * c,
                  (size_t)cw_ * c);
    }
  }

  bool mirror = p.rand_mirror && (xorshift(rng_state) & 1);

  // HWC uint8 -> CHW float32 normalised
  for (int ch2 = 0; ch2 < c && ch2 < 3; ++ch2) {
    float mean = p.mean[ch2];
    float stdv = p.std[ch2] != 0 ? p.std[ch2] : 1.0f;
    float* dst = out + (size_t)ch2 * ch_ * cw_;
    for (int y = 0; y < ch_; ++y) {
      for (int x = 0; x < cw_; ++x) {
        int sx2 = mirror ? (cw_ - 1 - x) : x;
        dst[(size_t)y * cw_ + x] =
            (crop[((size_t)y * cw_ + sx2) * c + ch2] - mean) / stdv;
      }
    }
  }
  return true;
}

}  // namespace mxt
