// RecordIO container format — binary-compatible with the reference
// (dmlc-core recordio + src/io/image_recordio.h IRHeader).
//
// Frame: u32 magic 0xced7230a, u32 lrec (upper 3 bits continuation flag,
// lower 29 length), payload, zero-pad to 4-byte alignment.
// IRHeader: u32 flag, f32 label, u64 id, u64 id2 (little-endian), followed
// by flag*4 bytes of extra float labels when flag > 0.
#pragma once

#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

namespace mxt {

constexpr uint32_t kRecordMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

#pragma pack(push, 1)
struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};
#pragma pack(pop)

class RecordReader {
 public:
  explicit RecordReader(const std::string& path);
  ~RecordReader();
  // Read next record payload into `out`; returns false at EOF.
  bool Next(std::vector<uint8_t>* out);
  // Random access: seek to byte offset.
  void Seek(uint64_t pos);
  uint64_t Tell() const;
  void Reset();
  bool ok() const { return fp_ != nullptr; }

 private:
  FILE* fp_;
};

class RecordWriter {
 public:
  explicit RecordWriter(const std::string& path);
  ~RecordWriter();
  // Returns the byte offset the record was written at.
  uint64_t Write(const uint8_t* data, size_t len);
  bool ok() const { return fp_ != nullptr; }

 private:
  FILE* fp_;
};

// Parse .idx file (key \t offset per line).
bool LoadIndex(const std::string& idx_path, std::vector<uint64_t>* keys,
               std::vector<uint64_t>* offsets);

}  // namespace mxt
