// JPEG decode + minimal augmentation — the native hot loop of the data
// pipeline (reference: src/io/iter_image_recordio_2.cc:138-171, OpenCV
// decode under OMP; here libjpeg + hand-rolled bilinear resize).
#pragma once

#include <cstdint>
#include <cstddef>
#include <vector>

namespace mxt {

// Decode JPEG bytes to RGB HWC uint8.  Returns false on failure.
bool DecodeJPEG(const uint8_t* data, size_t len, std::vector<uint8_t>* out,
                int* height, int* width, int* channels);

// Bilinear resize HWC uint8.
void ResizeBilinear(const uint8_t* src, int sh, int sw, int c, uint8_t* dst,
                    int dh, int dw);

struct AugmentParams {
  int out_h = 224;
  int out_w = 224;
  int resize_short = 0;   // resize shorter edge first if > 0
  bool rand_crop = false;
  bool rand_mirror = false;
  float mean[3] = {0, 0, 0};
  float std[3] = {1, 1, 1};
};

// Decode + augment into float32 CHW at `out` (size c*out_h*out_w).
// `rng_state` is a per-thread xorshift state for crop/mirror draws.
bool DecodeAugment(const uint8_t* jpeg, size_t len, const AugmentParams& p,
                   float* out, uint64_t* rng_state);

}  // namespace mxt
