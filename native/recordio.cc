#include "recordio.h"

#include <cstring>

namespace mxt {

RecordReader::RecordReader(const std::string& path) {
  fp_ = std::fopen(path.c_str(), "rb");
}

RecordReader::~RecordReader() {
  if (fp_) std::fclose(fp_);
}

bool RecordReader::Next(std::vector<uint8_t>* out) {
  uint32_t head[2];
  if (std::fread(head, 4, 2, fp_) != 2) return false;
  if (head[0] != kRecordMagic) return false;
  uint32_t n = head[1] & kLenMask;
  out->resize(n);
  if (n && std::fread(out->data(), 1, n, fp_) != n) return false;
  uint32_t pad = (4 - n % 4) % 4;
  if (pad) std::fseek(fp_, pad, SEEK_CUR);
  return true;
}

void RecordReader::Seek(uint64_t pos) { std::fseek(fp_, (long)pos, SEEK_SET); }
uint64_t RecordReader::Tell() const { return (uint64_t)std::ftell(fp_); }
void RecordReader::Reset() { std::fseek(fp_, 0, SEEK_SET); }

RecordWriter::RecordWriter(const std::string& path) {
  fp_ = std::fopen(path.c_str(), "wb");
}

RecordWriter::~RecordWriter() {
  if (fp_) std::fclose(fp_);
}

uint64_t RecordWriter::Write(const uint8_t* data, size_t len) {
  uint64_t pos = (uint64_t)std::ftell(fp_);
  uint32_t head[2] = {kRecordMagic, (uint32_t)(len & kLenMask)};
  std::fwrite(head, 4, 2, fp_);
  std::fwrite(data, 1, len, fp_);
  static const uint8_t zeros[4] = {0, 0, 0, 0};
  uint32_t pad = (4 - len % 4) % 4;
  if (pad) std::fwrite(zeros, 1, pad, fp_);
  return pos;
}

bool LoadIndex(const std::string& idx_path, std::vector<uint64_t>* keys,
               std::vector<uint64_t>* offsets) {
  FILE* f = std::fopen(idx_path.c_str(), "r");
  if (!f) return false;
  unsigned long long k, off;
  while (std::fscanf(f, "%llu\t%llu", &k, &off) == 2) {
    keys->push_back(k);
    offsets->push_back(off);
  }
  std::fclose(f);
  return true;
}

}  // namespace mxt
