// im2rec — pack an image list into a RecordIO file (+ .idx).
//
// Reference: tools/im2rec.cc (OpenCV + dmlc recordio).  This version packs
// encoded JPEG bytes directly (optional decode+resize+re-encode path via
// libjpeg), multi-threaded with OpenMP.
//
// Usage: im2rec <prefix.lst> <image_root> <output_prefix> [resize=0]
//        [quality=95] [num_thread=4]
// .lst line: index \t label[ \t label...] \t relative_path
#include <cstdio>
#include <jpeglib.h>
#include <omp.h>

#include <cstring>
#include <fstream>
#include <iostream>
#include <sstream>
#include <string>
#include <vector>

#include "image_decode.h"
#include "recordio.h"

namespace {

struct ListEntry {
  uint64_t index;
  std::vector<float> labels;
  std::string path;
};

bool ReadList(const std::string& path, std::vector<ListEntry>* out) {
  std::ifstream fin(path);
  if (!fin) return false;
  std::string line;
  while (std::getline(fin, line)) {
    if (line.empty()) continue;
    std::istringstream ss(line);
    std::vector<std::string> toks;
    std::string tok;
    while (std::getline(ss, tok, '\t')) toks.push_back(tok);
    if (toks.size() < 3) continue;
    ListEntry e;
    e.index = std::stoull(toks[0]);
    for (size_t i = 1; i + 1 < toks.size(); ++i)
      e.labels.push_back(std::stof(toks[i]));
    e.path = toks.back();
    out->push_back(std::move(e));
  }
  return true;
}

bool ReadFile(const std::string& path, std::vector<uint8_t>* out) {
  std::ifstream fin(path, std::ios::binary);
  if (!fin) return false;
  fin.seekg(0, std::ios::end);
  out->resize((size_t)fin.tellg());
  fin.seekg(0);
  fin.read(reinterpret_cast<char*>(out->data()), out->size());
  return true;
}

bool EncodeJPEG(const uint8_t* rgb, int h, int w, int quality,
                std::vector<uint8_t>* out) {
  jpeg_compress_struct cinfo;
  jpeg_error_mgr jerr;
  cinfo.err = jpeg_std_error(&jerr);
  jpeg_create_compress(&cinfo);
  unsigned char* mem = nullptr;
  unsigned long mem_size = 0;
  jpeg_mem_dest(&cinfo, &mem, &mem_size);
  cinfo.image_width = w;
  cinfo.image_height = h;
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, quality, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  while (cinfo.next_scanline < cinfo.image_height) {
    JSAMPROW row = const_cast<uint8_t*>(rgb + (size_t)cinfo.next_scanline * w * 3);
    jpeg_write_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_compress(&cinfo);
  out->assign(mem, mem + mem_size);
  jpeg_destroy_compress(&cinfo);
  free(mem);
  return true;
}

std::vector<uint8_t> PackRecord(const ListEntry& e,
                                const std::vector<uint8_t>& img) {
  mxt::IRHeader hdr;
  hdr.id = e.index;
  hdr.id2 = 0;
  std::vector<uint8_t> payload;
  if (e.labels.size() == 1) {
    hdr.flag = 0;
    hdr.label = e.labels[0];
  } else {
    hdr.flag = (uint32_t)e.labels.size();
    hdr.label = 0;
    payload.resize(e.labels.size() * 4);
    std::memcpy(payload.data(), e.labels.data(), payload.size());
  }
  std::vector<uint8_t> rec(sizeof(hdr) + payload.size() + img.size());
  std::memcpy(rec.data(), &hdr, sizeof(hdr));
  std::memcpy(rec.data() + sizeof(hdr), payload.data(), payload.size());
  std::memcpy(rec.data() + sizeof(hdr) + payload.size(), img.data(),
              img.size());
  return rec;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4) {
    std::cerr << "Usage: im2rec <prefix.lst> <image_root> <output_prefix> "
                 "[resize=0] [quality=95] [num_thread=4]\n";
    return 1;
  }
  std::string lst_path = argv[1];
  std::string root = argv[2];
  std::string out_prefix = argv[3];
  int resize = argc > 4 ? std::stoi(argv[4]) : 0;
  int quality = argc > 5 ? std::stoi(argv[5]) : 95;
  int threads = argc > 6 ? std::stoi(argv[6]) : 4;

  std::vector<ListEntry> entries;
  if (!ReadList(lst_path, &entries)) {
    std::cerr << "cannot read list " << lst_path << "\n";
    return 1;
  }
  mxt::RecordWriter writer(out_prefix + ".rec");
  std::ofstream idx(out_prefix + ".idx");

  const int chunk = 256;
  size_t done = 0;
  for (size_t start = 0; start < entries.size(); start += chunk) {
    size_t n = std::min((size_t)chunk, entries.size() - start);
    std::vector<std::vector<uint8_t>> recs(n);
    #pragma omp parallel for num_threads(threads) schedule(dynamic)
    for (int i = 0; i < (int)n; ++i) {
      const ListEntry& e = entries[start + i];
      std::vector<uint8_t> img;
      if (!ReadFile(root + "/" + e.path, &img)) continue;
      if (resize > 0) {
        std::vector<uint8_t> decoded;
        int h, w, c;
        if (mxt::DecodeJPEG(img.data(), img.size(), &decoded, &h, &w, &c) &&
            c == 3) {
          int nh, nw;
          if (h < w) {
            nh = resize;
            nw = (int)((int64_t)w * resize / h);
          } else {
            nw = resize;
            nh = (int)((int64_t)h * resize / w);
          }
          std::vector<uint8_t> resized((size_t)nh * nw * 3);
          mxt::ResizeBilinear(decoded.data(), h, w, 3, resized.data(), nh, nw);
          EncodeJPEG(resized.data(), nh, nw, quality, &img);
        }
      }
      recs[i] = PackRecord(e, img);
    }
    for (size_t i = 0; i < n; ++i) {
      if (recs[i].empty()) continue;
      uint64_t pos = writer.Write(recs[i].data(), recs[i].size());
      idx << entries[start + i].index << "\t" << pos << "\n";
    }
    done += n;
    if (done % 4096 < chunk)
      std::cerr << "packed " << done << "/" << entries.size() << "\n";
  }
  std::cerr << "done: " << entries.size() << " records\n";
  return 0;
}
