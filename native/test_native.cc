// Native-plane unit tests (the reference's tests/cpp tier, assert-based —
// no gtest in this image).  Covers the RecordIO container (framing,
// alignment, random access, index parsing) and the resize kernel.
//
// Build & run:  make -C native build/test_native && ./native/build/test_native
#undef NDEBUG   // the asserts ARE the test; never compile them away
#include <cassert>
#include <cstdlib>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "image_decode.h"
#include "recordio.h"

static std::string TmpPath(const char* name) {
  const char* dir = getenv("TMPDIR");
  return std::string(dir ? dir : "/tmp") + "/" + name;
}

static void TestHeaderLayout() {
  // binary compatibility: IRHeader is 24 packed bytes
  static_assert(sizeof(mxt::IRHeader) == 24, "IRHeader must pack to 24B");
}

static void TestRecordRoundtrip() {
  std::string path = TmpPath("mxt_test_rec.rec");
  // sizes hitting every 4-byte alignment phase, incl. empty
  std::vector<std::vector<uint8_t>> payloads;
  for (size_t len : {0u, 1u, 2u, 3u, 4u, 5u, 127u, 4096u}) {
    std::vector<uint8_t> p(len);
    for (size_t i = 0; i < len; ++i) p[i] = (uint8_t)(i * 31 + len);
    payloads.push_back(p);
  }
  std::vector<uint64_t> offsets;
  {
    mxt::RecordWriter w(path);
    assert(w.ok());
    for (auto& p : payloads)
      offsets.push_back(w.Write(p.data(), p.size()));
  }
  {
    mxt::RecordReader r(path);
    assert(r.ok());
    std::vector<uint8_t> buf;
    for (auto& want : payloads) {
      assert(r.Next(&buf));
      assert(buf == want);
    }
    assert(!r.Next(&buf));   // EOF
    // random access via recorded offsets, reverse order
    for (int i = (int)payloads.size() - 1; i >= 0; --i) {
      r.Seek(offsets[i]);
      assert(r.Next(&buf));
      assert(buf == payloads[i]);
    }
    r.Reset();
    assert(r.Next(&buf) && buf == payloads[0]);
  }
  std::remove(path.c_str());
}

static void TestCorruptMagicRejected() {
  std::string path = TmpPath("mxt_test_bad.rec");
  FILE* f = fopen(path.c_str(), "wb");
  uint32_t bad = 0xdeadbeef, len = 4, body = 0;
  fwrite(&bad, 4, 1, f);
  fwrite(&len, 4, 1, f);
  fwrite(&body, 4, 1, f);
  fclose(f);
  mxt::RecordReader r(path);
  std::vector<uint8_t> buf;
  assert(!r.Next(&buf));   // bad magic must read as end-of-stream, not data
  std::remove(path.c_str());
}

static void TestLoadIndex() {
  std::string path = TmpPath("mxt_test.idx");
  FILE* f = fopen(path.c_str(), "w");
  fprintf(f, "0\t0\n7\t128\n42\t4096\n");
  fclose(f);
  std::vector<uint64_t> keys, offs;
  assert(mxt::LoadIndex(path, &keys, &offs));
  assert(keys.size() == 3 && offs.size() == 3);
  assert(keys[1] == 7 && offs[1] == 128);
  assert(keys[2] == 42 && offs[2] == 4096);
  std::remove(path.c_str());
}

static void TestResizeBilinear() {
  // constant image stays constant at any scale
  std::vector<uint8_t> src(8 * 6 * 3, 77), dst(16 * 12 * 3, 0);
  mxt::ResizeBilinear(src.data(), 8, 6, 3, dst.data(), 16, 12);
  for (uint8_t v : dst) assert(v == 77);
  // identity resize is a copy
  std::vector<uint8_t> ramp(4 * 4 * 3), same(4 * 4 * 3);
  for (size_t i = 0; i < ramp.size(); ++i) ramp[i] = (uint8_t)i;
  mxt::ResizeBilinear(ramp.data(), 4, 4, 3, same.data(), 4, 4);
  assert(same == ramp);
}

static void TestDecodeGarbageFails() {
  std::vector<uint8_t> junk(64, 0x5a);
  std::vector<uint8_t> out;
  int h, w, c;
  assert(!mxt::DecodeJPEG(junk.data(), junk.size(), &out, &h, &w, &c));
}

int main() {
  TestHeaderLayout();
  TestRecordRoundtrip();
  TestCorruptMagicRejected();
  TestLoadIndex();
  TestResizeBilinear();
  TestDecodeGarbageFails();
  printf("native unit tests: OK\n");
  return 0;
}
