// Threaded RecordIO image-batch loader, exported with a C ABI consumed by
// Python via ctypes (mxnet_tpu/io/native.py).
//
// Pipeline shape mirrors the reference (src/io/iter_image_recordio_2.cc +
// iter_prefetcher.h): a producer reads record frames, an OpenMP loop
// decodes+augments JPEGs into pinned float batches, and a bounded queue of
// ready batches feeds the consumer.  This is the host-side hot loop that
// keeps the TPU fed.
#include <omp.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include "image_decode.h"
#include "recordio.h"

namespace mxt {

struct Batch {
  std::vector<float> data;
  std::vector<float> label;
  int pad = 0;
};

class RecordBatchIter {
 public:
  RecordBatchIter(const std::string& rec_path, const std::string& idx_path,
                  int batch_size, int c, int h, int w, int label_width,
                  int threads, bool shuffle, uint64_t seed,
                  const AugmentParams& aug, int prefetch, int part_index,
                  int num_parts)
      : reader_(rec_path), batch_size_(batch_size), c_(c), h_(h), w_(w),
        label_width_(label_width), threads_(threads > 0 ? threads : 1),
        shuffle_(shuffle), rng_(seed), aug_(aug),
        prefetch_(prefetch > 0 ? prefetch : 2) {
    if (!idx_path.empty()) {
      has_index_ = LoadIndex(idx_path, &keys_, &offsets_);
      if (num_parts > 1 && has_index_) {
        // same partition policy as the python path: contiguous equal
        // slices of the index order, remainder dropped.  A slice can be
        // EMPTY (num_parts > #records); that must mean "no data", never a
        // fallback to sequentially reading the whole file.
        if (part_index < 0 || part_index >= num_parts) {
          valid_ = false;
          keys_.clear();
          offsets_.clear();
        } else {
          size_t n = offsets_.size() / (size_t)num_parts;
          size_t lo = (size_t)part_index * n;
          std::vector<uint64_t> part_keys(keys_.begin() + lo,
                                          keys_.begin() + lo + n);
          std::vector<uint64_t> part_offs(offsets_.begin() + lo,
                                          offsets_.begin() + lo + n);
          keys_.swap(part_keys);
          offsets_.swap(part_offs);
        }
      }
    }
    Reset();
  }

  ~RecordBatchIter() { Stop(); }

  bool ok() const { return reader_.ok() && valid_; }

  void Reset() {
    Stop();
    if (!offsets_.empty()) {
      order_.resize(offsets_.size());
      for (size_t i = 0; i < order_.size(); ++i) order_[i] = i;
      if (shuffle_) {
        std::shuffle(order_.begin(), order_.end(), rng_);
      }
    }
    cursor_ = 0;
    reader_.Reset();
    done_ = false;
    stop_ = false;
    producer_ = std::thread([this] { ProducerLoop(); });
  }

  // Copies the next batch into caller buffers. Returns pad (>=0), or -1 at
  // epoch end.
  int Next(float* data_out, float* label_out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_pop_.wait(lk, [this] { return !queue_.empty() || done_; });
    if (queue_.empty()) return -1;
    Batch b = std::move(queue_.front());
    queue_.pop_front();
    cv_push_.notify_one();
    lk.unlock();
    std::memcpy(data_out, b.data.data(), b.data.size() * sizeof(float));
    std::memcpy(label_out, b.label.data(), b.label.size() * sizeof(float));
    return b.pad;
  }

 private:
  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_push_.notify_all();
    cv_pop_.notify_all();
    if (producer_.joinable()) producer_.join();
    queue_.clear();
  }

  bool ReadRaw(std::vector<uint8_t>* out) {
    if (has_index_) {
      if (cursor_ >= order_.size()) return false;
      reader_.Seek(offsets_[order_[cursor_++]]);
      return reader_.Next(out);
    }
    return reader_.Next(out);
  }

  void ProducerLoop() {
    const size_t img_elems = (size_t)c_ * h_ * w_;
    while (true) {
      // gather raw records for one batch
      std::vector<std::vector<uint8_t>> raws;
      raws.reserve(batch_size_);
      for (int i = 0; i < batch_size_; ++i) {
        std::vector<uint8_t> r;
        if (!ReadRaw(&r)) break;
        raws.push_back(std::move(r));
      }
      if (raws.empty()) break;
      Batch b;
      b.data.assign((size_t)batch_size_ * img_elems, 0.f);
      b.label.assign((size_t)batch_size_ * label_width_, 0.f);
      b.pad = batch_size_ - (int)raws.size();

      // the OMP hot loop: parallel decode + augment
      #pragma omp parallel for num_threads(threads_) schedule(dynamic)
      for (int i = 0; i < (int)raws.size(); ++i) {
        const auto& raw = raws[i];
        if (raw.size() < sizeof(IRHeader)) continue;
        IRHeader hdr;
        std::memcpy(&hdr, raw.data(), sizeof(IRHeader));
        const uint8_t* payload = raw.data() + sizeof(IRHeader);
        size_t plen = raw.size() - sizeof(IRHeader);
        if (hdr.flag > 0) {
          size_t lbytes = (size_t)hdr.flag * 4;
          for (int j = 0; j < label_width_ && j < (int)hdr.flag; ++j) {
            float lv;
            std::memcpy(&lv, payload + j * 4, 4);
            b.label[(size_t)i * label_width_ + j] = lv;
          }
          payload += lbytes;
          plen -= lbytes;
        } else {
          b.label[(size_t)i * label_width_] = hdr.label;
        }
        uint64_t rng = 0x9e3779b97f4a7c15ULL ^ ((uint64_t)seed_ctr_ + i);
        DecodeAugment(payload, plen, aug_, b.data.data() + (size_t)i * img_elems,
                      &rng);
      }
      ++seed_ctr_;
      // fill pad slots by repeating
      for (int j = (int)raws.size(); j < batch_size_; ++j) {
        int src = j % (int)raws.size();
        std::memcpy(b.data.data() + (size_t)j * img_elems,
                    b.data.data() + (size_t)src * img_elems,
                    img_elems * sizeof(float));
        std::memcpy(b.label.data() + (size_t)j * label_width_,
                    b.label.data() + (size_t)src * label_width_,
                    label_width_ * sizeof(float));
      }
      std::unique_lock<std::mutex> lk(mu_);
      cv_push_.wait(lk, [this] {
        return queue_.size() < (size_t)prefetch_ || stop_;
      });
      if (stop_) return;
      queue_.push_back(std::move(b));
      cv_pop_.notify_one();
    }
    std::lock_guard<std::mutex> lk(mu_);
    done_ = true;
    cv_pop_.notify_all();
  }

  RecordReader reader_;
  bool has_index_ = false;
  bool valid_ = true;
  std::vector<uint64_t> keys_, offsets_;
  std::vector<size_t> order_;
  size_t cursor_ = 0;
  int batch_size_, c_, h_, w_, label_width_, threads_;
  bool shuffle_;
  std::mt19937_64 rng_;
  AugmentParams aug_;
  int prefetch_;
  uint64_t seed_ctr_ = 0;

  std::thread producer_;
  std::mutex mu_;
  std::condition_variable cv_push_, cv_pop_;
  std::deque<Batch> queue_;
  bool done_ = false;
  bool stop_ = false;
};

}  // namespace mxt

// ----------------------------------------------------------------------
// C ABI (consumed via ctypes)
// ----------------------------------------------------------------------
extern "C" {

void* MXTRecordIterCreate(const char* rec_path, const char* idx_path,
                          int batch_size, int c, int h, int w,
                          int label_width, int threads, int shuffle,
                          unsigned long long seed, int resize_short,
                          int rand_crop, int rand_mirror, const float* mean,
                          const float* stdv, int prefetch, int part_index,
                          int num_parts) {
  mxt::AugmentParams aug;
  aug.out_h = h;
  aug.out_w = w;
  aug.resize_short = resize_short;
  aug.rand_crop = rand_crop != 0;
  aug.rand_mirror = rand_mirror != 0;
  for (int i = 0; i < 3; ++i) {
    if (mean) aug.mean[i] = mean[i];
    if (stdv) aug.std[i] = stdv[i];
  }
  auto* it = new mxt::RecordBatchIter(rec_path, idx_path ? idx_path : "",
                                      batch_size, c, h, w, label_width,
                                      threads, shuffle != 0, seed, aug,
                                      prefetch, part_index, num_parts);
  if (!it->ok()) {
    delete it;
    return nullptr;
  }
  return it;
}

int MXTRecordIterNext(void* handle, float* data_out, float* label_out) {
  return static_cast<mxt::RecordBatchIter*>(handle)->Next(data_out,
                                                          label_out);
}

void MXTRecordIterReset(void* handle) {
  static_cast<mxt::RecordBatchIter*>(handle)->Reset();
}

void MXTRecordIterFree(void* handle) {
  delete static_cast<mxt::RecordBatchIter*>(handle);
}

// Standalone decode helper (for tests / tools).
int MXTDecodeJPEG(const unsigned char* buf, size_t len, unsigned char* out,
                  int out_capacity, int* h, int* w, int* c) {
  std::vector<uint8_t> img;
  if (!mxt::DecodeJPEG(buf, len, &img, h, w, c)) return -1;
  if ((int)img.size() > out_capacity) return -2;
  std::memcpy(out, img.data(), img.size());
  return (int)img.size();
}
}
