"""Expert parallelism: a switch-MoE FFN layer trained over the 'ep' mesh
axis — tokens routed to their top-1 expert with one lax.all_to_all each
way (SURVEY §2.3 expert-parallelism row; new TPU-native work).

Run with 8 virtual devices:  JAX_PLATFORMS=cpu python switch_ffn.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
# must happen BEFORE the backend initializes (probing jax.default_backend
# or jax.devices first would lock in a single CPU device)
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:   # pre-0.4.34 jax: only XLA_FLAGS works
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8")

import jax.numpy as jnp
import numpy as np

from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.parallel.moe import moe_ffn


def main():
    n_dev = len(jax.devices())
    mesh = make_mesh((n_dev,), ("ep",))
    E, d, hidden = n_dev, 16, 32
    tokens = n_dev * 16
    rs = np.random.RandomState(0)

    # regression target: each token's output should match a fixed rotation
    x = jnp.asarray(rs.normal(0, 1, (tokens, d)).astype(np.float32))
    target = jnp.roll(x, 1, axis=1)

    params = {
        "wg": jnp.asarray(rs.normal(0, 0.5, (d, E)).astype(np.float32)),
        "w1": jnp.asarray(rs.normal(0, 0.3, (E, d, hidden)).astype(np.float32)),
        "w2": jnp.asarray(rs.normal(0, 0.3, (E, hidden, d)).astype(np.float32)),
    }

    def loss_fn(p):
        out, aux = moe_ffn(x, p["wg"], p["w1"], p["w2"], mesh,
                           capacity_factor=2.0)
        return jnp.mean((x + out - target) ** 2) + 0.01 * aux

    step = jax.jit(jax.grad(loss_fn))
    first = float(loss_fn(params))
    lr = 0.3
    for _ in range(150):
        g = step(params)
        params = {k: v - lr * g[k] for k, v in params.items()}
    last = float(loss_fn(params))
    print("loss: %.4f -> %.4f over %d experts / %d devices"
          % (first, last, E, n_dev))
    assert last < first * 0.5, (first, last)
    print("switch_ffn example OK")


if __name__ == "__main__":
    main()
