"""Sorting with a bidirectional LSTM (reference example/bi-lstm-sort/
role): read a sequence of symbols, emit the same symbols sorted — a
sequence-to-sequence-aligned task only solvable with BOTH directions
visible, exercising BidirectionalCell + per-step heads.

CI bar: >= 0.95 per-position accuracy on held-out sequences.

Run: python example/bi_lstm_sort/bi_lstm_sort.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

VOCAB, SEQ, HIDDEN = 8, 6, 64


def get_symbol():
    sym = mx.sym
    data = sym.Variable("data")                       # (N, SEQ)
    emb = sym.Embedding(data, input_dim=VOCAB, output_dim=16, name="emb")
    cell = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(HIDDEN, prefix="f_"),
        mx.rnn.LSTMCell(HIDDEN, prefix="b_"))
    outputs, _ = cell.unroll(SEQ, emb, layout="NTC", merge_outputs=True)
    pred = sym.FullyConnected(outputs, num_hidden=VOCAB, flatten=False,
                              name="head")            # (N, SEQ, VOCAB)
    pred = sym.Reshape(pred, shape=(-1, VOCAB))
    label = sym.Reshape(sym.Variable("softmax_label"), shape=(-1,))
    return sym.SoftmaxOutput(pred, label, name="softmax")


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    n = 1024
    data = rs.randint(0, VOCAB, (n, SEQ)).astype(np.float32)
    label = np.sort(data, axis=1)
    n_tr = 896
    it_tr = mx.io.NDArrayIter(data[:n_tr], label[:n_tr], batch_size=64,
                              shuffle=True, label_name="softmax_label")
    it_va = mx.io.NDArrayIter(data[n_tr:], label[n_tr:], batch_size=64,
                              label_name="softmax_label")

    def seq_acc(label, pred):
        return float((pred.argmax(1) == label.ravel()).mean())

    metric = mx.metric.np(seq_acc, name="seq_acc")
    mod = mx.mod.Module(get_symbol(), context=mx.context.current_context())
    mod.fit(it_tr, num_epoch=15, optimizer="adam",
            optimizer_params={"learning_rate": 3e-3},
            initializer=mx.init.Xavier(), eval_metric=metric)
    acc = dict(mod.score(it_va, mx.metric.np(seq_acc,
                                             name="seq_acc")))["seq_acc"]
    print("held-out per-position sort accuracy: %.3f" % acc)
    assert acc >= 0.95, acc
    print("bi_lstm_sort example OK")


if __name__ == "__main__":
    main()
