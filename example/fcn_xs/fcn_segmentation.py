"""Fully-convolutional segmentation (reference example/fcn-xs/ role,
CI-sized): conv encoder downsamples 4x, Deconvolution (transposed conv,
bilinear-initialized like the reference fcn-xs init scheme) upsamples
back to full resolution, per-pixel SoftmaxOutput (multi_output) trains
the mask.

Synthetic scenes: bright squares and dark discs on noise; each pixel
labeled background/square/disc.  CI bar: >= 0.9 held-out mean pixel
accuracy.

Run: python example/fcn_xs/fcn_segmentation.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

HW = 32
CLASSES = 3            # bg / square / disc


def synthetic_scene(rs):
    img = rs.uniform(0, 0.15, (3, HW, HW)).astype(np.float32)
    mask = np.zeros((HW, HW), np.float32)
    for cls in (1, 2):
        size = rs.randint(HW // 4, HW // 2)
        x = rs.randint(0, HW - size)
        y = rs.randint(0, HW - size)
        if cls == 1:
            img[:, y:y + size, x:x + size] += 0.7
            mask[y:y + size, x:x + size] = 1
        else:
            yy, xx = np.mgrid[0:size, 0:size]
            disc = ((yy - size / 2) ** 2 + (xx - size / 2) ** 2
                    <= (size / 2) ** 2)
            img[:, y:y + size, x:x + size] -= 0.5 * disc
            mask[y:y + size, x:x + size] = np.where(
                disc, 2, mask[y:y + size, x:x + size])
    return img, mask


def get_symbol():
    sym = mx.sym
    data = sym.Variable("data")
    body = sym.Activation(
        sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=24,
                        name="conv1"), act_type="relu")
    body = sym.Pooling(body, kernel=(2, 2), stride=(2, 2), pool_type="max")
    body = sym.Activation(
        sym.Convolution(body, kernel=(3, 3), pad=(1, 1), num_filter=48,
                        name="conv2"), act_type="relu")
    body = sym.Pooling(body, kernel=(2, 2), stride=(2, 2), pool_type="max")
    score = sym.Convolution(body, kernel=(1, 1), num_filter=CLASSES,
                            name="score")
    # 4x transposed-conv upsample back to full resolution (fcn-xs
    # bigscore layer; weights bilinear-initialized below)
    up = sym.Deconvolution(score, kernel=(8, 8), stride=(4, 4), pad=(2, 2),
                           num_filter=CLASSES, num_group=CLASSES,
                           no_bias=True, name="bigscore")
    return sym.SoftmaxOutput(up, multi_output=True, use_ignore=True,
                             ignore_label=-1, normalization="valid",
                             name="softmax")


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    n, batch = 96, 8
    scenes = [synthetic_scene(rs) for _ in range(n)]
    data = np.stack([i for i, _ in scenes])
    masks = np.stack([m for _, m in scenes])
    n_tr = 80
    it_tr = mx.io.NDArrayIter(data[:n_tr], masks[:n_tr], batch_size=batch,
                              shuffle=True, label_name="softmax_label")
    it_va = mx.io.NDArrayIter(data[n_tr:], masks[n_tr:], batch_size=batch,
                              label_name="softmax_label")

    mod = mx.mod.Module(get_symbol(), context=mx.context.current_context())
    mod.fit(it_tr, num_epoch=50, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.init.Mixed(
                ["bigscore_weight", ".*"],
                [mx.init.Bilinear(), mx.init.Xavier()]),
            eval_metric="acc")

    acc = dict(mod.score(it_va, "acc"))["accuracy"]
    print("held-out mean pixel accuracy: %.3f" % acc)
    assert acc >= 0.9, acc
    print("fcn_segmentation example OK")


if __name__ == "__main__":
    main()
