"""Profiling + monitoring a training run (reference example/profiler
role): dump a Chrome trace of op/executor/batch events, then use the
per-node Monitor to locate a NaN-producing layer.
"""
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import profiler


def main():
    rs = np.random.RandomState(0)
    x = rs.rand(64, 16).astype(np.float32)
    y = rs.randint(0, 2, 64).astype(np.float32)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    with tempfile.TemporaryDirectory() as d:
        trace = os.path.join(d, "profile.json")
        profiler.set_config(filename=trace)
        profiler.set_state("run")
        mod = mx.mod.Module(net, context=mx.cpu())
        it = mx.io.NDArrayIter(x, y, batch_size=16,
                               label_name="softmax_label")
        mod.fit(it, num_epoch=2, optimizer_params={"learning_rate": 0.1})
        profiler.set_state("stop")
        profiler.dump_profile()
        with open(trace) as f:
            events = json.load(f)["traceEvents"]
        cats = {e["cat"] for e in events}
        print("trace: %d events, categories %s" % (len(events), sorted(cats)))
        assert "batch" in cats and "symbolic" in cats

    # Monitor: find the layer where NaNs are born
    bad = mx.sym.Variable("data")
    bad = mx.sym.FullyConnected(bad, num_hidden=4, name="fc1")
    bad = mx.sym.log(bad, name="badlog")          # negatives -> NaN
    bad = mx.sym.FullyConnected(bad, num_hidden=2, name="fc2")

    def nan_stat(arr):
        return mx.nd.array([float(np.isnan(arr.asnumpy()).any())])

    mon = mx.mon.Monitor(interval=1, stat_func=nan_stat, monitor_all=True)
    ex = bad.simple_bind(mx.cpu(), data=(4, 16))
    for arr in ex.arg_arrays:
        arr[:] = mx.nd.array(rs.normal(0, 1, arr.shape))
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=True)
    nan_layers = [k for _, k, v in mon.toc() if v.strip().startswith("1")]
    print("NaN first appears at:", nan_layers[0])
    assert nan_layers[0] == "badlog_output"
    print("profile_mlp example OK")


if __name__ == "__main__":
    main()
