"""Manual layer placement across devices with ctx_group (reference
example/model-parallel role): the first half of an MLP runs on device 0,
the second half on device 1; the executor segments the graph and chains
per-segment forward/backward with cross-device transfers.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
# must happen BEFORE the backend initializes (probing jax.default_backend
# or jax.devices first would lock in a single CPU device)
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:   # pre-0.4.34 jax: only XLA_FLAGS works
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=2")

import numpy as np

import mxnet_tpu as mx


def main():
    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="stage1"):
        h = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
    with mx.AttrScope(ctx_group="stage2"):
        h = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
        net = mx.sym.SoftmaxOutput(h, name="softmax")

    rs = np.random.RandomState(0)
    x = rs.normal(0, 1, (32, 16)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.float32)

    mod = mx.mod.Module(net, context=mx.cpu(0),
                        group2ctxs={"stage1": mx.cpu(0),
                                    "stage2": mx.cpu(1)})
    it = mx.io.NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
    mod.fit(it, num_epoch=25, initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.5})

    devs = mod._exec_group.execs[0].ctx_group_devices
    print("segments on devices:", devs)
    assert devs is not None and len(devs) == 2 and devs[0] is not devs[1]

    metric = mx.metric.Accuracy()
    mod.score(it, metric)
    acc = dict(metric.get_name_value())["accuracy"]
    print("accuracy: %.3f" % acc)
    assert acc > 0.9, acc
    print("model_parallel two_stage example OK")


if __name__ == "__main__":
    main()
