"""Dense-Sparse-Dense training (reference example/dsd/ role): train
dense, prune the smallest half of each weight matrix to exact zero and
retrain with the mask re-applied after every UPDATE (a batch-end
callback zeroes the pruned slots, so the sparse phase genuinely trains
under the mask), then restore dense training from the sparse solution —
the DSD regularization schedule (Han et al. 2016).

CI bars: the sparse phase must hold >= 50% exact zeros while still
classifying (>= 0.9), and the final re-densified model must be at least
as accurate as the first dense pass on held-out real digit scans
(within 0.5 points, the run-to-run wobble of the 397-sample val set).

Run: python example/dsd/dsd_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

SPARSITY = 0.5


def get_symbol():
    sym = mx.sym
    net = sym.Variable("data")
    net = sym.Activation(sym.FullyConnected(net, num_hidden=64,
                                            name="fc1"), act_type="relu")
    net = sym.FullyConnected(net, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def fit_phase(mod, it, epochs, masks=None):
    """One training phase; with masks (name -> 0/1 array), a batch-end
    callback zeroes the pruned weight slots after EVERY update."""
    def reapply(_param):
        args, auxs = mod.get_params()
        pruned = {n: (mx.nd.array(a.asnumpy() * masks[n])
                      if n in masks else a)
                  for n, a in args.items()}
        mod.set_params(pruned, auxs)

    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.init.Xavier(), force_init=False,
            force_rebind=False, eval_metric="acc",
            batch_end_callback=reapply if masks else None)


def accuracy(mod, it):
    return dict(mod.score(it, "acc"))["accuracy"]


def main():
    mx.random.seed(0)
    np.random.seed(0)
    from sklearn.datasets import load_digits
    raw = load_digits()
    x = (raw.images.astype(np.float32) / 16.0).reshape(len(raw.target), -1)
    y = raw.target.astype(np.float32)
    order = np.random.RandomState(8).permutation(len(y))
    x, y = x[order], y[order]
    n_tr = 1400
    it_tr = mx.io.NDArrayIter(x[:n_tr], y[:n_tr], batch_size=64,
                              shuffle=True, label_name="softmax_label")
    it_va = mx.io.NDArrayIter(x[n_tr:], y[n_tr:], batch_size=64,
                              label_name="softmax_label")

    mod = mx.mod.Module(get_symbol(), context=mx.context.current_context())

    # D: dense
    fit_phase(mod, it_tr, 10)
    dense_acc = accuracy(mod, it_va)

    # S: prune the smallest |w| half per matrix, retrain masked
    args, _ = mod.get_params()
    masks = {}
    for name in ("fc1_weight", "fc2_weight"):
        w = args[name].asnumpy()
        cut = np.quantile(np.abs(w), SPARSITY)
        masks[name] = (np.abs(w) > cut).astype(np.float32)
    fit_phase(mod, it_tr, 10, masks=masks)
    sparse_acc = accuracy(mod, it_va)
    args, _ = mod.get_params()
    zero_frac = float(np.mean([
        (args[n].asnumpy() == 0).mean() for n in masks]))

    # D: release the mask, retrain dense from the sparse solution
    fit_phase(mod, it_tr, 10)
    final_acc = accuracy(mod, it_va)

    print("dense %.3f -> sparse %.3f (%.0f%% zeros) -> re-dense %.3f"
          % (dense_acc, sparse_acc, 100 * zero_frac, final_acc))
    assert zero_frac >= 0.5, zero_frac
    assert sparse_acc >= 0.9, sparse_acc
    assert final_acc >= dense_acc - 0.005, (dense_acc, final_acc)
    print("dsd_digits example OK")


if __name__ == "__main__":
    main()
