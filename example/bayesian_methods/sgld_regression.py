"""Bayesian inference with SGLD (reference example/bayesian-methods/
sgld.ipynb role, CI-sized): Stochastic Gradient Langevin Dynamics as a
USER-REGISTERED custom optimizer (mx.optimizer.register — the public
extension point), sampling the posterior of a small regression net on
heteroscedastic data.

The posterior predictive from averaged SGLD samples must (a) match the
data as well as point-SGD and (b) show calibrated uncertainty: the
predictive std must be at least 2x larger in the data-free gap region
than in the densely observed region.

Run: python example/bayesian_methods/sgld_regression.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


@mx.optimizer.register
class SGLDToy(mx.optimizer.Optimizer):
    """Langevin dynamics: w <- w - lr/2 * grad + N(0, lr).

    The injected noise turns SGD into a posterior sampler (Welling &
    Teh 2011); after burn-in, iterates are approximate posterior draws.
    """

    def __init__(self, seed=7, **kwargs):
        super().__init__(**kwargs)
        self._rs = np.random.RandomState(seed)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        noise = mx.nd.array(
            self._rs.normal(0, np.sqrt(lr), weight.shape)
            .astype(np.float32))
        weight[:] = weight - (lr / 2.0) * g + noise


def make_data(rs, n=400):
    """Two dense clusters with a gap in the middle."""
    x = np.concatenate([rs.uniform(-3, -1, n // 2),
                        rs.uniform(1, 3, n // 2)])
    y = np.sin(x) + 0.1 * x ** 2 + rs.normal(0, 0.1, x.shape)
    return x.astype(np.float32)[:, None], y.astype(np.float32)[:, None]


def net():
    sym = mx.sym
    body = sym.Activation(sym.FullyConnected(sym.Variable("data"),
                                             num_hidden=32, name="fc1"),
                          act_type="tanh")
    body = sym.FullyConnected(body, num_hidden=1, name="fc2")
    return sym.LinearRegressionOutput(body, sym.Variable("target"),
                                      name="reg")


def main():
    mx.random.seed(0)
    np.random.seed(0)   # NDArrayIter(shuffle=True) uses the global RNG
    rs = np.random.RandomState(0)
    x, y = make_data(rs)

    batch_size = 50
    it = mx.io.NDArrayIter(x, {"target": y}, batch_size=batch_size,
                           shuffle=True)
    mod = mx.mod.Module(net(), label_names=("target",),
                        context=mx.context.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    # posterior scaling: the batch loss-head gradient is a SUM over the
    # minibatch, so the full-data likelihood gradient is ~(N/batch) x
    # that; wd acts as the Gaussian prior precision
    mod.init_optimizer(optimizer="sgldtoy",
                       optimizer_params={"learning_rate": 5e-5,
                                         "wd": 1e-2,
                                         "rescale_grad": len(x) / batch_size})

    grid = np.linspace(-3, 3, 61).astype(np.float32)[:, None]
    samples = []
    step = 0
    for epoch in range(240):
        it.reset()
        # 240 epoch resets over a 100-row in-memory array: a prefetch
        # thread per reset costs more than the fetch it would overlap
        for batch in it:        # tpulint: disable=SL108
            mod.forward_backward(batch)
            mod.update()
            step += 1
        if epoch >= 120 and epoch % 5 == 0:     # thinned post-burn-in draws
            git = mx.io.NDArrayIter(
                grid, {"target": np.zeros((len(grid), 1), np.float32)},
                batch_size=batch_size)
            pred = mod.predict(git).asnumpy()
            samples.append(pred[:len(grid), 0])
    bank = np.stack(samples)                     # (S, 61)

    mean = bank.mean(0)
    std = bank.std(0)
    dense = (np.abs(grid[:, 0]) > 1.2)
    gap = (np.abs(grid[:, 0]) < 0.8)
    fit_mse = float(np.mean(
        (mean[dense] - (np.sin(grid[dense, 0])
                        + 0.1 * grid[dense, 0] ** 2)) ** 2))
    ratio = float(std[gap].mean() / std[dense].mean())
    print("posterior-mean MSE on observed region %.4f; "
          "gap/dense uncertainty ratio %.2f (%d samples)"
          % (fit_mse, ratio, len(bank)))
    assert fit_mse <= 0.05, fit_mse
    assert ratio >= 2.0, ratio
    print("sgld_regression example OK")


if __name__ == "__main__":
    main()
