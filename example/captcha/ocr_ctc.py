"""Captcha-style OCR (reference example/captcha/ role): variable-length
digit strings rendered as ONE image (real bundled scanned digits
composed side by side), read by a conv encoder whose column features
feed CTCLoss — image-to-sequence transcription with no per-character
segmentation labels.

CI bar: greedy CTC decoding must exactly transcribe >= 80% of held-out
strings.

Run: python example/captcha/ocr_ctc.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

MAX_DIGITS = 4
CELL = 8                       # each scanned digit is 8x8
WIDTH = MAX_DIGITS * CELL      # fixed canvas, right-padded
CLASSES = 11                   # blank + digits 0-9 (labels 1..10)


def build_strings(rs, images, targets, n):
    rows = []
    for _ in range(n):
        k = rs.randint(2, MAX_DIGITS + 1)
        picks = rs.randint(0, len(targets), k)
        canvas = np.zeros((CELL, WIDTH), np.float32)
        for j, p in enumerate(picks):
            canvas[:, j * CELL:(j + 1) * CELL] = images[p]
        label = np.zeros(MAX_DIGITS, np.float32)
        label[:k] = targets[picks] + 1          # 1-based; 0 = blank/pad
        rows.append((canvas[None], label))
    return (np.stack([c for c, _ in rows]),
            np.stack([l for _, l in rows]))


def get_symbol():
    sym = mx.sym
    data = sym.Variable("data")                 # (N, 1, CELL, WIDTH)
    body = sym.Activation(
        sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=16,
                        name="conv1"), act_type="relu")
    body = sym.Activation(
        sym.Convolution(body, kernel=(3, 3), pad=(1, 1), num_filter=32,
                        name="conv2"), act_type="relu")
    # pool the height away entirely; width (reading order) survives
    body = sym.Pooling(body, kernel=(CELL, 1), pool_type="max")
    # (N, C, 1, W) -> per-column feature sequence (N, W, C)
    seq = sym.transpose(sym.Reshape(body, shape=(0, 0, -1)),
                        axes=(0, 2, 1))
    cell = mx.rnn.BidirectionalCell(mx.rnn.LSTMCell(48, prefix="readf_"),
                                    mx.rnn.LSTMCell(48, prefix="readb_"))
    outputs, _ = cell.unroll(WIDTH, seq, layout="NTC", merge_outputs=True)
    head = sym.FullyConnected(outputs, num_hidden=CLASSES, flatten=False,
                              name="head")
    acts = sym.swapaxes(head, dim1=0, dim2=1)   # (T, N, C)
    ctc = sym.MakeLoss(sym.CTCLoss(acts, sym.Variable("label"),
                                   name="ctc"), name="ctc_loss")
    probs = sym.BlockGrad(sym.softmax(head, axis=-1), name="frame_probs")
    return mx.sym.Group([ctc, probs])


def greedy_decode(prob_tc):
    path = prob_tc.argmax(-1)
    out, prev = [], -1
    for p in path:
        if p != prev and p != 0:
            out.append(int(p))
        prev = p
    return out


def main():
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    from sklearn.datasets import load_digits
    raw = load_digits()
    images = raw.images.astype(np.float32) / 16.0
    data, labels = build_strings(rs, images, raw.target, 1024)
    n_tr = 896

    it = mx.io.NDArrayIter(data[:n_tr], {"label": labels[:n_tr]},
                           batch_size=32, shuffle=True)
    mod = mx.mod.Module(get_symbol(), data_names=("data",),
                        label_names=("label",),
                        context=mx.context.current_context())
    mod.fit(it, num_epoch=30, optimizer="adam",
            optimizer_params={"learning_rate": 4e-3},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss(output_names=["ctc_loss_output"],
                                       label_names=[]))

    ev = mx.io.NDArrayIter(data[n_tr:], {"label": labels[n_tr:]},
                           batch_size=32)
    hit = total = 0
    for batch in ev:
        mod.forward(batch, is_train=False)
        probs = mod.get_outputs()[1].asnumpy()
        labs = batch.label[0].asnumpy()
        pad = batch.pad or 0
        for n in range(probs.shape[0] - pad):
            want = [int(v) for v in labs[n] if v > 0]
            hit += greedy_decode(probs[n]) == want
            total += 1
    acc = hit / max(total, 1)
    print("held-out exact transcription: %.3f over %d strings"
          % (acc, total))
    assert acc >= 0.80, acc
    print("ocr_ctc example OK")


if __name__ == "__main__":
    main()
