"""Neural style transfer (reference example/neural-style/ role, CI-sized):
optimize an IMAGE, not weights — content features from a deep layer,
style as Gram matrices over shallow layers, with d(loss)/d(image) taken
by the imperative autograd engine (x.attach_grad / autograd.record /
loss.backward) and Adam stepping the pixels.

A compact conv feature stack stands in for VGG-19 (this host has no
pretrained weights and no egress; fixed random filters are the
classical random-feature variant of style transfer and keep the example
self-contained).  CI bar: 80 optimization steps must cut the combined
style+content objective by >= 5x.

Run: python example/neural_style/neural_style.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd

HW = 64
CHANNELS = (16, 32, 64)


def make_filters(rs):
    ws = []
    cin = 3
    for nf in CHANNELS:
        fan = cin * 9
        ws.append(mx.nd.array(
            rs.normal(0, np.sqrt(2.0 / fan), (nf, cin, 3, 3))
            .astype(np.float32)))
        cin = nf
    return ws


def features(x, ws):
    """Style taps after conv1/conv2, content tap after conv3."""
    taps = []
    body = x
    for i, w in enumerate(ws):
        body = mx.nd.Convolution(body, w, kernel=(3, 3), pad=(1, 1),
                                 num_filter=CHANNELS[i], no_bias=True)
        body = mx.nd.relu(body)
        taps.append(body)
        if i < len(ws) - 1:
            body = mx.nd.Pooling(body, kernel=(2, 2), stride=(2, 2),
                                 pool_type="avg")
    return taps


def gram(feat):
    c = feat.shape[1]
    f = feat.reshape((c, -1))
    return mx.nd.dot(f, f, transpose_b=True) / float(f.size)


def images():
    """Content: diagonal gradient scene; style: high-frequency checkers."""
    yy, xx = np.mgrid[0:HW, 0:HW] / HW
    content = np.stack([yy * 0.8, xx * 0.8, (yy + xx) / 2 * 0.8]) \
        .astype(np.float32)[None]
    checker = ((np.mgrid[0:HW, 0:HW] // 4).sum(0) % 2).astype(np.float32)
    style = np.stack([checker, 1 - checker, checker * 0.5])[None] \
        .astype(np.float32)
    return content, style


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    content_img, style_img = images()
    ws = make_filters(rs)

    style_grams = [gram(f).asnumpy()
                   for f in features(mx.nd.array(style_img), ws)[:2]]
    content_ref = features(mx.nd.array(content_img), ws)[2].asnumpy()

    style_w, content_w = 1.0, 0.2

    def objective_and_grad(img):
        x = mx.nd.array(img)
        x.attach_grad()
        with autograd.record():
            taps = features(x, ws)
            loss = None
            for g_ref, tap in zip(style_grams, taps[:2]):
                diff = gram(tap) - mx.nd.array(g_ref)
                term = style_w * mx.nd.sum(diff * diff)
                loss = term if loss is None else loss + term
            cdiff = taps[2] - mx.nd.array(content_ref)
            loss = loss + content_w * mx.nd.sum(cdiff * cdiff)
        loss.backward()
        return float(loss.asscalar()), x.grad.asnumpy()

    img = content_img.copy() + rs.normal(0, 0.05, content_img.shape) \
        .astype(np.float32)
    first = None
    lr, m, v = 0.02, np.zeros_like(img), np.zeros_like(img)
    for it in range(80):            # Adam on the image itself
        loss, grad = objective_and_grad(img)
        if first is None:
            first = loss
        m = 0.9 * m + 0.1 * grad
        v = 0.999 * v + 0.001 * grad * grad
        mh = m / (1 - 0.9 ** (it + 1))
        vh = v / (1 - 0.999 ** (it + 1))
        img -= lr * mh / (np.sqrt(vh) + 1e-8)
        img = np.clip(img, -0.2, 1.2)
        if it % 20 == 0:
            print("step %2d  objective %.4f" % (it, loss))
    final, _ = objective_and_grad(img)
    print("objective: %.4f -> %.4f (%.1fx)" % (first, final, first / final))
    assert final < first / 5, (first, final)
    print("neural_style example OK")


if __name__ == "__main__":
    main()
