"""Multi-task training (reference example/multi-task/ role): one shared
trunk with two SoftmaxOutput heads — digit identity (10-way) and
parity (2-way) — trained jointly on the real bundled scanned-digit
dataset, with a per-head metric wired through output/label names.

CI bar: >= 0.9 on both tasks held-out.

Run: python example/multi_task/multi_task_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def get_symbol():
    sym = mx.sym
    data = sym.Variable("data")
    trunk = sym.FullyConnected(data, num_hidden=96, name="fc1")
    trunk = sym.Activation(trunk, act_type="relu")
    digit = sym.FullyConnected(trunk, num_hidden=10, name="digit_fc")
    digit = sym.SoftmaxOutput(digit, sym.Variable("digit_label"),
                              name="digit")
    parity = sym.FullyConnected(trunk, num_hidden=2, name="parity_fc")
    parity = sym.SoftmaxOutput(parity, sym.Variable("parity_label"),
                               name="parity")
    return mx.sym.Group([digit, parity])


def main():
    mx.random.seed(0)
    from sklearn.datasets import load_digits
    raw = load_digits()
    x = (raw.images.astype(np.float32) / 16.0).reshape(len(raw.target), -1)
    y = raw.target.astype(np.float32)
    order = np.random.RandomState(1).permutation(len(y))
    x, y = x[order], y[order]
    n_tr = 1400
    labels = {"digit_label": y, "parity_label": (y % 2).astype(np.float32)}

    it_tr = mx.io.NDArrayIter(x[:n_tr],
                              {k: v[:n_tr] for k, v in labels.items()},
                              batch_size=64, shuffle=True)
    it_va = mx.io.NDArrayIter(x[n_tr:],
                              {k: v[n_tr:] for k, v in labels.items()},
                              batch_size=64)

    metric = mx.metric.CompositeEvalMetric([
        mx.metric.Accuracy(name="digit_acc",
                           output_names=["digit_output"],
                           label_names=["digit_label"]),
        mx.metric.Accuracy(name="parity_acc",
                           output_names=["parity_output"],
                           label_names=["parity_label"]),
    ])

    mod = mx.mod.Module(get_symbol(),
                        label_names=("digit_label", "parity_label"),
                        context=mx.context.current_context())
    mod.fit(it_tr, num_epoch=20, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            initializer=mx.init.Xavier(), eval_metric=metric)

    metric.reset()
    scores = dict(mod.score(it_va, metric))
    print("held-out: digit %.3f parity %.3f"
          % (scores["digit_acc"], scores["parity_acc"]))
    assert scores["digit_acc"] >= 0.9 and scores["parity_acc"] >= 0.9, scores
    print("multi_task example OK")


if __name__ == "__main__":
    main()
