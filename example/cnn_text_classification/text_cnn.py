"""CNN text classification (reference example/cnn_text_classification/
text_cnn.py role, CI-sized): the Kim-2014 architecture — Embedding,
parallel conv branches of widths 2/3/4 over time, max-over-time pooling,
concat, dropout, dense softmax — on a synthetic sentiment task.

Sentences are token streams over a 60-word vocabulary; class 1
sentences contain at least one token from a small "positive" set,
class 0 from a "negative" set, amid shared filler (so classification
requires spotting keyword n-grams, which is exactly what the
max-over-time conv does).  CI bar: >= 0.9 held-out accuracy.

Run: python example/cnn_text_classification/text_cnn.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

VOCAB, SEQ, EMBED = 60, 20, 16
POS_TOKENS = (50, 51, 52)
NEG_TOKENS = (55, 56, 57)


def synth_sentence(rs):
    toks = rs.randint(1, 50, SEQ)
    cls = rs.randint(0, 2)
    marker = rs.choice(POS_TOKENS if cls else NEG_TOKENS)
    toks[rs.randint(SEQ)] = marker
    return toks.astype(np.float32), float(cls)


def get_symbol():
    sym = mx.sym
    data = sym.Variable("data")                       # (N, SEQ)
    emb = sym.Embedding(data, input_dim=VOCAB, output_dim=EMBED,
                        name="embed")                 # (N, SEQ, EMBED)
    emb = sym.Reshape(emb, shape=(0, 1, SEQ, EMBED))  # (N, 1, T, E)
    pooled = []
    for width in (2, 3, 4):
        conv = sym.Convolution(emb, kernel=(width, EMBED), num_filter=32,
                               name="conv%d" % width)
        act = sym.Activation(conv, act_type="relu")
        pool = sym.Pooling(act, kernel=(SEQ - width + 1, 1),
                           pool_type="max")           # max over time
        pooled.append(sym.Flatten(pool))
    body = sym.Concat(*pooled, dim=1)
    body = sym.Dropout(body, p=0.3)
    fc = sym.FullyConnected(body, num_hidden=2, name="fc")
    return sym.SoftmaxOutput(fc, name="softmax")


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    n = 512
    rows = [synth_sentence(rs) for _ in range(n)]
    data = np.stack([d for d, _ in rows])
    label = np.array([l for _, l in rows], np.float32)
    n_tr = 384
    it_tr = mx.io.NDArrayIter(data[:n_tr], label[:n_tr], batch_size=32,
                              shuffle=True, label_name="softmax_label")
    it_va = mx.io.NDArrayIter(data[n_tr:], label[n_tr:], batch_size=32,
                              label_name="softmax_label")

    mod = mx.mod.Module(get_symbol(), context=mx.context.current_context())
    mod.fit(it_tr, num_epoch=8, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            initializer=mx.init.Xavier(),
            eval_metric="acc")
    acc = dict(mod.score(it_va, "acc"))["accuracy"]
    print("held-out accuracy: %.3f" % acc)
    assert acc >= 0.9, acc
    print("text_cnn example OK")


if __name__ == "__main__":
    main()
