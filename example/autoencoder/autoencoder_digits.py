"""Stacked autoencoder (reference example/autoencoder/ role): encoder
64->32->8, decoder mirroring back to 64, trained with
LinearRegressionOutput against the input itself on the real bundled
scanned digits; then the 8-d code must linearly separate digit
identity far better than chance (a probe classifier on frozen codes).

CI bars: reconstruction MSE <= 0.025 (the 8-d bottleneck's
practical limit on 64-d inputs of variance ~0.09); probe acc >= 0.75.

Run: python example/autoencoder/autoencoder_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def autoencoder_symbol():
    sym = mx.sym
    data = sym.Variable("data")
    enc = sym.Activation(sym.FullyConnected(data, num_hidden=32,
                                            name="enc1"), act_type="relu")
    code = sym.FullyConnected(enc, num_hidden=8, name="code")
    dec = sym.Activation(sym.FullyConnected(code, num_hidden=32,
                                            name="dec1"), act_type="relu")
    recon = sym.FullyConnected(dec, num_hidden=64, name="recon")
    out = sym.LinearRegressionOutput(recon, sym.Variable("recon_label"),
                                     name="recon_out")
    return mx.sym.Group([out, sym.BlockGrad(code, name="code_tap")])


def main():
    mx.random.seed(0)
    from sklearn.datasets import load_digits
    raw = load_digits()
    x = (raw.images.astype(np.float32) / 16.0).reshape(len(raw.target), -1)
    y = raw.target
    order = np.random.RandomState(2).permutation(len(y))
    x, y = x[order], y[order]

    it = mx.io.NDArrayIter(x, {"recon_label": x}, batch_size=128,
                           shuffle=True)
    mod = mx.mod.Module(autoencoder_symbol(), label_names=("recon_label",),
                        context=mx.context.current_context())
    mod.fit(it, num_epoch=40, optimizer="adam",
            optimizer_params={"learning_rate": 2e-3},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.MSE(output_names=["recon_out_output"],
                                      label_names=["recon_label"]))

    # reconstruction error + frozen codes over the whole set — through
    # an UNSHUFFLED iterator so code rows line up with y's order
    it = mx.io.NDArrayIter(x, {"recon_label": x}, batch_size=128)
    it.reset()
    recon_err, codes, labels = [], [], []
    for batch in it:
        mod.forward(batch, is_train=False)
        outs = mod.get_outputs()
        recon = outs[0].asnumpy()
        want = batch.label[0].asnumpy()
        pad = batch.pad or 0
        keep = recon.shape[0] - pad
        recon_err.append(((recon - want) ** 2).mean(1)[:keep])
        codes.append(outs[1].asnumpy()[:keep])
        labels.append(want[:keep])
    mse = float(np.concatenate(recon_err).mean())
    codes = np.concatenate(codes)
    digit_of = y[:len(codes)]

    # linear probe on the 8-d codes
    probe_it = mx.io.NDArrayIter(codes[:1400],
                                 digit_of[:1400].astype(np.float32),
                                 batch_size=64, shuffle=True,
                                 label_name="softmax_label")
    probe = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=10,
                              name="probe_fc"), name="softmax")
    pmod = mx.mod.Module(probe, context=mx.context.current_context())
    pmod.fit(probe_it, num_epoch=30, optimizer="adam",
             optimizer_params={"learning_rate": 5e-3},
             initializer=mx.init.Xavier(), eval_metric="acc")
    va_it = mx.io.NDArrayIter(codes[1400:],
                              digit_of[1400:].astype(np.float32),
                              batch_size=64, label_name="softmax_label")
    probe_acc = dict(pmod.score(va_it, "acc"))["accuracy"]

    print("reconstruction MSE %.4f; 8-d code linear probe acc %.3f"
          % (mse, probe_acc))
    assert mse <= 0.025, mse
    assert probe_acc >= 0.75, probe_acc
    print("autoencoder example OK")


if __name__ == "__main__":
    main()
