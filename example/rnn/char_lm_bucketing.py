"""Variable-length character language model with BucketingModule
(reference example/rnn/bucketing role): sentences bucketed by length,
one executor per bucket sharing parameters, LSTM unrolled per bucket.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def synthetic_corpus(n=200, seed=0):
    """Random 'abab...'-style periodic strings of varying length: the next
    char is predictable, so a tiny LSTM learns them quickly."""
    rs = np.random.RandomState(seed)
    sents = []
    for _ in range(n):
        period = rs.randint(2, 4)
        length = rs.randint(4, 13)
        motif = list(rs.randint(1, 9, period))
        s = (motif * (length // period + 1))[:length]
        sents.append(s)
    return sents


def main():
    vocab = 16
    hidden = 32
    sents = synthetic_corpus()
    buckets = [4, 8, 12]
    # the iterator derives next-char labels itself (data shifted left,
    # invalid_label padding)
    it = mx.rnn.BucketSentenceIter(sents, batch_size=20, buckets=buckets,
                                   invalid_label=0)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=8,
                               name="emb")
        cell = mx.rnn.LSTMCell(hidden, prefix="lstm_")
        outputs, _ = cell.unroll(seq_len, emb, layout="NTC",
                                 merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        label_f = mx.sym.Reshape(label, shape=(-1,))
        return mx.sym.SoftmaxOutput(pred, label_f, name="softmax"), \
            ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=max(buckets),
                                 context=mx.cpu())
    mod.fit(it, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Perplexity(ignore_label=None),
            num_epoch=10)

    # perplexity over the data after training must beat uniform (16)
    it.reset()
    metric = mx.metric.Perplexity(ignore_label=None)
    mod.score(it, metric)
    ppl = dict(metric.get_name_value())["perplexity"]
    print("final perplexity: %.2f (uniform would be %d)" % (ppl, vocab))
    assert ppl < 8.0, ppl
    print("char_lm_bucketing example OK")


if __name__ == "__main__":
    main()
