"""Stochastic-depth residual training (reference
example/stochastic-depth/sd_cifar10.py role, CI-sized): residual blocks
are randomly bypassed during training (in-graph Bernoulli via the
framework's RNG-carrying uniform op), scaled by survival probability at
test time — regularization that also shortens the effective backprop
path.

Like the reference, train and eval use DIFFERENT symbols over shared
weights: the stochastic graph trains (inverted scaling by the survival
probability), and a deterministic expectation graph — plain residual —
evaluates.  CI bars: >= 0.93 held-out accuracy through the eval graph,
and the training graph's forwards must actually vary (the gate is
live).

Run: python example/stochastic_depth/sd_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

BLOCKS, HIDDEN = 4, 96
SURVIVE = 0.8


def residual_block(body, idx, batch_size):
    sym = mx.sym
    branch = sym.Activation(
        sym.FullyConnected(body, num_hidden=HIDDEN,
                           name="blk%d_fc" % idx), act_type="relu")
    # per-sample Bernoulli gate, drawn in-graph; scaled like inverted
    # dropout so eval (gate==expectation) needs no weight rescale
    u = sym.random.uniform(0.0, 1.0, shape=(batch_size, 1),
                           name="blk%d_gate" % idx)
    gate = u < SURVIVE                              # per-sample Bernoulli
    branch = sym.broadcast_mul(branch, gate / SURVIVE)
    return body + branch


def get_symbol(batch_size, stochastic=True):
    sym = mx.sym
    body = sym.Activation(
        sym.FullyConnected(sym.Variable("data"), num_hidden=HIDDEN,
                           name="stem"), act_type="relu")
    for i in range(BLOCKS):
        if stochastic:
            body = residual_block(body, i, batch_size)
        else:
            branch = sym.Activation(
                sym.FullyConnected(body, num_hidden=HIDDEN,
                                   name="blk%d_fc" % i), act_type="relu")
            body = body + branch
    head = sym.FullyConnected(body, num_hidden=10, name="head")
    return sym.SoftmaxOutput(head, name="softmax")


def main():
    mx.random.seed(0)
    np.random.seed(0)   # NDArrayIter(shuffle=True) uses the global RNG
    from sklearn.datasets import load_digits
    raw = load_digits()
    x = (raw.images.astype(np.float32) / 16.0).reshape(len(raw.target), -1)
    y = raw.target.astype(np.float32)
    order = np.random.RandomState(6).permutation(len(y))
    x, y = x[order], y[order]
    n_tr, batch = 1400, 64

    it_tr = mx.io.NDArrayIter(x[:n_tr], y[:n_tr], batch_size=batch,
                              shuffle=True, label_name="softmax_label")
    it_va = mx.io.NDArrayIter(x[n_tr:], y[n_tr:], batch_size=batch,
                              label_name="softmax_label")
    mod = mx.mod.Module(get_symbol(batch),
                        context=mx.context.current_context())
    mod.fit(it_tr, num_epoch=35, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.init.Xavier(), eval_metric="acc")

    # deterministic expectation graph over the SAME weights for eval
    args, auxs = mod.get_params()
    emod = mx.mod.Module(get_symbol(batch, stochastic=False),
                         context=mx.context.current_context())
    emod.bind(data_shapes=it_va.provide_data,
              label_shapes=it_va.provide_label, for_training=False)
    emod.set_params(args, auxs)
    acc = dict(emod.score(it_va, "acc"))["accuracy"]

    # the training graph's gate must be LIVE (forwards vary)
    it_va.reset()
    probe = next(iter(it_va))
    outs = []
    for _ in range(3):
        mod.forward(probe, is_train=True)
        outs.append(mod.get_outputs()[0].asnumpy())
    train_var = float(np.var(np.stack(outs), axis=0).mean())

    print("held-out acc %.3f (deterministic eval graph); "
          "train-fwd variance %.2e" % (acc, train_var))
    assert acc >= 0.93, acc
    assert train_var > 1e-8, train_var
    print("sd_digits example OK")


if __name__ == "__main__":
    main()
