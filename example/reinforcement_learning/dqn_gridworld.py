"""Deep Q-learning on a gridworld (reference
example/reinforcement-learning/dqn role, CI-sized, no external gym):
replay buffer, epsilon-greedy exploration, target-network syncing, and
TD(0) regression through the Gluon API.

Environment: 5x5 grid, agent starts at a random cell, goal fixed at
(4,4), step reward -0.02, goal +1, 40-step horizon.  CI bar: the greedy
policy after training must reach the goal from every start cell (mean
return >= 0.7, vs ~-0.4 for a random walk).

Run: python example/reinforcement_learning/dqn_gridworld.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

from collections import deque

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

GRID = 5
ACTIONS = 4                      # N, S, W, E
GOAL = (GRID - 1, GRID - 1)
STEP_R, GOAL_R, HORIZON = -0.02, 1.0, 40
MOVES = ((-1, 0), (1, 0), (0, -1), (0, 1))


def encode(pos):
    """One-hot board plane the net consumes."""
    plane = np.zeros((GRID * GRID,), np.float32)
    plane[pos[0] * GRID + pos[1]] = 1.0
    return plane


def env_step(pos, action):
    r, c = pos
    dr, dc = MOVES[action]
    nxt = (min(max(r + dr, 0), GRID - 1), min(max(c + dc, 0), GRID - 1))
    if nxt == GOAL:
        return nxt, GOAL_R, True
    return nxt, STEP_R, False


def build_qnet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(ACTIONS))
    return net


def copy_params(src, dst):
    for (_, a), (_, b) in zip(sorted(src.collect_params().items()),
                              sorted(dst.collect_params().items())):
        b.set_data(a.data())


ALL_STATES = np.stack([encode((r, c))
                       for r in range(GRID) for c in range(GRID)])


def q_table(net):
    """One batched forward over every state: (GRID*GRID, ACTIONS)."""
    return net(mx.nd.array(ALL_STATES)).asnumpy()


def greedy_return(qtab, start):
    pos, total = start, 0.0
    for _ in range(HORIZON):
        action = int(qtab[pos[0] * GRID + pos[1]].argmax())
        pos, r, done = env_step(pos, action)
        total += r
        if done:
            return total
    return total


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    ctx = mx.context.current_context()

    qnet, target = build_qnet(), build_qnet()
    qnet.initialize(mx.init.Xavier(), ctx=ctx)
    target.initialize(mx.init.Xavier(), ctx=ctx)
    # shapes materialize on first forward (deferred init)
    probe = mx.nd.array(encode((0, 0))[None])
    qnet(probe), target(probe)
    copy_params(qnet, target)
    trainer = gluon.Trainer(qnet.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.L2Loss()

    replay = deque(maxlen=4000)
    gamma, batch = 0.95, 64
    eps = 1.0
    for episode in range(250):
        # acting policy: one batched forward refreshes the Q-table per
        # episode (the policy moves slowly; per-step forwards would be
        # 40x the dispatch cost for the same behaviour)
        qtab = q_table(qnet)
        pos = (rs.randint(GRID), rs.randint(GRID))
        for _ in range(HORIZON):
            if rs.rand() < eps:
                action = rs.randint(ACTIONS)
            else:
                action = int(qtab[pos[0] * GRID + pos[1]].argmax())
            nxt, r, done = env_step(pos, action)
            replay.append((encode(pos), action, r, encode(nxt), done))
            pos = nxt
            if done:
                break
        eps = max(0.05, eps * 0.985)

        if len(replay) >= batch:
            for _ in range(2):
                picks = rs.choice(len(replay), batch, replace=False)
                s, a, r, s2, d = map(np.asarray,
                                     zip(*(replay[i] for i in picks)))
                q_next = target(mx.nd.array(s2)).asnumpy().max(1)
                y = r + gamma * q_next * (1.0 - d.astype(np.float32))
                with autograd.record():
                    q_all = qnet(mx.nd.array(s))
                    q_sel = mx.nd.pick(q_all, mx.nd.array(a), axis=1)
                    loss = loss_fn(q_sel, mx.nd.array(y.astype(np.float32)))
                loss.backward()
                trainer.step(batch)
        if episode % 10 == 0:
            copy_params(qnet, target)

    starts = [(r, c) for r in range(GRID) for c in range(GRID)
              if (r, c) != GOAL]
    final_q = q_table(qnet)
    returns = [greedy_return(final_q, s) for s in starts]
    mean_ret = float(np.mean(returns))
    solved = sum(ret > 0 for ret in returns)
    print("greedy policy: mean return %.3f; %d/%d starts reach the goal"
          % (mean_ret, solved, len(starts)))
    assert mean_ret >= 0.7, mean_ret
    print("dqn_gridworld example OK")


if __name__ == "__main__":
    main()
