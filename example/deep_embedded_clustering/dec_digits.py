"""Deep Embedded Clustering (reference
example/deep-embedded-clustering/ role): pretrain an autoencoder on the
real bundled scanned digits, seed centroids from label-free k-means,
then refine encoder + centroids jointly by matching the Student-t soft
assignment to its own sharpened target distribution (the DEC KL
objective) through the imperative autograd engine.

CI bars: the DEC refinement must lift cluster accuracy by >= 3 points
over its own initialization and reach >= 0.70 (best one-to-one
cluster->digit mapping; labels are used for EVALUATION only).

Run: python example/deep_embedded_clustering/dec_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

LATENT, K = 10, 10


def cluster_accuracy(assign, truth):
    """Greedy best one-to-one cluster->digit mapping accuracy."""
    counts = np.zeros((K, 10), np.int64)
    for a, t in zip(assign, truth):
        counts[a, int(t)] += 1
    remaining = set(range(10))
    total = 0
    for k in np.argsort(-counts.max(1)):
        if not remaining:
            break
        best = max(remaining, key=lambda d: counts[k, d])
        total += counts[k, best]
        remaining.discard(best)
    return total / len(assign)


def kmeans(points, rs, iters=30):
    centers = points[rs.choice(len(points), K, replace=False)]
    for _ in range(iters):
        d = ((points[:, None] - centers[None]) ** 2).sum(-1)
        assign = d.argmin(1)
        for k in range(K):
            mine = points[assign == k]
            if len(mine):
                centers[k] = mine.mean(0)
    return centers, assign


def pretrain_autoencoder(x, rs):
    enc = gluon.nn.HybridSequential()
    enc.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(LATENT))
    dec = gluon.nn.HybridSequential()
    dec.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(x.shape[1]))
    enc.initialize(mx.init.Xavier())
    dec.initialize(mx.init.Xavier())
    params = list(enc.collect_params().values()) + \
        list(dec.collect_params().values())
    trainer = gluon.Trainer({p.name: p for p in params}, "adam",
                            {"learning_rate": 2e-3})
    l2 = gluon.loss.L2Loss()
    batch = 128
    for epoch in range(60):
        perm = rs.permutation(len(x))
        for i in range(0, len(perm) - batch + 1, batch):
            xb = mx.nd.array(x[perm[i:i + batch]])
            with autograd.record():
                loss = l2(dec(enc(xb)), xb)
            loss.backward()
            trainer.step(batch)
    return enc


def refine(enc, x, centers, iters=120, target_every=20):
    """DEC: minimize KL(P || Q) with Q the Student-t assignment and P
    its sharpened (squared, cluster-normalized) self-target."""
    mu = mx.nd.array(centers.astype(np.float32))
    mu.attach_grad()
    trainer = gluon.Trainer(enc.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    p_nd = logp_nd = None
    xb = mx.nd.array(x)          # loop-invariant: one h2d transfer
    for it in range(iters):
        with autograd.record():
            z = enc(xb)
            d2 = mx.nd.sum(
                (z.reshape((-1, 1, LATENT)) -
                 mu.reshape((1, K, LATENT))) ** 2, axis=2)
            q = 1.0 / (1.0 + d2)
            q = q / mx.nd.sum(q, axis=1, keepdims=True)
            if it % target_every == 0:       # refresh the fixed target
                qn = q.asnumpy()
                p = (qn ** 2) / qn.sum(0, keepdims=True)
                p = p / p.sum(1, keepdims=True)
                p_nd = mx.nd.array(p)
                logp_nd = mx.nd.array(np.log(p + 1e-12))
            kl = mx.nd.sum(p_nd * (logp_nd
                                   - mx.nd.log(q + 1e-12))) / len(x)
        kl.backward()
        # kl is already the per-sample mean: no further batch scaling
        trainer.step(1)
        mu[:] = mu - 0.1 * mu.grad
        mu.attach_grad()
    return mu.asnumpy()


def main():
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    from sklearn.datasets import load_digits
    raw = load_digits()
    x = (raw.images.astype(np.float32) / 16.0).reshape(len(raw.target), -1)
    y = raw.target

    enc = pretrain_autoencoder(x, rs)
    codes = enc(mx.nd.array(x)).asnumpy()

    # label-free centroid seed: k-means in pixel space, means in code space
    _, assign_raw = kmeans(x.copy(), rs)
    centers = np.stack([
        codes[assign_raw == k].mean(0) if (assign_raw == k).any()
        else codes[rs.randint(len(codes))]            # empty-cluster guard
        for k in range(K)])
    base_assign = ((codes[:, None] - centers[None]) ** 2).sum(-1).argmin(1)
    base_acc = cluster_accuracy(base_assign, y)

    mu = refine(enc, x, centers)

    codes = enc(mx.nd.array(x)).asnumpy()
    final_assign = ((codes[:, None] - mu[None]) ** 2).sum(-1).argmin(1)
    final_acc = cluster_accuracy(final_assign, y)
    print("cluster accuracy: init %.3f -> DEC-refined %.3f"
          % (base_acc, final_acc))
    assert final_acc >= 0.70 and final_acc >= base_acc + 0.03, \
        (base_acc, final_acc)
    print("dec_digits example OK")


if __name__ == "__main__":
    main()
