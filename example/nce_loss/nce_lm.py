"""Noise-contrastive estimation for large-vocabulary softmax (reference
example/nce-loss/ role, CI-sized): instead of a full-vocab softmax, each
step scores the true next token against k sampled noise tokens with a
shared output embedding, trained as binary logistic discrimination —
the cheap large-V trick.

A bigram language ("every token deterministically selects its
successor" plus noise) is learned with NCE; evaluation then runs the
FULL softmax ranking with the same trained embeddings and must place
the true successor in the top-1 for >= 80% of contexts — proving the
NCE-trained embeddings encode the full-vocab distribution.

Run: python example/nce_loss/nce_lm.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

VOCAB, EMBED, K_NOISE = 200, 24, 8


def make_bigram_data(rs, n=6000):
    succ = rs.permutation(VOCAB)          # token v -> succ[v]
    ctx = rs.randint(0, VOCAB, n)
    nxt = np.where(rs.rand(n) < 0.9, succ[ctx],
                   rs.randint(0, VOCAB, n))
    return ctx.astype(np.int64), nxt.astype(np.int64), succ


class NCEModel(gluon.Block):
    def __init__(self):
        super().__init__()
        self.in_embed = gluon.nn.Embedding(VOCAB, EMBED)
        self.out_embed = gluon.nn.Embedding(VOCAB, EMBED)

    def scores(self, ctx_tok, cand_toks):
        """(N,) contexts x (N, C) candidates -> (N, C) dot scores."""
        h = self.in_embed(ctx_tok)                      # (N, E)
        w = self.out_embed(cand_toks)                   # (N, C, E)
        return mx.nd.sum(w * h.reshape((-1, 1, EMBED)), axis=2)

    def forward(self, ctx_tok, cand_toks):
        return self.scores(ctx_tok, cand_toks)


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    ctx_toks, nxt_toks, succ = make_bigram_data(rs)

    model = NCEModel()
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": 0.01})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    batch = 128
    for epoch in range(8):
        perm = rs.permutation(len(ctx_toks))
        total = 0.0
        for i in range(0, len(perm) - batch + 1, batch):
            rows = perm[i:i + batch]
            # candidates: column 0 = true token, then K noise draws
            cands = np.concatenate(
                [nxt_toks[rows][:, None],
                 rs.randint(0, VOCAB, (batch, K_NOISE))], axis=1)
            target = np.zeros((batch, 1 + K_NOISE), np.float32)
            target[:, 0] = 1.0
            c = mx.nd.array(ctx_toks[rows].astype(np.float32))
            cd = mx.nd.array(cands.astype(np.float32))
            with autograd.record():
                s = model(c, cd)
                loss = bce(s, mx.nd.array(target))
            loss.backward()
            trainer.step(batch)
            total += float(loss.mean().asscalar())
        print("epoch %d nce loss %.4f" % (epoch, total / (len(perm) // batch)))

    # full-softmax evaluation with the SAME embeddings
    all_ids = mx.nd.array(np.arange(VOCAB, dtype=np.float32))
    out_w = model.out_embed(all_ids).asnumpy()          # (V, E)
    ctx_eval = np.arange(VOCAB, dtype=np.float32)
    h = model.in_embed(mx.nd.array(ctx_eval)).asnumpy()  # (V, E)
    ranks = (h @ out_w.T).argmax(1)
    top1 = float((ranks == succ).mean())
    print("full-vocab top-1 successor accuracy: %.3f" % top1)
    assert top1 >= 0.8, top1
    print("nce_lm example OK")


if __name__ == "__main__":
    main()
