"""Capsule network with dynamic routing (reference example/capsnet/
role, CI-sized): conv features -> primary capsules (8-d vectors,
squashed) -> digit capsules (16-d) via 3 iterations of routing by
agreement, margin loss on capsule lengths — all in imperative Gluon
autograd (the routing loop is plain tensor code).

CI bar: >= 0.9 held-out accuracy on the real bundled scanned digits,
with capsule length as the class score.

Run: python example/capsnet/capsnet_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

PRIMARY, PDIM = 16, 8       # primary capsules x their dimension
NCLASS, DDIM = 10, 16       # digit capsules x their dimension
ROUTING_ITERS = 3


def squash(v, axis):
    n2 = mx.nd.sum(v * v, axis=axis, keepdims=True)
    return v * (n2 / (1.0 + n2)) / mx.nd.sqrt(n2 + 1e-9)


class CapsNet(gluon.Block):
    def __init__(self):
        super().__init__()
        self.conv = gluon.nn.Conv2D(32, kernel_size=3, padding=1,
                                    activation="relu")
        self.primary = gluon.nn.Dense(PRIMARY * PDIM)
        # one (PDIM -> DDIM) transform per (primary, digit) pair
        self.W = gluon.Parameter(
            "caps_W", shape=(1, PRIMARY, NCLASS, DDIM, PDIM),
            init=mx.init.Normal(0.05))
        self.W.initialize()

    def forward(self, x):
        n = x.shape[0]
        feats = self.conv(x).reshape((n, -1))
        u = squash(self.primary(feats).reshape((n, PRIMARY, PDIM)), axis=2)
        # prediction vectors u_hat[n, i, j, :] = W_ij @ u[n, i]
        u_exp = u.reshape((n, PRIMARY, 1, 1, PDIM))
        u_hat = mx.nd.sum(self.W.data() * u_exp, axis=4)  # (n,P,C,D)
        # routing by agreement
        b = mx.nd.zeros((n, PRIMARY, NCLASS, 1))
        for it in range(ROUTING_ITERS):
            c = mx.nd.softmax(b, axis=2)
            s = mx.nd.sum(c * u_hat, axis=1)              # (n,C,D)
            v = squash(s, axis=2)
            if it < ROUTING_ITERS - 1:
                agree = mx.nd.sum(
                    u_hat * v.reshape((n, 1, NCLASS, DDIM)),
                    axis=3, keepdims=True)
                b = b + agree
        return mx.nd.sqrt(mx.nd.sum(v * v, axis=2) + 1e-9)  # lengths


def margin_loss(lengths, onehot):
    pos = mx.nd.relu(0.9 - lengths) ** 2
    neg = mx.nd.relu(lengths - 0.1) ** 2
    return mx.nd.sum(onehot * pos + 0.5 * (1 - onehot) * neg, axis=1)


def main():
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(0)
    from sklearn.datasets import load_digits
    raw = load_digits()
    x = (raw.images.astype(np.float32) / 16.0)[:, None, :, :]
    y = raw.target
    order = rs.permutation(len(y))
    x, y = x[order], y[order]
    n_tr, batch = 1400, 64

    net = CapsNet()
    net.conv.initialize(mx.init.Xavier())
    net.primary.initialize(mx.init.Xavier())
    params = {}
    for blk in (net.conv, net.primary):
        params.update(blk.collect_params())
    params[net.W.name] = net.W
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 2e-3})

    onehot = np.eye(NCLASS, dtype=np.float32)
    for epoch in range(12):
        perm = rs.permutation(n_tr)
        total = 0.0
        for i in range(0, n_tr - batch + 1, batch):
            rows = perm[i:i + batch]
            xb = mx.nd.array(x[rows])
            tb = mx.nd.array(onehot[y[rows]])
            with autograd.record():
                lengths = net(xb)
                loss = margin_loss(lengths, tb)
            loss.backward()
            trainer.step(batch)
            total += float(loss.mean().asscalar())
        print("epoch %d margin loss %.4f" % (epoch, total / (n_tr // batch)))

    hits = 0
    for i in range(n_tr, len(y), batch):
        xb = mx.nd.array(x[i:i + batch])
        pred = net(xb).asnumpy().argmax(1)
        hits += int((pred == y[i:i + batch]).sum())
    acc = hits / (len(y) - n_tr)
    print("held-out accuracy (capsule lengths): %.3f" % acc)
    assert acc >= 0.9, acc
    print("capsnet example OK")


if __name__ == "__main__":
    main()
