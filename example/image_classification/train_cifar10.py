"""CIFAR-10 training CLI (reference
example/image-classification/train_cifar10.py): the same engine as
train_imagenet.py with the CIFAR presets — 3x28x28 crops, 10 classes,
the resnet builder's CIFAR stage layout (models/resnet.py selects it
for heights <= 28), and the reference's lr schedule.

Run: python example/image_classification/train_cifar10.py \
        --data-train cifar10_train.rec [--num-layers 110]
     (or --benchmark 1 for synthetic data)
"""
import sys

import train_imagenet


def main():
    presets = [
        ("--num-classes", "10"), ("--image-shape", "3,28,28"),
        ("--num-examples", "50000"), ("--lr-step-epochs", "200,250"),
        ("--num-epochs", "300"), ("--lr", "0.05"),
        ("--batch-size", "128"), ("--num-layers", "110"),
    ]
    # presets go FIRST so any user-supplied value (either `--flag v` or
    # `--flag=v` form) wins under argparse's last-occurrence rule
    preset_args = [tok for pair in presets for tok in pair]
    sys.argv = [sys.argv[0]] + preset_args + sys.argv[1:]
    train_imagenet.main()


if __name__ == "__main__":
    main()
