"""ImageNet-class training CLI — the flagship end-to-end workload
(reference example/image-classification/train_imagenet.py +
common/fit.py:139), driven entirely through the public API:
model-zoo symbol -> ImageRecordIter (native C++ decode pipeline when
built) -> Module.fit with kvstore, lr schedule, Speedometer,
checkpoint/resume.  Pair with tools/launch.py --max-restarts for the
elastic multi-process mode.

Typical uses:
  # real data (RecordIO produced by tools/im2rec)
  python example/image_classification/train_imagenet.py \
      --data-train train.rec --network resnet --num-layers 50 \
      --batch-size 32 --num-epochs 90 --model-prefix ckpt/r50

  # synthetic-data benchmark mode (no IO in the loop)
  python example/image_classification/train_imagenet.py --benchmark 1 \
      --network resnet --num-layers 50 --num-examples 512 --num-epochs 1

  # resume
  ... --model-prefix ckpt/r50 --load-epoch 30
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def parse_args():
    p = argparse.ArgumentParser(
        description="train an image-classification model",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    d = p.add_argument
    d("--network", default="resnet",
      help="model family: resnet | resnet_v1 | resnext | mobilenet | "
           "googlenet | inception_v4 | vgg | alexnet | mlp | lenet")
    d("--num-layers", type=int, default=50,
      help="depth for depth-parameterised families "
           "(resnet/resnet_v1/resnext/vgg)")
    d("--num-classes", type=int, default=1000)
    d("--image-shape", default="3,224,224")
    d("--dtype", default="float32",
      help="float32 | bfloat16 (TPU-native mixed precision)")
    # data
    d("--data-train", default=None, help="training RecordIO (.rec)")
    d("--data-val", default=None, help="validation RecordIO (.rec)")
    d("--benchmark", type=int, default=0,
      help="1 = synthetic device-resident data, no IO in the loop")
    d("--num-examples", type=int, default=1281167,
      help="examples per epoch (drives the lr schedule)")
    d("--data-nthreads", type=int, default=os.cpu_count() or 4,
      help="decode threads for the native pipeline")
    d("--rand-crop", type=int, default=1)
    d("--rand-mirror", type=int, default=1)
    # optimizer
    d("--batch-size", type=int, default=32)
    d("--num-epochs", type=int, default=90)
    d("--lr", type=float, default=0.1)
    d("--lr-factor", type=float, default=0.1)
    d("--lr-step-epochs", default="30,60,80",
      help="epochs at which lr decays by --lr-factor")
    d("--mom", type=float, default=0.9)
    d("--wd", type=float, default=1e-4)
    d("--optimizer", default="sgd")
    # infra
    d("--kv-store", default="device",
      help="local | device | tpu | dist_sync | dist_device_sync | "
         "dist_async")
    d("--model-prefix", default=None, help="checkpoint path prefix")
    d("--load-epoch", type=int, default=None,
      help="resume from this checkpoint epoch")
    d("--disp-batches", type=int, default=20,
      help="Speedometer logging period")
    d("--top-k", type=int, default=0,
      help="also report top-k accuracy when > 0")
    d("--monitor", type=int, default=0,
      help="install a Monitor with this stat period")
    return p.parse_args()


def get_network(args):
    from mxnet_tpu import models
    shape = tuple(int(x) for x in args.image_shape.split(","))
    fam = args.network.lower()
    kw = dict(num_classes=args.num_classes, dtype=args.dtype)
    if fam == "resnet":
        return models.resnet.get_symbol(
            num_layers=args.num_layers, image_shape=args.image_shape, **kw), \
            shape
    if fam == "resnet_v1":
        return models.resnet_v1.get_symbol(num_layers=args.num_layers,
                                           **kw), shape
    if fam == "resnext":
        return models.resnext.get_symbol(num_layers=args.num_layers,
                                         **kw), shape
    if fam == "mobilenet":
        return models.mobilenet.get_symbol(**kw), shape
    if fam == "googlenet":
        return models.googlenet.get_symbol(**kw), shape
    if fam == "inception_v4":
        return models.inception_v4.get_symbol(**kw), shape
    if fam == "vgg":
        return models.vgg.get_symbol(num_layers=args.num_layers, **kw), shape
    if fam == "alexnet":
        return models.alexnet.get_symbol(**kw), shape
    if fam == "mlp":
        return models.mlp.get_symbol(num_classes=args.num_classes), shape
    if fam == "lenet":
        return models.lenet.get_symbol(num_classes=args.num_classes), shape
    raise ValueError("unknown --network %r" % args.network)


def data_iters(args, kv, shape):
    """ImageRecordIter pair partitioned across workers (reference
    common/data.py get_rec_iter)."""
    if args.benchmark:
        rs = np.random.RandomState(0)
        n = max(args.batch_size, min(args.num_examples, 4 * args.batch_size))
        x = rs.rand(n, *shape).astype(np.float32)
        y = rs.randint(0, args.num_classes, n).astype(np.float32)
        return mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True,
                                 label_name="softmax_label"), None
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train, data_shape=shape,
        batch_size=args.batch_size, shuffle=True,
        rand_crop=bool(args.rand_crop), rand_mirror=bool(args.rand_mirror),
        preprocess_threads=args.data_nthreads,
        num_parts=kv.num_workers, part_index=kv.rank)
    val = None
    if args.data_val:
        val = mx.io.ImageRecordIter(
            path_imgrec=args.data_val, data_shape=shape,
            batch_size=args.batch_size, shuffle=False,
            preprocess_threads=args.data_nthreads,
            num_parts=kv.num_workers, part_index=kv.rank)
    return train, val


def lr_schedule(args, kv):
    """MultiFactor decay at --lr-step-epochs, shifted for resume
    (reference common/fit.py _get_lr_scheduler)."""
    begin = args.load_epoch or 0
    epoch_size = max(args.num_examples // args.batch_size
                     // max(kv.num_workers, 1), 1)
    steps = [int(e) for e in args.lr_step_epochs.split(",") if e.strip()]
    lr = args.lr
    for s in steps:
        if begin >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjusted lr to %s for resume at epoch %d", lr, begin)
    remaining = [(s - begin) * epoch_size for s in steps if s > begin]
    sched = mx.lr_scheduler.MultiFactorScheduler(
        remaining, args.lr_factor) if remaining else None
    if sched is not None:
        sched.base_lr = lr
    return lr, sched


def main():
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    args = parse_args()
    if not args.benchmark and not args.data_train:
        raise SystemExit("--data-train is required (or use --benchmark 1)")

    kv = mx.kv.create(args.kv_store)
    net, shape = get_network(args)
    train, val = data_iters(args, kv, shape)
    lr, sched = lr_schedule(args, kv)

    # resume / checkpoint plumbing: rank-qualified prefix like the
    # reference's _save_model/_load_model
    arg_params = aux_params = None
    prefix = args.model_prefix
    if prefix and kv.rank > 0:
        prefix += "-%d" % kv.rank
    if prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            prefix, args.load_epoch)
        logging.info("Resumed from %s-%04d.params", prefix, args.load_epoch)
    epoch_cb = mx.callback.do_checkpoint(prefix) if prefix else None
    batch_cb = mx.callback.Speedometer(args.batch_size, args.disp_batches)

    metrics = [mx.metric.Accuracy(), mx.metric.CrossEntropy()]
    if args.top_k > 0:
        metrics.append(mx.metric.TopKAccuracy(top_k=args.top_k))

    opt_params = {"learning_rate": lr, "wd": args.wd}
    if args.optimizer in ("sgd", "nag"):
        opt_params["momentum"] = args.mom
    if sched is not None:
        opt_params["lr_scheduler"] = sched
    if args.dtype == "bfloat16":
        opt_params["multi_precision"] = True

    mon = mx.mon.Monitor(args.monitor, pattern=".*weight") \
        if args.monitor > 0 else None

    # train on the accelerator when one exists (the reference's --gpus
    # analog; mxnet's default context is cpu, which would silently run
    # the model on the host)
    ctx = mx.tpu() if mx.context.num_tpus() > 0 else \
        mx.context.current_context()
    logging.info("training on %s", ctx)
    mod = mx.mod.Module(net, context=ctx)
    mod.fit(train, eval_data=val,
            eval_metric=mx.metric.CompositeEvalMetric(metrics),
            kvstore=kv, optimizer=args.optimizer,
            optimizer_params=opt_params,
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2),
            arg_params=arg_params, aux_params=aux_params,
            begin_epoch=args.load_epoch or 0,
            num_epoch=args.num_epochs,
            batch_end_callback=batch_cb, epoch_end_callback=epoch_cb,
            allow_missing=True, monitor=mon)
    print("train_imagenet OK")


if __name__ == "__main__":
    main()
