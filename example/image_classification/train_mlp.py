"""Classic end-to-end training loop (reference
example/image-classification/train_mnist.py role): Module.fit with an
NDArrayIter, Xavier init, SGD with momentum, accuracy metric, per-epoch
checkpointing, and resume.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def synthetic_digits(n=512, dim=64, classes=10, seed=0):
    """Gaussian blobs, one per class — an MNIST stand-in with no download."""
    rs = np.random.RandomState(seed)
    centers = rs.normal(0, 3, (classes, dim)).astype(np.float32)
    y = rs.randint(0, classes, n)
    x = centers[y] + rs.normal(0, 1, (n, dim)).astype(np.float32)
    return x, y.astype(np.float32)


def build_net(classes=10):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc3")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    x, y = synthetic_digits()
    train = mx.io.NDArrayIter(x[:448], y[:448], batch_size=64, shuffle=True)
    val = mx.io.NDArrayIter(x[448:], y[448:], batch_size=64)

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "mlp")
        mod = mx.mod.Module(build_net(), context=mx.cpu())
        mod.fit(train, eval_data=val,
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                initializer=mx.init.Xavier(),
                eval_metric="acc",
                epoch_end_callback=mx.callback.do_checkpoint(prefix),
                batch_end_callback=mx.callback.Speedometer(64, 5),
                num_epoch=8)

        metric = mx.metric.Accuracy()
        mod.score(val, metric)
        acc = dict(metric.get_name_value())["accuracy"]
        print("final val accuracy: %.3f" % acc)
        assert acc > 0.9, acc

        # resume from the checkpoint: same accuracy
        sym, args, aux = mx.model.load_checkpoint(prefix, 8)
        mod2 = mx.mod.Module(sym, context=mx.cpu())
        mod2.bind(data_shapes=val.provide_data,
                  label_shapes=val.provide_label)
        mod2.set_params(args, aux)
        metric.reset()
        mod2.score(val, metric)
        acc2 = dict(metric.get_name_value())["accuracy"]
        assert abs(acc - acc2) < 1e-6
    print("train_mlp example OK")


if __name__ == "__main__":
    main()
