"""Real-data convergence proof: scanned handwritten digits end-to-end.

This environment has no network egress and no CIFAR/MNIST archive on
disk (keras/torchvision/huggingface caches all empty — checked), so the
real-dataset convergence evidence the reference establishes with
MNIST/CIFAR (tests/python/train/test_conv.py,
example/image-classification) runs here on the one real image dataset
shipped inside the software stack: scikit-learn's bundled UCI ML
hand-written digits (1,797 genuine 8x8 scans, NIST-derived).  Same
shape of proof — a conv net trained through the public Module API on
real pixels to a recorded held-out accuracy — on data that is actually
present.

Run: python example/image_classification/train_digits.py
     [--num-epochs 30] [--batch-size 64] [--lr 0.1] [--target 0.95]

Exits non-zero if held-out accuracy misses --target; prints a per-epoch
validation curve (the PERF.md record comes from this output).

When CIFAR-10 *is* staged on a host (cifar10_train.rec), use
train_cifar10.py — the full-size CLI path, CI-smoked on synthetic data.
"""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def build_net(sym):
    net = sym.Variable("data")
    net = sym.Convolution(net, kernel=(3, 3), num_filter=32, pad=(1, 1),
                          name="conv1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Convolution(net, kernel=(3, 3), num_filter=64, pad=(1, 1),
                          name="conv2")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=128, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def load_split(val_fraction=0.25, seed=7):
    from sklearn.datasets import load_digits
    raw = load_digits()
    images = (raw.images.astype(np.float32) / 16.0)[:, None, :, :]
    labels = raw.target.astype(np.float32)
    order = np.random.RandomState(seed).permutation(len(labels))
    images, labels = images[order], labels[order]
    n_val = int(len(labels) * val_fraction)
    return (images[n_val:], labels[n_val:]), (images[:n_val], labels[:n_val])


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--target", type=float, default=0.95)
    args = ap.parse_args(argv)

    import mxnet_tpu as mx
    logging.basicConfig(level=logging.INFO)

    (x_tr, y_tr), (x_va, y_va) = load_split()
    print("train on %d real digit scans, validate on %d"
          % (len(y_tr), len(y_va)))

    train_iter = mx.io.NDArrayIter(x_tr, y_tr, args.batch_size,
                                   shuffle=True, label_name="softmax_label")
    val_iter = mx.io.NDArrayIter(x_va, y_va, args.batch_size,
                                 label_name="softmax_label")

    mod = mx.mod.Module(build_net(mx.sym), context=mx.context.current_context())
    curve = []

    def at_epoch_end(epoch, sym=None, arg=None, aux=None):
        score = dict(mod.score(val_iter, "acc"))
        curve.append((epoch, score["accuracy"]))
        print("epoch %d val-acc %.4f" % (epoch, score["accuracy"]))

    t0 = time.time()
    mod.fit(train_iter, num_epoch=args.num_epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.init.Xavier(),
            epoch_end_callback=at_epoch_end,
            eval_metric="acc")
    wall = time.time() - t0

    best = max(acc for _, acc in curve)
    final = curve[-1][1]
    print("digits convergence: final val-acc %.4f (best %.4f) "
          "in %d epochs, %.1fs wall" % (final, best, args.num_epochs, wall))
    if best < args.target:
        print("FAILED: best val-acc %.4f < target %.4f" % (best, args.target))
        return 1
    print("CONVERGED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
