"""Speech-style CTC training: BiLSTM + ctc_loss + bucketing, end to end
(the reference example/speech_recognition/main.py role, CI-sized).

Synthetic "utterances": each token of a 5-symbol alphabet emits 3-5
frames of a token-specific spectral band plus noise; utterances have
variable token counts, so frame sequences land in length buckets and a
BucketingModule drives one executor per bucket over shared weights.
Per bucket: frames (N, T, F) -> bidirectional LSTM (FusedRNNCell)
-> per-frame vocabulary head -> CTCLoss (blank=0, 1-based labels)
wrapped in MakeLoss.  After training, greedy CTC decoding (argmax,
collapse repeats, strip blanks) must transcribe >= 80% of held-in
utterances exactly.

Run: python example/speech_recognition/train_ctc_toy.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

VOCAB = 5          # real tokens 1..5 (0 is the CTC blank / label pad)
FEAT = 16          # frames are FEAT-dim "spectra"
MAX_LABEL = 6      # label rows padded to this many tokens
BUCKETS = [12, 20, 28]


def synth_utterance(rs):
    """Token string -> frames; each token holds a noisy frequency band."""
    n_tok = rs.randint(2, 6)
    tokens = rs.randint(1, VOCAB + 1, n_tok)
    frames = []
    for tok in tokens:
        width = rs.randint(3, 6)
        band = np.zeros(FEAT, np.float32)
        lo = (tok - 1) * 3
        band[lo:lo + 3] = 1.0
        frames.extend(band + rs.normal(0, 0.15, FEAT).astype(np.float32)
                      for _ in range(width))
    return np.stack(frames), tokens


class SpeechBucketIter(mx.io.DataIter):
    """Buckets utterances by frame count; yields (data, label) batches
    with the bucket_key BucketingModule switches on."""

    def __init__(self, utts, batch_size):
        super().__init__(batch_size)
        self.buckets = sorted(BUCKETS)
        self.default_bucket_key = max(self.buckets)
        binned = {b: [] for b in self.buckets}
        for frames, tokens in utts:
            for b in self.buckets:
                if len(frames) <= b:
                    pad = np.zeros((b - len(frames), FEAT), np.float32)
                    lab = np.zeros(MAX_LABEL, np.float32)
                    lab[:len(tokens)] = tokens
                    binned[b].append((np.concatenate([frames, pad]), lab))
                    break
        self._batches = []
        for b, rows in binned.items():
            for i in range(0, len(rows) - batch_size + 1, batch_size):
                chunk = rows[i:i + batch_size]
                self._batches.append((b,
                                      np.stack([d for d, _ in chunk]),
                                      np.stack([l for _, l in chunk])))
        self._at = 0

    @property
    def provide_data(self):
        return [mx.io.DataDesc("data", (self.batch_size,
                                        self.default_bucket_key, FEAT))]

    @property
    def provide_label(self):
        return [mx.io.DataDesc("label", (self.batch_size, MAX_LABEL))]

    def reset(self):
        self._at = 0

    def next(self):
        if self._at == len(self._batches):
            raise StopIteration
        b, data, lab = self._batches[self._at]
        self._at += 1
        return mx.io.DataBatch(
            [mx.nd.array(data)], [mx.nd.array(lab)], pad=0, bucket_key=b,
            provide_data=[mx.io.DataDesc("data", data.shape)],
            provide_label=[mx.io.DataDesc("label", lab.shape)])


def sym_gen(seq_len):
    sym = mx.sym
    data = sym.Variable("data")          # (N, T, FEAT)
    label = sym.Variable("label")        # (N, MAX_LABEL), 0-padded
    cell = mx.rnn.FusedRNNCell(32, num_layers=1, mode="lstm",
                               bidirectional=True, prefix="bilstm_")
    outputs, _ = cell.unroll(seq_len, data, layout="NTC",
                             merge_outputs=True)   # (N, T, 2H)
    head = sym.FullyConnected(outputs, num_hidden=VOCAB + 1, flatten=False,
                              name="head")         # (N, T, C)
    acts = sym.swapaxes(head, dim1=0, dim2=1)      # (T, N, C) for CTC
    loss = sym.CTCLoss(acts, label, name="ctc")
    ctc = sym.MakeLoss(loss, name="ctc_loss")
    # decodable per-frame probabilities ride along for inference
    probs = sym.BlockGrad(sym.softmax(head, axis=-1), name="frame_probs")
    return mx.sym.Group([ctc, probs]), ("data",), ("label",)


def greedy_decode(prob_tn):
    """argmax -> collapse repeats -> drop blanks (0)."""
    path = prob_tn.argmax(-1)
    out = []
    prev = -1
    for p in path:
        if p != prev and p != 0:
            out.append(int(p))
        prev = p
    return out


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    utts = [synth_utterance(rs) for _ in range(160)]
    it = SpeechBucketIter(utts, batch_size=16)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=max(BUCKETS),
                                 context=mx.context.current_context())
    mod.fit(it, num_epoch=25, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            # the fused cell packs weights into one flat vector, which
            # Xavier cannot shape-analyse — route it to a uniform init
            initializer=mx.init.Mixed(
                [".*parameters", ".*"],
                [mx.init.Uniform(0.08), mx.init.Xavier()]),
            eval_metric=mx.metric.Loss(output_names=["ctc_loss_output"],
                                       label_names=[]))

    # exact-transcription rate under greedy decoding
    it.reset()
    hit = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        probs = mod.get_outputs()[1].asnumpy()       # (N, T, C)
        labels = batch.label[0].asnumpy()
        for n in range(probs.shape[0]):
            want = [int(t) for t in labels[n] if t > 0]
            got = greedy_decode(probs[n])
            hit += got == want
            total += 1
    acc = hit / max(total, 1)
    print("greedy exact-transcription rate: %.3f over %d utterances"
          % (acc, total))
    assert acc >= 0.8, "CTC toy failed transcription bar: %.3f" % acc
    print("train_ctc_toy example OK")


if __name__ == "__main__":
    main()
