"""DCGAN on synthetic images (reference example/gan/dcgan.py role):
adversarial training through the Gluon API — two networks, two
trainers, alternating updates — shrunk to a CI-sized workload.

Run: python example/gan/dcgan.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

HW, NZ = 16, 16


def generator():
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # z (N, NZ, 1, 1) -> (N, 1, 16, 16)
        net.add(nn.Conv2DTranspose(32, 4, 1, 0, use_bias=False))   # 4x4
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(16, 4, 2, 1, use_bias=False))   # 8x8
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        net.add(nn.Conv2DTranspose(1, 4, 2, 1, use_bias=False))    # 16x16
        net.add(nn.Activation("tanh"))
    return net


def discriminator():
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(16, 4, 2, 1, use_bias=False))            # 8x8
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(32, 4, 2, 1, use_bias=False))            # 4x4
        net.add(nn.BatchNorm())
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(1, 4, 1, 0, use_bias=False))             # 1x1
    return net


def real_batch(rs, n):
    """'Real' data: soft blobs with a fixed orientation the G must learn."""
    yy, xx = np.mgrid[0:HW, 0:HW] / (HW - 1.0)
    imgs = []
    for _ in range(n):
        cx, cy = rs.uniform(0.3, 0.7, 2)
        img = np.exp(-(((xx - cx) ** 2 + (yy - cy) ** 2) / 0.02))
        imgs.append(img * 2 - 1)
    return nd.array(np.stack(imgs)[:, None].astype(np.float32))


def main():
    mx.random.seed(42)
    rs = np.random.RandomState(42)
    G, D = generator(), discriminator()
    G.initialize(mx.init.Normal(0.02))
    D.initialize(mx.init.Normal(0.02))
    gt = gluon.Trainer(G.collect_params(), "adam",
                       {"learning_rate": 2e-4, "beta1": 0.5})
    dt = gluon.Trainer(D.collect_params(), "adam",
                       {"learning_rate": 2e-4, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    batch = 16
    d_hist, g_hist = [], []
    for it in range(20):
        real = real_batch(rs, batch)
        z = nd.random.normal(shape=(batch, NZ, 1, 1))
        # D step: real -> 1, fake -> 0
        with autograd.record():
            out_r = D(real).reshape((-1,))
            out_f = D(G(z).detach()).reshape((-1,))
            d_loss = (loss_fn(out_r, nd.ones(batch)) +
                      loss_fn(out_f, nd.zeros(batch)))
        d_loss.backward()
        dt.step(batch)
        # G step: fool D
        with autograd.record():
            out = D(G(z)).reshape((-1,))
            g_loss = loss_fn(out, nd.ones(batch))
        g_loss.backward()
        gt.step(batch)
        d_hist.append(float(d_loss.mean().asnumpy()))
        g_hist.append(float(g_loss.mean().asnumpy()))
    print("D loss %.3f -> %.3f | G loss %.3f -> %.3f"
          % (d_hist[0], d_hist[-1], g_hist[0], g_hist[-1]))
    assert np.isfinite(d_hist).all() and np.isfinite(g_hist).all()
    # the discriminator must have learned SOMETHING against a frozen-
    # then-updated generator: its loss moves off the initial value
    assert abs(d_hist[-1] - d_hist[0]) > 1e-3
    print("dcgan example OK")


if __name__ == "__main__":
    main()
