"""Multivariate time-series forecasting (reference
example/multivariate_time_series/ role, CI-sized): an LSTM reads a
window of 3 correlated noisy channels and regresses the next value of
each channel (LinearRegressionOutput head on the final state).

Series: coupled sinusoids with phase noise — predictable but not
trivially linear.  CI bar: one-step-ahead MSE must be at least 4x
better than the persistence baseline (predict last value).

Run: python example/time_series/lstm_forecast.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

CHANNELS, WINDOW, HIDDEN = 3, 16, 48


def make_series(rs, length=3000):
    t = np.arange(length) * 0.15
    base = np.stack([np.sin(t), np.sin(1.7 * t + 1.0),
                     np.sin(0.6 * t) * np.cos(1.1 * t)], -1)
    return (base + rs.normal(0, 0.05, base.shape)).astype(np.float32)


def windows(series):
    xs, ys = [], []
    for i in range(len(series) - WINDOW - 1):
        xs.append(series[i:i + WINDOW])
        ys.append(series[i + WINDOW])
    return np.stack(xs), np.stack(ys)


def get_symbol():
    sym = mx.sym
    data = sym.Variable("data")               # (N, WINDOW, CHANNELS)
    cell = mx.rnn.LSTMCell(HIDDEN, prefix="lstm_")
    outputs, _ = cell.unroll(WINDOW, data, layout="NTC",
                             merge_outputs=False)
    pred = sym.FullyConnected(outputs[-1], num_hidden=CHANNELS,
                              name="head")
    return sym.LinearRegressionOutput(pred, sym.Variable("target"),
                                      name="forecast")


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    x, y = windows(make_series(rs))
    n_tr = 2400
    it_tr = mx.io.NDArrayIter(x[:n_tr], {"target": y[:n_tr]},
                              batch_size=64, shuffle=True)
    it_va = mx.io.NDArrayIter(x[n_tr:], {"target": y[n_tr:]},
                              batch_size=64)

    mod = mx.mod.Module(get_symbol(), label_names=("target",),
                        context=mx.context.current_context())
    mod.fit(it_tr, num_epoch=12, optimizer="adam",
            optimizer_params={"learning_rate": 3e-3},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.MSE(output_names=["forecast_output"],
                                      label_names=["target"]))

    metric = mx.metric.MSE(output_names=["forecast_output"],
                           label_names=["target"])
    mod.score(it_va, metric)
    model_mse = dict(metric.get_name_value())["mse"]
    persist_mse = float(((y[n_tr:] - x[n_tr:, -1]) ** 2).mean())
    print("one-step MSE: model %.5f vs persistence %.5f (%.1fx better)"
          % (model_mse, persist_mse, persist_mse / model_mse))
    assert model_mse * 4 <= persist_mse, (model_mse, persist_mse)
    print("lstm_forecast example OK")


if __name__ == "__main__":
    main()
