"""Matrix-factorization recommender (reference
example/recommenders/demo1-MF.ipynb role): user/item embeddings whose
dot product predicts ratings, trained symbolically with Module on a
synthetic low-rank ratings matrix.

Run: python example/recommenders/matrix_factorization.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def build_net(n_users, n_items, k):
    user = sym.Variable("user")
    item = sym.Variable("item")
    score = sym.Variable("score_label")
    u = sym.Embedding(user, input_dim=n_users, output_dim=k, name="user_emb")
    v = sym.Embedding(item, input_dim=n_items, output_dim=k, name="item_emb")
    pred = sym.sum(u * v, axis=1)
    return sym.LinearRegressionOutput(pred, score, name="score")


def main():
    mx.random.seed(3)
    rs = np.random.RandomState(3)
    n_users, n_items, k, n_obs = 200, 120, 8, 4096
    # ground-truth low-rank structure
    U = rs.normal(0, 1, (n_users, k)).astype(np.float32)
    V = rs.normal(0, 1, (n_items, k)).astype(np.float32)
    users = rs.randint(0, n_users, n_obs).astype(np.float32)
    items = rs.randint(0, n_items, n_obs).astype(np.float32)
    scores = (U[users.astype(int)] * V[items.astype(int)]).sum(1) \
        + rs.normal(0, 0.1, n_obs).astype(np.float32)

    it = mx.io.NDArrayIter({"user": users, "item": items},
                           {"score_label": scores},
                           batch_size=256, shuffle=True)
    mod = mx.mod.Module(build_net(n_users, n_items, k),
                        data_names=("user", "item"),
                        label_names=("score_label",),
                        context=mx.cpu())
    mod.fit(it, num_epoch=30, optimizer="adam",
            optimizer_params={"learning_rate": 0.05},
            initializer=mx.init.Normal(0.1),
            eval_metric=mx.metric.RMSE())
    rmse = dict(mod.score(it, mx.metric.RMSE()))["rmse"]
    print("final RMSE: %.3f" % rmse)
    assert rmse < 1.0, rmse        # var(scores) ~ k = 8, so 1.0 is learned
    print("matrix_factorization example OK")


if __name__ == "__main__":
    main()
