"""Faster-RCNN end-to-end on a synthetic detection task (the reference
example/rcnn/train_end2end.py role, CI-sized).

The full two-stage pipeline, exercising every rcnn op in composition:

  backbone convs -> RPN head
      -> rpn_cls  : SoftmaxOutput(multi_output, ignore=-1) on anchor labels
      -> rpn_bbox : smooth_l1 on anchor-encoded gt deltas (MakeLoss)
  -> SoftmaxActivation(channel) -> _contrib_MultiProposal (decode+NMS)
  -> ProposalTarget (python CustomOp, like the reference's
     example/rcnn proposal_target layer) matching rois to gt
  -> ROIPooling -> FC head
      -> rcnn cls : SoftmaxOutput on matched labels
      -> rcnn bbox: smooth_l1 on class-slot deltas (MakeLoss)

Anchor targets are computed in the data iterator (the reference
AnchorLoader role) with the same anchor layout the Proposal op decodes
((h*W+w)*A + a ordering, +1 width convention; the RPN softmax labels
are re-ordered channel-major to match the score reshape).  After
training, a toy AP@0.5 over FRESH held-out scenes must clear 0.6.

Run: python example/detection/train_frcnn_toy.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import operator as op_mod

HW = 64                 # image side
STRIDE = 8              # backbone downsampling
FEAT = HW // STRIDE     # feature side
SCALES = (2.0, 4.0)
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
POST_NMS = 16           # proposals per image
ROIS_PER_IMG = 16       # sampled rois per image after matching
NUM_CLASSES = 2         # background + square


# ---------------------------------------------------------------------------
# anchors (must mirror ops/contrib.py _rpn_anchors exactly)
# ---------------------------------------------------------------------------

def make_anchors():
    base = []
    for r in RATIOS:
        for s in SCALES:
            size = STRIDE * STRIDE
            ws = np.sqrt(size / r) * s / STRIDE
            hs = ws * r
            base.append([-ws * STRIDE / 2, -hs * STRIDE / 2,
                         ws * STRIDE / 2, hs * STRIDE / 2])
    base = np.asarray(base, np.float32)                      # (A,4)
    shift = np.arange(FEAT, dtype=np.float32) * STRIDE
    sy, sx = np.meshgrid(shift, shift, indexing="ij")
    shifts = np.stack([sx, sy, sx, sy], -1).reshape(-1, 4)   # (HW,4)
    return (shifts[:, None, :] + base[None]).reshape(-1, 4)  # (HW*A,4)


def iou_matrix(a, b):
    """IoU of (N,4) vs (M,4) corner boxes (+1 width convention)."""
    area_a = (a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1)
    area_b = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    iw = np.minimum(a[:, None, 2], b[None, :, 2]) - \
        np.maximum(a[:, None, 0], b[None, :, 0]) + 1
    ih = np.minimum(a[:, None, 3], b[None, :, 3]) - \
        np.maximum(a[:, None, 1], b[None, :, 1]) + 1
    inter = np.maximum(iw, 0) * np.maximum(ih, 0)
    return inter / (area_a[:, None] + area_b[None] - inter)


def encode_deltas(rois, gt):
    """(dx,dy,dw,dh) targets, matching the Proposal decode convention."""
    rw = rois[:, 2] - rois[:, 0] + 1
    rh = rois[:, 3] - rois[:, 1] + 1
    rcx = rois[:, 0] + rw / 2
    rcy = rois[:, 1] + rh / 2
    gw = gt[:, 2] - gt[:, 0] + 1
    gh = gt[:, 3] - gt[:, 1] + 1
    gcx = gt[:, 0] + gw / 2
    gcy = gt[:, 1] + gh / 2
    return np.stack([(gcx - rcx) / rw, (gcy - rcy) / rh,
                     np.log(gw / rw), np.log(gh / rh)], -1)


# ---------------------------------------------------------------------------
# data: bright squares + AnchorLoader-style RPN targets
# ---------------------------------------------------------------------------

def synthetic_scene(rs):
    img = rs.uniform(0, 0.1, (3, HW, HW)).astype(np.float32)
    size = rs.randint(HW // 4, HW // 2)
    x = rs.randint(0, HW - size)
    y = rs.randint(0, HW - size)
    img[:, y:y + size, x:x + size] += 0.8
    return img, np.array([x, y, x + size - 1, y + size - 1], np.float32)


def rpn_targets(anchors, gt_box):
    """Per-anchor labels (1/0/-1 ignore) + fg bbox deltas/weights."""
    ious = iou_matrix(anchors, gt_box[None])[:, 0]
    labels = -np.ones(len(anchors), np.float32)
    labels[ious < 0.3] = 0
    labels[ious >= 0.5] = 1
    labels[ious.argmax()] = 1     # gt must own one anchor
    deltas = np.zeros((len(anchors), 4), np.float32)
    weights = np.zeros((len(anchors), 4), np.float32)
    fg = labels == 1
    deltas[fg] = encode_deltas(anchors[fg], np.repeat(gt_box[None],
                                                      fg.sum(), 0))
    weights[fg] = 1.0
    return labels, deltas, weights


def build_dataset(rs, n):
    anchors = make_anchors()
    data, gts, lab, dlt, wts = [], [], [], [], []
    for _ in range(n):
        img, gt = synthetic_scene(rs)
        l, d, w = rpn_targets(anchors, gt)
        data.append(img)
        gts.append(np.concatenate([[1.0], gt]))     # [cls, x1,y1,x2,y2]
        # label positions must match Reshape(0,2,-1)'s channel-major
        # (a*H*W + h*W + w) order, not the anchors' (h*W+w)*A + a order
        lab.append(l.reshape(FEAT, FEAT, A).transpose(2, 0, 1).reshape(-1))
        # (A*4, H, W) layout: anchor-major channel groups of 4
        dlt.append(d.reshape(FEAT, FEAT, A * 4).transpose(2, 0, 1))
        wts.append(w.reshape(FEAT, FEAT, A * 4).transpose(2, 0, 1))
    return (np.stack(data), np.stack(gts)[:, None, :], np.stack(lab),
            np.stack(dlt), np.stack(wts))


# ---------------------------------------------------------------------------
# ProposalTarget custom op (the reference rcnn example implements this
# exact layer as a python CustomOp too)
# ---------------------------------------------------------------------------

@op_mod.register("toy_proposal_target")
class ProposalTargetProp(op_mod.CustomOpProp):
    def __init__(self, batch_size="0"):
        super().__init__(need_top_grad=False)
        self._batch = int(batch_size)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_out", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        B = in_shape[1][0]
        S = B * ROIS_PER_IMG
        return ([in_shape[0], in_shape[1]],
                [(S, 5), (S,), (S, 4 * NUM_CLASSES), (S, 4 * NUM_CLASSES)],
                [])

    def create_operator(self, ctx, shapes, dtypes):
        return ProposalTarget()


class ProposalTarget(op_mod.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        rois = in_data[0].asnumpy()          # (B*POST_NMS, 5)
        gts = in_data[1].asnumpy()           # (B, 1, 5) [cls,x1,y1,x2,y2]
        B = gts.shape[0]
        out_r = np.zeros((B * ROIS_PER_IMG, 5), np.float32)
        out_l = np.zeros((B * ROIS_PER_IMG,), np.float32)
        out_t = np.zeros((B * ROIS_PER_IMG, 4 * NUM_CLASSES), np.float32)
        out_w = np.zeros_like(out_t)
        for b in range(B):
            mine = rois[rois[:, 0] == b][:, 1:]
            gt = gts[b, 0]
            # gt box joins the roi pool (reference proposal_target does this)
            mine = np.concatenate([gt[None, 1:], mine], 0)
            ious = iou_matrix(mine, gt[None, 1:])[:, 0]
            order = np.argsort(-ious)[:ROIS_PER_IMG]
            picked = mine[order]
            piou = ious[order]
            npick = len(picked)
            sl = slice(b * ROIS_PER_IMG, b * ROIS_PER_IMG + npick)
            out_r[sl, 0] = b
            out_r[sl, 1:] = picked
            fg = piou >= 0.5
            cls = int(gt[0])
            out_l[sl] = np.where(fg, cls, 0).astype(np.float32)
            deltas = encode_deltas(picked, np.repeat(gt[None, 1:], npick, 0))
            cols = slice(4 * cls, 4 * cls + 4)
            out_t[sl, cols] = deltas * fg[:, None]
            out_w[sl, cols] = fg[:, None].astype(np.float32)
        for i, blob in enumerate((out_r, out_l, out_t, out_w)):
            self.assign(out_data[i], req[i], mx.nd.array(blob))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for i in range(len(in_grad)):
            self.assign(in_grad[i], req[i],
                        mx.nd.zeros(in_grad[i].shape))


# ---------------------------------------------------------------------------
# the two-stage symbol
# ---------------------------------------------------------------------------

def get_symbol_train(batch_size):
    sym = mx.sym
    data = sym.Variable("data")
    gt_boxes = sym.Variable("gt_boxes")
    rpn_label = sym.Variable("rpn_label")
    rpn_bbox_target = sym.Variable("rpn_bbox_target")
    rpn_bbox_weight = sym.Variable("rpn_bbox_weight")
    im_info = sym.Variable("im_info")

    body = data
    for i, nf in enumerate((16, 32, 64)):
        body = sym.Convolution(body, kernel=(3, 3), pad=(1, 1),
                               num_filter=nf, name="conv%d" % i)
        body = sym.Activation(body, act_type="relu")
        body = sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                           pool_type="max")

    rpn = sym.Convolution(body, kernel=(3, 3), pad=(1, 1), num_filter=64,
                          name="rpn_conv")
    rpn = sym.Activation(rpn, act_type="relu")
    rpn_cls_score = sym.Convolution(rpn, kernel=(1, 1), num_filter=2 * A,
                                    name="rpn_cls_score")
    rpn_bbox_pred = sym.Convolution(rpn, kernel=(1, 1), num_filter=4 * A,
                                    name="rpn_bbox_pred")

    # stage-1 losses
    score_r = sym.Reshape(rpn_cls_score, shape=(0, 2, -1),
                          name="rpn_cls_score_reshape")
    rpn_cls_prob = sym.SoftmaxOutput(score_r, rpn_label, multi_output=True,
                                     use_ignore=True, ignore_label=-1,
                                     normalization="valid",
                                     name="rpn_cls_prob")
    rpn_bbox_l = sym.smooth_l1(
        (rpn_bbox_pred - rpn_bbox_target) * rpn_bbox_weight, scalar=3.0,
        name="rpn_bbox_l1")
    rpn_bbox_loss = sym.MakeLoss(sym.sum(rpn_bbox_l) / batch_size,
                                 grad_scale=1.0, name="rpn_bbox_loss")

    # proposals (no gradient through decode/NMS, like the reference op)
    prob_for_prop = sym.Reshape(
        sym.SoftmaxActivation(
            sym.BlockGrad(score_r), mode="channel", name="rpn_cls_act"),
        shape=(0, 2 * A, FEAT, FEAT), name="rpn_cls_act_reshape")
    rois = sym.contrib.MultiProposal(
        prob_for_prop, sym.BlockGrad(rpn_bbox_pred), im_info,
        feature_stride=STRIDE, scales=SCALES, ratios=RATIOS,
        rpn_pre_nms_top_n=64, rpn_post_nms_top_n=POST_NMS,
        rpn_min_size=4, threshold=0.7, name="rois")

    target = sym.Custom(rois, gt_boxes, op_type="toy_proposal_target",
                        batch_size=str(batch_size), name="ptarget")
    rois_out, label, bbox_target, bbox_weight = (
        target[0], target[1], target[2], target[3])

    # stage 2: ROI head
    pooled = sym.ROIPooling(body, sym.BlockGrad(rois_out),
                            pooled_size=(4, 4), spatial_scale=1.0 / STRIDE,
                            name="roi_pool")
    flat = sym.Flatten(pooled)
    fc = sym.Activation(sym.FullyConnected(flat, num_hidden=128, name="fc6"),
                        act_type="relu")
    cls_score = sym.FullyConnected(fc, num_hidden=NUM_CLASSES,
                                   name="cls_score")
    cls_prob = sym.SoftmaxOutput(cls_score, sym.BlockGrad(label),
                                 normalization="batch", name="cls_prob")
    bbox_pred = sym.FullyConnected(fc, num_hidden=4 * NUM_CLASSES,
                                   name="bbox_pred")
    bbox_l = sym.smooth_l1((bbox_pred - bbox_target) * bbox_weight,
                           scalar=1.0, name="bbox_l1")
    bbox_loss = sym.MakeLoss(sym.sum(bbox_l) / batch_size, grad_scale=1.0,
                             name="bbox_loss")

    return sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss,
                      sym.BlockGrad(rois_out), sym.BlockGrad(label)])


# ---------------------------------------------------------------------------


def toy_ap(mod, it, gts, batch_size):
    """AP@0.5 proxy: fraction of images whose highest-scoring roi
    (by P(square)) overlaps gt at IoU>=0.5."""
    hits, total = 0, 0
    it.reset()
    for bi, batch in enumerate(it):
        mod.forward(batch, is_train=False)
        outs = [o.asnumpy() for o in mod.get_outputs()]
        cls_prob, rois_out = outs[2], outs[4]
        for b in range(batch_size):
            idx = bi * batch_size + b
            if idx >= len(gts):
                break
            rows = slice(b * ROIS_PER_IMG, (b + 1) * ROIS_PER_IMG)
            scores = cls_prob[rows, 1]
            boxes = rois_out[rows, 1:]
            best = boxes[scores.argmax()][None]
            iou = iou_matrix(best, gts[idx][None, 1:])[0, 0]
            hits += iou >= 0.5
            total += 1
    return hits / max(total, 1)


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    n, batch_size = 64, 4
    data, gts, lab, dlt, wts = build_dataset(rs, n)
    im_info = np.tile(np.array([[HW, HW, 1.0]], np.float32), (n, 1))

    it = mx.io.NDArrayIter(
        {"data": data, "gt_boxes": gts, "im_info": im_info},
        {"rpn_label": lab, "rpn_bbox_target": dlt, "rpn_bbox_weight": wts},
        batch_size=batch_size)

    net = get_symbol_train(batch_size)
    mod = mx.mod.Module(
        net, context=mx.context.current_context(),
        data_names=("data", "gt_boxes", "im_info"),
        label_names=("rpn_label", "rpn_bbox_target", "rpn_bbox_weight"))
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.02, "momentum": 0.9,
                              "wd": 5e-4},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss(output_names=["bbox_loss_output"],
                                       label_names=[]))

    # held-out evaluation: fresh scenes the model never trained on
    ev_data, ev_gts, ev_lab, ev_dlt, ev_wts = build_dataset(rs, 32)
    ev_info = np.tile(np.array([[HW, HW, 1.0]], np.float32), (32, 1))
    ev_it = mx.io.NDArrayIter(
        {"data": ev_data, "gt_boxes": ev_gts, "im_info": ev_info},
        {"rpn_label": ev_lab, "rpn_bbox_target": ev_dlt,
         "rpn_bbox_weight": ev_wts},
        batch_size=batch_size)
    ap = toy_ap(mod, ev_it, ev_gts[:, 0], batch_size)
    print("toy AP@0.5 = %.3f" % ap)
    assert ap >= 0.6, "two-stage detector failed the AP sanity bar: %f" % ap
    print("train_frcnn_toy example OK")


if __name__ == "__main__":
    main()
