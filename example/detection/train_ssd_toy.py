"""SSD end-to-end on a synthetic detection task (reference
example/ssd/train.py role, CI-sized): the full pipeline —
MultiBoxPrior anchors, MultiBoxTarget matching, joint softmax +
smooth-L1 training, MultiBoxDetection decode+NMS at the end — through
Module on the models/ssd.py symbol.

Run: python example/detection/train_ssd_toy.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import ssd


def synthetic_scene(rs, hw=64):
    """One bright square on a dark field; label row [cls, x1,y1,x2,y2]."""
    img = rs.uniform(0, 0.1, (3, hw, hw)).astype(np.float32)
    size = rs.randint(hw // 4, hw // 2)
    x = rs.randint(0, hw - size)
    y = rs.randint(0, hw - size)
    img[:, y:y + size, x:x + size] += 0.8
    box = np.array([0, x / hw, y / hw, (x + size) / hw, (y + size) / hw],
                   np.float32)
    return img, box


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    n, hw = 128, 64
    scenes = [synthetic_scene(rs, hw) for _ in range(n)]
    data = np.stack([img for img, _ in scenes])
    labels = np.stack([box for _, box in scenes])
    labels = labels[:, None, :]     # (N, 1, 5): one object per image

    net = ssd.get_symbol_train(num_classes=1)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.cpu())
    it = mx.io.NDArrayIter(data, {"label": labels}, batch_size=16,
                           shuffle=True, label_name="label")
    mod.fit(it, num_epoch=3, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "wd": 5e-4},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss(output_names=["loc_loss_output"],
                                       label_names=[]),
            allow_missing=False)

    # forward once and decode detections
    it.reset()
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    det = mod.get_outputs()[3].asnumpy()     # (N, anchors, 6)
    valid = det[0][det[0, :, 0] >= 0]
    print("detections in image 0:", valid.shape[0])
    assert np.isfinite(det).all()
    print("train_ssd_toy example OK")


if __name__ == "__main__":
    main()
