"""SSD end-to-end on a synthetic multi-object detection task (reference
example/ssd/train.py role, CI-sized): the full multibox loop —
MultiBoxPrior anchors over a 4-scale feature pyramid, MultiBoxTarget
matching with 3:1 negative mining, joint softmax + smooth-L1 training,
MultiBoxDetection decode+NMS — through Module on the models/ssd.py
symbol, evaluated with a detection AP metric against the ground truth.

Scenes hold 1-3 objects of two classes (bright squares, dark discs);
training must reach toy AP@0.5 >= 0.5 on the training distribution.

Run: python example/detection/train_ssd_toy.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import ssd

HW = 64
MAX_OBJ = 3
NUM_CLASSES = 2         # square, disc (background is implicit)


def synthetic_scene(rs):
    """1-3 non-overlapping objects; label rows [cls, x1,y1,x2,y2] /HW,
    padded with -1 rows to MAX_OBJ (the reference label convention)."""
    img = rs.uniform(0, 0.1, (3, HW, HW)).astype(np.float32)
    rows = np.full((MAX_OBJ, 5), -1.0, np.float32)
    taken = []
    n_obj = rs.randint(1, MAX_OBJ + 1)
    placed = 0
    for _ in range(20):
        if placed == n_obj:
            break
        size = rs.randint(HW // 4, HW // 2)
        x = rs.randint(0, HW - size)
        y = rs.randint(0, HW - size)
        box = (x, y, x + size, y + size)
        if any(not (box[2] < t[0] or t[2] < box[0] or box[3] < t[1]
                    or t[3] < box[1]) for t in taken):
            continue
        cls = rs.randint(0, NUM_CLASSES)
        if cls == 0:                      # bright square
            img[:, y:y + size, x:x + size] += 0.8
        else:                             # dark disc
            yy, xx = np.mgrid[0:size, 0:size]
            disc = ((yy - size / 2) ** 2 + (xx - size / 2) ** 2
                    <= (size / 2) ** 2)
            img[:, y:y + size, x:x + size] -= 0.9 * disc
        rows[placed] = [cls, x / HW, y / HW, (x + size) / HW,
                        (y + size) / HW]
        taken.append(box)
        placed += 1
    return img, rows


def box_iou(a, b):
    iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = iw * ih
    area = ((a[2] - a[0]) * (a[3] - a[1])
            + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / max(area, 1e-12)


def detection_ap(dets, labels, iou_thr=0.5, score_thr=0.6):
    """Toy AP: precision x recall over all images at one operating point.

    dets: (N, anchors, 6) rows [cls, score, x1,y1,x2,y2] — cls is
    1-BASED (background 0 is suppressed to -1 by MultiBoxDetection);
    labels: (N, MAX_OBJ, 5) gt rows with 0-based cls (cls<0 padded).
    """
    tp = fp = n_gt = 0
    for det, lab in zip(dets, labels):
        gt = [row for row in lab if row[0] >= 0]
        n_gt += len(gt)
        used = set()
        keep = det[(det[:, 0] >= 0) & (det[:, 1] >= score_thr)]
        for row in keep[np.argsort(-keep[:, 1])]:
            best_iou, best_j = 0.0, -1
            for j, g in enumerate(gt):
                if j in used or int(g[0]) != int(row[0]) - 1:
                    continue
                iou = box_iou(row[2:6], g[1:5])
                if iou > best_iou:
                    best_iou, best_j = iou, j
            if best_iou >= iou_thr:
                tp += 1
                used.add(best_j)
            else:
                fp += 1
    precision = tp / max(tp + fp, 1)
    recall = tp / max(n_gt, 1)
    return precision, recall, precision * recall


def main():
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    n, batch_size = 128, 16
    scenes = [synthetic_scene(rs) for _ in range(n)]
    data = np.stack([img for img, _ in scenes])
    labels = np.stack([rows for _, rows in scenes])

    net = ssd.get_symbol_train(num_classes=NUM_CLASSES)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.context.current_context())
    it = mx.io.NDArrayIter(data, {"label": labels}, batch_size=batch_size,
                           shuffle=True, label_name="label")
    steps_per_epoch = max(n // batch_size, 1)
    schedule = mx.lr_scheduler.MultiFactorScheduler(
        step=[24 * steps_per_epoch], factor=0.1)
    mod.fit(it, num_epoch=32, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "wd": 5e-4, "lr_scheduler": schedule},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss(output_names=["loc_loss_output"],
                                       label_names=[]))

    # detection eval: decode+NMS output vs ground truth.  Labels come
    # from the iterator batches — it shuffled at construction, so the
    # original array order would not match the forward order.
    it.reset()
    all_dets, all_labels = [], []
    for batch in it:
        mod.forward(batch, is_train=False)
        all_dets.append(mod.get_outputs()[3].asnumpy())
        all_labels.append(batch.label[0].asnumpy())
    dets = np.concatenate(all_dets)[:n]
    gt_rows = np.concatenate(all_labels)[:n]
    # detections are in [0,1] box coords like the labels
    precision, recall, ap = detection_ap(dets, gt_rows)
    print("toy AP@0.5: precision=%.3f recall=%.3f ap=%.3f"
          % (precision, recall, ap))
    assert np.isfinite(dets).all()
    assert ap >= 0.5, "SSD failed the detection-AP sanity bar: %.3f" % ap
    print("train_ssd_toy example OK")


if __name__ == "__main__":
    main()
