"""Sparse linear classification over a wide embedding (reference
example/sparse/linear_classification role): gradients stay row_sparse
(data, indices) through push -> reduce -> lazy SGD, and pulls move only
the touched rows — the vocab never densifies.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse as sp


def main():
    vocab, dim, classes = 100_000, 16, 2
    n, active = 512, 8            # each sample touches `active` features
    rs = np.random.RandomState(0)

    # ground truth: a sparse linear model over feature embeddings
    w_true = rs.normal(0, 1, (dim,)).astype(np.float32)
    feat_emb_true = rs.normal(0, 1, (vocab, dim)).astype(np.float32)
    feats = rs.randint(0, vocab, (n, active)).astype(np.int64)
    scores = feat_emb_true[feats].mean(1) @ w_true
    labels = (scores > 0).astype(np.float32)

    kv = mx.kv.create("local")
    emb0 = rs.normal(0, 0.1, (vocab, dim)).astype(np.float32)
    kv.init("emb", mx.nd.array(emb0))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=5.0))
    w = mx.nd.array(rs.normal(0, 1.0, (dim,)).astype(np.float32))

    def batch_loss_and_grads(idx):
        """Manual logistic regression over mean-pooled embeddings; the
        embedding grad is built as row_sparse — O(active) rows, not O(vocab)."""
        ids = feats[idx].reshape(-1)
        rows = sp.zeros_sparse("row_sparse", (vocab, dim))
        kv.row_sparse_pull("emb", out=rows, row_ids=mx.nd.array(ids))
        table = dict(zip(rows.indices.asnumpy().tolist(),
                         rows.data.asnumpy()))
        e = np.stack([np.mean([table[i] for i in f], 0) for f in feats[idx]])
        z = e @ w.asnumpy()
        p = 1.0 / (1.0 + np.exp(-z))
        err = (p - labels[idx]) / len(idx)           # dL/dz
        gw = e.T @ err
        ge_rows = np.repeat((err[:, None] * w.asnumpy()[None, :] / active),
                            active, axis=0)
        grad = sp.embedding_grad(ids, mx.nd.array(ge_rows.astype(np.float32)),
                                 vocab)
        loss = -np.mean(labels[idx] * np.log(p + 1e-8)
                        + (1 - labels[idx]) * np.log(1 - p + 1e-8))
        return loss, mx.nd.array(gw.astype(np.float32)), grad

    first = last = None
    for epoch in range(30):
        order = rs.permutation(n)
        for start in range(0, n, 64):
            idx = order[start:start + 64]
            loss, gw, gemb = batch_loss_and_grads(idx)
            if first is None:
                first = loss
            last = loss
            w -= 0.5 * gw                      # dense head update
            kv.push("emb", gemb)               # sparse lazy update
    print("loss: %.4f -> %.4f (vocab %d, %d active rows/step)"
          % (first, last, vocab, n * active))
    assert last < first * 0.7, (first, last)
    print("sparse linear_classification example OK")


if __name__ == "__main__":
    main()
