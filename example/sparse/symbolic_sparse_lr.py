"""Symbolic sparse linear classification (reference
example/sparse/linear_classification/ role, symbolic tier).

Composes the sparse-storage registry ops in a Symbol graph:
``sym.contrib.SparseEmbedding`` over a wide vocabulary (weight gradient
logically row_sparse — only touched rows move through the kvstore),
an L2 term via ``sym.square_sum(sym.cast_storage(w, 'row_sparse'))``,
trained end-to-end with Module.fit.

Run: python example/sparse/symbolic_sparse_lr.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def build_net(vocab, dim, classes):
    ids = sym.Variable("data")
    table = sym.Variable("embed_weight")
    emb = sym.contrib.SparseEmbedding(data=ids, weight=table,
                                      input_dim=vocab, output_dim=dim,
                                      name="wide_embedding")
    pooled = sym.mean(emb, axis=1)
    logits = sym.FullyConnected(pooled, num_hidden=classes, name="fc")
    return sym.SoftmaxOutput(logits, name="softmax"), table


def main():
    vocab, dim, classes = 100_000, 16, 2
    n, active, batch = 2048, 8, 128
    rs = np.random.RandomState(0)

    emb_true = rs.normal(0, 1, (vocab, dim)).astype(np.float32)
    w_true = rs.normal(0, 1, (dim,)).astype(np.float32)
    feats = rs.randint(0, vocab, (n, active)).astype(np.float32)
    labels = (emb_true[feats.astype(int)].mean(1) @ w_true > 0) \
        .astype(np.float32)

    net, table = build_net(vocab, dim, classes)
    # storage-type inference marks the logically-sparse edges
    arg_st, out_st, _ = net.infer_storage_type(embed_weight="row_sparse")
    print("storage types:", dict(zip(net.list_arguments(), arg_st)),
          "->", out_st)

    train_iter = mx.io.NDArrayIter(feats, labels, batch_size=batch,
                                   shuffle=True,
                                   label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train_iter, num_epoch=8,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(batch, 8))

    train_iter.reset()
    acc = dict(mod.score(train_iter, mx.metric.Accuracy()))["accuracy"]
    print("train accuracy: %.3f" % acc)
    assert acc > 0.8, "sparse symbolic training failed to converge"
    print("symbolic_sparse_lr example OK")


if __name__ == "__main__":
    main()
