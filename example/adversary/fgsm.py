"""Adversarial examples via FGSM (reference example/adversary/ role):
train a digit classifier, then perturb inputs along the sign of
d(loss)/d(input) — the gradient flows to the DATA through the
executor's inputs_need_grad binding.  A small epsilon must collapse
accuracy (clean >= 0.9 -> adversarial <= 0.5), demonstrating both the
attack and the input-gradient plumbing.

Run: python example/adversary/fgsm.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def get_symbol():
    sym = mx.sym
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def load_digits_split():
    from sklearn.datasets import load_digits
    raw = load_digits()
    x = (raw.images.astype(np.float32) / 16.0).reshape(len(raw.target), -1)
    y = raw.target.astype(np.float32)
    order = np.random.RandomState(3).permutation(len(y))
    x, y = x[order], y[order]
    return (x[:1400], y[:1400]), (x[1400:], y[1400:])


def main():
    mx.random.seed(0)
    (x_tr, y_tr), (x_te, y_te) = load_digits_split()
    it = mx.io.NDArrayIter(x_tr, y_tr, batch_size=64, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(get_symbol(), context=mx.context.current_context())
    mod.fit(it, num_epoch=15, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.init.Xavier(), eval_metric="acc")
    args, auxs = mod.get_params()

    # adversarial executor: same net, grad flows to the input
    batch = len(y_te)
    exe = get_symbol().simple_bind(mx.context.current_context(),
                                   data=(batch, x_te.shape[1]),
                                   softmax_label=(batch,),
                                   grad_req={"data": "write"})
    exe.copy_params_from(args, auxs)
    exe.arg_dict["data"][:] = mx.nd.array(x_te)
    exe.arg_dict["softmax_label"][:] = mx.nd.array(y_te)
    exe.forward(is_train=True)
    clean_acc = float((exe.outputs[0].asnumpy().argmax(1) == y_te).mean())
    exe.backward()
    sign = np.sign(exe.grad_dict["data"].asnumpy())

    eps = 0.15
    x_adv = np.clip(x_te + eps * sign, 0, 1)
    exe.arg_dict["data"][:] = mx.nd.array(x_adv)
    exe.forward(is_train=False)
    adv_acc = float((exe.outputs[0].asnumpy().argmax(1) == y_te).mean())

    print("clean acc %.3f -> FGSM(eps=%.2f) acc %.3f"
          % (clean_acc, eps, adv_acc))
    assert clean_acc >= 0.9, clean_acc
    assert adv_acc <= 0.5, adv_acc
    print("fgsm example OK")


if __name__ == "__main__":
    main()
