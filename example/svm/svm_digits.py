"""SVM-headed classification (reference example/svm_mnist/ role): the
same MLP trunk trained twice — once with SoftmaxOutput, once with
SVMOutput (squared hinge, the reference example's regularization=True
mode) — on the real bundled scanned digits; both must clear 0.9
held-out accuracy, demonstrating the margin head as a drop-in for the
softmax head.

Run: python example/svm/svm_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def net_with(head):
    sym = mx.sym
    body = sym.Variable("data")
    body = sym.Activation(sym.FullyConnected(body, num_hidden=64,
                                             name="fc1"), act_type="relu")
    body = sym.FullyConnected(body, num_hidden=10, name="fc2")
    if head == "svm":
        return sym.SVMOutput(body, sym.Variable("softmax_label"),
                             use_linear=False, name="svm")
    return sym.SoftmaxOutput(body, name="softmax")


def main():
    mx.random.seed(0)
    from sklearn.datasets import load_digits
    raw = load_digits()
    x = (raw.images.astype(np.float32) / 16.0).reshape(len(raw.target), -1)
    y = raw.target.astype(np.float32)
    order = np.random.RandomState(4).permutation(len(y))
    x, y = x[order], y[order]
    n_tr = 1400

    # margin heads want a gentler step than softmax (raw-score
    # gradients are O(margin) per violating class, not probabilities)
    hyper = {"softmax": {"learning_rate": 0.1, "momentum": 0.9,
                         "wd": 1e-4},
             "svm": {"learning_rate": 0.03, "wd": 1e-4}}
    accs = {}
    for head in ("softmax", "svm"):
        it = mx.io.NDArrayIter(x[:n_tr], y[:n_tr], batch_size=64,
                               shuffle=True, label_name="softmax_label")
        mod = mx.mod.Module(net_with(head),
                            context=mx.context.current_context())
        mod.fit(it, num_epoch=15, optimizer="sgd",
                optimizer_params=hyper[head],
                initializer=mx.init.Xavier(), eval_metric="acc")
        va = mx.io.NDArrayIter(x[n_tr:], y[n_tr:], batch_size=64,
                               label_name="softmax_label")
        # both heads output per-class scores: argmax accuracy applies
        accs[head] = dict(mod.score(va, "acc"))["accuracy"]

    print("held-out: softmax %.3f | svm (squared hinge) %.3f"
          % (accs["softmax"], accs["svm"]))
    assert min(accs.values()) >= 0.9, accs
    print("svm_digits example OK")


if __name__ == "__main__":
    main()
