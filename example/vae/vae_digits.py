"""Variational autoencoder (reference example/vae/ role): encoder to a
diagonal Gaussian (mu, logvar), the reparameterization trick sampled
IN-GRAPH with the framework's RNG-carrying normal op, KL regularization
via MakeLoss, Bernoulli-style reconstruction — on the real bundled
scanned digits.

CI bars: ELBO reconstruction MSE <= 0.04 and the decoder must generate:
samples decoded from the prior N(0, I) land closer to the digit data
manifold than noise does (mean nearest-neighbour distance ratio <= 0.6).

Run: python example/vae/vae_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx

LATENT = 6


def vae_symbol(batch_size):
    sym = mx.sym
    data = sym.Variable("data")
    enc = sym.Activation(sym.FullyConnected(data, num_hidden=48,
                                            name="enc1"), act_type="relu")
    mu = sym.FullyConnected(enc, num_hidden=LATENT, name="mu")
    logvar = sym.FullyConnected(enc, num_hidden=LATENT, name="logvar")
    # reparameterization: z = mu + exp(logvar/2) * eps; eps drawn
    # in-graph by the RNG-carrying normal op (batch shape is static
    # under XLA, like everything else)
    eps = sym._random_normal(loc=0.0, scale=1.0,
                             shape=(batch_size, LATENT), name="eps")
    z = mu + sym.exp(logvar / 2.0) * eps
    dec = sym.Activation(sym.FullyConnected(z, num_hidden=48, name="dec1"),
                         act_type="relu")
    recon = sym.sigmoid(sym.FullyConnected(dec, num_hidden=64, name="dec2"))
    out = sym.LinearRegressionOutput(recon, sym.Variable("recon_label"),
                                     name="recon_out")
    kl = sym.MakeLoss(
        -0.5 * sym.mean(1 + logvar - mu * mu - sym.exp(logvar)),
        grad_scale=0.05, name="kl_loss")
    return mx.sym.Group([out, kl, sym.BlockGrad(mu, name="mu_tap")])


def decoder_forward(args, z):
    """Run the trained decoder weights on latents z (numpy)."""
    h = np.maximum(z @ args["dec1_weight"].asnumpy().T
                   + args["dec1_bias"].asnumpy(), 0)
    x = h @ args["dec2_weight"].asnumpy().T + args["dec2_bias"].asnumpy()
    return 1.0 / (1.0 + np.exp(-x))


def main():
    mx.random.seed(0)
    from sklearn.datasets import load_digits
    raw = load_digits()
    x = (raw.images.astype(np.float32) / 16.0).reshape(len(raw.target), -1)
    rs = np.random.RandomState(5)
    x = x[rs.permutation(len(x))]

    it = mx.io.NDArrayIter(x, {"recon_label": x}, batch_size=128,
                           shuffle=True)
    mod = mx.mod.Module(vae_symbol(128), label_names=("recon_label",),
                        context=mx.context.current_context())
    mod.fit(it, num_epoch=40, optimizer="adam",
            optimizer_params={"learning_rate": 2e-3},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.MSE(output_names=["recon_out_output"],
                                      label_names=["recon_label"]))

    # reconstruction quality
    ev = mx.io.NDArrayIter(x, {"recon_label": x}, batch_size=128)
    mse_metric = mx.metric.MSE(output_names=["recon_out_output"],
                               label_names=["recon_label"])
    mod.score(ev, mse_metric)
    mse = dict(mse_metric.get_name_value())["mse"]

    # generative quality: decode prior samples, compare NN-distance to
    # data against equally-sized uniform-noise images
    args, _ = mod.get_params()
    z = rs.normal(0, 1, (64, LATENT)).astype(np.float32)
    fakes = decoder_forward(args, z)
    noise = rs.uniform(0, 1, fakes.shape).astype(np.float32)

    def mean_nn_dist(batch):
        d = ((batch[:, None, :] - x[None, :500, :]) ** 2).sum(-1)
        return float(np.sqrt(d.min(1)).mean())

    gen_d, noise_d = mean_nn_dist(fakes), mean_nn_dist(noise)
    ratio = gen_d / noise_d
    print("recon MSE %.4f; NN-dist decoded %.3f vs noise %.3f (ratio %.2f)"
          % (mse, gen_d, noise_d, ratio))
    assert mse <= 0.04, mse
    assert ratio <= 0.6, ratio
    print("vae example OK")


if __name__ == "__main__":
    main()
