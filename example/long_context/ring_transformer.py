"""Long-context training over a device mesh: ring-attention sequence
parallelism + jax.remat activation mirroring.

New-work showcase (SURVEY §5.7: the reference predates attention): the
sequence axis is sharded over the 'sp' mesh axis, K/V blocks rotate over
ICI with compute overlapping transfer, and MXNET_BACKWARD_DO_MIRROR-style
remat trades activations for recompute so sequence length scales.

Run with 8 virtual devices:  JAX_PLATFORMS=cpu python ring_transformer.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
# must happen BEFORE the backend initializes (probing jax.default_backend
# or jax.devices first would lock in a single CPU device)
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:   # pre-0.4.34 jax: only XLA_FLAGS works
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8")

import jax.numpy as jnp
import numpy as np

from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.parallel.ring import ring_attention
from mxnet_tpu.executor import apply_backward_mirror


def transformer_block(params, x, mesh):
    """Pre-norm attention block; attention runs ring-parallel over 'sp'."""
    B, T, D = x.shape
    H, Dh = 4, D // 4
    xn = (x - x.mean(-1, keepdims=True)) / (x.std(-1, keepdims=True) + 1e-5)
    q = (xn @ params["wq"]).reshape(B, T, H, Dh)
    k = (xn @ params["wk"]).reshape(B, T, H, Dh)
    v = (xn @ params["wv"]).reshape(B, T, H, Dh)
    attn = ring_attention(q, k, v, mesh, axis="sp", causal=True)
    x = x + attn.reshape(B, T, D) @ params["wo"]
    h = jax.nn.gelu(x @ params["w1"])
    return x + h @ params["w2"]


def main():
    n_dev = len(jax.devices())
    mesh = make_mesh((n_dev,), ("sp",))
    B, T, D = 2, n_dev * 32, 64   # sequence sharded n_dev ways
    rs = np.random.RandomState(0)
    params = {k: jnp.asarray(rs.normal(0, 0.05, s).astype(np.float32))
              for k, s in [("wq", (D, D)), ("wk", (D, D)), ("wv", (D, D)),
                           ("wo", (D, D)), ("w1", (D, 4 * D)),
                           ("w2", (4 * D, D))]}
    x = jnp.asarray(rs.normal(0, 1, (B, T, D)).astype(np.float32))

    def loss_fn(params, x):
        y = transformer_block(params, x, mesh)
        return jnp.mean(y ** 2)

    # activation mirroring: recompute the forward during backward
    loss_remat = apply_backward_mirror(loss_fn, "dots")
    grads = jax.grad(loss_remat)(params, x)
    gnorm = float(sum(jnp.abs(g).sum() for g in grads.values()))
    print("seq len %d over %d devices; grad norm %.4f" % (T, n_dev, gnorm))
    assert np.isfinite(gnorm) and gnorm > 0

    # numerics: remat == no-remat
    g2 = jax.grad(loss_fn)(params, x)
    for k in grads:
        np.testing.assert_allclose(np.asarray(grads[k]), np.asarray(g2[k]),
                                   rtol=1e-4, atol=1e-6)
    print("ring_transformer example OK")


if __name__ == "__main__":
    main()
