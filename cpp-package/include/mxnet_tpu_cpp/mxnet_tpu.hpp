/*
 * mxnet_tpu C++ frontend — header-only RAII wrappers over the C ABI
 * (capi/mxnet_tpu_c_api.h).
 *
 * Role analog of the reference cpp-package (cpp-package/include/mxnet-cpp:
 * NDArray/Symbol/Executor/Context over c_api.h), designed fresh for this
 * runtime: handles are shared_ptr-managed, ops are looked up once through
 * a cached registry map, and errors surface as exceptions carrying
 * MXGetLastError().
 *
 * Usage:
 *   #include <mxnet_tpu_cpp/mxnet_tpu.hpp>
 *   using namespace mxtpu;
 *   auto x = Symbol::Variable("data");
 *   auto fc = Symbol::Op("FullyConnected", {x}, {{"num_hidden", "64"}});
 *   ...
 */
#ifndef MXNET_TPU_CPP_HPP_
#define MXNET_TPU_CPP_HPP_

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "mxnet_tpu_c_api.h"

namespace mxtpu {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string &what) : std::runtime_error(what) {}
};

inline void Check(int rc) {
  if (rc != 0) throw Error(MXGetLastError());
}

/* ---- Context -------------------------------------------------------- */

struct Context {
  int dev_type;
  int dev_id;
  static Context Cpu(int id = 0) { return {1, id}; }
  static Context Gpu(int id = 0) { return {2, id}; }  // alias of the chip
  static Context Tpu(int id = 0) { return {2, id}; }
};

/* ---- NDArray -------------------------------------------------------- */

class NDArray {
 public:
  NDArray() = default;

  explicit NDArray(NDArrayHandle h) : h_(Wrap(h)) {}

  NDArray(const std::vector<mx_uint> &shape, Context ctx = Context::Cpu()) {
    NDArrayHandle h;
    Check(MXNDArrayCreate(shape.data(), (mx_uint)shape.size(), ctx.dev_type,
                          ctx.dev_id, 0, &h));
    h_ = Wrap(h);
  }

  NDArray(const std::vector<float> &data, const std::vector<mx_uint> &shape,
          Context ctx = Context::Cpu())
      : NDArray(shape, ctx) {
    CopyFrom(data);
  }

  NDArrayHandle handle() const { return h_.get(); }
  bool IsNone() const { return !h_; }

  std::vector<mx_uint> Shape() const {
    mx_uint ndim;
    const mx_uint *dims;
    Check(MXNDArrayGetShape(h_.get(), &ndim, &dims));
    return std::vector<mx_uint>(dims, dims + ndim);
  }

  size_t Size() const {
    size_t n = 1;
    for (auto d : Shape()) n *= d;
    return n;
  }

  void CopyFrom(const std::vector<float> &data) {
    Check(MXNDArraySyncCopyFromCPU(h_.get(), data.data(), data.size()));
  }

  std::vector<float> CopyTo() const {
    std::vector<float> out(Size());
    Check(MXNDArraySyncCopyToCPU(h_.get(), out.data(), out.size()));
    return out;
  }

  float Scalar() const { return CopyTo().at(0); }

 private:
  static std::shared_ptr<void> Wrap(NDArrayHandle h) {
    return std::shared_ptr<void>(h, [](void *p) {
      if (p) MXNDArrayFree(p);
    });
  }
  std::shared_ptr<void> h_;
};

/* ---- operator registry ---------------------------------------------- */

using KwArgs = std::map<std::string, std::string>;

inline AtomicSymbolCreator FindOp(const std::string &name) {
  static std::map<std::string, AtomicSymbolCreator> cache = [] {
    std::map<std::string, AtomicSymbolCreator> m;
    mx_uint n;
    AtomicSymbolCreator *creators;
    Check(MXSymbolListAtomicSymbolCreators(&n, &creators));
    for (mx_uint i = 0; i < n; ++i) {
      const char *cname;
      Check(MXSymbolGetAtomicSymbolName(creators[i], &cname));
      m.emplace(cname, creators[i]);
    }
    return m;
  }();
  auto it = cache.find(name);
  if (it == cache.end()) throw Error("unknown operator: " + name);
  return it->second;
}

/* Imperative op call: outputs created by the runtime. */
inline std::vector<NDArray> Invoke(const std::string &op,
                                   const std::vector<NDArray> &inputs,
                                   const KwArgs &kwargs = {},
                                   std::vector<NDArray> outputs = {}) {
  std::vector<NDArrayHandle> in;
  in.reserve(inputs.size());
  for (auto &a : inputs) in.push_back(a.handle());
  std::vector<const char *> keys, vals;
  for (auto &kv : kwargs) {
    keys.push_back(kv.first.c_str());
    vals.push_back(kv.second.c_str());
  }
  int n_out = (int)outputs.size();
  std::vector<NDArrayHandle> out_h;
  for (auto &o : outputs) out_h.push_back(o.handle());
  NDArrayHandle *out_ptr = out_h.empty() ? nullptr : out_h.data();
  Check(MXImperativeInvoke(FindOp(op), (int)in.size(), in.data(), &n_out,
                           &out_ptr, (int)keys.size(), keys.data(),
                           vals.data()));
  // with caller-provided outputs the runtime validates the count and
  // fills them in place (wrong count -> MXGetLastError via Check above)
  if (!outputs.empty()) return outputs;
  std::vector<NDArray> fresh;
  for (int i = 0; i < n_out; ++i) fresh.emplace_back(out_ptr[i]);
  return fresh;
}

/* ---- Symbol --------------------------------------------------------- */

class Symbol {
 public:
  Symbol() = default;
  explicit Symbol(SymbolHandle h) : h_(Wrap(h)) {}

  static Symbol Variable(const std::string &name) {
    SymbolHandle h;
    Check(MXSymbolCreateVariable(name.c_str(), &h));
    return Symbol(h);
  }

  /* Op(inputs..., kwargs) — positional composition, auto-named. */
  static Symbol Op(const std::string &op, const std::vector<Symbol> &inputs,
                   const KwArgs &kwargs = {}, const std::string &name = "") {
    std::vector<const char *> keys, vals;
    for (auto &kv : kwargs) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    SymbolHandle h;
    Check(MXSymbolCreateAtomicSymbol(FindOp(op), (mx_uint)keys.size(),
                                     keys.data(), vals.data(), &h));
    std::vector<SymbolHandle> in;
    for (auto &s : inputs) in.push_back(s.handle());
    Check(MXSymbolCompose(h, name.empty() ? nullptr : name.c_str(),
                          (mx_uint)in.size(), nullptr, in.data()));
    return Symbol(h);
  }

  SymbolHandle handle() const { return h_.get(); }

  std::vector<std::string> ListArguments() const { return List(0); }
  std::vector<std::string> ListOutputs() const { return List(1); }
  std::vector<std::string> ListAuxiliaryStates() const { return List(2); }

  /* Infer all argument shapes from the named inputs. */
  std::map<std::string, std::vector<mx_uint>> InferArgShapes(
      const std::map<std::string, std::vector<mx_uint>> &known) const {
    std::vector<const char *> keys;
    std::vector<mx_uint> csr{0};
    std::vector<mx_uint> cdata;
    for (auto &kv : known) {
      keys.push_back(kv.first.c_str());
      cdata.insert(cdata.end(), kv.second.begin(), kv.second.end());
      csr.push_back((mx_uint)cdata.size());
    }
    mx_uint in_n, out_n, aux_n;
    const mx_uint *in_nd, *out_nd, *aux_nd;
    const mx_uint **in_dims, **out_dims, **aux_dims;
    int complete;
    Check(MXSymbolInferShape(h_.get(), (mx_uint)keys.size(), keys.data(),
                             csr.data(), cdata.data(), &in_n, &in_nd,
                             &in_dims, &out_n, &out_nd, &out_dims, &aux_n,
                             &aux_nd, &aux_dims, &complete));
    if (!complete)
      throw Error("shape inference incomplete: provide shapes for all "
                  "graph inputs");
    auto args = ListArguments();
    std::map<std::string, std::vector<mx_uint>> out;
    for (mx_uint i = 0; i < in_n && i < args.size(); ++i)
      out[args[i]] = std::vector<mx_uint>(in_dims[i], in_dims[i] + in_nd[i]);
    return out;
  }

 private:
  std::vector<std::string> List(int what) const {
    mx_uint n;
    const char **names;
    if (what == 0)
      Check(MXSymbolListArguments(h_.get(), &n, &names));
    else if (what == 1)
      Check(MXSymbolListOutputs(h_.get(), &n, &names));
    else
      Check(MXSymbolListAuxiliaryStates(h_.get(), &n, &names));
    return std::vector<std::string>(names, names + n);
  }
  static std::shared_ptr<void> Wrap(SymbolHandle h) {
    return std::shared_ptr<void>(h, [](void *p) {
      if (p) MXSymbolFree(p);
    });
  }
  std::shared_ptr<void> h_;
};

/* ---- Executor ------------------------------------------------------- */

enum class GradReq : mx_uint { kNull = 0, kWrite = 1, kAdd = 3 };

class Executor {
 public:
  /* Bind with explicit arg/grad arrays in ListArguments() order. */
  Executor(const Symbol &sym, Context ctx, std::vector<NDArray> args,
           std::vector<NDArray> arg_grads, std::vector<GradReq> reqs,
           std::vector<NDArray> aux = {})
      : sym_(sym), args_(std::move(args)), grads_(std::move(arg_grads)),
        aux_(std::move(aux)) {
    std::vector<NDArrayHandle> in, g, ax;
    std::vector<mx_uint> r;
    for (auto &a : args_) in.push_back(a.handle());
    for (auto &a : grads_) g.push_back(a.IsNone() ? nullptr : a.handle());
    for (auto &q : reqs) r.push_back((mx_uint)q);
    for (auto &a : aux_) ax.push_back(a.handle());
    ExecutorHandle h;
    Check(MXExecutorBind(sym_.handle(), ctx.dev_type, ctx.dev_id,
                         (mx_uint)in.size(), in.data(), g.data(), r.data(),
                         (mx_uint)ax.size(), ax.empty() ? nullptr : ax.data(),
                         &h));
    h_ = std::shared_ptr<void>(h, [](void *p) {
      if (p) MXExecutorFree(p);
    });
  }

  void Forward(bool is_train) {
    Check(MXExecutorForward(h_.get(), is_train ? 1 : 0));
    RefreshOutputs();
  }

  /* Backward with default head gradients (ones). */
  void Backward(const std::vector<NDArray> &head_grads = {}) {
    std::vector<NDArrayHandle> hg;
    for (auto &a : head_grads) hg.push_back(a.handle());
    Check(MXExecutorBackward(h_.get(), (mx_uint)hg.size(),
                             hg.empty() ? nullptr : hg.data()));
  }

  const std::vector<NDArray> &Outputs() const { return outputs_; }
  std::vector<NDArray> &Args() { return args_; }
  std::vector<NDArray> &Grads() { return grads_; }

 private:
  void RefreshOutputs() {
    mx_uint n;
    NDArrayHandle *outs;
    Check(MXExecutorOutputs(h_.get(), &n, &outs));
    outputs_.clear();
    for (mx_uint i = 0; i < n; ++i) outputs_.emplace_back(outs[i]);
  }
  Symbol sym_;
  std::vector<NDArray> args_, grads_, aux_, outputs_;
  std::shared_ptr<void> h_;
};

/* ---- SGD helper (cpp-package Optimizer role) ------------------------ */

class SGDOptimizer {
 public:
  explicit SGDOptimizer(float lr, float wd = 0.f) : lr_(lr), wd_(wd) {}

  void Update(NDArray &weight, const NDArray &grad) {
    Invoke("sgd_update", {weight, grad},
           {{"lr", std::to_string(lr_)}, {"wd", std::to_string(wd_)}},
           {weight});
  }

 private:
  float lr_, wd_;
};

}  // namespace mxtpu

#endif  // MXNET_TPU_CPP_HPP_
