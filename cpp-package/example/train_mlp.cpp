/*
 * cpp-package end-to-end example: train a 2-layer MLP on a synthetic
 * linearly-separable problem entirely through the C++ frontend
 * (mxnet_tpu.hpp over the C ABI).
 *
 * Role analog of the reference cpp-package/example/mlp.cpp: build the net
 * with Symbol::Op, bind an Executor, Forward/Backward, SGD updates, and
 * verify the loss decreases.
 */
#include <unistd.h>

#include <cmath>
#include <cstdio>
#include <random>
#include <vector>

#include <mxnet_tpu_cpp/mxnet_tpu.hpp>

using namespace mxtpu;

int main() {
  const mx_uint kBatch = 64, kDim = 16, kHidden = 32, kClasses = 2;

  // net: data -> FC(32) -> relu -> FC(2) -> SoftmaxOutput
  auto data = Symbol::Variable("data");
  auto label = Symbol::Variable("softmax_label");
  auto fc1 = Symbol::Op("FullyConnected", {data},
                        {{"num_hidden", std::to_string(kHidden)}}, "fc1");
  auto act = Symbol::Op("Activation", {fc1}, {{"act_type", "relu"}});
  auto fc2 = Symbol::Op("FullyConnected", {act},
                        {{"num_hidden", std::to_string(kClasses)}}, "fc2");
  auto net = Symbol::Op("SoftmaxOutput", {fc2, label},
                        {{"normalization", "batch"}}, "softmax");

  // synthetic separable data
  std::mt19937 rng(7);
  std::normal_distribution<float> gauss(0.f, 1.f);
  std::vector<float> w_true(kDim), xs(kBatch * kDim), ys(kBatch);
  for (auto &w : w_true) w = gauss(rng);
  for (mx_uint i = 0; i < kBatch; ++i) {
    float dot = 0;
    for (mx_uint j = 0; j < kDim; ++j) {
      xs[i * kDim + j] = gauss(rng);
      dot += xs[i * kDim + j] * w_true[j];
    }
    ys[i] = dot > 0 ? 1.f : 0.f;
  }

  // shape inference fills the parameter shapes
  auto shapes = net.InferArgShapes({{"data", {kBatch, kDim}},
                                    {"softmax_label", {kBatch}}});
  auto arg_names = net.ListArguments();
  std::vector<NDArray> args, grads;
  std::vector<GradReq> reqs;
  std::normal_distribution<float> init(0.f, 0.1f);
  for (auto &name : arg_names) {
    NDArray arr(shapes.at(name));
    if (name == "data") {
      arr.CopyFrom(xs);
      reqs.push_back(GradReq::kNull);
      grads.emplace_back();
    } else if (name == "softmax_label") {
      arr.CopyFrom(ys);
      reqs.push_back(GradReq::kNull);
      grads.emplace_back();
    } else {
      std::vector<float> w(arr.Size());
      for (auto &v : w) v = init(rng);
      arr.CopyFrom(w);
      reqs.push_back(GradReq::kWrite);
      grads.emplace_back(arr.Shape());
    }
    args.push_back(arr);
  }

  Executor exec(net, Context::Cpu(), args, grads, reqs);
  SGDOptimizer sgd(0.5f);

  auto loss_of = [&](const std::vector<float> &probs) {
    double nll = 0;
    for (mx_uint i = 0; i < kBatch; ++i) {
      float p = probs[i * kClasses + (int)ys[i]];
      nll -= std::log(p > 1e-8f ? p : 1e-8f);
    }
    return (float)(nll / kBatch);
  };

  float first = 0, last = 0;
  for (int step = 0; step < 25; ++step) {
    exec.Forward(true);
    auto probs = exec.Outputs()[0].CopyTo();
    last = loss_of(probs);
    if (step == 0) first = last;
    exec.Backward();
    for (size_t i = 0; i < arg_names.size(); ++i) {
      if (reqs[i] == GradReq::kWrite)
        sgd.Update(exec.Args()[i], exec.Grads()[i]);
    }
  }
  std::printf("loss: %.4f -> %.4f\n", first, last);
  if (!(last < first * 0.8f) || !std::isfinite(last)) {
    std::fprintf(stderr, "FAILED: loss did not decrease enough\n");
    return 1;
  }
  std::printf("cpp-package MLP training: OK\n");
  // skip static-destructor teardown: the embedded interpreter's JAX
  // worker threads race it and segfault AFTER success (see test_lenet.c)
  std::fflush(nullptr);
  _exit(0);
}
