"""Flash vjp parity across the MXNET_FLASH_MIN_SEQ dispatch boundary
(round-6 satellite): below the threshold the op IS the einsum
formulation — grads bit-match it; at/above the threshold the Pallas
flash fwd+bwd pair must agree with the einsum vjp to float tolerance —
under jit, and under the trainer's in-jit grad_accum scan.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.models.transformer import get_symbol
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.parallel.trainer import ShardedTrainer
from mxnet_tpu.ops.nn import _contrib_fused_attention
from mxnet_tpu.ops.registry import get_op


def _op_fn(T, flash_min_seq, causal=True):
    """The registered op body with a pinned dispatch threshold — the
    exact code path Symbol/Gluon models trace."""
    op = get_op("_contrib_fused_attention")
    attrs = op.parse_attrs(dict(causal=causal,
                                flash_min_seq=flash_min_seq))

    def f(q, k, v):
        return op.fn(attrs, q, k, v)

    return f


def _einsum_ref(q, k, v, causal=True):
    scale = float(1.0 / np.sqrt(q.shape[-1]))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _grads(fn, q, k, v, g):
    def loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) *
                       g.astype(jnp.float32))

    return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)


def test_vjp_bit_matches_einsum_below_threshold():
    """T < flash_min_seq: the op runs the einsum formulation end to end;
    its jitted grads are BIT-identical to the reference einsum vjp."""
    rs = np.random.RandomState(0)
    B, T, H, D = 2, 16, 2, 8
    q, k, v, g = (jnp.asarray(rs.normal(0, 1, (B, T, H, D))
                              .astype(np.float32)) for _ in range(4))
    got = _grads(_op_fn(T, flash_min_seq=32), q, k, v, g)
    want = _grads(lambda a, b, c: _einsum_ref(a, b, c), q, k, v, g)
    for x, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(w))


@pytest.mark.parametrize("dtype,rtol,atol",
                         [(np.float32, 1e-4, 1e-5),
                          ("bfloat16", 0.1, 0.05)],
                         ids=["f32", "bf16"])
def test_vjp_matches_einsum_above_threshold(dtype, rtol, atol):
    """T >= flash_min_seq: the Pallas flash fwd+bwd under jit agrees
    with the einsum vjp — f32 to float roundoff, bf16 within bf16
    tolerance."""
    rs = np.random.RandomState(1)
    B, T, H, D = 2, 32, 2, 8
    mk = lambda: jnp.asarray(rs.normal(0, 1, (B, T, H, D))
                             .astype(np.float32))
    q, k, v, g = mk(), mk(), mk(), mk()
    if dtype == "bfloat16":
        q, k, v, g = (x.astype(jnp.bfloat16) for x in (q, k, v, g))
    got = _grads(_op_fn(T, flash_min_seq=T), q, k, v, g)
    f32 = lambda x: jnp.asarray(np.asarray(x, np.float32))
    want = _grads(lambda a, b, c: _einsum_ref(a, b, c),
                  f32(q), f32(k), f32(v), f32(g))
    for x, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(w), rtol=rtol, atol=atol)


def _lm_trainer(flash_min_seq, accum, seed=5):
    spec = MeshSpec(make_mesh((2,), ("dp",)))
    net = get_symbol(vocab_size=12, seq_len=16, num_layers=1, hidden=16,
                     heads=2, flash_min_seq=flash_min_seq)
    tr = ShardedTrainer(net, spec, lr=0.1, momentum=0.9, wd=0.0,
                        grad_accum=accum)
    shapes = {"data": (8, 16), "softmax_label": (8, 16)}
    p, m, x = tr.init_state(shapes, seed=seed)
    return tr, p, m, x


def _lm_batches(n=2):
    rs = np.random.RandomState(11)
    return [{"data": rs.randint(0, 12, (8, 16)).astype(np.float32),
             "softmax_label": rs.randint(0, 12, (8, 16))
             .astype(np.float32)}
            for _ in range(n)]


@pytest.mark.parametrize("flash_min_seq", [10000, 1],
                         ids=["einsum-path", "flash-path"])
def test_grad_accum_invariant_holds_with_flash_vjp(flash_min_seq):
    """grad_accum=2 must produce the same update as accum=1 on the same
    rows THROUGH the attention custom vjp — on both sides of the
    dispatch boundary (the flash side runs the Pallas backward inside
    the in-jit lax.scan).  Tolerance is f32-reassociation-tight, not
    bitwise: unlike the MLP invariant test, the LM's LayerNorm/softmax
    reductions reassociate between the one-big-batch and the
    scan-accumulated program."""
    batches = _lm_batches()
    outs = {}
    for accum in (1, 2):
        tr, p, m, x = _lm_trainer(flash_min_seq, accum)
        for b in batches:
            p, m, x, loss = tr.step(p, m, x, b)
        outs[accum] = (p, float(loss))
    for a, b in zip(outs[1][0], outs[2][0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    assert outs[1][1] == pytest.approx(outs[2][1], rel=1e-4)


def test_training_parity_across_dispatch_boundary():
    """The SAME tiny LM trained with the einsum path vs the flash path
    lands on matching parameters — the dispatch boundary changes the
    schedule, not the math."""
    batches = _lm_batches()
    final = {}
    for key, fms in (("einsum", 10000), ("flash", 1)):
        tr, p, m, x = _lm_trainer(fms, accum=1)
        for b in batches:
            p, m, x, _ = tr.step(p, m, x, b)
        final[key] = p
    for a, b in zip(final["einsum"], final["flash"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
