"""KVStore tests (reference tests/python/unittest/test_kvstore.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def init_kv(kv_type="local"):
    kv = mx.kv.create(kv_type)
    kv.init(3, nd.zeros(SHAPE))
    kv.init(KEYS, [nd.zeros(SHAPE)] * len(KEYS))
    return kv


def test_single_kv_pair():
    kv = init_kv()
    kv.push(3, nd.ones(SHAPE))
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE))


def test_list_kv_pair():
    kv = init_kv()
    kv.push(KEYS, [nd.ones(SHAPE) * 4] * len(KEYS))
    out = [nd.empty(SHAPE)] * len(KEYS)
    kv.pull(KEYS, out=out)
    for o in out:
        assert_almost_equal(o.asnumpy(), np.full(SHAPE, 4.0))


def test_aggregator_multi_devs():
    """Values from 4 'devices' are summed (reference test_kvstore.py
    test_aggregator)."""
    kv = init_kv("device")
    num_devs = 4
    devs = [mx.cpu(i) for i in range(num_devs)]
    vals = [nd.ones(SHAPE, ctx=d) for d in devs]
    kv.push(3, vals)
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, num_devs))


def test_updater():
    kv = init_kv()

    def updater(key, recv, local):
        local += recv

    kv.set_updater(updater)
    kv.push(3, [nd.ones(SHAPE, ctx=mx.cpu(i)) for i in range(4)])
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, 4.0))
    # twice
    kv.push(3, [nd.ones(SHAPE, ctx=mx.cpu(i)) for i in range(4)])
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, 8.0))


def test_set_optimizer_updates():
    kv = init_kv()
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0))
    kv.push(3, nd.ones(SHAPE))
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, -0.1), rtol=1e-5)


def test_sparse_row_pull():
    kv = mx.kv.create("local")
    from mxnet_tpu.ndarray.sparse import row_sparse_array
    w = np.random.rand(8, 4).astype(np.float32)
    kv.init("emb", nd.array(w))
    out = row_sparse_array((np.zeros((2, 4), np.float32),
                            np.array([0, 1])), shape=(8, 4))
    kv.row_sparse_pull("emb", out=out, row_ids=nd.array([2, 5], dtype="int64"))
    assert_almost_equal(out.data.asnumpy(), w[[2, 5]])


def test_gradient_compression():
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(3, nd.zeros(SHAPE))
    grad = np.full(SHAPE, 0.3, np.float32)
    kv.push(3, nd.array(grad))
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    # 0.3 < threshold → quantised to 0; residual kept
    assert_almost_equal(out.asnumpy(), np.zeros(SHAPE))
    kv.push(3, nd.array(grad))
    kv.pull(3, out=out)
    # residual 0.3 + 0.3 = 0.6 ≥ 0.5 → emits +0.5
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, 0.5))


def test_strkey_and_rank():
    kv = mx.kv.create("local")
    kv.init("w0", nd.ones((2, 2)))
    assert kv.rank == 0
    assert kv.num_workers == 1
    kv.barrier()
    out = nd.empty((2, 2))
    kv.pull("w0", out=out)
    assert_almost_equal(out.asnumpy(), np.ones((2, 2)))


def test_dist_kv_single_process():
    """dist_sync degrades to local semantics in one process (the reference
    needs a launcher; our DCN path activates under jax.distributed)."""
    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 1
    kv.init(3, nd.zeros(SHAPE))
    kv.push(3, nd.ones(SHAPE))
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.ones(SHAPE))


def test_push_no_updater_replaces():
    """Without an updater, push REPLACES the stored value (reference
    kvstore_local.h:190 "local = merged") — it must not accumulate."""
    kv = mx.kv.create("local")
    kv.init(3, nd.ones(SHAPE))
    kv.push(3, nd.ones(SHAPE) * 8)
    out = nd.empty(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, 8.0))
    # a second push replaces again, no accumulation across steps
    kv.push(3, nd.ones(SHAPE) * 2)
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, 2.0))
    # multi-device push still reduces the pushed list, then replaces
    kv.push(3, [nd.ones(SHAPE), nd.ones(SHAPE) * 3])
    kv.pull(3, out=out)
    assert_almost_equal(out.asnumpy(), np.full(SHAPE, 4.0))
