"""Transformer LM model family + fused-attention graph op (beyond the
attention-less reference; SURVEY §5.7 long-context pillar)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.models.transformer import get_symbol


def test_fused_attention_op_matches_naive_and_trains():
    rs = np.random.RandomState(0)
    B, T, H, D = 2, 16, 2, 8
    q = nd.array(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    k = nd.array(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    v = nd.array(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    out = nd.contrib.fused_attention(q, k, v, causal=True).asnumpy()
    # naive reference
    s = np.einsum("bqhd,bkhd->bhqk", q.asnumpy(), k.asnumpy()) / np.sqrt(D)
    mask = np.tril(np.ones((T, T), bool))
    s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bkhd->bqhd", p, v.asnumpy())
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)
    # gradients flow through the custom vjp
    gq = nd.zeros((B, T, H, D))
    mx.autograd.mark_variables([q], [gq])
    with mx.autograd.record():
        o = nd.contrib.fused_attention(q, k, v, causal=True)
        mx.autograd.backward([o])
    assert np.isfinite(gq.asnumpy()).all() and np.abs(gq.asnumpy()).sum() > 0


def test_transformer_lm_learns_periodic_sequences():
    """Next-token prediction on period-2 token streams: a 1-layer causal
    transformer must beat the uniform-perplexity floor decisively."""
    vocab, T = 12, 8
    rs = np.random.RandomState(0)
    n = 64
    X = np.zeros((n, T), np.float32)
    for i in range(n):
        a, b = rs.randint(1, vocab, 2)
        X[i] = [a if t % 2 == 0 else b for t in range(T)]
    Y = np.roll(X, -1, axis=1)
    Y[:, -1] = 0

    net = get_symbol(vocab_size=vocab, seq_len=T, num_layers=1,
                     hidden=32, heads=2)
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    metric = mx.metric.Perplexity(ignore_label=None)
    mod.fit(it, num_epoch=15, initializer=mx.init.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": 0.01},
            eval_metric=metric)
    it.reset()
    metric.reset()
    mod.score(it, metric)
    ppl = dict(metric.get_name_value())["perplexity"]
    assert ppl < 6.0, ppl   # uniform would be 12


def test_transformer_symbol_shapes():
    net = get_symbol(vocab_size=20, seq_len=16, num_layers=2, hidden=32,
                     heads=4)
    args = net.list_arguments()
    assert "pos_embed" in args and "tok_embed_weight" in args
    arg_shapes, out_shapes, _ = net.infer_shape(
        data=(4, 16), softmax_label=(4, 16))
    assert out_shapes == [(64, 20)]   # (N*T, vocab)
