"""srclint fixture: every rule violated at least once, with the expected
rule id noted on each line.  NEVER imported — parsed by
tests/test_analysis.py only, and excluded from the repo self-lint (which
covers mxnet_tpu/, example/ and tools/)."""
import os
import random
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def bad_host_numpy(x):
    return np.sqrt(x)                                     # SL101


@jax.jit
def bad_clock(x):
    t0 = time.time()                                      # SL102
    return x + t0


@jax.jit
def bad_env(x):
    if os.environ.get("DEBUG"):                           # SL103
        x = x * 2.0
    scale = float(os.environ["SCALE"])                    # SL103
    return x * scale


@jax.jit
def bad_rng(x):
    noise = random.random()                               # SL104
    jitter = np.random.randn()                            # SL104
    return x + noise + jitter


class Leaky:
    @jax.jit
    def bad_leak(self, x):
        y = x * 2.0
        self.cache = y                                    # SL105
        return y


def traced_by_combinator(x):
    # marked traced because it is handed to lax.scan below
    return x, np.log(x)                                   # SL101


def drives_scan(xs):
    return lax.scan(traced_by_combinator, xs[0], xs)


def contains_collective(x):
    # traced level inferred from the collective call
    y = lax.psum(x, "dp")
    return y + time.perf_counter()                        # SL102


def suppressed_ok(x):
    """Same violations, suppressed — must produce NO findings."""
    return jax.jit(lambda v: v + time.time())(x)  # tpulint: disable=SL102
