"""srclint fixture for the library-only rule SL106: a shard_map entry
point that executes collectives with no watchdog arming.  Parsed with
``in_library=True`` by tests/test_analysis.py; never imported."""
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def unarmed_entry(fn, mesh, x):                           # SL106
    mapped = shard_map(fn, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    return jax.jit(mapped)(x)


def armed_entry(fn, mesh, x):
    from mxnet_tpu.resilience import watchdog as _wd
    mapped = shard_map(fn, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    with _wd.watch("fixture.armed_entry", kind="collective"):
        return jax.jit(mapped)(x)
