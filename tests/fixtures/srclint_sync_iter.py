"""SL108 fixture: synchronous-iterator training loops, seeded + clean.

Each ``bad_*`` function must produce exactly one SL108 finding; every
other function must stay clean (prefetch-wrapped, eval-only, or
suppressed).  Kept import-free on purpose — srclint never executes the
file.
"""
import mxnet_tpu as mx
from mxnet_tpu.io import NDArrayIter, PrefetchingIter


def bad_module_loop(x, y, mod):
    it = NDArrayIter(x, y, batch_size=8)
    for batch in it:                       # SL108: sync fetch per step
        mod.forward_backward(batch)
        mod.update()


def bad_trainer_loop(x, trainer, state):
    it = mx.io.CSVIter(data_csv=x, batch_size=8)
    for batch in it:                       # SL108: sync fetch per step
        state = trainer.step(state, batch)
    return state


def good_prefetched_loop(x, y, mod):
    it = NDArrayIter(x, y, batch_size=8)
    it = PrefetchingIter(it)
    for batch in it:                       # wrapped: fetch overlaps
        mod.forward_backward(batch)
        mod.update()


def good_eval_sweep(x, y, mod):
    it = NDArrayIter(x, y, batch_size=8)
    preds = []
    for batch in it:                       # no optimizer advance: eval
        preds.append(mod.predict(batch))
    return preds


def good_suppressed(x, y, mod):
    it = NDArrayIter(x, y, batch_size=8)
    for batch in it:  # tpulint: disable=SL108
        mod.forward_backward(batch)
        mod.update()
