"""In-jit sharded embedding plane (mxnet_tpu/sparse): routed lookup,
touched-rows lazy updates, Pallas kernels, GC306, resharding restore.

The defining properties verified throughout:

* lookup/update collective payload is a function of touched rows x dim
  (never table size) — asserted against the analytic wire model over
  compiled HLO;
* the sharded lazy SGD/Adam BIT-match the host ``optimizer.py`` lazy
  reference (``sgd_row_sparse_update`` / ``adam_row_sparse_update``) on
  random id multisets including duplicates — exact-representable grads
  make the routed sums association-free, so "close" is not accepted;
* a 4-shard snapshot restores onto a 3-shard mesh (the elastic resize
  seam) and training continues bit-identically.
"""
import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse as sp
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh, reform_mesh
from mxnet_tpu.sparse import (ShardedEmbedding, embed_backend,
                              embedding_gather, embedding_scatter,
                              lower_step, make_recommender_step,
                              recommender_state, step_alltoall_model_bytes,
                              tune_embedding)


def _spec(n=8):
    if jax.device_count() < n:
        pytest.skip("needs %d devices" % n)
    return MeshSpec(make_mesh((n,), ("dp",)))


def _exact_grads(rs, b, d):
    """Multiples of 2^-10: f32 addition over them is exact, so sums are
    independent of association — the bit-parity tests rest on this.
    The parity tests also pin hyperparameters to power-of-two /
    few-mantissa-bit values: the sharded update compiles FUSED and
    XLA:CPU FMA-contracts `a*b + c`, which only coincides with the host
    kernels' two-op rounding when the products are exact."""
    return (rs.randint(-8, 8, (b, d)) / 1024.0).astype(np.float32)


# ---------------------------------------------------------------------------
# routed lookup
# ---------------------------------------------------------------------------

def test_lookup_matches_dense_with_duplicates():
    spec = _spec()
    V, D, B = 100, 8, 32
    emb = ShardedEmbedding(V, D, spec, name="lk")
    table = emb.init_state(seed=0)
    rs = np.random.RandomState(0)
    ids = rs.randint(0, V, B).astype(np.int64)
    ids[5:9] = ids[0]                      # duplicates within a shard's slice
    ids[8:16] = ids[1]                     # duplicates across senders
    out = emb.lookup(table, jnp.asarray(ids))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(table)[ids])


def test_lookup_single_owner_and_boundary_ids():
    """Every id owned by ONE shard (other buckets empty — the zero-nnz
    routing case) plus the first/last row of each shard."""
    spec = _spec()
    V, D, B = 104, 4, 32                   # 13 rows/shard
    emb = ShardedEmbedding(V, D, spec, name="lk2")
    table = emb.init_state(seed=1)
    one_shard = np.full(B, 3, np.int64)    # all ids -> shard 0
    out = emb.lookup(table, jnp.asarray(one_shard))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(table)[one_shard])
    edges = np.array([s * 13 for s in range(8)] +
                     [s * 13 + 12 for s in range(8)] +
                     [0] * 16, np.int64)
    out2 = emb.lookup(table, jnp.asarray(edges))
    np.testing.assert_array_equal(np.asarray(out2),
                                  np.asarray(table)[edges])


def test_lookup_stats_and_capacity_drops():
    spec = _spec()
    V, D, B = 96, 4, 64
    emb = ShardedEmbedding(V, D, spec, name="lk3")
    table = emb.init_state(seed=2)
    rs = np.random.RandomState(3)
    ids = rs.randint(0, V, B).astype(np.int64)
    out, received, dropped = emb.lookup(table, jnp.asarray(ids),
                                        stats=True)
    # received counts match the exact combinatorial expectation
    b_local = B // 8
    exp = np.zeros(8, np.int64)
    for d in range(8):
        loc = ids[d * b_local:(d + 1) * b_local]
        own = loc // emb.rows_per_shard
        for s in range(8):
            exp[s] += len(np.unique(loc[own == s]))
    np.testing.assert_array_equal(np.asarray(received), exp)
    assert int(np.asarray(dropped).sum()) == 0
    # a deliberately starved capacity drops ids, counts them, and the
    # dropped ids come back as zero rows (documented degradation)
    tiny = ShardedEmbedding(V, D, spec, capacity_factor=0.25, name="lk4")
    ttab = tiny.init_state(seed=2)
    skew = np.arange(B, dtype=np.int64) % 12   # all ids owned by shard 0
    out3, _rec, dropped3 = tiny.lookup(ttab, jnp.asarray(skew),
                                       stats=True)
    assert int(np.asarray(dropped3).sum()) > 0
    got = np.asarray(out3)
    ref = np.asarray(ttab)[skew]
    kept = np.any(got != 0, axis=1)
    np.testing.assert_array_equal(got[kept], ref[kept])
    assert not np.all(kept)


def test_lookup_dedup_bounds_hot_row_load():
    """Power-law ids: the per-sender dedup caps a hot row at one bucket
    slot per sender, so routed load stays far under raw demand."""
    spec = _spec()
    V, D, B = 96, 4, 64
    emb = ShardedEmbedding(V, D, spec, name="hot")
    table = emb.init_state(seed=4)
    ids = np.zeros(B, np.int64)            # ONE row, every example
    out, received, dropped = emb.lookup(table, jnp.asarray(ids),
                                        stats=True)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(table)[ids])
    # raw demand on shard 0 is B; deduped routing delivers one id per
    # sender: exactly 8
    assert int(np.asarray(received).sum()) == 8
    assert int(np.asarray(dropped).sum()) == 0


# ---------------------------------------------------------------------------
# sharded lazy updates: bit-parity with the host reference
# ---------------------------------------------------------------------------

def _host_sgd(w0, ids, grads, V, **kw):
    w_nd = mx.nd.array(w0.copy())
    m_nd = mx.nd.zeros(w0.shape)
    sp.sgd_row_sparse_update(w_nd, sp.embedding_grad(ids, mx.nd.array(grads), V),
                             m_nd if kw.pop("with_mom", True) else None,
                             **kw)
    return w_nd.asnumpy(), m_nd.asnumpy()


def test_lazy_sgd_bit_matches_host_reference():
    spec = _spec()
    V, D, B = 96, 8, 32
    rs = np.random.RandomState(7)
    for trial in range(3):
        emb = ShardedEmbedding(V, D, spec, name="p%d" % trial)
        table = emb.init_state(seed=trial)
        mom = emb.zeros_slot()
        ids = rs.randint(0, V, B).astype(np.int64)
        ids[:B // 4] = ids[0]              # heavy duplication
        grads = _exact_grads(rs, B, D)
        t2, m2 = emb.apply_sgd(table, mom, jnp.asarray(ids),
                               jnp.asarray(grads), lr=0.5, momentum=0.5,
                               wd=0.0078125)
        ref_w, ref_m = _host_sgd(np.asarray(table)[:V], ids, grads, V,
                                 lr=0.5, momentum=0.5, wd=0.0078125)
        np.testing.assert_array_equal(np.asarray(t2)[:V], ref_w)
        np.testing.assert_array_equal(np.asarray(m2)[:V], ref_m)


def test_lazy_sgd_arbitrary_hypers_roundoff():
    """Arbitrary (non-power-of-two) hyperparameters: the fused program's
    FMA contraction may differ from the host's two-op rounding by ~1
    ulp per product — agreement to f32 roundoff, exactness not
    claimed."""
    spec = _spec()
    V, D, B = 96, 8, 32
    rs = np.random.RandomState(21)
    emb = ShardedEmbedding(V, D, spec, name="ph")
    table = emb.init_state(seed=13)
    mom = emb.zeros_slot()
    ids = rs.randint(0, V, B).astype(np.int64)
    grads = rs.randn(B, D).astype(np.float32)
    t2, m2 = emb.apply_sgd(table, mom, jnp.asarray(ids),
                           jnp.asarray(grads), lr=0.5, momentum=0.9,
                           wd=0.01)
    ref_w, ref_m = _host_sgd(np.asarray(table)[:V], ids, grads, V,
                             lr=0.5, momentum=0.9, wd=0.01)
    np.testing.assert_allclose(np.asarray(t2)[:V], ref_w, rtol=0,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(m2)[:V], ref_m, rtol=0,
                               atol=1e-6)


def test_lazy_sgd_momentum_free_clip_rescale():
    spec = _spec()
    V, D, B = 96, 8, 32
    rs = np.random.RandomState(9)
    emb = ShardedEmbedding(V, D, spec, name="pc")
    table = emb.init_state(seed=5)
    ids = rs.randint(0, V, B).astype(np.int64)
    grads = _exact_grads(rs, B, D)
    t2, m2 = emb.apply_sgd(table, None, jnp.asarray(ids),
                           jnp.asarray(grads), lr=0.25, wd=0.0078125,
                           rescale_grad=0.5, clip_gradient=0.001953125)
    assert m2 is None
    w_nd = mx.nd.array(np.asarray(table)[:V].copy())
    sp.sgd_row_sparse_update(
        w_nd, sp.embedding_grad(ids, mx.nd.array(grads), V), None,
        lr=0.25, wd=0.0078125, rescale_grad=0.5,
        clip_gradient=0.001953125)
    np.testing.assert_array_equal(np.asarray(t2)[:V], w_nd.asnumpy())


def test_lazy_adam_bit_matches_host_reference():
    spec = _spec()
    V, D, B = 96, 8, 32
    rs = np.random.RandomState(11)
    emb = ShardedEmbedding(V, D, spec, name="pa")
    table = emb.init_state(seed=6)
    mean, var = emb.zeros_slot(), emb.zeros_slot()
    ids = rs.randint(0, V, B).astype(np.int64)
    ids[3:7] = ids[2]
    grads = _exact_grads(rs, B, D)
    kw = dict(lr=0.0078125, wd=0.0078125, beta1=0.875, beta2=0.96875)
    t2, me2, va2 = emb.apply_adam(table, mean, var, jnp.asarray(ids),
                                  jnp.asarray(grads), **kw)
    w_nd = mx.nd.array(np.asarray(table)[:V].copy())
    me_nd, va_nd = mx.nd.zeros((V, D)), mx.nd.zeros((V, D))
    sp.adam_row_sparse_update(
        w_nd, sp.embedding_grad(ids, mx.nd.array(grads), V), me_nd, va_nd,
        **kw)
    np.testing.assert_array_equal(np.asarray(t2)[:V], w_nd.asnumpy())
    np.testing.assert_array_equal(np.asarray(me2)[:V], me_nd.asnumpy())
    np.testing.assert_array_equal(np.asarray(va2)[:V], va_nd.asnumpy())


def test_update_touches_only_active_rows():
    spec = _spec()
    V, D, B = 96, 8, 16
    emb = ShardedEmbedding(V, D, spec, name="tr")
    table = emb.init_state(seed=8)
    mom = emb.zeros_slot()
    ids = np.array([1, 5, 9, 13, 17, 21, 25, 29] * 2, np.int64)
    grads = np.ones((B, D), np.float32) / 1024.0
    t2, m2 = emb.apply_sgd(table, mom, jnp.asarray(ids),
                           jnp.asarray(grads), lr=0.5, momentum=0.9)
    untouched = np.setdiff1d(np.arange(V), ids)
    np.testing.assert_array_equal(np.asarray(t2)[untouched],
                                  np.asarray(table)[untouched])
    assert np.all(np.asarray(m2)[untouched] == 0)
    assert np.all(np.asarray(m2)[np.unique(ids)] != 0)


# ---------------------------------------------------------------------------
# Pallas kernels + autotune registration
# ---------------------------------------------------------------------------

def test_kernels_gather_scatter_vs_numpy():
    rs = np.random.RandomState(0)
    table = jnp.asarray(rs.rand(32, 8).astype(np.float32))
    ids = np.sort(rs.randint(0, 32, 12)).astype(np.int32)
    rows = jnp.asarray(rs.rand(12, 8).astype(np.float32))
    for backend in ("xla", "pallas"):
        got = embedding_gather(table, jnp.asarray(ids), backend=backend)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(table)[ids])
        added = embedding_scatter(table, jnp.asarray(ids), rows,
                                  mode="add", backend=backend)
        ref = np.asarray(table).copy()
        np.add.at(ref, ids, np.asarray(rows))
        np.testing.assert_allclose(np.asarray(added), ref, rtol=1e-6)
    # set mode: unique sorted ids, both backends identical
    uids = np.unique(ids).astype(np.int32)
    urows = jnp.asarray(rs.rand(len(uids), 8).astype(np.float32))
    for backend in ("xla", "pallas"):
        setv = embedding_scatter(table, jnp.asarray(uids), urows,
                                 mode="set", backend=backend)
        ref = np.asarray(table).copy()
        ref[uids] = np.asarray(urows)
        np.testing.assert_array_equal(np.asarray(setv), ref)


def test_pallas_backend_full_pipeline_parity():
    spec = _spec()
    V, D, B = 96, 8, 32
    rs = np.random.RandomState(2)
    ids = rs.randint(0, V, B).astype(np.int64)
    grads = _exact_grads(rs, B, D)
    outs = {}
    for backend in ("xla", "pallas"):
        emb = ShardedEmbedding(V, D, spec, backend=backend,
                               name="bk_" + backend)
        table = emb.init_state(seed=3)
        mom = emb.zeros_slot()
        rows = emb.lookup(table, jnp.asarray(ids))
        t2, m2 = emb.apply_sgd(table, mom, jnp.asarray(ids),
                               jnp.asarray(grads), lr=0.5, momentum=0.5)
        outs[backend] = (np.asarray(rows), np.asarray(t2), np.asarray(m2))
    for a, b in zip(outs["xla"], outs["pallas"]):
        np.testing.assert_array_equal(a, b)


def test_autotune_records_winner_and_knob_overrides(tmp_path, monkeypatch):
    from mxnet_tpu.ops import autotune as at
    monkeypatch.setenv("MXNET_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    monkeypatch.delenv("MXNET_TPU_PALLAS_EMBED", raising=False)
    at.invalidate()
    try:
        # before tuning: auto resolves to the static default
        assert embed_backend("gather", 64, 8, 16) == "xla"
        got = tune_embedding(64, 8, 16, iters=1, force=True)
        assert got["gather"] in ("xla", "pallas")
        assert got["scatter"] in ("xla", "pallas")
        # the persisted winner IS what auto resolves to now
        assert embed_backend("gather", 64, 8, 16) == got["gather"]
        assert at.lookup("embed_gather", (64, 8, 16, "float32"))
        # the env knob overrides the cache in both directions
        monkeypatch.setenv("MXNET_TPU_PALLAS_EMBED", "1")
        assert embed_backend("gather", 64, 8, 16) == "pallas"
        monkeypatch.setenv("MXNET_TPU_PALLAS_EMBED", "0")
        assert embed_backend("gather", 64, 8, 16) == "xla"
    finally:
        at.invalidate()


# ---------------------------------------------------------------------------
# wire model vs compiled HLO + GC306
# ---------------------------------------------------------------------------

def test_step_alltoall_bytes_match_model_and_gc306_clean():
    spec = _spec()
    from mxnet_tpu.analysis import graphcheck
    from mxnet_tpu.parallel.audit import collective_accounting
    V, D, B = 96, 8, 32
    embs = [ShardedEmbedding(V, D, spec, name="m%d" % f)
            for f in range(2)]
    state = recommender_state(embs, dense_dim=4, hidden=(16,))
    step = make_recommender_step(embs, lr=0.05, momentum=0.9)
    rs = np.random.RandomState(5)
    batch = {"ids": jnp.asarray(rs.randint(0, V, (2, B)).astype(np.int32)),
             "dense": jnp.asarray(rs.rand(B, 4).astype(np.float32)),
             "label": jnp.asarray((rs.rand(B) > 0.5).astype(np.float32))}
    state, loss0 = step(state, batch)
    for _ in range(4):
        state, loss = step(state, batch)
    assert float(loss) < float(loss0)
    hlo = lower_step(step, state, batch)
    acct = collective_accounting(hlo, mesh=spec.mesh)
    measured = acct.get("all-to-all", {}).get("bytes", 0)
    model = 2 * step_alltoall_model_bytes(B, D, 8)
    assert measured == model, (measured, model)
    # per-axis attribution: the routing is dp traffic
    assert acct["all-to-all"]["by_axis"] == {
        "dp": {"count": acct["all-to-all"]["count"], "bytes": measured}}
    rep = graphcheck.check_embedding_grad(
        hlo, table_bytes=[e.table_bytes for e in embs], min_bytes=1024)
    assert not rep.findings, rep.findings


def test_gc306_seeded_densified_grad_fires():
    spec = _spec()
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.analysis import graphcheck
    V, D, B = 96, 8, 32
    emb = ShardedEmbedding(V, D, spec, name="gcA")
    table = emb.init_state(seed=0)
    rs = np.random.RandomState(1)
    Vb = 128
    tableB = jax.device_put(rs.rand(Vb, D).astype(np.float32),
                            NamedSharding(spec.mesh, P()))
    ids = jax.device_put(
        jnp.asarray(rs.randint(0, V, B).astype(np.int32)),
        NamedSharding(spec.mesh, P("dp")))

    def bad_step(tA, tB, i):
        rows = emb.lookup(tA, i)

        def loss(tb):
            return jnp.sum((rows + jnp.take(tb, i, axis=0)) ** 2)
        return jnp.sum(jax.grad(loss)(tB))

    with spec.mesh:
        hlo = jax.jit(bad_step).lower(table, tableB,
                                      ids).compile().as_text()
    rep = graphcheck.check_embedding_grad(
        hlo, table_bytes=[emb.table_bytes, Vb * D * 4], min_bytes=1024)
    assert any(f.rule == "GC306" for f in rep.findings), rep.findings
    f = [f for f in rep.findings if f.rule == "GC306"][0]
    assert f.severity == "warning"
    assert "densified" in f.message
    # under the default 8 MB floor the toy payload is ignored
    rep2 = graphcheck.check_embedding_grad(
        hlo, table_bytes=[emb.table_bytes, Vb * D * 4])
    assert not rep2.findings
    # a program with no all-to-all (no routed lookup) never fires
    def plain(tB, i):
        def loss(tb):
            return jnp.sum(jnp.take(tb, i, axis=0) ** 2)
        return jnp.sum(jax.grad(loss)(tB))
    with spec.mesh:
        hlo3 = jax.jit(plain).lower(tableB, ids).compile().as_text()
    rep3 = graphcheck.check_embedding_grad(hlo3, table_bytes=[Vb * D * 4],
                                           min_bytes=1)
    assert not rep3.findings


def test_preflight_writes_sparse_report(tmp_path, monkeypatch):
    spec = _spec()
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT", "1")
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT_DIR", str(tmp_path))
    V, D, B = 96, 8, 32
    embs = [ShardedEmbedding(V, D, spec, name="pf")]
    state = recommender_state(embs, dense_dim=4, hidden=(16,))
    step = make_recommender_step(embs, lr=0.05, momentum=0.9)
    rs = np.random.RandomState(5)
    batch = {"ids": jnp.asarray(rs.randint(0, V, (1, B)).astype(np.int32)),
             "dense": jnp.asarray(rs.rand(B, 4).astype(np.float32)),
             "label": jnp.asarray((rs.rand(B) > 0.5).astype(np.float32))}
    state, _ = step(state, batch)
    reports = [p for p in os.listdir(str(tmp_path))
               if p.startswith("preflight-sparse") and p.endswith(".json")]
    assert reports, os.listdir(str(tmp_path))
    import json
    doc = json.load(open(os.path.join(str(tmp_path), reports[0])))
    assert doc["target"] == "sparse.recommender_step"
    assert not [f for f in doc.get("findings", [])
                if f.get("rule") == "GC306"]


# ---------------------------------------------------------------------------
# checkpoint + elastic resharding seam
# ---------------------------------------------------------------------------

def test_checkpoint_reshard_4_to_3_continues_bit_exact():
    from mxnet_tpu.resilience import (CheckpointManager, restore_embedding,
                                      save_embedding)
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    spec4 = MeshSpec(make_mesh((4,), ("dp",), devices=devs[:4]))
    V, D, B = 50, 8, 24                    # V divides neither 4 nor 3
    emb4 = ShardedEmbedding(V, D, spec4, name="ck")
    table, mom = emb4.init_state(seed=0), emb4.zeros_slot()
    rs = np.random.RandomState(0)
    ids = rs.randint(0, V, B).astype(np.int64)
    grads = _exact_grads(rs, B, D)
    table, mom = emb4.apply_sgd(table, mom, jnp.asarray(ids),
                                jnp.asarray(grads), lr=0.5, momentum=0.5)
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)
        save_embedding(mgr, emb4, {"table": table, "mom": mom}, step=1,
                       extra_meta={"note": "pre-resize"})
        mgr.wait()
        spec3 = reform_mesh(spec4, devices=devs[:3])
        emb3 = emb4.reshard(spec3)
        assert emb3.num_shards == 3 and emb3.padded_rows % 3 == 0
        res = restore_embedding(mgr, emb3)
        assert res is not None
        (st3,), step_no, meta = res
        assert step_no == 1 and meta["note"] == "pre-resize"
        np.testing.assert_array_equal(np.asarray(st3["table"])[:V],
                                      np.asarray(table)[:V])
        # residency really re-sharded 1/3
        shard = st3["table"].addressable_shards[0].data.nbytes
        assert shard * 3 == st3["table"].nbytes
        # the NEXT update on 3 shards bit-matches the same update on 4
        t3, m3 = emb3.apply_sgd(st3["table"], st3["mom"],
                                jnp.asarray(ids), jnp.asarray(grads),
                                lr=0.5, momentum=0.5)
        t4, m4 = emb4.apply_sgd(table, mom, jnp.asarray(ids),
                                jnp.asarray(grads), lr=0.5, momentum=0.5)
        np.testing.assert_array_equal(np.asarray(t3)[:V],
                                      np.asarray(t4)[:V])
        np.testing.assert_array_equal(np.asarray(m3)[:V],
                                      np.asarray(m4)[:V])


def test_restore_embedding_wrong_kind_raises():
    from mxnet_tpu.resilience import CheckpointManager, restore_embedding
    spec = _spec()
    emb = ShardedEmbedding(16, 4, spec, name="wk")
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d)
        mgr.save(1, {"x": np.zeros(3)}, {"kind": "sharded_trainer"})
        mgr.wait()
        with pytest.raises(mx.base.MXNetError, match="sharded_embedding"):
            restore_embedding(mgr, emb)


# ---------------------------------------------------------------------------
# memory plane
# ---------------------------------------------------------------------------

def test_embedding_tag_accounts_table_residency(monkeypatch):
    from mxnet_tpu.telemetry import memory as _memory
    assert "embedding" in _memory.TAGS
    spec = _spec()
    monkeypatch.setenv("MXNET_TPU_MEMWATCH", "1")
    _memory.reset()
    try:
        emb = ShardedEmbedding(256, 16, spec, name="mem")
        table = emb.init_state(seed=0)
        mom = emb.zeros_slot()
        by_tag = _memory.live_bytes_by_tag()
        assert by_tag.get("embedding", 0) >= \
            table.nbytes + mom.nbytes
        # OOM post-mortem by-tag totals carry the bucket
        top = [r for r in _memory.top_buffers(50)
               if r["tag"] == "embedding"]
        assert top and top[0]["label"].startswith("mem")
    finally:
        monkeypatch.delenv("MXNET_TPU_MEMWATCH", raising=False)
        _memory.reset()


# ---------------------------------------------------------------------------
# srclint self-check over the new package
# ---------------------------------------------------------------------------

def test_srclint_clean_over_sparse_package():
    from mxnet_tpu.analysis import srclint
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "mxnet_tpu", "sparse")
    findings = []
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py"):
            rep = srclint.lint_file(os.path.join(root, fn))
            findings.extend(rep.findings)
    assert not findings, [(f.rule, f.location, f.message)
                          for f in findings]
