"""Resilient serving runtime tests (mxnet_tpu/serving/ + deploy.py
topology guard + tools/servebench.py).

Three tiers:
 - synthetic-program units: admission/shedding, deadline accounting,
   batching, breaker, swap/rollback, watchdog forensics — no device in
   the loop, so each behavior is isolated and fast;
 - real-artifact tier: export_compiled -> ServingRuntime end-to-end,
   the topology guard, and every ServedProgram.load negative path
   (truncation, CRC flip, pickle refusal, topology mismatch) asserting
   the exact typed error;
 - e2e: the env-armed chaos serving drill (tests/serving_drill.py,
   kill-and-verify) and the tools/servebench.py smoke.
"""
import ctypes  # noqa: F401  (parity with test_capi style)
import json
import os
import pickle
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.deploy import ServedProgram, TopologyMismatch
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience.container import (CorruptContainer,
                                            read_container,
                                            write_container)
from mxnet_tpu.serving import (BROKEN, SERVING, CircuitOpen,
                               DeadlineExceeded, ExecFailed, Overloaded,
                               ServingError, ServingRuntime, SwapFailed)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


class SynthProgram:
    """Program-like test double: fixed (4, 3) batch, optional latency,
    scaled identity math, call counting."""

    def __init__(self, latency=0.0, scale=1.0, features=3):
        self.input_names = ["data"]
        self.input_shapes = {"data": (4, features)}
        self.input_dtypes = {"data": np.dtype(np.float32)}
        self.output_shapes = [(4, features)]
        self.latency = latency
        self.scale = scale
        self.calls = 0

    def forward(self, data):
        self.calls += 1
        if self.latency:
            time.sleep(self.latency)
        return [data * self.scale]


def _row(value=1.0):
    return np.full((3,), value, np.float32)


def _full(value=1.0):
    return np.full((4, 3), value, np.float32)


# ---------------------------------------------------------------------------
# synthetic units
# ---------------------------------------------------------------------------

def test_single_rows_pack_into_one_batch():
    prog = SynthProgram()
    with ServingRuntime(prog, linger=0.1, default_deadline=5) as rt:
        reqs = [rt.submit(data=_row(i)) for i in range(4)]
        for i, r in enumerate(reqs):
            (out,) = r.result(timeout=5)
            assert out.shape == (1, 3)
            np.testing.assert_allclose(out, i)
    assert prog.calls == 1, "4 single rows must dispatch as ONE batch"


def test_full_batch_and_validation_errors():
    with ServingRuntime(SynthProgram(), default_deadline=5) as rt:
        (out,) = rt.predict(data=_full(2.0))
        assert out.shape == (4, 3)
        with pytest.raises(ServingError, match="missing inputs"):
            rt.submit()
        with pytest.raises(ServingError, match="unknown inputs"):
            rt.submit(data=_row(), bogus=_row())
        with pytest.raises(ServingError, match="shape"):
            rt.submit(data=np.zeros((7,), np.float32))
        with pytest.raises(ServingError, match="at most"):
            rt.submit(data=np.zeros((9, 3), np.float32))


def test_overload_sheds_and_priority_evicts():
    prog = SynthProgram(latency=0.3)
    with ServingRuntime(prog, queue_depth=2, linger=0.001,
                        default_deadline=10) as rt:
        r0 = rt.submit(data=_full())            # occupies the executor
        time.sleep(0.05)                        # let the worker pop r0
        r1 = rt.submit(data=_full(), priority=0)
        r2 = rt.submit(data=_full(), priority=0)
        # higher priority evicts the OLDEST lowest-priority request
        r3 = rt.submit(data=_full(), priority=5)
        with pytest.raises(Overloaded, match="evicted"):
            r1.result(timeout=1)
        # equal priority at a full queue is rejected, not admitted
        with pytest.raises(Overloaded, match="queue full"):
            rt.submit(data=_full(), priority=0)
        for r in (r0, r2, r3):
            r.result(timeout=10)
        assert rt.stats()["shed_overload"] == 2


def test_expired_request_dropped_before_dispatch():
    prog = SynthProgram(latency=0.2)
    with ServingRuntime(prog, linger=0.001, default_deadline=10) as rt:
        r0 = rt.submit(data=_full())            # executor busy 0.2s
        time.sleep(0.05)
        r1 = rt.submit(data=_full(), deadline=0.05)
        with pytest.raises(DeadlineExceeded, match="before"):
            r1.result(timeout=5)
        r0.result(timeout=5)
        time.sleep(0.1)                         # worker drains the queue
        assert prog.calls == 1, "expired request must never hit the device"
        assert rt.stats()["shed_expired"] == 1


def test_late_completion_reported_as_deadline_exceeded():
    prog = SynthProgram(latency=0.15)
    with ServingRuntime(prog, linger=0.001, default_deadline=10) as rt:
        r = rt.submit(data=_full(), deadline=0.05)   # dispatches, too slow
        with pytest.raises(DeadlineExceeded):
            r.result(timeout=5)
    assert prog.calls == 1, "this one DID dispatch; lateness is at delivery"


def test_deadline_closes_batch_before_linger():
    prog = SynthProgram()
    with ServingRuntime(prog, linger=2.0, default_deadline=10) as rt:
        t0 = time.monotonic()
        r = rt.submit(data=_row(), deadline=0.2)
        r.result(timeout=5)
        elapsed = time.monotonic() - t0
    assert elapsed < 1.0, ("deadline margin must close the batch long "
                           "before the 2s linger (took %.3fs)" % elapsed)


def test_retry_absorbs_transient_exec_error():
    prog = SynthProgram()
    with ServingRuntime(prog, retry_tries=2, retry_backoff=0.001,
                        default_deadline=5) as rt:
        with chaos.inject("exec_error", count=1):
            (out,) = rt.predict(data=_full(3.0))
        np.testing.assert_allclose(out, 3.0)
        assert rt.health() == SERVING
        assert rt.stats()["counters"].get("exec_failures", 0) == 0


def test_circuit_breaker_opens_sheds_and_recovers():
    prog = SynthProgram()
    with ServingRuntime(prog, retry_tries=1, breaker_threshold=2,
                        breaker_cooldown=0.25, linger=0.001,
                        default_deadline=5) as rt:
        with chaos.inject("exec_error", count=2):
            for _ in range(2):
                with pytest.raises(ExecFailed):
                    rt.predict(data=_full())
        assert rt.health() == BROKEN
        with pytest.raises(CircuitOpen):
            rt.submit(data=_full())
        time.sleep(0.3)                          # cooldown -> probe allowed
        rt.predict(data=_full())
        assert rt.health() == SERVING
        breaker = rt.stats()["breaker"]
        assert breaker["opened_total"] == 1
        assert breaker["recovered_total"] == 1


def test_swap_rollback_and_bad_swap():
    with ServingRuntime(SynthProgram(scale=1.0), default_deadline=5) as rt:
        with chaos.inject("bad_swap"):
            with pytest.raises(SwapFailed, match="non-finite"):
                rt.swap(SynthProgram(scale=2.0))
        np.testing.assert_allclose(rt.predict(data=_full())[0], 1.0)
        rt.swap(SynthProgram(scale=2.0))
        np.testing.assert_allclose(rt.predict(data=_full())[0], 2.0)
        rt.rollback()
        np.testing.assert_allclose(rt.predict(data=_full())[0], 1.0)
        with pytest.raises(SwapFailed, match="schema mismatch"):
            rt.swap(SynthProgram(features=5))
        stats = rt.stats()["counters"]
        assert stats["swaps"] == 1
        assert stats["swap_failures"] == 2
        assert stats["rollbacks"] == 1


def test_wedged_executor_writes_watchdog_postmortem(tmp_path):
    prog = SynthProgram(latency=0.4)
    with ServingRuntime(prog, exec_timeout=0.1, watchdog_action="wait",
                        report_dir=str(tmp_path), linger=0.001,
                        default_deadline=10, name="wedge-test") as rt:
        with pytest.raises(DeadlineExceeded):
            rt.predict(data=_full(), deadline=0.2)
        deadline = time.monotonic() + 3.0
        reports = []
        while time.monotonic() < deadline and not reports:
            reports = [f for f in os.listdir(str(tmp_path))
                       if f.startswith("watchdog-postmortem")
                       and f.endswith(".json")]
            time.sleep(0.05)
    assert reports, "wedged dispatch must leave stack-dump forensics"
    with open(str(tmp_path / reports[0])) as f:
        report = json.load(f)
    assert report["tag"] == "wedge-test.execute"
    assert report["action"] == "wait"


def test_runtime_close_fails_queued_requests():
    prog = SynthProgram(latency=0.3)
    rt = ServingRuntime(prog, linger=0.001, default_deadline=10)
    r0 = rt.submit(data=_full())
    time.sleep(0.05)
    r1 = rt.submit(data=_full())
    rt.close()
    with pytest.raises(ServingError, match="closed"):
        r1.result(timeout=1)
    with pytest.raises(ServingError):
        rt.submit(data=_full())
    r0.result(timeout=5)     # in-flight work still completes


# ---------------------------------------------------------------------------
# real-artifact tier
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("served") / "model.mxt")
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=5, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    ex = net.simple_bind(mx.cpu(), data=(4, 3))
    rs = np.random.RandomState(0)
    for a in ex.arg_arrays:
        a[:] = mx.nd.array(rs.normal(0, 0.3, a.shape))
    ex.export_compiled(path, input_names=("data",))
    return path


def test_serving_runtime_matches_direct_forward(artifact):
    direct = ServedProgram.load(artifact)
    batch = np.linspace(-1, 1, 12, dtype=np.float32).reshape(4, 3)
    want = direct.forward(data=batch)[0]
    with ServingRuntime(artifact, linger=0.05, default_deadline=10) as rt:
        reqs = [rt.submit(data=batch[i]) for i in range(4)]
        got = np.concatenate([r.result(timeout=10)[0] for r in reqs])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_artifact_records_topology(artifact):
    _, meta, _ = read_container(artifact)
    import jax
    assert meta["platform"] == jax.default_backend()
    assert meta["device_kind"] == jax.devices()[0].device_kind
    assert meta["device_count"] == len(jax.devices())


def _rewrite_meta(artifact, out_path, mutate):
    arrays, meta, blobs = read_container(artifact)
    meta = dict(meta)
    mutate(meta)
    write_container(out_path, arrays=arrays, meta=meta, blobs=blobs)
    return out_path


def test_topology_mismatch_refused_and_overridable(artifact, tmp_path,
                                                   monkeypatch):
    wrong = _rewrite_meta(
        artifact, str(tmp_path / "wrong.mxt"),
        lambda m: m.update(platform="tpu", device_kind="TPU v9000",
                           device_count=4096,
                           topologies={"tpu|TPU v9000|4096": "executable"}))
    with pytest.raises(TopologyMismatch, match="TPU v9000"):
        ServedProgram.load(wrong)
    monkeypatch.setenv("MXNET_TPU_SERVED_IGNORE_TOPOLOGY", "1")
    ServedProgram.load(wrong)        # expert override: loads (and warns)


def test_legacy_artifact_without_topology_loads_with_warning(
        artifact, tmp_path, caplog):
    legacy = _rewrite_meta(
        artifact, str(tmp_path / "legacy.mxt"),
        lambda m: [m.pop(k, None) for k in
                   ("platform", "device_kind", "device_count",
                    "topologies")])
    import logging
    with caplog.at_level(logging.WARNING):
        ServedProgram.load(legacy)
    assert any("topology metadata" in r.message for r in caplog.records)


def test_load_negative_paths_each_typed(artifact, tmp_path):
    # truncated file -> CorruptContainer before any buffer is touched
    with open(artifact, "rb") as f:
        raw = f.read()
    truncated = str(tmp_path / "truncated.mxt")
    with open(truncated, "wb") as f:
        f.write(raw[:len(raw) // 2])
    with pytest.raises(CorruptContainer):
        ServedProgram.load(truncated)

    # one flipped byte inside a payload buffer -> CRC mismatch
    flipped = bytearray(raw)
    flipped[-20] ^= 0xFF             # inside the executable blob tail
    flipped_path = str(tmp_path / "flipped.mxt")
    with open(flipped_path, "wb") as f:
        f.write(bytes(flipped))
    with pytest.raises(CorruptContainer, match="CRC mismatch"):
        ServedProgram.load(flipped_path)

    # pickle streams are refused outright (no code execution on load)
    pickled = str(tmp_path / "evil.mxt")
    with open(pickled, "wb") as f:
        pickle.dump({"innocent": "model"}, f)
    with pytest.raises(CorruptContainer, match="pickle"):
        ServedProgram.load(pickled)


def test_capi_served_predictor_serving_errors(artifact):
    """Python-side C ABI surface: typed serving errors + health/deadline/
    swap entry points (the ctypes boundary itself is test_capi.py)."""
    from mxnet_tpu import capi
    with pytest.raises(Exception):
        capi.pred_create_served("/nonexistent/model.mxt")
    h = capi.pred_create_served(artifact)
    try:
        assert capi.pred_get_health(h) == 0           # SERVING
        capi.pred_set_input(h, "data", np.zeros(12, np.float32))
        capi.pred_set_deadline(h, 1e-6)
        with pytest.raises(DeadlineExceeded):
            capi.pred_forward(h)
        capi.pred_set_deadline(h, 0)                  # back to default
        capi.pred_forward(h)
        assert capi.pred_get_output_shape(h, 0) == [4, 5]
        with pytest.raises(SwapFailed):
            capi.pred_swap_served(h, "/nonexistent/model.mxt")
        capi.pred_forward(h)                          # old model serving
        # non-served handles reject the serving-only entry points
        nh = capi.ndarray_create_none()
        try:
            with pytest.raises(MXNetError, match="served predictor"):
                capi.pred_get_health(nh)
        finally:
            capi.free_handle(nh)
    finally:
        capi.pred_free(h)


# ---------------------------------------------------------------------------
# e2e: chaos drill + servebench
# ---------------------------------------------------------------------------

def test_chaos_serving_drill_kill_and_verify(tmp_path):
    """Acceptance drill: env-armed slow_exec/exec_error/bad_swap against
    a real artifact under saturating load, then a wedged executor that
    the watchdog must kill (exit 43) leaving forensics."""
    env = dict(os.environ,
               MXNET_TPU_CHAOS="exec_errorx4,slow_execx6,bad_swap",
               MXNET_TPU_CHAOS_SLOW_EXEC_SECONDS="0.08")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "serving_drill.py"),
         str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=600, cwd=REPO)
    assert r.returncode == 43, \
        "watchdog must abort the wedged server (rc=%s)\n%s\n%s" \
        % (r.returncode, r.stdout, r.stderr)
    verdict_lines = [ln for ln in r.stdout.splitlines()
                     if ln.startswith("DRILL_VERDICT ")]
    assert verdict_lines, r.stdout + r.stderr
    v = json.loads(verdict_lines[0][len("DRILL_VERDICT "):])
    # breaker: opens on consecutive failures, sheds typed, recovers
    assert v["health_after_failures"] == "BROKEN"
    assert v["circuit_shed_typed"] is True
    assert v["probe_ok"] is True
    assert v["health_after_probe"] == "SERVING"
    assert v["breaker_opened_total"] == 1
    assert v["breaker_recovered_total"] == 1
    # saturation: bounded queue, typed shedding, pre-dispatch expiry
    assert v["flood_outcomes"]["Overloaded"] > 0
    assert v["flood_outcomes"]["DeadlineExceeded"] > 0
    assert v["flood_outcomes"]["ok"] > 0
    assert v["queue_depth_max"] <= v["queue_bound"]
    assert v["late_ok"] == 0, "no request may be OK past its deadline"
    # hot swap: bad_swap rejected with zero request impact, clean swap
    # actually changes the model
    assert v["bad_swap_typed"] is True
    assert v["unchanged_after_bad_swap"] is True
    assert v["swap_ok"] is True
    assert v["changed_after_good_swap"] is True
    assert v["bg_failures_during_swaps"] == 0
    # chaos telemetry: every env-armed fault firing was counted — the
    # drill asserts "N injected, N absorbed" instead of grepping logs
    # (MXNET_TPU_CHAOS=exec_errorx4,slow_execx6,bad_swap above)
    assert v["faults_injected"] == {"exec_error": 4, "slow_exec": 6,
                                    "bad_swap": 1}
    # kill-and-verify forensics: post-mortem from the wedged phase
    reports = [f for f in os.listdir(str(tmp_path))
               if f.startswith("watchdog-postmortem")
               and f.endswith(".json")]
    assert reports, "abort must leave a post-mortem"
    with open(str(tmp_path / reports[0])) as f:
        report = json.load(f)
    assert report["tag"] == "drill-wedge.execute"
    assert report["action"] == "abort"


def _run_servebench(args):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "servebench.py"),
         "--json"] + args,
        capture_output=True, text=True, timeout=600, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    return json.loads(r.stdout)


def test_servebench_smoke():
    rep = _run_servebench(["--duration", "0.5", "--concurrency", "4",
                           "--exec-latency", "0.001"])
    assert rep["requests"] > 0 and rep["ok"] > 0
    assert {"p50_ms", "p95_ms", "p99_ms"} <= set(rep["latency"])
    assert "shed_rate" in rep and "queue_depth_max" in rep
    assert rep["runtime_stats"]["health"] == "SERVING"


@pytest.mark.slow
def test_servebench_sustained_open_loop_sheds_not_queues():
    rep = _run_servebench(["--mode", "open", "--rate", "2000",
                           "--duration", "5", "--queue-depth", "32",
                           "--exec-latency", "0.01", "--deadline", "0.1"])
    assert rep["requests"] > 1000
    assert rep["shed_rate"] > 0, "sustained overload must shed"
    assert rep["queue_depth_max"] <= 32, "queue must stay bounded"
    assert rep["ok"] > 0
