"""The dist_async parameter-server lane (mxnet_tpu/kvstore/): protocol
arithmetic, server semantics (async apply, SSP staleness gate, duplicate
-push idempotence, checkpoint/restore exactly-once), the PSClient
transport (retry absorption, PullRowSparse wire accounting), the
KVStorePS facade behind ``kvstore.create("dist_async")``, the hardened
FileKVClient under concurrent writers, chaos rank targeting, and the
``postmortem --kvstore`` timeline.  Everything here is in-process
(``serve_in_thread``); the multi-process SIGKILL/straggler drills live
in tests/test_ps_drills.py."""
import json
import os
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore import protocol
from mxnet_tpu.kvstore.client import KVStorePS, PSClient
from mxnet_tpu.kvstore.server import KVServer
from mxnet_tpu.ndarray.ndarray import array as nd_array
from mxnet_tpu.ndarray.sparse import RowSparseNDArray
from mxnet_tpu.optimizer import Optimizer, Updater
from mxnet_tpu.resilience import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WRAP = protocol.CLOCK_WRAP


@pytest.fixture
def lane(tmp_path):
    """One kv dir + helpers to start in-process servers and clients,
    with teardown that stops everything."""
    servers, clients = [], []

    def start(world=1, staleness=None, **kw):
        s = KVServer(str(tmp_path), world=world, staleness=staleness, **kw)
        s.serve_in_thread()
        servers.append(s)
        return s

    def connect(rank=0):
        c = PSClient(str(tmp_path), rank=rank, connect_timeout=10)
        clients.append(c)
        return c

    yield SimpleNamespace(dir=str(tmp_path), start=start, connect=connect)
    for c in clients:
        c.close()
    for s in servers:
        s.stop()


# ---------------------------------------------------------------------------
# protocol arithmetic
# ---------------------------------------------------------------------------

def test_clock_lag_wraps():
    assert protocol.clock_lag(5, 3) == 2
    assert protocol.clock_lag(3, 5) == -2
    # across the wrap boundary a "newer" counter is still newer
    assert protocol.clock_lag(0, WRAP - 1) == 1
    assert protocol.clock_lag(1, WRAP - 2) == 3
    assert protocol.clock_lag(WRAP - 1, 0) == -1
    assert protocol.next_version(WRAP - 1) == 0


def test_endpoint_epoch_counts_relaunches(tmp_path):
    d = str(tmp_path)
    assert protocol.publish_endpoint(d, "127.0.0.1", 1111) == 1
    assert protocol.publish_endpoint(d, "127.0.0.1", 2222) == 2
    host, port, epoch = protocol.resolve_endpoint(d, timeout=2)
    assert (host, port, epoch) == ("127.0.0.1", 2222, 2)


def test_read_events_skips_torn_lines(tmp_path):
    d = str(tmp_path)
    protocol.log_event(d, "push", worker=0, key="w")
    protocol.log_event(d, "pull", worker=2, key="w")
    with open(protocol.events_path(d), "a") as f:
        f.write('{"event": "push", "worker": 1, "ke')   # SIGKILL mid-append
    evs = protocol.read_events(d)
    assert [e["event"] for e in evs] == ["push", "pull"]


# ---------------------------------------------------------------------------
# server semantics
# ---------------------------------------------------------------------------

def test_push_pull_server_side_sgd_bitmatch(lane):
    """Server-side updates run through the SAME Updater/SGD code path a
    local kvstore uses — the pulled weights must match bit-for-bit."""
    lane.start(world=1)
    c = lane.connect(0)
    w0 = np.arange(8, dtype=np.float32) / 4.0
    c.init("w", w0)
    c.set_optimizer("sgd", {"learning_rate": 0.5})
    grads = [np.full(8, 0.25, np.float32), np.full(8, -0.5, np.float32)]
    for g in grads:
        assert c.push("w", g)["applied"] is True
    got, reply = c.pull("w")
    assert reply["version"] == 2

    stored = nd_array(w0.copy())
    upd = Updater(Optimizer.create_optimizer("sgd", learning_rate=0.5))
    for g in grads:
        upd("w", nd_array(g), stored)
    assert np.array_equal(got, stored.asnumpy())


def test_duplicate_push_acked_not_reapplied(lane):
    srv = lane.start(world=1)
    c = lane.connect(0)
    c.init("w", np.zeros(4, np.float32))
    g = np.ones(4, np.float32)
    assert c.push("w", g)["applied"] is True
    # a retransmit of the same version (retry after a lost ack)
    reply, _ = c.call({"op": "push", "key": "w", "worker": 0,
                       "version": 1}, {"grad": g})
    assert reply["applied"] is False
    value, _ = c.pull("w")
    assert np.array_equal(value, g)          # applied exactly once
    assert srv._stats["duplicate_pushes"] == 1


def test_restarted_worker_resumes_version_sequence(lane):
    """The register reply carries the worker's applied map, so a
    restarted worker continues its push numbering instead of colliding
    with the dedup table and silently losing gradients."""
    lane.start(world=1)
    c1 = lane.connect(0)
    c1.init("w", np.zeros(4, np.float32))
    c1.set_optimizer("sgd", {"learning_rate": 1.0})
    c1.push("w", np.ones(4, np.float32))
    c1.push("w", np.ones(4, np.float32))
    c1.close()

    c2 = lane.connect(0)                     # same rank, fresh process
    r = c2.push("w", np.full(4, 2.0, np.float32))
    assert c2.applied["w"] == 3 and r["applied"] is True
    value, _ = c2.pull("w")
    assert np.array_equal(value, np.full(4, -4.0, np.float32))


def test_staleness_gate_blocks_then_releases(lane):
    lane.start(world=2, staleness=1, pull_timeout=10.0)
    c0, c1 = lane.connect(0), lane.connect(1)
    c0.init("w", np.zeros(4, np.float32))
    c0.set_optimizer("sgd", {"learning_rate": 1.0})
    g = np.ones(4, np.float32)
    c0.push("w", g)
    c1.push("w", g)
    c0.push("w", g)
    c0.push("w", g)        # c0 at 3, c1 at 1: lag 2 > K=1

    got = []
    t = threading.Thread(target=lambda: got.append(c0.pull("w")[0]),
                         daemon=True)
    t.start()
    time.sleep(0.5)
    assert t.is_alive(), "pull should be gated at lag 2 > bound 1"
    c1.push("w", g)        # slowest advances: lag 1 <= 1
    t.join(8)
    assert not t.is_alive() and got
    assert np.array_equal(got[0], np.full(4, -5.0, np.float32))


def test_staleness_zero_is_lockstep_sync_equivalent(lane):
    srv = lane.start(world=2, staleness=0, pull_timeout=10.0)
    c0, c1 = lane.connect(0), lane.connect(1)
    w0 = np.zeros(4, np.float32)
    c0.init("w", w0)
    c0.set_optimizer("sgd", {"learning_rate": 1.0})

    ga = np.full(4, 0.25, np.float32)
    gb = np.full(4, 0.5, np.float32)
    c0.push("w", ga)
    # c0 is 1 ahead of c1 (who has pushed nothing yet but counts only
    # once it pushes) — after c1's first push both are at 1 and anyone
    # may pull; c0 pushing AGAIN then gates its own pull: lockstep.
    c1.push("w", gb)
    c0.push("w", ga)
    gated = []
    t = threading.Thread(target=lambda: gated.append(c0.pull("w")[0]),
                         daemon=True)
    t.start()
    time.sleep(0.4)
    assert t.is_alive(), "K=0: a worker one round ahead must wait"
    c1.push("w", gb)
    t.join(8)
    assert not t.is_alive() and gated
    # two full rounds of (ga + gb) at lr=1: exactly the sync result
    assert np.allclose(gated[0], w0 - 2 * (ga + gb))
    assert srv._stats["staleness_waits"] >= 1


def test_pull_only_worker_never_blocks_nor_gates(lane):
    lane.start(world=2, staleness=0, pull_timeout=5.0)
    pusher, reader = lane.connect(0), lane.connect(1)
    pusher.init("w", np.zeros(4, np.float32))
    pusher.set_optimizer("sgd", {"learning_rate": 1.0})
    for _ in range(5):       # a lone pusher is never gated by K
        pusher.push("w", np.ones(4, np.float32))
    t0 = time.monotonic()
    value, reply = reader.pull("w")       # eval reader: no clock entry
    assert time.monotonic() - t0 < 1.0
    assert reply["waited_ms"] == 0.0
    assert np.array_equal(value, np.full(4, -5.0, np.float32))
    # and the pusher can still pull: the reader holds nobody back
    value, _ = pusher.pull("w")
    assert np.array_equal(value, np.full(4, -5.0, np.float32))


def test_version_wraparound_push_and_staleness(lane):
    """Counters live on the mod-2**32 circle: pushes crossing the wrap
    stay 'newer', and SSP lags computed across the boundary are small
    numbers, not ~4 billion."""
    srv = lane.start(world=2, staleness=2, pull_timeout=10.0)
    w0 = np.zeros(4, np.float32)
    with srv._lock:
        srv._values["w"] = nd_array(w0)
        srv._versions["w"] = WRAP - 2
        srv._applied[(0, "w")] = WRAP - 2
        srv._applied[(1, "w")] = WRAP - 2
    c0, c1 = lane.connect(0), lane.connect(1)
    c0.ensure_registered()
    assert c0.applied["w"] == WRAP - 2       # register restored the clock
    c0.set_optimizer("sgd", {"learning_rate": 1.0})
    g = np.ones(4, np.float32)
    assert c0.push("w", g)["applied"] is True          # version WRAP-1
    assert c0.push("w", g)["applied"] is True          # version 0 (wrap)
    assert c0.applied["w"] == 0
    assert srv._stats["duplicate_pushes"] == 0
    # c0 (wrapped to 0) leads c1 (WRAP-2) by exactly 2 == K: no gate
    value, reply = c0.pull("w")
    assert reply["waited_ms"] == 0.0
    assert np.array_equal(value, np.full(4, -2.0, np.float32))
    # one more push puts c0 3 ahead across the boundary: gate closes
    # (c1 must be LIVE to count in the staleness set at all)
    c1.ensure_registered()
    c0.push("w", g)
    got = []
    t = threading.Thread(target=lambda: got.append(c0.pull("w")[0]),
                         daemon=True)
    t.start()
    time.sleep(0.4)
    assert t.is_alive(), "wrap-aware lag 3 > bound 2 must gate"
    c1.push("w", g)
    t.join(8)
    assert not t.is_alive() and got


def test_server_restart_applies_each_push_exactly_once(lane):
    """Satellite: server restart mid-stream.  A push the restored
    checkpoint already contains is acked-not-reapplied on retry; a new
    push after the restart is applied once — no silent loss, no
    double-apply."""
    srv1 = lane.start(world=1)
    c = lane.connect(0)
    w0 = np.full(4, 8.0, np.float32)
    c.init("w", w0)
    c.set_optimizer("sgd", {"learning_rate": 1.0})
    g1 = np.full(4, 0.5, np.float32)
    c.push("w", g1)
    srv1.checkpoint()
    c.close()                  # the SIGKILL drops every connection
    srv1.stop()

    srv2 = KVServer(lane.dir, world=1)
    srv2.serve_in_thread()
    try:
        c2 = lane.connect(0)
        # worker retries g1 (it never saw the ack): dedup table survived
        reply, _ = c2.call({"op": "push", "key": "w", "worker": 0,
                            "version": 1}, {"grad": g1})
        assert reply["applied"] is False
        g2 = np.full(4, 0.25, np.float32)
        assert c2.push("w", g2)["applied"] is True
        value, _ = c2.pull("w")
        assert np.array_equal(value, w0 - g1 - g2)
        assert srv2._stats["duplicate_pushes"] == 1
        evs = [e["event"] for e in protocol.read_events(lane.dir)]
        assert "restore" in evs and "checkpoint" in evs
    finally:
        srv2.stop()


def test_pull_rows_bitmatch_and_wire_bytes(lane):
    """True PullRowSparse: the server's sparse apply goes through the
    SAME lazy sgd_row_sparse_update as the in-mesh sparse plane (bit
    match), and the wire ledger scales with touched rows, not table
    size."""
    lane.start(world=1)
    c = lane.connect(0)
    rows, dim = 16, 4
    table0 = np.arange(rows * dim, dtype=np.float32).reshape(rows, dim)
    c.init("emb", table0)
    c.set_optimizer("sgd", {"learning_rate": 0.25})
    data = np.array([[1.0] * dim, [2.0] * dim, [3.0] * dim], np.float32)
    ids = np.array([3, 7, 3], np.int64)        # duplicate id: client dedups
    c.push_sparse("emb", data, ids)

    # local mirror: identical RowSparseNDArray grad through the same
    # Updater — touched rows only, lazy O(nnz) update
    stored = nd_array(table0.copy())
    upd = Updater(Optimizer.create_optimizer("sgd", learning_rate=0.25))
    import jax.numpy as jnp
    merged = np.array([[4.0] * dim, [2.0] * dim], np.float32)  # 3 summed
    grad = RowSparseNDArray(jnp.asarray(merged),
                            jnp.asarray(np.array([3, 7])), (rows, dim))
    upd("emb", grad, stored)

    full, _ = c.pull("emb")
    assert np.array_equal(full, stored.asnumpy())

    # wire accounting: ids out (int64) + rows back (f32) + indices back
    c.op_bytes.pop("pull_rows", None)
    data2, idx2, reply = c.pull_rows("emb", np.array([3, 7], np.int64))
    assert list(idx2) == [3, 7] and tuple(reply["shape"]) == (rows, dim)
    assert np.array_equal(data2, stored.asnumpy()[[3, 7]])
    two_row_bytes = c.op_bytes["pull_rows"]
    assert two_row_bytes == 2 * 8 + 2 * dim * 4 + 2 * 8
    c.op_bytes.pop("pull_rows")
    c.pull_rows("emb", np.arange(6, dtype=np.int64))
    assert c.op_bytes["pull_rows"] == 3 * two_row_bytes   # ∝ touched rows
    table_bytes = rows * dim * 4
    assert two_row_bytes < table_bytes // 2


# ---------------------------------------------------------------------------
# KVStorePS facade (kvstore.create("dist_async") with the lane armed)
# ---------------------------------------------------------------------------

def test_create_dispatches_on_kv_dir(lane, monkeypatch):
    from mxnet_tpu import kvstore as kvs
    monkeypatch.delenv("MXNET_TPU_KV_DIR", raising=False)
    kv = kvs.create("dist_async")
    assert not isinstance(kv, KVStorePS)      # in-mesh async lane
    lane.start(world=1)
    monkeypatch.setenv("MXNET_TPU_KV_DIR", lane.dir)
    monkeypatch.setenv("MXNET_TPU_KV_RANK", "0")
    monkeypatch.setenv("MXNET_TPU_KV_WORLD", "1")
    kv = kvs.create("dist_async")
    try:
        assert isinstance(kv, KVStorePS)
        assert kv.rank == 0 and kv.num_workers == 1
    finally:
        kv.close()


def test_kvstore_ps_end_to_end(lane, monkeypatch):
    import jax.numpy as jnp
    from mxnet_tpu import kvstore as kvs
    lane.start(world=1)
    monkeypatch.setenv("MXNET_TPU_KV_DIR", lane.dir)
    monkeypatch.setenv("MXNET_TPU_KV_RANK", "0")
    monkeypatch.setenv("MXNET_TPU_KV_WORLD", "1")
    kv = kvs.create("dist_async")
    try:
        w0 = np.linspace(0, 1, 8).astype(np.float32)
        kv.init("w", nd_array(w0))
        opt = Optimizer.create_optimizer("sgd", learning_rate=0.5)
        kv.set_optimizer(opt)
        with pytest.raises(MXNetError):
            kv.set_updater(lambda k, g, w: None)     # callables don't travel
        kv.push("w", nd_array(np.full(8, 0.5, np.float32)))
        out = nd_array(np.zeros(8, np.float32))
        kv.pull("w", out=out)
        assert np.allclose(out.asnumpy(), w0 - 0.25)

        # row_sparse_pull into a RowSparseNDArray out
        table = np.ones((8, 2), np.float32)
        kv.init("emb", nd_array(table))
        o = RowSparseNDArray(jnp.zeros((1, 2)), jnp.zeros((1,), jnp.int32),
                             (8, 2))
        kv.row_sparse_pull("emb", out=o,
                           row_ids=nd_array(np.array([5, 1, 5],
                                                     np.float32)))
        assert list(np.asarray(o._indices)) == [1, 5]
        assert np.array_equal(np.asarray(o._data), table[[1, 5]])
        kv.barrier()
        assert kv.num_dead_node() == 0
    finally:
        kv.close()


# ---------------------------------------------------------------------------
# chaos: retry absorption + rank targeting
# ---------------------------------------------------------------------------

def test_io_error_absorbed_by_retry(lane, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_KV_RETRY_BACKOFF", "0.01")
    lane.start(world=1)
    c = lane.connect(0)
    c.init("w", np.ones(2, np.float32))
    chaos.reset()
    with chaos.inject("io_error"):
        value, reply = c.pull("w")        # first attempt raises, retried
    assert reply["ok"] and np.array_equal(value, np.ones(2, np.float32))
    chaos.reset()


def test_chaos_ranks_pins_faults(monkeypatch):
    # this process is rank 1; the fault is pinned to rank 2 -> no fire
    monkeypatch.setenv("MXNET_TPU_CHAOS_RANK", "1")
    monkeypatch.setenv("MXNET_TPU_CHAOS_RANKS", "2")
    chaos.reset()
    with chaos.inject("io_error"):
        assert chaos.fire("io_error") is None
    # pinned set includes rank 1 -> fires
    monkeypatch.setenv("MXNET_TPU_CHAOS_RANKS", "2,1")
    chaos.reset()
    with chaos.inject("io_error"):
        assert chaos.fire("io_error") is not None
    # no resolvable rank at all -> a targeted fault never fires
    for var in ("MXNET_TPU_CHAOS_RANK", "MXNET_TPU_KV_RANK",
                "DMLC_WORKER_ID"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("MXNET_TPU_CHAOS_RANKS", "0")
    chaos.reset()
    with chaos.inject("io_error"):
        assert chaos.fire("io_error") is None
    # unset -> faults fire everywhere (the pre-satellite behaviour)
    monkeypatch.delenv("MXNET_TPU_CHAOS_RANKS")
    chaos.reset()
    with chaos.inject("io_error"):
        assert chaos.fire("io_error") is not None
    chaos.reset()


# ---------------------------------------------------------------------------
# FileKVClient concurrent-writer stress (satellite 1)
# ---------------------------------------------------------------------------

_STRESS = r"""
import random, sys
sys.path.insert(0, %r)
from mxnet_tpu.resilience.watchdog import FileKVClient
d, wid = sys.argv[1], int(sys.argv[2])
kv = FileKVClient(d)
rng = random.Random(wid)
for i in range(120):
    n = rng.randint(0, 1500)
    kv.key_value_set("shared", "%%d|%%s" %% (n, "x" * n))
    try:
        v = kv.key_value_get("shared")
    except KeyError:
        continue
    head, _, tail = v.partition("|")
    assert head.isdigit() and len(tail) == int(head), (
        "torn value: %%r..." %% v[:40])
print("worker %%d ok" %% wid)
"""


def test_filekv_multiprocess_stress(tmp_path):
    """Many processes hammering one key: every read must be a complete,
    framed value — never a torn or partially-flushed one."""
    script = _STRESS % REPO
    procs = [subprocess.Popen(
        [sys.executable, "-c", script, str(tmp_path), str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
        for i in range(4)]
    for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=120)
        assert p.returncode == 0, "writer %d:\n%s" % (i, out.decode())
    from mxnet_tpu.resilience.watchdog import FileKVClient
    v = FileKVClient(str(tmp_path)).key_value_get("shared")
    head, _, tail = v.partition("|")
    assert len(tail) == int(head)


# ---------------------------------------------------------------------------
# postmortem --kvstore timeline
# ---------------------------------------------------------------------------

def test_postmortem_kvstore_timeline(lane, capsys):
    lane.start(world=1)
    c = lane.connect(0)
    c.init("w", np.zeros(4, np.float32))
    c.push("w", np.ones(4, np.float32))
    c.pull("w")
    c.pull_rows("w", np.array([0, 2], np.int64))
    c.server_checkpoint()
    c.close()
    time.sleep(0.3)          # let the server log the eviction

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import postmortem
    finally:
        sys.path.pop(0)
    assert postmortem.main([lane.dir, "--kvstore"]) == 0
    out = capsys.readouterr().out
    assert "KVSTORE (dist_async PS) TIMELINE" in out
    for ev in ("listen", "register", "push", "pull", "checkpoint",
               "evict"):
        assert ev in out, out
    assert "per-worker traffic" in out
