"""Distributed-tracing tests (mxnet_tpu/telemetry/tracing.py +
serving propagation + tools/tracewatch.py).

Three tiers, like test_fleet.py:
 - unit seams with no processes: context mint/wire round trip, the
   sampling bit, the bounded flight-recorder sink, request-lane
   reconstruction, the tracewatch merge (lanes, flows, orphans), the
   disarmed zero-cost gate, and the compile/ span family;
 - process drills: real replica processes behind the router with
   tracing armed — THE kill drill (chaos ``replica_crash`` SIGKILLs a
   replica mid-batch under load: evict + re-dispatch under ONE
   trace_id, zero orphan spans, merge passes the existing
   trace-nesting validity helper) and the hedge drill (winner ok,
   loser marked cancelled, hedge events in fleet-events.jsonl with
   trace ids);
 - tenant SLO: the flooding tenant burns only its own budget —
   router stats table, registry mirror, render_fleet table.
"""
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.resilience import chaos
from mxnet_tpu.serving import TenantPolicy
from mxnet_tpu.serving.errors import Cancelled, DeadlineExceeded
from mxnet_tpu.serving.fleet import ServingFleet
from mxnet_tpu.serving.request import Request
from mxnet_tpu.telemetry import tracing

from test_telemetry import _check_nesting

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tracewatch():
    spec = importlib.util.spec_from_file_location(
        "tracewatch", os.path.join(REPO, "tools", "tracewatch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


tracewatch = _load_tracewatch()


@pytest.fixture(autouse=True)
def _clean():
    chaos.reset()
    telemetry.reset()          # clears tracing arm state + cached sink
    yield
    chaos.reset()
    telemetry.reset()


def _settled_request(trace=None, error=None, popped=True, exec_done=True):
    req = Request({"data": None}, 2, priority=1,
                  deadline=time.monotonic() + 60.0)
    # phase timestamps sit slightly in the PAST so the settle time the
    # one-shot future stamps (now) bounds them all
    now = time.monotonic() - 0.01
    if popped:
        req.t_popped = now
        req.t_dispatched = now + 0.001
        req.batch_seq = 7
    if exec_done:
        req.t_exec_done = now + 0.004
    req.trace = trace
    if error is None:
        req._deliver([])
    else:
        req._fail(error)
    return req


def _sink_spans(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# unit seams
# ---------------------------------------------------------------------------

def test_context_mint_wire_roundtrip_and_sampling(tmp_path):
    assert tracing.new_context() is None          # disarmed: no work
    tracing.arm(sample=1.0)
    tracing.set_sink_dir(str(tmp_path))
    ctx = tracing.new_context()
    assert ctx is not None and ctx.sampled and ctx.parent_id is None
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    # wire round trip: the sender's span id becomes the receiver's
    # PARENT (W3C-traceparent discipline) under a fresh local span id
    rebound = tracing.from_wire(child.to_wire())
    assert rebound.trace_id == ctx.trace_id
    assert rebound.parent_id == child.span_id
    assert rebound.span_id != child.span_id
    assert rebound.sampled
    # garbage on the wire is tolerated, never fatal
    assert tracing.from_wire(None) is None
    assert tracing.from_wire({"tid": "x"}) is None
    assert tracing.from_wire("nonsense") is None

    # unsampled: ids still mint (event logs stay correlatable), spans
    # do not record
    tracing.arm(sample=0.0)
    ctx0 = tracing.new_context()
    assert ctx0 is not None and not ctx0.sampled
    assert tracing.record("x", ctx0, time.time(), 0.1) is None
    assert tracing.from_wire(ctx0.child().to_wire()).sampled is False


def test_sink_is_bounded_flight_recorder(tmp_path):
    path = str(tmp_path / "trace-t-1.jsonl")
    sink = tracing.TraceSink(path, max_spans=20)
    for i in range(95):
        sink.append({"trace": "t", "span": "s%d" % i, "name": "n"})
    sink.close()
    spans = _sink_spans(path)
    assert len(spans) <= 20                     # hard bound held
    assert spans[-1]["span"] == "s94"           # newest survive
    assert int(spans[0]["span"][1:]) > 0        # oldest compacted away


def test_record_served_request_reconstructs_lanes(tmp_path):
    tracing.arm(sample=1.0)
    tracing.set_sink_dir(str(tmp_path))
    tracing.set_process_label("replica9")
    wirectx = tracing.from_wire(
        {"tid": "t" * 16, "sid": "d" * 16, "smp": 1})
    tracing.record_served_request(_settled_request(trace=wirectx))
    spans = _sink_spans(tracing.sink_path())
    by_name = {s["name"]: s for s in spans}
    root = by_name["replica/request"]
    assert root["parent"] == "d" * 16            # the dispatch span
    assert root["outcome"] == "ok"
    assert root["attrs"]["batch"] == 7           # executor batch seq
    for phase in ("serve/queue_wait", "serve/batch_fill", "serve/exec",
                  "serve/deliver"):
        assert by_name[phase]["parent"] == root["span"]
        assert by_name[phase]["proc"] == "replica9"

    # a request with no trace records nothing; outcomes map typed errors
    tracing.record_served_request(_settled_request(trace=None))
    assert len(_sink_spans(tracing.sink_path())) == len(spans)
    tracing.record_served_request(_settled_request(
        trace=wirectx.child(), error=Cancelled("hedge lost"),
        exec_done=False))
    cancelled = [s for s in _sink_spans(tracing.sink_path())
                 if s["outcome"] == "cancelled"]
    assert cancelled and any(s["name"] == "replica/request"
                             for s in cancelled)


def test_request_outcome_vocabulary():
    assert tracing.request_outcome(_settled_request()) == "ok"
    assert tracing.request_outcome(
        _settled_request(error=Cancelled("x"))) == "cancelled"
    assert tracing.request_outcome(
        _settled_request(error=DeadlineExceeded("x"))) == "deadline"
    assert tracing.request_outcome(
        _settled_request(error=RuntimeError("x"))) == "error:RuntimeError"


def test_bind_donates_ordinary_spans_to_the_trace(tmp_path):
    tracing.arm(sample=1.0)
    tracing.set_sink_dir(str(tmp_path))
    ctx = tracing.new_context()
    with tracing.bind(ctx):
        with telemetry.span("work/inner", cat="test", step=3):
            pass
    with telemetry.span("work/outside", cat="test"):
        pass                                     # unbound: not recorded
    spans = _sink_spans(tracing.sink_path())
    names = [s["name"] for s in spans]
    assert "work/inner" in names and "work/outside" not in names
    inner = next(s for s in spans if s["name"] == "work/inner")
    assert inner["trace"] == ctx.trace_id
    assert inner["parent"] == ctx.span_id


def test_disarmed_gates_are_zero_cost():
    """The tracing gates the serving hot path gained (context mint at
    submit, request-lane emission at settle) must stay inside the
    telemetry layer's disarmed per-call bound."""
    req = _settled_request(trace=None)
    n = 3000
    t0 = time.perf_counter()
    for i in range(n):
        tracing.new_context()
        tracing.record_served_request(req)
        with telemetry.span("t/hot", step=i):
            pass
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 50e-6, "disarmed tracing cost %.1fus" % (
        per_call * 1e6)
    assert tracing.sink_path() is None          # nothing ever opened


def test_tracewatch_merge_lanes_flows_and_orphans(tmp_path):
    """Two synthetic process sinks -> one merged Perfetto trace: the
    existing nesting validity helper passes, cross-process edges get
    flow events, hedged (overlapping) dispatches land on sibling lanes,
    and a parentless span is flagged as an orphan."""
    t0 = 1000.0

    def rec(trace, span, parent, name, pid, proc, a, b, outcome="ok"):
        return {"trace": trace, "span": span, "parent": parent,
                "name": name, "cat": "t", "pid": pid, "proc": proc,
                "t0": t0 + a, "dur": b - a, "outcome": outcome}

    router = [
        rec("T1", "R1", None, "fleet/request", 1, "router", 0.0, 0.100),
        # two OVERLAPPING dispatches (a hedge): must fan out onto
        # sibling lanes, not overlap on one
        rec("T1", "D1", "R1", "fleet/dispatch", 1, "router", 0.001,
            0.095, outcome="cancelled"),
        rec("T1", "D2", "R1", "fleet/dispatch", 1, "router", 0.050,
            0.099),
    ]
    replica = [
        rec("T1", "S1", "D2", "replica/request", 2, "replica0", 0.052,
            0.090),
        rec("T1", "S2", "S1", "serve/exec", 2, "replica0", 0.053, 0.089),
    ]
    with open(tmp_path / "trace-router-1.jsonl", "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in router)
    with open(tmp_path / "trace-replica0-2.jsonl", "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in replica)

    spans, bad = tracewatch.load_spans([str(tmp_path)])
    assert bad == 0 and len(spans) == 5
    assert tracewatch.find_orphans(spans) == []
    trace = tracewatch.merge_trace(spans)
    events = trace["traceEvents"]
    _check_nesting([e for e in events if e["ph"] == "X"])
    xs = {e["args"]["span"]: e for e in events if e["ph"] == "X"}
    assert xs["D1"]["tid"] != xs["D2"]["tid"]       # hedge fan-out
    assert xs["S1"]["pid"] != xs["D2"]["pid"]
    flows = [e for e in events if e["ph"] in ("s", "f")]
    assert len(flows) >= 2                          # D2 -> S1 at least
    procs = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert {"router", "replica0"} <= procs

    # text rendering of one request
    import io
    buf = io.StringIO()
    tracewatch.render_request(spans, "T1", out=buf)
    text = buf.getvalue()
    assert "fleet/request" in text and "replica/request" in text
    assert "cancelled" in text

    # an orphan (parent never recorded anywhere) is flagged
    with open(tmp_path / "trace-ghost-3.jsonl", "w") as f:
        f.write(json.dumps(rec("T1", "X1", "NOPE", "serve/exec", 3,
                               "ghost", 0.01, 0.02)) + "\n")
    spans2, _ = tracewatch.load_spans([str(tmp_path)])
    orphans = tracewatch.find_orphans(spans2)
    assert [s["span"] for s in orphans] == ["X1"]
    assert tracewatch.main([str(tmp_path), "--check",
                            "--out", str(tmp_path / "m.json")]) == 1


def test_compile_span_family_trainer_first_step():
    """ROADMAP item 5 prep: the trainer's first-step jit compile lands
    in the compile.seconds registry histogram and the always-on
    compile_summary() the bench ledger extra reads."""
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    telemetry.arm()
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc1")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    trainer = ShardedTrainer(net, MeshSpec(make_mesh((1,), ("dp",))))
    shapes = {"data": (4, 3), "softmax_label": (4,)}
    params, mom, aux = trainer.init_state(shapes)
    rs = np.random.RandomState(0)
    batch = {"data": rs.rand(4, 3).astype(np.float32),
             "softmax_label": rs.randint(0, 2, 4).astype(np.float32)}
    before = tracing.compile_summary()["count"]
    for _ in range(2):
        params, mom, aux, loss = trainer.step(params, mom, aux, batch)
    summary = tracing.compile_summary()
    assert summary["count"] == before + 1          # compiled ONCE
    assert summary["by_name"].get("train_step", 0) > 0
    assert summary["total_seconds"] > 0
    hist = telemetry.histogram("compile.seconds").summary(
        what="train_step")
    assert hist["count"] >= 1 and hist["sum"] > 0


# ---------------------------------------------------------------------------
# process drills
# ---------------------------------------------------------------------------

def _mk_traced_fleet(n, tmp_path, monkeypatch, latency=0.005, **kw):
    monkeypatch.setenv("MXNET_TPU_TRACE", "1")
    tracing.reset()            # re-read the env in THIS (router) process
    kw.setdefault("synthetic", (4, 3, latency))
    kw.setdefault("fleet_dir", str(tmp_path / "fleet"))
    kw.setdefault("stale_after", 0.8)
    kw.setdefault("scan_interval", 0.05)
    kw.setdefault("ready_timeout", 45.0)
    return ServingFleet(n, **kw)


def _events(fleet):
    path = os.path.join(fleet.fleet_dir, "fleet-events.jsonl")
    if not os.path.isfile(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _merged_ok(fleet_dir):
    """Load all sinks, assert zero orphans + nesting validity; returns
    the spans.  A SIGKILLed replica may leave at most one partial
    line (killed mid-append) — tolerated, like the loader itself does."""
    spans, bad = tracewatch.load_spans([fleet_dir])
    assert bad <= 1, "unreadable sink lines: %d" % bad
    assert spans, "no trace spans recorded"
    orphans = tracewatch.find_orphans(spans)
    assert orphans == [], "orphan spans: %r" % orphans[:5]
    events = tracewatch.merge_trace(spans)["traceEvents"]
    _check_nesting([e for e in events if e["ph"] == "X"])
    # cross-process parent/child edges became flow links
    assert any(e["ph"] == "s" for e in events)
    return spans


def test_trace_kill_drill_one_trace_zero_orphans(tmp_path, monkeypatch):
    """THE acceptance drill, traced: chaos ``replica_crash`` SIGKILLs a
    replica mid-batch under load.  The merged trace shows the evicted
    dispatch AND its re-dispatch under ONE trace_id across >= 3
    processes, with zero orphan spans and valid nesting."""
    fleet = _mk_traced_fleet(
        3, tmp_path, monkeypatch, latency=0.01,
        replica_env={1: {"MXNET_TPU_CHAOS": "replica_crash@15"}})
    try:
        deadline = 1.5
        errs = {}
        lock = threading.Lock()
        stop_at = time.monotonic() + 2.5
        x = np.full((3,), 1.0, np.float32)

        def worker():
            while time.monotonic() < stop_at:
                try:
                    req = fleet.submit(data=x, deadline=deadline)
                    req.result(timeout=deadline + 5.0)
                except Exception as e:
                    with lock:
                        k = type(e).__name__
                        errs[k] = errs.get(k, 0) + 1

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errs, "requests failed during the kill drill: %s" % errs
        c = fleet.stats()["counters"]
        assert c["evictions"] >= 1
        events = _events(fleet)
    finally:
        fleet.close()

    spans = _merged_ok(fleet.fleet_dir)
    procs = {s["proc"] for s in spans}
    assert len(procs) >= 4, procs        # router + 3 replicas
    # the re-dispatch events carry trace ids that resolve to real trees
    redis = [e for e in events if e["event"] == "redispatch"]
    assert redis, "no redispatch events in fleet-events.jsonl"
    traced = [e for e in redis if e.get("trace")]
    assert traced, "redispatch events lost their trace ids"
    tid = traced[0]["trace"]
    mine = [s for s in spans if s["trace"] == tid]
    dispatches = [s for s in mine if s["name"] == "fleet/dispatch"]
    assert len(dispatches) >= 2, \
        "re-dispatched request shows %d dispatch spans" % len(dispatches)
    outcomes = {s["outcome"] for s in dispatches}
    assert "ok" in outcomes and outcomes - {"ok"}, outcomes
    roots = [s for s in mine if s["name"] == "fleet/request"]
    assert len(roots) == 1 and roots[0]["outcome"] == "ok"
    # every span of this request's story is under the ONE trace id
    assert all(s["trace"] == tid for s in mine)


def test_trace_hedge_winner_and_cancelled_loser(tmp_path, monkeypatch):
    """Hedge drill, traced: the straggler replica's copy is marked
    cancelled on BOTH sides (router dispatch span + replica request
    span), the winner is ok, and the hedge/cancel events carry the
    trace id into fleet-events.jsonl and postmortem --fleet."""
    fleet = _mk_traced_fleet(
        2, tmp_path, monkeypatch, latency=0.005,
        hedge_min=0.05, hedge_factor=1.5,
        replica_env={1: {"MXNET_TPU_CHAOS": "hedge_lagx1000000",
                         "MXNET_TPU_CHAOS_HEDGE_LAG_SECONDS": "0.4"}})
    try:
        x = np.full((3,), 1.0, np.float32)
        for _ in range(12):
            fleet.predict(data=x, deadline=2.0)
        c = fleet.stats()["counters"]
        assert c.get("hedge_won", 0) >= 1, c
        time.sleep(0.4)        # let cancelled losers settle replica-side
        events = _events(fleet)
    finally:
        fleet.close()

    spans = _merged_ok(fleet.fleet_dir)
    hedged = [s for s in spans if s["name"] == "fleet/dispatch"
              and (s.get("attrs") or {}).get("hedge")]
    assert hedged, "no hedge dispatch spans"
    tid = hedged[0]["trace"]
    mine = [s for s in spans if s["trace"] == tid]
    d_out = {s["outcome"] for s in mine if s["name"] == "fleet/dispatch"}
    assert d_out == {"ok", "cancelled"}, d_out
    # the loser is cancelled on the REPLICA side too — both copies'
    # request spans are present in the merged trace
    rep_out = {s["outcome"] for s in mine
               if s["name"] == "replica/request"}
    assert "cancelled" in rep_out, rep_out
    assert "ok" in rep_out, rep_out
    # events carry the trace id; all three previously-missing kinds land
    kinds = {e["event"] for e in events}
    assert {"hedge_fired", "hedge_won", "cancelled"} <= kinds, kinds
    for e in events:
        if e["event"] in ("hedge_fired", "hedge_won", "cancelled"):
            assert e.get("trace"), e

    # postmortem --fleet renders the hedge timeline with trace ids
    import subprocess, sys
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "postmortem.py"),
         "--fleet", fleet.fleet_dir],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    assert "hedge_fired" in out.stdout and "hedge_won" in out.stdout
    assert "trace=" in out.stdout


def test_tenant_slo_flood_burns_only_its_own_budget(tmp_path,
                                                    monkeypatch):
    """Per-tenant SLO accounting: a flooding tenant's sheds and budget
    burn stay on its own row; the vip tenant keeps availability 1.0 —
    in router.stats(), in the registry mirror, and in render_fleet()'s
    tenant table via the router's lane digest."""
    telemetry.arm()
    fleet = _mk_traced_fleet(
        2, tmp_path, monkeypatch, latency=0.002,
        quotas={"flood": TenantPolicy(rate=25, burst=4, priority=0),
                "vip": TenantPolicy(priority=5)})
    try:
        x = np.full((3,), 1.0, np.float32)
        stop_at = time.monotonic() + 1.6

        def flooder():
            while time.monotonic() < stop_at:
                try:
                    fleet.predict(data=x, tenant="flood", deadline=1.0)
                except Exception:
                    time.sleep(0.002)

        threads = [threading.Thread(target=flooder, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        vip_ok = 0
        while time.monotonic() < stop_at:
            fleet.predict(data=x, tenant="vip", deadline=1.0)
            vip_ok += 1
            time.sleep(0.02)
        for t in threads:
            t.join(timeout=10.0)
        time.sleep(0.7)                    # let the router publish SLO
        tenants = fleet.stats()["tenants"]

        assert vip_ok >= 20
        assert tenants["flood"]["shed"].get("quota", 0) > 0
        assert tenants["vip"]["shed"] == {}
        assert tenants["vip"]["availability"] == 1.0
        assert tenants["vip"]["ok"] == vip_ok
        assert "latency_ms" in tenants["vip"]
        assert tenants["flood"]["budget_burn"]["p95"] < 1.0

        # registry mirror carries tenant labels
        shed = telemetry.counter("fleet.tenant.shed")
        assert shed.value(cause="quota", tenant="flood") > 0
        assert shed.value(cause="quota", tenant="vip") == 0

        # render_fleet() shows the tenant table from the lane digest
        monkeypatch.setenv("MXNET_TPU_FLEET_DIR", fleet.fleet_dir)
        text = telemetry.render_fleet(
            telemetry.serving_fleet_view(fleet.fleet_dir))
        assert "tenant SLO" in text
        assert "flood" in text and "vip" in text
    finally:
        fleet.close()
