"""ImageRecordIter: native C++ pipeline vs pure-Python path.

The native plane (native/record_iter.cc — OMP JPEG decode + bounded
prefetch queue, the analog of the reference's
src/io/iter_image_recordio_2.cc:50,138-171 + iter_prefetcher.h:47-77) must
produce the same batches as the Python path on the same RecordIO file, and
the im2rec tool (native/im2rec.cc, reference tools/im2rec.cc) must produce
files both can read.
"""
import io as pyio
import os
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.io.native import load_native

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

N_IMG = 10
SHAPE = (3, 32, 32)          # c, h, w
BS = 4


def _jpeg_bytes(rs, h, w):
    from PIL import Image
    arr = rs.randint(0, 256, (h, w, 3), dtype=np.uint8)
    buf = pyio.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


@pytest.fixture(scope="module")
def rec_file(tmp_path_factory):
    """Indexed RecordIO file with N_IMG random JPEGs, label = index."""
    d = tmp_path_factory.mktemp("recio")
    prefix = str(d / "synth")
    rs = np.random.RandomState(0)
    writer = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(N_IMG):
        hdr = recordio.IRHeader(0, float(i), i, 0)
        writer.write_idx(i, recordio.pack(hdr, _jpeg_bytes(rs, 32, 32)))
    writer.close()
    return prefix


def _collect(it):
    """Iterate an epoch → (data [n,b,c,h,w], labels [n,b], pads)."""
    it.reset()
    data, labels, pads = [], [], []
    while True:
        try:
            b = it.next()
        except StopIteration:
            break
        data.append(b.data[0].asnumpy())
        labels.append(b.label[0].asnumpy())
        pads.append(b.pad)
    return np.stack(data), np.stack(labels), pads


def _make_iter(rec_file, native, **kw):
    kw.setdefault("batch_size", BS)
    os.environ["MXNET_TPU_NATIVE_IO"] = "1" if native else "0"
    try:
        return mx.io.ImageRecordIter(
            path_imgrec=rec_file + ".rec", path_imgidx=rec_file + ".idx",
            data_shape=SHAPE, **kw)
    finally:
        os.environ.pop("MXNET_TPU_NATIVE_IO", None)


needs_native = pytest.mark.skipif(
    load_native() is None, reason="native IO library not built")


@needs_native
def test_iter_selects_native_backend(rec_file):
    it = _make_iter(rec_file, native=True)
    assert it._native is not None
    it2 = _make_iter(rec_file, native=False)
    assert it2._native is None


@needs_native
def test_native_matches_python_batches(rec_file):
    """Deterministic config (no shuffle/crop/mirror): both backends must
    produce the same batches in the same order."""
    kw = dict(mean_r=123.0, mean_g=117.0, mean_b=104.0,
              std_r=58.0, std_g=57.0, std_b=57.0)
    dn, ln, pn = _collect(_make_iter(rec_file, native=True, **kw))
    dp, lp, pp = _collect(_make_iter(rec_file, native=False, **kw))
    assert dn.shape == dp.shape == (3, BS, *SHAPE)
    np.testing.assert_array_equal(ln, lp)
    assert pn == pp == [0, 0, 2]
    # decode is libjpeg in both paths; allow 2/255 for rounding differences
    # in the normalize order, scaled by std
    assert np.max(np.abs(dn - dp)) < 2.0 / 57.0 + 1e-5


@needs_native
def test_native_two_epochs_identical(rec_file):
    it = _make_iter(rec_file, native=True)
    d1, l1, _ = _collect(it)
    d2, l2, _ = _collect(it)
    np.testing.assert_array_equal(l1, l2)
    np.testing.assert_array_equal(d1, d2)


@needs_native
def test_native_pad_repeats_records(rec_file):
    """10 records, bs=4 → last batch pad=2, pad slots repeat real ones."""
    _, labels, pads = _collect(_make_iter(rec_file, native=True))
    assert pads == [0, 0, 2]
    # pad slots repeat slot j % (bs - pad)
    assert labels[2][2] == labels[2][0]
    assert labels[2][3] == labels[2][1]


@needs_native
def test_native_shuffle_is_permutation(rec_file):
    """Shuffled epoch covers the same records, in a different order, and
    reshuffles across epochs."""
    it = _make_iter(rec_file, native=True, shuffle=True, seed=5)
    _, l1, _ = _collect(it)
    _, l2, _ = _collect(it)
    seen1 = set(l1.ravel().astype(int))
    assert set(range(N_IMG)) <= seen1
    assert not np.array_equal(l1, l2) or N_IMG <= 2


@needs_native
def test_native_partition_disjoint(rec_file):
    """num_parts=2: each worker sees a disjoint half of the records
    (reference part_index/num_parts contract)."""
    halves = []
    for part in range(2):
        it = _make_iter(rec_file, native=True, num_parts=2, part_index=part,
                        batch_size=5)
        _, labels, pads = _collect(it)
        assert labels.shape == (1, 5)
        assert pads == [0]
        halves.append(set(labels.ravel().astype(int)))
    assert halves[0].isdisjoint(halves[1])
    assert halves[0] | halves[1] == set(range(N_IMG))


@needs_native
def test_native_rand_augment_shapes(rec_file):
    """resize + rand_crop + rand_mirror exercise the native augment path."""
    it = _make_iter(rec_file, native=True, resize=40, rand_crop=True,
                    rand_mirror=True)
    data, labels, _ = _collect(it)
    assert data.shape == (3, BS, *SHAPE)
    assert np.isfinite(data).all()


@needs_native
def test_im2rec_tool_roundtrip(tmp_path):
    """native/build/im2rec packs a .lst of images into .rec+.idx readable
    by BOTH backends."""
    im2rec = os.path.join(REPO, "native", "build", "im2rec")
    if not os.path.isfile(im2rec):
        pytest.skip("im2rec not built")
    from PIL import Image
    rs = np.random.RandomState(1)
    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    lines = []
    for i in range(6):
        arr = rs.randint(0, 256, (32, 32, 3), dtype=np.uint8)
        name = "img%d.jpg" % i
        Image.fromarray(arr).save(str(img_dir / name), quality=95)
        lines.append("%d\t%d\t%s" % (i, i * 10, name))
    lst = tmp_path / "set.lst"
    lst.write_text("\n".join(lines) + "\n")
    prefix = str(tmp_path / "packed")
    subprocess.run([im2rec, str(lst), str(img_dir) + "/", prefix],
                   check=True, capture_output=True)
    assert os.path.isfile(prefix + ".rec")
    assert os.path.isfile(prefix + ".idx")
    for native in (True, False):
        it = _make_iter(prefix, native=native, batch_size=3)
        _, labels, pads = _collect(it)
        assert labels.shape == (2, 3)
        assert sorted(labels.ravel().astype(int)) == [0, 10, 20, 30, 40, 50]
        assert pads == [0, 0]


@needs_native
def test_module_fit_on_native_record_iter(rec_file):
    """End-to-end: Module.fit consumes the native pipeline (the wiring the
    r2 verdict flagged as dead code)."""
    it = _make_iter(rec_file, native=True, shuffle=True)
    assert it._native is not None
    data = mx.sym.Variable("data")
    net = mx.sym.Flatten(data)
    net = mx.sym.FullyConnected(net, name="fc", num_hidden=N_IMG)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.fit(it, num_epoch=2,
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.init.Uniform(0.05))
    params, _ = mod.get_params()
    assert all(np.isfinite(v.asnumpy()).all() for v in params.values())


@needs_native
def test_native_partition_edge_cases(rec_file):
    """num_parts > #records must yield an EMPTY partition, never fall back
    to reading the whole file; part_index out of range fails loudly."""
    from mxnet_tpu.io.native import NativeRecordIter
    it = NativeRecordIter(rec_file + ".rec", SHAPE, 2,
                          idx_path=rec_file + ".idx",
                          part_index=0, num_parts=N_IMG + 5)
    with pytest.raises(StopIteration):
        it.next()
    with pytest.raises(ValueError):
        NativeRecordIter(rec_file + ".rec", SHAPE, 2,
                         idx_path=rec_file + ".idx",
                         part_index=3, num_parts=2)


def test_rec2idx_rebuilds_index(rec_file, tmp_path):
    """tools/rec2idx.py (reference tools/rec2idx.py): a rebuilt .idx must
    let MXIndexedRecordIO random-access the same records."""
    import sys
    rebuilt = str(tmp_path / "rebuilt.idx")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "rec2idx.py"),
         rec_file + ".rec", rebuilt],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stderr
    orig = dict(tuple(l.split("\t")) for l in open(rec_file + ".idx"))
    new = dict(tuple(l.split("\t")) for l in open(rebuilt))
    assert orig == new
    rd = recordio.MXIndexedRecordIO(rebuilt, rec_file + ".rec", "r")
    hdr, img = recordio.unpack(rd.read_idx(N_IMG - 1))
    assert hdr.label == float(N_IMG - 1)
    rd.close()


def test_native_cpp_unit_tests():
    """The native-plane C++ unit-test binary (tests/cpp tier analog):
    RecordIO framing/alignment/random-access, index parsing, resize
    kernel, and decode failure paths."""
    import shutil
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain")
    r = subprocess.run(["make", "-C", os.path.join(REPO, "native"),
                        "build/test_native"], capture_output=True, text=True)
    # a broken test build is a FAILURE, not a skip — only environments
    # without the toolchain may skip
    assert r.returncode == 0, "native test build failed: " + r.stderr[-600:]
    r = subprocess.run([os.path.join(REPO, "native", "build", "test_native")],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "native unit tests: OK" in r.stdout
