"""Prediction-conformance plane tests (ISSUE 20).

Covers the calibration store (roundtrip, running-mean updates, fallback
ladder, ledger fitting), pre-flight budgets + env-limit gating, the
conformance verdict bands, the CI-gated prediction-agreement loop for
the trainer and ring entry points, input-bound detection on a genuinely
starved toy run, and the fleet-level drill where a rank slow against its
OWN budget is fingered through the heartbeat-digest conformance column.
"""
import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.analysis import predict
from mxnet_tpu.io import DataIter, DataBatch, NDArrayIter
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.parallel.ring import local_ring_attention_fn
from mxnet_tpu.parallel.trainer import ShardedTrainer
from mxnet_tpu.resilience import watchdog
from mxnet_tpu.telemetry import perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_COMPAT = {} if hasattr(jax.lax, "pvary") else {"check_rep": False}


@pytest.fixture(autouse=True)
def _isolated_plane(tmp_path, monkeypatch):
    """Every test gets its own calibration store + clean noted budgets;
    nothing leaks into (or reads) the developer's ~/.cache store."""
    monkeypatch.setenv("MXNET_TPU_CALIBRATION_CACHE",
                       str(tmp_path / "calibration.json"))
    for var in ("MXNET_TPU_STEP_BUDGET_MS", "MXNET_TPU_WIRE_BUDGET_MB",
                "MXNET_TPU_DEVICE_HBM_GB", "MXNET_TPU_THROUGHPUT_FLOOR"):
        monkeypatch.delenv(var, raising=False)
    predict.reset()
    telemetry.reset()
    yield
    telemetry.disarm()
    telemetry.reset()
    predict.reset()


def _toy_compiled(n=128):
    return jax.jit(lambda a, b: a @ b).lower(
        jnp.ones((n, n), jnp.float32),
        jnp.ones((n, n), jnp.float32)).compile()


# ---------------------------------------------------------------------------
# calibration store
# ---------------------------------------------------------------------------

def test_store_roundtrip_and_running_mean(tmp_path):
    path = str(tmp_path / "c.json")
    store = predict.load_store(path)
    assert store["entries"] == {}
    predict.update_calibration(store, "cpu", "compute", 0.4)
    predict.update_calibration(store, "cpu", "compute", 0.6)
    e = store["entries"]["cpu|compute"]
    assert e["achievable_fraction"] == pytest.approx(0.5)
    assert e["n"] == 2
    saved = predict.save_store(store, path)
    assert saved == path
    back = predict.load_store(path)
    assert back["entries"]["cpu|compute"]["n"] == 2
    assert back["fitted_t"] > 0
    # corrupt file degrades to an empty store, never raises
    with open(path, "w") as fh:
        fh.write("{nope")
    assert predict.load_store(path)["entries"] == {}
    # fractions are clamped into (0, 1]
    predict.update_calibration(store, "cpu", "hbm", 7.5)
    assert store["entries"]["cpu|hbm"]["achievable_fraction"] == 1.0


def test_achievable_fraction_fallback_ladder():
    store = {"entries": {
        "tpu v4|compute": {"achievable_fraction": 0.42, "n": 9,
                           "source": "telemetry"},
        "tpu v4|hbm": {"achievable_fraction": 0.62, "n": 3,
                       "source": "ledger"}}}
    # exact entry
    hit = predict.achievable_fraction(store, "tpu v4", "compute")
    assert hit["fraction"] == 0.42 and hit["source"] == "telemetry"
    # same kind, other bucket: nearest-bucket mean
    near = predict.achievable_fraction(store, "tpu v4", "collective")
    assert near["fraction"] == pytest.approx((0.42 + 0.62) / 2)
    assert near["source"] == "nearest-bucket"
    # unknown kind: the documented default
    miss = predict.achievable_fraction(store, "gpu", "compute")
    assert miss["fraction"] == predict.DEFAULT_FRACTION
    assert miss["source"] == "default" and miss["n"] == 0


def test_fit_from_ledger_committed_and_synthetic(tmp_path):
    # the committed ledger must yield a usable compute fraction
    store = predict.fit_from_ledger(
        ledger_path=os.path.join(REPO, "PERF_LEDGER.jsonl"), kind="cpu")
    e = store["entries"]["cpu|compute"]
    assert 0.0 < e["achievable_fraction"] <= 1.0
    assert e["source"] == "ledger" and e["n"] >= 1
    # synthetic ledger: median of the *_mfu metrics, junk rows ignored
    path = tmp_path / "ledger.jsonl"
    rows = [{"metrics": {"train_mfu": 0.30}},
            {"metrics": {"train_mfu": 0.40}},
            {"metrics": {"decode_mfu": 0.50}},
            {"metrics": {"train_mfu": 0.0}},      # not a real sample
            {"metrics": {"tokens_per_sec": 9e9}},  # not an mfu
            {"not": "json-with-metrics"}]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    store2 = predict.fit_from_ledger(ledger_path=str(path), kind="x")
    e2 = store2["entries"]["x|compute"]
    assert e2["achievable_fraction"] == pytest.approx(0.40)
    assert e2["n"] == 3


# ---------------------------------------------------------------------------
# budgets + gating
# ---------------------------------------------------------------------------

def test_predict_budget_shape_and_table():
    rep = predict.predict_budget(_toy_compiled(), "toy",
                                 items_per_step=128)
    assert rep["kind"] == "predict_report"
    b = rep["budget"]
    assert b["step_time_s"] > 0 and b["peak_hbm_bytes"] > 0
    # step_time_s is rounded to ns in the report; the throughput was
    # computed from the exact value
    assert b["throughput_per_s"] == pytest.approx(
        128 / b["step_time_s"], rel=0.01)
    assert rep["basis"]["bound"] in ("compute", "hbm", "collective")
    assert 0 < rep["basis"]["achievable_fraction"] <= 1.0
    assert rep["over_budget"] == []
    table = predict.budget_table([rep])
    assert "toy" in table and "ok" in table
    # the budget was noted for later runtime conformance
    assert predict.noted_budget("toy")["budget"] == b


def test_budget_gating_from_env_limits(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_STEP_BUDGET_MS", "0.000001")
    monkeypatch.setenv("MXNET_TPU_THROUGHPUT_FLOOR", "1e18")
    rep = predict.predict_budget(_toy_compiled(), "gated",
                                 items_per_step=4)
    assert set(rep["over_budget"]) == {"step_time_s", "throughput_per_s"}
    assert "OVER BUDGET" in predict.budget_table([rep])
    # decode budgets gate through the same limits
    drep = predict.predict_decode_budget(2, 64, 256, 4, 128,
                                         name="decode-gated")
    assert "step_time_s" in drep["over_budget"]


def test_decode_budget_model():
    rep = predict.predict_decode_budget(2, 64, 256, 4, 128, quant_bits=8,
                                        name="decode8")
    wide = predict.predict_decode_budget(2, 64, 256, 4, 128,
                                         quant_bits=32, name="decode32")
    assert rep["budget"]["step_time_s"] > 0
    # quantized weights move fewer bytes -> cheaper hbm-bound step
    assert rep["basis"]["hbm_bytes"] < wide["basis"]["hbm_bytes"]
    assert rep["budget"]["throughput_per_s"] > 0


# ---------------------------------------------------------------------------
# conformance verdicts
# ---------------------------------------------------------------------------

def test_conformance_bands_floor_and_sigma():
    flat = predict.conformance_bands([])
    assert flat["basis"] == "floor"
    assert flat["degraded_tolerance"] == predict.CONFORMANCE_FLOOR
    assert flat["violated_tolerance"] == 2 * predict.CONFORMANCE_FLOOR
    # a genuinely noisy history widens the band past the floor
    noisy = predict.conformance_bands([1.0, 0.7, 1.1, 0.6, 1.2, 0.65])
    assert noisy["basis"] == "sigma"
    assert noisy["degraded_tolerance"] > predict.CONFORMANCE_FLOOR


def test_conformance_verdict_ladder():
    budget = {"program": "p", "budget": {"step_time_s": 1.0,
                                         "throughput_per_s": 100.0},
              "basis": {"calibration_source": "ledger"}}
    within = predict.conformance(budget, {"step_time_s": 1.1})
    assert within["verdict"] == "WITHIN"
    degraded = predict.conformance(budget, {"step_time_s": 1.3})
    assert degraded["metrics"]["step_time_s"]["verdict"] == "DEGRADED"
    violated = predict.conformance(budget, {"step_time_s": 2.0})
    assert violated["verdict"] == "VIOLATED"
    assert violated["metrics"]["step_time_s"]["ratio"] == 2.0
    assert violated["calibration_source"] == "ledger"
    # higher-is-better metrics invert: 2x the promised tokens is WITHIN
    toks = predict.conformance(budget, {"decode_tokens_per_s": 200.0})
    assert toks["verdict"] == "WITHIN"
    starved = predict.conformance(budget, {"decode_tokens_per_s": 40.0})
    assert starved["verdict"] == "VIOLATED"
    # nothing comparable -> None
    assert predict.conformance(budget, {"unknown_metric": 1.0}) is None


def test_digest_column_picks_worst():
    budget = {"program": "a", "budget": {"step_time_s": 1.0}}
    predict.note_budget("a", budget)
    predict.runtime_conformance(
        "a", {"step": {"measured_s": 1.05}})
    budget2 = {"program": "b", "budget": {"step_time_s": 1.0}}
    predict.note_budget("b", budget2)
    predict.runtime_conformance(
        "b", {"step": {"measured_s": 1.9}})
    col = predict.digest_column()
    assert col["program"] == "b" and col["verdict"] == "VIOLATED"
    assert col["metric"] == "step_time_s"
    assert col["ratio"] == pytest.approx(1.9)
    predict.reset()
    assert predict.digest_column() is None


# ---------------------------------------------------------------------------
# prediction agreement (the CI-gated ~20% acceptance for trainer + ring)
# ---------------------------------------------------------------------------

def _agreement(compiled, name, measured_s, tmp_path):
    """Calibrate from one attributed run, then predict with the fitted
    store: the budget must land within the conformance floor (20%) of
    what was measured."""
    data = perf.attribute_compiled(
        compiled, name, measured_step_s=measured_s).to_dict()
    store = predict.load_store(str(tmp_path / "agree.json"))
    assert predict.fit_from_attribution(store, data) is not None
    rep = predict.predict_budget(compiled, name, store=store)
    assert rep["basis"]["calibration_source"] == "telemetry"
    predicted = rep["budget"]["step_time_s"]
    assert predicted == pytest.approx(measured_s, rel=0.20), \
        "%s: predicted %.3g vs measured %.3g" % (name, predicted,
                                                 measured_s)


def test_trainer_prediction_agreement(tmp_path):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=256, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=64, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")
    tr = ShardedTrainer(net, MeshSpec(make_mesh((1,), ("dp",))), lr=0.1)
    shapes = {"data": (64, 256), "softmax_label": (64,)}
    params, mom, aux = tr.init_state(shapes)
    rs = np.random.RandomState(0)
    feed = {"data": rs.rand(64, 256).astype(np.float32),
            "softmax_label": rs.randint(0, 10, 64).astype(np.float32)}
    for _ in range(3):                                   # compile + warm
        params, mom, aux, _ = tr.step(params, mom, aux, feed)
    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        params, mom, aux, loss = tr.step(params, mom, aux, feed)
        jax.block_until_ready(loss)
        times.append(time.perf_counter() - t0)
    measured = sorted(times)[len(times) // 2]
    inputs = {k: jax.ShapeDtypeStruct(v, jnp.float32)
              for k, v in shapes.items()}
    jitted = tr._step or tr._build_step()
    compiled = jitted.lower(params, mom, aux, inputs, tr._keys(),
                            tr._guard_arrays()).compile()
    _agreement(compiled, "trainer", measured, tmp_path)


def test_ring_prediction_agreement(tmp_path):
    n = min(2, jax.device_count())
    mesh = make_mesh((n,), ("sp",))
    fn = local_ring_attention_fn("sp", causal=True, scale=1.0,
                                 num_devices=n)
    mapped = shard_map(fn, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                       out_specs=P(None, "sp"), **_COMPAT)
    jitted = jax.jit(mapped)
    blk = jnp.ones((1, 128 * n, 8, 32), jnp.float32)
    out = jitted(blk, blk, blk)                          # compile + warm
    jax.block_until_ready(out)
    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        jax.block_until_ready(jitted(blk, blk, blk))
        times.append(time.perf_counter() - t0)
    measured = sorted(times)[len(times) // 2]
    compiled = jitted.lower(blk, blk, blk).compile()
    _agreement(compiled, "ring", measured, tmp_path)


# ---------------------------------------------------------------------------
# input-bound detection
# ---------------------------------------------------------------------------

def test_input_verdict_unit():
    v = perf.input_verdict(step_s=0.001, io_s=0.009)
    assert v["bound_input"] is True
    assert v["input_share"] == pytest.approx(0.9)
    fast = perf.input_verdict(step_s=0.009, io_s=0.001)
    assert fast["bound_input"] is False
    # histogram-backed path honours the min-sample floor
    telemetry.arm()
    telemetry.observe("data.next_seconds", 0.05)
    assert perf.input_verdict(step_s=0.001) is None      # n=1 < floor
    telemetry.observe("data.next_seconds", 0.05)
    v2 = perf.input_verdict(step_s=0.001)
    assert v2["bound_input"] is True
    assert v2["io_s"] == pytest.approx(0.05, rel=0.01)


class _StarvedIter(DataIter):
    """Tiny in-memory iterator whose fetch is deliberately slower than
    the step it feeds — the SL108 footgun made real."""

    def __init__(self, x, y, batches, delay):
        super().__init__(batch_size=x.shape[0])
        self._x, self._y = x, y
        self._batches, self._delay = batches, delay
        self._i = 0

    def iter_next(self):
        self._i += 1
        return self._i <= self._batches

    def getdata(self):
        time.sleep(self._delay)                # the starved fetch
        return [self._x]

    def getlabel(self):
        return [self._y]

    def getpad(self):
        return 0

    def getindex(self):
        return None


def test_input_starved_run_reads_bound_input(tmp_path, monkeypatch):
    """A toy training loop over a synchronous, slow iterator must come
    out of attribution with the phases verdict ``bound: input`` — the
    runtime twin of srclint's SL108."""
    monkeypatch.setenv("MXNET_TPU_ATTRIBUTION", "1")
    monkeypatch.setenv("MXNET_TPU_ATTRIBUTION_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_TPU_ATTRIBUTION_AFTER", "2")
    perf.reset_attributed()
    telemetry.reset()
    telemetry.arm()
    try:
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        net = mx.sym.SoftmaxOutput(fc, name="softmax")
        tr = ShardedTrainer(net, MeshSpec(make_mesh((1,), ("dp",))),
                            lr=0.1)
        shapes = {"data": (4, 8), "softmax_label": (4,)}
        params, mom, aux = tr.init_state(shapes)
        rs = np.random.RandomState(0)
        x = rs.rand(4, 8).astype(np.float32)
        y = rs.randint(0, 10, 4).astype(np.float32)
        it = _StarvedIter(x, y, batches=4, delay=0.05)
        for batch in it:  # tpulint: disable=SL108  (the point of the test)
            feed = {"data": np.asarray(batch.data[0]),
                    "softmax_label": np.asarray(batch.label[0])}
            params, mom, aux, loss = tr.step(params, mom, aux, feed)
        assert np.isfinite(float(loss))
    finally:
        telemetry.disarm()
    reports = [f for f in os.listdir(str(tmp_path))
               if f.startswith("attribution-")]
    assert len(reports) == 1
    d = json.load(open(os.path.join(str(tmp_path), reports[0])))
    assert d["roofline"]["bound"] == "input"
    assert d["roofline"]["input_share"] > 0.5
    assert d["step"]["io_s"] == pytest.approx(0.05, rel=0.5)
    # the bench/servebench mirror: phases_block carries the verdict too
    rep = perf.AttributionReport.load(
        os.path.join(str(tmp_path), reports[0]))
    block = perf.phases_block(rep, "r.json")
    assert block["bound"] == "input"
    assert block["input_share"] > 0.5
    assert "INPUT-BOUND" in rep.pretty()


# ---------------------------------------------------------------------------
# runtime conformance inside attribution + the fleet drill
# ---------------------------------------------------------------------------

def test_attribution_report_carries_conformance(tmp_path, monkeypatch):
    """With a noted pre-flight budget, the attribution report judges the
    measured step against it and exports the per-metric gauge."""
    c = _toy_compiled(64)
    budget = predict.predict_budget(c, "matmul64")
    slow = budget["budget"]["step_time_s"] / 0.4          # 2.5x budget
    telemetry.arm()
    rep = perf.attribute_compiled(c, "matmul64", measured_step_s=slow)
    d = rep.to_dict()
    conf = d["conformance"]
    assert conf["verdict"] == "VIOLATED"
    assert conf["metrics"]["step_time_s"]["ratio"] == pytest.approx(
        2.5, rel=0.01)
    assert conf["budget_program"] == "matmul64"
    g = telemetry.gauge("perf.conformance")
    assert g.value(entry="matmul64", metric="step_time_s") \
        == pytest.approx(2.5, rel=0.01)
    assert "conformance vs budget" in rep.pretty()
    counters = rep.perfetto_counters(ts_us=1.0)
    assert any(ev["name"].endswith("/conformance") for ev in counters)
    # ... and the refit fed the measured sample back into the store
    store = predict.load_store()
    assert store["entries"], "refit should have written the store"


def test_fleet_drill_flags_rank_over_budget(monkeypatch):
    """4-rank digest drill: rank 2 runs 1.8x over its own budget while
    every p50 looks alike — only the conformance column fingers it."""
    from tests.test_watchdog import FakeKVClient
    telemetry.arm()
    client = FakeKVClient()
    lane = watchdog.HeartbeatLane(client=client)
    monkeypatch.setattr(watchdog, "_LANE", lane)
    now = time.time()
    for rank in range(4):
        conf = {"ratio": 1.02, "verdict": "WITHIN",
                "metric": "step_time_s", "program": "trainer"}
        if rank == 2:
            conf = {"ratio": 1.8, "verdict": "VIOLATED",
                    "metric": "step_time_s", "program": "trainer"}
        client.kv["mxt_hb/%d" % rank] = "9:%.6f" % now
        client.kv["mxt_md/%d" % rank] = json.dumps(
            {"t": now, "step": 9, "conf": conf,
             "step_ms": {"p50": 12.0, "p95": 14.0, "mean": 12.1, "n": 6}})
    rep = lane.straggler_report()
    st = rep["step_time"]
    assert st["budget_violators"] == ["2"]
    assert st["conformance"]["2"]["verdict"] == "VIOLATED"
    assert st["skew"] == pytest.approx(1.0, rel=0.01)     # p50s agree
    rendered = telemetry.render_fleet(telemetry.fleet_view())
    assert "VIOL x1.80" in rendered
    assert "over budget: rank 2 step_time_s x1.80" in rendered
    assert "WITH x1.02" in rendered


def test_straggler_skew_excludes_low_sample_ranks(monkeypatch):
    """A warming-up rank with 1 slow sample must not skew p50 blame."""
    from tests.test_watchdog import FakeKVClient
    telemetry.arm()
    client = FakeKVClient()
    lane = watchdog.HeartbeatLane(client=client)
    monkeypatch.setattr(watchdog, "_LANE", lane)
    now = time.time()
    for rank, (p50, n) in enumerate([(12.0, 8), (13.0, 8), (480.0, 1)]):
        client.kv["mxt_hb/%d" % rank] = "9:%.6f" % now
        client.kv["mxt_md/%d" % rank] = json.dumps(
            {"t": now, "step": 9,
             "step_ms": {"p50": p50, "p95": p50, "mean": p50, "n": n}})
    st = lane.straggler_report()["step_time"]
    assert st["low_sample_ranks"] == [2]
    assert st["min_samples"] == 3
    assert st["slowest_rank"] == 1                 # rank 2 sat out
    assert st["skew"] < 2
    rendered = telemetry.render_fleet(telemetry.fleet_view())
    assert "skew excludes rank(s) 2" in rendered
    # the floor is tunable
    monkeypatch.setenv("MXNET_TPU_SKEW_MIN_SAMPLES", "1")
    st2 = lane.straggler_report()["step_time"]
    assert "low_sample_ranks" not in st2
    assert st2["slowest_rank"] == 2
