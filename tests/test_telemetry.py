"""Unified telemetry layer (mxnet_tpu/telemetry): registry semantics,
span nesting, the merged Chrome trace, heartbeat digests, post-mortem
metrics windows, chaos/retry counters, and the disarmed zero-cost path.

The multi-process fleet-view drill (every rank's digest visible to rank
0, slow rank fingered by step-time skew) rides the existing 4-proc dist
test (tests/dist/dist_sync_kvstore.py); these are the single-process
seams plus the ISSUE-5 end-to-end merged-trace acceptance test.
"""
import json
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler, telemetry
from mxnet_tpu.resilience import chaos, watchdog


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.disarm()
    chaos.reset()
    watchdog.reset()
    yield
    profiler.set_state("stop")
    telemetry.reset()
    chaos.reset()
    watchdog.reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_with_labels():
    telemetry.arm()
    telemetry.count("t.requests", outcome="ok")
    telemetry.count("t.requests", 2, outcome="ok")
    telemetry.count("t.requests", outcome="err")
    telemetry.set_gauge("t.depth", 7)
    for v in (0.001, 0.004, 0.02, 0.02, 1.5):
        telemetry.observe("t.lat", v)

    c = telemetry.counter("t.requests")
    assert c.value(outcome="ok") == 3
    assert c.value(outcome="err") == 1
    assert c.total() == 4
    assert telemetry.gauge("t.depth").value() == 7

    h = telemetry.histogram("t.lat")
    s = h.summary()
    assert s["count"] == 5
    assert s["min"] == 0.001 and s["max"] == 1.5
    assert abs(s["sum"] - 1.545) < 1e-9
    # exact percentiles from the reservoir, servebench's old formula
    xs = sorted((0.001, 0.004, 0.02, 0.02, 1.5))
    assert h.percentiles((0.5,))[0.5] == xs[int(0.5 * 4)]


def test_snapshot_delta_roundtrip():
    telemetry.arm()
    telemetry.count("t.steps")
    telemetry.observe("t.lat", 0.01)
    before = telemetry.snapshot()
    telemetry.count("t.steps", 4)
    telemetry.observe("t.lat", 0.02)
    d = telemetry.delta(telemetry.snapshot(), before)
    steps = d["metrics"]["t.steps"]["series"][0]
    assert steps["value"] == 4
    lat = d["metrics"]["t.lat"]["series"][0]
    assert lat["count"] == 1
    # snapshots are JSON-serializable end to end (the JSONL feed)
    json.loads(json.dumps(before))


def test_prometheus_text_format():
    telemetry.arm()
    telemetry.count("train.steps", 3)
    telemetry.observe("serve.lat", 0.003)
    text = telemetry.prometheus_text()
    assert "# TYPE train_steps counter" in text
    assert "train_steps 3" in text
    assert "# TYPE serve_lat histogram" in text
    assert 'serve_lat_bucket{le="+Inf"} 1' in text
    assert "serve_lat_count 1" in text


def test_export_jsonl_and_metricsdump_render(tmp_path):
    telemetry.arm()
    path = str(tmp_path / "m.jsonl")
    telemetry.count("t.steps")
    telemetry.export_jsonl(path)
    telemetry.count("t.steps", 5)
    telemetry.export_jsonl(path)
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "metricsdump", os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "metricsdump.py"))
    md = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(md)
    with open(path) as f:
        snaps = md._parse_lines(f.readlines())
    assert len(snaps) == 2
    text = md.render(snaps[1], snaps[0])
    assert "t.steps" in text and "/s)" in text    # rate rendered


def test_disarmed_is_zero_cost_and_records_nothing():
    assert not telemetry.is_armed()
    telemetry.count("t.nope")
    telemetry.observe("t.nope_h", 1.0)
    with telemetry.span("t/span", metric="t.nope_h"):
        pass
    telemetry.arm()
    assert telemetry.counter_total("t.nope") == 0
    assert telemetry.histogram("t.nope_h").summary()["count"] == 0
    telemetry.disarm()
    # per-call cost of the disarmed gate: generous bound, catches only
    # a lost fast path (a lock or a clock read would blow way past it)
    n = 3000
    t0 = time.perf_counter()
    for i in range(n):
        with telemetry.span("t/hot", step=i):
            pass
        telemetry.count("t.hot")
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 50e-6, "disarmed telemetry cost %.1fus" % (
        per_call * 1e6)


# ---------------------------------------------------------------------------
# spans + merged trace
# ---------------------------------------------------------------------------

def _check_nesting(events, eps_us=0.5):
    """Every pair of X events on one (pid, tid) lane must be disjoint or
    properly nested.  Counter-track events (ph "C": the attribution and
    live-HBM counters) carry no duration and are skipped."""
    lanes = {}
    for e in events:
        if e["ph"] == "C":
            continue
        assert e["ph"] == "X" and e["dur"] >= 0, e
        lanes.setdefault((e.get("pid", 0), e["tid"]), []).append(e)
    for lane_events in lanes.values():
        lane_events.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for e in lane_events:
            end = e["ts"] + e["dur"]
            while stack and e["ts"] >= stack[-1] - eps_us:
                stack.pop()
            if stack:
                assert end <= stack[-1] + eps_us, \
                    ("overlap, not nesting", e)
            stack.append(end)


def test_span_nesting_across_threads(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t.json"))
    profiler.set_state("run")

    def work(tag):
        with telemetry.span("outer/%s" % tag, cat="test"):
            with telemetry.span("inner/%s" % tag, cat="test"):
                time.sleep(0.005)

    threads = [threading.Thread(target=work, args=("t%d" % i,))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    work("main")
    profiler.set_state("stop")
    events = json.load(open(profiler.dump_profile()))["traceEvents"]
    names = {e["name"] for e in events}
    assert {"outer/t0", "inner/t0", "outer/t1", "inner/t1",
            "outer/main", "inner/main"} <= names
    # the two worker threads and main each get their own lane
    assert len({e["tid"] for e in events}) == 3
    _check_nesting(events)
    for tag in ("t0", "t1", "main"):
        outer = next(e for e in events if e["name"] == "outer/%s" % tag)
        inner = next(e for e in events if e["name"] == "inner/%s" % tag)
        assert outer["tid"] == inner["tid"]
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 0.5


def test_open_spans_visible_cross_thread():
    telemetry.arm()
    entered = threading.Event()
    release = threading.Event()

    def work():
        with telemetry.span("t/holding", cat="test", step=3):
            entered.set()
            release.wait(5)

    t = threading.Thread(target=work, name="holder")
    t.start()
    try:
        assert entered.wait(5)
        spans = telemetry.open_spans()
        holder = [v for k, v in spans.items() if k.startswith("holder")]
        assert holder and holder[0][0]["name"] == "t/holding"
        assert holder[0][0]["attrs"]["step"] == "3"
    finally:
        release.set()
        t.join()
    assert not any(k.startswith("holder")
                   for k in telemetry.open_spans())


def test_dump_keeps_events_across_dumps(tmp_path):
    """Per-thread buffers: a dump must not drop or drain events — events
    recorded after one dump appear alongside the old ones in the next
    (the old global-lock store lost in-flight events on restart)."""
    profiler.set_config(filename=str(tmp_path / "d.json"))
    profiler.set_state("run")
    profiler.record_event("first", 1.0, 2.0)
    p1 = profiler.dump_profile()
    assert len(json.load(open(p1))["traceEvents"]) == 1
    profiler.record_event("second", 5.0, 2.0)
    profiler.set_state("stop")
    events = json.load(open(profiler.dump_profile()))["traceEvents"]
    assert {e["name"] for e in events} == {"first", "second"}


# ---------------------------------------------------------------------------
# ISSUE 5 acceptance: ONE merged trace from training + serving
# ---------------------------------------------------------------------------

class _SyntheticServed:
    """Program-like stand-in (servebench's trick): fixed batch shape,
    no device — the serving RUNTIME's spans are what this test needs."""

    def __init__(self, batch=4, features=8):
        self.input_names = ["data"]
        self.input_shapes = {"data": (batch, features)}
        self.input_dtypes = {"data": np.dtype(np.float32)}

    def forward(self, data):
        time.sleep(0.001)
        return [np.tanh(data)]


def test_merged_trace_end_to_end(tmp_path):
    """Short sharded-training run + served-inference burst -> ONE Chrome
    trace with nested spans from >= 4 subsystems (trainer, collective,
    data iter, serving), every event JSON-valid and properly nested."""
    import jax
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu.parallel.ring import ring_attention, reference_attention
    from mxnet_tpu.serving import ServingRuntime

    profiler.set_config(filename=str(tmp_path / "merged.json"))
    telemetry.arm()
    profiler.set_state("run")
    try:
        # -- sharded training fed from a real data iterator ------------
        n = 2
        mesh = make_mesh((n,), ("dp",))
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        net = mx.sym.SoftmaxOutput(fc, name="softmax")
        trainer = ShardedTrainer(net, MeshSpec(mesh))
        shapes = {"data": (8, 4), "softmax_label": (8,)}
        params, mom, aux = trainer.init_state(shapes)
        rs = np.random.RandomState(0)
        X = rs.rand(24, 4).astype(np.float32)
        y = rs.randint(0, 2, 24).astype(np.float32)
        it = mx.io.NDArrayIter(X, y, batch_size=8)
        for batch in it:
            feed = {"data": batch.data[0].asnumpy(),
                    "softmax_label": batch.label[0].asnumpy()}
            params, mom, aux, loss = trainer.step(params, mom, aux, feed)

        # -- an explicit collective entry point ------------------------
        sp_mesh = make_mesh((n,), ("sp",))
        q = rs.rand(1, 4, 2, 4).astype(np.float32)
        out = ring_attention(q, q, q, sp_mesh, axis="sp")
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(reference_attention(q, q, q)),
                                   rtol=2e-4, atol=2e-5)

        # -- served-inference burst ------------------------------------
        with ServingRuntime(_SyntheticServed(), name="e2e") as rt:
            for _ in range(5):
                rt.predict({"data": np.zeros(8, np.float32)},
                           deadline=5.0)
            stats = rt.stats()
    finally:
        profiler.set_state("stop")
        telemetry.disarm()

    path = profiler.dump_profile()
    with open(path) as f:
        events = json.load(f)["traceEvents"]       # every event parses
    assert events
    names = {e["name"] for e in events}
    cats = {e["cat"] for e in events}

    # >= 4 subsystems present, nested spans each
    assert "train/step" in names and "train/host_enqueue" in names
    assert "data/next" in names
    assert "collective/ring_attention" in names
    assert "collective/psum" in names              # trainer grad psum marker
    assert {"serve/request", "serve/queue_wait", "serve/exec"} <= names
    assert {"train", "io", "collective", "serve"} <= cats

    _check_nesting(events)

    # nested: host_enqueue inside its train/step
    step1 = next(e for e in events if e["name"] == "train/step")
    enq = next(e for e in events if e["name"] == "train/host_enqueue"
               and e["ts"] >= step1["ts"] - 0.5)
    assert enq["ts"] + enq["dur"] <= step1["ts"] + step1["dur"] + 0.5
    # the collective marker carries kind + operand bytes
    psum = next(e for e in events if e["name"] == "collective/psum")
    assert psum["args"]["kind"] == "psum" and psum["args"]["bytes"] > 0

    # and the same run fed the metrics side: step histogram + serving
    # percentiles out of the telemetry histogram
    assert telemetry.histogram("train.step_seconds").summary()["count"] == 3
    assert telemetry.counter_total("train.steps") == 3
    assert stats["latency_s"]["p50"] > 0
    assert stats["counters"]["completed"] == 5


# ---------------------------------------------------------------------------
# cross-rank digests (single-process seams; dist drill in test_dist)
# ---------------------------------------------------------------------------

def _fake_kv_client():
    from tests.test_watchdog import FakeKVClient
    return FakeKVClient()


def test_heartbeat_digest_roundtrip(monkeypatch):
    telemetry.arm()
    client = _fake_kv_client()
    lane = watchdog.HeartbeatLane(client=client)
    monkeypatch.setattr(watchdog, "_LANE", lane)
    for _ in range(4):
        telemetry.observe("train.step_seconds", 0.012)
    telemetry.count("train.steps", 4)
    assert lane.beat(7, force=True)
    # digest piggybacked on the SAME lane: one overwritten key per rank
    md_keys = [k for k in client.kv if k.startswith(lane.MD_PREFIX)]
    assert md_keys == ["%s/0" % lane.MD_PREFIX]
    d = lane.digests()[0]
    assert d["step"] == 7
    assert d["step_ms"]["n"] == 4
    assert abs(d["step_ms"]["p50"] - 12.0) < 1.0
    assert d["counters"]["steps_done"] == 4

    # a slow peer: higher p50 -> step-time straggler despite fresh beats
    now = time.time()
    client.kv["mxt_hb/1"] = "7:%.6f" % now
    client.kv["mxt_md/1"] = json.dumps(
        {"t": now, "step": 7, "step_ms": {"p50": 240.0, "p95": 260.0,
                                          "mean": 241.0, "n": 4}})
    rep = lane.straggler_report()
    assert rep["lag_steps"] == 0                    # invisible to lag...
    st = rep["step_time"]
    assert st["slowest_rank"] == 1                  # ...visible to skew
    assert st["fastest_rank"] == 0
    assert st["skew"] > 5

    view = telemetry.fleet_view()
    assert set(view["ranks"]) == {"0", "1"}
    assert view["ranks"]["1"]["digest"]["step_ms"]["p50"] == 240.0
    rendered = telemetry.render_fleet(view)
    assert "step-time straggler: rank 1" in rendered


def test_digest_not_published_when_disarmed():
    client = _fake_kv_client()
    lane = watchdog.HeartbeatLane(client=client)
    assert lane.beat(3, force=True)
    assert not [k for k in client.kv if k.startswith(lane.MD_PREFIX)]
    assert lane.digests() == {}


# ---------------------------------------------------------------------------
# post-mortems show what the process was DOING
# ---------------------------------------------------------------------------

def test_postmortem_embeds_metrics_window_and_open_spans(tmp_path):
    telemetry.arm()
    telemetry.count("train.steps", 5)
    telemetry.observe("train.step_seconds", 0.03)
    telemetry.window_tick()
    fired = []
    watchdog.configure(step_timeout=0.25, action="wait",
                       report_dir=str(tmp_path), poll=0.05,
                       on_expire=fired.append)
    with telemetry.span("train/step", cat="train", step=9):
        with watchdog.watch("unit.step", step=9):
            time.sleep(0.6)
    assert fired and fired[0]
    rep = json.load(open(fired[0]))
    win = rep["metrics_window"]
    assert win["armed"] is True
    assert win["snapshots"] >= 1
    assert "train.steps" in win["last"]["metrics"]
    assert "delta" in win
    names = [s["name"] for spans in rep["open_spans"].values()
             for s in spans]
    assert "train/step" in names


# ---------------------------------------------------------------------------
# chaos + retry counters
# ---------------------------------------------------------------------------

def test_chaos_faults_are_counted():
    telemetry.arm()
    with chaos.inject("io_error", count=2):
        for _ in range(2):
            with pytest.raises(OSError):
                chaos.maybe_io_error("unit")
    with chaos.inject("exec_error", count=1):
        with pytest.raises(RuntimeError):
            chaos.maybe_exec_error(1)
    c = telemetry.counter("chaos.faults_injected")
    assert c.value(kind="io_error") == 2
    assert c.value(kind="exec_error") == 1
    assert c.total() == 3


def test_retry_absorption_is_counted():
    from mxnet_tpu.resilience.retry import call_with_retry
    telemetry.arm()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise OSError("transient")
        return "ok"

    assert call_with_retry(flaky, backoff=0.001, desc="unit.flaky") == "ok"
    assert telemetry.counter("retry.absorbed").value(desc="unit.flaky") == 1
    # N injected == N absorbed, assertable without grepping logs
    with chaos.inject("io_error", count=1):
        def chaotic():
            chaos.maybe_io_error("unit2")
            return "ok"
        assert call_with_retry(chaotic, backoff=0.001,
                               desc="unit.chaotic") == "ok"
    assert telemetry.counter("chaos.faults_injected").value(
        kind="io_error") == 1
    assert telemetry.counter("retry.absorbed").value(
        desc="unit.chaotic") == 1


# ---------------------------------------------------------------------------
# single-source-of-truth percentiles (serving + checkpoints)
# ---------------------------------------------------------------------------

def test_serving_stats_read_from_telemetry_histogram():
    from mxnet_tpu.serving import ServingRuntime
    with ServingRuntime(_SyntheticServed(), name="hist") as rt:
        for _ in range(6):
            rt.predict({"data": np.zeros(8, np.float32)}, deadline=5.0)
        stats = rt.stats()
        # stats percentiles == the histogram's percentiles, to the digit
        ps = rt._lat_hist.percentiles((0.50, 0.95, 0.99))
        assert stats["latency_s"]["p50"] == round(ps[0.50], 6)
        assert stats["latency_s"]["p99"] == round(ps[0.99], 6)
        assert stats["latency_s"]["max"] == rt._lat_hist.summary()["max"]
        assert rt._lat_hist.summary()["count"] == 6
        assert stats["queue_wait_s"]["max"] >= 0
    # works with telemetry disarmed (always=True instruments)
    assert not telemetry.is_armed()


def test_checkpoint_save_restore_counted_and_spanned(tmp_path):
    from mxnet_tpu.resilience.checkpoint import CheckpointManager
    telemetry.arm()
    mgr = CheckpointManager(str(tmp_path), keep=2)
    mgr.save(1, {"w": np.ones((3,), np.float32)}, meta={"kind": "unit"})
    ck = mgr.latest()
    assert ck is not None and ck.meta["kind"] == "unit"
    assert telemetry.counter_total("checkpoint.saves") == 1
    assert telemetry.counter_total("checkpoint.restores") == 1
    assert telemetry.histogram(
        "checkpoint.save_seconds").summary()["count"] == 1
