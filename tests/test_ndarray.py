"""NDArray tests (modeled on reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4) and a.dtype == np.float32
    assert (a.asnumpy() == 0).all()
    b = nd.ones((2,), dtype="int32")
    assert b.dtype == np.int32
    c = nd.full((2, 2), 7.5)
    assert (c.asnumpy() == 7.5).all()
    d = nd.array([[1, 2], [3, 4]])
    assert d.dtype == np.float32  # python list defaults to f32
    e = nd.array(np.arange(4, dtype=np.float64))
    assert e.dtype == np.float64
    f = nd.arange(0, 10, 2)
    assert (f.asnumpy() == np.arange(0, 10, 2)).all()
    g = nd.eye(3)
    assert (g.asnumpy() == np.eye(3)).all()


def test_arithmetic():
    a = nd.array([[1.0, 2], [3, 4]])
    b = nd.array([[5.0, 6], [7, 8]])
    assert_almost_equal((a + b).asnumpy(), np.array([[6, 8], [10, 12.]]))
    assert_almost_equal((a - b).asnumpy(), np.array([[-4.0] * 2] * 2))
    assert_almost_equal((a * 2 + 1).asnumpy(), np.array([[3, 5], [7, 9.]]))
    assert_almost_equal((1 / a).asnumpy(), 1 / a.asnumpy(), rtol=1e-6)
    assert_almost_equal((b % a).asnumpy(), np.array([[0, 0], [1, 0.]]))
    assert_almost_equal((a ** 2).asnumpy(), a.asnumpy() ** 2)
    assert_almost_equal((-a).asnumpy(), -a.asnumpy())
    assert_almost_equal((2 - a).asnumpy(), 2 - a.asnumpy())
    assert_almost_equal((2 ** a).asnumpy(), 2 ** a.asnumpy())


def test_broadcast_arith():
    a = nd.ones((3, 4))
    b = nd.arange(0, 4).reshape((1, 4))
    out = a + b
    assert out.shape == (3, 4)
    assert_almost_equal(out.asnumpy(), 1 + np.arange(4)[None, :] * np.ones((3, 4)))


def test_comparison():
    a = nd.array([1.0, 2, 3])
    b = nd.array([3.0, 2, 1])
    assert ((a == b).asnumpy() == [0, 1, 0]).all()
    assert ((a > b).asnumpy() == [0, 0, 1]).all()
    assert ((a >= 2).asnumpy() == [0, 1, 1]).all()


def test_inplace():
    a = nd.ones((2, 2))
    aid = id(a)
    a += 1
    assert id(a) == aid
    assert (a.asnumpy() == 2).all()
    a *= 3
    assert (a.asnumpy() == 6).all()


def test_indexing():
    a = nd.array(np.arange(12).reshape(3, 4))
    assert (a[1].asnumpy() == [4, 5, 6, 7]).all()
    assert (a[1:3].asnumpy() == np.arange(12).reshape(3, 4)[1:3]).all()
    assert a[1, 2].asscalar() == 6
    a[0] = 9
    assert (a.asnumpy()[0] == 9).all()
    a[1:3] = 0
    assert (a.asnumpy()[1:] == 0).all()
    idx = nd.array([0, 2], dtype="int32")
    assert (a[idx].asnumpy() == a.asnumpy()[[0, 2]]).all()


def test_shape_ops():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1, 4)).shape == (6, 4)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert nd.Reshape(a, shape=(-3, 4)).shape == (6, 4)
    assert nd.Reshape(a, shape=(-4, 1, 2, -2)).shape == (1, 2, 3, 4)
    assert a.transpose().shape == (4, 3, 2)
    assert a.transpose((1, 0, 2)).shape == (3, 2, 4)
    assert a.flatten().shape == (2, 12)
    assert a.expand_dims(0).shape == (1, 2, 3, 4)
    assert a.swapaxes(0, 2).shape == (4, 3, 2)
    assert nd.tile(a, reps=(2, 1, 1)).shape == (4, 3, 4)
    assert nd.repeat(a, repeats=2, axis=1).shape == (2, 6, 4)
    assert nd.squeeze(a.expand_dims(0), axis=0).shape == (2, 3, 4)


def test_reduce():
    x = np.random.rand(3, 4, 5).astype(np.float32)
    a = nd.array(x)
    assert_almost_equal(a.sum().asnumpy(), x.sum().reshape(1), rtol=1e-4)
    assert_almost_equal(nd.sum(a, axis=1).asnumpy(), x.sum(1), rtol=1e-4)
    assert_almost_equal(nd.sum(a, axis=(0, 2)).asnumpy(), x.sum((0, 2)), rtol=1e-4)
    assert_almost_equal(nd.sum(a, axis=1, keepdims=True).asnumpy(),
                        x.sum(1, keepdims=True), rtol=1e-4)
    assert_almost_equal(nd.sum(a, axis=1, exclude=True).asnumpy(),
                        x.sum((0, 2)), rtol=1e-4)
    assert_almost_equal(nd.mean(a, axis=0).asnumpy(), x.mean(0), rtol=1e-4)
    assert_almost_equal(nd.max(a, axis=2).asnumpy(), x.max(2))
    assert_almost_equal(nd.min(a, axis=0).asnumpy(), x.min(0))
    assert_almost_equal(nd.prod(a, axis=2).asnumpy(), x.prod(2), rtol=1e-4)


def test_dot():
    a = np.random.rand(4, 5).astype(np.float32)
    b = np.random.rand(5, 6).astype(np.float32)
    assert_almost_equal(nd.dot(nd.array(a), nd.array(b)).asnumpy(),
                        a.dot(b), rtol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(a), nd.array(b.T), transpose_b=True).asnumpy(),
        a.dot(b), rtol=1e-4)
    assert_almost_equal(
        nd.dot(nd.array(a.T), nd.array(b), transpose_a=True).asnumpy(),
        a.dot(b), rtol=1e-4)
    # batch_dot
    x = np.random.rand(3, 4, 5).astype(np.float32)
    y = np.random.rand(3, 5, 2).astype(np.float32)
    assert_almost_equal(nd.batch_dot(nd.array(x), nd.array(y)).asnumpy(),
                        np.matmul(x, y), rtol=1e-4)


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    c2 = nd.Concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    parts = nd.split(nd.array(np.arange(12).reshape(4, 3)), num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)
    s = nd.stack(a, b, axis=0, num_args=2)
    assert s.shape == (2, 2, 3)


def test_take_onehot():
    w = nd.array(np.arange(20).reshape(10, 2))
    idx = nd.array([1, 3, 5], dtype="int32")
    out = nd.take(w, idx)
    assert (out.asnumpy() == w.asnumpy()[[1, 3, 5]]).all()
    oh = nd.one_hot(idx, depth=10)
    assert oh.shape == (3, 10)
    assert oh.asnumpy()[0, 1] == 1
    emb = nd.Embedding(idx, w, input_dim=10, output_dim=2)
    assert (emb.asnumpy() == w.asnumpy()[[1, 3, 5]]).all()


def test_ordering():
    x = np.random.rand(5, 10).astype(np.float32)
    a = nd.array(x)
    topv, topi = nd.topk(a, k=3, ret_typ="both")
    ref = np.sort(x, axis=1)[:, ::-1][:, :3]
    assert_almost_equal(topv.asnumpy(), ref, rtol=1e-5)
    assert_almost_equal(nd.sort(a, axis=1).asnumpy(), np.sort(x, 1), rtol=1e-6)
    assert (nd.argmax(a, axis=1).asnumpy() == x.argmax(1)).all()
    assert (nd.argmin(a, axis=1).asnumpy() == x.argmin(1)).all()


def test_save_load(tmp_path):
    fname = str(tmp_path / "t.params")
    a = nd.array(np.random.rand(3, 3))
    b = nd.array(np.random.rand(2,))
    nd.save(fname, {"a": a, "b": b})
    loaded = nd.load(fname)
    assert set(loaded) == {"a", "b"}
    assert_almost_equal(loaded["a"].asnumpy(), a.asnumpy())
    nd.save(fname, [a, b])
    lst = nd.load(fname)
    assert len(lst) == 2
    assert_almost_equal(lst[1].asnumpy(), b.asnumpy())


def test_astype_copy_context():
    a = nd.ones((2, 2))
    b = a.astype("float64")
    assert b.dtype == np.float64
    c = a.copy()
    c[0] = 5
    assert (a.asnumpy() == 1).all()
    d = a.as_in_context(mx.cpu())
    assert d.context.device_type == "cpu"


def test_clip_where_maximum():
    x = np.array([-2, -1, 0, 1, 2], dtype=np.float32)
    a = nd.array(x)
    assert_almost_equal(nd.clip(a, a_min=-1, a_max=1).asnumpy(),
                        np.clip(x, -1, 1))
    assert_almost_equal(nd.maximum(a, 0).asnumpy(), np.maximum(x, 0))
    assert_almost_equal(nd.minimum(a, 0).asnumpy(), np.minimum(x, 0))
    cond = nd.array([1, 0, 1, 0, 1], dtype="float32")
    y = nd.array(-x)
    assert_almost_equal(nd.where(cond, a, y).asnumpy(),
                        np.where(cond.asnumpy() != 0, x, -x))


def test_save_load_reference_binary(tmp_path):
    """nd.save writes the reference binary container (ndarray.cc:890-1129):
    verify exact header bytes and full round-trip for list/dict/sparse."""
    import struct
    f = str(tmp_path / "x.params")
    a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    mx.nd.save(f, [a])
    buf = open(f, "rb").read()
    # uint64 list magic 0x112, uint64 reserved, uint64 count=1,
    # uint32 V2 magic, int32 stype=0, uint32 ndim=2, int64 dims 2,3,
    # int32 dev_type=1 (cpu), int32 dev_id=0, int32 type_flag=0 (f32)
    expect = struct.pack("<QQQIiIqqiii", 0x112, 0, 1, 0xF993FAC9, 0,
                         2, 2, 3, 1, 0, 0)
    assert buf[:len(expect)] == expect
    assert buf[len(expect):len(expect) + 24] == a.asnumpy().tobytes()
    (back,) = mx.nd.load(f)
    np.testing.assert_array_equal(back.asnumpy(), a.asnumpy())

    # dict round-trip, several dtypes
    d = {"w": mx.nd.array(np.random.rand(3, 4).astype(np.float64)),
         "b": mx.nd.array(np.arange(5, dtype=np.int32)),
         "h": mx.nd.array(np.random.rand(2, 2).astype(np.float16))}
    mx.nd.save(f, d)
    back = mx.nd.load(f)
    assert set(back) == set(d)
    for k in d:
        np.testing.assert_array_equal(back[k].asnumpy(), d[k].asnumpy())
        assert back[k].dtype == d[k].dtype

    # sparse round-trip
    import mxnet_tpu.ndarray.sparse as sp
    rs = sp.row_sparse_array((np.ones((2, 4), np.float32), [1, 5]),
                             shape=(8, 4))
    csr = sp.csr_matrix(np.array([[0, 1.0], [2.0, 0]], np.float32))
    mx.nd.save(f, {"rs": rs, "csr": csr})
    back = mx.nd.load(f)
    assert back["rs"].stype == "row_sparse"
    np.testing.assert_array_equal(back["rs"].asnumpy(), rs.asnumpy())
    np.testing.assert_array_equal(np.asarray(back["rs"]._indices), [1, 5])
    assert back["csr"].stype == "csr"
    np.testing.assert_array_equal(back["csr"].asnumpy(), csr.asnumpy())
