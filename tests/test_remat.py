"""Activation-memory mirroring (jax.remat) tests.

Reference analog: MXNET_BACKWARD_DO_MIRROR (src/executor/graph_executor.cc
:253-311, docs/faq/env_var.md:89-94) — recompute cheap forward activations
during backward instead of keeping them.  Here the policy is jax.checkpoint
around the fused forward+backward XLA computation.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.executor import backward_mirror_policy, set_backward_mirror
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=16, name="fc2")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=10, name="fc3")
    return sym.SoftmaxOutput(net, name="softmax")


def _run_grads(policy):
    set_backward_mirror(policy)
    try:
        rng = np.random.RandomState(0)
        net = _mlp()
        ex = net.simple_bind(mx.cpu(), data=(8, 64))
        for name, arr in zip(net.list_arguments(), ex.arg_arrays):
            if name == "data":
                arr[:] = nd.array(rng.uniform(-1, 1, arr.shape))
            elif name == "softmax_label":
                arr[:] = nd.array(rng.randint(0, 10, arr.shape))
            else:
                arr[:] = nd.array(rng.normal(0, 0.1, arr.shape))
        ex.forward(is_train=True)
        ex.backward()
        return {n: g.asnumpy() for n, g in zip(net.list_arguments(),
                                               ex.grad_arrays)
                if g is not None}
    finally:
        set_backward_mirror(None)


def test_mirror_policies_match_baseline():
    base = _run_grads("none")
    for policy in ("dots", "dots_no_batch", "full"):
        got = _run_grads(policy)
        assert set(got) == set(base)
        for n in base:
            assert_almost_equal(got[n], base[n], rtol=1e-5, atol=1e-6,
                                names=("%s[%s]" % (n, policy), n))


def test_env_resolution(monkeypatch):
    set_backward_mirror(None)
    monkeypatch.delenv("MXNET_TPU_REMAT_POLICY", raising=False)
    monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR", raising=False)
    assert backward_mirror_policy() == "none"
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    assert backward_mirror_policy() == "dots"
    monkeypatch.setenv("MXNET_TPU_REMAT_POLICY", "full")
    assert backward_mirror_policy() == "full"
    set_backward_mirror("dots_no_batch")
    assert backward_mirror_policy() == "dots_no_batch"
    set_backward_mirror(None)
    with pytest.raises(ValueError):
        set_backward_mirror("bogus")


def test_mirror_with_module_fit():
    """End-to-end: Module.fit converges with full remat on."""
    set_backward_mirror("full")
    try:
        rng = np.random.RandomState(1)
        x = rng.uniform(-1, 1, (64, 16)).astype(np.float32)
        w = rng.normal(0, 1, (16,)).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        net = sym.Variable("data")
        net = sym.FullyConnected(net, num_hidden=8)
        net = sym.Activation(net, act_type="relu")
        net = sym.FullyConnected(net, num_hidden=2)
        net = sym.SoftmaxOutput(net, name="softmax")
        mod = mx.mod.Module(net, context=mx.cpu())
        it = mx.io.NDArrayIter(x, y, batch_size=16, label_name="softmax_label")
        mod.fit(it, num_epoch=40, initializer=mx.init.Xavier(),
                optimizer_params={"learning_rate": 0.5})
        score = mod.score(it, mx.metric.Accuracy())
        acc = dict(score)["accuracy"]
        assert acc > 0.7, acc
    finally:
        set_backward_mirror(None)


def test_remat_reduces_live_activations():
    """The 'full' policy should not keep intermediate activations live
    across the forward/backward boundary.  Verified structurally: the
    jitted fwd+bwd HLO for 'full' contains a rematerialised (second)
    forward — detectable as more dot ops than the 'none' build."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.executor import GraphProgram

    net = _mlp()
    prog = GraphProgram(net)
    args = [jnp.zeros(s, jnp.float32) for s in
            net.infer_shape(data=(8, 64))[0]]
    mask = tuple(n not in ("data", "softmax_label")
                 for n in net.list_arguments())
    cots = (jnp.ones((8, 10), jnp.float32),)

    def n_dots(policy):
        fn = prog._jit_fwd_bwd_impl(True, mask, policy)
        txt = jax.jit(lambda a, c: fn(a, (), (), c)).lower(
            tuple(args), cots).as_text()
        return txt.count("dot_general")

    assert n_dots("full") > n_dots("none")
