"""Real sparse storage tests (reference tests/python/unittest/
test_sparse_ndarray.py + test_sparse_operator.py + the wide-embedding
workflow of example/sparse/).

The defining property verified throughout: the (data, indices) pair flows
through retain/merge/push/pull/optimizer WITHOUT the dense form ever being
materialised — asserted via nnz-sized buffers, not just values."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse as sp


def _rs(dense, shape=None):
    return sp.row_sparse_array(np.asarray(dense, np.float32),
                               shape=shape or np.asarray(dense).shape)


def test_retain_and_gather_stay_sparse():
    arr = sp.RowSparseNDArray(
        mx.nd.array(np.arange(12).reshape(3, 4)).astype("float32")._handle,
        mx.nd.array([1, 5, 9]).astype("int64")._handle, (12, 4))
    kept = arr.retain([5, 9, 11])
    assert kept._data.shape == (2, 4)          # only present rows kept
    np.testing.assert_array_equal(np.asarray(kept._indices), [5, 9])
    np.testing.assert_array_equal(np.asarray(kept._data),
                                  np.arange(4, 12).reshape(2, 4))
    assert kept._dense_cache is None           # never densified

    got = arr.gather_rows([0, 5, 11])
    assert got._data.shape == (3, 4)
    np.testing.assert_array_equal(np.asarray(got._data[0]), np.zeros(4))
    np.testing.assert_array_equal(np.asarray(got._data[1]),
                                  np.arange(4, 8))
    assert got._dense_cache is None


def test_merge_row_sparse_union_sum():
    a = sp.RowSparseNDArray(mx.nd.ones((2, 3))._handle,
                            mx.nd.array([0, 4]).astype("int64")._handle,
                            (8, 3))
    b = sp.RowSparseNDArray((mx.nd.ones((2, 3)) * 2)._handle,
                            mx.nd.array([4, 6]).astype("int64")._handle,
                            (8, 3))
    m = sp.merge_row_sparse([a, b])
    np.testing.assert_array_equal(np.asarray(m._indices), [0, 4, 6])
    np.testing.assert_array_equal(np.asarray(m._data),
                                  [[1] * 3, [3] * 3, [2] * 3])
    assert m._dense_cache is None


def test_csr_dot_sparse_compute():
    rs = np.random.RandomState(3)
    dense = rs.rand(6, 5).astype(np.float32)
    dense[dense < 0.7] = 0  # sparse
    csr = sp.csr_matrix(dense)
    rhs = mx.nd.array(rs.rand(5, 4).astype(np.float32))
    out = sp.sparse_dot(csr, rhs)
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs.asnumpy(),
                               rtol=1e-5)
    rhs_t = mx.nd.array(rs.rand(6, 4).astype(np.float32))
    out_t = sp.sparse_dot(csr, rhs_t, transpose_a=True)
    np.testing.assert_allclose(out_t.asnumpy(), dense.T @ rhs_t.asnumpy(),
                               rtol=1e-5)
    assert csr._dense_cache is None  # dot never built the dense matrix


def test_wide_embedding_lazy_sgd():
    """The example/sparse workflow: a vocab 100x+ wider than the touched
    rows; grads stay (data, indices) through push -> reduce -> lazy SGD,
    and row_sparse_pull moves only the requested rows."""
    vocab, dim, touched = 50_000, 16, 64
    rs = np.random.RandomState(0)
    w0 = rs.rand(vocab, dim).astype(np.float32)
    assert vocab / touched > 100  # the VERDICT's wide-embedding criterion

    kv = mx.kv.create("local")
    kv.init("emb", mx.nd.array(w0))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5, momentum=0.0,
                                      wd=0.0))

    ids = rs.randint(0, vocab, touched).astype(np.int64)
    grad_rows = rs.rand(touched, dim).astype(np.float32)
    grad = sp.embedding_grad(ids, mx.nd.array(grad_rows), vocab)
    assert grad._data.shape[0] == len(np.unique(ids))  # dupes summed
    kv.push("emb", grad)

    # expected: only touched rows move (lazy update)
    exp = w0.copy()
    np.add.at(exp, ids, -0.5 * grad_rows)

    out = sp.zeros_sparse("row_sparse", (vocab, dim))
    kv.row_sparse_pull("emb", out=out, row_ids=mx.nd.array(ids))
    uniq = np.unique(ids)
    assert out._data.shape == (len(uniq), dim)  # O(|row_ids|) moved
    np.testing.assert_allclose(np.asarray(out._data), exp[uniq], rtol=1e-5)

    # untouched rows unchanged
    untouched = np.setdiff1d(np.arange(0, 1000), uniq)[:8]
    out2 = sp.zeros_sparse("row_sparse", (vocab, dim))
    kv.row_sparse_pull("emb", out=out2, row_ids=mx.nd.array(untouched))
    np.testing.assert_allclose(np.asarray(out2._data), w0[untouched],
                               rtol=1e-6)


def test_row_sparse_pull_dense_out_honors_row_ids():
    kv = mx.kv.create("local")
    w = np.arange(20, dtype=np.float32).reshape(10, 2)
    kv.init("w", mx.nd.array(w))
    out = mx.nd.zeros((10, 2))
    kv.row_sparse_pull("w", out=out, row_ids=mx.nd.array([2, 7]))
    got = out.asnumpy()
    exp = np.zeros_like(w)
    exp[[2, 7]] = w[[2, 7]]
    np.testing.assert_array_equal(got, exp)  # ONLY requested rows filled


def test_lazy_sgd_momentum_touches_only_active_rows():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    w = mx.nd.ones((100, 4))
    state = opt.create_state(0, w)
    grad = _rs(np.zeros((100, 4)))  # build RS with rows 3, 50
    grad = sp.RowSparseNDArray(mx.nd.ones((2, 4))._handle,
                               mx.nd.array([3, 50]).astype("int64")._handle,
                               (100, 4))
    opt.update(0, w, grad, state)
    wn = w.asnumpy()
    mn = state.asnumpy()
    # active rows moved, others untouched
    np.testing.assert_allclose(wn[3], 1 - 0.1 * (1 + 0.0001), rtol=1e-4)
    np.testing.assert_array_equal(wn[4], np.ones(4))
    assert np.all(mn[3] != 0) and np.all(mn[4] == 0)


def test_lazy_adam_row_sparse():
    opt = mx.optimizer.Adam(learning_rate=0.01)
    w = mx.nd.ones((1000, 8))
    state = opt.create_state(0, w)
    grad = sp.RowSparseNDArray(mx.nd.ones((3, 8))._handle,
                               mx.nd.array([1, 7, 999]).astype(
                                   "int64")._handle, (1000, 8))
    opt.update(0, w, grad, state)
    wn = w.asnumpy()
    mean, var = state[0].asnumpy(), state[1].asnumpy()
    assert not np.allclose(wn[1], 1.0) and np.allclose(wn[2], 1.0)
    assert np.all(mean[7] != 0) and np.all(mean[8] == 0)
    assert np.all(var[999] != 0) and np.all(var[0] == 0)


def test_row_sparse_weight_lazy_update():
    """wide_deep pattern: the weight itself is row_sparse."""
    w = sp.RowSparseNDArray(mx.nd.ones((3, 2))._handle,
                            mx.nd.array([0, 5, 9]).astype("int64")._handle,
                            (10, 2))
    grad = sp.RowSparseNDArray(mx.nd.ones((2, 2))._handle,
                               mx.nd.array([0, 9]).astype("int64")._handle,
                               (10, 2))
    sp.sgd_row_sparse_update(w, grad, None, lr=0.5)
    np.testing.assert_allclose(np.asarray(w._data),
                               [[0.5, 0.5], [1, 1], [0.5, 0.5]])
    # grad with a row the weight doesn't hold -> informative error
    bad = sp.RowSparseNDArray(mx.nd.ones((1, 2))._handle,
                              mx.nd.array([4]).astype("int64")._handle,
                              (10, 2))
    with pytest.raises(mx.base.MXNetError, match="missing rows"):
        sp.sgd_row_sparse_update(w, bad, None, lr=0.5)


def test_storage_ops_compose_symbolically():
    """VERDICT r3 item 3: cast_storage / sparse_retain / square_sum /
    SparseEmbedding are registry ops usable from sym.* (reference
    cast_storage.cc:33, sparse_retain.cc:33, square_sum.cc:50,
    indexing_op.cc:249)."""
    from mxnet_tpu import sym
    ids = sym.Variable("data")
    w = sym.Variable("embed_weight")
    emb = sym.contrib.SparseEmbedding(data=ids, weight=w, input_dim=6,
                                      output_dim=4, name="emb")
    pooled = sym.mean(emb, axis=1)
    reg = sym.square_sum(sym.cast_storage(w, stype="row_sparse"), axis=(0, 1))
    out = sym.Group([pooled, reg])
    ex = out.simple_bind(mx.cpu(), data=(2, 3), embed_weight=(6, 4))
    ids_np = np.array([[0, 1, 5], [2, 2, 3]], np.float32)
    w_np = np.random.RandomState(0).rand(6, 4).astype(np.float32)
    ex.arg_dict["data"][:] = ids_np
    ex.arg_dict["embed_weight"][:] = w_np
    pooled_out, reg_out = ex.forward()
    np.testing.assert_allclose(pooled_out.asnumpy(),
                               w_np[ids_np.astype(int)].mean(1), rtol=1e-5)
    np.testing.assert_allclose(reg_out.asnumpy(), (w_np ** 2).sum(),
                               rtol=1e-5)


def test_infer_storage_type_propagation():
    from mxnet_tpu import sym
    x = sym.Variable("x")
    rs = sym.cast_storage(x, stype="row_sparse")
    kept = sym.sparse_retain(rs, sym.Variable("idx"))
    dense = sym.square_sum(kept, axis=(1,))
    args, outs, _ = dense.infer_storage_type()
    assert outs == ["default"]
    _, outs2, _ = kept.infer_storage_type()
    assert outs2 == ["row_sparse"]
    _, outs3, _ = rs.infer_storage_type()
    assert outs3 == ["row_sparse"]
    # csr feeds tagged at the variable flow through dot densely
    d = sym.dot(sym.Variable("csr_x"), sym.Variable("w"))
    _, outs4, _ = d.infer_storage_type(csr_x="csr")
    assert outs4 == ["default"]


def test_eager_cast_storage_and_retain():
    dense = mx.nd.array(np.array([[1., 0.], [0., 0.], [3., 4.]],
                                 np.float32))
    rsp = mx.nd.cast_storage(dense, "row_sparse")
    assert rsp.stype == "row_sparse"
    csr = mx.nd.cast_storage(dense, "csr")
    assert csr.stype == "csr"
    back = mx.nd.cast_storage(rsp, "default")
    assert back.stype == "default"
    np.testing.assert_allclose(back.asnumpy(), dense.asnumpy())
    kept = mx.nd.sparse_retain(rsp, mx.nd.array([2.]))
    assert kept.stype == "row_sparse"
    np.testing.assert_allclose(
        kept.asnumpy(), [[0., 0.], [0., 0.], [3., 4.]])
    # dense fallback path of the registry op
    kept_d = mx.nd.sparse_retain(dense, mx.nd.array([0.]))
    np.testing.assert_allclose(
        kept_d.asnumpy(), [[1., 0.], [0., 0.], [0., 0.]])


def test_sparse_embedding_trains_symbolically():
    """End-to-end: a Module trains a SparseEmbedding classifier graph
    (the symbolic analog of example/sparse/linear_classification)."""
    from mxnet_tpu import sym
    V, D, C, N, A = 50, 8, 2, 64, 4
    rs = np.random.RandomState(1)
    table = rs.normal(0, 1, (V, D)).astype(np.float32)
    proj = rs.normal(0, 1, (D,)).astype(np.float32)
    feats = rs.randint(0, V, (N, A)).astype(np.float32)
    y = (table[feats.astype(int)].mean(1) @ proj > 0).astype(np.float32)

    mx.random.seed(7)
    ids = sym.Variable("data")
    emb = sym.contrib.SparseEmbedding(data=ids,
                                      weight=sym.Variable("w"),
                                      input_dim=V, output_dim=D)
    net = sym.FullyConnected(sym.mean(emb, axis=1), num_hidden=C)
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(feats, y, batch_size=16, shuffle=True,
                           label_name="softmax_label")
    mod.fit(it, num_epoch=20,
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.init.Xavier(), force_init=True)
    it.reset()
    score = mod.score(it, mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    assert acc > 0.8, acc


# ---------------------------------------------------------------------------
# exactness under duplicates / unsorted ids / zero-nnz shards, against
# dense reference math (the host plane is the semantic reference the
# in-jit sharded embedding plane is proven equal to — its own edge cases
# must be airtight)
# ---------------------------------------------------------------------------

def test_merge_row_sparse_duplicates_within_one_input():
    """Duplicate indices INSIDE one array: merge segment-sums them too,
    matching the dense sum."""
    data = np.array([[1., 2.], [10., 20.], [100., 200.]], np.float32)
    idx = np.array([4, 4, 1], np.int64)
    a = sp.RowSparseNDArray(mx.nd.array(data)._handle,
                            mx.nd.array(idx).astype("int64")._handle,
                            (6, 2))
    m = sp.merge_row_sparse([a])
    dense = np.zeros((6, 2), np.float32)
    np.add.at(dense, idx, data)
    np.testing.assert_array_equal(np.asarray(m._indices), [1, 4])
    np.testing.assert_array_equal(np.asarray(m._data),
                                  dense[[1, 4]])
    np.testing.assert_array_equal(m.asnumpy(), dense)


def test_merge_row_sparse_unsorted_and_cross_array_duplicates():
    rs = np.random.RandomState(0)
    shape = (20, 3)
    dense_sum = np.zeros(shape, np.float32)
    arrays = []
    for seed in range(3):
        k = rs.randint(1, 8)
        idx = rs.randint(0, shape[0], k).astype(np.int64)  # dupes likely
        data = rs.randn(k, shape[1]).astype(np.float32)
        np.add.at(dense_sum, idx, data)
        # constructor receives UNSORTED indices (sorts internally)
        arrays.append(sp.RowSparseNDArray(
            mx.nd.array(data)._handle,
            mx.nd.array(idx).astype("int64")._handle, shape))
    m = sp.merge_row_sparse(arrays)
    # indices sorted unique, data is nnz-sized — and the merge itself
    # never densified (asnumpy below is what builds the dense view)
    got_idx = np.asarray(m._indices)
    assert np.all(np.diff(got_idx) > 0)
    assert m._data.shape[0] == len(got_idx)
    assert m._dense_cache is None
    np.testing.assert_allclose(m.asnumpy(), dense_sum, rtol=1e-6)


def test_merge_row_sparse_zero_nnz_shards_mixed():
    """Zero-nnz inputs mixed with real ones (a worker that touched no
    rows this step) must neither crash nor perturb the sum; the
    all-empty merge is the empty gradient."""
    shape = (8, 2)
    empty = sp.zeros_sparse("row_sparse", shape)
    a = sp.RowSparseNDArray(mx.nd.ones((2, 2))._handle,
                            mx.nd.array([1, 6]).astype("int64")._handle,
                            shape)
    m = sp.merge_row_sparse([empty, a, empty])
    dense = np.zeros(shape, np.float32)
    dense[[1, 6]] = 1.0
    np.testing.assert_array_equal(m.asnumpy(), dense)
    m0 = sp.merge_row_sparse([empty, empty])
    assert m0._data.shape[0] == 0
    np.testing.assert_array_equal(m0.asnumpy(), np.zeros(shape))


def test_row_sparse_pull_repeated_and_unsorted_ids():
    """row_sparse_pull with repeated + unsorted row_ids: the pulled
    row_sparse holds each requested row ONCE (sorted unique), valued
    exactly as the dense store."""
    kv = mx.kv.create("local")
    rs = np.random.RandomState(2)
    w = rs.rand(12, 3).astype(np.float32)
    kv.init("w", mx.nd.array(w))
    req = np.array([7, 2, 7, 2, 11, 0, 0], np.int64)
    out = sp.zeros_sparse("row_sparse", (12, 3))
    kv.row_sparse_pull("w", out=out, row_ids=mx.nd.array(req))
    uniq = np.unique(req)
    np.testing.assert_array_equal(np.asarray(out._indices), uniq)
    np.testing.assert_allclose(np.asarray(out._data), w[uniq], rtol=1e-6)
    # dense out honors the same contract: requested rows only
    dense_out = mx.nd.zeros((12, 3))
    kv.row_sparse_pull("w", out=dense_out, row_ids=mx.nd.array(req))
    exp = np.zeros_like(w)
    exp[uniq] = w[uniq]
    np.testing.assert_allclose(dense_out.asnumpy(), exp, rtol=1e-6)


def test_row_sparse_pull_from_zero_nnz_store():
    """Pulling from a store holding a zero-nnz row_sparse value returns
    zero rows for every requested id (the gather_rows empty-store
    path)."""
    kv = mx.kv.create("local")
    kv.init("z", sp.zeros_sparse("row_sparse", (10, 4)))
    out = sp.zeros_sparse("row_sparse", (10, 4))
    kv.row_sparse_pull("z", out=out, row_ids=mx.nd.array([3, 3, 8]))
    np.testing.assert_array_equal(np.asarray(out._indices), [3, 8])
    np.testing.assert_array_equal(np.asarray(out._data),
                                  np.zeros((2, 4), np.float32))


def test_retain_unsorted_request_and_empties():
    arr = sp.RowSparseNDArray(
        mx.nd.array(np.arange(8).reshape(4, 2)).astype("float32")._handle,
        mx.nd.array([0, 3, 5, 7]).astype("int64")._handle, (9, 2))
    # unsorted + duplicated + absent ids in the request
    kept = arr.retain([7, 1, 3, 7, 3])
    np.testing.assert_array_equal(np.asarray(kept._indices), [3, 7])
    np.testing.assert_array_equal(np.asarray(kept._data),
                                  [[2., 3.], [6., 7.]])
    # empty request -> empty result, dense shape preserved
    kept0 = arr.retain(np.array([], np.int64))
    assert kept0._data.shape[0] == 0
    np.testing.assert_array_equal(kept0.asnumpy(), np.zeros((9, 2)))
