"""Registry-wide operator coverage: every public op gets at least a forward
check (finite outputs, shape, numpy reference where cheap) and — for
differentiable ops — a finite-difference gradient check via
mxnet_tpu.test_utils.check_numeric_gradient (reference
python/mxnet/test_utils.py:794, tests/python/unittest/test_operator.py).

The meta-test at the bottom fails if a public registry op is neither
spec'd here nor in the explicit KNOWN_ELSEWHERE list, so newly registered
ops must arrive with coverage.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops.registry import get_op, list_ops
from mxnet_tpu.test_utils import check_numeric_gradient

RS = np.random.RandomState(42)


def _pos(*shape):
    return (RS.rand(*shape) * 0.8 + 0.2).astype(np.float32)


def _unit(*shape):
    return (RS.rand(*shape) * 1.6 - 0.8).astype(np.float32)


def _farz(*shape):
    """Values away from zero (for abs/sign/reciprocal-style kinks)."""
    a = RS.rand(*shape).astype(np.float32) + 0.3
    return a * np.where(RS.rand(*shape) > 0.5, 1, -1).astype(np.float32)


def _any(*shape):
    return RS.randn(*shape).astype(np.float32)


def S(arrays, attrs=None, grad=False, grad_nodes=None, ref=None,
      train=False, rtol=1e-2, atol=1e-2, out_shape=None):
    return dict(arrays=arrays, attrs=attrs or {}, grad=grad,
                grad_nodes=grad_nodes, ref=ref, train=train, rtol=rtol,
                atol=atol, out_shape=out_shape)


# --- generic families ------------------------------------------------------

UNARY_SMOOTH_POS = ["cbrt", "exp", "expm1", "gamma", "gammaln", "log",
                    "log10", "log1p", "log2", "rcbrt", "reciprocal", "rsqrt",
                    "sqrt", "square"]
UNARY_SMOOTH_UNIT = ["arccos", "arcsin", "arctan", "arctanh", "cos", "erf",
                     "erfinv", "sigmoid", "sin", "sinh", "softsign", "tan",
                     "tanh", "cosh", "degrees", "radians", "negative"]
UNARY_ARCCOSH = ["arccosh"]              # domain (1, inf)
UNARY_KINKED = ["abs", "relu"]           # grad checked away from 0
UNARY_STEP = ["ceil", "floor", "fix", "rint", "trunc", "sign",
              "logical_not"]             # forward only, piecewise-constant
UNARY_LIKE = ["zeros_like", "ones_like", "identity", "BlockGrad"]

BINARY_GRAD = ["elemwise_add", "elemwise_sub", "elemwise_mul",
               "broadcast_add", "broadcast_sub", "broadcast_mul",
               "broadcast_maximum", "broadcast_minimum", "broadcast_hypot"]
BINARY_NOGRAD = ["broadcast_equal", "broadcast_greater",
                 "broadcast_greater_equal", "broadcast_lesser",
                 "broadcast_lesser_equal", "broadcast_not_equal",
                 "broadcast_logical_and", "broadcast_logical_or",
                 "broadcast_logical_xor", "broadcast_mod"]

_NP_UNARY = dict(
    abs=np.abs, ceil=np.ceil, floor=np.floor, rint=np.rint, trunc=np.trunc,
    sign=np.sign, exp=np.exp, log=np.log, sqrt=np.sqrt, square=np.square,
    sin=np.sin, cos=np.cos, tanh=np.tanh, negative=np.negative,
)

SPECS = {}

for _n in UNARY_SMOOTH_POS:
    SPECS[_n] = S([_pos(2, 3)], grad=True, ref=_NP_UNARY.get(_n))
for _n in UNARY_SMOOTH_UNIT:
    SPECS[_n] = S([_unit(2, 3)], grad=True, ref=_NP_UNARY.get(_n))
for _n in UNARY_ARCCOSH:
    SPECS[_n] = S([_pos(2, 3) + 1.2], grad=True)
SPECS["arcsinh"] = S([_unit(2, 3)], grad=True)
for _n in UNARY_KINKED:
    SPECS[_n] = S([_farz(2, 3)], grad=True, ref=_NP_UNARY.get(_n))
for _n in UNARY_STEP:
    SPECS[_n] = S([_farz(2, 3)], ref=_NP_UNARY.get(_n))
for _n in UNARY_LIKE:
    SPECS[_n] = S([_any(2, 3)])

for _n in BINARY_GRAD:
    SPECS[_n] = S([_farz(2, 3), _farz(2, 3)], grad=True)
for _n in BINARY_NOGRAD:
    SPECS[_n] = S([_farz(2, 3), _farz(2, 3)])

# --- individual specs ------------------------------------------------------

SPECS.update({
    "elemwise_div": S([_any(2, 3), _farz(2, 3)], grad=True),
    "broadcast_div": S([_any(2, 3), _farz(1, 3)], grad=True),
    "broadcast_power": S([_pos(2, 3), _unit(1, 3)], grad=True),
    "smooth_l1": S([_any(2, 3)], dict(scalar=1.0), grad=True),
    "clip": S([_any(2, 3)], dict(a_min=-0.5, a_max=0.5),
              ref=lambda a, **kw: np.clip(a, -0.5, 0.5)),
    # reductions
    "sum": S([_any(2, 3)], dict(axis=1), grad=True,
             ref=lambda a, **kw: a.sum(axis=1)),
    "mean": S([_any(2, 3)], dict(axis=1), grad=True,
              ref=lambda a, **kw: a.mean(axis=1)),
    "prod": S([_farz(2, 3)], dict(axis=1), grad=True,
              ref=lambda a, **kw: a.prod(axis=1)),
    "nansum": S([_any(2, 3)], dict(axis=1), grad=True),
    "nanprod": S([_farz(2, 3)], dict(axis=1)),
    "max": S([_any(2, 3)], dict(axis=1), ref=lambda a, **kw: a.max(axis=1)),
    "min": S([_any(2, 3)], dict(axis=1), ref=lambda a, **kw: a.min(axis=1)),
    "norm": S([_any(2, 3)], grad=True,
              ref=lambda a, **kw: np.linalg.norm(a.ravel())),
    "square_sum": S([_any(2, 3)], dict(axis=1), grad=True,
                    ref=lambda a, **kw: (a * a).sum(axis=1)),
    "argmax": S([_any(2, 5)], dict(axis=1),
                ref=lambda a, **kw: a.argmax(axis=1).astype(np.float32)),
    "argmin": S([_any(2, 5)], dict(axis=1),
                ref=lambda a, **kw: a.argmin(axis=1).astype(np.float32)),
    "argmax_channel": S([_any(2, 5)],
                        ref=lambda a: a.argmax(axis=1).astype(np.float32)),
    # shape ops
    "Reshape": S([_any(2, 6)], dict(shape=(3, 4)), grad=True,
                 ref=lambda a, **kw: a.reshape(3, 4)),
    "Flatten": S([_any(2, 3, 2)], grad=True,
                 ref=lambda a: a.reshape(2, 6)),
    "expand_dims": S([_any(2, 3)], dict(axis=1), grad=True),
    "squeeze": S([_any(2, 1, 3)], dict(axis=1), grad=True),
    "transpose": S([_any(2, 3)], dict(axes=(1, 0)), grad=True,
                   ref=lambda a, **kw: a.T),
    "swapaxes": S([_any(2, 3, 4)], dict(dim1=0, dim2=2), grad=True),
    "tile": S([_any(2, 3)], dict(reps=(2, 1)), grad=True,
              ref=lambda a, **kw: np.tile(a, (2, 1))),
    "repeat": S([_any(2, 3)], dict(repeats=2, axis=1), grad=True,
                ref=lambda a, **kw: np.repeat(a, 2, axis=1)),
    "reverse": S([_any(2, 3)], dict(axis=1), grad=True,
                 ref=lambda a, **kw: a[:, ::-1]),
    "slice": S([_any(3, 4)], dict(begin=(1, 0), end=(3, 2)), grad=True,
               ref=lambda a, **kw: a[1:3, 0:2]),
    "slice_axis": S([_any(3, 4)], dict(axis=1, begin=1, end=3), grad=True,
                    ref=lambda a, **kw: a[:, 1:3]),
    "slice_like": S([_any(4, 5), _any(2, 3)], grad=True, grad_nodes=["x0"],
                    ref=lambda a, b: a[:2, :3]),
    "broadcast_to": S([_any(1, 3)], dict(shape=(4, 3)), grad=True),
    "broadcast_axis": S([_any(1, 3)], dict(axis=0, size=4), grad=True),
    "broadcast_like": S([_any(1, 3), _any(4, 3)], grad=True,
                        grad_nodes=["x0"]),
    "Pad": S([_any(1, 2, 3, 3)],
             dict(mode="constant", pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
             grad=True),
    "pad": S([_any(1, 2, 3, 3)],
             dict(mode="edge", pad_width=(0, 0, 0, 0, 1, 1, 1, 1))),
    "stack": S([_any(2, 3), _any(2, 3)], dict(axis=1), grad=True),
    "Concat": S([_any(2, 3), _any(2, 4)], dict(dim=1, num_args=2), grad=True,
                ref=lambda a, b, **kw: np.concatenate([a, b], axis=1)),
    "SliceChannel": S([_any(2, 6)], dict(num_outputs=2, axis=1), grad=True),
    "depth_to_space": S([_any(1, 8, 2, 2)], dict(block_size=2), grad=True),
    "space_to_depth": S([_any(1, 2, 4, 4)], dict(block_size=2), grad=True),
    "Cast": S([_any(2, 3)], dict(dtype="float64"),
              ref=lambda a, **kw: a.astype(np.float64)),
    # indexing
    "take": S([_any(5, 3), np.array([0., 2., 4.], np.float32)], dict(axis=0),
              grad=True, grad_nodes=["x0"],
              ref=lambda a, i, **kw: a[i.astype(int)]),
    "batch_take": S([_any(3, 4), np.array([0., 3., 1.], np.float32)],
                    ref=lambda a, i: a[np.arange(3), i.astype(int)]),
    "pick": S([_any(3, 4), np.array([0., 3., 1.], np.float32)], dict(axis=1),
              grad=True, grad_nodes=["x0"]),
    "one_hot": S([np.array([0., 2., 1.], np.float32)], dict(depth=4),
                 ref=lambda i, **kw: np.eye(4, dtype=np.float32)[
                     i.astype(int)]),
    "gather_nd": S([_any(4, 3), np.array([[0., 2.], [1., 0.]],
                                         np.float32).T],
                   grad=True, grad_nodes=["x0"]),
    "scatter_nd": S([_any(2), np.array([[0., 2.], [1., 0.]],
                                       np.float32).T],
                    dict(shape=(4, 3)), grad=True, grad_nodes=["x0"]),
    "Embedding": S([np.array([1., 0., 3.], np.float32), _any(5, 4)],
                   dict(input_dim=5, output_dim=4), grad=True,
                   grad_nodes=["x1"],
                   ref=lambda i, w, **kw: w[i.astype(int)]),
    "choose_element_0index": S(
        [_any(3, 4), np.array([1., 0., 3.], np.float32)],
        ref=lambda a, i: a[np.arange(3), i.astype(int)]),
    "fill_element_0index": S(
        [_any(3, 4), _any(3), np.array([1., 0., 3.], np.float32)]),
    "where": S([np.array([1., 0., 1.], np.float32), _any(3), _any(3)],
               grad=True, grad_nodes=["x1", "x2"],
               ref=lambda c, x, y: np.where(c > 0, x, y)),
    "topk": S([_any(2, 6)], dict(k=2, ret_typ="value")),
    "sort": S([_any(2, 6)], ref=lambda a, **kw: np.sort(a, axis=-1)),
    "argsort": S([_any(2, 6)],
                 ref=lambda a, **kw: np.argsort(a, -1).astype(np.float32)),
    "shuffle": S([_any(6, 2)]),
    # NN
    "Activation": S([_any(2, 3)], dict(act_type="softrelu"), grad=True),
    "LeakyReLU": S([_farz(2, 3)], dict(act_type="leaky", slope=0.1),
                   grad=True),
    "softmax": S([_any(2, 5)], dict(axis=-1), grad=True),
    "log_softmax": S([_any(2, 5)], dict(axis=-1), grad=True),
    "SoftmaxActivation": S([_any(2, 5)], grad=True),
    "FullyConnected": S([_any(2, 3), _any(4, 3), _any(4)],
                        dict(num_hidden=4), grad=True,
                        ref=lambda x, w, b, **kw: x @ w.T + b),
    "Convolution": S([_any(1, 2, 5, 5), _any(3, 2, 3, 3), _any(3)],
                     dict(kernel=(3, 3), num_filter=3), grad=True),
    "Deconvolution": S([_any(1, 3, 3, 3), _any(3, 2, 3, 3), _any(2)],
                       dict(kernel=(3, 3), num_filter=2), grad=True),
    "Pooling": S([_any(1, 2, 4, 4)],
                 dict(kernel=(2, 2), stride=(2, 2), pool_type="avg"),
                 grad=True),
    "UpSampling": S([_any(1, 2, 3, 3)],
                    dict(scale=2, sample_type="nearest", num_args=1),
                    grad=True),
    "BatchNorm": S([_any(2, 3, 4, 4), _pos(3), _any(3),
                    np.zeros(3, np.float32), np.ones(3, np.float32)],
                   dict(fix_gamma=False), grad=True, train=True,
                   grad_nodes=["x0", "x1", "x2"]),
    "LayerNorm": S([_any(2, 5), _pos(5), _any(5)], grad=True),
    "InstanceNorm": S([_any(2, 3, 4, 4), _pos(3), _any(3)], grad=True),
    "LRN": S([_any(1, 4, 3, 3)], dict(nsize=3), grad=True),
    "L2Normalization": S([_farz(2, 5)], grad=True),
    # *RegressionOutput/SoftmaxOutput backward = (pred - label) regardless
    # of head cotangents (reference softmax_output-inl.h) — numeric FD of
    # the forward cannot equal that custom gradient; training-path checks
    # live in test_module/test_operator.
    "SoftmaxOutput": S([_any(4, 5), np.array([0., 2., 1., 4.], np.float32)],
                       train=True),
    "LinearRegressionOutput": S([_any(4, 3), _any(4, 3)], train=True),
    "MAERegressionOutput": S([_farz(4, 3), _any(4, 3)], train=True),
    "LogisticRegressionOutput": S([_any(4, 3),
                                   (RS.rand(4, 3) > .5).astype(np.float32)],
                                  train=True),
    "SVMOutput": S([_any(4, 5), np.array([0., 2., 1., 4.], np.float32)],
                   train=True),
    "MakeLoss": S([_pos(2, 3)], grad=True, train=True),
    "make_loss": S([_pos(2, 3)], grad=True, train=True),
    "Dropout": S([_any(2, 6)], dict(p=0.5)),      # eval mode = identity
    "CTCLoss": S([_any(5, 2, 6), np.array([[1., 2.], [2., 3.]],
                                          np.float32)]),
    "SequenceMask": S([_any(3, 2, 4), np.array([1., 3.], np.float32)],
                      dict(use_sequence_length=True)),
    "SequenceLast": S([_any(3, 2, 4), np.array([1., 3.], np.float32)],
                      dict(use_sequence_length=True)),
    "SequenceReverse": S([_any(3, 2, 4), np.array([1., 3.], np.float32)],
                         dict(use_sequence_length=True)),
    # linear algebra
    "dot": S([_any(2, 3), _any(3, 4)], grad=True,
             ref=lambda a, b, **kw: a @ b),
    "batch_dot": S([_any(2, 2, 3), _any(2, 3, 2)], grad=True,
                   ref=lambda a, b, **kw: a @ b),
    "khatri_rao": S([_any(2, 3), _any(4, 3)], grad=True),
    "_linalg_gemm": S([_any(2, 3), _any(3, 4), _any(2, 4)],
                      dict(alpha=1.0, beta=1.0), grad=True),
    "_linalg_gemm2": S([_any(2, 3), _any(3, 4)], grad=True,
                       ref=lambda a, b, **kw: a @ b),
    "_linalg_syrk": S([_any(2, 3)], grad=True,
                      ref=lambda a, **kw: a @ a.T),
    "_linalg_trmm": S([np.tril(_pos(3, 3) + np.eye(3,
                                                   dtype=np.float32)),
                       _any(3, 2)], grad=True),
    "_linalg_trsm": S([np.tril(_pos(3, 3) + np.eye(3, dtype=np.float32)),
                       _any(3, 2)]),
    "_linalg_potrf": S([(lambda a: (a @ a.T + 3 * np.eye(3,
                                                         dtype=np.float32))
                         )(_any(3, 3))],
                       ref=lambda a: np.linalg.cholesky(a)),
    "_linalg_potri": S([(lambda a: np.linalg.cholesky(
        a @ a.T + 3 * np.eye(3, dtype=np.float32)))(_any(3, 3))]),
    "_linalg_gelqf": S([_any(2, 4)]),
    "_linalg_sumlogdiag": S([_pos(3, 3) + np.eye(3, dtype=np.float32)],
                            grad=True),
    "_linalg_extractdiag": S([_any(3, 3)], grad=True,
                             ref=lambda a, **kw: np.diag(a)),
    "_linalg_makediag": S([_any(3)], grad=True,
                          ref=lambda a, **kw: np.diag(a)),
    "_linalg_extracttrian": S([_any(3, 3)], grad=True),
    "_linalg_maketrian": S([_any(6)], grad=True),
    # spatial
    "GridGenerator": S([_any(2, 6)],
                       dict(transform_type="affine", target_shape=(3, 3)),
                       grad=True),
    "BilinearSampler": S([_any(1, 2, 4, 4), _unit(1, 2, 3, 3)], grad=True),
    "SpatialTransformer": S([_any(1, 2, 4, 4),
                             np.tile(np.array([.62, .17, .07, -.13, .58,
                                               .11], np.float32), (1, 1))],
                            dict(target_shape=(3, 3)), grad=True),
    "Correlation": S([_any(1, 2, 5, 5), _any(1, 2, 5, 5)],
                     dict(kernel_size=1, max_displacement=1, pad_size=1),
                     grad=True),
    "Crop": S([_any(1, 2, 5, 5)],
              dict(offset=(1, 1), h_w=(3, 3), num_args=1), grad=True),
    # contrib
    # sparse-storage ops (dense graph semantics; see ops/sparse_storage.py)
    "cast_storage": S([_any(3, 4)], dict(stype="row_sparse"),
                      grad=True, ref=lambda a, **kw: a),
    "_sparse_retain": S(
        [_any(4, 3), np.array([0., 2.], np.float32)],
        ref=lambda a, idx: a * np.isin(np.arange(4),
                                       idx.astype(int))[:, None]),
    "_square_sum": S([_any(3, 4)], dict(axis=(1,)), grad=True,
                     ref=lambda a, **kw: (a * a).sum(1)),
    "_contrib_SparseEmbedding": S(
        [np.array([[0., 2.], [1., 1.]], np.float32), _any(4, 3)],
        dict(input_dim=4, output_dim=3),
        ref=lambda idx, w, **kw: w[idx.astype(int)]),
    "_contrib_fft": S([_any(2, 4)], out_shape=(2, 8)),
    "_contrib_ifft": S([_any(2, 8)], out_shape=(2, 4)),
    "_contrib_count_sketch": S(
        [_any(2, 5), np.array([0., 2., 1., 3., 0.], np.float32),
         np.array([1., -1., 1., 1., -1.], np.float32)],
        dict(out_dim=4), out_shape=(2, 4)),
    "_contrib_quantize": S(
        [_unit(2, 3), np.array([-1.], np.float32),
         np.array([1.], np.float32)]),
    "_contrib_dequantize": S(
        [(RS.randint(0, 255, (2, 3)) - 127).astype(np.float32),
         np.array([-1.], np.float32), np.array([1.], np.float32)]),
    "_contrib_MultiBoxPrior": S([_any(1, 3, 4, 4)],
                                dict(sizes=(0.5,), ratios=(1.0,))),
    "_contrib_MultiBoxTarget": S(
        [np.array([[[0.1, 0.1, 0.4, 0.4]]], np.float32),
         np.array([[[0., 0.1, 0.1, 0.5, 0.5]]], np.float32),
         _any(1, 2, 1)]),
    "_contrib_MultiBoxDetection": S(
        [_pos(1, 2, 1),
         np.array([[0.1] * 4], np.float32).reshape(1, 4),
         np.array([[[0.2, 0.2, 0.4, 0.4]]], np.float32)]),
    "_contrib_Proposal": S(
        [_pos(1, 2, 4, 4), _any(1, 4, 4, 4),
         np.array([[16., 16., 1.]], np.float32)],
        dict(feature_stride=4, scales=(8,), ratios=(1.0,),
             rpn_pre_nms_top_n=6, rpn_post_nms_top_n=4,
             rpn_min_size=0)),
    "_contrib_MultiProposal": S(
        [_pos(2, 2, 4, 4), _any(2, 4, 4, 4),
         np.array([[16., 16., 1.], [16., 16., 1.]], np.float32)],
        dict(feature_stride=4, scales=(8,), ratios=(1.0,),
             rpn_pre_nms_top_n=6, rpn_post_nms_top_n=4,
             rpn_min_size=0), out_shape=(8, 5)),
    "_contrib_DeformablePSROIPooling": S(
        [_any(1, 8, 6, 6), np.array([[0., 0., 0., 4., 4.]], np.float32),
         _any(1, 2, 2, 2)],
        dict(output_dim=2, group_size=2, pooled_size=2, spatial_scale=1.0,
             part_size=2, sample_per_part=2, trans_std=0.1)),
    "ROIPooling": S(
        [_any(1, 2, 6, 6), np.array([[0., 0., 0., 3., 3.]], np.float32)],
        dict(pooled_size=(2, 2), spatial_scale=1.0)),
    "_contrib_PSROIPooling": S(
        [_any(1, 8, 6, 6), np.array([[0., 0., 0., 4., 4.]], np.float32)],
        dict(output_dim=2, pooled_size=2, spatial_scale=1.0)),
    "_contrib_DeformableConvolution": S(
        [_any(1, 2, 5, 5), _any(1, 18, 3, 3), _any(3, 2, 3, 3), _any(3)],
        dict(kernel=(3, 3), num_filter=3)),
    # random (forward-only: shapes/finiteness; draws differ per call)
    "_random_uniform": S([], dict(shape=(2, 3)), out_shape=(2, 3)),
    "_random_normal": S([], dict(shape=(2, 3)), out_shape=(2, 3)),
    "_random_gamma": S([], dict(shape=(2, 3)), out_shape=(2, 3)),
    "_random_exponential": S([], dict(shape=(2, 3)), out_shape=(2, 3)),
    "_random_poisson": S([], dict(shape=(2, 3)), out_shape=(2, 3)),
    "_random_negative_binomial": S([], dict(shape=(2, 3)),
                                   out_shape=(2, 3)),
    "_random_generalized_negative_binomial": S([], dict(shape=(2, 3)),
                                               out_shape=(2, 3)),
    "_random_randint": S([], dict(shape=(2, 3), low=0, high=9),
                         out_shape=(2, 3)),
    "_sample_uniform": S([np.zeros(2, np.float32), np.ones(2, np.float32)],
                         dict(shape=(3,)), out_shape=(2, 3)),
    "_sample_normal": S([np.zeros(2, np.float32), np.ones(2, np.float32)],
                        dict(shape=(3,)), out_shape=(2, 3)),
    "_sample_gamma": S([_pos(2), _pos(2)], dict(shape=(3,)),
                       out_shape=(2, 3)),
    "_sample_exponential": S([_pos(2)], dict(shape=(3,)), out_shape=(2, 3)),
    "_sample_poisson": S([_pos(2) * 4], dict(shape=(3,)), out_shape=(2, 3)),
    "_sample_negative_binomial": S([np.array([1., 3.], np.float32),
                                    _pos(2) * 0.5 + 0.25],
                                   dict(shape=(3,)), out_shape=(2, 3)),
    "_sample_generalized_negative_binomial": S(
        [_pos(2) * 3, _pos(2)], dict(shape=(3,)), out_shape=(2, 3)),
    "_sample_multinomial": S([_pos(2, 4) / 4.0], dict(shape=(3,)),
                             out_shape=(2, 3)),
    # fused optimizer updates (forward semantics; full optimizer behaviour
    # covered in test_optimizer.py)
    "sgd_update": S([_any(4), _any(4)], dict(lr=0.1)),
    "sgd_mom_update": S([_any(4), _any(4), _any(4)],
                        dict(lr=0.1, momentum=0.9)),
    "mp_sgd_update": S([_any(4), _any(4), _any(4)], dict(lr=0.1)),
    "mp_sgd_mom_update": S([_any(4), _any(4), _any(4), _any(4)],
                           dict(lr=0.1, momentum=0.9)),
    "multi_sgd_update": S([_any(4), _any(4)],
                          dict(lrs=(0.1,), wds=(0.0,), num_weights=1)),
    "multi_sgd_mom_update": S([_any(4), _any(4), _any(4)],
                              dict(lrs=(0.1,), wds=(0.0,), momentum=0.9,
                                   num_weights=1)),
    "multi_mp_sgd_update": S([_any(4), _any(4), _any(4)],
                             dict(lrs=(0.1,), wds=(0.0,), num_weights=1)),
    "multi_mp_sgd_mom_update": S([_any(4), _any(4), _any(4), _any(4)],
                                 dict(lrs=(0.1,), wds=(0.0,), momentum=0.9,
                                      num_weights=1)),
    "adam_update": S([_any(4), _any(4), _any(4), _pos(4)], dict(lr=0.1)),
    "rmsprop_update": S([_any(4), _any(4), _pos(4)], dict(lr=0.1)),
    "rmspropalex_update": S([_any(4), _any(4), _pos(4),
                             np.zeros(4, np.float32),
                             np.zeros(4, np.float32)], dict(lr=0.1)),
    "ftrl_update": S([_any(4), _any(4), _any(4), _pos(4)], dict(lr=0.1)),
    "signsgd_update": S([_any(4), _any(4)], dict(lr=0.1)),
    "signum_update": S([_any(4), _any(4), _any(4)],
                       dict(lr=0.1, momentum=0.9)),
    # round-3 completeness sweep (reference registrations diff)
    "round": S([_farz(2, 3)],
               ref=lambda a: np.sign(a) * np.floor(np.abs(a) + 0.5)),
    "add_n": S([_any(2, 3), _any(2, 3), _any(2, 3)],
               ref=lambda a, b, c: a + b + c),
    "reshape_like": S([_any(2, 6), _any(3, 4)], out_shape=(3, 4)),
    "softmax_cross_entropy": S(
        [_any(4, 5), np.array([0, 1, 2, 3], np.float32)], out_shape=(1,)),
    "ftml_update": S([_any(4), _any(4), np.ones(4, np.float32),
                      _pos(4), _any(4)], dict(lr=0.1, t=1)),
    "_linalg_syevd": S([(lambda m: (m + m.T) / 2)(_any(4, 4))],
                       out_shape=(4, 4)),
    "IdentityAttachKLSparseReg": S([_pos(4, 3)], grad=True,
                                   ref=lambda a: a),
    "_image_to_tensor": S(
        [(_pos(5, 6, 3) * 255).astype(np.uint8)], out_shape=(3, 5, 6),
        ref=lambda a: a.astype(np.float32).transpose(2, 0, 1) / 255.0),
    "_image_normalize": S([_pos(3, 5, 6)],
                          dict(mean=(0.5, 0.5, 0.5), std=(2.0, 2.0, 2.0)),
                          ref=lambda a, **kw: (a - 0.5) / 2.0),
    "_contrib_box_iou": S([_pos(3, 4).cumsum(-1), _pos(2, 4).cumsum(-1)],
                          out_shape=(3, 2)),
    "_contrib_box_nms": S([np.array([[1, 0.9, 0, 0, 1, 1],
                                     [1, 0.8, 0, 0, 1, 1],
                                     [0, 0.7, 2, 2, 3, 3]], np.float32)],
                          dict(overlap_thresh=0.5, coord_start=2,
                               score_index=1, id_index=0),
                          out_shape=(3, 6)),
    "_contrib_bipartite_matching": S(
        [np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)],
        dict(threshold=0.05), out_shape=None),
})

# Ops whose coverage lives in a dedicated test file (kept explicit so the
# meta-test still accounts for every public op).
KNOWN_ELSEWHERE = {
    "RNN": "tests/test_rnn.py (cells, fused layers, bucketing)",
    "Custom": "tests/test_custom_op.py (frontend-defined ops)",
    "_contrib_fused_attention":
        "tests/test_transformer.py (naive parity + custom-vjp gradients)",
}


def _sym_for(name, spec):
    xs = [mx.sym.Variable("x%d" % i) for i in range(len(spec["arrays"]))]
    return getattr(mx.sym, name)(*xs, **spec["attrs"])


@pytest.mark.parametrize("name", sorted(SPECS))
def test_op_forward(name):
    spec = SPECS[name]
    fn = getattr(mx.nd, name)
    nds = [mx.nd.array(a) for a in spec["arrays"]]
    was_train = False
    if spec["train"]:
        was_train = True
        mx.autograd.set_training(True)
    try:
        out = fn(*nds, **spec["attrs"])
    finally:
        if was_train:
            mx.autograd.set_training(False)
    outs = list(out) if isinstance(out, (tuple, list)) else [out]
    first = outs[0].asnumpy()
    assert np.isfinite(first.astype(np.float64)).all(), \
        "%s produced non-finite output" % name
    if spec["out_shape"] is not None:
        assert tuple(first.shape) == tuple(spec["out_shape"]), \
            "%s: shape %s != %s" % (name, first.shape, spec["out_shape"])
    if spec["ref"] is not None:
        expect = spec["ref"](*spec["arrays"], **spec["attrs"])
        np.testing.assert_allclose(first, expect, rtol=1e-4, atol=1e-4)


GRAD_OPS = sorted(n for n, s in SPECS.items() if s["grad"])

# numeric grad checks that dominate the tier-1 clock (Correlation alone
# is ~1 min; the PR-16 re-profile added the next four, 15-24 s each);
# every op keeps forward coverage in test_forward_shape_and_ref
_SLOW_GRADS = {"Correlation", "InstanceNorm", "BatchNorm",
               "SpatialTransformer", "BilinearSampler"}


@pytest.mark.parametrize(
    "name", [pytest.param(n, marks=pytest.mark.slow) if n in _SLOW_GRADS
             else n for n in GRAD_OPS])
def test_op_gradient(name):
    spec = SPECS[name]
    sym = _sym_for(name, spec)
    if isinstance(sym, (list, tuple)):
        sym = mx.sym.Group(list(sym))
    arg_names = set(sym.list_arguments())
    location = {"x%d" % i: a.copy() for i, a in enumerate(spec["arrays"])
                if "x%d" % i in arg_names}
    grad_nodes = spec["grad_nodes"] or list(location)
    aux = None
    aux_names = sym.list_auxiliary_states()
    if aux_names:
        extra = [a for i, a in enumerate(spec["arrays"])
                 if "x%d" % i not in arg_names]
        aux = dict(zip(aux_names, extra))
    check_numeric_gradient(sym, location, aux_states=aux,
                           numeric_eps=1e-3, rtol=spec["rtol"],
                           atol=spec["atol"],
                           grad_nodes=grad_nodes,
                           use_forward_train=spec["train"])


def test_all_public_ops_covered():
    """Every public registry op must be spec'd here or explicitly
    accounted for — newly added ops cannot land untested."""
    canonical = {get_op(n).name for n in list_ops()
                 if not n.startswith("_") or n.startswith(("_contrib_",
                                                           "_linalg_",
                                                           "_random_",
                                                           "_sample_"))}
    covered = set(SPECS) | set(KNOWN_ELSEWHERE)
    # alias groups count as covered if their canonical name is
    missing = sorted(n for n in canonical if n not in covered)
    assert not missing, "untested public ops: %s" % missing


def test_correlation_subtract_mode():
    """is_multiply=False is the |a-b| cost volume (positive, reference
    correlation-inl.h subtract mode)."""
    a = mx.nd.array(np.ones((1, 1, 3, 3), np.float32))
    b = mx.nd.array(np.zeros((1, 1, 3, 3), np.float32))
    out = mx.nd.Correlation(a, b, kernel_size=1, max_displacement=0,
                            is_multiply=False)
    np.testing.assert_allclose(out.asnumpy(), np.ones((1, 1, 3, 3)))
    out2 = mx.nd.Correlation(a, a, kernel_size=1, max_displacement=0,
                             is_multiply=False)
    np.testing.assert_allclose(out2.asnumpy(), np.zeros((1, 1, 3, 3)))


def test_box_iou_outer_batch_semantics():
    """reference bounding_box.cc: output is lhs.shape[:-1]+rhs.shape[:-1]."""
    rs = np.random.RandomState(0)
    lhs = mx.nd.array(np.abs(rs.rand(2, 3, 4)).cumsum(-1).astype(np.float32))
    rhs = mx.nd.array(np.abs(rs.rand(5, 4)).cumsum(-1).astype(np.float32))
    out = mx.nd.contrib.box_iou(lhs, rhs)
    assert out.shape == (2, 3, 5)
    same = mx.nd.contrib.box_iou(rhs, rhs).asnumpy()
    np.testing.assert_allclose(np.diag(same), np.ones(5), rtol=1e-5)


def test_box_nms_background_and_format():
    data = np.array([
        [0, 0.9, 0.5, 0.5, 1.0, 1.0],    # background (id 0)
        [1, 0.8, 0.5, 0.5, 1.0, 1.0],    # kept (center format)
        [1, 0.7, 0.5, 0.5, 1.0, 1.0],    # suppressed by the one above
    ], np.float32)
    out = mx.nd.contrib.box_nms(
        mx.nd.array(data), overlap_thresh=0.5, coord_start=2, score_index=1,
        id_index=0, background_id=0, in_format="center",
        out_format="corner").asnumpy()
    assert (out[0] == -1).all()          # background dropped
    assert (out[2] == -1).all()          # duplicate suppressed
    np.testing.assert_allclose(out[1, 2:], [0, 0, 1, 1], atol=1e-6)
