"""Optimizer tests vs numpy references (reference test_optimizer.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import optimizer as opt
from mxnet_tpu.test_utils import assert_almost_equal


def _step(optimizer, w0, g0, nsteps=3):
    w = nd.array(w0.copy())
    state = optimizer.create_state(0, w)
    for _ in range(nsteps):
        optimizer.update(0, w, nd.array(g0), state)
    return w.asnumpy()


def test_sgd():
    w0 = np.random.rand(4, 3).astype(np.float32)
    g0 = np.random.rand(4, 3).astype(np.float32)
    got = _step(opt.SGD(learning_rate=0.1, rescale_grad=1.0, wd=0.0), w0, g0, 1)
    assert_almost_equal(got, w0 - 0.1 * g0, rtol=1e-5)


def test_sgd_momentum_wd():
    w0 = np.random.rand(5).astype(np.float32)
    g0 = np.random.rand(5).astype(np.float32)
    lr, mom, wd = 0.1, 0.9, 0.01
    got = _step(opt.SGD(learning_rate=lr, momentum=mom, wd=wd,
                        rescale_grad=1.0), w0, g0, 3)
    w = w0.copy()
    v = np.zeros_like(w)
    for _ in range(3):
        v = mom * v - lr * (g0 + wd * w)
        w = w + v
    assert_almost_equal(got, w, rtol=1e-5)


def test_adam():
    w0 = np.random.rand(6).astype(np.float32)
    g0 = np.random.rand(6).astype(np.float32)
    o = opt.Adam(learning_rate=0.01, rescale_grad=1.0)
    got = _step(o, w0, g0, 2)
    # numpy reference (bias-corrected lr form used by the fused op)
    w = w0.copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    b1, b2, eps = 0.9, 0.999, 1e-8
    for t in range(1, 3):
        lr = 0.01 * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g0
        v = b2 * v + (1 - b2) * g0 * g0
        w = w - lr * m / (np.sqrt(v) + eps)
    assert_almost_equal(got, w, rtol=1e-4)


def test_rmsprop():
    w0 = np.random.rand(4).astype(np.float32)
    g0 = np.random.rand(4).astype(np.float32)
    o = opt.RMSProp(learning_rate=0.01, gamma1=0.9, rescale_grad=1.0)
    got = _step(o, w0, g0, 2)
    w = w0.copy()
    n = np.zeros_like(w)
    for _ in range(2):
        n = 0.1 * g0 * g0 + 0.9 * n
        w = w - 0.01 * g0 / np.sqrt(n + 1e-8)
    assert_almost_equal(got, w, rtol=1e-4)


def test_signum():
    w0 = np.random.rand(4).astype(np.float32)
    g0 = np.random.randn(4).astype(np.float32)
    o = opt.Signum(learning_rate=0.1, momentum=0.0, rescale_grad=1.0, wd=0.0)
    got = _step(o, w0, g0, 1)
    assert_almost_equal(got, w0 - 0.1 * np.sign(g0), rtol=1e-5)


def test_adagrad_adadelta_ftrl_run():
    w0 = np.random.rand(4).astype(np.float32)
    g0 = np.random.rand(4).astype(np.float32)
    for o in [opt.AdaGrad(learning_rate=0.1, rescale_grad=1.0),
              opt.AdaDelta(rescale_grad=1.0),
              opt.Ftrl(rescale_grad=1.0),
              opt.Adamax(rescale_grad=1.0),
              opt.Nadam(rescale_grad=1.0),
              opt.NAG(learning_rate=0.1, momentum=0.9, rescale_grad=1.0),
              opt.FTML(rescale_grad=1.0),
              opt.DCASGD(rescale_grad=1.0),
              opt.SGLD(rescale_grad=1.0)]:
        got = _step(o, w0, g0, 2)
        assert got.shape == w0.shape
        assert not np.allclose(got, w0)  # moved


def test_lr_wd_mult():
    o = opt.SGD(learning_rate=0.1,
                param_idx2name={0: "w_weight", 1: "b_bias"})
    o.set_lr_mult({"w_weight": 2.0})
    o.set_wd_mult({})
    assert o._get_lr(0) == pytest.approx(0.2)
    assert o._get_lr(1) == pytest.approx(0.1)
    # bias gets wd 0 by default naming rule
    assert o._get_wd(1) == 0.0


def test_clip_gradient():
    w0 = np.zeros(3, np.float32)
    g0 = np.array([10.0, -10, 0.1], np.float32)
    o = opt.SGD(learning_rate=1.0, rescale_grad=1.0, clip_gradient=1.0)
    got = _step(o, w0, g0, 1)
    assert_almost_equal(got, -np.array([1.0, -1, 0.1]), rtol=1e-5)


def test_lr_scheduler():
    from mxnet_tpu.lr_scheduler import (FactorScheduler, MultiFactorScheduler,
                                        PolyScheduler)
    s = FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    assert s(1) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25
    m = MultiFactorScheduler(step=[5, 15], factor=0.1)
    m.base_lr = 1.0
    assert m(1) == 1.0
    assert m(6) == pytest.approx(0.1)
    assert m(16) == pytest.approx(0.01)
    p = PolyScheduler(max_update=100, base_lr=1.0, pwr=1)
    assert p(0) == 1.0
    assert p(50) == pytest.approx(0.5)


def test_multi_precision():
    w0 = np.random.rand(4).astype(np.float16)
    g0 = np.random.rand(4).astype(np.float16)
    o = opt.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True,
                rescale_grad=1.0)
    w = nd.array(w0)
    state = o.create_state_multi_precision(0, w)
    assert state[0].dtype == np.float32  # fp32 master weight
    o.update_multi_precision(0, w, nd.array(g0), state)
    assert w.dtype == np.float16


def test_updater_states_roundtrip():
    o = opt.SGD(learning_rate=0.1, momentum=0.9, rescale_grad=1.0)
    u = opt.get_updater(o)
    w = nd.array(np.random.rand(3).astype(np.float32))
    g = nd.array(np.random.rand(3).astype(np.float32))
    u(0, g, w)
    blob = u.get_states()
    u2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9,
                                 rescale_grad=1.0))
    u2.set_states(blob)
    assert 0 in u2.states


def test_adam_clip_after_wd():
    """adam_update applies clip_gradient AFTER adding wd*weight (reference
    optimizer_op-inl.h:773: grad = rescale*grad + wd*weight, then clip)."""
    import numpy as np
    w = mx.nd.array(np.full((4,), 10.0, np.float32))
    g = mx.nd.array(np.zeros((4,), np.float32))
    mean = mx.nd.zeros((4,))
    var = mx.nd.zeros((4,))
    # wd*weight = 1.0 exceeds clip 0.5 even though grad itself is 0:
    # effective g must be clipped to 0.5, not 0 + 1.0
    mx.nd.adam_update(w, g, mean, var, out=w, lr=1.0, wd=0.1,
                      clip_gradient=0.5, beta1=0.0, beta2=0.0, epsilon=0.0)
    # with beta1=beta2=0: mean=g_eff=0.5, var=0.25, step=lr*0.5/0.5=1.0
    np.testing.assert_allclose(w.asnumpy(), np.full((4,), 9.0), rtol=1e-5)


def test_updater_fused_batch_matches_per_param():
    """Updater.update_batch (the one-dispatch Module.fit path) must be
    numerically identical to per-parameter sgd_mom_update calls."""
    rs = np.random.RandomState(0)
    shapes = [(5, 3), (7,), (2, 4, 3)]
    weights_a = [nd.array(rs.rand(*s).astype(np.float32)) for s in shapes]
    weights_b = [w.copy() for w in weights_a]
    grads = [nd.array(rs.rand(*s).astype(np.float32)) for s in shapes]

    def make(lr):
        o = opt.SGD(learning_rate=lr, momentum=0.9, wd=0.01,
                    rescale_grad=1.0 / 8, clip_gradient=0.5)
        return opt.get_updater(o)

    up_a, up_b = make(0.1), make(0.1)
    for step in range(3):
        up_a.update_batch([(i, g, w) for i, (g, w)
                           in enumerate(zip(grads, weights_a))])
        for i, (g, w) in enumerate(zip(grads, weights_b)):
            up_b(i, g, w)
    for wa, wb in zip(weights_a, weights_b):
        assert_almost_equal(wa.asnumpy(), wb.asnumpy(), rtol=1e-5,
                            atol=1e-6)
    # momentum states agree too
    for i in range(len(shapes)):
        assert_almost_equal(up_a.states[i].asnumpy(),
                            up_b.states[i].asnumpy(), rtol=1e-5, atol=1e-6)


def test_updater_fused_batch_falls_back_for_adam():
    rs = np.random.RandomState(1)
    w = nd.array(rs.rand(4, 2).astype(np.float32))
    w_ref = w.copy()
    g = nd.array(rs.rand(4, 2).astype(np.float32))
    up = opt.get_updater(opt.Adam(learning_rate=0.01))
    up_ref = opt.get_updater(opt.Adam(learning_rate=0.01))
    up.update_batch([(0, g, w)])
    up_ref(0, g, w_ref)
    assert_almost_equal(w.asnumpy(), w_ref.asnumpy(), rtol=1e-6)


def test_multi_sgd_lr_schedule_does_not_recompile():
    """lrs/wds are tuple-of-float dynamic params (ops/registry.py): a
    scheduled lr must reuse ONE compiled program across steps instead of
    recompiling the fused multi-tensor update every value change."""
    from mxnet_tpu.ops import registry

    op = registry.get_op("multi_sgd_update")
    fns = []
    for lr in (0.1, 0.05, 0.025):
        attrs = op.parse_attrs(dict(lrs=(lr, lr * 2), wds=(0.0, 1e-4),
                                    num_weights=2))
        fns.append(registry.jitted_apply(op, attrs))
    assert all(f.func is fns[0].func for f in fns), \
        "changing lrs must hit the same jitted closure (traced args)"
    w = nd.ones((3,))._handle
    g = nd.ones((3,))._handle
    new_w = fns[1](w, g, w, g)[0]
    assert_almost_equal(np.asarray(new_w), np.full(3, 1 - 0.05, np.float32),
                        rtol=1e-6)

    mom_op = registry.get_op("multi_mp_sgd_mom_update")
    a1 = mom_op.parse_attrs(dict(lrs=(0.1,), wds=(0.0,), momentum=0.9,
                                 num_weights=1))
    a2 = mom_op.parse_attrs(dict(lrs=(0.2,), wds=(0.0,), momentum=0.9,
                                 num_weights=1))
    f1 = registry.jitted_apply(mom_op, a1)
    f2 = registry.jitted_apply(mom_op, a2)
    assert f1.func is f2.func
