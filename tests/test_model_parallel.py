"""ctx_group model parallelism (reference
tests/python/unittest/test_model_parallel.py + the group2ctxs Module path).

Runs on the 8-device virtual CPU mesh (conftest).  The grouped executor
must place segments on DIFFERENT jax devices and still match the
ungrouped single-device executor bit-for-bit-close, forward and backward.
"""
import numpy as np

import mxnet_tpu as mx


def _reldiff(a, b):
    diff = np.sum(np.abs(a - b))
    norm = np.sum(np.abs(a))
    return 0 if diff == 0 else diff / norm


def test_chain():
    """The reference test_chain scenario: (data1+data2)*3 on dev1,
    +data3 on dev2."""
    ctx1, ctx2 = mx.cpu(0), mx.cpu(1)
    data1 = mx.sym.Variable("data1")
    data2 = mx.sym.Variable("data2")
    data3 = mx.sym.Variable("data3")
    with mx.AttrScope(ctx_group="dev1"):
        net = data1 + data2
        net = net * 3
    with mx.AttrScope(ctx_group="dev2"):
        net = net + data3

    shape = (4, 5)
    arr = [mx.nd.ones(shape), mx.nd.ones(shape) * 2, mx.nd.ones(shape) * 3]
    grad = [mx.nd.empty(shape) for _ in range(3)]
    names = net.list_arguments()
    exec1 = net.bind(ctx1, args=dict(zip(names, arr)),
                     args_grad=dict(zip(names, grad)),
                     group2ctx={"dev1": ctx1, "dev2": ctx2})
    assert exec1._seg is not None and len(exec1._seg.segments) == 2
    d1, d2 = (s.device for s in exec1._seg.segments)
    assert d1 is not d2          # really two devices

    exec2 = net.bind(ctx1, args=dict(zip(names, arr)),
                     args_grad={n: mx.nd.empty(shape) for n in names})
    exec1.forward(is_train=True)
    exec2.forward(is_train=True)
    assert _reldiff(exec1.outputs[0].asnumpy(),
                    exec2.outputs[0].asnumpy()) < 1e-6
    np.testing.assert_allclose(exec1.outputs[0].asnumpy(),
                               (1 + 2) * 3 + 3 * np.ones(shape))

    out_grad = mx.nd.ones(shape) * 0.5
    exec1.backward([out_grad])
    exec2.backward([out_grad])
    for n in names:
        assert _reldiff(exec1.grad_dict[n].asnumpy(),
                        exec2.grad_dict[n].asnumpy()) < 1e-6
    # chain rule: d/d_data1 = 3 * 0.5
    np.testing.assert_allclose(exec1.grad_dict["data1"].asnumpy(),
                               1.5 * np.ones(shape))


def test_module_group2ctxs():
    """Two-stage MLP split across devices, trained via Module; must match
    the single-device module numerically."""
    rs = np.random.RandomState(0)
    x = rs.rand(8, 10).astype(np.float32)
    y = rs.randint(0, 4, (8,)).astype(np.float32)

    def build():
        data = mx.sym.Variable("data")
        with mx.AttrScope(ctx_group="stage1"):
            h = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
            h = mx.sym.Activation(h, act_type="relu")
        with mx.AttrScope(ctx_group="stage2"):
            h = mx.sym.FullyConnected(h, name="fc2", num_hidden=4)
        return mx.sym.SoftmaxOutput(h, name="softmax")

    def run(group2ctxs):
        mod = mx.mod.Module(build(), context=mx.cpu(0),
                            group2ctxs=group2ctxs)
        mod.bind(data_shapes=[("data", x.shape)],
                 label_shapes=[("softmax_label", y.shape)])
        mod.init_params(mx.init.Uniform(0.1), force_init=True)
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        batch = mx.io.DataBatch(data=[mx.nd.array(x)],
                                label=[mx.nd.array(y)])
        for _ in range(3):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    mx.random.seed(7)
    split = run({"stage1": mx.cpu(1), "stage2": mx.cpu(2)})
    mx.random.seed(7)
    single = run(None)
    for k in single:
        np.testing.assert_allclose(split[k], single[k], rtol=2e-5, atol=2e-6)


def test_fanout_across_groups():
    """One value consumed by TWO later segments on different devices —
    backward must accumulate cotangents arriving from different devices."""
    ctx = {"g1": mx.cpu(1), "g2": mx.cpu(2), "g3": mx.cpu(3)}
    x = mx.sym.Variable("x")
    with mx.AttrScope(ctx_group="g1"):
        h = x * 2
    with mx.AttrScope(ctx_group="g2"):
        a = h + 1
    with mx.AttrScope(ctx_group="g3"):
        b = h * h
    out = a + b          # default group: bind device
    shape = (3, 4)
    args = {"x": mx.nd.ones(shape) * 2}
    grads = {"x": mx.nd.empty(shape)}
    ex = out.bind(mx.cpu(0), args=args, args_grad=grads, group2ctx=ctx)
    assert len(ex._seg.segments) >= 3
    ex.forward(is_train=True)
    # h=4; a=5, b=16 -> 21
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), 21 * np.ones(shape))
    ex.backward([mx.nd.ones(shape)])
    # d/dx = 2*(1 + 2h) = 2*9 = 18
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(),
                               18 * np.ones(shape))


def test_group2ctxs_list_values_split_across_replicas():
    """A dict whose values are context LISTS distributes one context per
    data-parallel replica (reference _prepare_group2ctxs); a single Context
    or length-1 list is broadcast to every replica."""
    from mxnet_tpu.module.executor_group import DataParallelExecutorGroup
    prep = DataParallelExecutorGroup._prepare_group2ctxs
    c = [mx.cpu(i) for i in range(8)]
    out = prep({"a": [c[2], c[3]], "b": c[4], "c": [c[5]]}, 2)
    assert out == [{"a": c[2], "b": c[4], "c": c[5]},
                   {"a": c[3], "b": c[4], "c": c[5]}]
    # wrong lengths must fail loudly, not crash later in group_devices
    import pytest
    with pytest.raises(ValueError):
        prep({"a": [c[0], c[1], c[2]]}, 2)
    with pytest.raises(ValueError):
        prep([{"a": c[0]}], 2)

    # end-to-end: 2 DP replicas, each stage pinned per-replica
    rs = np.random.RandomState(3)
    x = rs.rand(8, 10).astype(np.float32)
    y = rs.randint(0, 4, (8,)).astype(np.float32)
    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="stage1"):
        h = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    net = mx.sym.SoftmaxOutput(h, name="softmax")
    mod = mx.mod.Module(net, context=[mx.cpu(0), mx.cpu(1)],
                        group2ctxs={"stage1": [mx.cpu(2), mx.cpu(3)]})
    mod.bind(data_shapes=[("data", x.shape)],
             label_shapes=[("softmax_label", y.shape)])
    mod.init_params(mx.init.Uniform(0.1), force_init=True)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    assert all(np.isfinite(v.asnumpy()).all()
               for v in mod.get_params()[0].values())


def test_integer_boundary_cotangent():
    """An integer-dtype value crossing a segment boundary: backward must
    seed a float0 cotangent for it (jax.vjp requirement), not a dtype
    error (advisor r2 placement.py:249)."""
    ctx = {"g1": mx.cpu(1), "g2": mx.cpu(2)}
    x = mx.sym.Variable("x")
    with mx.AttrScope(ctx_group="g1"):
        h = x * 2
        i = mx.sym.cast(x, dtype="int32")
    with mx.AttrScope(ctx_group="g2"):
        out = h + mx.sym.cast(i, dtype="float32")
    shape = (3, 4)
    args = {"x": mx.nd.ones(shape) * 1.5}
    grads = {"x": mx.nd.empty(shape)}
    ex = out.bind(mx.cpu(0), args=args, args_grad=grads, group2ctx=ctx)
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), 4 * np.ones(shape))
    ex.backward([mx.nd.ones(shape)])
    # cast-to-int contributes no gradient; d/dx = 2
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(),
                               2 * np.ones(shape))


def test_disconnected_arg_gets_zero_grad_segmented():
    """grad_req='write' arg whose path to the loss is blocked: the
    segmented path must write zeros (matching _jit_fwd_bwd), not leave the
    uninitialized buffer (advisor r2 executor.py:374)."""
    ctx = {"g1": mx.cpu(1), "g2": mx.cpu(2)}
    x = mx.sym.Variable("x")
    w = mx.sym.Variable("w")
    with mx.AttrScope(ctx_group="g1"):
        h = x * 3
        dead = mx.sym.BlockGrad(w)
    with mx.AttrScope(ctx_group="g2"):
        out = h + dead
    shape = (2, 3)
    args = {"x": mx.nd.ones(shape), "w": mx.nd.ones(shape)}
    grads = {"x": mx.nd.empty(shape), "w": mx.nd.full(shape, 7.0)}
    ex = out.bind(mx.cpu(0), args=args, args_grad=grads, group2ctx=ctx)
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones(shape)])
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), 3 * np.ones(shape))
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(), np.zeros(shape))
