"""Compile-time plane tests: the persistent executable cache, the warm
standby pre-compiler, corruption quarantine, and the promoted
compile_seconds benchwatch gate (ROADMAP item 5 / PR 13).

The acceptance-level facts proven here at unit scale (the 4-proc drill
in tests/dist/dist_elastic_resize.py proves them across real process
relaunches):

* a second trainer of the same program deserializes a warm executable
  (``result=hit``) and its numerics are BIT-identical to the cold run;
* a standby pre-compile at world N makes the first step of a world-N−1
  trainer warm — zero compilation where the elastic resume would pay it;
* a corrupted cache entry (chaos ``corrupt_compile_cache``) quarantines
  and falls back to a fresh compile — never a crash, never a stale or
  wrong executable (donated programs are refused on backends whose
  deserialize path would mis-execute them);
* a compile-time IMPROVEMENT can never read as a benchwatch regression,
  a compile-time blow-up fails the gate.
"""
import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import compile as cc
from mxnet_tpu.compile import cache as cache_mod
from mxnet_tpu.compile import paths as paths_mod
from mxnet_tpu.compile import treedefs
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.parallel.trainer import ShardedTrainer
from mxnet_tpu.resilience import chaos, elastic
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_cache_state():
    yield
    cache_mod.reset()
    chaos.reset()
    telemetry.reset()


@pytest.fixture
def armed(tmp_path):
    d = str(tmp_path / "ccache")
    cc.arm(d)
    return d


def _mlp():
    d = mx.sym.Variable("data")
    f1 = mx.sym.FullyConnected(d, num_hidden=8, name="fc1")
    a = mx.sym.Activation(f1, act_type="relu")
    f2 = mx.sym.FullyConnected(a, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(f2, name="softmax")


def _trainer(n_dev=2, accum=1):
    spec = MeshSpec(make_mesh((n_dev,), ("dp",),
                              devices=jax.devices()[:n_dev]))
    tr = ShardedTrainer(_mlp(), spec, lr=0.01, momentum=0.9, wd=0.0,
                        grad_accum=accum)
    p, m, a = tr.init_state({"data": (12 // accum, 4),
                             "softmax_label": (12 // accum,)}, seed=3)
    return tr, p, m, a


def _batches(n, rows=12):
    rs = np.random.RandomState(0)
    return [{"data": rs.randn(rows, 4).astype(np.float32),
             "softmax_label": (rs.rand(rows) > .5).astype(np.float32)}
            for _ in range(n)]


def _train(n_dev=2, accum=1, steps=2):
    tr, p, m, a = _trainer(n_dev, accum)
    for b in _batches(steps):
        p, m, a, loss = tr.step(p, m, a, b)
    return tr, [np.asarray(x).copy() for x in p]


def _last_result(name="train_step"):
    ev = [e for e in tracing._COMPILES_LOCK_FREE if e["name"] == name]
    return ev[-1].get("result") if ev else None


# ---------------------------------------------------------------------------
# treedef codec + path helper
# ---------------------------------------------------------------------------

def test_treedef_codec_roundtrip():
    for template in (0,
                     (0, 0),
                     ((0,), [0, 0], {"b": 0, "a": (0, None)}),
                     {"x": [{"y": (0,)}, None]}):
        td = jax.tree_util.tree_structure(template)
        assert treedefs.obj_to_treedef(treedefs.treedef_to_obj(td)) == td


def test_treedef_codec_rejects_custom_nodes():
    import collections
    Point = collections.namedtuple("Point", "x y")
    td = jax.tree_util.tree_structure(Point(0, 0))
    with pytest.raises(treedefs.UnsupportedTreedef):
        treedefs.treedef_to_obj(td)


def test_cache_location_convention(monkeypatch):
    # default: under ~/.cache/mxnet_tpu
    monkeypatch.delenv("MXNET_TPU_TESTX_CACHE", raising=False)
    loc = paths_mod.cache_location("MXNET_TPU_TESTX_CACHE", "x.json")
    assert loc == os.path.join(paths_mod.cache_root(), "x.json")
    # explicit path wins
    monkeypatch.setenv("MXNET_TPU_TESTX_CACHE", "/tmp/elsewhere.json")
    assert paths_mod.cache_location("MXNET_TPU_TESTX_CACHE",
                                    "x.json") == "/tmp/elsewhere.json"
    # "1" means "on, default location"; "0" means disabled
    monkeypatch.setenv("MXNET_TPU_TESTX_CACHE", "1")
    assert paths_mod.cache_location(
        "MXNET_TPU_TESTX_CACHE", "x.json") == os.path.join(
        paths_mod.cache_root(), "x.json")
    monkeypatch.setenv("MXNET_TPU_TESTX_CACHE", "0")
    assert paths_mod.cache_location("MXNET_TPU_TESTX_CACHE",
                                    "x.json") is None
    # the autotuner rides the same helper (the dedupe satellite)
    from mxnet_tpu.ops import autotune
    monkeypatch.setenv("MXNET_TPU_AUTOTUNE_CACHE", "/tmp/at.json")
    assert autotune.cache_path() == "/tmp/at.json"
    monkeypatch.delenv("MXNET_TPU_AUTOTUNE_CACHE")
    assert autotune.cache_path().startswith(paths_mod.cache_root())


# ---------------------------------------------------------------------------
# the cache itself
# ---------------------------------------------------------------------------

def _toy_lowered(scale=0.1):
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    rep, bat = NamedSharding(mesh, P()), NamedSharding(mesh, P("dp"))

    def step(w, x):
        return w - scale * jnp.mean(x @ w, axis=0)

    jitted = jax.jit(step, in_shardings=(rep, bat), out_shardings=rep)
    return jitted.lower(jax.ShapeDtypeStruct((8, 8), jnp.float32),
                        jax.ShapeDtypeStruct((4, 8), jnp.float32)), mesh


def test_cache_miss_store_hit_and_run(armed):
    telemetry.arm()
    low, mesh = _toy_lowered()
    c1, r1 = cc.cached_compile(low, "toy", mesh=mesh)
    assert r1 == "miss"
    assert cc.cache_stats()["entries"] == 1
    low2, _ = _toy_lowered()
    c2, r2 = cc.cached_compile(low2, "toy", mesh=mesh)
    assert r2 == "hit"
    rep, bat = (NamedSharding(mesh, P()), NamedSharding(mesh, P("dp")))
    w = jax.device_put(np.eye(8, dtype=np.float32), rep)
    x = jax.device_put(np.ones((4, 8), np.float32), bat)
    np.testing.assert_array_equal(np.asarray(c1(w, x)),
                                  np.asarray(c2(w, x)))
    hits = telemetry.counter_total("compile.cache", result="hit")
    assert hits == 1.0


def test_cache_key_separates_call_sites(armed):
    low, mesh = _toy_lowered()
    cc.cached_compile(low, "siteA", mesh=mesh)
    low2, _ = _toy_lowered()
    _, r = cc.cached_compile(low2, "siteB", mesh=mesh)
    assert r == "miss"          # same text, different `what` -> own entry
    assert cc.cache_stats()["entries"] == 2


@pytest.mark.parametrize("mode", ["garbage", "truncate"])
def test_corrupt_entry_quarantines_and_falls_back(armed, mode):
    telemetry.arm()
    low, mesh = _toy_lowered()
    cc.cached_compile(low, "toy", mesh=mesh)
    low2, _ = _toy_lowered()
    with chaos.inject("corrupt_compile_cache", mode=mode):
        c, r = cc.cached_compile(low2, "toy", mesh=mesh)
    assert r == "miss"          # fallback compile, never a crash
    assert c is not None
    stats = cc.cache_stats()
    assert stats["quarantined"] == 1
    assert stats["entries"] == 1        # the fallback wrote a fresh entry
    assert telemetry.counter_total("compile.cache", result="corrupt") == 1.0
    # and the fresh entry is loadable again
    low3, _ = _toy_lowered()
    _, r3 = cc.cached_compile(low3, "toy", mesh=mesh)
    assert r3 == "hit"


def test_callback_programs_never_stored(armed):
    def cb(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct((4,), np.float32), x)

    low = jax.jit(cb).lower(jax.ShapeDtypeStruct((4,), jnp.float32))
    _, r = cc.cached_compile(low, "cb")
    assert r == "miss"
    assert cc.cache_stats()["entries"] == 0     # refused: result stays miss
    low2 = jax.jit(cb).lower(jax.ShapeDtypeStruct((4,), jnp.float32))
    _, r2 = cc.cached_compile(low2, "cb")
    assert r2 == "miss"


def test_donated_programs_refused_on_cpu(armed):
    """The reason the trainer builds donation-free under the cache on
    CPU: a DESERIALIZED executable with donated (aliased) inputs
    mis-executes there, so the cache must refuse to persist one."""
    assert not cc.donation_safe()       # this suite runs on XLA:CPU
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    rep = NamedSharding(mesh, P())

    def step(w):
        return w * 2.0

    jitted = jax.jit(step, in_shardings=(rep,), out_shardings=rep,
                     donate_argnums=(0,))
    low = jitted.lower(jax.ShapeDtypeStruct((8, 8), jnp.float32))
    assert "tf.aliasing_output" in low.as_text()
    _, r = cc.cached_compile(low, "donated", mesh=mesh)
    assert r == "miss"
    assert cc.cache_stats()["entries"] == 0


# ---------------------------------------------------------------------------
# trainer integration
# ---------------------------------------------------------------------------

def test_trainer_warm_start_bit_identical(armed):
    _, ref = _train()                   # cold: miss + write-through
    assert _last_result() == "miss"
    _, warm = _train()                  # same program: hit
    assert _last_result() == "hit"
    for x, y in zip(ref, warm):
        np.testing.assert_array_equal(x, y)
    cc.disarm()
    cache_mod.reset()
    _, plain = _train()                 # cache off: the stock jit path
    assert _last_result() == "off"
    for x, y in zip(ref, plain):
        np.testing.assert_array_equal(x, y)


def test_trainer_chaos_corrupt_cache_drill(armed):
    """End-to-end through ShardedTrainer.step: a corrupted entry is
    quarantined, the step falls back to a fresh compile, training
    continues, and the counter proves which path ran."""
    telemetry.arm()
    _train()
    with chaos.inject("corrupt_compile_cache", mode="garbage"):
        _, p = _train()
    assert _last_result() == "miss"
    assert all(np.isfinite(x).all() for x in p)
    assert telemetry.counter_total("compile.cache", result="corrupt") == 1.0
    assert cc.cache_stats()["quarantined"] == 1


def test_standby_warms_smaller_world(armed):
    """The elastic shape at unit scale: a 4-device trainer pre-compiles
    the 3-device step program in the background; the real 3-device
    trainer's first step deserializes it — zero compilation where the
    resize drill would pay one."""
    tr, p, m, a = _trainer(4)
    jobs = cc.trainer_standby_jobs(
        tr, (p, m, a), [(3, 1)],
        {"data": (12, 4), "softmax_label": (12,)})
    comp = cc.StandbyCompiler(jobs).start()
    assert comp.wait(120)
    res = comp.results()["world3"]
    assert res["result"] == "standby", res
    _, warm = _train(n_dev=3)
    assert _last_result() == "hit"
    # the warm resized run must match a cold resized run bit-for-bit
    cc.disarm()
    cache_mod.reset()
    _, cold = _train(n_dev=3)
    for x, y in zip(warm, cold):
        np.testing.assert_array_equal(x, y)


def test_standby_grad_accum_variant_and_infeasible(armed):
    """Candidates carry their own grad-accum (the global-batch-constant
    rule); worlds needing more devices than visible are reported, not
    attempted."""
    tr, p, m, a = _trainer(4, accum=1)
    jobs = cc.trainer_standby_jobs(
        tr, (p, m, a), [(3, 2), (64, 1)],
        {"data": (12, 4), "softmax_label": (12,)})
    comp = cc.StandbyCompiler(jobs).start()
    assert comp.wait(120)
    res = comp.results()
    assert res["world3"]["result"] == "standby"
    assert res["world64"]["result"] == "unavailable"
    # the warmed program IS the accum-2 resized trainer's program
    _, _ = _train(n_dev=3, accum=2)
    assert _last_result() == "hit"


def test_elastic_coordinator_standby_and_manifest(armed, tmp_path):
    """ElasticCoordinator.enable_standby pre-compiles the N−1 world and
    the resize manifest records what is warm (the satellite: 'manifest
    records the pre-compiled generation')."""
    # micro 1 × world 4 × accum 3 = global batch 12; at world 3 the
    # standby keeps it constant with accum 4 (the elastic rule)
    tr, p, m, a = _trainer(4, accum=3)
    exits = []
    coord = elastic.ElasticCoordinator(
        manager=None, trainer=tr, rank=0, world=4, capacity=4,
        min_workers=3, elastic_dir=str(tmp_path), check_interval=0.0,
        on_exit=exits.append, register=False)
    sb = coord.enable_standby(
        (p, m, a), micro_batch=1,
        batch_shapes={"data": (12, 4), "softmax_label": (12,)},
        wait=True, timeout=120)
    assert sb is not None and sb.done
    report = coord.standby_report()
    assert report["complete"]
    assert report["worlds"]["world3"]["result"] in ("standby", "hit")
    assert report["cache_dir"] == cc.cache_dir()
    # a resize writes the standby report into the manifest
    assert coord.resign("test_resize", target_world=3, step=7)
    assert exits == [coord.exit_code]
    manifest = elastic.read_manifest(str(tmp_path), 1)
    assert manifest is not None
    pre = manifest.get("precompiled")
    assert pre and pre["worlds"]["world3"]["result"] in ("standby", "hit")


def test_standby_noop_when_disarmed(tmp_path):
    tr, p, m, a = _trainer(2)
    coord = elastic.ElasticCoordinator(
        manager=None, trainer=tr, rank=0, world=2, min_workers=1,
        elastic_dir=str(tmp_path), on_exit=lambda c: None, register=False)
    assert coord.enable_standby(
        (p, m, a), micro_batch=6,
        batch_shapes={"data": (12, 4), "softmax_label": (12,)}) is None
    assert coord.standby_report() is None


# ---------------------------------------------------------------------------
# autotune write-through (trials share the cache)
# ---------------------------------------------------------------------------

def test_autotune_trials_write_through_cache(armed, tmp_path, monkeypatch):
    from mxnet_tpu.ops import autotune
    monkeypatch.setenv("MXNET_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "at.json"))
    autotune.invalidate()

    def lower(cand):
        def f(x):
            return x * float(cand)
        return jax.jit(f).lower(jax.ShapeDtypeStruct((8,), jnp.float32))

    calls = []

    def measure(cand, compiled):
        calls.append(cand)
        out = compiled(jnp.ones((8,), jnp.float32))
        jax.block_until_ready(out)
        return 1.0 if cand == 2 else 2.0

    win = autotune.autotune("cc_trial", ("sig",), [1, 2], measure,
                            force=True, lower=lower)
    assert win == 2 and calls == [1, 2]
    assert cc.cache_stats()["entries"] == 2     # both trials persisted
    # a re-tune of the same candidates compiles nothing
    autotune.invalidate()
    os.unlink(str(tmp_path / "at.json"))
    telemetry.arm()
    win2 = autotune.autotune("cc_trial", ("sig",), [1, 2], measure,
                             force=True, lower=lower)
    assert win2 == 2
    assert telemetry.counter_total("compile.cache", result="hit") == 2.0


# ---------------------------------------------------------------------------
# benchwatch: compile_seconds is a gated, lower-is-better metric
# ---------------------------------------------------------------------------

def _benchwatch():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import benchwatch
    return benchwatch


def test_benchwatch_compile_seconds_gate():
    bw = _benchwatch()
    assert bw.lower_is_better("compile_seconds")
    assert bw.lower_is_better("transformer_compile_seconds")
    assert not bw.lower_is_better("resnet50_train_img_per_sec_per_chip")
    # an IMPROVEMENT (75s -> 2s after the cache landed) never regresses
    r = bw.check_series([75.0, 71.0, 74.0, 2.1], lower=True)
    assert r["checked"] and not r["regression"]
    # a blow-up fails the gate
    r = bw.check_series([75.0, 71.0, 74.0, 2.1, 90.0], lower=True)
    assert r["regression"]
    # the same series through the higher-is-better path would have
    # called the improvement a 97% "drop" — the inversion is the point
    r = bw.check_series([75.0, 71.0, 74.0, 2.1], lower=False)
    assert r["regression"]


def test_benchwatch_extracts_and_merges_compile_seconds(tmp_path):
    bw = _benchwatch()
    doc = {"metric": "resnet", "value": 100.0,
           "phases": {"compile_seconds": 42.5, "peak_hbm_bytes": 1000},
           "transformer": {"metric": "transformer", "value": 5.0,
                           "phases": {"compile_seconds": 7.25}}}
    metrics = bw.extract_metrics(doc)
    assert metrics["compile_seconds"] == 42.5
    assert metrics["transformer_compile_seconds"] == 7.25
    assert "compile_seconds" not in bw.extract_extra(doc)
    # legacy rounds that recorded compile_seconds as an ungated extra
    # feed the same gated series
    ledger = str(tmp_path / "ledger.jsonl")
    bw.append_entry(ledger, {"resnet": 100.0},
                    extra={"compile_seconds": 70.0})
    bw.append_entry(ledger, {"resnet": 101.0},
                    extra={"compile_seconds": 72.0})
    bw.append_entry(ledger, {"resnet": 99.5, "compile_seconds": 2.0})
    entries = bw.read_ledger(ledger)
    series = bw.metric_series(entries)
    assert series["compile_seconds"] == [70.0, 72.0, 2.0]
    ok, results = bw.check_ledger(entries)
    assert ok, results                   # the improvement gates green
    bw.append_entry(ledger, {"resnet": 100.0, "compile_seconds": 95.0})
    ok, results = bw.check_ledger(bw.read_ledger(ledger))
    assert not ok and results["compile_seconds"]["regression"]


def test_benchwatch_single_excursion_uses_floor_band():
    """One bad round in an otherwise-flat history used to widen the σ
    band to 4x its own drawdown and wave the next regression through;
    a single excursion now contributes no σ and the 5% floor gates."""
    bw = _benchwatch()
    assert bw.drawdown_sigma([100.0, 60.0]) == 0.0
    assert bw.rise_sigma([60.0, 100.0]) == 0.0
    # flat-then-drop: the 5% floor (not a self-sized band) catches it
    r = bw.check_series([100.0, 100.0, 92.0])
    assert r["checked"] and r["regression"]
    assert r["band_basis"] == "floor"
    # a genuinely noisy series still gets the wider σ band
    noisy = bw.check_series([100.0, 80.0, 110.0, 75.0, 105.0, 75.0])
    assert noisy["band_basis"] == "sigma"
    assert not noisy["regression"]
    # the too-short series contract is unchanged (and basis-free)
    assert bw.check_series([1.0]) == {"checked": False,
                                      "regression": False, "n": 1}


def test_committed_ledger_still_green():
    bw = _benchwatch()
    ok, results = bw.check_ledger(bw.read_ledger(
        os.path.join(REPO, "PERF_LEDGER.jsonl")))
    assert ok, results


# ---------------------------------------------------------------------------
# serving artifacts: per-topology blobs + warm swap
# ---------------------------------------------------------------------------

def _export_artifact(path):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                name="out")
    ex = net.simple_bind(mx.cpu(), data=(4, 3))
    rs = np.random.RandomState(0)
    for arr in ex.arg_arrays:
        arr[:] = mx.nd.array(rs.normal(0, 0.3, arr.shape))
    ex.export_compiled(path, input_names=("data",))
    return path


def test_artifact_append_topology_and_warm_load(tmp_path):
    from mxnet_tpu import deploy
    from mxnet_tpu.resilience.container import read_container
    path = _export_artifact(str(tmp_path / "m.mxt"))
    _, meta, _ = read_container(path)
    fp = deploy.device_fingerprint()
    assert meta["topologies"] == {fp: "executable"}
    prog = deploy.ServedProgram.load(path)
    assert prog.load_result == "hit"    # exact AOT match = warm load
    # re-export with append=True: same topology replaces its own blob,
    # schema/weights verified, still one artifact
    _export_artifact_append(path)
    _, meta2, blobs2 = read_container(path)
    assert meta2["topologies"] == {fp: "executable"}
    prog2 = deploy.ServedProgram.load(path)
    out1 = prog.forward(data=np.ones((4, 3), np.float32))
    out2 = prog2.forward(data=np.ones((4, 3), np.float32))
    np.testing.assert_allclose(out1[0], out2[0])
    # a foreign-topology-only artifact refuses with the fingerprints
    from mxnet_tpu.resilience.container import write_container
    arrays, meta3, blobs3 = read_container(path)
    meta3 = dict(meta3)
    meta3["topologies"] = {"tpu|TPU v99|256": "executable"}
    wrong = str(tmp_path / "wrong.mxt")
    write_container(wrong, arrays=arrays, meta=meta3, blobs=blobs3)
    with pytest.raises(deploy.TopologyMismatch, match="TPU v99"):
        deploy.ServedProgram.load(wrong)


def _export_artifact_append(path):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                name="out")
    ex = net.simple_bind(mx.cpu(), data=(4, 3))
    rs = np.random.RandomState(0)
    for arr in ex.arg_arrays:
        arr[:] = mx.nd.array(rs.normal(0, 0.3, arr.shape))
    ex.export_compiled(path, input_names=("data",), append=True)


def test_artifact_append_refuses_different_weights(tmp_path):
    from mxnet_tpu.base import MXNetError
    path = _export_artifact(str(tmp_path / "m.mxt"))
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                name="out")
    ex = net.simple_bind(mx.cpu(), data=(4, 3))
    for arr in ex.arg_arrays:
        arr[:] = mx.nd.ones(arr.shape)          # different weights
    with pytest.raises(MXNetError, match="refusing to mix"):
        ex.export_compiled(path, input_names=("data",), append=True)


def test_runtime_prewarm_then_warm_swap():
    from mxnet_tpu.serving.replica import SyntheticProgram
    from mxnet_tpu.serving.runtime import ServingRuntime
    rt = ServingRuntime(SyntheticProgram(batch=4, features=3, scale=1.0),
                        linger=0.001)
    try:
        v2 = SyntheticProgram(batch=4, features=3, scale=2.0)
        rt.prewarm(v2, key="v2")
        assert rt.stats()["counters"]["prewarms"] == 1
        # old model still serving after prewarm
        out = rt.predict(data=np.ones((1, 3), np.float32), deadline=5.0)
        assert float(out[0][0][0]) == pytest.approx(1.0)
        # warm swap: flips the prewarmed standby, no revalidation
        rt.swap(v2, prewarmed="v2")
        c = rt.stats()["counters"]
        assert c["swaps"] == 1 and c["swaps_warm"] == 1
        out = rt.predict(data=np.ones((1, 3), np.float32), deadline=5.0)
        assert float(out[0][0][0]) == pytest.approx(2.0)
        # a key mismatch falls back to the validated cold path
        v3 = SyntheticProgram(batch=4, features=3, scale=3.0)
        rt.swap(v3, prewarmed="not-the-key")
        c = rt.stats()["counters"]
        assert c["swaps"] == 2 and c["swaps_warm"] == 1
    finally:
        rt.close()


def test_prewarm_rejects_bad_model_before_any_drain():
    from mxnet_tpu.serving.errors import SwapFailed
    from mxnet_tpu.serving.replica import SyntheticProgram
    from mxnet_tpu.serving.runtime import ServingRuntime
    rt = ServingRuntime(SyntheticProgram(batch=4, features=3, scale=1.0),
                        linger=0.001)
    try:
        bad = SyntheticProgram(batch=4, features=3, scale=float("nan"))
        with pytest.raises(SwapFailed, match="non-finite"):
            rt.prewarm(bad, key="bad")
        out = rt.predict(data=np.ones((1, 3), np.float32), deadline=5.0)
        assert float(out[0][0][0]) == pytest.approx(1.0)
        assert rt.stats()["counters"]["swap_failures"] == 1
    finally:
        rt.close()


# ---------------------------------------------------------------------------
# tooling: postmortem --compile + tracewatch --check over compile sinks
# ---------------------------------------------------------------------------

def test_compile_spans_land_in_trace_sink_and_tools(armed, tmp_path,
                                                    monkeypatch):
    """A traced run leaves compile/* root spans in the flight recorder;
    tracewatch --check passes over them (no orphans) and postmortem
    --compile renders the timeline with hit/miss tags + cache stats."""
    sink_dir = str(tmp_path / "sinks")
    os.makedirs(sink_dir)
    monkeypatch.setenv("MXNET_TPU_TRACE_DIR", sink_dir)
    tracing.reset()
    tracing.arm()
    try:
        _train()                         # miss
        _train()                         # hit
    finally:
        tracing.reset()
    sinks = glob.glob(os.path.join(sink_dir, "trace-*.jsonl"))
    assert sinks
    spans = [json.loads(line) for p in sinks for line in open(p)
             if line.strip()]
    compile_spans = [s for s in spans
                     if s["name"].startswith("compile/train_step")]
    results = [s.get("attrs", {}).get("result") for s in compile_spans]
    assert "miss" in results and "hit" in results

    # tracewatch --check: merged, orphan-free, exit 0
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tracewatch.py"),
         sink_dir, "--check", "--out", str(tmp_path / "merged.json")],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]

    # postmortem --compile renders the timeline + cache stats
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "postmortem.py"),
         sink_dir, "--compile", "--cache-dir", cc.cache_dir()],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "COMPILE TIMELINE" in out.stdout
    assert "hit" in out.stdout and "miss" in out.stdout
    assert "CACHE" in out.stdout and "quarantined" in out.stdout


def test_compile_summary_by_result(armed):
    tracing.reset()
    _train()
    _train()
    summary = tracing.compile_summary()
    assert summary["by_result"].get("miss", 0) >= 1
    assert summary["by_result"].get("hit", 0) >= 1
    assert summary["total_seconds"] > 0
