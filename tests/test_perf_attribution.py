"""Performance attribution plane + bench regression gate.

Covers the ISSUE-6 acceptance surface:

* analytic FLOPs/bytes (analysis/costmodel.py) validated against XLA's
  own ``Compiled.cost_analysis()`` within 5% on seeded programs
  (matmul, conv, psum);
* collective accounting + the static collective/compute overlap
  instrument (including the audit_report line the dp8 dryrun prints);
* attribution reports end to end: toy jitted ShardedTrainer step smoke
  (tier-1), report schema/pretty/Perfetto counters, bench phases block;
* tools/benchwatch.py: gate unit-tested on synthetic trajectories
  (injected 10% regression caught, sigma-level jitter passes) and
  ``--check`` green on the committed PERF_LEDGER.jsonl (the real
  r01→r05 trajectory);
* tools/metricsdump.py follow mode surviving truncation and rotation;
* ServingRuntime.stats() device-utilization ratio.
"""
import importlib.util
import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu  # noqa: F401
from mxnet_tpu.analysis import costmodel
from mxnet_tpu.telemetry import perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _hlo_flops(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return float(ca["flops"])


# ---------------------------------------------------------------------------
# analytic model vs XLA cost analysis (the 5% acceptance gate)
# ---------------------------------------------------------------------------

def test_analytic_flops_matmul_within_5pct():
    c = jax.jit(lambda a, b: a @ b).lower(
        jnp.ones((256, 512), jnp.float32),
        jnp.ones((512, 128), jnp.float32)).compile()
    analytic = costmodel.analytic_flops(c.as_text())["flops"]
    assert analytic == pytest.approx(2 * 256 * 512 * 128, rel=0.01)
    assert analytic == pytest.approx(_hlo_flops(c), rel=0.05)


def test_analytic_flops_conv_within_5pct():
    # strided SAME conv: exercises the padded-border and window-stride
    # discounts in the per-dim valid-tap count
    def conv(x, w):
        return jax.lax.conv_general_dilated(x, w, (2, 2), "SAME")
    c = jax.jit(conv).lower(
        jnp.ones((8, 16, 32, 32), jnp.float32),
        jnp.ones((32, 16, 3, 3), jnp.float32)).compile()
    analytic = costmodel.analytic_flops(c.as_text())["flops"]
    assert analytic == pytest.approx(_hlo_flops(c), rel=0.05)


def test_analytic_flops_conv_backward_dilated():
    # the gradient of a strided conv lowers with lhs_dilate: the zero
    # holes must be discounted or ResNet backward overcounts ~4x
    def loss(x, w):
        y = jax.lax.conv_general_dilated(x, w, (2, 2), "SAME")
        return jnp.sum(y * y)
    c = jax.jit(jax.grad(loss, argnums=(0, 1))).lower(
        jnp.ones((4, 8, 16, 16), jnp.float32),
        jnp.ones((16, 8, 3, 3), jnp.float32)).compile()
    analytic = costmodel.analytic_flops(c.as_text())["flops"]
    assert analytic == pytest.approx(_hlo_flops(c), rel=0.05)


def _psum_compiled():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    try:
        from jax import shard_map
        smap = lambda f, mesh: shard_map(  # noqa: E731
            f, mesh=mesh, in_specs=P("dp"), out_specs=P())
    except ImportError:
        from jax.experimental.shard_map import shard_map
        smap = lambda f, mesh: shard_map(  # noqa: E731
            f, mesh=mesh, in_specs=P("dp"), out_specs=P())
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))

    def f(x):
        return jax.lax.psum(x * 2.0, "dp")

    x = jax.device_put(jnp.ones((8, 1024), jnp.float32),
                       NamedSharding(mesh, P("dp")))
    return jax.jit(smap(f, mesh)).lower(x).compile()


def test_analytic_psum_bytes_and_flops():
    c = _psum_compiled()
    txt = c.as_text()
    from mxnet_tpu.parallel.audit import collective_accounting
    acct = collective_accounting(txt)
    # per-device shard is (1, 1024) f32 -> 4096B all-reduce payload
    assert acct["all-reduce"]["bytes"] == 4096
    assert costmodel.analytic_flops(txt)["flops"] == pytest.approx(
        _hlo_flops(c), rel=0.05)


def test_instruction_bytes_and_contributors():
    c = jax.jit(lambda a, b: (a @ b).astype(jnp.bfloat16)).lower(
        jnp.ones((64, 64), jnp.float32),
        jnp.ones((64, 64), jnp.float32)).compile()
    per_class = costmodel.instruction_bytes(c.as_text())
    split = costmodel.bytes_by_dtype(per_class)
    assert split.get("f32", 0) > 0 and split.get("bf16", 0) > 0
    top = costmodel.top_contributors(per_class, n=3)
    assert top and top[0]["bytes"] >= top[-1]["bytes"]
    assert {"op", "dtype", "bytes"} <= set(top[0])


# ---------------------------------------------------------------------------
# collective/compute overlap instrument
# ---------------------------------------------------------------------------

SYNC_HLO = """
ENTRY %main (p0: f32[1024]) -> f32[1024] {
  %p0 = f32[1024]{0} parameter(0)
  %ar = f32[1024]{0} all-reduce(f32[1024]{0} %p0), replica_groups={}
  ROOT %out = f32[1024]{0} add(f32[1024]{0} %ar, f32[1024]{0} %ar)
}
"""

ASYNC_HLO = """
ENTRY %main (p0: f32[1024]) -> f32[1024] {
  %p0 = f32[1024]{0} parameter(0)
  %ar-start = f32[1024]{0} all-reduce-start(f32[1024]{0} %p0), replica_groups={}
  %w = f32[1024]{0} multiply(f32[1024]{0} %p0, f32[1024]{0} %p0)
  %ar-done = f32[1024]{0} all-reduce-done(f32[1024]{0} %ar-start)
  ROOT %out = f32[1024]{0} add(f32[1024]{0} %ar-done, f32[1024]{0} %w)
}
"""


PIPELINED_SYNC_HLO = """
ENTRY %main (p0: f32[1024], q0: f32[64,64]) -> f32[1024] {
  %p0 = f32[1024]{0} parameter(0)
  %q0 = f32[64,64]{1,0} parameter(1)
  %ar = f32[1024]{0} all-reduce(f32[1024]{0} %p0), replica_groups={}
  %mm = f32[64,64]{1,0} dot(f32[64,64]{1,0} %q0, f32[64,64]{1,0} %q0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %out = f32[1024]{0} add(f32[1024]{0} %ar, f32[1024]{0} %ar)
}
"""


def test_overlap_sync_is_zero():
    """A sync collective whose only neighbors are its own producers and
    consumers (no independent heavy compute) cannot be hidden by any
    scheduler: 0%."""
    ov = costmodel.collective_compute_overlap(SYNC_HLO)
    assert ov["collective_bytes"] == 4096
    assert ov["overlap_pct"] == 0.0
    assert ov["sync_ops"] == 1 and ov["async_ops"] == 0
    assert ov["pipelined_ops"] == 0


def test_overlap_pipelined_sync_counts():
    """r6 extension: a sync collective with an independent dot in the
    same computation is schedulable overlap — backends with async
    collectives (TPU) hide it; the CPU dryrun proves the schedule."""
    ov = costmodel.collective_compute_overlap(PIPELINED_SYNC_HLO)
    assert ov["sync_ops"] == 1 and ov["pipelined_ops"] == 1
    assert ov["overlapped_bytes"] == 4096
    assert ov["overlap_pct"] == 100.0
    assert ov["by_kind"]["all-reduce"]["pipelined"] == 1


def test_overlap_pipelined_ignores_ancestor_descendant_compute():
    """The dot being the collective's producer or consumer must NOT
    count — that is exactly the serialized GPipe-hop shape."""
    serial = """
ENTRY %main (q0: f32[64,64]) -> f32[64,64] {
  %q0 = f32[64,64]{1,0} parameter(1)
  %mm = f32[64,64]{1,0} dot(f32[64,64]{1,0} %q0, f32[64,64]{1,0} %q0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %cp = f32[64,64]{1,0} collective-permute(f32[64,64]{1,0} %mm), source_target_pairs={{0,1},{1,0}}
  ROOT %mm2 = f32[64,64]{1,0} dot(f32[64,64]{1,0} %cp, f32[64,64]{1,0} %q0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
    ov = costmodel.collective_compute_overlap(serial)
    assert ov["sync_ops"] == 1 and ov["pipelined_ops"] == 0
    assert ov["overlap_pct"] == 0.0


def test_overlap_async_with_compute_between():
    ov = costmodel.collective_compute_overlap(ASYNC_HLO)
    assert ov["async_ops"] == 1
    assert ov["overlapped_bytes"] == 4096
    assert ov["overlap_pct"] == 100.0


def test_overlap_ring_and_pipeline_schedules():
    """The r6 double-buffered parallel schedules measure overlapped on
    their boundary hops (the acceptance instrument for the dp8 dryrun
    audit): every ring ppermute is hidden; the pipeline's hop is hidden
    while its output psum (inherently after the loop) is not."""
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.ring import local_ring_attention_fn
    try:
        from jax import shard_map as smap2
    except ImportError:
        from jax.experimental.shard_map import shard_map as smap2
    from jax.sharding import PartitionSpec as PS
    n = 2
    mesh = make_mesh((n,), ("sp",))
    compat = {} if hasattr(jax.lax, "pvary") else {"check_rep": False}
    fn = local_ring_attention_fn("sp", False, 0.25, n)
    spec = PS(None, "sp", None, None)
    mapped = smap2(fn, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                   **compat)
    x = jnp.ones((1, 4 * n, 2, 8), jnp.float32)
    txt = jax.jit(mapped).lower(x, x, x).compile().as_text()
    ov = costmodel.collective_compute_overlap(txt)
    assert ov["overlap_pct"] == 100.0
    assert ov["by_kind"]["collective-permute"]["pipelined"] == 2

    from mxnet_tpu.parallel.pipeline import pipeline_apply
    pp_mesh = make_mesh((n,), ("pp",))
    Ws = jnp.ones((n, 8, 8), jnp.float32) * 0.1
    xm = jnp.ones((4, 2, 8), jnp.float32)

    def run(p, xmi):
        return pipeline_apply(lambda w, v: jnp.tanh(v @ w), n, pp_mesh,
                              "pp", p, xmi)

    txt = jax.jit(run).lower(Ws, xm).compile().as_text()
    ov = costmodel.collective_compute_overlap(txt)
    cp = ov["by_kind"]["collective-permute"]
    assert cp["pipelined"] == cp["sync"], \
        "every boundary hop must be double-buffered"
    assert ov["overlapped_bytes"] >= cp["bytes"]


def test_audit_report_carries_overlap_line():
    # the dp8 dryrun's accounting line must name the overlap %
    from mxnet_tpu.parallel.audit import audit_report
    line, acct = audit_report("dp8", SYNC_HLO, 8)
    assert "collective/compute overlap" in line
    assert "all-reduce" in line and acct["all-reduce"]["count"] == 1


# ---------------------------------------------------------------------------
# attribution reports end to end
# ---------------------------------------------------------------------------

def test_attribute_compiled_report_schema(tmp_path):
    c = jax.jit(lambda a, b: a @ b).lower(
        jnp.ones((128, 128), jnp.float32),
        jnp.ones((128, 128), jnp.float32)).compile()
    rep = perf.attribute_compiled(c, "matmul", measured_step_s=1e-5)
    d = rep.to_dict()
    assert d["kind"] == "attribution_report"
    assert d["hlo_cost"]["flops_ratio_analytic_vs_hlo"] == pytest.approx(
        1.0, abs=0.05)
    assert d["roofline"]["bound"] in ("compute", "hbm", "collective",
                                      "host")
    shares = d["roofline"]["shares"]
    assert {"compute", "hbm", "collective", "host"} <= set(shares)
    assert d["step"]["mfu"] == pytest.approx(
        d["analytic"]["flops"] / 1e-5
        / d["roofline"]["peaks"]["flops"], rel=0.01)
    # atomic save + reload round-trip
    path = rep.save(str(tmp_path / "attr.json"))
    assert perf.AttributionReport.load(path).to_dict()["program"] \
        == "matmul"
    # pretty + perfetto renderings exist and carry the headline numbers
    text = rep.pretty()
    assert "MFU vs chip peak" in text and "roofline" in text
    counters = rep.perfetto_counters(ts_us=123.0)
    assert any(ev["ph"] == "C" and "mfu" in ev["args"]
               for ev in counters)


def test_phases_block_shape():
    c = jax.jit(lambda a, b: a @ b).lower(
        jnp.ones((64, 64), jnp.float32),
        jnp.ones((64, 64), jnp.float32)).compile()
    rep = perf.attribute_compiled(c, "bench.toy", measured_step_s=0.002)
    block = perf.phases_block(rep, "/tmp/r.json")
    assert {"bound", "compute_share", "hbm_share", "collective_share",
            "host_share", "mfu", "overlap_pct", "report"} <= set(block)
    assert block["report"] == "/tmp/r.json"
    assert block["mfu"] == rep.to_dict()["step"]["mfu"]


def test_toy_trainer_step_attribution_smoke(tmp_path, monkeypatch):
    """Tier-1 smoke (CI satellite): MXNET_TPU_ATTRIBUTION=1 on a toy
    jitted ShardedTrainer step writes one report with the measured step
    split folded in."""
    from mxnet_tpu import symbol as S
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    from mxnet_tpu import telemetry

    monkeypatch.setenv("MXNET_TPU_ATTRIBUTION", "1")
    monkeypatch.setenv("MXNET_TPU_ATTRIBUTION_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_TPU_ATTRIBUTION_AFTER", "2")
    perf.reset_attributed()
    telemetry.reset()
    telemetry.arm()
    try:
        data = S.Variable("data")
        fc1 = S.FullyConnected(data=data, num_hidden=32, name="fc1")
        act = S.Activation(data=fc1, act_type="relu", name="relu1")
        fc2 = S.FullyConnected(data=act, num_hidden=10, name="fc2")
        sym = S.SoftmaxOutput(data=fc2, name="softmax")
        tr = ShardedTrainer(sym, MeshSpec(make_mesh((1,), ("dp",))),
                            lr=0.1)
        shapes = {"data": (8, 16), "softmax_label": (8,)}
        params, mom, aux = tr.init_state(shapes)
        rs = np.random.RandomState(0)
        feed = {"data": rs.rand(8, 16).astype(np.float32),
                "softmax_label": rs.randint(0, 10, 8).astype(np.float32)}
        for _ in range(3):
            params, mom, aux, loss = tr.step(params, mom, aux, feed)
        assert np.isfinite(float(loss))
    finally:
        telemetry.disarm()
        telemetry.reset()
    reports = [f for f in os.listdir(str(tmp_path))
               if f.startswith("attribution-") and f.endswith(".json")]
    assert len(reports) == 1
    d = json.load(open(os.path.join(str(tmp_path), reports[0])))
    assert d["program"].startswith("ShardedTrainer.step")
    assert d["analytic"]["flops"] > 0
    assert d["step"]["measured_s"] > 0
    assert d["step"]["host_enqueue_s"] is not None
    assert d["hlo_cost"]["flops_ratio_analytic_vs_hlo"] == pytest.approx(
        1.0, abs=0.10)
    # a second trainer step must NOT write a second report (once per
    # program)
    params, mom, aux, _ = tr.step(params, mom, aux, feed)
    assert len([f for f in os.listdir(str(tmp_path))
                if f.startswith("attribution-")]) == 1


def test_transformer_attribution_matches_bench_formula():
    """The bench-MFU acceptance: analytic FLOPs from the compiled
    transformer step agree with bench.py's formula (tools/bench_ideal)
    within 5% — which bounds |attribution MFU - bench MFU| by 0.02 at
    MFU 0.4."""
    from mxnet_tpu.models.transformer import get_symbol
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    # mid-size geometry: dots must dominate enough that the matmul-only
    # bench formula and the full-program analytic count agree within 5%
    # (at the real L12/H768/T1024 bench geometry the elementwise share
    # is smaller still)
    batch, seq, layers, hidden, heads, vocab = 2, 256, 2, 512, 4, 2048
    sym = get_symbol(vocab_size=vocab, seq_len=seq, num_layers=layers,
                     hidden=hidden, heads=heads)
    tr = ShardedTrainer(sym, MeshSpec(make_mesh((1,), ("dp",))),
                        lr=1e-4, wd=0.0, param_dtype="bfloat16")
    shapes = {"data": (batch, seq), "softmax_label": (batch, seq)}
    params, mom, aux = tr.init_state(shapes)
    step, params, mom, aux = tr.build_step_auto_layout(
        params, mom, aux, shapes)
    rep = perf.attribute_compiled(step, "transformer",
                                  measured_step_s=0.1)
    d = rep.to_dict()
    bi = _load_tool("bench_ideal")
    formula = bi.transformer_flops_per_step(batch, seq, layers, hidden,
                                            vocab)
    assert d["analytic"]["flops"] == pytest.approx(formula, rel=0.05)
    assert d["analytic"]["flops"] == pytest.approx(
        d["hlo_cost"]["flops"], rel=0.05)
    # MFU consistency: same measured time + flops within 5% -> MFU
    # within 0.02 at the bench's 0.4 operating point
    peak = d["roofline"]["peaks"]["flops"]
    bench_mfu = formula / 0.1 / peak
    assert abs(d["step"]["mfu"] - bench_mfu) <= 0.05 * bench_mfu + 1e-9
    # the r5 accounting the report must reproduce: dtype split with
    # named top contributors
    assert d["analytic"]["bytes_by_dtype"]
    assert len(d["analytic"]["top_contributors"]) >= 3


@pytest.mark.slow
def test_bench_py_emits_phases_and_feeds_ledger(tmp_path):
    """Bench-backed e2e: `python bench.py` (transformer, toy geometry)
    emits the self-describing phases block — bench MFU == attribution
    MFU — and appends to the BENCH_LEDGER trajectory."""
    import subprocess
    import sys
    ledger = str(tmp_path / "ledger.jsonl")
    attr = str(tmp_path / "attr.json")
    env = dict(os.environ, BENCH_MODEL="transformer", BENCH_LAYERS="2",
               BENCH_HIDDEN="128", BENCH_HEADS="4", BENCH_SEQ="128",
               BENCH_VOCAB="512", BENCH_BATCH="2", BENCH_ITERS="3",
               BENCH_WARMUP="1", BENCH_LEDGER=ledger,
               BENCH_ATTRIBUTION_PATH=attr, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=900)
    assert r.returncode == 0, r.stderr[-1500:]
    doc = json.loads(r.stdout.strip().splitlines()[-1])
    phases = doc["phases"]
    assert phases["bound"] in ("compute", "hbm", "collective", "host")
    assert phases["report"] == attr
    full = json.load(open(attr))
    assert full["hlo_cost"]["flops_ratio_analytic_vs_hlo"] \
        == pytest.approx(1.0, abs=0.05)
    # bench MFU and attribution MFU must agree (acceptance: within 0.02
    # at the real operating point; here both are computed from the same
    # measured time, so agreement is a flops-model statement)
    assert phases["mfu"] == pytest.approx(doc["mfu"], abs=0.02)
    bw = _load_tool("benchwatch")
    entries = bw.read_ledger(ledger)
    assert len(entries) == 1
    assert "transformer_train_tokens_per_sec_per_chip" \
        in entries[0]["metrics"]


# ---------------------------------------------------------------------------
# benchwatch: the regression gate
# ---------------------------------------------------------------------------

def test_benchwatch_catches_injected_10pct_regression():
    bw = _load_tool("benchwatch")
    rs = np.random.RandomState(0)
    base = [1000.0 * (1 + rs.uniform(-0.01, 0.01)) for _ in range(8)]
    ok = bw.check_series(base + [base[-1]])
    assert not ok["regression"]
    bad = bw.check_series(base + [max(base) * 0.90])
    assert bad["regression"]
    assert bad["drop"] >= 0.09


def test_benchwatch_sigma_jitter_passes():
    bw = _load_tool("benchwatch")
    rs = np.random.RandomState(1)
    vals = [2000.0 * (1 + rs.normal(0, 0.01)) for _ in range(10)]
    # a sigma-sized wiggle on the last point is noise, not a regression
    vals.append(float(np.mean(vals) * (1 - 0.01)))
    assert not bw.check_series(vals)["regression"]


def test_benchwatch_short_series_not_gated():
    bw = _load_tool("benchwatch")
    assert bw.check_series([1.0]) == {"checked": False,
                                      "regression": False, "n": 1}


def test_benchwatch_committed_ledger_green():
    """--check on the committed r01→r05 trajectory must pass (the 0.2%
    r02→r03 dip is inside the noise floor)."""
    bw = _load_tool("benchwatch")
    ledger = os.path.join(REPO, "PERF_LEDGER.jsonl")
    entries = bw.read_ledger(ledger)
    assert len(entries) >= 5
    ok, results = bw.check_ledger(entries)
    assert ok, results
    r = results["resnet50_train_img_per_sec_per_chip"]
    assert r["checked"] and not r["regression"]
    # and through the CLI exactly as CI invokes it
    assert bw.main(["--check", "--ledger", ledger]) == 0


def test_benchwatch_append_and_extract(tmp_path):
    bw = _load_tool("benchwatch")
    # driver-wrapper format (BENCH_r*.json)
    doc = {"parsed": {"metric": "m", "value": 10.0,
                      "transformer": {"metric": "t", "value": 5.0,
                                      "mfu": 0.4}}}
    metrics = bw.extract_metrics(doc)
    assert metrics == {"m": 10.0, "t": 5.0, "t_mfu": 0.4}
    ledger = str(tmp_path / "ledger.jsonl")
    bw.append_entry(ledger, metrics, source="r1")
    bw.append_entry(ledger, {"m": 11.0}, source="r2")
    series = bw.metric_series(bw.read_ledger(ledger))
    assert series["m"] == [10.0, 11.0]
    # one-point series are reported but never gated
    assert bw.main(["check", "--ledger", ledger]) == 0


def test_benchwatch_collective_extras_ungated(tmp_path):
    """phases.collective_bytes_per_step rides the ledger's extra block
    (ungated, like peak_hbm_bytes): a wire-bytes IMPROVEMENT — the ZeRO
    78->39 MB-shaped drop — must never read as a regression."""
    bw = _load_tool("benchwatch")
    doc = {"metric": "m", "value": 100.0,
           "phases": {"peak_hbm_bytes": 1000,
                      "collective_bytes_per_step": 78_000_000},
           "transformer": {"metric": "t", "value": 5.0,
                           "phases": {"collective_bytes_per_step": 50}}}
    extra = bw.extract_extra(doc)
    assert extra["collective_bytes_per_step"] == 78_000_000
    assert extra["peak_hbm_bytes"] == 1000
    assert extra["transformer_collective_bytes_per_step"] == 50
    ledger = str(tmp_path / "l.jsonl")
    wires = (78_000_000, 78_100_000, 78_050_000, 39_000_000)
    for v, wire in zip((100.0, 100.5, 99.8, 100.2), wires):
        bw.append_entry(ledger, {"m": v},
                        extra={"collective_bytes_per_step": wire})
    entries = bw.read_ledger(ledger)
    ok, results = bw.check_ledger(entries)
    assert ok, results
    # the wire series is recorded (visible to `show`/trend tooling) but
    # never enters the gated metric set
    assert "collective_bytes_per_step" not in results
    assert entries[-1]["extra"]["collective_bytes_per_step"] == 39_000_000


def test_phases_block_and_report_carry_collective_bytes():
    """bench phases block exposes the per-step wire bytes; multi-device
    programs attribute them per mesh axis in the report."""
    c = jax.jit(lambda a, b: a @ b).lower(
        jnp.ones((32, 32), jnp.float32),
        jnp.ones((32, 32), jnp.float32)).compile()
    rep = perf.attribute_compiled(c, "bench.toy", measured_step_s=0.001)
    block = perf.phases_block(rep)
    assert block["collective_bytes_per_step"] == 0   # single-chip toy

    if len(jax.devices()) >= 4:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
        spec = MeshSpec(make_mesh((4,), ("dp",)))
        bat = NamedSharding(spec.mesh, P("dp"))
        rep_s = spec.replicated()
        cd = jax.jit(lambda x: jnp.sum(x, axis=0),
                     in_shardings=bat, out_shardings=rep_s).lower(
            jnp.ones((8, 128), jnp.float32)).compile()
        r = perf.attribute_compiled(cd, "dp.toy", n_devices=4,
                                    mesh=spec.mesh)
        d = r.to_dict()["analytic"]
        assert d["collectives_by_axis"].get("dp", 0) > 0
        assert perf.phases_block(r)["collective_bytes_per_step"] > 0
        assert "collective bytes by axis" in r.pretty()


def test_benchwatch_extras_only_round(tmp_path):
    """An audit-level round (the MULTICHIP_r06 shape) carries ONLY
    ungated extras — appendable via the CLI's --extra, readable by the
    gate, and never gated."""
    bw = _load_tool("benchwatch")
    ledger = str(tmp_path / "l.jsonl")
    bw.append_entry(ledger, {"m": 100.0}, source="r1")
    assert bw.main(["append", "--ledger", ledger,
                    "--source", "MULTICHIP_rX",
                    "--extra", "dp8_overlap_pct=100.0",
                    "--extra", "dp8_optimizer_state_mb_per_device=5.59"]) \
        == 0
    entries = bw.read_ledger(ledger)
    assert entries[-1]["metrics"] == {}
    assert entries[-1]["extra"]["dp8_overlap_pct"] == 100.0
    ok, results = bw.check_ledger(entries)
    assert ok and "dp8_overlap_pct" not in results
    # a round with neither metrics nor extras is still refused
    with pytest.raises(ValueError):
        bw.append_entry(ledger, {}, source="empty")


def test_benchwatch_cli_regression_exit_code(tmp_path):
    bw = _load_tool("benchwatch")
    ledger = str(tmp_path / "ledger.jsonl")
    for v in (100.0, 101.0, 99.5, 102.0, 85.0):     # 17% drop at the end
        bw.append_entry(ledger, {"m": v})
    assert bw.main(["check", "--ledger", ledger]) == 1
    assert bw.main(["check", "--ledger", ledger, "--json"]) == 1
    assert bw.main(["check", "--ledger",
                    str(tmp_path / "missing.jsonl")]) == 2


# ---------------------------------------------------------------------------
# metricsdump follow survives truncation/rotation
# ---------------------------------------------------------------------------

def test_metricsdump_follow_reader_truncate_and_rotate(tmp_path):
    md = _load_tool("metricsdump")
    path = str(tmp_path / "feed.jsonl")
    with open(path, "w") as f:
        f.write('{"time": 1, "metrics": {}}\n')
    reader = md.FollowReader(path)
    try:
        assert len(reader.poll()) == 1
        with open(path, "a") as f:
            f.write('{"time": 2, "metrics": {}}\n')
        assert len(reader.poll()) == 1
        # truncation (exporter restarted with a fresh file)
        with open(path, "w") as f:
            f.write('{"time": 3, "metrics": {}}\n')
        assert [s["time"] for s in reader.poll()] == [3]
        # rotation: file disappears, then a NEW inode takes the name
        os.remove(path)
        assert reader.poll() == []
        side = str(tmp_path / "fresh.jsonl")
        with open(side, "w") as f:
            f.write('{"time": 4, "metrics": {}}\n')
        os.replace(side, path)
        assert [s["time"] for s in reader.poll()] == [4]
    finally:
        reader.close()


# ---------------------------------------------------------------------------
# serving device-utilization satellite
# ---------------------------------------------------------------------------

class _SleepProgram:
    input_names = ["data"]
    input_shapes = {"data": (4, 8)}
    input_dtypes = {"data": np.dtype(np.float32)}
    output_shapes = [(4, 8)]

    def __init__(self, latency):
        self.latency = latency

    def forward(self, data):
        time.sleep(self.latency)
        return [np.asarray(data)]


def test_serving_stats_device_utilization():
    from mxnet_tpu.serving import ServingRuntime
    with ServingRuntime(_SleepProgram(0.01),
                        default_deadline=5.0) as rt:
        for _ in range(5):
            rt.submit({"data": np.ones((1, 8), np.float32)}) \
              .result(timeout=5)
        s = rt.stats()
    assert 0.0 < s["device_utilization"] <= 1.0
    # additive: the pre-existing schema is intact
    assert {"health", "queue_depth", "exec_time_ewma_s",
            "counters"} <= set(s)
