"""Autograd tests (modeled on reference tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_basic_backward():
    x = nd.array([[1.0, 2], [3, 4]])
    x.attach_grad()
    with ag.record():
        y = (x * x + 2 * x).sum()
    y.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * x.asnumpy() + 2)


def test_chain():
    x = nd.array(np.random.rand(3, 4).astype(np.float32))
    x.attach_grad()
    with ag.record():
        y = nd.exp(nd.log(x + 1))
        z = (y * y).sum()
    z.backward()
    assert_almost_equal(x.grad.asnumpy(), 2 * (x.asnumpy() + 1), rtol=1e-4)


def test_multi_head():
    x = nd.array([1.0, 2, 3])
    x.attach_grad()
    with ag.record():
        a = x * 2
        b = x * 3
    ag.backward([a, b])
    assert_almost_equal(x.grad.asnumpy(), np.full(3, 5.0))


def test_head_grads():
    x = nd.array([1.0, 2])
    x.attach_grad()
    with ag.record():
        y = x * x
    y.backward(out_grad=nd.array([2.0, 0.5]))
    assert_almost_equal(x.grad.asnumpy(), np.array([4.0, 2.0]))


def test_grad_add_req():
    x = nd.array([1.0, 1])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = (x * 2).sum()
        y.backward()
    assert_almost_equal(x.grad.asnumpy(), np.full(2, 6.0))


def test_pause_and_modes():
    assert not ag.is_recording()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
        with ag.pause():
            assert not ag.is_recording()
        with ag.predict_mode():
            assert not ag.is_training()
    with ag.record(train_mode=False):
        assert not ag.is_training()


def test_detach():
    x = nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    # z = const(4) * x -> dz/dx = 4
    assert_almost_equal(x.grad.asnumpy(), np.array([4.0]))


def test_grad_function():
    x = nd.array([1.0, 2, 3])
    g = ag.grad(_loss(x, record=True), x)
    assert_almost_equal(g.asnumpy(), 2 * x.asnumpy())


def _loss(x, record=False):
    x.attach_grad()
    with ag.record():
        return (x * x).sum()


def test_mark_variables():
    x = nd.array([1.0, 4.0])
    gbuf = nd.zeros((2,))
    ag.mark_variables([x], [gbuf])
    with ag.record():
        y = (nd.sqrt(x)).sum()
    y.backward()
    assert_almost_equal(gbuf.asnumpy(), 0.5 / np.sqrt(x.asnumpy()), rtol=1e-4)


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array(np.random.uniform(-3, 3, size=(5,)).astype(np.float32))
    x.attach_grad()
    f = Sigmoid()
    with ag.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad.asnumpy(), s * (1 - s), rtol=1e-4)


def test_rng_op_under_autograd():
    x = nd.ones((4, 4))
    x.attach_grad()
    with ag.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
        z = y.sum()
    z.backward()
    # grad equals the dropout mask scaling
    g = x.grad.asnumpy()
    assert set(np.unique(g)).issubset({0.0, 2.0})
