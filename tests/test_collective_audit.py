"""Collective-traffic accounting (parallel/audit.py): the dp gradient
all-reduce payload extracted from compiled HLO must match the analytic
model (sum of f32 grad bytes) — the quantitative basis of the scaling
story (BASELINE north star; reference measured ~90% linear at 256 GPUs
with the same ring-allreduce cost model)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel.audit import (collective_accounting,
                                      grad_payload_bytes,
                                      ring_allreduce_wire_bytes)
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.parallel.trainer import ShardedTrainer


def _need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip("needs %d devices" % n)


def test_dp_allreduce_payload_matches_grad_bytes():
    _need_devices(4)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    spec = MeshSpec(make_mesh((4,), ("dp",)))
    tr = ShardedTrainer(net, spec, lr=0.1, momentum=0.9, wd=0.0)
    shapes = {"data": (8, 16), "softmax_label": (8,)}
    params, mom, aux = tr.init_state(shapes)
    feed = {"data": jax.device_put(np.zeros((8, 16), np.float32),
                                   spec.batch_sharding()),
            "softmax_label": jax.device_put(np.zeros((8,), np.float32),
                                            spec.batch_sharding())}
    jitted = tr._build_step(donate=False)
    txt = jitted.lower(params, mom, aux, feed, tr._keys(),
                       tr._guard_arrays()).compile().as_text()

    acct = collective_accounting(txt)
    assert "all-reduce" in acct, sorted(acct)
    measured = acct["all-reduce"]["bytes"]
    model = grad_payload_bytes(params)
    # XLA may fold the loss scalar or small aux reductions in; the grad
    # payload must dominate and match within 10%
    assert model > 0
    assert abs(measured - model) / model < 0.10, (measured, model)


def test_ring_wire_model():
    assert ring_allreduce_wire_bytes(1000, 8) == 2 * 7 * 1000 // 8
    assert ring_allreduce_wire_bytes(1000, 1) == 0


_FUSED_RS_HLO = """
ENTRY %main (p0: f32[64,32]) -> f32[8,32] {
  %p0 = f32[64,32]{1,0} parameter(0)
  %all-reduce = f32[64,32]{1,0} all-reduce(f32[64,32]{1,0} %p0), replica_groups=[1,8]<=[8], use_global_device_ids=true, to_apply=%add.clone
  %partition-id = u32[] partition-id()
  %convert = s32[] convert(u32[] %partition-id)
  %multiply = s32[] multiply(s32[] %convert, s32[] %c8)
  ROOT %dynamic-slice = f32[8,32]{1,0} dynamic-slice(f32[64,32]{1,0} %all-reduce, s32[] %multiply, s32[] %c0), dynamic_slice_sizes={8,32}
}
"""

# same shape but the slice offset is a constant — NOT partition-derived,
# so the all-reduce really is a replica all-reduce and must stay one
_PLAIN_AR_HLO = """
ENTRY %main (p0: f32[64,32]) -> f32[8,32] {
  %p0 = f32[64,32]{1,0} parameter(0)
  %all-reduce = f32[64,32]{1,0} all-reduce(f32[64,32]{1,0} %p0), replica_groups=[1,8]<=[8], use_global_device_ids=true, to_apply=%add.clone
  ROOT %dynamic-slice = f32[8,32]{1,0} dynamic-slice(f32[64,32]{1,0} %all-reduce, s32[] %c8, s32[] %c0), dynamic_slice_sizes={8,32}
}
"""


def test_fused_allreduce_slice_classified_reduce_scatter():
    """The ReduceScatterCreator pattern — an all-reduce whose every
    consumer takes a partition-id-derived slice — is accounted as the
    reduce-scatter it is on the wire (shard payload), with the
    reclassification visible via fused_from_all_reduce."""
    acct = collective_accounting(_FUSED_RS_HLO)
    assert "all-reduce" not in acct
    rs = acct["reduce-scatter"]
    assert rs["count"] == 1 and rs["fused_from_all_reduce"] == 1
    assert rs["bytes"] == 64 * 32 * 4 // 8      # the 1/8 shard


def test_constant_slice_of_allreduce_stays_allreduce():
    acct = collective_accounting(_PLAIN_AR_HLO)
    assert "reduce-scatter" not in acct
    assert acct["all-reduce"]["bytes"] == 64 * 32 * 4


def test_replica_groups_parsing_both_syntaxes():
    from mxnet_tpu.parallel.audit import parse_replica_groups
    assert parse_replica_groups("replica_groups={{0,4},{1,5}}, x=y") == \
        [(0, 4), (1, 5)]
    assert parse_replica_groups("replica_groups=[1,8]<=[8]") == \
        [tuple(range(8))]
    # iota with reshape+transpose: [4,2]<=[2,4]T(1,0) pairs stride-4 ids
    assert parse_replica_groups("replica_groups=[4,2]<=[2,4]T(1,0)") == \
        [(0, 4), (1, 5), (2, 6), (3, 7)]
    assert parse_replica_groups("channel_id=1") is None


def test_by_axis_attribution_on_dp_tp_mesh():
    """Replica groups map back to the mesh axes they span: dp groups
    label 'dp', tp groups 'tp', whole-mesh 'dpxtp', ppermute rings via
    their source-target pairs."""
    _need_devices(4)
    from mxnet_tpu.parallel.audit import AxisLabeler
    mesh = MeshSpec(make_mesh((2, 2), ("dp", "tp")))  # ids [[0,1],[2,3]]
    lab = AxisLabeler(mesh)
    assert lab.label_groups([(0, 2), (1, 3)]) == "dp"
    assert lab.label_groups([(0, 1), (2, 3)]) == "tp"
    assert lab.label_groups([(0, 1, 2, 3)]) == "dpxtp"
    assert lab.label_groups([(0, 3)]) == "unmapped"
    assert lab.label_groups([(0,), (1,)]) == "self"
    assert lab.label_pairs([(0, 2), (2, 0)]) == "dp"
    assert lab.label_pairs([(0, 1), (1, 0), (2, 3), (3, 2)]) == "tp"
    # accounting end: synthetic module over this mesh
    hlo = "\n".join([
        "ENTRY %main (p0: f32[16]) -> f32[16] {",
        "  %ar1 = f32[16]{0} all-reduce(f32[16]{0} %p0), "
        "replica_groups={{0,2},{1,3}}",
        "  ROOT %ar2 = f32[16]{0} all-reduce(f32[16]{0} %ar1), "
        "replica_groups={{0,1},{2,3}}",
        "}"])
    acct = collective_accounting(hlo, mesh=mesh)
    assert acct["all-reduce"]["by_axis"]["dp"]["bytes"] == 64
    assert acct["all-reduce"]["by_axis"]["tp"]["bytes"] == 64


def test_collective_wire_models():
    from mxnet_tpu.parallel.audit import (collective_wire_bytes,
                                          zero_update_model_bytes)
    assert collective_wire_bytes("all-reduce", 1000, 8) == 2 * 7 * 1000 // 8
    # reduce-scatter payload is the output shard: (n-1) hops of it
    assert collective_wire_bytes("reduce-scatter", 125, 8) == 7 * 125
    # all-gather payload is the gathered result: (n-1)/n of it on wire
    assert collective_wire_bytes("all-gather", 1000, 8) == 7 * 1000 // 8
    assert collective_wire_bytes("collective-permute", 42, 8) == 42
    m = zero_update_model_bytes(8000, 30, 8)
    assert m == {"reduce-scatter": 1000, "all-gather": 8000,
                 "all-reduce": 30}


def test_async_start_counts_operand_shapes_only():
    """-start accounting (audit.py): all-gather/reduce-scatter are
    asymmetric — halving the (operand, result) tuple overstated the
    all-gather payload by (1+n)/2; the operand shapes alone are what the
    collective is fed."""
    hlo = "\n".join([
        "  %ag = (f32[4]{0}, f32[16]{0}) all-gather-start(f32[4]{0} %x), "
        "replica_groups={{0,1,2,3}}, dimensions={0}",
        "  %rs = (f32[16]{0}, f32[4]{0}) reduce-scatter-start(f32[16]{0} "
        "%y), replica_groups={{0,1,2,3}}",
        "  %ar = (f32[8]{0}, f32[8]{0}) all-reduce-start(f32[8]{0} %z), "
        "replica_groups={}",
        "  %done = f32[16]{0} all-gather-done(%ag)",
    ])
    acct = collective_accounting(hlo)
    assert acct["all-gather"]["bytes"] == 4 * 4      # operand, not result
    assert acct["reduce-scatter"]["bytes"] == 16 * 4
    # symmetric op: operand == result == old halved-tuple accounting
    assert acct["all-reduce"]["bytes"] == 8 * 4
    assert acct["all-gather"]["count"] == 1          # -done not re-counted
