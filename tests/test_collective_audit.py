"""Collective-traffic accounting (parallel/audit.py): the dp gradient
all-reduce payload extracted from compiled HLO must match the analytic
model (sum of f32 grad bytes) — the quantitative basis of the scaling
story (BASELINE north star; reference measured ~90% linear at 256 GPUs
with the same ring-allreduce cost model)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel.audit import (collective_accounting,
                                      grad_payload_bytes,
                                      ring_allreduce_wire_bytes)
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.parallel.trainer import ShardedTrainer


def _need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip("needs %d devices" % n)


def test_dp_allreduce_payload_matches_grad_bytes():
    _need_devices(4)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    spec = MeshSpec(make_mesh((4,), ("dp",)))
    tr = ShardedTrainer(net, spec, lr=0.1, momentum=0.9, wd=0.0)
    shapes = {"data": (8, 16), "softmax_label": (8,)}
    params, mom, aux = tr.init_state(shapes)
    feed = {"data": jax.device_put(np.zeros((8, 16), np.float32),
                                   spec.batch_sharding()),
            "softmax_label": jax.device_put(np.zeros((8,), np.float32),
                                            spec.batch_sharding())}
    jitted = tr._build_step(donate=False)
    txt = jitted.lower(params, mom, aux, feed, tr._keys(),
                       tr._guard_arrays()).compile().as_text()

    acct = collective_accounting(txt)
    assert "all-reduce" in acct, sorted(acct)
    measured = acct["all-reduce"]["bytes"]
    model = grad_payload_bytes(params)
    # XLA may fold the loss scalar or small aux reductions in; the grad
    # payload must dominate and match within 10%
    assert model > 0
    assert abs(measured - model) / model < 0.10, (measured, model)


def test_ring_wire_model():
    assert ring_allreduce_wire_bytes(1000, 8) == 2 * 7 * 1000 // 8
    assert ring_allreduce_wire_bytes(1000, 1) == 0


def test_async_start_counts_operand_shapes_only():
    """-start accounting (audit.py): all-gather/reduce-scatter are
    asymmetric — halving the (operand, result) tuple overstated the
    all-gather payload by (1+n)/2; the operand shapes alone are what the
    collective is fed."""
    hlo = "\n".join([
        "  %ag = (f32[4]{0}, f32[16]{0}) all-gather-start(f32[4]{0} %x), "
        "replica_groups={{0,1,2,3}}, dimensions={0}",
        "  %rs = (f32[16]{0}, f32[4]{0}) reduce-scatter-start(f32[16]{0} "
        "%y), replica_groups={{0,1,2,3}}",
        "  %ar = (f32[8]{0}, f32[8]{0}) all-reduce-start(f32[8]{0} %z), "
        "replica_groups={}",
        "  %done = f32[16]{0} all-gather-done(%ag)",
    ])
    acct = collective_accounting(hlo)
    assert acct["all-gather"]["bytes"] == 4 * 4      # operand, not result
    assert acct["reduce-scatter"]["bytes"] == 16 * 4
    # symmetric op: operand == result == old halved-tuple accounting
    assert acct["all-reduce"]["bytes"] == 8 * 4
    assert acct["all-gather"]["count"] == 1          # -done not re-counted
