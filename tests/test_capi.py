"""C ABI tests (reference: src/c_api/ + cpp-package usage patterns).

Two tiers:
 - an embedded-interpreter tier: compile and run capi/test_lenet.c, a real
   C program that builds LeNet through the symbol ABI, binds an executor,
   and trains until the loss drops (the cpp-package lenet example's call
   sequence).
 - an in-process tier: load libmxnet_tpu.so with ctypes (the hosted-
   interpreter path) and exercise NDArray/op/symbol/kvstore/recordio calls.
"""
import ctypes
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI = os.path.join(REPO, "capi")
LIB = os.path.join(CAPI, "build", "libmxnet_tpu.so")


@pytest.fixture(scope="module")
def capi_lib():
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    r = subprocess.run(["make", "-C", CAPI], capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip("capi build failed: " + r.stderr[-500:])
    lib = ctypes.CDLL(LIB)
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.MXGetLastError().decode()


def test_c_lenet_trains(capi_lib):
    """The compiled C program trains LeNet one+ steps through the ABI."""
    env = dict(os.environ, MXNET_TPU_HOME=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run([os.path.join(CAPI, "build", "test_lenet")],
                      capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "C ABI LeNet training: OK" in r.stdout


def test_cpp_package_mlp_trains(capi_lib):
    """The header-only C++ frontend (cpp-package/include/mxnet_tpu_cpp)
    trains an MLP end-to-end — the reference cpp-package/example/mlp.cpp
    role."""
    env = dict(os.environ, MXNET_TPU_HOME=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run([os.path.join(CAPI, "build", "train_mlp_cpp")],
                       capture_output=True, text=True, env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "cpp-package MLP training: OK" in r.stdout


def test_ndarray_roundtrip(capi_lib):
    lib = capi_lib
    ver = ctypes.c_int()
    _check(lib, lib.MXGetVersion(ctypes.byref(ver)))
    assert ver.value == 10100

    shape = (ctypes.c_uint * 2)(3, 4)
    h = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayCreate(shape, 2, 1, 0, 0, ctypes.byref(h)))
    src = np.arange(12, dtype=np.float32)
    _check(lib, lib.MXNDArraySyncCopyFromCPU(
        h, src.ctypes.data_as(ctypes.c_void_p), src.size))

    ndim = ctypes.c_uint()
    pdata = ctypes.POINTER(ctypes.c_uint)()
    _check(lib, lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                      ctypes.byref(pdata)))
    assert [pdata[i] for i in range(ndim.value)] == [3, 4]

    back = np.zeros(12, np.float32)
    _check(lib, lib.MXNDArraySyncCopyToCPU(
        h, back.ctypes.data_as(ctypes.c_void_p), back.size))
    np.testing.assert_array_equal(back, src)
    _check(lib, lib.MXNDArrayFree(h))


def test_imperative_invoke_and_ops(capi_lib):
    lib = capi_lib
    n = ctypes.c_uint()
    names = ctypes.POINTER(ctypes.c_char_p)()
    _check(lib, lib.MXListAllOpNames(ctypes.byref(n), ctypes.byref(names)))
    all_ops = {names[i].decode() for i in range(n.value)}
    assert {"Convolution", "FullyConnected", "dot", "sgd_update"} <= all_ops

    creators = ctypes.POINTER(ctypes.c_void_p)()
    _check(lib, lib.MXSymbolListAtomicSymbolCreators(ctypes.byref(n),
                                                     ctypes.byref(creators)))
    dot = None
    for i in range(n.value):
        cname = ctypes.c_char_p()
        _check(lib, lib.MXSymbolGetAtomicSymbolName(
            ctypes.c_void_p(creators[i]), ctypes.byref(cname)))
        if cname.value == b"dot":
            dot = ctypes.c_void_p(creators[i])
            break
    assert dot is not None

    def make_nd(arr):
        shp = (ctypes.c_uint * arr.ndim)(*arr.shape)
        h = ctypes.c_void_p()
        _check(lib, lib.MXNDArrayCreate(shp, arr.ndim, 1, 0, 0,
                                        ctypes.byref(h)))
        _check(lib, lib.MXNDArraySyncCopyFromCPU(
            h, arr.ctypes.data_as(ctypes.c_void_p), arr.size))
        return h

    a = np.random.rand(2, 3).astype(np.float32)
    b = np.random.rand(3, 4).astype(np.float32)
    ins = (ctypes.c_void_p * 2)(make_nd(a), make_nd(b))
    n_out = ctypes.c_int(0)
    outs = ctypes.POINTER(ctypes.c_void_p)()
    _check(lib, lib.MXImperativeInvoke(dot, 2, ins, ctypes.byref(n_out),
                                       ctypes.byref(outs), 0, None, None))
    assert n_out.value == 1
    res = np.zeros((2, 4), np.float32)
    _check(lib, lib.MXNDArraySyncCopyToCPU(
        ctypes.c_void_p(outs[0]), res.ctypes.data_as(ctypes.c_void_p),
        res.size))
    np.testing.assert_allclose(res, a @ b, rtol=1e-5)


def test_symbol_json_and_save(capi_lib, tmp_path):
    lib = capi_lib
    h = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"x", ctypes.byref(h)))
    json_str = ctypes.c_char_p()
    _check(lib, lib.MXSymbolSaveToJSON(h, ctypes.byref(json_str)))
    assert b"x" in json_str.value

    # nd save/load through the ABI, read back in python
    shape = (ctypes.c_uint * 1)(4,)
    nd = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayCreate(shape, 1, 1, 0, 0, ctypes.byref(nd)))
    v = np.array([1, 2, 3, 4], np.float32)
    _check(lib, lib.MXNDArraySyncCopyFromCPU(
        nd, v.ctypes.data_as(ctypes.c_void_p), v.size))
    fname = str(tmp_path / "c.params").encode()
    keys = (ctypes.c_char_p * 1)(b"w")
    arr = (ctypes.c_void_p * 1)(nd)
    _check(lib, lib.MXNDArraySave(fname, 1, arr, keys))

    import mxnet_tpu as mx
    loaded = mx.nd.load(fname.decode())
    np.testing.assert_array_equal(loaded["w"].asnumpy(), v)


def test_kvstore_over_abi(capi_lib):
    lib = capi_lib
    kv = ctypes.c_void_p()
    _check(lib, lib.MXKVStoreCreate(b"local", ctypes.byref(kv)))
    rank, size = ctypes.c_int(), ctypes.c_int()
    _check(lib, lib.MXKVStoreGetRank(kv, ctypes.byref(rank)))
    _check(lib, lib.MXKVStoreGetGroupSize(kv, ctypes.byref(size)))
    assert (rank.value, size.value) == (0, 1)

    shape = (ctypes.c_uint * 2)(2, 2)

    def make(val):
        h = ctypes.c_void_p()
        _check(lib, lib.MXNDArrayCreate(shape, 2, 1, 0, 0, ctypes.byref(h)))
        a = np.full((2, 2), val, np.float32)
        _check(lib, lib.MXNDArraySyncCopyFromCPU(
            h, a.ctypes.data_as(ctypes.c_void_p), a.size))
        return h

    keys = (ctypes.c_int * 1)(3)
    vals = (ctypes.c_void_p * 1)(make(1.0))
    _check(lib, lib.MXKVStoreInit(kv, 1, keys, vals))
    push_vals = (ctypes.c_void_p * 1)(make(8.0))
    _check(lib, lib.MXKVStorePush(kv, 1, keys, push_vals, 0))
    out = (ctypes.c_void_p * 1)(make(0.0))
    _check(lib, lib.MXKVStorePull(kv, 1, keys, out, 0))
    res = np.zeros((2, 2), np.float32)
    _check(lib, lib.MXNDArraySyncCopyToCPU(
        ctypes.c_void_p(out[0]), res.ctypes.data_as(ctypes.c_void_p),
        res.size))
    np.testing.assert_array_equal(res, np.full((2, 2), 8.0))
    _check(lib, lib.MXKVStoreBarrier(kv))
    _check(lib, lib.MXKVStoreFree(kv))


def test_recordio_over_abi(capi_lib, tmp_path):
    lib = capi_lib
    uri = str(tmp_path / "t.rec").encode()
    w = ctypes.c_void_p()
    _check(lib, lib.MXRecordIOWriterCreate(uri, ctypes.byref(w)))
    payload = b"hello mxnet_tpu recordio"
    _check(lib, lib.MXRecordIOWriterWriteRecord(w, payload, len(payload)))
    _check(lib, lib.MXRecordIOWriterFree(w))

    r = ctypes.c_void_p()
    _check(lib, lib.MXRecordIOReaderCreate(uri, ctypes.byref(r)))
    buf = ctypes.c_char_p()
    size = ctypes.c_size_t()
    _check(lib, lib.MXRecordIOReaderReadRecord(r, ctypes.byref(buf),
                                               ctypes.byref(size)))
    assert ctypes.string_at(buf, size.value) == payload
    _check(lib, lib.MXRecordIOReaderReadRecord(r, ctypes.byref(buf),
                                               ctypes.byref(size)))
    assert size.value == 0  # EOF
    _check(lib, lib.MXRecordIOReaderFree(r))


def test_error_reporting(capi_lib):
    lib = capi_lib
    bad = ctypes.c_void_p(999999)
    ndim = ctypes.c_uint()
    pdata = ctypes.POINTER(ctypes.c_uint)()
    rc = lib.MXNDArrayGetShape(bad, ctypes.byref(ndim), ctypes.byref(pdata))
    assert rc == -1
    assert b"invalid handle" in lib.MXGetLastError()


def test_simple_bind_over_abi(capi_lib):
    """MXExecutorSimpleBind: shapes in, allocated args/grads/aux out."""
    lib = capi_lib
    # mlp: fc(10->4) -> SoftmaxOutput
    data = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"data", ctypes.byref(data)))
    fc = ctypes.c_void_p()
    kk = (ctypes.c_char_p * 1)(b"num_hidden")
    vv = (ctypes.c_char_p * 1)(b"4")
    creators = ctypes.POINTER(ctypes.c_void_p)()
    n = ctypes.c_uint()
    _check(lib, lib.MXSymbolListAtomicSymbolCreators(ctypes.byref(n),
                                                     ctypes.byref(creators)))
    fc_creator = None
    for i in range(n.value):
        cname = ctypes.c_char_p()
        _check(lib, lib.MXSymbolGetAtomicSymbolName(
            ctypes.c_void_p(creators[i]), ctypes.byref(cname)))
        if cname.value == b"FullyConnected":
            fc_creator = ctypes.c_void_p(creators[i])
            break
    _check(lib, lib.MXSymbolCreateAtomicSymbol(fc_creator, 1, kk, vv,
                                               ctypes.byref(fc)))
    args_in = (ctypes.c_void_p * 1)(data)
    _check(lib, lib.MXSymbolCompose(fc, b"fc", 1, None, args_in))

    shape_names = (ctypes.c_char_p * 1)(b"data")
    shape_data = (ctypes.c_uint * 2)(8, 10)
    shape_idx = (ctypes.c_uint * 2)(0, 2)
    num_in = ctypes.c_uint()
    in_args = ctypes.POINTER(ctypes.c_void_p)()
    arg_grads = ctypes.POINTER(ctypes.c_void_p)()
    num_aux = ctypes.c_uint()
    aux = ctypes.POINTER(ctypes.c_void_p)()
    exe = ctypes.c_void_p()
    shared_len = ctypes.c_int(-1)
    _check(lib, lib.MXExecutorSimpleBind(
        fc, 1, 0,
        0, None, None, None,            # g2c
        0, None, None,                  # grad_req overrides
        1, shape_names, shape_data, shape_idx,
        0, None, None,                  # dtypes
        0, None, None,                  # stypes
        0, None,                        # shared arg names
        ctypes.byref(shared_len), None, None, None, None,
        ctypes.byref(num_in), ctypes.byref(in_args), ctypes.byref(arg_grads),
        ctypes.byref(num_aux), ctypes.byref(aux),
        None, ctypes.byref(exe)))
    assert num_in.value == 3  # data, fc_weight, fc_bias
    # weight shape got inferred: (4, 10)
    ndim = ctypes.c_uint()
    pdata = ctypes.POINTER(ctypes.c_uint)()
    _check(lib, lib.MXNDArrayGetShape(ctypes.c_void_p(in_args[1]),
                                      ctypes.byref(ndim), ctypes.byref(pdata)))
    assert [pdata[i] for i in range(ndim.value)] == [4, 10]
    _check(lib, lib.MXExecutorForward(exe, 0))
    n_out = ctypes.c_uint()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    _check(lib, lib.MXExecutorOutputs(exe, ctypes.byref(n_out),
                                      ctypes.byref(outs)))
    assert n_out.value == 1
    _check(lib, lib.MXExecutorFree(exe))


def test_c_predict_api(capi_lib, tmp_path):
    """capi/test_predict.c: save a checkpoint from python, then a real C
    program loads and scores it through MXPred* (reference
    c_predict_api.h / amalgamation deployment role)."""
    import mxnet_tpu as mx
    from mxnet_tpu.ndarray import serialization
    rs = np.random.RandomState(0)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc", num_hidden=5)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    prefix = str(tmp_path / "model")
    net.save(prefix + "-symbol.json")
    serialization.save(prefix + ".params", {
        "arg:fc_weight": mx.nd.array(rs.rand(5, 3).astype(np.float32)),
        "arg:fc_bias": mx.nd.array(rs.rand(5).astype(np.float32))})
    exe = os.path.join(CAPI, "build", "test_predict")
    assert os.path.isfile(exe)
    env = dict(os.environ, MXNET_TPU_HOME=REPO)
    r = subprocess.run([exe, prefix], capture_output=True, text=True,
                       env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PREDICT OK" in r.stdout


def test_c_predict_aot_served(capi_lib, tmp_path):
    """capi/test_predict_aot.c: Executor.export_compiled writes a
    serialized AOT artifact; a real C consumer loads and scores it via
    MXPredCreateFromServed with no symbol layer or tracing (the
    amalgamation-deployment answer, deploy.py).  Export runs in a clean
    subprocess so artifact and consumer share one jax backend."""
    artifact = str(tmp_path / "model.mxt")
    code = (
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "net = mx.sym.Variable('data')\n"
        "net = mx.sym.FullyConnected(net, num_hidden=8, name='fc1')\n"
        "net = mx.sym.Activation(net, act_type='relu')\n"
        "net = mx.sym.FullyConnected(net, num_hidden=5, name='fc2')\n"
        "net = mx.sym.SoftmaxOutput(net, name='softmax')\n"
        "ex = net.simple_bind(mx.cpu(), data=(4, 3))\n"
        "rs = np.random.RandomState(0)\n"
        "for a in ex.arg_arrays:\n"
        "    a[:] = mx.nd.array(rs.normal(0, 0.3, a.shape))\n"
        "ex.export_compiled(%r, input_names=('data',))\n" % artifact)
    env = dict(os.environ, MXNET_TPU_HOME=REPO,
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    exe = os.path.join(CAPI, "build", "test_predict_aot")
    assert os.path.isfile(exe)
    r = subprocess.run([exe, artifact], capture_output=True, text=True,
                       env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PREDICT AOT OK" in r.stdout


def test_c_served_serving_error_propagation(capi_lib, tmp_path):
    """Serving errors (deadline, swap failure, corrupt artifact) must
    cross the embedded-interpreter boundary as error-return -1 + typed
    text in MXGetLastError — never as an unwinding Python exception.
    Uses the in-process (hosted interpreter) tier so export and load
    share one jax backend/topology."""
    lib = capi_lib
    import mxnet_tpu as mx

    # corrupt artifact: typed refusal, not a crash
    evil = str(tmp_path / "evil.mxt").encode()
    import pickle
    with open(evil, "wb") as f:
        pickle.dump({"innocent": "model"}, f)
    h = ctypes.c_void_p()
    assert lib.MXPredCreateFromServed(evil, ctypes.byref(h)) == -1
    assert b"pickle" in lib.MXGetLastError()

    artifact = str(tmp_path / "model.mxt")
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=5, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    ex = net.simple_bind(mx.cpu(), data=(4, 3))
    rs = np.random.RandomState(0)
    for a in ex.arg_arrays:
        a[:] = mx.nd.array(rs.normal(0, 0.3, a.shape))
    ex.export_compiled(artifact, input_names=("data",))

    _check(lib, lib.MXPredCreateFromServed(artifact.encode(),
                                           ctypes.byref(h)))
    health = ctypes.c_int(-1)
    _check(lib, lib.MXPredGetHealth(h, ctypes.byref(health)))
    assert health.value == 0            # SERVING

    batch = np.zeros(12, np.float32)
    _check(lib, lib.MXPredSetInput(h, b"data",
                                   batch.ctypes.data_as(ctypes.c_void_p),
                                   12))
    lib.MXPredSetDeadline.argtypes = [ctypes.c_void_p, ctypes.c_double]
    _check(lib, lib.MXPredSetDeadline(h, ctypes.c_double(1e-6)))
    assert lib.MXPredForward(h) == -1
    assert b"DeadlineExceeded" in lib.MXGetLastError()

    _check(lib, lib.MXPredSetDeadline(h, ctypes.c_double(0.0)))
    _check(lib, lib.MXPredForward(h))

    assert lib.MXPredSwapServed(h, b"/nonexistent/model.mxt") == -1
    assert b"SwapFailed" in lib.MXGetLastError()
    _check(lib, lib.MXPredForward(h))   # previous model keeps serving
    _check(lib, lib.MXPredFree(h))


def test_c_autograd_and_cachedop(capi_lib):
    """MXAutograd* + MXCreateCachedOp/MXInvokeCachedOp over ctypes."""
    lib = capi_lib
    ctypes_arr = (ctypes.c_uint * 1)(3)

    def make_nd(vals):
        h = ctypes.c_void_p()
        _check(lib, lib.MXNDArrayCreate(ctypes_arr, 1, 1, 0, 0,
                                        ctypes.byref(h)))
        host = np.asarray(vals, np.float32)
        _check(lib, lib.MXNDArraySyncCopyFromCPU(
            h, host.ctypes.data_as(ctypes.c_void_p), 3))
        return h

    def read_nd(h):
        out = np.zeros(3, np.float32)
        _check(lib, lib.MXNDArraySyncCopyToCPU(
            h, out.ctypes.data_as(ctypes.c_void_p), 3))
        return out

    x = make_nd([1., 2., 3.])
    g = make_nd([0., 0., 0.])
    reqs = (ctypes.c_uint * 1)(1)
    vars_ = (ctypes.c_void_p * 1)(x)
    grads = (ctypes.c_void_p * 1)(g)
    _check(lib, lib.MXAutogradMarkVariables(1, vars_, reqs, grads))
    prev = ctypes.c_int()
    _check(lib, lib.MXAutogradSetIsRecording(1, ctypes.byref(prev)))

    # y = square(x) via imperative invoke
    creators = ctypes.POINTER(ctypes.c_void_p)()
    ncr = ctypes.c_uint()
    _check(lib, lib.MXSymbolListAtomicSymbolCreators(ctypes.byref(ncr),
                                                     ctypes.byref(creators)))
    sq = None
    name = ctypes.c_char_p()
    for i in range(ncr.value):
        _check(lib, lib.MXSymbolGetAtomicSymbolName(
            ctypes.c_void_p(creators[i]), ctypes.byref(name)))
        if name.value == b"square":
            sq = ctypes.c_void_p(creators[i])
            break
    assert sq is not None
    n_out = ctypes.c_int(0)
    outs = ctypes.POINTER(ctypes.c_void_p)()
    ins = (ctypes.c_void_p * 1)(x)
    _check(lib, lib.MXImperativeInvoke(sq, 1, ins, ctypes.byref(n_out),
                                       ctypes.byref(outs), 0, None, None))
    _check(lib, lib.MXAutogradSetIsRecording(0, ctypes.byref(prev)))
    heads = (ctypes.c_void_p * 1)(outs[0])
    _check(lib, lib.MXAutogradBackward(1, heads, None, 0))
    np.testing.assert_allclose(read_nd(g), [2., 4., 6.])

    # grad handle retrievable through the ABI
    gh = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayGetGrad(x, ctypes.byref(gh)))
    np.testing.assert_allclose(read_nd(gh), [2., 4., 6.])

    # CachedOp: fc symbol invoked with raw inputs
    json_sym = None
    import mxnet_tpu as mx
    net = mx.sym.square(mx.sym.Variable("a"))
    sym_h = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateFromJSON(net.tojson().encode(),
                                           ctypes.byref(sym_h)))
    cop = ctypes.c_void_p()
    _check(lib, lib.MXCreateCachedOp(sym_h, ctypes.byref(cop)))
    n_out2 = ctypes.c_int(0)
    outs2 = ctypes.POINTER(ctypes.c_void_p)()
    ins2 = (ctypes.c_void_p * 1)(x)
    _check(lib, lib.MXInvokeCachedOp(cop, 1, ins2, ctypes.byref(n_out2),
                                     ctypes.byref(outs2)))
    assert n_out2.value == 1
    np.testing.assert_allclose(read_nd(outs2[0]), [1., 4., 9.])
    _check(lib, lib.MXFreeCachedOp(cop))


def test_c_sparse_and_raw_bytes(capi_lib):
    lib = capi_lib
    import mxnet_tpu as mx
    # raw bytes roundtrip
    h = ctypes.c_void_p()
    shape = (ctypes.c_uint * 2)(2, 2)
    _check(lib, lib.MXNDArrayCreate(shape, 2, 1, 0, 0, ctypes.byref(h)))
    host = np.arange(4, dtype=np.float32)
    _check(lib, lib.MXNDArraySyncCopyFromCPU(
        h, host.ctypes.data_as(ctypes.c_void_p), 4))
    size = ctypes.c_size_t()
    buf = ctypes.c_char_p()
    _check(lib, lib.MXNDArraySaveRawBytes(h, ctypes.byref(size),
                                          ctypes.byref(buf)))
    raw = ctypes.string_at(buf, size.value)
    h2 = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayLoadFromRawBytes(raw, len(raw),
                                              ctypes.byref(h2)))
    out = np.zeros(4, np.float32)
    _check(lib, lib.MXNDArraySyncCopyToCPU(
        h2, out.ctypes.data_as(ctypes.c_void_p), 4))
    np.testing.assert_allclose(out, host)
    # sparse creation + aux introspection
    hs = ctypes.c_void_p()
    sshape = (ctypes.c_uint * 2)(4, 3)
    _check(lib, lib.MXNDArrayCreateSparseEx(1, sshape, 2, 1, 0, 0, 0, 0,
                                            None, None, None,
                                            ctypes.byref(hs)))
    st = ctypes.c_int()
    _check(lib, lib.MXNDArrayGetStorageType(hs, ctypes.byref(st)))
    assert st.value == 1      # row_sparse
    at = ctypes.c_int()
    _check(lib, lib.MXNDArrayGetAuxType(hs, 0, ctypes.byref(at)))
    assert at.value == 6      # int64 indices


def test_c_misc_abi_surface(capi_lib):
    lib = capi_lib
    prev = ctypes.c_int()
    _check(lib, lib.MXEngineSetBulkSize(32, ctypes.byref(prev)))
    _check(lib, lib.MXSetNumOMPThreads(2))
    ret = ctypes.c_int()
    _check(lib, lib.MXKVStoreIsWorkerNode(ctypes.byref(ret)))
    assert ret.value == 1
    _check(lib, lib.MXKVStoreIsServerNode(ctypes.byref(ret)))
    assert ret.value == 0
    # legacy function API: square via MXFuncInvoke
    fh = ctypes.c_void_p()
    _check(lib, lib.MXGetFunction(b"square", ctypes.byref(fh)))
    nu = ctypes.c_uint(); ns = ctypes.c_uint(); nm = ctypes.c_uint()
    tm = ctypes.c_int()
    _check(lib, lib.MXFuncDescribe(fh, ctypes.byref(nu), ctypes.byref(ns),
                                   ctypes.byref(nm), ctypes.byref(tm)))
    assert nu.value == 1
    # Rtc is documented-unsupported and must fail loudly, not crash
    assert lib.MXRtcFree(None) != 0
