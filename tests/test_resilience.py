"""Fault-tolerant training (mxnet_tpu/resilience/): atomic checkpoints,
preemption recovery, non-finite-gradient guards, retry/backoff, and the
chaos fault-injection harness that proves all of it end-to-end.

The headline test is kill-and-resume: a run preempted mid-epoch by the
chaos harness, whose NEWEST checkpoint the harness then corrupts, must
resume from the newest *valid* snapshot and land on the same final params
as an uninterrupted run — params, momentum, loss scale and step counter
all round-trip.
"""
import os
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.parallel.trainer import ShardedTrainer
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience.checkpoint import (CheckpointManager,
                                             restore_gluon_trainer,
                                             restore_module, restore_trainer,
                                             save_gluon_trainer, save_module,
                                             save_trainer)
from mxnet_tpu.resilience.container import (CorruptContainer, read_container,
                                            write_container)
from mxnet_tpu.resilience.guards import GradientGuard, NonFiniteError
from mxnet_tpu.resilience.retry import call_with_retry
from mxnet_tpu.test_utils import assert_almost_equal


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


@pytest.fixture(autouse=True)
def _fast_retry(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_RETRY_BACKOFF", "0.001")


# ---------------------------------------------------------------------------
# container format
# ---------------------------------------------------------------------------

def test_container_roundtrip(tmp_path):
    p = str(tmp_path / "c.mxtck")
    arrays = {"w": np.arange(12).reshape(3, 4).astype(np.float32),
              "i": np.array([1, 2, 3], np.int64)}
    write_container(p, arrays, {"step": 7, "note": "x"}, {"blob": b"\x00abc"})
    arrs, meta, blobs = read_container(p)
    assert meta["step"] == 7 and meta["note"] == "x"
    assert blobs["blob"] == b"\x00abc"
    for k in arrays:
        assert arrs[k].dtype == arrays[k].dtype
        np.testing.assert_array_equal(arrs[k], arrays[k])
    arrs["w"][0, 0] = 99   # buffers must come back writable


def test_container_rejects_pickle(tmp_path):
    p = str(tmp_path / "evil.mxtck")
    with open(p, "wb") as f:
        pickle.dump({"innocent": "looking"}, f)
    with pytest.raises(CorruptContainer, match="pickle"):
        read_container(p)


def test_container_detects_buffer_corruption(tmp_path):
    p = str(tmp_path / "c.mxtck")
    write_container(p, {"w": np.ones(64, np.float32)}, {})
    size = os.path.getsize(p)
    with open(p, "r+b") as f:      # flip bytes inside the buffer region
        f.seek(size - 30)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(CorruptContainer):
        read_container(p)


def test_checkpoint_file_has_no_pickled_code(tmp_path):
    """Acceptance: checkpoint files contain no pickled code objects —
    the whole file fails pickle.loads and the header is plain JSON."""
    mgr = CheckpointManager(str(tmp_path), keep=2)
    mgr.save(1, {"w": np.ones(4, np.float32)}, {"epoch": 0})
    mgr.wait()   # reading the FILE directly: drain the async writer
    path = mgr.path_for(1)
    raw = open(path, "rb").read()
    with pytest.raises(Exception):
        pickle.loads(raw)
    assert raw[:8] == b"MXTPURC1"
    assert b"GLOBAL" not in raw and b"c__builtin__" not in raw


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

def test_checkpoint_retention(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2)
    for s in (1, 2, 3, 4):
        mgr.save(s, {"w": np.full(3, s, np.float32)})
    assert mgr.steps() == [3, 4]
    ck = mgr.latest()
    assert ck.step == 4
    np.testing.assert_array_equal(ck.arrays["w"], np.full(3, 4, np.float32))


@pytest.mark.parametrize("mode", ["truncate", "garbage"])
def test_corrupt_latest_quarantined_and_fallback(tmp_path, mode):
    mgr = CheckpointManager(str(tmp_path), keep=3)
    for s in (1, 2, 3):
        mgr.save(s, {"w": np.full(3, s, np.float32)})
    mgr.wait()   # chaos corrupts FILES directly: drain the async writer
    assert chaos.corrupt_latest(str(tmp_path), mode=mode) is not None
    ck = mgr.latest()
    assert ck.step == 2, "must fall back to the newest VALID checkpoint"
    np.testing.assert_array_equal(ck.arrays["w"], np.full(3, 2, np.float32))
    # the corrupt file is quarantined, not deleted (post-mortem evidence)
    assert any(n.endswith(".corrupt") for n in os.listdir(str(tmp_path)))
    assert mgr.steps() == [1, 2]


def test_latest_on_empty_dir(tmp_path):
    assert CheckpointManager(str(tmp_path)).latest() is None


# ---------------------------------------------------------------------------
# ShardedTrainer: guards, chaos, kill-and-resume
# ---------------------------------------------------------------------------

def _mlp():
    from mxnet_tpu.models.mlp import get_symbol
    return get_symbol(num_classes=4)


def _batches(n, bs=16, dim=8, seed=0):
    rs = np.random.RandomState(seed)
    return [{"data": rs.rand(bs, dim).astype(np.float32),
             "softmax_label": rs.randint(0, 4, bs).astype(np.float32)}
            for _ in range(n)]


_SHAPES = {"data": (16, 8), "softmax_label": (16,)}


def _trainer(**kw):
    spec = MeshSpec(make_mesh((4,), ("dp",)))
    kw.setdefault("lr", 0.1)
    kw.setdefault("momentum", 0.9)
    kw.setdefault("wd", 0.0)
    return ShardedTrainer(_mlp(), spec, **kw)


def test_kill_and_resume_matches_uninterrupted(tmp_path):
    """THE end-to-end chaos drill: preempted mid-epoch, newest checkpoint
    corrupted, resume from the newest valid one → final params match the
    uninterrupted run."""
    batches = _batches(6)

    # uninterrupted reference run
    tr_a = _trainer()
    pa, ma, xa = tr_a.init_state(_SHAPES, seed=3)
    for b in batches:
        pa, ma, xa, _ = tr_a.step(pa, ma, xa, b)

    # faulted run: checkpoint after steps 2 and 4, preempt at step 5
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=3)
    tr_b = _trainer()
    pb, mb, xb = tr_b.init_state(_SHAPES, seed=3)
    with chaos.inject("preempt", at_step=5):
        with pytest.raises(chaos.SimulatedPreemption):
            for i, b in enumerate(batches):
                pb, mb, xb, _ = tr_b.step(pb, mb, xb, b)
                if (i + 1) % 2 == 0:
                    save_trainer(mgr, tr_b, pb, mb, xb, step=i + 1)
    assert mgr.steps() == [2, 4]
    # the newest snapshot dies too (truncated write / bit rot)
    chaos.corrupt_latest(mgr.directory)

    # recovery process: fresh trainer, restore newest VALID, resume
    tr_c = _trainer()
    restored = restore_trainer(mgr, tr_c)
    assert restored is not None
    pc, mc, xc, step, meta = restored
    assert step == 2, "corrupt step-4 ckpt must fall back to step 2"
    assert tr_c._step_count == 2
    for b in batches[step:]:
        pc, mc, xc, _ = tr_c.step(pc, mc, xc, b)

    for a, c in zip(pa, pc):
        assert_almost_equal(np.asarray(a), np.asarray(c),
                            rtol=1e-4, atol=1e-5)
    for a, c in zip(ma, mc):
        assert_almost_equal(np.asarray(a), np.asarray(c),
                            rtol=1e-4, atol=1e-5)


def test_nan_injection_skips_update_and_halves_scale():
    tr = _trainer(loss_scale=64.0, dynamic_loss_scale=True)
    params, mom, aux = tr.init_state(_SHAPES, seed=3)
    batch = _batches(1)[0]
    params, mom, aux, _ = tr.step(params, mom, aux, batch)
    before = [np.asarray(p).copy() for p in params]
    with chaos.inject("nan_grad", at_step=2):
        params, mom, aux, loss = tr.step(params, mom, aux, batch)
    for b, p in zip(before, params):
        np.testing.assert_array_equal(b, np.asarray(p)), \
            "non-finite step must not touch params"
    assert tr.loss_scale == 32.0, "loss scale must halve on a bad step"
    assert tr.skipped_steps == 1
    # training continues: next clean step applies an update again
    params, mom, aux, _ = tr.step(params, mom, aux, batch)
    assert not np.array_equal(before[0], np.asarray(params[0]))


def test_nonfinite_budget_aborts_with_diagnostics():
    tr = _trainer(nonfinite_budget=2)
    params, mom, aux = tr.init_state(_SHAPES, seed=3)
    batch = _batches(1)[0]
    with chaos.inject("nan_grad", count=10):
        with pytest.raises(NonFiniteError) as ei:
            for _ in range(6):
                params, mom, aux, _ = tr.step(params, mom, aux, batch)
    diag = ei.value.diagnostics
    assert diag["bad_streak"] == 3 and diag["skipped_steps"] == 3


def test_trainer_restore_reshards_onto_different_mesh(tmp_path):
    """A snapshot taken on a pure-dp mesh must restore onto a dp x tp
    mesh with the trainer's OWN sharding rules applied."""
    mgr = CheckpointManager(str(tmp_path), keep=2)
    tr1 = _trainer()
    p1, m1, x1 = tr1.init_state(_SHAPES, seed=5)
    save_trainer(mgr, tr1, p1, m1, x1, step=1)

    spec2 = MeshSpec(make_mesh((2, 2), ("dp", "tp")))
    tr2 = ShardedTrainer(_mlp(), spec2, lr=0.1, momentum=0.9, wd=0.0)
    p2, m2, x2, step, _ = restore_trainer(mgr, tr2)
    assert step == 1
    for n, a, b in zip(tr1.param_names, p1, p2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        want = tr2.param_sharding(n, np.asarray(b).shape)
        assert b.sharding.is_equivalent_to(want, np.asarray(b).ndim)


# ---------------------------------------------------------------------------
# retry / flaky IO
# ---------------------------------------------------------------------------

def test_call_with_retry_recovers_and_gives_up():
    calls = {"n": 0}

    def flaky(fail_times):
        calls["n"] += 1
        if calls["n"] <= fail_times:
            raise OSError("transient")
        return "ok"

    assert call_with_retry(flaky, 2, max_tries=3, backoff=0.001) == "ok"
    assert calls["n"] == 3

    calls["n"] = 0
    with pytest.raises(OSError):
        call_with_retry(flaky, 5, max_tries=3, backoff=0.001)


def test_kvstore_dist_create_retries_transient_failures():
    from mxnet_tpu import kvstore
    with chaos.inject("io_error", count=2):
        kv = kvstore.create("dist_sync")
    assert kv.type == "dist_sync"
    with chaos.inject("io_error", count=10):
        with pytest.raises(OSError):
            kvstore.create("dist_sync")


def test_record_iter_retries_flaky_reads(tmp_path):
    PIL = pytest.importorskip("PIL")  # noqa: F841
    import io as pyio
    from PIL import Image
    from mxnet_tpu import recordio

    prefix = str(tmp_path / "synth")
    rs = np.random.RandomState(0)
    writer = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(8):
        arr = rs.randint(0, 256, (16, 16, 3), dtype=np.uint8)
        buf = pyio.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG")
        hdr = recordio.IRHeader(0, float(i), i, 0)
        writer.write_idx(i, recordio.pack(hdr, buf.getvalue()))
    writer.close()

    os.environ["MXNET_TPU_NATIVE_IO"] = "0"
    try:
        it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                                   data_shape=(3, 16, 16), batch_size=4,
                                   preprocess_threads=1)
        # two transient read failures are absorbed by backoff+retry
        with chaos.inject("io_error", count=2):
            batch = it.next()
        assert batch.data[0].shape == (4, 3, 16, 16)
    finally:
        os.environ.pop("MXNET_TPU_NATIVE_IO", None)


# ---------------------------------------------------------------------------
# exact-resume iterator state (ISSUE 2: mid-epoch kill-and-resume sees
# every sample exactly once — no replay, no drop)
# ---------------------------------------------------------------------------

def _drain_labels(it):
    out = []
    while True:
        try:
            b = it.next()
        except StopIteration:
            return out
        out += list(b.label[0].asnumpy())


def test_ndarray_iter_midepoch_resume_exactly_once():
    X = np.arange(80).reshape(40, 2).astype(np.float32)
    y = np.arange(40).astype(np.float32)   # label == sample id
    it = mx.io.NDArrayIter(X, y, batch_size=8, shuffle=True)
    seen = []
    for _ in range(2):                     # 2 of 5 batches, then "die"
        seen += list(it.next().label[0].asnumpy())
    state = it.state_dict()

    # recovery process: fresh iterator over the same source, restore
    it2 = mx.io.NDArrayIter(X, y, batch_size=8, shuffle=True)
    it2.load_state_dict(state)
    rest = _drain_labels(it2)
    assert len(seen) + len(rest) == 40
    assert sorted(seen + rest) == sorted(range(40)), \
        "each sample must appear exactly once per epoch"
    # data rows ride the same permutation as labels
    it3 = mx.io.NDArrayIter(X, y, batch_size=8, shuffle=True)
    it3.load_state_dict(state)
    b = it3.next()
    np.testing.assert_array_equal(
        b.data[0].asnumpy()[:, 0] // 2, b.label[0].asnumpy())


def test_ndarray_iter_state_roundtrips_through_checkpoint(tmp_path):
    """Iterator state rides the Module checkpoint adapters (data_iter=)."""
    X = np.random.RandomState(1).rand(24, 16).astype(np.float32)
    y = (np.arange(24) % 4).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=4, shuffle=True)
    mx.seed(3)
    mod = _module()
    for _ in range(3):
        it.next()
    mgr = CheckpointManager(str(tmp_path), keep=2)
    save_module(mgr, mod, step=3, data_iter=it)

    it2 = mx.io.NDArrayIter(X, y, batch_size=4, shuffle=True)
    mx.seed(3)
    mod2 = _module()
    step, _ = restore_module(mgr, mod2, data_iter=it2)
    assert step == 3
    assert it2._pos == it._pos
    np.testing.assert_array_equal(it2._order, it._order)
    assert sorted(_drain_labels(it) + [0, 1, 2, 3] * 3) == \
        sorted(_drain_labels(it2) + [0, 1, 2, 3] * 3)


def test_ndarray_iter_state_rejects_mismatched_dataset():
    X = np.random.rand(20, 2).astype(np.float32)
    it = mx.io.NDArrayIter(X, np.zeros(20, np.float32), batch_size=4)
    state = it.state_dict()
    other = mx.io.NDArrayIter(np.random.rand(32, 2).astype(np.float32),
                              np.zeros(32, np.float32), batch_size=4)
    with pytest.raises(ValueError, match="mismatch"):
        other.load_state_dict(state)


# ---------------------------------------------------------------------------
# iterator re-shard on elastic resize (ISSUE 8: after world N -> N-1, one
# epoch still sees every sample exactly once — no replay, no drop)
# ---------------------------------------------------------------------------

def _sharded_iters(X, y, world, per_rank):
    return [mx.io.NDArrayIter(X, y, batch_size=per_rank, shuffle=True,
                              seed=7, num_parts=world, part_index=r)
            for r in range(world)]


def test_sharded_iter_covers_dataset_exactly_once():
    """Baseline: 4 parts x 12 rows walk one shuffled epoch with no
    overlap and full coverage."""
    X = np.arange(96).reshape(48, 2).astype(np.float32)
    y = np.arange(48).astype(np.float32)
    seen = []
    for it in _sharded_iters(X, y, world=4, per_rank=12):
        seen += _drain_labels(it)
    assert sorted(seen) == list(range(48))


def test_sharded_iter_reshard_midepoch_exactly_once():
    """The elastic data path: world 4 (bs 12) consumes part of an epoch,
    a rank dies, the survivors restore the SAME global cursor/order at
    world 3 (bs 16, global batch still 48) — the epoch completes with
    every sample exactly once across both incarnations."""
    X = np.arange(480).reshape(240, 2).astype(np.float32)
    y = np.arange(240).astype(np.float32)
    iters4 = _sharded_iters(X, y, world=4, per_rank=12)
    seen = []
    for _ in range(2):                    # 2 of 5 global batches, then die
        for it in iters4:
            seen += list(it.next().label[0].asnumpy())
    state = iters4[0].state_dict()        # what rank 0 checkpointed

    iters3 = _sharded_iters(X, y, world=3, per_rank=16)
    for it in iters3:
        it.load_state_dict(state)         # different split, same globals
    rest = []
    while True:
        try:
            batches = [it.next() for it in iters3]
        except StopIteration:
            break
        for b in batches:
            rest += list(b.label[0].asnumpy())
    assert len(seen) == 96 and len(rest) == 144
    assert sorted(seen + rest) == list(range(240)), \
        "resize must replay nothing and drop nothing"


def test_sharded_iter_inplace_reshard_and_next_epoch():
    """reshard() re-splits the remaining epoch in place; the following
    epoch is a clean full pass at the new world size."""
    X = np.arange(96).reshape(48, 2).astype(np.float32)
    y = np.arange(48).astype(np.float32)
    its = _sharded_iters(X, y, world=4, per_rank=4)   # global batch 16
    first = []
    for it in its:
        first += list(it.next().label[0].asnumpy())
    for r, it in enumerate(its[:2]):
        it.reshard(r, 2, batch_size=8)                # world 4 -> 2
    rest = []
    while True:
        try:
            batches = [it.next() for it in its[:2]]
        except StopIteration:
            break
        for b in batches:
            rest += list(b.label[0].asnumpy())
    assert sorted(first + rest) == list(range(48))
    for it in its[:2]:                                # next epoch at 2
        it.reset()
    again = []
    for it in its[:2]:
        again += _drain_labels(it)
    assert sorted(again) == list(range(48))


def test_sharded_iter_state_accepts_any_split_with_same_global_batch():
    X = np.arange(96).reshape(48, 2).astype(np.float32)
    y = np.arange(48).astype(np.float32)
    it4 = mx.io.NDArrayIter(X, y, batch_size=12, shuffle=True, seed=7,
                            num_parts=4, part_index=0)
    it4.next()
    state = it4.state_dict()
    assert state["num_parts"] == 4 and state["batch_size"] == 12
    # 3x16 == 4x12: accepted; 3x12 != 48: rejected
    it3 = mx.io.NDArrayIter(X, y, batch_size=16, shuffle=True, seed=7,
                            num_parts=3, part_index=1)
    it3.load_state_dict(state)
    assert it3._pos == it4._pos
    bad = mx.io.NDArrayIter(X, y, batch_size=12, shuffle=True, seed=7,
                            num_parts=3, part_index=1)
    with pytest.raises(ValueError, match="global batch"):
        bad.load_state_dict(state)


def test_sharded_iter_guardrails():
    X = np.arange(96).reshape(48, 2).astype(np.float32)
    y = np.arange(48).astype(np.float32)
    with pytest.raises(ValueError, match="seed"):
        mx.io.NDArrayIter(X, y, batch_size=12, shuffle=True, num_parts=4)
    with pytest.raises(ValueError, match="roll_over"):
        mx.io.NDArrayIter(X, y, batch_size=12, num_parts=4,
                          last_batch_handle="roll_over")
    with pytest.raises(ValueError, match="part_index"):
        mx.io.NDArrayIter(X, y, batch_size=12, num_parts=4, part_index=4)


def test_record_iter_midepoch_resume_exactly_once(tmp_path):
    """ImageRecordIter: cursor + shuffled key order + shuffle-RNG state
    round-trip, so the resumed iterator finishes the epoch exactly and
    future epochs reshuffle identically to an uninterrupted run."""
    PIL = pytest.importorskip("PIL")  # noqa: F841
    import io as pyio
    from PIL import Image
    from mxnet_tpu import recordio

    prefix = str(tmp_path / "synth")
    rs = np.random.RandomState(0)
    writer = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(12):
        arr = rs.randint(0, 256, (8, 8, 3), dtype=np.uint8)
        buf = pyio.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG")
        writer.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), buf.getvalue()))
    writer.close()

    def make():
        return mx.io.ImageRecordIter(
            path_imgrec=prefix + ".rec", data_shape=(3, 8, 8), batch_size=4,
            shuffle=True, seed=5, preprocess_threads=1)

    os.environ["MXNET_TPU_NATIVE_IO"] = "0"
    try:
        # uninterrupted reference: this epoch's order + next epoch's
        ref = make()
        ref_epoch1 = _drain_labels(ref)
        ref.reset()
        ref_epoch2 = _drain_labels(ref)

        it = make()
        seen = list(it.next().label[0].asnumpy())   # 1 of 3 batches
        state = it.state_dict()

        it2 = make()                                # fresh process analog
        it2.load_state_dict(state)
        rest = _drain_labels(it2)
        assert seen + rest == ref_epoch1, \
            "resumed epoch must replay nothing and drop nothing"
        it2.reset()
        assert _drain_labels(it2) == ref_epoch2, \
            "restored RNG state must reshuffle future epochs identically"
    finally:
        os.environ.pop("MXNET_TPU_NATIVE_IO", None)


# ---------------------------------------------------------------------------
# Module / gluon.Trainer checkpoint round-trips + guards
# ---------------------------------------------------------------------------

def _mlp_sym():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _module(seed=7):
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Xavier(rnd_type="gaussian"))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    return mod


def _module_step(mod, data, label):
    batch = mx.io.DataBatch(data=[nd.array(data)], label=[nd.array(label)])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()


def test_module_checkpoint_roundtrip(tmp_path):
    """Params + optimizer (momentum) state + step round-trip through the
    non-executable container; the resumed module continues identically."""
    rs = np.random.RandomState(0)
    data = [rs.rand(8, 16).astype(np.float32) for _ in range(4)]
    label = [rs.randint(0, 4, 8).astype(np.float32) for _ in range(4)]

    mx.seed(11)
    mod_a = _module()
    for i in range(2):
        _module_step(mod_a, data[i], label[i])
    mgr = CheckpointManager(str(tmp_path), keep=2)
    save_module(mgr, mod_a, step=2, extra_meta={"epoch": 0})

    mx.seed(11)
    mod_b = _module()
    step, meta = restore_module(mgr, mod_b)
    assert step == 2 and meta["epoch"] == 0
    # momentum must be live: continue both and compare params exactly
    for i in range(2, 4):
        _module_step(mod_a, data[i], label[i])
        _module_step(mod_b, data[i], label[i])
    args_a, _ = mod_a.get_params()
    args_b, _ = mod_b.get_params()
    for n in args_a:
        assert_almost_equal(args_a[n].asnumpy(), args_b[n].asnumpy(),
                            rtol=1e-5, atol=1e-6)


def test_module_grad_guard_skips_nonfinite_update():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Xavier())
    guard = GradientGuard(budget=5)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1},
                       grad_guard=guard)
    before, _ = mod.get_params()
    before = {n: v.asnumpy().copy() for n, v in before.items()}
    bad = np.full((8, 16), np.nan, np.float32)
    _module_step(mod, bad, np.zeros(8, np.float32))
    after, _ = mod.get_params()
    for n in before:
        np.testing.assert_array_equal(before[n], after[n].asnumpy())
    assert guard.skipped_steps == 1 and guard.bad_streak == 1


def test_gluon_trainer_checkpoint_roundtrip(tmp_path):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn as gnn

    def build(seed):
        mx.seed(seed)
        net = gnn.Dense(4, in_units=8, prefix="ckpt_dense_")
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        return net, tr

    rs = np.random.RandomState(0)
    xs = [rs.rand(8, 8).astype(np.float32) for _ in range(4)]

    def one_step(net, tr, x):
        with mx.autograd.record():
            y = net(nd.array(x))
            loss = (y * y).sum()
        mx.autograd.backward([loss])
        tr.step(batch_size=8)

    net_a, tr_a = build(21)
    for x in xs[:2]:
        one_step(net_a, tr_a, x)
    mgr = CheckpointManager(str(tmp_path), keep=2)
    save_gluon_trainer(mgr, tr_a, step=2)

    net_b, tr_b = build(22)   # different init — restore must overwrite
    tr_b._ready                # force updater/kvstore resolution
    step, _ = restore_gluon_trainer(mgr, tr_b)
    assert step == 2
    for x in xs[2:]:
        one_step(net_a, tr_a, x)
        one_step(net_b, tr_b, x)
    for pa, pb in zip(tr_a._params, tr_b._params):
        assert_almost_equal(pa.data().asnumpy(), pb.data().asnumpy(),
                            rtol=1e-5, atol=1e-6)


def test_gluon_trainer_guard_skips_nonfinite():
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn as gnn

    mx.seed(5)
    net = gnn.Dense(4, in_units=8)
    net.initialize(mx.init.Xavier())
    guard = GradientGuard(budget=3)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, grad_guard=guard)
    before = [p.data().asnumpy().copy() for p in tr._params]
    with mx.autograd.record():
        y = net(nd.array(np.full((8, 8), np.nan, np.float32)))
        loss = (y * y).sum()
    mx.autograd.backward([loss])
    tr.step(batch_size=8)
    for b, p in zip(before, tr._params):
        np.testing.assert_array_equal(b, p.data().asnumpy())
    assert guard.skipped_steps == 1


def test_gradient_guard_budget_raises():
    guard = GradientGuard(budget=2)
    bad = [np.array([np.nan], np.float32)]
    assert guard.step(bad) is False
    assert guard.step(bad) is False
    with pytest.raises(NonFiniteError):
        guard.step(bad)


# ---------------------------------------------------------------------------
# chaos env parsing
# ---------------------------------------------------------------------------

def test_chaos_env_spec(monkeypatch):
    chaos.reset()
    monkeypatch.setenv("MXNET_TPU_CHAOS", "nan_grad@3,io_errorx2")
    assert chaos.fire("nan_grad", step=2) is None
    assert chaos.fire("nan_grad", step=3) is not None
    assert chaos.fire("nan_grad", step=3) is None   # consumed
    assert chaos.fire("io_error") is not None
    assert chaos.fire("io_error") is not None
    assert chaos.fire("io_error") is None
    chaos.reset()
