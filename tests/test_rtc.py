"""Runtime custom kernels (reference python/mxnet/rtc.py CudaModule ->
TPU-native rtc.TPUModule over Pallas)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, rtc


def test_custom_axpy_kernel():
    def axpy(x_ref, y_ref, out_ref, *, alpha):
        out_ref[:] = x_ref[:] * alpha + y_ref[:]

    mod = rtc.TPUModule({"axpy": axpy})
    k = mod.get_kernel("axpy", out_shapes=[(8, 128)], alpha=2.0)
    rs = np.random.RandomState(0)
    x = nd.array(rs.rand(8, 128).astype(np.float32))
    y = nd.array(rs.rand(8, 128).astype(np.float32))
    (out,) = k.launch([x, y])
    np.testing.assert_allclose(out.asnumpy(), 2.0 * x.asnumpy() + y.asnumpy(),
                               rtol=1e-6)


def test_custom_kernel_with_grid():
    from jax.experimental import pallas as pl

    def double(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0

    mod = rtc.TPUModule(double)   # single callable: name from __name__
    k = mod.get_kernel(
        "double", out_shapes=[(16, 128)], grid=(2,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)))
    x = nd.ones((16, 128))
    (out,) = k.launch([x])
    assert (out.asnumpy() == 2.0).all()
    # reference launch signature: grid override at launch time
    (out2,) = k.launch([x], grid_dims=(2,))
    assert (out2.asnumpy() == 2.0).all()


def test_multi_output_and_errors():
    def split_sign(x_ref, pos_ref, neg_ref):
        import jax.numpy as jnp
        pos_ref[:] = jnp.maximum(x_ref[:], 0.0)
        neg_ref[:] = jnp.minimum(x_ref[:], 0.0)

    mod = rtc.TPUModule({"split_sign": split_sign})
    k = mod.get_kernel("split_sign", out_shapes=[(8, 128), (8, 128)])
    x = nd.array(np.random.RandomState(1).randn(8, 128).astype(np.float32))
    pos, neg = k.launch([x])
    np.testing.assert_allclose(pos.asnumpy() + neg.asnumpy(), x.asnumpy(),
                               rtol=1e-6)
    with pytest.raises(mx.base.MXNetError):
        mod.get_kernel("nope", out_shapes=[(1,)])
    with pytest.raises(mx.base.MXNetError):
        rtc.CudaModule("__global__ void k() {}")


def test_launch_ctx_placement():
    def ident(x_ref, o_ref):
        o_ref[:] = x_ref[:]

    k = rtc.TPUModule(ident).get_kernel("ident", out_shapes=[(8, 128)])
    x = nd.ones((8, 128))
    (out,) = k.launch([x], ctx=mx.cpu(0))
    assert out.context.device_type == "cpu"
