"""The dist_async robustness drills (ISSUE 19 acceptance): real worker
PROCESSES against a real server over the wire.

* straggler survival — 4 workers, ``hedge_lag`` chaos pinned to rank 3
  via ``MXNET_TPU_CHAOS_RANKS``; the async lane must keep the healthy
  workers at full speed (strictly higher aggregate throughput than the
  K=0 lockstep run under the SAME straggler) while still converging.
* server SIGKILL — the supervised server process is killed mid-stream;
  the supervisor relaunches it, it restores from its checkpoint, the
  worker's retry/backoff rides out the outage, and no push is ever
  double-applied (a retransmit of a restored version is acked-not-
  applied).
* worker kill -9 — a SIGKILLed worker costs exactly its own in-flight
  contribution: the survivor completes every step, the corpse is evicted
  from the staleness set, its applied pushes stay applied.
"""
import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

from mxnet_tpu.kvstore import protocol
from mxnet_tpu.kvstore.client import PSClient
from mxnet_tpu.kvstore.server import KVServer, launch_server

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist", "ps_async_worker.py")


def _spawn_worker(kv_dir, rank, world, extra_env=None):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env.update({"JAX_PLATFORMS": "cpu",
                "MXNET_TPU_KV_DIR": str(kv_dir),
                "MXNET_TPU_KV_RANK": str(rank),
                "MXNET_TPU_KV_WORLD": str(world)})
    env.update(extra_env or {})
    return subprocess.Popen([sys.executable, WORKER],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT,
                            text=True, env=env, cwd=REPO)


def _parse_ok(out):
    m = re.search(r"PSWORKER rank=(\d+) steps=(\d+) "
                  r"eval_loss=([0-9.eE+-]+) OK", out)
    assert m, out[-2000:]
    return int(m.group(2)), float(m.group(3))


def _run_fleet(kv_dir, world, seconds, staleness, chaos_env):
    """One time-boxed 4-worker run against a fresh in-process server;
    returns {rank: (steps, eval_loss)}."""
    srv = KVServer(str(kv_dir), world=world, staleness=staleness,
                   ckpt_interval=0, pull_timeout=20.0)
    srv.serve_in_thread()
    try:
        procs = [_spawn_worker(kv_dir, r, world,
                               {"PS_SECONDS": str(seconds),
                                "PS_BARRIER": "1", **chaos_env})
                 for r in range(world)]
        results = {}
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=180)
            assert p.returncode == 0, "rank %d:\n%s" % (r, out[-2000:])
            results[r] = _parse_ok(out)
        return results
    finally:
        srv.stop()


@pytest.mark.slow
def test_straggler_async_beats_lockstep(tmp_path):
    """THE throughput acceptance: same straggler (hedge_lag 0.25s/step
    pinned to rank 3), same wall-clock box — the async lane's aggregate
    step count must strictly beat bounded-K=0 lockstep, the healthy
    workers must run far ahead of the straggler, and both lanes must
    still converge on the toy problem."""
    chaos_env = {"MXNET_TPU_CHAOS": "hedge_lagx1000000",
                 "MXNET_TPU_CHAOS_RANKS": "3",
                 "MXNET_TPU_CHAOS_HEDGE_LAG_SECONDS": "0.25"}
    seconds = 6.0
    res_async = _run_fleet(tmp_path / "async", 4, seconds,
                           staleness=None, chaos_env=chaos_env)
    res_sync = _run_fleet(tmp_path / "sync", 4, seconds,
                          staleness=0, chaos_env=chaos_env)

    agg_async = sum(s for s, _ in res_async.values())
    agg_sync = sum(s for s, _ in res_sync.values())
    straggler = res_async[3][0]
    healthy_min = min(res_async[r][0] for r in range(3))
    # the straggler cannot stall the async lane...
    assert healthy_min >= 3 * max(1, straggler), res_async
    # ...and lockstep pays for the same straggler with aggregate
    # throughput the async lane strictly beats
    assert agg_async > agg_sync, (res_async, res_sync)
    # lockstep really was lockstep: nobody ran more than a few steps
    # ahead of the straggler (K=0 pins everyone to its pace once its
    # first push enters the clock set)
    spread = max(s for s, _ in res_sync.values()) - \
        min(s for s, _ in res_sync.values())
    assert spread <= 4, res_sync
    # convergence within a bounded gap of sync (toy noise floor ~5e-5,
    # init loss ~1.3)
    loss_async = min(l for _, l in res_async.values())
    loss_sync = min(l for _, l in res_sync.values())
    assert loss_async < 0.02, res_async
    assert loss_async < loss_sync + 0.02, (loss_async, loss_sync)


def test_server_sigkill_recovery(tmp_path):
    """SIGKILL the supervised server mid-stream: relaunch + checkpoint
    restore + worker retry/backoff, and exactly-once across the crash —
    a retransmit of a restored version is acked-not-applied, every
    version the restored server counts is reflected in the weights."""
    kv_dir = str(tmp_path)
    sup = launch_server(kv_dir, world=1,
                        env={"JAX_PLATFORMS": "cpu",
                             "MXNET_TPU_KV_CKPT_INTERVAL": "5"},
                        restart_backoff=0.2)
    try:
        os.environ.pop("MXNET_TPU_CHAOS", None)
        c = PSClient(kv_dir, rank=0, connect_timeout=60)
        w0 = np.full(8, 4.0, np.float32)
        g = np.full(8, 0.125, np.float32)
        c.init("w", w0)
        c.set_optimizer("sgd", {"learning_rate": 1.0})
        for _ in range(12):
            c.push("w", g)
        epoch0 = c.server_epoch

        sup.kill()                 # -9: no checkpoint-on-exit, no goodbye
        c.close()                  # the worker's socket dies with it

        # the worker just keeps going: retry/backoff + re-resolve rides
        # out the outage, the relaunched server restores from its newest
        # checkpoint (interval 5 -> versions 1..10 are durable)
        for _ in range(8):
            r = c.push("w", g)
            assert r["applied"] is True
        assert c.server_epoch >= epoch0 + 1
        # retransmit of a version the restored checkpoint already holds
        reply, _ = c.call({"op": "push", "key": "w", "worker": 0,
                           "version": 3}, {"grad": g})
        assert reply["applied"] is False

        stats = c.stats()
        applied = dict(((w, k), v) for w, k, v in stats["applied"])
        # the crash window (versions 11-12, acked after the last durable
        # checkpoint) is lost; the register reply resynced the worker's
        # counter to the restored dedup table, so those version numbers
        # were RE-USED for the 8 post-crash gradients: 10 + 8
        total = applied[(0, "w")]
        assert total == 18
        assert c.applied["w"] == 18
        value, _ = c.pull("w")
        assert np.isfinite(value).all()
        # every version the server COUNTS is in the weights exactly once
        # (constant grad: value is a pure function of the apply count);
        # versions lost to the crash window are NOT silently half-applied
        versions = stats["versions"]["w"]
        assert versions == 18
        assert np.array_equal(value, w0 - versions * g)

        evs = [e["event"] for e in protocol.read_events(kv_dir)]
        assert evs.count("listen") >= 2, evs     # relaunch re-published
        assert "restore" in evs and "checkpoint" in evs
        c.close()
    finally:
        sup.stop()


def test_worker_kill9_costs_only_its_contribution(tmp_path):
    """kill -9 on a worker mid-run: the survivor completes every step,
    the corpse is evicted (it can never gate an SSP pull again), and its
    already-applied pushes stay applied."""
    kv_dir = str(tmp_path)
    srv = KVServer(kv_dir, world=2, staleness=None, ckpt_interval=0)
    srv.serve_in_thread()
    try:
        chaos_env = {"MXNET_TPU_CHAOS": "replica_crash@8",
                     "MXNET_TPU_CHAOS_RANKS": "1"}
        procs = [_spawn_worker(kv_dir, r, 2, {"PS_STEPS": "25",
                                              **chaos_env})
                 for r in range(2)]
        out0, _ = procs[0].communicate(timeout=180)
        out1, _ = procs[1].communicate(timeout=180)
        assert procs[0].returncode == 0, out0[-2000:]
        assert procs[1].returncode == -9, (procs[1].returncode,
                                           out1[-2000:])
        steps0, loss0 = _parse_ok(out0)
        assert steps0 == 25                  # survivor lost NOTHING
        assert "PSWORKER" not in out1        # the corpse never reported

        with srv._lock:
            applied = dict(srv._applied)
            alive = {w for w, n in srv._alive.items() if n > 0}
        assert applied[(0, "w")] == 25
        # the victim pushed steps 0..7 before the kill at step 8; every
        # one of those is still applied, nothing after
        assert applied[(1, "w")] == 8
        assert 1 not in alive
        evs = [e["event"] for e in protocol.read_events(kv_dir)]
        assert "evict" in evs
    finally:
        srv.stop()
