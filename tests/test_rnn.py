"""Symbolic RNN cell tests (reference tests/python/unittest/test_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


def test_rnn_cell_unroll_shapes():
    for cell_cls, name in [(mx.rnn.RNNCell, "rnn_"), (mx.rnn.LSTMCell, "lstm_"),
                           (mx.rnn.GRUCell, "gru_")]:
        cell = cell_cls(10, prefix=name)
        inputs = [sym.Variable("t%d_data" % i) for i in range(3)]
        outputs, states = cell.unroll(3, inputs)
        outputs = sym.Group(outputs)
        arg_shapes, out_shapes, _ = outputs.infer_shape(
            t0_data=(4, 7), t1_data=(4, 7), t2_data=(4, 7))
        assert out_shapes == [(4, 10)] * 3


def test_lstm_forward_matches_fused():
    """Unrolled LSTMCell == FusedRNNCell given packed weights."""
    T, N, C, H = 4, 2, 5, 6
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="l_")
    cell = fused.unfuse()
    data = sym.Variable("data")
    f_out, _ = fused.unroll(T, data, layout="NTC", merge_outputs=True)
    c_out, _ = cell.unroll(T, data, layout="NTC", merge_outputs=True)

    rs = np.random.RandomState(0)
    x = rs.rand(N, T, C).astype(np.float32)
    from mxnet_tpu.ops.rnn import rnn_param_size
    nparam = rnn_param_size(1, C, H, False, "lstm")
    blob = (rs.rand(nparam).astype(np.float32) - 0.5) * 0.2

    ex_f = f_out.bind(mx.cpu(), {"data": nd.array(x),
                                 "l_parameters": nd.array(blob)})
    res_f = ex_f.forward()[0].asnumpy()

    # unpack blob into per-gate cell weights
    cell_args = {"data": nd.array(x)}
    h = H
    wx = blob[:4 * H * C].reshape(4 * H, C)
    wh = blob[4 * H * C:4 * H * (C + H)].reshape(4 * H, H)
    bx = blob[4 * H * (C + H):4 * H * (C + H) + 4 * H]
    bh = blob[4 * H * (C + H) + 4 * H:]
    cell_args["l_l0_i2h_weight"] = nd.array(wx)
    cell_args["l_l0_h2h_weight"] = nd.array(wh)
    cell_args["l_l0_i2h_bias"] = nd.array(bx)
    cell_args["l_l0_h2h_bias"] = nd.array(bh)
    ex_c = c_out.bind(mx.cpu(), cell_args)
    res_c = ex_c.forward()[0].asnumpy()
    assert_almost_equal(res_f, res_c, rtol=1e-4, atol=1e-5)


def test_bidirectional_cell():
    cell = mx.rnn.BidirectionalCell(mx.rnn.LSTMCell(4, prefix="l_"),
                                    mx.rnn.LSTMCell(4, prefix="r_"))
    inputs = [sym.Variable("t%d_data" % i) for i in range(3)]
    outputs, _ = cell.unroll(3, inputs)
    outputs = sym.Group(outputs)
    _, out_shapes, _ = outputs.infer_shape(
        t0_data=(2, 5), t1_data=(2, 5), t2_data=(2, 5))
    assert out_shapes == [(2, 8)] * 3


def test_residual_zoneout_dropout_cells():
    base = mx.rnn.GRUCell(6, prefix="g_")
    res = mx.rnn.ResidualCell(base)
    inputs = [sym.Variable("t%d_data" % i) for i in range(2)]
    outputs, _ = res.unroll(2, inputs)
    _, out_shapes, _ = sym.Group(outputs).infer_shape(
        t0_data=(3, 6), t1_data=(3, 6))
    assert out_shapes == [(3, 6)] * 2

    zo = mx.rnn.ZoneoutCell(mx.rnn.RNNCell(4, prefix="z_"),
                            zoneout_outputs=0.3)
    outputs, _ = zo.unroll(2, [sym.Variable("u%d" % i) for i in range(2)])
    assert len(outputs) == 2

    do = mx.rnn.DropoutCell(0.5)
    outputs, _ = do.unroll(2, [sym.Variable("v%d" % i) for i in range(2)])
    assert len(outputs) == 2


def test_sequential_stack_unroll():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(8, prefix="l0_"))
    stack.add(mx.rnn.LSTMCell(4, prefix="l1_"))
    outputs, states = stack.unroll(3, sym.Variable("data"),
                                   merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(2, 3, 10))
    assert out_shapes == [(2, 3, 4)]
    assert len(states) == 4  # 2 cells x (h, c)


def test_bucket_sentence_iter():
    sents = [[1, 2, 3], [4, 5], [1, 2, 3, 4, 5, 6], [7, 8, 9], [1, 2]]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=1, buckets=[3, 6],
                                   invalid_label=0)
    seen = 0
    for batch in it:
        assert batch.bucket_key in (3, 6)
        assert batch.data[0].shape[1] == batch.bucket_key
        seen += 1
    assert seen == 5  # 2-length sentences padded into bucket 3


def test_encode_sentences():
    sents, vocab = mx.rnn.encode_sentences([["a", "b"], ["b", "c"]],
                                           invalid_label=0, start_label=1)
    assert len(vocab) >= 3
    assert sents[0][1] == sents[1][0]  # "b" same id


def test_rnn_save_load_checkpoint(tmp_path):
    cell = mx.rnn.FusedRNNCell(6, num_layers=1, mode="lstm", prefix="l_")
    data = sym.Variable("data")
    out, _ = cell.unroll(3, data, layout="NTC", merge_outputs=True)
    from mxnet_tpu.ops.rnn import rnn_param_size
    nparam = rnn_param_size(1, 4, 6, False, "lstm")
    args = {"l_parameters": nd.array(np.random.rand(nparam).astype(np.float32))}
    prefix = str(tmp_path / "rnnmodel")
    mx.rnn.save_rnn_checkpoint(cell, prefix, 1, out, args, {})
    sym2, arg2, aux2 = mx.rnn.load_rnn_checkpoint(cell, prefix, 1)
    assert_almost_equal(arg2["l_parameters"].asnumpy(),
                        args["l_parameters"].asnumpy(), rtol=1e-6)
