"""CI gate against reference transcription.

Runs the full-tree normalized-line overlap sweep (tools/overlap_check.py)
and fails if ANY mxnet_tpu source file shares >=45% of its non-trivial
lines verbatim with its reference counterpart.  The sweep resolves
counterparts structurally (same relative path / collapsed path / unique
basename anywhere in the reference python tree), so newly added files are
covered automatically — rewrites cannot be cherry-picked to a named list.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = "/root/reference/python/mxnet"


@pytest.mark.skipif(not os.path.isdir(REFERENCE),
                    reason="reference tree not present on this host")
def test_no_file_is_a_reference_transcription():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "overlap_check.py"),
         "--sweep", "45"],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, \
        "overlap sweep found transcription-band files:\n" + proc.stdout
