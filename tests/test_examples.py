"""Smoke-run every example script (the reference keeps examples working
via nightly runs; here they are part of CI).  Each runs in its own
process on the CPU backend and must print its final 'OK' line."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# heavyweight scripts (tier-1 runs `-m 'not slow'` under a time budget;
# the PR-16 re-profile on the 1-core rig added the 8-20 s scripts below —
# their model families keep symbol/module coverage in test_model_symbols
# and ~19 faster example scripts stay in the default selection)
_SLOW = {"detection/train_ssd_toy.py", "captcha/ocr_ctc.py",
         "capsnet/capsnet_digits.py",
         "deep_embedded_clustering/dec_digits.py",
         "fcn_xs/fcn_segmentation.py",
         "detection/train_frcnn_toy.py",
         "gan/dcgan.py",
         "reinforcement_learning/dqn_gridworld.py"}

EXAMPLES = [
    ("image_classification/train_mlp.py", "train_mlp example OK"),
    ("rnn/char_lm_bucketing.py", "char_lm_bucketing example OK"),
    ("long_context/ring_transformer.py", "ring_transformer example OK"),
    ("moe/switch_ffn.py", "switch_ffn example OK"),
    ("sparse/linear_classification.py",
     "sparse linear_classification example OK"),
    ("sparse/symbolic_sparse_lr.py", "symbolic_sparse_lr example OK"),
    ("model_parallel/two_stage.py", "model_parallel two_stage example OK"),
    ("profiler/profile_mlp.py", "profile_mlp example OK"),
    ("gan/dcgan.py", "dcgan example OK"),
    ("recommenders/matrix_factorization.py",
     "matrix_factorization example OK"),
    ("detection/train_ssd_toy.py", "train_ssd_toy example OK"),
    ("detection/train_frcnn_toy.py", "train_frcnn_toy example OK"),
    ("speech_recognition/train_ctc_toy.py", "train_ctc_toy example OK"),
    ("neural_style/neural_style.py", "neural_style example OK"),
    ("reinforcement_learning/dqn_gridworld.py", "dqn_gridworld example OK"),
    ("cnn_text_classification/text_cnn.py", "text_cnn example OK"),
    ("adversary/fgsm.py", "fgsm example OK"),
    ("multi_task/multi_task_digits.py", "multi_task example OK"),
    ("autoencoder/autoencoder_digits.py", "autoencoder example OK"),
    ("bi_lstm_sort/bi_lstm_sort.py", "bi_lstm_sort example OK"),
    ("svm/svm_digits.py", "svm_digits example OK"),
    ("fcn_xs/fcn_segmentation.py", "fcn_segmentation example OK"),
    ("vae/vae_digits.py", "vae example OK"),
    ("time_series/lstm_forecast.py", "lstm_forecast example OK"),
    ("nce_loss/nce_lm.py", "nce_lm example OK"),
    ("stochastic_depth/sd_digits.py", "sd_digits example OK"),
    ("bayesian_methods/sgld_regression.py", "sgld_regression example OK"),
    ("captcha/ocr_ctc.py", "ocr_ctc example OK"),
    ("deep_embedded_clustering/dec_digits.py", "dec_digits example OK"),
    ("dsd/dsd_digits.py", "dsd_digits example OK"),
    ("capsnet/capsnet_digits.py", "capsnet example OK"),
]


@pytest.mark.parametrize(
    "script,ok_line",
    [pytest.param(s, ok, marks=pytest.mark.slow) if s in _SLOW
     else (s, ok) for s, ok in EXAMPLES],
    ids=[s for s, _ in EXAMPLES])
def test_example_runs(script, ok_line):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "example", script)],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert ok_line in r.stdout, r.stdout[-1000:]


def test_real_data_convergence_digits():
    """Real-pixel convergence assertion (reference
    tests/python/train/test_conv.py trains MNIST to an accuracy bar):
    the digits CLI must reach >=0.90 held-out accuracy on the bundled
    real scanned-digit dataset in a short run."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "example", "image_classification",
                      "train_digits.py"),
         "--num-epochs", "12", "--target", "0.90"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "CONVERGED" in r.stdout, r.stdout[-1000:]


def test_train_imagenet_cli(tmp_path):
    """The flagship CLI (reference example/image-classification/
    train_imagenet.py + common/fit.py): one command trains through the
    public API — model zoo symbol, ImageRecordIter (native pipeline when
    built), kvstore, Speedometer, checkpoint + resume."""
    import io as pyio

    import numpy as np
    from PIL import Image

    from mxnet_tpu import recordio

    rec = tmp_path / "train.rec"
    w = recordio.MXIndexedRecordIO(str(tmp_path / "train.idx"), str(rec),
                                   "w")
    rs = np.random.RandomState(0)
    for i in range(64):
        arr = rs.randint(0, 256, (36, 36, 3), dtype=np.uint8)
        buf = pyio.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG")
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 4), i, 0), buf.getvalue()))
    w.close()

    prefix = str(tmp_path / "ckpt" / "lenet")
    (tmp_path / "ckpt").mkdir()
    script = os.path.join(REPO, "example", "image_classification",
                          "train_imagenet.py")
    common = [sys.executable, script, "--data-train", str(rec),
              "--network", "lenet", "--image-shape", "3,28,28",
              "--num-classes", "4", "--num-examples", "64",
              "--batch-size", "16", "--disp-batches", "2",
              "--kv-store", "local", "--model-prefix", prefix]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(common + ["--num-epochs", "1"], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "train_imagenet OK" in r.stdout
    assert os.path.isfile(prefix + "-0001.params")
    # resume from the checkpoint
    r2 = subprocess.run(common + ["--num-epochs", "2", "--load-epoch", "1"],
                        env=env, cwd=REPO, capture_output=True, text=True,
                        timeout=420)
    assert r2.returncode == 0, r2.stdout[-2000:] + r2.stderr[-2000:]
    assert "Resumed from" in r2.stderr + r2.stdout
    assert os.path.isfile(prefix + "-0002.params")
