"""Smoke-run every example script (the reference keeps examples working
via nightly runs; here they are part of CI).  Each runs in its own
process on the CPU backend and must print its final 'OK' line."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXAMPLES = [
    ("image_classification/train_mlp.py", "train_mlp example OK"),
    ("rnn/char_lm_bucketing.py", "char_lm_bucketing example OK"),
    ("long_context/ring_transformer.py", "ring_transformer example OK"),
    ("moe/switch_ffn.py", "switch_ffn example OK"),
    ("sparse/linear_classification.py",
     "sparse linear_classification example OK"),
    ("model_parallel/two_stage.py", "model_parallel two_stage example OK"),
    ("profiler/profile_mlp.py", "profile_mlp example OK"),
]


@pytest.mark.parametrize("script,ok_line",
                         EXAMPLES, ids=[s for s, _ in EXAMPLES])
def test_example_runs(script, ok_line):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "example", script)],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert ok_line in r.stdout, r.stdout[-1000:]
