"""Multi-process distributed tests, run through tools/launch.py local mode
(the reference dmlc-tracker trick: tests/nightly/test_all.sh:55
`tools/launch.py -n 4 python dist_sync_kvstore.py`).

Each case forks 4 real processes that initialise jax.distributed over a
gloo CPU backend and must all exit 0.

The 19-49 s drills (elastic resize/notice, hang watchdog, async train)
are @slow per the PR-16 tier-1 re-profile: 4-proc gangs on the 1-core
rig are both the slowest and the most load-fragile cases; the default
selection keeps sync kvstore, mlp train, and the elastic full-restart
path, and every @slow drill's machinery retains fast unit coverage
(test_elastic.py, test_watchdog.py, test_kvstore_ps.py).
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(REPO, "tools", "launch.py")


def _run_dist(script, n=4, timeout=420, launch_args=(), extra_env=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # workers self-configure cpu+gloo
    env.update(extra_env or {})
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", str(n), *launch_args, sys.executable,
         os.path.join(REPO, "tests", "dist", script)],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    # count occurrences, not lines: ranks finishing simultaneously can
    # interleave their stdout writes onto one line
    n_ok = out.count(" OK")
    assert n_ok == n, (n_ok, out[-1500:])
    return out


def test_dist_sync_kvstore_4proc():
    """push/pull/barrier/allreduce invariants across 4 ranks (reference
    tests/nightly/dist_sync_kvstore.py)."""
    _run_dist("dist_sync_kvstore.py")


def test_dist_train_mlp_4proc():
    """Module.fit with kvstore('dist_sync') over 4 ranks: converges and
    all ranks hold identical params (reference dist_lenet.py analog)."""
    _run_dist("dist_train_mlp.py")


def test_dist_elastic_restart_4proc(tmp_path):
    """Checkpoint-restart elasticity: rank 1 crashes mid-training, the
    launcher (--max-restarts 1) relaunches the gang, training resumes
    from the checkpoint and converges (SURVEY §5.3 failure model)."""
    out = _run_dist("dist_elastic_train.py",
                    launch_args=("--max-restarts", "1"),
                    extra_env={"ELASTIC_CKPT_DIR": str(tmp_path)})
    assert "CRASHING" in out and "restart 1/1" in out


def _run_elastic(mode, tmp_path, final_world, timeout=420):
    """Run the elastic-resize drill through the ELASTIC launcher and
    return its combined output (asserts rc 0 + one OK per final rank)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # workers self-configure cpu+gloo
    env.pop("XLA_FLAGS", None)      # ... with ONE local device per rank
    env.update({"ELASTIC_CKPT_DIR": str(tmp_path),
                "ELASTIC_DRILL_MODE": mode,
                "MXNET_TPU_TELEMETRY": "1",
                # the compile-time plane (PR 13): persistent executable
                # cache + warm standby armed for every generation, trace
                # sinks in the drill dir so warmness is provable post-hoc
                "MXNET_TPU_COMPILE_CACHE": str(tmp_path / "compile-cache"),
                "MXNET_TPU_TRACE": "1"})
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", "4", "--elastic", "--min-workers",
         "3", "--elastic-dir", str(tmp_path), sys.executable,
         os.path.join(REPO, "tests", "dist", "dist_elastic_resize.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    assert out.count(" OK") == final_world, out[-1500:]
    return out


@pytest.mark.slow
def test_dist_elastic_resize_4proc(tmp_path):
    """THE elastic acceptance drill (ROADMAP item 5): rank 1 is
    hard-preempted mid-epoch; the 3 survivors agree on membership over
    the heartbeat-lane KV, re-form a 3-rank mesh from the latest
    checkpoint (resuming within one update, grad-accum 3->4 so the
    global batch stays 48), then grow back to 4 ranks when the launcher
    re-advertises capacity, and finish with params/loss matching the
    uninterrupted baseline.  The fleet view carries the generation bump
    + world-size column and both resize events."""
    import json

    out = _run_elastic("kill", tmp_path, final_world=4)
    assert "PREEMPTED at update 8" in out
    assert "[launch] elastic resize: generation 1, world 4 -> 3" in out
    assert "[launch] elastic resize: generation 2, world 3 -> 4" in out
    assert "RESUMED gen=1 world=3 updates=7 accum=4" in out
    assert "RESUMED gen=2 world=4 updates=14 accum=3" in out
    assert "generation 2  world 4" in out          # fleet view header
    assert "resize: generation 1 -> world 3" in out
    assert "resize: generation 2 -> world 4" in out

    # zero in-drill compilation (ROADMAP item 5 acceptance): every
    # resized rank asserted its compile events were all cache hits —
    # 3 ranks at gen 1 + 4 at gen 2
    assert out.count("WARM compile by_result=") == 7, out[-1500:]
    assert "MANIFEST precompiled world3=" in out

    # the committed manifests ARE the resize record the tooling renders
    with open(tmp_path / "elastic-manifest-g0001.json") as f:
        m1 = json.load(f)
    assert m1["world_size"] == 3 and m1["dead"] == [1]
    # the manifest records the pre-compiled generation (warm standby)
    assert m1["precompiled"]["worlds"]["world3"]["result"] in (
        "standby", "hit"), m1
    with open(tmp_path / "elastic-manifest-g0002.json") as f:
        m2 = json.load(f)
    assert m2["world_size"] == 4 and m2["reason"] == "grow_back"

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "postmortem.py"),
         "--elastic", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    assert "ELASTIC RESIZE TIMELINE" in r.stdout
    assert "4 -> 3" in r.stdout and "3 -> 4" in r.stdout

    # the drill's trace sinks carry the compile/* spans: tracewatch
    # --check must merge them orphan-free, and postmortem --compile
    # renders the hit/miss timeline + cache stats
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tracewatch.py"),
         str(tmp_path), "--check",
         "--out", str(tmp_path / "merged-trace.json")],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "postmortem.py"),
         str(tmp_path), "--compile",
         "--cache-dir", str(tmp_path / "compile-cache")],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "COMPILE TIMELINE" in r.stdout
    assert "hit=" in r.stdout        # summary counts warm loads
    assert "CACHE" in r.stdout       # entry/quarantine stats rendered


@pytest.mark.slow
def test_dist_elastic_notice_4proc(tmp_path):
    """The graceful path: rank 1 gets a preemption NOTICE (chaos
    preempt_notice with a grace window), checkpoints-then-exits cleanly
    at the agreed hand-off step, and the 3 survivors resize with ZERO
    lost updates (no failed collective anywhere), finishing at the
    reduced size with the same loss as the uninterrupted run."""
    out = _run_elastic("notice", tmp_path, final_world=3)
    assert "preemption notice (30.0s grace)" in out
    assert "leaving cleanly" in out
    assert "[launch] elastic resize: generation 1, world 4 -> 3" in out
    # graceful = nothing lost: survivors resume exactly after the
    # hand-off update
    assert "RESUMED gen=1 world=3 updates=9 accum=4" in out
    assert "resize: generation 1 -> world 3 (from 4, peer_preempt_notice)" \
        in out
    # the graceful resize is warm too: the 3 survivors' first step at
    # world 3 deserialized the standby executable
    assert out.count("WARM compile by_result=") == 3, out[-1500:]


@pytest.mark.slow
def test_dist_async_train_4proc():
    """Module.fit with kvstore('dist_async') over 4 ranks stepping at
    different speeds: no deadlock, per-rank convergence, identical params
    after sync_weights (reference kvstore_dist_server.h:503 semantics)."""
    _run_dist("dist_async_train.py")


@pytest.mark.slow
def test_dist_hang_watchdog_4proc(tmp_path):
    """Silent-hang e2e drill (ISSUE 2 acceptance): rank 1 stalls inside
    the fit step; the watchdog fires within its deadline, dumps stacks +
    a post-mortem naming the stuck frame into the checkpoint dir, and
    fail-fasts; the launcher relaunches and training resumes from the
    newest checkpoint and converges."""
    import glob
    import json

    out = _run_dist("dist_hang_watchdog.py",
                    launch_args=("--max-restarts", "1"),
                    extra_env={"HANG_CKPT_DIR": str(tmp_path),
                               "MXNET_TPU_TELEMETRY": "1"})
    assert "chaos: rank hanging" in out
    assert "restart 1/1" in out

    reports = sorted(glob.glob(str(tmp_path / "watchdog-postmortem-*.json")))
    assert reports, "watchdog must leave a post-mortem next to the ckpts"
    stalled = []
    for path in reports:
        with open(path) as f:
            rep = json.load(f)
        assert rep["kind"] == "watchdog_postmortem"
        assert rep["action"] == "abort"
        assert os.path.isfile(rep["stack_dump"])
        funcs = [f["function"] for f in (rep["stuck_frames"] or [])]
        if "maybe_hang" in funcs:       # the stalled rank's report
            stalled.append(rep)
            assert rep["tag"] == "Module.fit step"
            assert "maybe_hang" in open(rep["stack_dump"]).read()
            # ISSUE 5: the post-mortem shows what the process was DOING —
            # a recent metrics window (telemetry armed via env) and the
            # spans still open at expiry (the hung train/step)
            window = rep["metrics_window"]
            assert window["armed"] is True, window
            assert window["snapshots"] >= 1, window
            assert "train.step_seconds" in window["last"]["metrics"]
            chaos_counts = window["last"]["metrics"].get(
                "chaos.faults_injected", {}).get("series", [])
            assert any(s["labels"].get("kind") == "hang"
                       for s in chaos_counts), chaos_counts
            open_names = [s["name"]
                          for spans in rep["open_spans"].values()
                          for s in spans]
            assert "train/step" in open_names, rep["open_spans"]
    assert stalled, "the hung rank's report must name the stuck frame"
