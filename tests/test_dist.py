"""Multi-process distributed tests, run through tools/launch.py local mode
(the reference dmlc-tracker trick: tests/nightly/test_all.sh:55
`tools/launch.py -n 4 python dist_sync_kvstore.py`).

Each case forks 4 real processes that initialise jax.distributed over a
gloo CPU backend and must all exit 0.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(REPO, "tools", "launch.py")


def _run_dist(script, n=4, timeout=420, launch_args=(), extra_env=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # workers self-configure cpu+gloo
    env.update(extra_env or {})
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", str(n), *launch_args, sys.executable,
         os.path.join(REPO, "tests", "dist", script)],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out[-3000:]
    # count occurrences, not lines: ranks finishing simultaneously can
    # interleave their stdout writes onto one line
    n_ok = out.count(" OK")
    assert n_ok == n, (n_ok, out[-1500:])
    return out


def test_dist_sync_kvstore_4proc():
    """push/pull/barrier/allreduce invariants across 4 ranks (reference
    tests/nightly/dist_sync_kvstore.py)."""
    _run_dist("dist_sync_kvstore.py")


def test_dist_train_mlp_4proc():
    """Module.fit with kvstore('dist_sync') over 4 ranks: converges and
    all ranks hold identical params (reference dist_lenet.py analog)."""
    _run_dist("dist_train_mlp.py")


def test_dist_elastic_restart_4proc(tmp_path):
    """Checkpoint-restart elasticity: rank 1 crashes mid-training, the
    launcher (--max-restarts 1) relaunches the gang, training resumes
    from the checkpoint and converges (SURVEY §5.3 failure model)."""
    out = _run_dist("dist_elastic_train.py",
                    launch_args=("--max-restarts", "1"),
                    extra_env={"ELASTIC_CKPT_DIR": str(tmp_path)})
    assert "CRASHING" in out and "restart 1/1" in out


def test_dist_async_train_4proc():
    """Module.fit with kvstore('dist_async') over 4 ranks stepping at
    different speeds: no deadlock, per-rank convergence, identical params
    after sync_weights (reference kvstore_dist_server.h:503 semantics)."""
    _run_dist("dist_async_train.py")


def test_dist_hang_watchdog_4proc(tmp_path):
    """Silent-hang e2e drill (ISSUE 2 acceptance): rank 1 stalls inside
    the fit step; the watchdog fires within its deadline, dumps stacks +
    a post-mortem naming the stuck frame into the checkpoint dir, and
    fail-fasts; the launcher relaunches and training resumes from the
    newest checkpoint and converges."""
    import glob
    import json

    out = _run_dist("dist_hang_watchdog.py",
                    launch_args=("--max-restarts", "1"),
                    extra_env={"HANG_CKPT_DIR": str(tmp_path),
                               "MXNET_TPU_TELEMETRY": "1"})
    assert "chaos: rank hanging" in out
    assert "restart 1/1" in out

    reports = sorted(glob.glob(str(tmp_path / "watchdog-postmortem-*.json")))
    assert reports, "watchdog must leave a post-mortem next to the ckpts"
    stalled = []
    for path in reports:
        with open(path) as f:
            rep = json.load(f)
        assert rep["kind"] == "watchdog_postmortem"
        assert rep["action"] == "abort"
        assert os.path.isfile(rep["stack_dump"])
        funcs = [f["function"] for f in (rep["stuck_frames"] or [])]
        if "maybe_hang" in funcs:       # the stalled rank's report
            stalled.append(rep)
            assert rep["tag"] == "Module.fit step"
            assert "maybe_hang" in open(rep["stack_dump"]).read()
            # ISSUE 5: the post-mortem shows what the process was DOING —
            # a recent metrics window (telemetry armed via env) and the
            # spans still open at expiry (the hung train/step)
            window = rep["metrics_window"]
            assert window["armed"] is True, window
            assert window["snapshots"] >= 1, window
            assert "train.step_seconds" in window["last"]["metrics"]
            chaos_counts = window["last"]["metrics"].get(
                "chaos.faults_injected", {}).get("series", [])
            assert any(s["labels"].get("kind") == "hang"
                       for s in chaos_counts), chaos_counts
            open_names = [s["name"]
                          for spans in rep["open_spans"].values()
                          for s in spans]
            assert "train/step" in open_names, rep["open_spans"]
    assert stalled, "the hung rank's report must name the stuck frame"
