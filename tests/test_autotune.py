"""Block-size autotuner (ops/autotune.py): cache round-trip and
persistence, measure-driven search semantics, trace-time safety of the
read path, and the flash kernel integration."""
import json
import os
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu  # noqa: F401
from mxnet_tpu import telemetry
from mxnet_tpu.ops import autotune


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    monkeypatch.delenv("MXNET_TPU_AUTOTUNE", raising=False)
    autotune.invalidate()
    telemetry.reset()
    telemetry.disarm()
    yield
    autotune.invalidate()
    telemetry.reset()


def test_defaults_without_cache():
    assert autotune.flash_blocks("fwd", 8192, 8192, 64, "bfloat16") \
        == autotune.DEFAULT_FLASH_BLOCKS["fwd"]
    assert autotune.flash_blocks("bwd", 8192, 8192, 64, "bfloat16") \
        == autotune.DEFAULT_FLASH_BLOCKS["bwd"]


def test_record_lookup_and_persistence():
    sig = ("fwd", 4096, 4096, 64, "bfloat16")
    autotune.record("flash_fwd", sig, (256, 512), 3.2, trials=6)
    assert autotune.flash_blocks("fwd", 4096, 4096, 64, "bfloat16") \
        == (256, 512)
    # a fresh process (simulated by dropping the in-memory cache) reads
    # the persisted winner back
    autotune.invalidate()
    assert autotune.flash_blocks("fwd", 4096, 4096, 64, "bfloat16") \
        == (256, 512)
    raw = json.load(open(autotune.cache_path()))
    (entry,) = raw.values()
    assert entry["config"] == [256, 512]
    assert entry["score_ms"] == pytest.approx(3.2)
    assert entry["device_kind"] == autotune.device_kind()


def test_key_discriminates_shape_dtype():
    autotune.record("flash_fwd", ("fwd", 1024, 1024, 64, "bfloat16"),
                    (512, 512), 1.0)
    assert autotune.flash_blocks("fwd", 1024, 1024, 64, "bfloat16") \
        == (512, 512)
    # different T / dtype: default again
    assert autotune.flash_blocks("fwd", 2048, 2048, 64, "bfloat16") \
        == autotune.DEFAULT_FLASH_BLOCKS["fwd"]
    assert autotune.flash_blocks("fwd", 1024, 1024, 64, "float32") \
        == autotune.DEFAULT_FLASH_BLOCKS["fwd"]


def test_autotune_disabled_returns_default_without_measuring():
    calls = []
    got = autotune.autotune("op", ("sig",), [(1,), (2,)],
                            lambda c: calls.append(c) or 1.0,
                            default=(9,))
    assert got == (9,) and calls == []


def test_autotune_measures_picks_fastest_and_caches():
    telemetry.arm()
    times = {(1,): 0.02, (2,): 0.005, (3,): 0.01}
    calls = []

    def measure(c):
        calls.append(c)
        return times[c]

    got = autotune.autotune("op", ("s1",), [(1,), (2,), (3,)], measure,
                            force=True)
    assert got == (2,) and len(calls) == 3
    # second call: pure cache hit, no measuring
    calls.clear()
    got2 = autotune.autotune("op", ("s1",), [(1,), (2,), (3,)], measure,
                             force=True)
    assert got2 == (2,) and calls == []
    # the search itself landed on the measurement plane
    assert telemetry.counter("autotune.trials").total() == 3
    assert telemetry.histogram(
        "autotune.trial_seconds").summary()["count"] == 3


def test_autotune_skips_failing_candidates():
    def measure(c):
        if c == (1,):
            raise RuntimeError("over VMEM budget")
        return 0.5

    got = autotune.autotune("op", ("s2",), [(1,), (2,)], measure,
                            force=True)
    assert got == (2,)


def test_autotune_all_fail_returns_default():
    def measure(c):
        raise RuntimeError("no")

    got = autotune.autotune("op", ("s3",), [(1,), (2,)], measure,
                            default=(7,), force=True)
    assert got == (7,)
    assert autotune.lookup("op", ("s3",)) is None


def test_flash_candidates_respect_vmem_budget():
    cands = autotune._flash_candidates("bwd", 32768, 32768, 64)
    assert cands, "candidate set must never be empty"
    for bq, bk in cands:
        assert bq <= 32768 and bk <= 32768
    # a (512, 1024) backward tile at D=256 blows the 12MB budget
    big = autotune._flash_candidates("bwd", 32768, 32768, 256)
    assert (512, 1024) not in big


def test_fused_attention_uses_cached_blocks(monkeypatch):
    """The kernel wrapper consults the cache at trace time: plant an
    entry and observe it win over the static default (visible through
    the clamping behavior at small T: a cached (8, 8) beats the
    (128, 512) default)."""
    from mxnet_tpu.ops import pallas_kernels as pk
    seen = {}
    real = pk._flash_call

    def spy(qf, kf, vf, dtype, *, scale, causal, bq, bk, with_lse,
            interpret):
        seen["blocks"] = (bq, bk)
        return real(qf, kf, vf, dtype, scale=scale, causal=causal,
                    bq=bq, bk=bk, with_lse=with_lse, interpret=interpret)

    monkeypatch.setattr(pk, "_flash_call", spy)
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.normal(0, 1, (1, 32, 1, 8)).astype(np.float32))
    autotune.record("flash_fwd", ("fwd", 32, 32, 8, "float32"), (8, 8),
                    1.0)
    pk.fused_attention(q, q, q)
    assert seen["blocks"] == (8, 8)


def test_tune_flash_end_to_end_interpret(tmp_path):
    """The flash search driver runs (forced) on the interpret path and
    persists winners for both directions."""
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.normal(0, 1, (1, 16, 1, 8)).astype(np.float32))
    res = autotune.tune_flash(q, q, q, causal=True, iters=1, force=True)
    assert set(res) == {"fwd", "bwd"}
    autotune.invalidate()
    assert autotune.lookup(
        "flash_fwd", ("fwd", 16, 16, 8, "float32")) is not None
    assert autotune.lookup(
        "flash_bwd", ("bwd", 16, 16, 8, "float32")) is not None
