"""Image augmentation + ImageIter/ImageDetIter tests on synthetic JPEGs.

Reference behaviors: python/mxnet/image/image.py:482-873 (augmenters),
:999 (ImageIter), python/mxnet/image/detection.py (ImageDetIter).  The
augmenter math here is BATCHED (batch_call over (N,H,W,C)); these tests pin
it against per-sample closed forms.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img_mod
from mxnet_tpu import nd

LUMA = np.array([0.299, 0.587, 0.114], np.float32)


def _batch(n=4, h=8, w=6, seed=0):
    rs = np.random.RandomState(seed)
    return rs.uniform(0, 255, (n, h, w, 3)).astype(np.float32)


# -- batched augmenter math --------------------------------------------------

def test_brightness_batch_independent_alphas():
    arr = _batch()
    rng = np.random.default_rng(1)
    out = img_mod.BrightnessJitterAug(0.5).batch_call(arr.copy(), rng)
    # recover per-sample alpha; all pixels of a sample share it, samples differ
    alphas = out.reshape(4, -1) / arr.reshape(4, -1)
    per_sample = alphas.mean(axis=1)
    np.testing.assert_allclose(
        alphas, np.broadcast_to(per_sample[:, None], alphas.shape),
        rtol=1e-4)
    assert np.std(per_sample) > 1e-4, "samples must get independent draws"
    assert np.all(np.abs(per_sample - 1.0) <= 0.5 + 1e-6)


def test_contrast_batch_matches_closed_form():
    arr = _batch()
    rng = np.random.default_rng(2)
    out = img_mod.ContrastJitterAug(0.4).batch_call(arr.copy(), rng)
    # out = a*x + (1-a)*mean_luma  =>  recover a from any two pixels, then
    # verify against the sample's own mean luma
    for i in range(arr.shape[0]):
        x = arr[i].ravel()
        y = out[i].ravel()
        a = (y[0] - y[1]) / (x[0] - x[1])
        mluma = (arr[i] @ LUMA).mean()
        np.testing.assert_allclose(y, a * x + (1 - a) * mluma, rtol=1e-3)


def test_saturation_batch_matches_closed_form():
    arr = _batch()
    rng = np.random.default_rng(3)
    out = img_mod.SaturationJitterAug(0.4).batch_call(arr.copy(), rng)
    for i in range(arr.shape[0]):
        luma = (arr[i] @ LUMA)[..., None]
        # gray pixels (all channels equal) are fixed points => recover a
        # from a colored pixel's deviation
        dev_in = arr[i] - luma
        dev_out = out[i] - luma
        nz = np.abs(dev_in) > 1e-3
        a = (dev_out[nz] / dev_in[nz]).mean()
        np.testing.assert_allclose(out[i], a * arr[i] + (1 - a) * luma,
                                   rtol=1e-3, atol=1e-2)


def test_hue_zero_is_identity_and_preserves_luma_rotation():
    arr = _batch()
    rng = np.random.default_rng(4)
    out0 = img_mod.HueJitterAug(0.0).batch_call(arr.copy(), rng)
    # FROM_YIQ @ TO_YIQ is the reference's approximate inverse pair
    # (identity only to ~0.3% of full scale)
    np.testing.assert_allclose(out0, arr, rtol=0.2, atol=1.0)
    out = img_mod.HueJitterAug(0.3).batch_call(arr.copy(), rng)
    assert not np.allclose(out, arr)
    # Y (luma) channel of YIQ is invariant under the chroma rotation
    np.testing.assert_allclose(out @ LUMA, arr @ LUMA, rtol=1e-2, atol=0.5)


def test_lighting_shifts_whole_sample_uniformly():
    arr = _batch()
    eigval = np.array([55.46, 4.794, 1.148])
    eigvec = np.random.RandomState(0).normal(size=(3, 3))
    rng = np.random.default_rng(5)
    out = img_mod.LightingAug(0.5, eigval, eigvec).batch_call(arr.copy(), rng)
    shift = out - arr  # every pixel of a sample shifts by the same rgb
    np.testing.assert_allclose(
        shift, np.broadcast_to(shift[:, :1, :1, :], shift.shape),
        rtol=1e-4, atol=1e-3)
    assert np.std(shift[:, 0, 0, :], axis=0).max() > 1e-4


def test_random_gray_all_and_none():
    arr = _batch()
    rng = np.random.default_rng(6)
    out = img_mod.RandomGrayAug(1.0).batch_call(arr.copy(), rng)
    expect = arr @ np.array([[0.21] * 3, [0.72] * 3, [0.07] * 3], np.float32)
    np.testing.assert_allclose(out, expect, rtol=1e-4)
    same = img_mod.RandomGrayAug(0.0).batch_call(arr.copy(), rng)
    np.testing.assert_allclose(same, arr)


def test_flip_batch_and_partial():
    arr = _batch()
    rng = np.random.default_rng(7)
    out = img_mod.HorizontalFlipAug(1.0).batch_call(arr.copy(), rng)
    np.testing.assert_allclose(out, arr[:, :, ::-1])
    # partial: each sample either flipped or untouched
    out2 = img_mod.HorizontalFlipAug(0.5).batch_call(arr.copy(), rng)
    for i in range(arr.shape[0]):
        ok = np.allclose(out2[i], arr[i]) or \
            np.allclose(out2[i], arr[i, :, ::-1])
        assert ok


def test_normalize_and_cast_batch():
    arr = _batch()
    rng = np.random.default_rng(8)
    mean = np.array([10.0, 20.0, 30.0], np.float32)
    std = np.array([2.0, 4.0, 8.0], np.float32)
    out = img_mod.ColorNormalizeAug(mean, std).batch_call(arr.copy(), rng)
    np.testing.assert_allclose(out, (arr - mean) / std, rtol=1e-5)
    assert img_mod.CastAug().batch_call(arr.astype(np.uint8), rng).dtype \
        == np.float32


def test_seed_makes_batched_draws_reproducible():
    import mxnet_tpu.image.image as im
    arr = _batch()
    mx.random.seed(42)
    a = img_mod.BrightnessJitterAug(0.5).batch_call(arr.copy(), im._rng)
    mx.random.seed(42)
    b = img_mod.BrightnessJitterAug(0.5).batch_call(arr.copy(), im._rng)
    np.testing.assert_array_equal(a, b)


def test_random_gray_single_image_dtype_passthrough():
    src = nd.array(np.arange(48, dtype=np.uint8).reshape(4, 4, 3),
                   dtype=np.uint8)
    out = img_mod.RandomGrayAug(0.0)(src)
    assert out.dtype == np.uint8 and out is src
    gray = img_mod.RandomGrayAug(1.0)(src)
    assert np.allclose(np.ptp(gray.asnumpy(), axis=2), 0, atol=1e-4)


def test_single_image_call_delegates_to_batch():
    arr = _batch(n=1)[0]
    out = img_mod.BrightnessJitterAug(0.0)(nd.array(arr))
    np.testing.assert_allclose(out.asnumpy(), arr, rtol=1e-5)
    out = img_mod.SaturationJitterAug(0.0)(nd.array(arr))
    np.testing.assert_allclose(out.asnumpy(), arr, rtol=1e-4, atol=1e-2)


def test_sequential_and_random_order_batchable():
    seq = img_mod.SequentialAug([img_mod.BrightnessJitterAug(0.1),
                                 img_mod.ColorNormalizeAug([0.] * 3,
                                                           [1.] * 3)])
    assert seq.batchable
    mixed = img_mod.SequentialAug([img_mod.ResizeAug(8),
                                   img_mod.CastAug()])
    assert not mixed.batchable
    jit = img_mod.ColorJitterAug(0.1, 0.1, 0.1)
    assert jit.batchable
    out = jit.batch_call(_batch(), np.random.default_rng(0))
    assert out.shape == (4, 8, 6, 3)


def test_scale_down_reference_equivalence():
    """The one-scale formulation must agree with the reference's two-step
    clamp (image.py scale_down) across a grid."""
    def ref(src_size, size):
        w, h = size
        sw, sh = src_size
        if sh < h:
            w, h = float(w * sh) / h, sh
        if sw < w:
            w, h = sw, float(h * sw) / w
        return int(w), int(h)

    for sw in (1, 3, 7, 20, 100):
        for sh in (1, 4, 9, 33, 50):
            for w in (1, 5, 12, 40):
                for h in (2, 8, 25, 60):
                    assert img_mod.scale_down((sw, sh), (w, h)) == \
                        ref((sw, sh), (w, h)), ((sw, sh), (w, h))


# -- ImageIter on synthetic JPEGs -------------------------------------------

@pytest.fixture(scope="module")
def jpeg_dir(tmp_path_factory):
    from PIL import Image
    d = tmp_path_factory.mktemp("imgs")
    rs = np.random.RandomState(0)
    entries = []
    for i in range(10):
        arr = rs.randint(0, 255, (32 + i, 40, 3), np.uint8)
        fname = "img%d.jpg" % i
        Image.fromarray(arr).save(str(d / fname), quality=95)
        entries.append((i % 3, fname))
    return str(d), entries


def test_image_iter_batches(jpeg_dir):
    root, entries = jpeg_dir
    it = img_mod.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                           imglist=[[lab, fn] for lab, fn in entries],
                           path_root=root)
    batches = list(it)
    assert len(batches) == 3
    for b in batches:
        assert b.data[0].shape == (4, 3, 16, 16)
        assert b.label[0].shape == (4,)
    assert batches[-1].pad == 2  # 10 imgs -> 4+4+2(+2 pad)


def test_image_iter_batched_tail_matches_per_image(jpeg_dir):
    """Deterministic augs (center-crop + normalize) must give identical
    batches whether the tail runs vectorized or per image."""
    root, entries = jpeg_dir
    imglist = [[lab, fn] for lab, fn in entries]
    mean = [100., 110., 120.]
    std = [50., 60., 70.]

    def make_iter():
        return img_mod.ImageIter(
            batch_size=5, data_shape=(3, 16, 16), imglist=imglist,
            path_root=root,
            aug_list=[img_mod.CenterCropAug((16, 16)),
                      img_mod.CastAug(),
                      img_mod.ColorNormalizeAug(mean, std)])

    it = make_iter()
    got = next(it).data[0].asnumpy()
    # hand-rolled per-image pipeline
    want = []
    for lab, fn in entries[:5]:
        im = img_mod.imread(os.path.join(root, fn))
        im = img_mod.CenterCropAug((16, 16))(im)
        arr = im.asnumpy().astype(np.float32)
        want.append((arr - mean) / std)
    want = np.stack(want).transpose(0, 3, 1, 2)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_image_iter_partition_disjoint(jpeg_dir):
    root, entries = jpeg_dir
    imglist = [[lab, fn] for lab, fn in entries]
    seen = []
    for part in range(2):
        it = img_mod.ImageIter(batch_size=5, data_shape=(3, 16, 16),
                               imglist=imglist, path_root=root,
                               part_index=part, num_parts=2)
        seen.append(list(it.seq))
        assert len(it.seq) == 5
    assert not set(seen[0]) & set(seen[1])


def test_image_iter_rand_aug_shapes(jpeg_dir):
    root, entries = jpeg_dir
    it = img_mod.ImageIter(batch_size=4, data_shape=(3, 16, 16),
                           imglist=[[lab, fn] for lab, fn in entries],
                           path_root=root, rand_crop=True, rand_mirror=True,
                           brightness=0.2, contrast=0.2, saturation=0.2,
                           hue=0.1, pca_noise=0.05, rand_gray=0.2,
                           mean=True, std=True)
    b = next(it)
    assert b.data[0].shape == (4, 3, 16, 16)
    assert np.isfinite(b.data[0].asnumpy()).all()


def test_image_det_iter(jpeg_dir):
    root, entries = jpeg_dir
    # detection label: [header_width=2, obj_width=5, cls, x1, y1, x2, y2]
    rs = np.random.RandomState(1)
    imglist = []
    for lab, fn in entries:
        x1, y1 = rs.uniform(0, 0.4, 2)
        x2, y2 = x1 + rs.uniform(0.1, 0.5), y1 + rs.uniform(0.1, 0.5)
        imglist.append([[2, 5, float(lab), x1, y1, min(x2, 1.), min(y2, 1.)],
                        fn])
    it = img_mod.ImageDetIter(batch_size=4, data_shape=(3, 16, 16),
                              imglist=imglist, path_root=root)
    b = next(it)
    assert b.data[0].shape == (4, 3, 16, 16)
    lab = b.label[0].asnumpy()
    assert lab.ndim == 3 and lab.shape[0] == 4 and lab.shape[2] == 5
    assert (lab[:, 0, 0] >= 0).all()  # first object is real in every sample
