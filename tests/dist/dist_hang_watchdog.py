"""Hang-detection + checkpoint-restart e2e drill (the watchdog analog of
dist_elastic_train.py).

Failure model (ISSUE 2 / SURVEY §5.3): one rank silently stalls inside
the step; every peer blocks in the next collective with zero diagnostics.
This script is run via `tools/launch.py --max-restarts 1` with the
watchdog armed (MXNET_TPU_WATCHDOG_STEP_TIMEOUT small):

  incarnation 0: all ranks train with per-epoch checkpoints; rank 1
    HANGS (chaos `hang` fault: sleeps inside the fit step) after the
    epoch-2 checkpoint exists.  Rank 1's watchdog fires on the step
    deadline — stack dump + post-mortem into the checkpoint dir — and
    fail-fasts (exit 43); peers blocked in the gradient collective are
    reaped by the launcher, which relaunches the gang;
  incarnation 1: every rank resumes from the checkpoint (begin_epoch
    >= 2), finishes, and checks convergence + cross-rank agreement.

The pytest wrapper (tests/test_dist.py) additionally asserts the
post-mortem exists and its stack dump names the stuck frame.
"""
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel  # noqa: E402
from mxnet_tpu.resilience import chaos, watchdog  # noqa: E402

CKPT_DIR = os.environ.get("HANG_CKPT_DIR", "/tmp/mxt_hang")
TOTAL_EPOCHS = 12
HANG_AFTER_EPOCH = 2    # rank 1 stalls on the first step of epoch 3
BATCHES_PER_EPOCH = 2   # 64 samples / batch 32


def latest_checkpoint(prefix):
    eps = []
    for p in glob.glob(prefix + "-*.params"):
        try:
            eps.append(int(p.rsplit("-", 1)[1].split(".")[0]))
        except ValueError:
            pass
    return max(eps) if eps else None


def main():
    parallel.init_distributed()
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    incarnation = int(os.environ.get("MXNET_TPU_RESTART_COUNT", "0"))
    prefix = os.path.join(CKPT_DIR, "mlp")
    if rank == 0 and incarnation == 0:
        os.makedirs(CKPT_DIR, exist_ok=True)
        for p in glob.glob(os.path.join(CKPT_DIR, "*")):
            os.remove(p)
    kv.barrier()

    # arm the watchdog explicitly: short step deadline, fail-fast abort,
    # post-mortems next to the checkpoints
    watchdog.configure(step_timeout=float(
        os.environ.get("MXNET_TPU_WATCHDOG_STEP_TIMEOUT", "8")),
        action="abort", report_dir=CKPT_DIR, poll=0.2)

    rs = np.random.RandomState(0)
    X = rs.randn(256, 16).astype(np.float32)
    w_true = rs.randn(16).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)
    shard = slice(rank * 64, (rank + 1) * 64)
    it = mx.io.NDArrayIter(X[shard], y[shard], batch_size=32, shuffle=False)

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")

    begin_epoch = 0
    arg_params = aux_params = None
    resumed_from = latest_checkpoint(prefix)
    if resumed_from is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            prefix, resumed_from)
        begin_epoch = resumed_from
    if incarnation > 0:
        assert resumed_from is not None and resumed_from >= HANG_AFTER_EPOCH, \
            "restarted incarnation must find the pre-hang checkpoint"
        pm = glob.glob(os.path.join(CKPT_DIR, "watchdog-postmortem-*.json"))
        assert pm, "incarnation 1 must find the watchdog post-mortem"
        with open(sorted(pm)[0]) as f:
            report = json.load(f)
        assert report["kind"] == "watchdog_postmortem", report

    # incarnation 0, rank 1: stall inside the fit step after the epoch-2
    # checkpoint is durable — the chaos sleep far outlives the watchdog
    # deadline, so only the watchdog can end this incarnation
    if incarnation == 0 and rank == 1:
        chaos.inject("hang", at_step=HANG_AFTER_EPOCH * BATCHES_PER_EPOCH + 1,
                     seconds=300).__enter__()

    mod = mx.mod.Module(net, context=mx.cpu())

    def checkpoint_cb(epoch, symbol, args_p, aux_p):
        if rank == 0:
            mx.model.save_checkpoint(prefix, epoch + 1, symbol, args_p, aux_p)
        kv.barrier()   # peers wait until the checkpoint is durable

    metric = mx.metric.Accuracy()
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3},
            initializer=mx.init.Xavier(),
            arg_params=arg_params, aux_params=aux_params,
            begin_epoch=begin_epoch, num_epoch=TOTAL_EPOCHS,
            eval_metric=metric, kvstore=kv,
            epoch_end_callback=checkpoint_cb)

    args_p, _ = mod.get_params()
    for name, arr in sorted(args_p.items()):
        mine = arr.asnumpy().astype(np.float64)
        total = np.asarray(parallel.allreduce_array(jax.numpy.asarray(mine)))
        np.testing.assert_allclose(total, mine * nworker, rtol=1e-5)

    it.reset()
    metric.reset()
    mod.score(it, metric)
    acc = dict(metric.get_name_value())["accuracy"]
    assert acc > 0.9, "rank %d accuracy %.3f" % (rank, acc)
    assert incarnation == 1, "must be the restarted incarnation to succeed"
    assert begin_epoch >= HANG_AFTER_EPOCH
    print("dist_hang rank %d/%d OK resumed_at=%d acc=%.3f"
          % (rank, nworker, begin_epoch, acc), flush=True)


if __name__ == "__main__":
    main()
