"""Multi-process ASYNC data-parallel training through Module.fit with
kvstore('dist_async') — the reference dist_async mode
(kvstore_dist_server.h:503: each push applied immediately, workers never
wait per step).  Here asynchrony = rank-local immediate updates + periodic
cross-rank weight averaging, so the invariants differ from dist_sync:

 1. ranks deliberately step at DIFFERENT speeds (per-rank sleep) and must
    not deadlock — no per-step barrier exists between averaging rounds;
 2. training still converges on every rank despite bounded staleness;
 3. after kv.sync_weights() all ranks agree exactly (checkpoint contract).

Run:  python tools/launch.py -n 4 python tests/dist/dist_async_train.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

os.environ.setdefault("MXNET_TPU_ASYNC_AVG_INTERVAL", "4")

import jax  # noqa: E402
import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel  # noqa: E402


def main():
    parallel.init_distributed()
    kv = mx.kv.create("dist_async")
    rank, nworker = kv.rank, kv.num_workers
    assert type(kv).__name__ == "KVStoreTPUDistAsync"

    rs = np.random.RandomState(0)
    X = rs.randn(512, 16).astype(np.float32)
    w_true = rs.randn(16).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)
    shard = slice(rank * 128, (rank + 1) * 128)
    it = mx.io.NDArrayIter(X[shard], y[shard], batch_size=32, shuffle=False)

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")

    # rank-dependent per-batch delay: rank 3 is 4x slower than rank 0;
    # async mode must neither deadlock nor stop converging
    def slow_batch(param):
        time.sleep(0.002 * rank)

    mod = mx.mod.Module(net, context=mx.cpu())
    metric = mx.metric.Accuracy()
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2},
            initializer=mx.init.Xavier(),
            eval_metric=metric, num_epoch=15, kvstore=kv,
            batch_end_callback=slow_batch)

    # rank-local params converge despite staleness
    it.reset()
    metric.reset()
    mod.score(it, metric)
    acc = dict(metric.get_name_value())["accuracy"]
    assert acc > 0.85, "rank %d accuracy %.3f" % (rank, acc)

    kv.sync_weights()
    # after an explicit sync, every rank must hold identical stored params
    for k in list(kv._store):
        mine = kv._store[k].asnumpy().astype(np.float64)
        total = np.asarray(parallel.allreduce_array(jax.numpy.asarray(mine)))
        np.testing.assert_allclose(total, mine * nworker, rtol=1e-5,
                                   err_msg="key %r diverged post-sync" % (k,))
    # row-sparse averaging: a row held by k<N ranks must be divided by k,
    # not N (union-sum + per-row holder counts)
    import jax.numpy as jnp
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    data = np.full((2, 3), float(rank + 1), np.float32)
    idx = np.array([0, rank + 1], np.int64)   # row 0: all ranks; row rank+1: one
    kv._store["rs_probe"] = RowSparseNDArray(
        jnp.asarray(data), jnp.asarray(idx), (nworker + 1, 3))
    kv._average_key("rs_probe")
    dense = kv._store["rs_probe"].asnumpy()
    np.testing.assert_allclose(dense[0], np.full(3, 2.5), rtol=1e-6)
    for r in range(nworker):
        np.testing.assert_allclose(dense[r + 1], np.full(3, r + 1.0),
                                   rtol=1e-6)

    print("dist_async_train rank %d/%d OK acc=%.3f" % (rank, nworker, acc),
          flush=True)


if __name__ == "__main__":
    main()
