"""Elastic (checkpoint-restart) distributed training.

Reference failure model (SURVEY §5.3): a dead worker fails the gang; the
tracker relaunches and training resumes from the last checkpoint.  This
script is run via `tools/launch.py --max-restarts 1`:

  incarnation 0: all ranks train with per-epoch checkpoints; rank 1
    CRASHES mid-training (after the epoch-2 checkpoint exists);
  incarnation 1 (MXNET_TPU_RESTART_COUNT=1): every rank finds the
    checkpoint, resumes from it (begin_epoch > 0), finishes, and checks
    convergence + cross-rank parameter agreement.
"""
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel  # noqa: E402

CKPT_DIR = os.environ.get("ELASTIC_CKPT_DIR", "/tmp/mxt_elastic")
TOTAL_EPOCHS = 12
CRASH_AFTER_EPOCH = 2


def latest_checkpoint(prefix):
    """Highest epoch with a saved params file, or None."""
    eps = []
    for p in glob.glob(prefix + "-*.params"):
        try:
            eps.append(int(p.rsplit("-", 1)[1].split(".")[0]))
        except ValueError:
            pass
    return max(eps) if eps else None


def main():
    parallel.init_distributed()
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    incarnation = int(os.environ.get("MXNET_TPU_RESTART_COUNT", "0"))
    prefix = os.path.join(CKPT_DIR, "mlp")
    if rank == 0 and incarnation == 0:
        os.makedirs(CKPT_DIR, exist_ok=True)
        for p in glob.glob(prefix + "-*"):
            os.remove(p)
    kv.barrier()

    rs = np.random.RandomState(0)
    X = rs.randn(256, 16).astype(np.float32)
    w_true = rs.randn(16).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)
    shard = slice(rank * 64, (rank + 1) * 64)
    it = mx.io.NDArrayIter(X[shard], y[shard], batch_size=32, shuffle=False)

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")

    begin_epoch = 0
    arg_params = aux_params = None
    resumed_from = latest_checkpoint(prefix)
    if resumed_from is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            prefix, resumed_from)
        begin_epoch = resumed_from
    if incarnation > 0:
        assert resumed_from is not None and resumed_from >= CRASH_AFTER_EPOCH, \
            "restarted incarnation must find the pre-crash checkpoint"

    mod = mx.mod.Module(net, context=mx.cpu())

    def crash_or_checkpoint(epoch, symbol, args_p, aux_p):
        # rank 0 checkpoints every epoch (shared fs in local mode)
        if rank == 0:
            mx.model.save_checkpoint(prefix, epoch + 1, symbol, args_p, aux_p)
        kv.barrier()   # peers wait until the checkpoint is durable
        if incarnation == 0 and rank == 1 and epoch + 1 == CRASH_AFTER_EPOCH:
            print("dist_elastic rank 1 CRASHING after epoch %d" % (epoch + 1),
                  flush=True)
            os._exit(17)

    metric = mx.metric.Accuracy()
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3},
            initializer=mx.init.Xavier(),
            arg_params=arg_params, aux_params=aux_params,
            begin_epoch=begin_epoch, num_epoch=TOTAL_EPOCHS,
            eval_metric=metric, kvstore=kv,
            epoch_end_callback=crash_or_checkpoint)

    # every rank must hold identical parameters after sync training
    args_p, _ = mod.get_params()
    for name, arr in sorted(args_p.items()):
        mine = arr.asnumpy().astype(np.float64)
        total = np.asarray(parallel.allreduce_array(jax.numpy.asarray(mine)))
        np.testing.assert_allclose(total, mine * nworker, rtol=1e-5)

    it.reset()
    metric.reset()
    mod.score(it, metric)
    acc = dict(metric.get_name_value())["accuracy"]
    assert acc > 0.9, "rank %d accuracy %.3f" % (rank, acc)
    assert incarnation == 1, "must be the restarted incarnation to succeed"
    assert begin_epoch >= CRASH_AFTER_EPOCH
    print("dist_elastic rank %d/%d OK resumed_at=%d acc=%.3f"
          % (rank, nworker, begin_epoch, acc), flush=True)


if __name__ == "__main__":
    main()
