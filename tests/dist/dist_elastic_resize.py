"""Elastic-resize e2e drill (ROADMAP item 5 acceptance; the resize analog
of dist_elastic_train.py's checkpoint-RESTART).

Run via ``tools/launch.py -n 4 --elastic --min-workers 3``:

  generation 0 (world 4): every rank trains a toy MLP with ShardedTrainer
    over a dp=4 mesh — global batch 48 held as 4 ranks x micro 4 x
    grad-accum 3 — with per-update checkpoints (rank 0) and the elastic
    coordinator armed.  Rank 1 is HARD-preempted (chaos ``preempt``) at
    its 8th update, mid-epoch.  The survivors' step blows up in the dead
    collective (or the resize-action watchdog fires on a silent hang);
    the heartbeat lane names rank 1 dead, the three survivors agree on
    membership {0,2,3} over the KV, commit the generation-1 manifest and
    exit 44.
  generation 1 (world 3): the launcher relaunches 3 ranks.  They re-form
    a dp=3 mesh, restore the newest checkpoint (resharding restore),
    re-shard the SAME global iterator order (num_parts 4x12 -> 3x16) and
    raise grad-accum to 4 — global batch still 48 — resuming within one
    update of the kill.  After a soak the coordinator sees the launcher's
    capacity file offering 4 workers again and grows back (manifest
    generation 2, exit 44).
  generation 2 (world 4): full size again; training completes.  Rank 0
    re-runs the whole schedule uninterrupted on a single-device mesh and
    checks the elastic run's final params/loss match within tolerance,
    and that the fleet view shows the current generation/world plus both
    resize events.

Since PR 13 the drill also proves the compile-time plane (ROADMAP item
5): the persistent compile cache + warm standby are armed
(MXNET_TPU_COMPILE_CACHE / MXNET_TPU_TRACE set by the test harness), so
rank 0 pre-compiles the world-3 step program during generation 0 and
the generation-1 manifest records it; every resized generation's first
step must then be a cache HIT — each gen>0 rank asserts ZERO miss/
untagged compile events (recovery paid no compilation), provable
post-hoc from the compile/* spans in the trace sinks
(tools/tracewatch.py --check / tools/postmortem.py --compile run over
them in the tier-1 test).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel, telemetry  # noqa: E402
from mxnet_tpu.io.io import NDArrayIter  # noqa: E402
from mxnet_tpu.parallel.mesh import MeshSpec, data_parallel_mesh, \
    make_mesh, set_current_mesh  # noqa: E402
from mxnet_tpu.parallel.trainer import ShardedTrainer  # noqa: E402
from mxnet_tpu.resilience import (CheckpointManager, chaos, elastic,  # noqa: E402
                                  restore_trainer, watchdog)

import jax.numpy as jnp  # noqa: E402

from mxnet_tpu.resilience import restore_embedding, save_embedding  # noqa: E402
from mxnet_tpu.sparse import ShardedEmbedding  # noqa: E402

CKPT_DIR = os.environ["ELASTIC_CKPT_DIR"]
# "kill": hard preemption (no goodbye) -> shrink -> grow back to full.
# "notice": graceful preempt_notice -> checkpoint-then-leave -> finish at
#           the reduced size (no grow) — zero lost updates.
MODE = os.environ.get("ELASTIC_DRILL_MODE", "kill")
N_SAMPLES = 240
DIM = 16
GLOBAL_BATCH = 48       # must divide at every world size: 4x12 / 3x16
MICRO = 4               # per-rank rows per micro-step
TOTAL_UPDATES = 30      # 6 epochs x 5 updates
KILL_AT = 8             # rank 1 hard-preempted at its 8th update (gen 0)
NOTICE_AT = 8           # rank 1 gets the graceful notice after update 8
GROW_AFTER = 6          # updates at reduced size before growing back
SEED = 11
# sharded-embedding side plane: the table rides the SAME dp mesh (rows
# 1/world per rank), takes one routed touched-rows lazy-SGD update per
# trainer update, checkpoints unpadded, and RESHARDS across every resize
# (4 -> 3 -> 4).  48 rows divide both world sizes; grads are exact
# multiples of 2^-10 and lr/momentum are powers of two, so routed sums
# and the fused update math are association- and FMA-free — the final-
# parity check against an uninterrupted single-device replay is
# BIT-exact across any shard-count history.
EMB_V, EMB_D, EMB_B = 48, 8, 24


def emb_batch(u):
    ers = np.random.RandomState(1000 + u)
    ids = ers.randint(0, EMB_V, EMB_B).astype(np.int32)
    rows = (ers.randint(-8, 8, (EMB_B, EMB_D)) / 1024.0).astype(np.float32)
    return ids, rows


def emb_apply(emb, state, u):
    from jax.sharding import NamedSharding, PartitionSpec as P
    ids, rows = emb_batch(u)
    bat = NamedSharding(emb.mesh, P(emb.axis))
    t, m = emb.apply_sgd(state["table"], state["mom"],
                         jax.device_put(ids, bat),
                         jax.device_put(rows, bat),
                         lr=0.125, momentum=0.5)
    return {"table": t, "mom": m}


def make_data():
    rs = np.random.RandomState(0)
    X = rs.randn(N_SAMPLES, DIM).astype(np.float32)
    w = rs.randn(DIM).astype(np.float32)
    y = (X @ w > 0).astype(np.float32)
    return X, y


def make_symbol():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def make_iter(X, y, world, rank):
    accum = elastic.grad_accum_for(GLOBAL_BATCH, MICRO, world)
    return NDArrayIter(X, y, batch_size=MICRO * accum, shuffle=True,
                       seed=5, num_parts=world, part_index=rank), accum


def next_update_batch(it):
    try:
        b = it.next()
    except StopIteration:
        it.reset()
        b = it.next()
    return {"data": b.data[0].asnumpy(),
            "softmax_label": b.label[0].asnumpy()}


def eval_loss(param_arrays, names, X, y):
    """Mean cross-entropy of the MLP on the full dataset, recomputed in
    numpy from the raw parameter tensors — the trainer's in-graph "loss"
    output is the SoftmaxOutput forward sum, not a metric."""
    p = {n: np.asarray(a) for n, a in zip(names, param_arrays)}
    h = np.maximum(X @ p["fc1_weight"].T + p["fc1_bias"], 0.0)
    logits = h @ p["fc2_weight"].T + p["fc2_bias"]
    logits -= logits.max(axis=1, keepdims=True)
    logp = logits - np.log(np.exp(logits).sum(axis=1, keepdims=True))
    return float(-logp[np.arange(len(y)), y.astype(int)].mean())


def reference_run(X, y):
    """The uninterrupted baseline: same init seed, same global order,
    same global batch, single-device mesh, no accumulation."""
    spec = MeshSpec(make_mesh((1,), ("dp",),
                    devices=jax.local_devices()[:1]))
    tr = ShardedTrainer(make_symbol(), spec, lr=0.01, momentum=0.9, wd=0.0)
    params, mom, aux = tr.init_state(
        {"data": (GLOBAL_BATCH, DIM), "softmax_label": (GLOBAL_BATCH,)},
        seed=SEED)
    it = NDArrayIter(X, y, batch_size=GLOBAL_BATCH, shuffle=True, seed=5)
    for _ in range(TOTAL_UPDATES):
        params, mom, aux, _ = tr.step(params, mom, aux,
                                      next_update_batch(it))
    return tr.param_names, params


def main():
    parallel.init_distributed()
    telemetry.arm()
    rank, world = jax.process_index(), jax.process_count()
    gen = elastic.generation()
    telemetry.tracing.set_process_label("rank%d-g%d" % (rank, gen))
    if rank == 0 and gen == 0:
        os.makedirs(CKPT_DIR, exist_ok=True)
    parallel.barrier("elastic_start")

    spec = data_parallel_mesh()
    assert spec.generation == gen, (spec.generation, gen)
    set_current_mesh(spec)
    trainer = ShardedTrainer(make_symbol(), spec, lr=0.01, momentum=0.9,
                             wd=0.0)
    X, y = make_data()
    it, accum = make_iter(X, y, world, rank)
    trainer.set_grad_accum(accum)
    mgr = CheckpointManager(CKPT_DIR, keep=5)
    # embedding side plane over THIS generation's mesh, created — and
    # its routed-update + host-gather programs COMPILED, via a discarded
    # priming call — BEFORE the elastic machinery arms: a ~20 s first
    # compile mid-loop would stall heartbeats past dead_sec and spam
    # false-alarm resize rounds right when the real kill needs a clean
    # one
    emb = ShardedEmbedding(EMB_V, EMB_D, spec, name="drill")
    emb_mgr = CheckpointManager(CKPT_DIR, prefix="emb", keep=5)
    emb_state = {"table": emb.init_state(seed=42), "mom": emb.zeros_slot()}
    emb_apply(emb, emb_state, 0)               # discarded: compile only
    emb.state_dict(emb_state["table"], mom=emb_state["mom"])
    # watchdog backstop with the RESIZE action: if the dead peer wedges
    # the collective instead of erroring it, the deadline still turns
    # the hang into a coordinated resize (post-mortem included)
    watchdog.configure(step_timeout=45, action="resize",
                       report_dir=CKPT_DIR, poll=0.2)
    coord = elastic.ElasticCoordinator(
        mgr, trainer, data_iter=it, min_workers=3, ckpt_every=1,
        grow_after_steps=GROW_AFTER if MODE == "kill" else 10 ** 6,
        dead_sec=2.0, check_interval=0.0,
        consensus_timeout=60.0, round_sec=2.0)
    coord.announce()
    # the monitor thread joins a peer-initiated round even while this
    # rank is wedged inside a dead collective (the hard-kill drill)
    coord.start_monitor(poll=0.2)

    params, mom, aux = trainer.init_state(
        {"data": (world * MICRO, DIM), "softmax_label": (world * MICRO,)},
        seed=SEED)
    updates = 0
    restored = restore_trainer(mgr, trainer, data_iter=it,
                               old_state=(params, mom, aux))
    if restored is not None:
        params, mom, aux, updates, _meta = restored

    # embedding restore: a resized generation reshards the unpadded
    # snapshot onto the new world and replays any updates the trainer
    # is ahead by (ids/grads are deterministic functions of the update
    # index, so the replay is exact)
    emb_updates = 0
    restored_emb = restore_embedding(emb_mgr, emb, old_states=[emb_state])
    if restored_emb is not None:
        (emb_state,), emb_updates, _emeta = restored_emb
    if gen > 0:
        assert restored_emb is not None, \
            "a resized generation must reshard the embedding table"
        shard_b = emb_state["table"].addressable_shards[0].data.nbytes
        assert shard_b * world == emb_state["table"].nbytes, \
            (shard_b, emb_state["table"].nbytes, world)
        print("dist_elastic_resize rank %d EMB resharded gen=%d world=%d"
              " rows/rank=%d emb_updates=%d" % (
                  rank, gen, world, EMB_V // world, emb_updates),
              flush=True)
    assert emb_updates <= updates, (emb_updates, updates)
    while emb_updates < updates:           # replay the save gap
        emb_updates += 1
        emb_state = emb_apply(emb, emb_state, emb_updates)
    if gen > 0:
        assert restored is not None, \
            "a resized generation must resume from a checkpoint"
        # the acceptance bound: survivors resume within ONE update of
        # the kill (per-update checkpoints; the in-flight one is lost)
        if gen == 1:
            if MODE == "kill":
                # per-update checkpoints; only the in-flight one is lost
                assert updates >= KILL_AT - 1, \
                    "resumed at %d, expected >= %d" % (updates, KILL_AT - 1)
            else:
                # graceful leave checkpoints AFTER the hand-off update:
                # zero updates lost
                assert updates == NOTICE_AT + 1, \
                    "graceful resize lost work: resumed at %d" % updates
            assert world == 3, world
        print("dist_elastic_resize rank %d RESUMED gen=%d world=%d "
              "updates=%d accum=%d" % (rank, gen, world, updates, accum),
              flush=True)
        if gen == 1 and rank == 0:
            # the satellite: the resize manifest names the pre-compiled
            # generation — world 3 must have been warmed before the kill
            m = elastic.read_manifest(CKPT_DIR, 1) or {}
            w3 = ((m.get("precompiled") or {}).get("worlds")
                  or {}).get("world3") or {}
            assert w3.get("result") in ("standby", "hit"), m
            print("dist_elastic_resize MANIFEST precompiled world3=%s"
                  % w3.get("result"), flush=True)

    # warm-standby plane (ROADMAP item 5): rank 0 pre-compiles the
    # adjacent generations' step programs into the shared persistent
    # cache BEFORE anything fails, so each resized generation's first
    # step below deserializes instead of compiling.  The drill waits
    # for the background compile (the kill at update 8 must find the
    # cache warm); production would let it run free.
    coord.enable_standby(
        (params, mom, aux), micro_batch=MICRO,
        batch_shapes={"data": (GLOBAL_BATCH, DIM),
                      "softmax_label": (GLOBAL_BATCH,)},
        wait=True, timeout=120)

    if gen == 0 and rank == 1:
        if MODE == "kill":
            chaos.inject("preempt", at_step=KILL_AT).__enter__()
        else:
            chaos.inject("preempt_notice", at_step=NOTICE_AT,
                         grace=30.0).__enter__()

    resumed_at = updates
    while updates < TOTAL_UPDATES:
        coord.precheck(updates)
        batch = next_update_batch(it)
        with coord.guard(updates):
            try:
                params, mom, aux, _loss = trainer.step(
                    params, mom, aux, batch, local_batch=True)
            except chaos.SimulatedPreemption:
                # the hard kill: no goodbye, no checkpoint, no KV note
                print("dist_elastic_resize rank %d PREEMPTED at update %d"
                      % (rank, updates + 1), flush=True)
                os._exit(77)
        updates += 1
        # one routed touched-rows update on the sharded table per
        # trainer update; every rank gathers the host snapshot (the
        # state_dict all-gather is a collective), rank 0 persists it
        emb_state = emb_apply(emb, emb_state, updates)
        emb_updates = updates
        emb_host = emb.state_dict(emb_state["table"],
                                  mom=emb_state["mom"])
        if rank == 0:
            save_embedding(emb_mgr, emb, emb_host, updates)
        coord.note_step(updates, (params, mom, aux))
        if gen > 0 and updates == resumed_at + 1:
            # ROADMAP item 5 acceptance, checked at the exact moment it
            # matters — the first post-resize update: the step program
            # was deserialized from the warm cache (hit), nothing was
            # compiled in-drill (no miss, no untagged event)
            cs = telemetry.tracing.compile_summary()
            assert cs["by_result"].get("miss", 0) == 0 and \
                cs["by_result"].get("untagged", 0) == 0, cs
            assert cs["by_result"].get("hit", 0) >= 1, cs
            print("dist_elastic_resize rank %d gen=%d WARM compile "
                  "by_result=%s" % (rank, gen, cs["by_result"]),
                  flush=True)

    # -- completion ---------------------------------------------------------
    if MODE == "kill":
        # kill -> shrink -> grow: only a full-size final generation passes
        assert gen == 2, "expected kill->shrink->grow, got gen %d" % gen
        assert world == 4, world
    else:
        # notice -> shrink, no capacity pressure to grow: finish at 3
        assert gen == 1, "expected one graceful resize, got gen %d" % gen
        assert world == 3, world
    # the acceptance bound (ROADMAP item 5): a resized generation must
    # resume with ZERO in-drill compilation — every compile/* event in
    # this process was a cache hit, none was a miss (the standby or the
    # previous full-size run warmed the cache).  Asserted BEFORE the
    # reference run below, which deliberately compiles a fresh program.
    if gen > 0:
        # still zero in-drill compilation by the END of the generation
        cs = telemetry.tracing.compile_summary()
        assert cs["by_result"].get("miss", 0) == 0 and \
            cs["by_result"].get("untagged", 0) == 0, cs

    # training is done — de-arm the elastic machinery and relax the
    # watchdog before the verification phase: rank 0's solo reference
    # run keeps the others waiting in the final barrier far longer than
    # any training-step deadline, and that silence must not read as a
    # death
    coord.stop_monitor()
    watchdog.configure(step_timeout=600, action="abort",
                       report_dir=CKPT_DIR, poll=0.2)
    watchdog.heartbeat(updates, force=True)   # freshen digests for the view

    # embedding final state to host on EVERY rank (collective gather)
    # before the rank-0-only verification below
    emb_final = emb.state_dict(emb_state["table"], mom=emb_state["mom"])

    if rank == 0:
        view = telemetry.fleet_view()
        assert view["generation"] == gen and view["world_size"] == world, \
            (view["generation"], view["world_size"])
        events = view["resize_events"]
        worlds = [e["world_size"] for e in events]
        if MODE == "kill":
            assert worlds == [3, 4], events
            assert any("grow" in (e.get("reason") or "")
                       for e in events), events
        else:
            assert worlds == [3], events
            assert any("preempt_notice" in (e.get("reason") or "")
                       for e in events), events
        print("FLEET VIEW (rank 0):\n%s" % telemetry.render_fleet(view),
              flush=True)

        ref_names, ref_params = reference_run(X, y)
        for n, a, b in zip(trainer.param_names, params, ref_params):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4,
                err_msg="param %s diverged from the uninterrupted run" % n)
        ref_ce = eval_loss(ref_params, ref_names, X, y)
        el_ce = eval_loss(params, trainer.param_names, X, y)
        assert abs(ref_ce - el_ce) <= max(0.05 * abs(ref_ce), 0.02), \
            (ref_ce, el_ce)
        assert el_ce < 0.2, "elastic run failed to converge: CE=%.4f" % el_ce
        print("dist_elastic_resize LOSS ref=%.4f elastic=%.4f"
              % (ref_ce, el_ce), flush=True)

        # embedding parity: the table that lived through 4 -> 3 -> 4
        # resharding must BIT-match an uninterrupted single-device
        # replay of the same update schedule (exact-representable
        # grads make the routed sums association-free)
        ref_spec = MeshSpec(make_mesh((1,), ("dp",),
                                      devices=jax.local_devices()[:1]))
        ref_emb = ShardedEmbedding(EMB_V, EMB_D, ref_spec, name="drill")
        ref_state = {"table": ref_emb.init_state(seed=42),
                     "mom": ref_emb.zeros_slot()}
        for u in range(1, TOTAL_UPDATES + 1):
            ref_state = emb_apply(ref_emb, ref_state, u)
        ref_host = ref_emb.state_dict(ref_state["table"],
                                      mom=ref_state["mom"])
        assert np.array_equal(emb_final["table"], ref_host["table"]), \
            "embedding table diverged from the uninterrupted replay"
        assert np.array_equal(emb_final["mom"], ref_host["mom"]), \
            "embedding momentum diverged from the uninterrupted replay"
        print("dist_elastic_resize EMB table bit-exact vs uninterrupted"
              " replay after %d resharded updates" % TOTAL_UPDATES,
              flush=True)

    parallel.barrier("elastic_done")
    print("dist_elastic_resize rank %d/%d OK gen=%d updates=%d"
          % (rank, world, gen, updates), flush=True)


if __name__ == "__main__":
    main()
