"""Multi-process synchronous data-parallel training through Module.fit
with kvstore('dist_sync') — the reference tests/nightly/dist_lenet.py
analog, asserting the invariants that matter for sync SGD:

 1. training converges (loss drops, accuracy rises) on rank-sharded data;
 2. after every epoch all ranks hold IDENTICAL parameters (the defining
    property of synchronous data parallelism).

Run:  python tools/launch.py -n 4 python tests/dist/dist_train_mlp.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

# parallel.init_distributed() (called first thing in main, before any
# device is touched) configures the cpu+gloo backend from the launcher's
# env protocol — no manual jax config here.
import jax  # noqa: E402

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel  # noqa: E402


def main():
    parallel.init_distributed()
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers

    # synthetic separable problem; every rank gets a distinct shard
    rs = np.random.RandomState(0)
    X = rs.randn(512, 16).astype(np.float32)
    w_true = rs.randn(16).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)
    shard = slice(rank * 128, (rank + 1) * 128)
    it = mx.io.NDArrayIter(X[shard], y[shard], batch_size=32, shuffle=False)

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")

    mod = mx.mod.Module(net, context=mx.cpu())
    metric = mx.metric.Accuracy()
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier(),
            eval_metric=metric, num_epoch=10, kvstore=kv)

    # every rank must hold identical parameters
    args, _ = mod.get_params()
    for name, arr in sorted(args.items()):
        mine = arr.asnumpy().astype(np.float64)
        global_sum = np.asarray(
            parallel.allreduce_array(jax.numpy.asarray(mine)))
        np.testing.assert_allclose(global_sum, mine * nworker, rtol=1e-5,
                                   err_msg="param %s diverged on rank %d"
                                           % (name, rank))

    it.reset()
    metric.reset()
    mod.score(it, metric)
    acc = dict(metric.get_name_value())["accuracy"]
    assert acc > 0.9, "rank %d accuracy %.3f" % (rank, acc)
    print("dist_train_mlp rank %d/%d OK acc=%.3f" % (rank, nworker, acc),
          flush=True)


if __name__ == "__main__":
    main()
