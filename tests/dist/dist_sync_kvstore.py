"""Multi-process dist_sync kvstore invariants — the reference
tests/nightly/dist_sync_kvstore.py:29-90 rewritten for the TPU stack.

Run under the local launcher (the dmlc-tracker local-mode analog):

    python tools/launch.py -n 4 python tests/dist/dist_sync_kvstore.py

Every rank pushes rank-dependent values; sync semantics require each pull
to observe the SAME globally-reduced value on every rank.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

# parallel.init_distributed() (called first thing in main, before any
# device is touched) configures the cpu+gloo backend from the launcher's
# env protocol — no manual jax config here.
import jax  # noqa: E402

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel  # noqa: E402

RATE = 2
SHAPE = (2, 3)


def check_equal_scalar(arr, x, rank):
    a = arr.asnumpy()
    assert np.sum(np.abs(a - x)) == 0, (rank, a, x)


def main():
    parallel.init_distributed()
    kv = mx.kv.create("dist_sync")
    nworker = int(os.environ["DMLC_NUM_WORKER"])
    rank = kv.rank
    assert kv.num_workers == nworker, (kv.num_workers, nworker)
    assert jax.process_count() == nworker

    keys = ["3", "5", "7"]
    kv.init(keys, [mx.nd.ones(SHAPE)] * len(keys))

    # server-side optimizer analog: every rank applies the same update to
    # the same globally-reduced gradient (reference 'test' optimizer with
    # rescale_grad=RATE: weight += grad * rate)
    def updater(key, recv, stored):
        stored[:] = stored + recv * RATE

    kv.set_updater(updater)

    # sync push/pull: pull after each push must see the global sum
    # (reference check_default_keys: num = (n+1)*n*rate/2*(i+1) + 1)
    for i in range(3):
        kv.push("3", mx.nd.ones(SHAPE) * (rank + 1))
        kv.barrier()
        val = mx.nd.zeros(SHAPE)
        kv.pull("3", out=val)
        num = (nworker + 1) * nworker * RATE / 2 * (i + 1) + 1
        check_equal_scalar(val, num, rank)

    # rank-dependent single-key push: only one worker pushes nonzero
    v = mx.nd.ones(SHAPE) if rank == 0 else mx.nd.zeros(SHAPE)
    kv.push("5", v)
    kv.barrier()
    val = mx.nd.zeros(SHAPE)
    kv.pull("5", out=val)
    check_equal_scalar(val, 1 + RATE, rank)  # init 1 + 1*rate

    # row_sparse push/pull across ranks (reference check_row_sparse_keys):
    # each rank pushes one rank-dependent row; the union-sum must be
    # observed by every rank, moving only the requested rows
    from mxnet_tpu.ndarray import sparse as sp
    kv.init("9", mx.nd.ones(SHAPE))
    my_row = rank % SHAPE[0]
    grad = sp.RowSparseNDArray(
        (mx.nd.ones((1, SHAPE[1])) * (rank + 1))._handle,
        mx.nd.array([my_row]).astype("int64")._handle, SHAPE)
    kv.push("9", grad)
    kv.barrier()
    expected = np.ones(SHAPE)
    for r in range(nworker):
        expected[r % SHAPE[0]] += (r + 1) * RATE
    val = sp.zeros_sparse("row_sparse", SHAPE)
    kv.row_sparse_pull("9", out=val,
                       row_ids=mx.nd.array(np.arange(SHAPE[0])))
    np.testing.assert_allclose(np.asarray(val._handle), expected, rtol=1e-6,
                               err_msg="rank %d" % rank)

    # raw DCN allreduce + barrier primitives
    import jax.numpy as jnp
    total = parallel.allreduce_array(jnp.full((4,), float(rank + 1)))
    assert float(total[0]) == nworker * (nworker + 1) / 2, total
    kv.barrier()

    # telemetry fleet view round-trip (ISSUE 5): each rank runs a few
    # telemetry-spanned "steps" — rank 1 deliberately slowed — beats the
    # heartbeat lane (which piggybacks the metrics digest), and every
    # rank must then see every peer's digest; rank 0's straggler report
    # must finger the slow rank by STEP-TIME skew, not heartbeat lag
    # (rank 1 beats on time; it is merely slow).
    import time
    from mxnet_tpu import telemetry
    from mxnet_tpu.resilience import watchdog
    telemetry.arm()
    slow_rank = 1
    step_sleep = 0.15 if rank == slow_rank else 0.01
    for s in range(1, 4):
        with telemetry.span("train/step", cat="train",
                            metric="train.step_seconds", step=s):
            time.sleep(step_sleep)
        watchdog.heartbeat(s, force=True)
    kv.barrier()   # all digests published before anyone reads
    digests = watchdog.lane().digests()
    assert set(digests) == set(range(nworker)), digests
    for r, d in digests.items():
        assert d["step_ms"]["n"] >= 3, (r, d)
    view = telemetry.fleet_view()
    assert set(view["ranks"]) == {str(r) for r in range(nworker)}
    strag = view["straggler"]["step_time"]
    assert strag["slowest_rank"] == slow_rank, strag
    assert strag["skew"] > 2.0, strag
    if rank == 0:
        print(telemetry.render_fleet(view), flush=True)
    telemetry.disarm()
    kv.barrier()

    print("dist_sync_kvstore rank %d/%d OK" % (rank, nworker), flush=True)


if __name__ == "__main__":
    main()
