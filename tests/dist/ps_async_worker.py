"""One dist_async PS worker for the drills in tests/test_ps_drills.py.

A plain OS process — NOT a jax gang member: rank/world and the server
location come from env (``MXNET_TPU_KV_DIR`` / ``MXNET_TPU_KV_RANK``),
and the loop is pull -> local collective-free step -> push, with no
barrier anywhere.  Chaos hooks fire INSIDE the step region so the drills
can pin a persistent straggler (``hedge_lag`` + ``MXNET_TPU_CHAOS_RANKS``)
or a kill -9 (``replica_crash@step``) to one deterministic worker while
every process runs this same script with the same ``MXNET_TPU_CHAOS``.

Env knobs: ``PS_STEPS`` (fixed step count, default 40) or ``PS_SECONDS``
(time-boxed run — the throughput drills), ``PS_LR`` (default 0.1).

Prints exactly one ``PSWORKER rank=R steps=N eval_loss=L OK`` line on
success; a SIGKILLed worker prints nothing (that is the point).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from mxnet_tpu import kvstore as kvs  # noqa: E402
from mxnet_tpu.kvstore.worker import (  # noqa: E402
    TOY_DIM, make_worker_step, toy_batch, toy_init)
from mxnet_tpu.ndarray.ndarray import array as nd_array  # noqa: E402
from mxnet_tpu.optimizer import Optimizer  # noqa: E402
from mxnet_tpu.resilience import chaos  # noqa: E402


def main():
    rank = int(os.environ.get("MXNET_TPU_KV_RANK", "0"))
    lr = float(os.environ.get("PS_LR", "0.1"))
    seconds = os.environ.get("PS_SECONDS", "").strip()
    max_steps = int(os.environ.get("PS_STEPS", "40"))

    kv = kvs.create("dist_async")
    assert type(kv).__name__ == "KVStorePS", type(kv)
    kv.init("w", nd_array(toy_init()))
    kv.set_optimizer(Optimizer.create_optimizer("sgd", learning_rate=lr))
    if os.environ.get("PS_BARRIER"):
        # throughput drills: a coordination barrier (init sync point —
        # NOT part of the step path) puts every worker on the same start
        # line, so step counts measure the lane, not process launch skew
        kv.barrier()
    # the clock starts after the common start line
    deadline = (time.monotonic() + float(seconds)) if seconds else None

    step_fn = make_worker_step(TOY_DIM)
    out = nd_array(toy_init())
    steps = 0
    while True:
        if deadline is not None:
            if time.monotonic() >= deadline:
                break
        elif steps >= max_steps:
            break
        kv.pull("w", out=out)              # the SSP gate lives here
        x, y = toy_batch(rank, steps)
        chaos.maybe_replica_crash(steps)   # kill -9 drill injection
        _, grad = step_fn(out._handle, x, y)
        kv.push("w", nd_array(np.asarray(grad)))
        steps += 1
        # straggler drill injection: the lag lands AFTER the push so the
        # straggler is in the SSP clock set from its first round — the
        # drill measures the lane under a slow worker, not the window
        # before the server has ever heard from it
        chaos.maybe_hedge_lag(steps)

    # eval on a batch NO worker trained on, with the weights of the last
    # pull — no extra pull here, so nobody re-enters the SSP gate after
    # peers have exited
    xe, ye = toy_batch(999, 0, batch_size=256)
    w = np.asarray(out.asnumpy())
    err = xe @ w - ye
    eval_loss = float(0.5 * np.mean(err * err))
    assert np.isfinite(w).all(), "non-finite weights pulled"
    kv.close()
    print("PSWORKER rank=%d steps=%d eval_loss=%.6f OK"
          % (rank, steps, eval_loss), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
