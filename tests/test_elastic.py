"""Elastic training (mxnet_tpu/resilience/elastic.py): join-based
membership consensus over the coordination KV, resize manifests, graceful
preemption notices, grow-back, generation-stamped heartbeats/digests (no
ghost rows), the watchdog `resize` action, gradient accumulation in
ShardedTrainer, and the elastic launcher's verdict logic.

The 4-proc end-to-end drills (hard kill -> shrink -> grow back; graceful
notice -> shrink) live in tests/test_dist.py::test_dist_elastic_resize_*;
these are the single-process seams.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.resilience import chaos, elastic, watchdog
from mxnet_tpu.resilience.watchdog import HeartbeatLane
from tests.test_watchdog import FakeKVClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    chaos.reset()
    watchdog.reset()
    elastic.reset()
    yield
    chaos.reset()
    watchdog.reset()
    elastic.reset()


def _coord(client, rank, world, tmp_path, exits, **kw):
    lane = HeartbeatLane(client=client)
    kw.setdefault("min_workers", 1)
    kw.setdefault("dead_sec", 0.5)
    kw.setdefault("check_interval", 0.0)
    kw.setdefault("consensus_timeout", 8.0)
    kw.setdefault("round_sec", 0.3)
    return elastic.ElasticCoordinator(
        lane=lane, rank=rank, world=world, generation=0,
        elastic_dir=str(tmp_path), register=False,
        on_exit=lambda code, r=rank: exits.__setitem__(r, code), **kw)


def _beat_all(client, ranks, gen=0, step=5, stale=()):
    now = time.time()
    for r in ranks:
        t = now - 100 if r in stale else now
        client.kv["mxt_hb/%d" % r] = "%d:%f:%d" % (step, t, gen)


# ---------------------------------------------------------------------------
# consensus
# ---------------------------------------------------------------------------

def test_consensus_join_based_convergence():
    """Every rank that shows up is a member; the dead rank (which never
    proposes) is excluded without any vote about it."""
    client = FakeKVClient()
    results = {}

    def run(r):
        results[r] = elastic.propose_membership(client, r, 1, timeout=5,
                                                round_min=0.3)

    ts = [threading.Thread(target=run, args=(r,)) for r in (0, 2, 3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results == {0: [0, 2, 3], 2: [0, 2, 3], 3: [0, 2, 3]}


def test_consensus_late_joiner_inside_round_window():
    """A rank wedged in the dying collective joins late (its monitor
    thread saw the round) and must still be a member."""
    client = FakeKVClient()
    results = {}

    def run(r, delay=0.0):
        time.sleep(delay)
        results[r] = elastic.propose_membership(client, r, 1, timeout=5,
                                                round_min=0.6)

    ts = [threading.Thread(target=run, args=(0,)),
          threading.Thread(target=run, args=(2, 0.3))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results == {0: [0, 2], 2: [0, 2]}


def test_consensus_ignores_stale_proposals():
    """Litter from an aborted round (or a dead rank's old proposal) must
    not count as proof of life in a later round."""
    client = FakeKVClient()
    client.kv["mxt_el/prop/1/7"] = json.dumps(
        {"members": [0, 7], "t": time.time() - 3600})
    out = elastic.propose_membership(client, 0, 1, timeout=5, round_min=0.2)
    assert out == [0]


def test_consensus_commit_short_circuits():
    client = FakeKVClient()
    client.kv["mxt_el/commit/1"] = json.dumps({"members": [0, 2]})
    out = elastic.propose_membership(client, 3, 1, timeout=5)
    assert out == [0, 2]


def test_consensus_timeout():
    class NoKV(FakeKVClient):
        def key_value_dir_get(self, prefix):
            return []       # my own proposal never becomes visible

    with pytest.raises(elastic.ConsensusTimeout):
        elastic.propose_membership(NoKV(), 0, 1, timeout=0.5, round_min=0.1)


# ---------------------------------------------------------------------------
# resign: shrink, false alarm, ghost eviction
# ---------------------------------------------------------------------------

def test_resign_shrink_manifest_eviction_and_commit(tmp_path):
    client = FakeKVClient()
    _beat_all(client, range(4), stale=(1,))
    client.kv["mxt_md/1"] = json.dumps({"gen": 0})
    exits = {}

    def resign(r):
        coord = _coord(client, r, 4, tmp_path, exits, min_workers=3)
        assert coord.dead_ranks() == [1]
        coord.resign("dead_peer")

    ts = [threading.Thread(target=resign, args=(r,)) for r in (0, 2, 3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert exits == {0: 44, 2: 44, 3: 44}
    m = elastic.read_manifest(str(tmp_path))
    assert m["generation"] == 1 and m["world_size"] == 3
    assert m["members"] == [0, 2, 3] and m["dead"] == [1]
    assert "mxt_hb/1" not in client.kv, "dead rank's heartbeat key evicted"
    assert "mxt_md/1" not in client.kv, "dead rank's digest key evicted"
    assert elastic.read_commit(client, 1)["world_size"] == 3


def test_resign_full_membership_is_false_alarm(tmp_path):
    """If every rank of the current world shows up in the round, nothing
    died — resign returns False and nobody exits (the guard re-raises
    the original program bug on every rank)."""
    client = FakeKVClient()
    exits = {}
    results = {}

    def resign(r):
        coord = _coord(client, r, 3, tmp_path, exits)
        results[r] = coord.resign("collective_error:Boom")

    ts = [threading.Thread(target=resign, args=(r,)) for r in (0, 1, 2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results == {0: False, 1: False, 2: False}
    assert exits == {}
    assert elastic.read_manifest(str(tmp_path)) is None


def test_resign_below_min_workers_gives_up(tmp_path):
    client = FakeKVClient()
    exits = {}
    coord = _coord(client, 0, 4, tmp_path, exits, min_workers=3,
                   consensus_timeout=1.0, round_sec=0.2)
    coord.resign("dead_peer")   # only this rank shows up -> world 1 < 3
    assert exits == {0: 1}, "must exit with a PLAIN failure code so the " \
        "launcher's full checkpoint-restart path recovers"


# ---------------------------------------------------------------------------
# graceful preemption notice (two-phase) + grow-back (two-phase)
# ---------------------------------------------------------------------------

def test_preempt_notice_two_phase_leave(tmp_path):
    client = FakeKVClient()
    exits = {}
    coord = _coord(client, 1, 4, tmp_path, exits)
    with chaos.inject("preempt_notice", at_step=8, grace=12.5):
        coord.precheck(8)           # phase 1: announce, keep training
        assert exits == {}
        notice = json.loads(client.kv["mxt_el/leaving/1"])
        assert notice["after_step"] == 9
        assert notice["grace_sec"] == 12.5
        coord.precheck(8)           # idempotent: still training
        assert exits == {}
        coord.precheck(9)           # phase 2: the agreed step -> exit
    assert exits == {1: 44}


def test_peers_resize_on_leaving_notice(tmp_path):
    client = FakeKVClient()
    client.kv["mxt_el/leaving/1"] = json.dumps(
        {"grace_sec": 30, "step": 8, "after_step": 9})
    exits = {}
    phase = threading.Barrier(3)

    def run(r):
        coord = _coord(client, r, 4, tmp_path, exits)
        coord.precheck(8)       # before the hand-off step: keep training
        assert r not in exits
        phase.wait()            # align phase 2 (a rank that reaches the
        coord.precheck(9)       # hand-off first legitimately opens the
        # round and the laggards would join it from precheck(8))

    ts = [threading.Thread(target=run, args=(r,)) for r in (0, 2, 3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert exits == {0: 44, 2: 44, 3: 44}
    m = elastic.read_manifest(str(tmp_path))
    assert m["world_size"] == 3 and m["reason"] == "peer_preempt_notice"
    assert "mxt_el/leaving/1" not in client.kv, "leaver's notice evicted"


def test_grow_back_two_phase(tmp_path):
    elastic.write_capacity(str(tmp_path), 4)
    client = FakeKVClient()
    exits = {}
    coord = _coord(client, 0, 3, tmp_path, exits, grow_after_steps=2)
    coord.note_step(1)
    coord.precheck(1)
    assert exits == {} and not client.key_value_dir_get("mxt_el/grow/"), \
        "must soak grow_after_steps before growing"
    coord.note_step(2)
    coord.precheck(2)           # phase 1: intent published, keep training
    assert exits == {}
    intent = json.loads(client.kv["mxt_el/grow/1"])
    assert intent["world_size"] == 4 and intent["after_step"] == 3
    coord.note_step(3)
    coord.precheck(3)           # phase 2: resign into the bigger world
    assert exits == {0: 44}
    m = elastic.read_manifest(str(tmp_path))
    assert m["generation"] == 1 and m["world_size"] == 4
    assert m["reason"] == "grow_back" and m["prev_world"] == 3

    # a follower rank acts on the same intent at its own phase-2 check
    exits2 = {}
    follower = _coord(client, 1, 3, tmp_path, exits2, grow_after_steps=10)
    follower.precheck(3)
    assert exits2 == {1: 44}


def test_grow_respects_capacity(tmp_path):
    elastic.write_capacity(str(tmp_path), 3)   # no spare capacity
    client = FakeKVClient()
    exits = {}
    coord = _coord(client, 0, 3, tmp_path, exits, grow_after_steps=1)
    for s in (1, 2, 3):
        coord.note_step(s)
        coord.precheck(s)
    assert exits == {} and not client.key_value_dir_get("mxt_el/grow/")


# ---------------------------------------------------------------------------
# monitor thread: a wedged rank joins a peer-initiated round
# ---------------------------------------------------------------------------

def test_monitor_thread_joins_open_round(tmp_path):
    client = FakeKVClient()
    exits = {}
    wedged = _coord(client, 2, 3, tmp_path, exits, round_sec=0.3)
    wedged.start_monitor(poll=0.05)
    try:
        results = {}

        def run(r):
            coord = _coord(client, r, 3, tmp_path, exits)
            results[r] = coord.resign("dead_peer")

        # ranks 0,1 open the round (they think 2 died); the monitor must
        # bring 2 in -> FULL membership -> false alarm everywhere
        ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert results == {0: False, 1: False}
        assert elastic.read_manifest(str(tmp_path)) is None
    finally:
        wedged.stop_monitor()


# ---------------------------------------------------------------------------
# watchdog action=resize
# ---------------------------------------------------------------------------

def test_watchdog_accepts_resize_action():
    wd = watchdog.Watchdog(action="resize", step_timeout=100)
    assert wd.action == "resize"
    with pytest.raises(ValueError):
        watchdog.Watchdog(action="nonsense")


def test_watchdog_resize_without_coordinator_falls_back():
    assert elastic.watchdog_resize("tag") is False


def test_watchdog_resize_with_dead_peer(tmp_path, monkeypatch):
    client = FakeKVClient()
    _beat_all(client, range(3), stale=(1,))
    exits = {}
    coord = _coord(client, 0, 3, tmp_path, exits, dead_sec=0.2,
                   round_sec=0.2, consensus_timeout=3.0)
    monkeypatch.setattr(elastic, "_COORD", coord)

    other_exits = {}
    peer = _coord(client, 2, 3, tmp_path, other_exits, round_sec=0.2,
                  consensus_timeout=3.0)
    t = threading.Thread(target=lambda: peer.resign("dead_peer"))
    t.start()
    assert elastic.watchdog_resize("ShardedTrainer.step", step=7) is True
    t.join(timeout=5)
    assert exits == {0: 44} and other_exits == {2: 44}
    m = elastic.read_manifest(str(tmp_path))
    assert m["members"] == [0, 2] and m["reason"].startswith("watchdog:")


# ---------------------------------------------------------------------------
# generation-stamped heartbeats/digests: no ghost rows after a resize
# ---------------------------------------------------------------------------

def test_beats_carry_generation_and_parse():
    client = FakeKVClient()
    lane = HeartbeatLane(client=client)
    elastic.set_generation(2)
    assert lane.beat(7, force=True)
    value = client.kv["mxt_hb/0"]
    assert value.endswith(":2"), value
    peers = lane.peers()
    assert peers[0]["step"] == 7 and peers[0]["gen"] == 2
    # legacy two-field beats parse as generation 0
    client.kv["mxt_hb/9"] = "3:%f" % time.time()
    assert lane.peers()[9]["gen"] == 0


def test_fleet_view_drops_stale_generation_ghosts(monkeypatch):
    from mxnet_tpu import telemetry
    client = FakeKVClient()
    monkeypatch.setattr(
        "jax._src.distributed.global_state.client", client, raising=False)
    elastic.set_generation(1)
    now = time.time()
    # live generation-1 rows for ranks 0..2, a ghost generation-0 row for
    # the evicted rank 3 (its keys survived the resize)
    for r in range(3):
        client.kv["mxt_hb/%d" % r] = "12:%f:1" % now
        client.kv["mxt_md/%d" % r] = json.dumps(
            {"gen": 1, "world": 3, "step_ms": {"p50": 10.0 + r}})
    client.kv["mxt_hb/3"] = "8:%f:0" % (now - 50)
    client.kv["mxt_md/3"] = json.dumps(
        {"gen": 0, "world": 4, "step_ms": {"p50": 500.0}})

    view = telemetry.fleet_view()
    assert view["generation"] == 1
    assert sorted(view["ranks"]) == ["0", "1", "2"]
    assert view["ghosts"] == [{"rank": 3, "gen": 0}]
    # the ghost must not poison the straggler report either
    strag = view["straggler"]
    assert "3" not in strag["ranks"]
    assert strag["step_time"]["slowest_rank"] != 3
    rendered = telemetry.render_fleet(view)
    assert "generation 1" in rendered
    assert "ghosts dropped" in rendered
    # ... and num_dead must not count evicted incarnations
    lane = HeartbeatLane(client=client)
    assert lane.num_dead(timeout_sec=30) == 0


def test_fleet_view_shows_resize_events(monkeypatch):
    from mxnet_tpu import telemetry
    client = FakeKVClient()
    monkeypatch.setattr(
        "jax._src.distributed.global_state.client", client, raising=False)
    client.kv[elastic.HISTORY_KEY] = json.dumps(
        [{"generation": 1, "world_size": 3, "prev_world": 4,
          "reason": "dead_peer", "step": 7, "time": 1.0}])
    client.kv["mxt_el/commit/2"] = json.dumps(
        {"generation": 2, "world_size": 4, "prev_world": 3,
         "reason": "grow_back", "step": 14, "time": 2.0, "members": [0, 1, 2]})
    view = telemetry.fleet_view()
    worlds = [e["world_size"] for e in view["resize_events"]]
    assert worlds == [3, 4]
    rendered = telemetry.render_fleet(view)
    assert "resize: generation 1 -> world 3" in rendered
    assert "resize: generation 2 -> world 4" in rendered


def test_digest_carries_generation_and_world():
    from mxnet_tpu import telemetry
    elastic.set_generation(3)
    d = telemetry.rank_digest(step=4)
    assert d["gen"] == 3 and d["world"] == 1


# ---------------------------------------------------------------------------
# chaos preempt_notice
# ---------------------------------------------------------------------------

def test_preempt_notice_fire_and_grace():
    with chaos.inject("preempt_notice", at_step=3, grace=7.0):
        assert chaos.maybe_preempt_notice(2) is None
        assert chaos.maybe_preempt_notice(3) == 7.0
        assert chaos.maybe_preempt_notice(3) is None, "one-shot"


def test_preempt_notice_env_spec_and_default_grace(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_CHAOS", "preempt_notice@5")
    monkeypatch.setenv("MXNET_TPU_CHAOS_PREEMPT_GRACE_SECONDS", "11")
    chaos.reset()
    assert chaos.maybe_preempt_notice(4) is None
    assert chaos.maybe_preempt_notice(5) == 11.0


# ---------------------------------------------------------------------------
# manifests, capacity, launcher verdicts
# ---------------------------------------------------------------------------

def test_manifest_roundtrip_and_ordering(tmp_path):
    for gen, world in ((2, 4), (1, 3)):
        elastic.write_manifest(str(tmp_path), {
            "generation": gen, "world_size": world, "prev_world": 4,
            "members": list(range(world)), "dead": [], "reason": "x",
            "step": gen * 7, "time": float(gen)})
    ms = elastic.read_manifests(str(tmp_path))
    assert [m["generation"] for m in ms] == [1, 2]
    assert elastic.read_manifest(str(tmp_path))["generation"] == 2
    assert elastic.read_manifest(str(tmp_path), 1)["world_size"] == 3
    assert elastic.read_manifest(str(tmp_path), 9) is None


def test_capacity_file_roundtrip(tmp_path):
    assert elastic.read_capacity(str(tmp_path)) is None
    elastic.write_capacity(str(tmp_path), 4)
    assert elastic.read_capacity(str(tmp_path)) == 4


def test_launcher_decide_next(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import launch
    finally:
        sys.path.pop(0)
    d = str(tmp_path)
    assert launch.decide_next([0, 0, 0, 0], d, 0, 4, 3) == ("done", None)
    # resize exits without a manifest are a plain failure
    assert launch.decide_next([77, 44, 44, 44], d, 0, 4, 3) == ("fail", None)
    elastic.write_manifest(d, {"generation": 1, "world_size": 3,
                               "prev_world": 4, "members": [0, 2, 3],
                               "dead": [1], "reason": "dead_peer",
                               "step": 7, "time": 1.0})
    assert launch.decide_next([77, 44, 44, 44], d, 0, 4, 3) == ("resize", 3)
    # clamped to launcher capacity
    elastic.write_manifest(d, {"generation": 2, "world_size": 9,
                               "prev_world": 3, "members": [0, 1, 2],
                               "dead": [], "reason": "grow_back",
                               "step": 14, "time": 2.0})
    assert launch.decide_next([44, 44, 44], d, 1, 4, 3) == ("resize", 4)
    # below min-workers is a plain failure
    elastic.write_manifest(d, {"generation": 3, "world_size": 2,
                               "prev_world": 4, "members": [0, 1],
                               "dead": [2, 3], "reason": "dead_peer",
                               "step": 20, "time": 3.0})
    assert launch.decide_next([44, 44, 1, 1], d, 2, 4, 3) == ("fail", None)


def test_postmortem_renders_elastic_timeline(tmp_path):
    elastic.write_manifest(str(tmp_path), {
        "generation": 1, "world_size": 3, "prev_world": 4,
        "members": [0, 2, 3], "dead": [1], "reason": "dead_peer",
        "step": 7, "time": time.time()})
    elastic.write_manifest(str(tmp_path), {
        "generation": 2, "world_size": 4, "prev_world": 3,
        "members": [0, 1, 2], "dead": [], "reason": "grow_back",
        "step": 14, "time": time.time()})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "postmortem.py"),
         "--elastic", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "ELASTIC RESIZE TIMELINE" in r.stdout
    assert "dead_peer" in r.stdout and "grow_back" in r.stdout
    assert "4 -> 3" in r.stdout and "3 -> 4" in r.stdout
    assert "(lost: 1)" in r.stdout


# ---------------------------------------------------------------------------
# grad accumulation + mesh re-form
# ---------------------------------------------------------------------------

def test_grad_accum_for():
    assert elastic.grad_accum_for(48, 4, 4) == 3
    assert elastic.grad_accum_for(48, 4, 3) == 4
    assert elastic.grad_accum_for(48, 48, 1) == 1
    with pytest.raises(ValueError):
        elastic.grad_accum_for(48, 5, 4)


def test_grad_accum_matches_single_big_batch():
    """accum=k over one (k*m)-row batch must produce the SAME update as
    accum=1 over the same rows — the invariant the elastic resize leans
    on to keep the global batch constant across world sizes."""
    from mxnet_tpu.models.mlp import get_symbol
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    shapes = {"data": (16, 8), "softmax_label": (16,)}
    rs = np.random.RandomState(0)
    batch = {"data": rs.rand(16, 8).astype(np.float32),
             "softmax_label": rs.randint(0, 4, 16).astype(np.float32)}

    outs = {}
    for accum in (1, 4):
        spec = MeshSpec(make_mesh((4,), ("dp",)))
        tr = ShardedTrainer(get_symbol(num_classes=4), spec, lr=0.1,
                            momentum=0.9, wd=0.0, grad_accum=accum)
        p, m, x = tr.init_state(shapes, seed=3)
        for _ in range(3):
            p, m, x, loss = tr.step(p, m, x, batch)
        outs[accum] = (p, float(loss))
    for a, b in zip(outs[1][0], outs[4][0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert outs[1][1] == pytest.approx(outs[4][1], rel=1e-5)


def test_grad_accum_validation():
    from mxnet_tpu.models.mlp import get_symbol
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    spec = MeshSpec(make_mesh((4,), ("dp",)))
    with pytest.raises(ValueError):
        ShardedTrainer(get_symbol(num_classes=4), spec, grad_accum=0)
    tr = ShardedTrainer(get_symbol(num_classes=4), spec, grad_accum=3)
    p, m, x = tr.init_state({"data": (16, 8), "softmax_label": (16,)},
                            seed=3)
    bad = {"data": np.zeros((16, 8), np.float32),
           "softmax_label": np.zeros((16,), np.float32)}
    with pytest.raises(ValueError, match="not divisible"):
        tr.step(p, m, x, bad)       # 16 rows don't fold into 3 micros


def test_reform_mesh_bumps_generation_and_keeps_axes():
    import jax
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh, reform_mesh

    n = len(jax.devices())
    spec = MeshSpec(make_mesh((n,), ("dp",)), generation=4)
    out = reform_mesh(spec)
    assert out.generation == 5
    assert out.mesh.shape["dp"] == n
    assert out.dp_axis == spec.dp_axis


def test_data_parallel_mesh_stamps_elastic_generation():
    from mxnet_tpu.parallel.mesh import data_parallel_mesh
    elastic.set_generation(6)
    assert data_parallel_mesh().generation == 6
