"""Serving-fleet tests (mxnet_tpu/serving/{wire,replica,router,fleet}.py
+ the FileKVClient lane + chaos replica_crash/hedge_lag + tools).

Three tiers, like test_serving.py:
 - protocol/unit seams with no processes: wire framing, the file-backed
   coordination-KV lane, tenant token buckets, replica digests, fleet
   rendering, cancelled-request queue behavior;
 - process drills: real replica processes behind the router — the
   kill-one-replica acceptance drill (chaos ``replica_crash`` SIGKILLs a
   replica MID-BATCH; zero late OKs, in-flight requests complete via
   hedging/re-dispatch, eject + relaunch + re-admit), the hedge_lag
   straggler drill, tenant fairness, priority-eviction parity with the
   PR-4 in-replica semantics, and the rolling swap with fleet-wide
   rollback on a failing canary;
 - tools: servebench --replicas smoke (+ @slow sustained kill drill) and
   postmortem --fleet rendering; @slow 1->4 replica QPS scaling.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience.watchdog import FileKVClient, HeartbeatLane
from mxnet_tpu.serving import (Overloaded, QuotaExceeded, ServingRuntime,
                               SwapFailed, TenantPolicy)
from mxnet_tpu.serving import wire
from mxnet_tpu.serving.admission import AdmissionQueue
from mxnet_tpu.serving.errors import Cancelled
from mxnet_tpu.serving.fleet import ServingFleet, fleet_lane
from mxnet_tpu.serving.replica import SyntheticProgram, _schema_of
from mxnet_tpu.serving.request import Request
from mxnet_tpu.telemetry import render_fleet, replica_digest, \
    serving_fleet_view

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


def _row(value=1.0, features=3):
    return np.full((features,), value, np.float32)


def _mk_fleet(n, tmp_path, latency=0.005, **kw):
    kw.setdefault("synthetic", (4, 3, latency))
    kw.setdefault("fleet_dir", str(tmp_path / "fleet"))
    kw.setdefault("stale_after", 0.8)
    kw.setdefault("scan_interval", 0.05)
    kw.setdefault("ready_timeout", 45.0)
    return ServingFleet(n, **kw)


def _events(fleet):
    path = os.path.join(fleet.fleet_dir, "fleet-events.jsonl")
    if not os.path.isfile(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# protocol / unit seams (no processes)
# ---------------------------------------------------------------------------

def test_wire_roundtrip_and_framing_errors():
    a, b = socket.socketpair()
    try:
        arrays = {"data": np.arange(12, dtype=np.float32).reshape(3, 4),
                  "mask": np.array([1, 0, 1], np.int8)}
        wire.send_msg(a, {"op": "submit", "id": 7, "deadline": 0.5},
                      arrays)
        header, got = wire.recv_msg(b)
        assert header["op"] == "submit" and header["id"] == 7
        assert set(got) == {"data", "mask"}
        np.testing.assert_array_equal(got["data"], arrays["data"])
        np.testing.assert_array_equal(got["mask"], arrays["mask"])
        assert got["data"].dtype == np.float32

        # empty-array and no-array frames round-trip too
        wire.send_msg(a, {"op": "ping"},
                      {"empty": np.zeros((0, 4), np.float32)})
        header, got = wire.recv_msg(b)
        assert got["empty"].shape == (0, 4)

        # garbage magic is a typed WireError, not a hang or a crash
        a.sendall(b"GARBAGE-NOT-A-FRAME!")
        with pytest.raises(wire.WireError):
            wire.recv_msg(b)
    finally:
        a.close()
        b.close()

    # an absurd payload_len is refused BEFORE any allocation: a corrupt
    # frame must not be able to force a multi-GB buffer into existence
    a, b = socket.socketpair()
    try:
        a.sendall(wire._FIXED.pack(wire.MAGIC, 2, (1 << 62)) + b"{}")
        with pytest.raises(wire.WireError, match="payload length"):
            wire.recv_msg(b)
    finally:
        a.close()
        b.close()


def test_file_kv_client_and_lane(tmp_path):
    kv = FileKVClient(str(tmp_path / "kv"))
    kv.key_value_set("mxt_hb/0", "1:2.0:0")
    kv.key_value_set("mxt_hb/1", "5:3.0:0")
    kv.key_value_set("other/9", "zzz")
    got = dict(kv.key_value_dir_get("mxt_hb/"))
    assert got == {"mxt_hb/0": "1:2.0:0", "mxt_hb/1": "5:3.0:0"}
    # overwrite-in-place and delete
    kv.key_value_set("mxt_hb/0", "2:4.0:0")
    assert kv.key_value_get("mxt_hb/0") == "2:4.0:0"
    kv.key_value_delete("mxt_hb/1")
    assert dict(kv.key_value_dir_get("mxt_hb/")) == {"mxt_hb/0": "2:4.0:0"}

    # the PR-5 HeartbeatLane runs unchanged over the file client, with
    # an explicit rank and an explicit (serving) digest
    lane = HeartbeatLane(client=kv, rank=3)
    assert lane.beat(17, force=True, digest={"kind": "serving", "x": 1})
    peers = lane.peers()
    assert peers[3]["step"] == 17
    assert lane.digests()[3] == {"kind": "serving", "x": 1}
    lane.evict(3)
    assert 3 not in lane.peers() and 3 not in lane.digests()


def test_tenant_policy_token_bucket():
    pol = TenantPolicy(rate=10, burst=3)
    t0 = 1000.0
    # burst drains first
    assert [pol.try_acquire(now=t0) for _ in range(4)] == \
        [True, True, True, False]
    # 0.25s at 10/s refills 2.5 tokens -> exactly 2 more admits
    assert pol.try_acquire(now=t0 + 0.25)
    assert pol.try_acquire(now=t0 + 0.25)
    assert not pol.try_acquire(now=t0 + 0.25)
    # unlimited tenant never sheds
    assert all(TenantPolicy().try_acquire() for _ in range(100))


def test_replica_digest_carries_router_facts():
    prog = SyntheticProgram(4, 3, 0.0)
    with ServingRuntime(prog, name="digest-test") as rt:
        rt.predict({"data": _row()}, deadline=2.0)
        d = replica_digest(rt, 2, port=4567, qps=12.5, model="v1",
                           schema=_schema_of(prog))
    assert d["kind"] == "serving" and d["replica"] == 2
    assert d["port"] == 4567 and d["qps"] == 12.5
    assert d["health"] == "SERVING" and d["pid"] == os.getpid()
    assert d["schema"]["input_names"] == ["data"]
    assert d["schema"]["input_shapes"]["data"] == [4, 3]
    assert "p95" in d["lat_ms"]
    assert d["counters"]["completed"] == 1


def test_serving_fleet_view_and_render(tmp_path, monkeypatch):
    fleet_dir = str(tmp_path / "f")
    prog = SyntheticProgram(4, 3, 0.0)
    with ServingRuntime(prog, name="view-test") as rt:
        rt.predict({"data": _row()}, deadline=2.0)
        for rid in (0, 1):
            lane = fleet_lane(fleet_dir, rank=rid)
            lane.beat(3, force=True,
                      digest=replica_digest(rt, rid, port=1000 + rid,
                                            qps=5.0,
                                            schema=_schema_of(prog)))
    view = serving_fleet_view(fleet_dir)
    assert set(view["replicas"]) == {"0", "1"}
    assert view["replicas"]["0"]["digest"]["port"] == 1000
    rendered = render_fleet(view)
    assert "serving replicas" in rendered
    assert "SERVING" in rendered
    # and the combined training fleet_view picks the serving table up
    # from MXNET_TPU_FLEET_DIR, rendering both planes in one call
    monkeypatch.setenv("MXNET_TPU_FLEET_DIR", fleet_dir)
    from mxnet_tpu.telemetry import fleet_view
    combined = fleet_view()
    assert set(combined["serving"]["replicas"]) == {"0", "1"}
    assert "serving replicas" in render_fleet(combined)


def test_admission_queue_skips_cancelled_requests():
    q = AdmissionQueue(4)
    live = Request({"data": _row()[None]}, 1, seq=1)
    dead = Request({"data": _row()[None]}, 1, seq=2)
    q.offer(dead)
    q.offer(live)
    dead._fail(Cancelled("hedge won elsewhere"))
    got = q.pop_live(timeout=0.1)
    assert got is live                 # the cancelled one was dropped
    assert q.pop_live(timeout=0.01) is None
    # and the cancellation did not count as an expiry shed
    assert q.shed_expired == 0


# ---------------------------------------------------------------------------
# process drills
# ---------------------------------------------------------------------------

def test_fleet_kill_replica_drill(tmp_path):
    """THE acceptance drill: chaos ``replica_crash`` SIGKILLs replica 1
    mid-batch under sustained load.  Zero late OKs, zero failed
    requests (in-flight ones complete elsewhere via hedging/re-dispatch
    within their deadlines), the router ejects the dead replica, the
    supervisor relaunches it, and the router re-admits it."""
    fleet = _mk_fleet(
        3, tmp_path, latency=0.01,
        replica_env={1: {"MXNET_TPU_CHAOS": "replica_crash@15"}})
    try:
        deadline = 1.5
        results = {"ok": 0, "late": 0, "err": {}}
        lock = threading.Lock()
        stop_at = time.monotonic() + 2.5
        x = _row()

        def worker():
            while time.monotonic() < stop_at:
                t0 = time.monotonic()
                try:
                    req = fleet.submit(data=x, deadline=deadline)
                    req.result(timeout=deadline + 5.0)
                    lat = time.monotonic() - t0
                    with lock:
                        if lat > deadline + 0.05:
                            results["late"] += 1
                        else:
                            results["ok"] += 1
                except Exception as e:
                    with lock:
                        k = type(e).__name__
                        results["err"][k] = results["err"].get(k, 0) + 1

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)

        assert results["late"] == 0, "late OK delivered: %s" % results
        assert not results["err"], \
            "requests failed during the kill drill: %s" % results
        assert results["ok"] > 50
        c = fleet.stats()["counters"]
        assert c["evictions"] >= 1
        # the in-flight requests of the dead replica completed elsewhere
        assert c.get("redispatched", 0) + c.get("hedge_fired", 0) >= 1
        # relaunch + re-admit: all three slots READY again (the crashed
        # replica only re-arms its chaos once traffic resumes, and the
        # load is over)
        assert fleet.router.wait_ready(3, timeout=20.0), \
            fleet.router.replicas()
        events = [e["event"] for e in _events(fleet)]
        assert "evict" in events and "readmit" in events
    finally:
        fleet.close()


def test_fleet_hedging_bounds_straggler_tail(tmp_path):
    """chaos ``hedge_lag`` turns replica 1 into a persistent 0.4s
    straggler.  The router's digest-informed hedging keeps every request
    inside a small multiple of the healthy replica's latency — no
    request ever waits out the full lag."""
    fleet = _mk_fleet(
        2, tmp_path, latency=0.005,
        hedge_min=0.05, hedge_factor=1.5,
        replica_env={1: {"MXNET_TPU_CHAOS": "hedge_lagx1000000",
                         "MXNET_TPU_CHAOS_HEDGE_LAG_SECONDS": "0.4"}})
    try:
        lat = []
        x = _row()
        for _ in range(30):
            t0 = time.monotonic()
            fleet.predict(data=x, deadline=2.0)
            lat.append(time.monotonic() - t0)
        c = fleet.stats()["counters"]
        assert c["ok"] == 30
        assert c.get("hedge_fired", 0) >= 1, c
        # every request that landed on the straggler was rescued by its
        # hedge far below the 0.4s lag
        assert max(lat) < 0.3, "tail not bounded: max=%.3fs" % max(lat)
    finally:
        fleet.close()


def test_hedge_losers_are_reaped_and_fleet_still_swaps(tmp_path):
    """Regression: a cancelled hedge loser gets no reply from the
    replica, so the router must reap its bookkeeping itself in _finish.
    Before the fix, one won hedge left the loser's ``inflight`` pinned
    at 1 forever — skewing least-loaded dispatch and wedging
    ``swap_fleet`` (whose drain waits for inflight == 0)."""
    fleet = _mk_fleet(
        2, tmp_path, latency=0.005,
        hedge_min=0.05, hedge_factor=1.5,
        replica_env={1: {"MXNET_TPU_CHAOS": "hedge_lagx1000000",
                         "MXNET_TPU_CHAOS_HEDGE_LAG_SECONDS": "0.4"}})
    try:
        x = _row()
        for _ in range(10):
            fleet.predict(data=x, deadline=2.0)
        c = fleet.stats()["counters"]
        assert c.get("hedge_won", 0) >= 1, c   # losers actually existed
        # every loser's inflight must have been reaped at finish time,
        # not parked waiting for a cancel reply that never comes
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            inflight = {rid: r["inflight"]
                        for rid, r in fleet.stats()["replicas"].items()}
            if all(n == 0 for n in inflight.values()):
                break
            time.sleep(0.02)
        assert all(n == 0 for n in inflight.values()), \
            "leaked inflight after won hedges: %r" % inflight
        # and the drain-gated rolling swap still completes
        swapped = fleet.swap({"batch": 4, "features": 3, "scale": 3.0},
                             tag="post-hedge")
        assert len(swapped) == 2
        out = fleet.predict(data=x, deadline=2.0)
        np.testing.assert_allclose(out[0][0], 3.0 * x, rtol=1e-6)
    finally:
        fleet.close()


def test_tenant_fairness_quota_and_priority(tmp_path):
    """A flooding low-priority tenant is shed at ITS quota with
    QuotaExceeded while a low-QPS high-priority tenant keeps its p99 —
    nobody else pays for the flood."""
    fleet = _mk_fleet(
        2, tmp_path, latency=0.002,
        quotas={"flood": TenantPolicy(rate=30, burst=5, priority=0),
                "vip": TenantPolicy(priority=5)})
    try:
        x = _row()
        stats = {"flood_ok": 0, "shed": 0, "vip_ok": 0, "other": {}}
        vip_lat = []
        lock = threading.Lock()
        stop_at = time.monotonic() + 2.5

        def flooder():
            while time.monotonic() < stop_at:
                try:
                    fleet.predict(data=x, tenant="flood", deadline=1.0)
                    with lock:
                        stats["flood_ok"] += 1
                except QuotaExceeded:
                    with lock:
                        stats["shed"] += 1
                    time.sleep(0.002)      # paced flood, not a spin
                except Exception as e:
                    with lock:
                        k = type(e).__name__
                        stats["other"][k] = stats["other"].get(k, 0) + 1

        threads = [threading.Thread(target=flooder, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        while time.monotonic() < stop_at:
            t0 = time.monotonic()
            fleet.predict(data=x, tenant="vip", deadline=1.0)
            vip_lat.append(time.monotonic() - t0)
            with lock:
                stats["vip_ok"] += 1
            time.sleep(0.02)
        for t in threads:
            t.join(timeout=10.0)

        assert stats["shed"] > 0, stats
        assert not stats["other"], stats
        # the flood got through at ~its token rate (30/s for 2.5s +
        # burst), not at its attempt rate
        assert stats["flood_ok"] <= 30 * 2.5 + 5 + 10, stats
        assert stats["vip_ok"] >= 50
        vip_lat.sort()
        p99 = vip_lat[max(0, int(len(vip_lat) * 0.99) - 1)]
        assert p99 < 0.5, "vip p99 %.3fs collateral from the flood" % p99
        # the shed is attributed to the flooding tenant in the counters
        assert fleet.stats()["counters"]["quota_shed"] == stats["shed"]
    finally:
        fleet.close()


def test_router_priority_maps_to_in_replica_eviction(tmp_path):
    """Priority classes resolved at the router ride into the replica's
    AdmissionQueue, so under replica overload the eviction order is
    exactly the PR-4 semantics: the lowest-priority, oldest request
    pays; a high-priority arrival is admitted."""
    fleet = _mk_fleet(
        1, tmp_path, latency=0.08,
        quotas={"bulk": TenantPolicy(priority=0),
                "vip": TenantPolicy(priority=7)},
        # tiny queue + slow exec: the single replica saturates instantly
        replica_env={0: {"MXNET_TPU_SERVE_QUEUE_DEPTH": "2",
                         "MXNET_TPU_SERVE_MAX_BATCH": "1",
                         "MXNET_TPU_SERVE_LINGER": "0"}},
        retry_max=1)      # no second replica: sheds must surface typed
    try:
        x = _row()
        bulk = [fleet.submit(data=x, tenant="bulk", deadline=3.0)
                for _ in range(8)]
        time.sleep(0.05)
        vip = fleet.submit(data=x, tenant="vip", deadline=3.0)
        outcomes = {"ok": 0, "Overloaded": 0}
        for req in bulk:
            try:
                req.result(timeout=6.0)
                outcomes["ok"] += 1
            except Overloaded:
                outcomes["Overloaded"] += 1
        vip.result(timeout=6.0)            # never shed, never evicted
        assert outcomes["Overloaded"] >= 1, outcomes
        assert outcomes["ok"] >= 1, outcomes
    finally:
        fleet.close()


def test_rolling_swap_under_load_with_rollback(tmp_path):
    """Rolling fleet swap under live load: zero failed requests during a
    good swap; a failing canary (chaos ``bad_swap`` on replica 1)
    triggers fleet-wide rollback with the OLD model still serving; a
    clean retry then lands the new model everywhere."""
    fleet = _mk_fleet(
        2, tmp_path, latency=0.002,
        replica_env={1: {"MXNET_TPU_CHAOS": "bad_swap"}})
    try:
        x = _row()
        res = {"ok": 0, "err": {}}
        stop_at = time.monotonic() + 4.0

        def loader():
            while time.monotonic() < stop_at:
                try:
                    fleet.predict(data=x, deadline=1.0)
                    res["ok"] += 1
                except Exception as e:
                    k = type(e).__name__
                    res["err"][k] = res["err"].get(k, 0) + 1

        threads = [threading.Thread(target=loader, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)

        spec = {"batch": 4, "features": 3, "latency": 0.002, "scale": 2.0}
        # first attempt: replica 0 swaps, replica 1's canary is poisoned
        # -> fleet-wide rollback, old model (scale 1) keeps serving
        with pytest.raises(SwapFailed):
            fleet.swap(spec, tag="v2")
        out = fleet.predict(data=x, deadline=1.0)
        assert float(out[0][0][0]) == pytest.approx(1.0)
        events = [e["event"] for e in _events(fleet)]
        assert "swap_fail" in events and "rollback" in events

        # retry (the one-shot chaos fault is consumed): lands everywhere
        assert fleet.swap(spec, tag="v2") == [0, 1]
        out = fleet.predict(data=x, deadline=1.0)
        assert float(out[0][0][0]) == pytest.approx(2.0)

        for t in threads:
            t.join(timeout=10.0)
        assert not res["err"], \
            "requests failed during rolling swaps: %s" % res
        assert res["ok"] > 100
        all_events = _events(fleet)
        events = [e["event"] for e in all_events]
        assert "swap_complete" in events
        assert events.count("drain") >= 3
        # warm-load on every replica: each swapped replica prewarmed the
        # incoming model BEFORE its drain (prewarm_ok precedes drain in
        # the event log) and activated the prewarmed standby (warm=True
        # echoed by the replica) — the drained window held nothing but
        # the pointer flip
        swap_oks = [e for e in all_events if e["event"] == "swap_ok"]
        assert swap_oks and all(e.get("warm") for e in swap_oks), swap_oks
        prewarm_rids = {e["replica"] for e in all_events
                        if e["event"] == "prewarm_ok"}
        assert {e["replica"] for e in swap_oks} <= prewarm_rids
        for rid in sorted(prewarm_rids):
            seq = [e["event"] for e in all_events
                   if e.get("replica") == rid
                   and e["event"] in ("prewarm_ok", "drain")]
            assert seq.index("prewarm_ok") < seq.index("drain"), seq
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# tools
# ---------------------------------------------------------------------------

def _run_servebench(extra, timeout=120):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "servebench.py"),
         "--json"] + extra,
        capture_output=True, text=True, timeout=timeout, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout[out.stdout.index("{"):])


def test_servebench_fleet_smoke():
    rep = _run_servebench(["--replicas", "2", "--duration", "1.5",
                           "--exec-latency", "0.004",
                           "--concurrency", "4", "--deadline", "0.5",
                           "--tenants", "search,ads"])
    assert rep["replicas"] == 2
    assert rep["ok"] > 20 and rep["late_ok"] == 0
    assert rep["ready_at_end"] == 2
    share = rep["per_replica_share"]
    assert set(share) == {"0", "1"}
    assert abs(share["0"] - share["1"]) < 0.5      # both replicas served
    assert "p99_ms" in rep["latency"]
    # per-tenant SLO block (additive schema): both synthetic tenants
    # show availability + budget burn, nobody shed
    tenants = rep["tenants"]
    assert set(tenants) == {"search", "ads"}
    for t in tenants.values():
        assert t["availability"] == 1.0
        assert t["budget_burn"]["p95"] < 1.0
        assert "latency_ms" in t


def test_postmortem_fleet_renders_timeline(tmp_path):
    path = tmp_path / "fleet-events.jsonl"
    events = [
        {"t": 1000.0, "event": "join", "replica": 0, "port": 4000},
        {"t": 1001.0, "event": "evict", "replica": 0, "cause": "link"},
        {"t": 1002.5, "event": "readmit", "replica": 0, "port": 4001},
        {"t": 1003.0, "event": "swap_begin", "targets": [0]},
        {"t": 1003.2, "event": "drain", "replica": 0},
        {"t": 1003.4, "event": "swap_ok", "replica": 0, "tag": "v2"},
        {"t": 1003.5, "event": "swap_complete", "replicas": [0]},
    ]
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "postmortem.py"),
         "--fleet", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "SERVING FLEET TIMELINE (7 event(s))" in out.stdout
    assert "evict" in out.stdout and "cause=link" in out.stdout
    assert "swap_ok" in out.stdout and "tag=v2" in out.stdout
    assert "evict=1" in out.stdout       # the summary line


# ---------------------------------------------------------------------------
# @slow: sustained drills + scaling
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_qps_scales_1_to_4_replicas(tmp_path):
    """Near-linear QPS 1 -> 4 replicas with bounded p99.  The synthetic
    executor sleeps (latency-bound), so replica processes genuinely
    parallelize even on one host core; the router/wire overhead is what
    could break linearity, and this guards it."""
    def measure(n, seconds=6.0):
        fleet = _mk_fleet(n, tmp_path / ("s%d" % n), latency=0.02)
        lat = []
        lock = threading.Lock()
        try:
            x = _row()
            stop_at = time.monotonic() + seconds
            done = [0]

            def worker():
                while time.monotonic() < stop_at:
                    t0 = time.monotonic()
                    fleet.predict(data=x, deadline=3.0)
                    with lock:
                        done[0] += 1
                        lat.append(time.monotonic() - t0)

            threads = [threading.Thread(target=worker, daemon=True)
                       for _ in range(16)]
            t_start = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=seconds + 30.0)
            elapsed = time.monotonic() - t_start
            lat.sort()
            return (done[0] / elapsed,
                    lat[max(0, int(len(lat) * 0.99) - 1)])
        finally:
            fleet.close()

    qps1, p99_1 = measure(1)
    qps4, p99_4 = measure(4)
    assert qps4 > 2.5 * qps1, \
        "QPS did not scale: 1 replica %.0f/s, 4 replicas %.0f/s" \
        % (qps1, qps4)
    # bounded p99: adding replicas must not grow the tail
    assert p99_4 < max(4 * p99_1, 0.5), \
        "p99 grew from %.3fs to %.3fs" % (p99_1, p99_4)


@pytest.mark.slow
def test_servebench_sustained_kill_drill():
    """The --kill-after acceptance drill at sustained load: a replica is
    SIGKILLed mid-run, the fleet sheds nothing, delivers zero late OKs,
    and ends with the relaunched replica re-enrolled."""
    rep = _run_servebench(["--replicas", "3", "--duration", "8",
                           "--exec-latency", "0.01",
                           "--concurrency", "8", "--deadline", "1.0",
                           "--kill-after", "3", "--kill-slot", "1"],
                          timeout=300)
    assert rep["kill"]["slot"] == 1
    assert rep["ok"] > 500
    assert rep["late_ok"] == 0
    assert not rep["errors"], rep["errors"]
    assert rep["evictions"] >= 1
    assert rep["redispatched"] + rep["hedge"]["fired"] >= 1
    assert rep["ready_at_end"] == 3
