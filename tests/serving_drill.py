"""End-to-end chaos serving drill — run as a SUBPROCESS by
tests/test_serving.py::test_chaos_serving_drill.

The parent arms faults through the environment (the production drill
path, not the in-code context manager):

    MXNET_TPU_CHAOS="exec_errorx4,slow_execx6,bad_swap"
    MXNET_TPU_CHAOS_SLOW_EXEC_SECONDS=<small>

and this script drives a real exported ServedProgram through the
serving runtime, asserting with live traffic that

  1. repeated executor failures open the circuit breaker (health
     BROKEN, instant typed CircuitOpen shedding) and a post-cooldown
     probe closes it again;
  2. a saturating load sheds with typed Overloaded and the queue never
     grows past its bound;
  3. no request is ever reported OK past its deadline;
  4. an env-armed bad_swap hot-swap is rejected (typed SwapFailed) with
     ZERO failed requests attributable to the swap, and the follow-up
     clean swap actually changes the served model.

It prints one "DRILL_VERDICT {json}" line, then wedges the executor
under a watchdog armed with action=abort: the watchdog must dump a
post-mortem and KILL this process with exit code 43, which the parent
verifies (the kill-and-verify step).

Usage: python tests/serving_drill.py <workdir>
"""
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np           # noqa: E402

import mxnet_tpu as mx       # noqa: E402
from mxnet_tpu.resilience import chaos                    # noqa: E402
from mxnet_tpu.serving import (CircuitOpen, Overloaded,   # noqa: E402
                               ServingRuntime, SwapFailed)

DEADLINE = 0.25


def export_artifact(path, seed):
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=5, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    ex = net.simple_bind(mx.cpu(), data=(4, 3))
    rs = np.random.RandomState(seed)
    for a in ex.arg_arrays:
        a[:] = mx.nd.array(rs.normal(0, 0.5, a.shape))
    ex.export_compiled(path, input_names=("data",))
    return path


def main():
    workdir = sys.argv[1]
    verdict = {}
    # telemetry armed so every env-armed chaos firing is a labeled
    # counter the wrapper can assert on (injected == expected)
    from mxnet_tpu import telemetry
    telemetry.arm()
    art_a = export_artifact(os.path.join(workdir, "model_a.mxt"), seed=0)
    art_b = export_artifact(os.path.join(workdir, "model_b.mxt"), seed=1)

    full = np.linspace(-1, 1, 12, dtype=np.float32).reshape(4, 3)
    # depth 16: small enough that a 60-request flood sheds (Overloaded),
    # large enough that admitted requests sit behind slow_exec batches
    # long enough to expire (DeadlineExceeded before dispatch)
    rt = ServingRuntime(
        art_a, queue_depth=16, linger=0.005, default_deadline=DEADLINE,
        retry_tries=2, retry_backoff=0.005, breaker_threshold=2,
        breaker_cooldown=0.4, report_dir=workdir)

    # warm-up on the program directly (not through the runtime): pays the
    # lazy device work without consuming any armed chaos firings, so
    # phase 1 starts from the exact env-armed fault counts
    rt._program.forward(data=full)

    # -- phase 1: circuit breaker opens on consecutive executor failures
    exec_failures = 0
    for _ in range(2):           # 2 batches x 2 retry attempts = 4 firings
        try:
            rt.predict(data=full, deadline=2.0)
        except Exception:
            exec_failures += 1
    verdict["exec_failures"] = exec_failures
    verdict["health_after_failures"] = rt.health_name()
    try:
        rt.submit(data=full, deadline=2.0)
        verdict["circuit_shed_typed"] = False
    except CircuitOpen:
        verdict["circuit_shed_typed"] = True
    time.sleep(rt._breaker.cooldown + 0.1)
    try:
        rt.predict(data=full, deadline=2.0)     # probe (slow_exec but ok)
        verdict["probe_ok"] = True
    except Exception as e:
        verdict["probe_ok"] = False
        verdict["probe_error"] = repr(e)
    verdict["health_after_probe"] = rt.health_name()

    # -- phase 2: saturating load -> bounded queue, typed shedding, no
    #    late OK (slow_exec still has firings left; after those the tiny
    #    model is fast, so the flood sees both regimes)
    outcomes = {"ok": 0, "Overloaded": 0, "DeadlineExceeded": 0,
                "other": 0}
    late_ok = 0
    depth_max = [0]
    stop = [False]

    def sampler():
        while not stop[0]:
            depth_max[0] = max(depth_max[0], len(rt._queue))
            time.sleep(0.002)

    samp = threading.Thread(target=sampler, daemon=True)
    samp.start()
    lock = threading.Lock()
    late_counter = [0]

    def flood():
        # open loop: submit everything up front (saturation), collect
        # afterwards — shed happens at submit, deadlines at collect
        row = np.ones((3,), np.float32)
        admitted = []
        for _ in range(15):
            try:
                admitted.append(rt.submit(data=row, deadline=DEADLINE))
            except Exception as e:
                with lock:
                    outcomes[type(e).__name__] = \
                        outcomes.get(type(e).__name__, 0) + 1
        for req in admitted:
            try:
                req.result(timeout=DEADLINE + 5)
                with lock:
                    outcomes["ok"] += 1
                    if req.latency > DEADLINE:
                        late_counter[0] += 1
            except Exception as e:
                with lock:
                    outcomes[type(e).__name__] = \
                        outcomes.get(type(e).__name__, 0) + 1

    threads = [threading.Thread(target=flood) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop[0] = True
    samp.join(timeout=1)
    late_ok = late_counter[0]
    verdict["flood_outcomes"] = outcomes
    verdict["late_ok"] = late_ok
    verdict["queue_depth_max"] = depth_max[0]
    verdict["queue_bound"] = rt._queue.depth

    # -- phase 3: bad_swap rejected with zero request impact, then a
    #    clean swap changes the model
    before = rt.predict(data=full, deadline=2.0)[0]
    bg_failures = [0]
    bg_stop = [False]

    def background():
        while not bg_stop[0]:
            try:
                rt.predict(data=full, deadline=2.0)
            except Exception:
                bg_failures[0] += 1

    bg = threading.Thread(target=background, daemon=True)
    bg.start()
    try:
        rt.swap(art_b)               # env-armed bad_swap poisons canary
        verdict["bad_swap_typed"] = False
    except SwapFailed:
        verdict["bad_swap_typed"] = True
    after_bad = rt.predict(data=full, deadline=2.0)[0]
    try:
        rt.swap(art_b)               # fault consumed: clean swap
        swap_ok = True
    except Exception:
        swap_ok = False
    after_good = rt.predict(data=full, deadline=2.0)[0]
    bg_stop[0] = True
    bg.join(timeout=5)
    verdict["swap_ok"] = swap_ok
    verdict["bg_failures_during_swaps"] = bg_failures[0]
    verdict["unchanged_after_bad_swap"] = bool(
        np.allclose(before, after_bad, atol=1e-6))
    verdict["changed_after_good_swap"] = bool(
        not np.allclose(before, after_good, atol=1e-4))
    stats = rt.stats()
    verdict["breaker_opened_total"] = stats["breaker"]["opened_total"]
    verdict["breaker_recovered_total"] = stats["breaker"]["recovered_total"]
    rt.close()

    fault_counter = telemetry.counter("chaos.faults_injected")
    verdict["faults_injected"] = {
        "exec_error": fault_counter.value(kind="exec_error"),
        "slow_exec": fault_counter.value(kind="slow_exec"),
        "bad_swap": fault_counter.value(kind="bad_swap"),
    }

    print("DRILL_VERDICT " + json.dumps(verdict), flush=True)

    # -- phase 4 (kill-and-verify): wedge the executor under an
    #    abort-mode watchdog; it must write forensics and _exit(43)
    rt2 = ServingRuntime(art_a, default_deadline=5.0, retry_tries=1,
                         exec_timeout=0.15, watchdog_action="abort",
                         report_dir=workdir, name="drill-wedge")
    with chaos.inject("slow_exec", seconds=60):
        rt2.submit(data=full, deadline=5.0)
        time.sleep(30)               # the watchdog kills us first
    sys.exit(7)                      # unreachable if the watchdog works


if __name__ == "__main__":
    main()
