"""Expert-parallel MoE tests on the 8-device virtual mesh (SURVEY §2.3
expert parallelism; switch-style top-1 routing with lax.all_to_all)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.parallel.moe import moe_ffn, moe_ffn_dense, top1_gating


def _weights(E=8, d=8, h=16, seed=0):
    rs = np.random.RandomState(seed)
    wg = rs.normal(0, 1, (d, E)).astype(np.float32)
    w1 = rs.normal(0, 0.3, (E, d, h)).astype(np.float32)
    w2 = rs.normal(0, 0.3, (E, h, d)).astype(np.float32)
    return jnp.asarray(wg), jnp.asarray(w1), jnp.asarray(w2)


def test_top1_gating_masks():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.normal(0, 1, (16, 4)).astype(np.float32))
    dispatch, combine, aux = top1_gating(logits, capacity=16)
    d = np.asarray(dispatch)
    # with ample capacity every token is dispatched exactly once
    assert (d.sum(axis=(1, 2)) == 1).all()
    # combine weight equals the winning softmax prob
    probs = np.asarray(jax.nn.softmax(logits, -1))
    np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                               probs.max(axis=1), rtol=1e-5)
    assert np.isfinite(float(aux))
    # capacity 1: at most one token per expert survives
    d1, _, _ = top1_gating(logits, capacity=1)
    assert np.asarray(d1).sum(axis=(0, 2)).max() <= 1.0 + 1e-6


def test_moe_matches_dense_when_no_drops():
    mesh = make_mesh((8,), ("ep",))
    wg, w1, w2 = _weights()
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.normal(0, 1, (64, 8)).astype(np.float32))
    # capacity_factor = E guarantees capacity >= local tokens: no drops
    out, aux = moe_ffn(x, wg, w1, w2, mesh, capacity_factor=8.0)
    want, want_aux = moe_ffn_dense(x, wg, w1, w2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    # sharded aux is the mean of per-shard losses — same scale, not equal
    assert np.isfinite(float(aux))


def test_moe_capacity_drops_are_zero_rows():
    mesh = make_mesh((8,), ("ep",))
    wg, w1, w2 = _weights()
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.normal(0, 1, (64, 8)).astype(np.float32))
    out_tight, _ = moe_ffn(x, wg, w1, w2, mesh, capacity_factor=0.5)
    out_ample, _ = moe_ffn(x, wg, w1, w2, mesh, capacity_factor=8.0)
    o_t, o_a = np.asarray(out_tight), np.asarray(out_ample)
    # a dropped token's output row is exactly zero; kept rows match ample
    dropped = np.all(o_t == 0, axis=1)
    assert dropped.any(), "capacity 0.5 must drop something"
    np.testing.assert_allclose(o_t[~dropped], o_a[~dropped], rtol=1e-4,
                               atol=1e-5)


def test_moe_differentiable_over_mesh():
    mesh = make_mesh((8,), ("ep",))
    wg, w1, w2 = _weights()
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.normal(0, 1, (64, 8)).astype(np.float32))

    def loss(w1_, w2_, wg_):
        out, aux = moe_ffn(x, wg_, w1_, w2_, mesh, capacity_factor=4.0)
        return jnp.sum(out ** 2) + 0.01 * aux

    g1, g2, gg = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(w1, w2, wg)
    assert np.isfinite(np.asarray(g1)).all()
    assert np.isfinite(np.asarray(g2)).all()
    assert np.isfinite(np.asarray(gg)).all()
    assert float(jnp.abs(g1).sum()) > 0 and float(jnp.abs(gg).sum()) > 0


def test_moe_single_device_fallback():
    mesh = make_mesh((1,), ("ep",))
    wg, w1, w2 = _weights()
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.normal(0, 1, (16, 8)).astype(np.float32))
    out, aux = moe_ffn(x, wg, w1, w2, mesh)
    want, _ = moe_ffn_dense(x, wg, w1, w2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-5)


def test_moe_shape_validation():
    mesh = make_mesh((8,), ("ep",))
    wg, w1, w2 = _weights()
    with pytest.raises(ValueError):
        moe_ffn(jnp.zeros((63, 8)), wg, w1, w2, mesh)
