"""Gluon tests (reference tests/python/unittest/test_gluon*.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert p.list_ctx()[0].device_type == "cpu"


def test_parameter_dict_sharing():
    params1 = gluon.ParameterDict("net1_")
    # sharing adopts the shared dict's prefix (reference Block(params=...))
    params2 = gluon.ParameterDict(params1.prefix, shared=params1)
    params1.get("w0", shape=(10, 10))
    assert list(params2.get("w0").shape) == [10, 10]
    assert params2.get("w0") is params1.get("w0")


def test_constant():
    c = gluon.Constant("const", [[1, 2], [3, 4]])
    c.initialize()
    assert c.grad_req == "null"
    assert_almost_equal(c.data().asnumpy(), np.array([[1, 2], [3, 4.]]))


def test_dense():
    net = nn.Dense(8, in_units=4, activation="relu")
    net.initialize()
    x = nd.random.uniform(shape=(2, 4))
    out = net(x)
    assert out.shape == (2, 8)
    assert (out.asnumpy() >= 0).all()
    # deferred init
    net2 = nn.Dense(8)
    net2.initialize()
    out2 = net2(nd.ones((3, 5)))
    assert out2.shape == (3, 8)
    assert net2.weight.shape == (8, 5)


def test_sequential_and_hybridize():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize()
    x = nd.random.uniform(shape=(5, 10))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-4, atol=1e-5)


def test_hybrid_backward_matches_eager():
    def run(hybridize):
        mx.seed(42)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="tanh"), nn.Dense(2))
        net.initialize(mx.init.Constant(0.05))
        if hybridize:
            net.hybridize()
        x = nd.array(np.random.RandomState(0).rand(4, 6))
        with mx.autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        return [p.grad().asnumpy() for _, p in
                sorted(net.collect_params().items())]

    g1 = run(False)
    g2 = run(True)
    for a, b in zip(g1, g2):
        assert_almost_equal(a, b, rtol=1e-4, atol=1e-5)


def test_conv_layers():
    x = nd.random.uniform(shape=(2, 3, 16, 16))
    conv = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3)
    conv.initialize()
    assert conv(x).shape == (2, 8, 16, 16)
    pool = nn.MaxPool2D(2, 2)
    assert pool(x).shape == (2, 3, 8, 8)
    gap = nn.GlobalAvgPool2D()
    assert gap(x).shape == (2, 3, 1, 1)
    tconv = nn.Conv2DTranspose(4, kernel_size=2, strides=2, in_channels=3)
    tconv.initialize()
    assert tconv(x).shape == (2, 4, 32, 32)
    c1 = nn.Conv1D(4, kernel_size=3, in_channels=3)
    c1.initialize()
    assert c1(nd.ones((2, 3, 10))).shape == (2, 4, 8)


def test_batchnorm_layer():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = nd.random.uniform(shape=(8, 4, 3, 3))
    rm0 = bn.running_mean.data().asnumpy().copy()
    with mx.autograd.record():
        out = bn(x)
    assert out.shape == x.shape
    assert not np.allclose(rm0, bn.running_mean.data().asnumpy())


def test_embedding_flatten_dropout():
    emb = nn.Embedding(10, 6)
    emb.initialize()
    out = emb(nd.array([1, 2, 3], dtype="int32"))
    assert out.shape == (3, 6)
    assert nn.Flatten()(nd.ones((2, 3, 4))).shape == (2, 12)
    do = nn.Dropout(0.5)
    assert (do(nd.ones((4, 4))).asnumpy() == 1).all()  # predict mode


def test_losses():
    pred = nd.array(np.random.rand(4, 5).astype(np.float32))
    label = nd.array([0, 1, 2, 3], dtype="float32")
    for loss_fn, args in [
            (gluon.loss.SoftmaxCrossEntropyLoss(), (pred, label)),
            (gluon.loss.L2Loss(), (pred, nd.zeros((4, 5)))),
            (gluon.loss.L1Loss(), (pred, nd.zeros((4, 5)))),
            (gluon.loss.SigmoidBinaryCrossEntropyLoss(),
             (pred, nd.zeros((4, 5)))),
            (gluon.loss.HuberLoss(), (pred, nd.zeros((4, 5)))),
            (gluon.loss.HingeLoss(), (pred, nd.ones((4, 5)))),
            (gluon.loss.KLDivLoss(from_logits=False),
             (pred, nd.softmax(pred)))]:
        out = loss_fn(*args)
        assert out.shape == (4,)
    # CE matches manual
    ce = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label).asnumpy()
    p = pred.asnumpy()
    ls = p - np.log(np.exp(p).sum(-1, keepdims=True))
    manual = -ls[np.arange(4), label.asnumpy().astype(int)]
    assert_almost_equal(ce, manual, rtol=1e-4)


def test_trainer_convergence():
    mx.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(0)
    y = rs.randint(0, 4, 256)
    X = rs.rand(256, 16).astype(np.float32) * 0.1
    for i in range(256):
        X[i, y[i] * 4:(y[i] + 1) * 4] += 1
    for epoch in range(10):
        for i in range(0, 256, 64):
            xb = nd.array(X[i:i + 64])
            yb = nd.array(y[i:i + 64].astype(np.float32))
            with mx.autograd.record():
                l = loss_fn(net(xb), yb)
            l.backward()
            trainer.step(64)
    preds = net(nd.array(X)).asnumpy().argmax(1)
    assert (preds == y).mean() > 0.95


def test_save_load_params(tmp_path):
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    net.initialize(mx.init.Xavier())
    fname = str(tmp_path / "net.params")
    net.save_params(fname)
    w0 = net[0].weight.data().asnumpy()

    net2 = nn.HybridSequential(prefix="model_")
    with net2.name_scope():
        net2.add(nn.Dense(4, in_units=3))
    net2.load_params(fname)
    assert_almost_equal(net2[0].weight.data().asnumpy(), w0)


def test_rnn_layers():
    for cls, nstate in [(gluon.rnn.RNN, 1), (gluon.rnn.LSTM, 2),
                        (gluon.rnn.GRU, 1)]:
        layer = cls(12, num_layers=2, input_size=6)
        layer.initialize()
        x = nd.random.uniform(shape=(7, 3, 6))
        out = layer(x)
        assert out.shape == (7, 3, 12)
        states = layer.begin_state(batch_size=3)
        out, new_states = layer(x, states)
        assert out.shape == (7, 3, 12)
        assert len(new_states) == nstate
    bi = gluon.rnn.LSTM(12, num_layers=1, bidirectional=True, input_size=6)
    bi.initialize()
    assert bi(nd.random.uniform(shape=(7, 3, 6))).shape == (7, 3, 24)
    # NTC layout
    ntc = gluon.rnn.GRU(5, layout="NTC", input_size=4)
    ntc.initialize()
    assert ntc(nd.random.uniform(shape=(2, 9, 4))).shape == (2, 9, 5)


def test_rnn_cells():
    for cell_cls in [gluon.rnn.RNNCell, gluon.rnn.LSTMCell, gluon.rnn.GRUCell]:
        cell = cell_cls(8, input_size=4)
        cell.initialize()
        outs, states = cell.unroll(5, nd.random.uniform(shape=(2, 5, 4)),
                                   merge_outputs=True)
        assert outs.shape == (2, 5, 8)
    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(8, input_size=4))
    stack.add(gluon.rnn.LSTMCell(6, input_size=8))
    stack.initialize()
    outs, states = stack.unroll(3, nd.random.uniform(shape=(2, 3, 4)),
                                merge_outputs=True)
    assert outs.shape == (2, 3, 6)
    # residual
    res = gluon.rnn.ResidualCell(gluon.rnn.GRUCell(4, input_size=4))
    res.initialize()
    outs, _ = res.unroll(3, nd.random.uniform(shape=(2, 3, 4)),
                         merge_outputs=True)
    assert outs.shape == (2, 3, 4)


def test_rnn_fused_vs_cell():
    """Fused LSTM layer output matches the unfused cell stack."""
    mx.seed(7)
    layer = gluon.rnn.LSTM(8, num_layers=1, input_size=5, prefix="m_")
    layer.initialize(mx.init.Xavier())
    x = nd.random.uniform(shape=(6, 2, 5))
    fused_out = layer(x).asnumpy()
    cell = layer._unfuse()
    outs, _ = cell.unroll(6, x, layout="TNC", merge_outputs=True)
    assert_almost_equal(outs.asnumpy(), fused_out, rtol=1e-4, atol=1e-5)


def test_model_zoo_shapes():
    for name, shape in [("resnet18_v1", (2, 3, 32, 32)),
                        ("resnet18_v2", (2, 3, 32, 32)),
                        ("squeezenet1.1", (2, 3, 64, 64)),
                        ("mobilenet0.25", (2, 3, 32, 32))]:
        net = gluon.model_zoo.get_model(name, classes=10)
        net.initialize(mx.init.Xavier())
        out = net(nd.random.uniform(shape=shape))
        assert out.shape == (2, 10), name


def test_model_zoo_inception_v3():
    """reference gluon/model_zoo/vision/inception.py (299x299 canonical
    input; the E-block concats land at 2048 channels before the pool)."""
    net = gluon.model_zoo.get_model("inceptionv3", classes=7)
    net.initialize(mx.init.Xavier())
    out = net(nd.random.uniform(shape=(1, 3, 299, 299)))
    assert out.shape == (1, 7)


def test_dataset_dataloader():
    X = np.random.rand(20, 3).astype(np.float32)
    y = np.arange(20, dtype=np.float32)
    dataset = gluon.data.ArrayDataset(X, y)
    assert len(dataset) == 20
    loader = gluon.data.DataLoader(dataset, batch_size=6, shuffle=False,
                                   last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 3)
    assert batches[-1][0].shape == (2, 3)
    # threaded workers give same content
    loader2 = gluon.data.DataLoader(dataset, batch_size=5, num_workers=2)
    total = sum(b[1].asnumpy().sum() for b in loader2)
    assert total == y.sum()
    # transform
    t = dataset.transform_first(lambda x: x * 2)
    assert_almost_equal(t[3][0], X[3] * 2, rtol=1e-6)


def test_split_and_load():
    data = nd.arange(0, 16).reshape((8, 2))
    parts = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(1)])
    assert len(parts) == 2
    assert parts[0].shape == (4, 2)


def test_clip_global_norm():
    arrays = [nd.ones((2, 2)) * 3, nd.ones((2,)) * 4]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    total = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert total <= 1.01


def test_symbol_block():
    from mxnet_tpu import sym
    data = sym.Variable("data")
    net_sym = sym.FullyConnected(data, num_hidden=6, name="fc")
    blk = gluon.SymbolBlock(net_sym, data)
    blk.collect_params().initialize()
    out = blk(nd.ones((2, 4)))
    assert out.shape == (2, 6)


def test_gluon_contrib_layers_and_sampler():
    """gluon.contrib.nn Concurrent/HybridConcurrent/Identity + contrib.data
    IntervalSampler (reference gluon/contrib)."""
    from mxnet_tpu.gluon import contrib as gcontrib
    cat = gcontrib.nn.HybridConcurrent(axis=1)
    cat.add(gluon.nn.Dense(3), gcontrib.nn.Identity(), gluon.nn.Dense(2))
    cat.initialize(mx.init.Xavier())
    x = nd.random.uniform(shape=(4, 5))
    out = cat(x)
    assert out.shape == (4, 3 + 5 + 2)
    np.testing.assert_allclose(out.asnumpy()[:, 3:8], x.asnumpy(), rtol=1e-6)

    s = gcontrib.data.IntervalSampler(13, interval=3)
    assert list(s) == [0, 3, 6, 9, 12, 1, 4, 7, 10, 2, 5, 8, 11]
    assert len(s) == 13
    s2 = gcontrib.data.IntervalSampler(13, interval=3, rollover=False)
    assert list(s2) == [0, 3, 6, 9, 12] and len(s2) == 5


def test_dataloader_multiprocess_workers():
    """The forked worker plane (reference dataloader.py:23 multiprocess
    workers + shared-memory handoff): numpy batches cross process
    boundaries via shared memory, order is preserved, and worker
    exceptions surface in the parent."""
    X = np.random.rand(30, 4).astype(np.float32)
    y = np.arange(30, dtype=np.float32)
    dataset = gluon.data.ArrayDataset(X, y)
    loader = gluon.data.DataLoader(dataset, batch_size=7, num_workers=3,
                                   thread_workers=False)
    batches = list(loader)
    assert [b[0].shape[0] for b in batches] == [7, 7, 7, 7, 2]
    got = np.concatenate([b[1].asnumpy() for b in batches])
    assert_almost_equal(got, y, rtol=0)          # in order, complete
    assert_almost_equal(batches[1][0].asnumpy(), X[7:14], rtol=1e-6)

    class Boom(gluon.data.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, idx):
            if idx == 5:
                raise ValueError("bad sample")
            return np.float32(idx)

    with pytest.raises(RuntimeError, match="bad sample"):
        list(gluon.data.DataLoader(Boom(), batch_size=4, num_workers=2,
                                   thread_workers=False))

    # thread mode still available for jax-backed datasets
    loader_t = gluon.data.DataLoader(dataset, batch_size=10,
                                     num_workers=2, thread_workers=True)
    tot = sum(b[1].asnumpy().sum() for b in loader_t)
    assert tot == y.sum()
