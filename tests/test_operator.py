"""Operator correctness tests (modeled on reference test_operator.py),
including finite-difference gradient checks."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_symbolic_forward, check_consistency)


def test_unary_math():
    x = np.random.rand(3, 4).astype(np.float32) + 0.5
    a = nd.array(x)
    for name, ref in [("sqrt", np.sqrt), ("exp", np.exp), ("log", np.log),
                      ("square", np.square), ("abs", np.abs),
                      ("sin", np.sin), ("cos", np.cos), ("tanh", np.tanh),
                      ("floor", np.floor), ("ceil", np.ceil),
                      ("sign", np.sign), ("log1p", np.log1p),
                      ("expm1", np.expm1), ("rint", np.rint)]:
        out = getattr(nd, name)(a)
        assert_almost_equal(out.asnumpy(), ref(x), rtol=1e-4, atol=1e-6)
    assert_almost_equal(nd.rsqrt(a).asnumpy(), 1 / np.sqrt(x), rtol=1e-4)
    assert_almost_equal(nd.reciprocal(a).asnumpy(), 1 / x, rtol=1e-4)
    assert_almost_equal(nd.sigmoid(a).asnumpy(), 1 / (1 + np.exp(-x)), rtol=1e-4)
    assert_almost_equal(nd.relu(nd.array(x - 1)).asnumpy(),
                        np.maximum(x - 1, 0), rtol=1e-5)


def test_activation():
    x = np.random.randn(2, 5).astype(np.float32)
    for act, ref in [("relu", lambda v: np.maximum(v, 0)),
                     ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
                     ("tanh", np.tanh),
                     ("softrelu", lambda v: np.log1p(np.exp(v)))]:
        out = nd.Activation(nd.array(x), act_type=act)
        assert_almost_equal(out.asnumpy(), ref(x), rtol=1e-4, atol=1e-5)


def test_leaky_relu():
    x = np.array([[-2.0, -1, 0, 1, 2]], dtype=np.float32)
    out = nd.LeakyReLU(nd.array(x), act_type="leaky", slope=0.1)
    assert_almost_equal(out.asnumpy(), np.where(x >= 0, x, 0.1 * x), rtol=1e-5)
    out = nd.LeakyReLU(nd.array(x), act_type="elu", slope=1.0)
    assert_almost_equal(out.asnumpy(), np.where(x >= 0, x, np.expm1(x)),
                        rtol=1e-5)


def test_softmax():
    x = np.random.randn(4, 10).astype(np.float32)
    out = nd.softmax(nd.array(x))
    e = np.exp(x - x.max(-1, keepdims=True))
    assert_almost_equal(out.asnumpy(), e / e.sum(-1, keepdims=True), rtol=1e-4)
    lout = nd.log_softmax(nd.array(x))
    assert_almost_equal(lout.asnumpy(), np.log(e / e.sum(-1, keepdims=True)),
                        rtol=1e-3, atol=1e-5)


def test_fully_connected():
    x = np.random.rand(4, 3, 2).astype(np.float32)
    w = np.random.rand(5, 6).astype(np.float32)
    b = np.random.rand(5).astype(np.float32)
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b), num_hidden=5)
    ref = x.reshape(4, 6).dot(w.T) + b
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-4)
    out2 = nd.FullyConnected(nd.array(x.reshape(4, 6)), nd.array(w),
                             num_hidden=5, no_bias=True)
    assert_almost_equal(out2.asnumpy(), ref - b, rtol=1e-4)


def test_convolution_vs_numpy():
    # 1x1 conv equals matmul over channels
    x = np.random.rand(2, 3, 5, 5).astype(np.float32)
    w = np.random.rand(4, 3, 1, 1).astype(np.float32)
    b = np.zeros(4, np.float32)
    out = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(1, 1), num_filter=4)
    ref = np.einsum("nchw,kc->nkhw", x, w[:, :, 0, 0])
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-4)


def test_conv_grad_numeric():
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=2, pad=(1, 1),
                          name="conv")
    x = np.random.rand(1, 2, 5, 5)
    w = np.random.rand(2, 2, 3, 3)
    b = np.random.rand(2)
    check_numeric_gradient(net, {"data": x, "conv_weight": w, "conv_bias": b},
                           numeric_eps=1e-2, rtol=0.1, atol=1e-2)


def test_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="max")
    assert_almost_equal(out.asnumpy(),
                        np.array([[[[5, 7], [13, 15.]]]]))
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="avg")
    assert_almost_equal(out.asnumpy(),
                        np.array([[[[2.5, 4.5], [10.5, 12.5]]]]))
    out = nd.Pooling(nd.array(x), global_pool=True, pool_type="max")
    assert out.asnumpy().reshape(()) == 15


def test_batchnorm_train_stats():
    x = np.random.rand(8, 3, 4, 4).astype(np.float32) * 5
    gamma = np.ones(3, np.float32)
    beta = np.zeros(3, np.float32)
    mm = nd.zeros((3,))
    mv = nd.ones((3,))
    with mx.autograd.train_mode():
        out = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                           mm, mv, fix_gamma=False, momentum=0.9)
    # normalized output has ~zero mean / unit var per channel
    o = out.asnumpy()
    assert abs(o.mean(axis=(0, 2, 3))).max() < 1e-4
    assert abs(o.var(axis=(0, 2, 3)) - 1).max() < 1e-2
    # moving stats updated toward batch stats
    assert (mm.asnumpy() != 0).all()


def test_dropout_modes():
    x = nd.ones((100, 100))
    with mx.autograd.train_mode():
        out = nd.Dropout(x, p=0.5)
    frac = (out.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7
    out_eval = nd.Dropout(x, p=0.5)  # predict mode: identity
    assert (out_eval.asnumpy() == 1).all()


def test_softmax_output_grad():
    """Backward must be softmax - onehot regardless of out_grad."""
    data = sym.Variable("data")
    label = sym.Variable("label")
    net = sym.SoftmaxOutput(data, label, name="sm")
    x = np.random.randn(4, 5).astype(np.float32)
    y = np.array([0, 1, 2, 3], np.float32)
    ex = net.bind(mx.cpu(), {"data": nd.array(x), "label": nd.array(y)},
                  args_grad={"data": nd.zeros((4, 5))},
                  grad_req={"data": "write", "label": "null"})
    ex.forward(is_train=True)
    ex.backward()
    prob = np.exp(x) / np.exp(x).sum(-1, keepdims=True)
    oh = np.eye(5, dtype=np.float32)[y.astype(int)]
    assert_almost_equal(ex.grad_dict["data"].asnumpy(), prob - oh, rtol=1e-4,
                        atol=1e-5)


def test_regression_outputs():
    x = np.random.randn(4, 3).astype(np.float32)
    y = np.random.randn(4, 3).astype(np.float32)
    data = sym.Variable("data")
    label = sym.Variable("label")
    net = sym.LinearRegressionOutput(data, label)
    ex = net.bind(mx.cpu(), {"data": nd.array(x), "label": nd.array(y)},
                  args_grad={"data": nd.zeros((4, 3))},
                  grad_req={"data": "write", "label": "null"})
    out = ex.forward(is_train=True)
    assert_almost_equal(out[0].asnumpy(), x)
    ex.backward()
    assert_almost_equal(ex.grad_dict["data"].asnumpy(), (x - y) / 3, rtol=1e-4)


def test_elemwise_grad_numeric():
    a = sym.Variable("a")
    b = sym.Variable("b")
    net = sym.elemwise_add(a * 2, sym.elemwise_mul(a, b))
    check_numeric_gradient(net, {"a": np.random.rand(3, 3),
                                 "b": np.random.rand(3, 3)},
                           numeric_eps=1e-3, rtol=0.05, atol=1e-3)


def test_reshape_infer_codes():
    from mxnet_tpu.ops.matrix import infer_reshape
    assert infer_reshape((2, 3, 4), (0, -1)) == (2, 12)
    assert infer_reshape((2, 3, 4), (-1, 0), reverse=True) == (6, 4)
    assert infer_reshape((2, 3, 4), (-2,)) == (2, 3, 4)
    assert infer_reshape((2, 3, 4), (0, -3)) == (2, 12)
    assert infer_reshape((2, 12), (0, -4, 3, 4)) == (2, 3, 4)
    assert infer_reshape((2, 12), (0, -4, -1, 4)) == (2, 3, 4)


def test_sequence_ops():
    x = np.random.rand(4, 3, 2).astype(np.float32)  # (T, N, C)
    seq_len = np.array([2, 4, 1], np.float32)
    out = nd.SequenceMask(nd.array(x), nd.array(seq_len),
                          use_sequence_length=True, value=-1)
    o = out.asnumpy()
    assert (o[2:, 0] == -1).all() and (o[1:, 2] == -1).all()
    assert_almost_equal(o[:2, 1], x[:2, 1])
    last = nd.SequenceLast(nd.array(x), nd.array(seq_len),
                           use_sequence_length=True)
    assert_almost_equal(last.asnumpy()[0], x[1, 0])
    assert_almost_equal(last.asnumpy()[1], x[3, 1])
    rev = nd.SequenceReverse(nd.array(x), nd.array(seq_len),
                             use_sequence_length=True)
    assert_almost_equal(rev.asnumpy()[0, 0], x[1, 0])
    assert_almost_equal(rev.asnumpy()[1, 0], x[0, 0])
    assert_almost_equal(rev.asnumpy()[2, 0], x[2, 0])


def test_rnn_op_shapes():
    T, N, C, H, L = 5, 3, 4, 6, 2
    from mxnet_tpu.ops.rnn import rnn_param_size
    for mode in ["rnn_tanh", "gru", "lstm"]:
        nparam = rnn_param_size(L, C, H, False, mode)
        data = nd.array(np.random.rand(T, N, C).astype(np.float32))
        params = nd.array(np.random.rand(nparam).astype(np.float32) * 0.1)
        state = nd.zeros((L, N, H))
        if mode == "lstm":
            out, hN, cN = nd.RNN(data, params, state, nd.zeros((L, N, H)),
                                 state_size=H, num_layers=L, mode=mode,
                                 state_outputs=True)
            assert cN.shape == (L, N, H)
        else:
            out, hN = nd.RNN(data, params, state, state_size=H, num_layers=L,
                             mode=mode, state_outputs=True)
        assert out.shape == (T, N, H)
        assert hN.shape == (L, N, H)
    # bidirectional
    nparam = rnn_param_size(1, C, H, True, "lstm")
    out = nd.RNN(nd.array(np.random.rand(T, N, C).astype(np.float32)),
                 nd.array(np.random.rand(nparam).astype(np.float32) * 0.1),
                 nd.zeros((2, N, H)), nd.zeros((2, N, H)),
                 state_size=H, num_layers=1, mode="lstm", bidirectional=True)
    assert out.shape == (T, N, 2 * H)


def test_lstm_matches_manual():
    """Single-layer LSTM against a hand-rolled numpy step."""
    T, N, C, H = 3, 2, 4, 5
    from mxnet_tpu.ops.rnn import rnn_param_size
    nparam = rnn_param_size(1, C, H, False, "lstm")
    rng = np.random.RandomState(0)
    params = rng.rand(nparam).astype(np.float32) * 0.2 - 0.1
    x = rng.rand(T, N, C).astype(np.float32)
    out = nd.RNN(nd.array(x), nd.array(params), nd.zeros((1, N, H)),
                 nd.zeros((1, N, H)), state_size=H, num_layers=1, mode="lstm")
    wx = params[:4 * H * C].reshape(4 * H, C)
    wh = params[4 * H * C:4 * H * C + 4 * H * H].reshape(4 * H, H)
    bx = params[4 * H * (C + H):4 * H * (C + H) + 4 * H]
    bh = params[4 * H * (C + H) + 4 * H:]
    sigmoid = lambda v: 1 / (1 + np.exp(-v))
    h = np.zeros((N, H), np.float32)
    c = np.zeros((N, H), np.float32)
    outs = []
    for t in range(T):
        g = x[t] @ wx.T + bx + h @ wh.T + bh
        i, f, gg, o = np.split(g, 4, axis=-1)
        c = sigmoid(f) * c + sigmoid(i) * np.tanh(gg)
        h = sigmoid(o) * np.tanh(c)
        outs.append(h.copy())
    assert_almost_equal(out.asnumpy(), np.stack(outs), rtol=1e-4, atol=1e-5)


def test_check_consistency_dtypes():
    """The backend-equivalence harness: same op, float32 vs float64."""
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc")
    ctx_list = [
        {"ctx": mx.cpu(), "data": (4, 10), "type_dict": {"data": np.float64}},
        {"ctx": mx.cpu(), "data": (4, 10), "type_dict": {"data": np.float32}},
    ]
    check_consistency(net, ctx_list)


def test_linalg_ops():
    a = np.random.rand(3, 3).astype(np.float32)
    spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    l = nd.linalg_potrf(nd.array(spd))
    assert_almost_equal(l.asnumpy() @ l.asnumpy().T, spd, rtol=1e-3)
    sld = nd.linalg_sumlogdiag(nd.array(spd))
    assert_almost_equal(sld.asnumpy(),
                        np.log(np.diag(spd)).sum().reshape(sld.shape),
                        rtol=1e-4)
    x = np.random.rand(2, 3).astype(np.float32)
    y = np.random.rand(3, 4).astype(np.float32)
    c = np.random.rand(2, 4).astype(np.float32)
    out = nd.linalg_gemm(nd.array(x), nd.array(y), nd.array(c),
                         alpha=2.0, beta=0.5)
    assert_almost_equal(out.asnumpy(), 2 * x @ y + 0.5 * c, rtol=1e-4)


def test_gather_scatter():
    data = np.random.rand(4, 5).astype(np.float32)
    indices = np.array([[1, 3], [2, 4]], np.int32)
    out = nd.gather_nd(nd.array(data), nd.array(indices, dtype="int32"))
    assert_almost_equal(out.asnumpy(), data[[1, 3], [2, 4]])
    sc = nd.scatter_nd(out, nd.array(indices, dtype="int32"), shape=(4, 5))
    ref = np.zeros((4, 5), np.float32)
    ref[[1, 3], [2, 4]] = data[[1, 3], [2, 4]]
    assert_almost_equal(sc.asnumpy(), ref)


def test_pick_batch_take():
    x = np.random.rand(4, 6).astype(np.float32)
    idx = np.array([0, 2, 5, 1], np.float32)
    out = nd.pick(nd.array(x), nd.array(idx))
    assert_almost_equal(out.asnumpy(), x[np.arange(4), idx.astype(int)])
    bt = nd.batch_take(nd.array(x), nd.array(idx, dtype="int32"))
    assert_almost_equal(bt.asnumpy(), x[np.arange(4), idx.astype(int)])


def test_cast_block_grad():
    a = nd.array([1.5, 2.5])
    assert nd.Cast(a, dtype="int32").dtype == np.int32
    v = nd.array([1.0, 2.0])
    v.attach_grad()
    with mx.autograd.record():
        out = (nd.BlockGrad(v) * v).sum()
    out.backward()
    assert_almost_equal(v.grad.asnumpy(), v.asnumpy())  # only one path flows


def test_multi_proposal_matches_per_image_proposal():
    """reference contrib/multi_proposal-inl.h:121 — batched output is the
    per-image Proposal results stacked with the image index in col 0."""
    rs = np.random.RandomState(7)
    B, A, H, W = 3, 2, 5, 5
    cls_prob = rs.rand(B, 2 * A, H, W).astype(np.float32)
    bbox_pred = (rs.randn(B, 4 * A, H, W) * 0.1).astype(np.float32)
    im_info = np.tile(np.array([[40., 40., 1.]], np.float32), (B, 1))
    kw = dict(feature_stride=8, scales=(4,), ratios=(0.5, 1.0),
              rpn_pre_nms_top_n=12, rpn_post_nms_top_n=5, rpn_min_size=0)
    multi = nd.contrib.MultiProposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        **kw).asnumpy()
    assert multi.shape == (B * 5, 5)
    for b in range(B):
        single = nd.contrib.Proposal(
            nd.array(cls_prob[b:b + 1]), nd.array(bbox_pred[b:b + 1]),
            nd.array(im_info[b:b + 1]), **kw).asnumpy()
        got = multi[b * 5:(b + 1) * 5]
        assert_almost_equal(got[:, 0], np.full(5, b, np.float32))
        assert_almost_equal(got[:, 1:], single[:, 1:], rtol=1e-5, atol=1e-5)


def test_deformable_psroi_pooling():
    """reference contrib/deformable_psroi_pooling.cu ForwardKernel: with
    no_trans and sample_per_part=1 each output cell is the bilinear
    sample at the bin's top-left sampling point of the matching
    position-sensitive channel."""
    od, gs, k = 2, 2, 2
    H = W = 4
    rs = np.random.RandomState(3)
    data = rs.rand(1, od * gs * gs, H, W).astype(np.float32)
    rois = np.array([[0., 0., 0., 3., 3.]], np.float32)
    out, cnt = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), nd.zeros((1, 2, k, k)),
        spatial_scale=1.0, output_dim=od, group_size=gs, pooled_size=k,
        sample_per_part=1, no_trans=True)
    out, cnt = out.asnumpy(), cnt.asnumpy()
    assert out.shape == (1, od, k, k) and cnt.shape == (1, od, k, k)
    # mirror of the kernel math for this config
    x0 = y0 = 0.0 * 1.0 - 0.5
    rw = rh = max((3. + 1) * 1.0 - 0.5 - x0, 0.1)
    bin_sz = rw / k
    for ctop in range(od):
        for py in range(k):
            for px in range(k):
                w = np.clip(px * bin_sz + x0, 0, W - 1)
                h = np.clip(py * bin_sz + y0, 0, H - 1)
                c = (ctop * gs + py) * gs + px   # gh=py, gw=px when gs==k
                wl, hl = int(np.floor(w)), int(np.floor(h))
                wr, hr = min(wl + 1, W - 1), min(hl + 1, H - 1)
                fw, fh = w - wl, h - hl
                ch = data[0, c]
                want = ((1 - fh) * (1 - fw) * ch[hl, wl] +
                        (1 - fh) * fw * ch[hl, wr] +
                        fh * (1 - fw) * ch[hr, wl] +
                        fh * fw * ch[hr, wr])
                assert abs(out[0, ctop, py, px] - want) < 1e-5
                assert cnt[0, ctop, py, px] == 1.0


def test_deformable_psroi_trans_shifts_window():
    """A positive x-offset in trans moves the sampling window right by
    trans_std * offset * roi_width pixels."""
    od, gs, k = 1, 1, 1
    H = W = 6
    data = np.arange(H * W, dtype=np.float32).reshape(1, 1, H, W)
    rois = np.array([[0., 1., 1., 4., 4.]], np.float32)
    base = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), nd.zeros((1, 2, 1, 1)),
        spatial_scale=1.0, output_dim=od, group_size=gs, pooled_size=k,
        sample_per_part=2, trans_std=0.1, no_trans=False)[0].asnumpy()
    trans = np.zeros((1, 2, 1, 1), np.float32)
    trans[0, 0, 0, 0] = 1.0   # x offset
    shifted = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), nd.array(trans),
        spatial_scale=1.0, output_dim=od, group_size=gs, pooled_size=k,
        sample_per_part=2, trans_std=0.1, no_trans=False)[0].asnumpy()
    # moving right on a row-major ramp increases the pooled value by the
    # x-shift: 0.1 * 1.0 * roi_width(=4) = 0.4
    assert shifted[0, 0, 0, 0] > base[0, 0, 0, 0]
    assert abs((shifted - base)[0, 0, 0, 0] - 0.4) < 1e-4


def test_ctc_loss_matches_brute_force():
    """CTCLoss against exhaustive path enumeration (the defining
    semantics): sum over all T-length paths that collapse to the label,
    blank_label='first' (channel 0 blank, labels 1-based, 0 padding)."""
    import itertools
    rs = np.random.RandomState(3)
    T, N, C = 5, 3, 4          # 3 real classes (1..3) + blank 0
    acts = rs.normal(0, 1.5, (T, N, C)).astype(np.float32)
    labels = np.array([[1, 2, 0], [3, 3, 0], [2, 0, 0]], np.float32)

    out = mx.nd.CTCLoss(mx.nd.array(acts), mx.nd.array(labels)).asnumpy()

    probs = np.exp(acts) / np.exp(acts).sum(-1, keepdims=True)
    for n in range(N):
        want_seq = [int(v) for v in labels[n] if v > 0]
        total = 0.0
        for path in itertools.product(range(C), repeat=T):
            collapsed = [k for k, g in itertools.groupby(path) if k != 0]
            if collapsed == want_seq:
                p = 1.0
                for t, ch in enumerate(path):
                    p *= probs[t, n, ch]
                total += p
        np.testing.assert_allclose(out[n], -np.log(total), rtol=1e-4)


def test_ctc_loss_empty_label_row():
    """An all-padding label row means 'emit only blanks': the loss must
    equal -log P(all-blank path), not a wrapped-index overcount."""
    rs = np.random.RandomState(5)
    T, N, C = 6, 2, 3
    acts = rs.normal(0, 1.0, (T, N, C)).astype(np.float32)
    labels = np.array([[1, 2], [0, 0]], np.float32)   # row 1 is empty
    out = mx.nd.CTCLoss(mx.nd.array(acts), mx.nd.array(labels)).asnumpy()
    probs = np.exp(acts) / np.exp(acts).sum(-1, keepdims=True)
    want = -np.log(np.prod(probs[:, 1, 0]))           # all-blank path
    np.testing.assert_allclose(out[1], want, rtol=1e-5)


def test_grouped_deconvolution_matches_per_group():
    """Grouped transposed conv (reference deconvolution-inl.h group
    semantics: block-diagonal (C_in, C_out/g) weights) must equal
    running each group densely and concatenating."""
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(2, 4, 5, 5).astype(np.float32))
    w = mx.nd.array(rs.rand(4, 2, 3, 3).astype(np.float32))
    got = mx.nd.Deconvolution(x, w, kernel=(3, 3), stride=(2, 2),
                              pad=(1, 1), num_filter=4, num_group=2,
                              no_bias=True).asnumpy()
    parts = []
    for i in range(2):
        xi = mx.nd.array(x.asnumpy()[:, i * 2:(i + 1) * 2])
        wi = mx.nd.array(w.asnumpy()[i * 2:(i + 1) * 2])
        parts.append(mx.nd.Deconvolution(
            xi, wi, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
            num_filter=2, num_group=1, no_bias=True).asnumpy())
    np.testing.assert_allclose(got, np.concatenate(parts, 1), atol=1e-5)
