"""ZeRO-style sharded optimizer state (reference analog: BIGARRAY sharding
across servers kvstore_dist.h:156 + server-side optimizer
kvstore_dist_server.h:187; SURVEY §5.8 maps both to reduce-scatter +
sharded update + all-gather under GSPMD).

shard_optimizer_state=True must (a) place momentum dp-sharded so per-chip
optimizer memory drops by the dp degree, and (b) produce bit-comparable
training numerics to the replicated path.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.parallel.trainer import ShardedTrainer


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=8)
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _run(zero, steps=4, seed=5):
    spec = MeshSpec(make_mesh((8,), ("dp",)))
    trainer = ShardedTrainer(_mlp(), spec, lr=0.1, momentum=0.9, wd=1e-4,
                             shard_optimizer_state=zero)
    shapes = {"data": (16, 12), "softmax_label": (16,)}
    params, mom, aux = trainer.init_state(shapes, seed=seed)
    rs = np.random.RandomState(2)
    for _ in range(steps):
        data = rs.rand(16, 12).astype(np.float32)
        label = rs.randint(0, 8, 16).astype(np.float32)
        params, mom, aux, loss = trainer.step(
            params, mom, aux, {"data": data, "softmax_label": label})
    return trainer, params, mom, float(loss)


def test_zero_matches_replicated():
    tr_z, p_z, m_z, loss_z = _run(zero=True)
    tr_r, p_r, m_r, loss_r = _run(zero=False)
    assert abs(loss_z - loss_r) < 1e-4
    for n, a, b in zip(tr_z.param_names, p_z, p_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=n)
    for n, a, b in zip(tr_z.param_names, m_z, m_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_zero_memory_drops_8x():
    """Per-device optimizer-state bytes must drop by the dp degree for
    every dp-divisible tensor."""
    tr, params, mom, _ = _run(zero=True, steps=1)
    by_name = dict(zip(tr.param_names, mom))
    m = by_name["fc1_weight"]             # (32, 12) momentum
    assert m.addressable_shards[0].data.shape == (4, 12)   # 32/8 rows
    m2 = by_name["fc1_bias"]              # (32,) momentum
    assert m2.addressable_shards[0].data.shape == (4,)
    # params stay replicated (ZeRO-1)
    p = dict(zip(tr.param_names, params))["fc1_weight"]
    assert p.addressable_shards[0].data.shape == (32, 12)

    # replicated control: full momentum everywhere
    tr_r, _, mom_r, _ = _run(zero=False, steps=1)
    mr = dict(zip(tr_r.param_names, mom_r))["fc1_weight"]
    assert mr.addressable_shards[0].data.shape == (32, 12)


def test_zero_composes_with_tp():
    """dp x tp mesh with ZeRO: momentum carries BOTH the tp sharding of
    its parameter and an extra dp-sharded dim."""
    spec = MeshSpec(make_mesh((2, 2), ("dp", "tp")))
    trainer = ShardedTrainer(_mlp(), spec, shard_optimizer_state=True)
    params, mom, aux = trainer.init_state(
        {"data": (8, 12), "softmax_label": (8,)})
    m = dict(zip(trainer.param_names, mom))["fc1_weight"]   # (32, 12)
    # tp shards dim0 (32→16), dp shards dim1 (12→6)
    assert m.addressable_shards[0].data.shape == (16, 6)
    p = dict(zip(trainer.param_names, params))["fc1_weight"]
    assert p.addressable_shards[0].data.shape == (16, 12)
