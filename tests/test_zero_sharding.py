"""ZeRO-style sharded weight update (reference analog: BIGARRAY sharding
across servers kvstore_dist.h:156 + server-side optimizer
kvstore_dist_server.h:187; SURVEY §5.8 and "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training" map both to
reduce-scatter + shard-local update + weight all-gather under GSPMD).

shard_optimizer_state=True (which now implies the sharded UPDATE unless
MXNET_TPU_ZERO=0) must (a) place momentum dp-sharded so per-chip
optimizer memory drops by the dp degree, (b) run the update math on the
shards — the replica grad all-reduce becomes reduce-scatter + weight
all-gather in the compiled HLO, and (c) produce bit-comparable training
numerics to the replicated path, grad accumulation included.
"""
import jax
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.parallel import audit
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.parallel.trainer import ShardedTrainer


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=8)
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _run(zero, steps=4, seed=5, grad_accum=1, **kw):
    spec = MeshSpec(make_mesh((8,), ("dp",)))
    trainer = ShardedTrainer(_mlp(), spec, lr=0.1, momentum=0.9, wd=1e-4,
                             shard_optimizer_state=zero,
                             grad_accum=grad_accum, **kw)
    shapes = {"data": (16, 12), "softmax_label": (16,)}
    params, mom, aux = trainer.init_state(shapes, seed=seed)
    rs = np.random.RandomState(2)
    for _ in range(steps):
        data = rs.rand(16, 12).astype(np.float32)
        label = rs.randint(0, 8, 16).astype(np.float32)
        params, mom, aux, loss = trainer.step(
            params, mom, aux, {"data": data, "softmax_label": label})
    return trainer, params, mom, float(loss)


def test_zero_matches_replicated():
    tr_z, p_z, m_z, loss_z = _run(zero=True)
    tr_r, p_r, m_r, loss_r = _run(zero=False)
    assert abs(loss_z - loss_r) < 1e-4
    for n, a, b in zip(tr_z.param_names, p_z, p_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=n)
    for n, a, b in zip(tr_z.param_names, m_z, m_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_zero_memory_drops_8x():
    """Per-device optimizer-state bytes must drop by the dp degree for
    every dp-divisible tensor."""
    tr, params, mom, _ = _run(zero=True, steps=1)
    by_name = dict(zip(tr.param_names, mom))
    m = by_name["fc1_weight"]             # (32, 12) momentum
    assert m.addressable_shards[0].data.shape == (4, 12)   # 32/8 rows
    m2 = by_name["fc1_bias"]              # (32,) momentum
    assert m2.addressable_shards[0].data.shape == (4,)
    # params stay replicated (ZeRO-1)
    p = dict(zip(tr.param_names, params))["fc1_weight"]
    assert p.addressable_shards[0].data.shape == (32, 12)

    # replicated control: full momentum everywhere
    tr_r, _, mom_r, _ = _run(zero=False, steps=1)
    mr = dict(zip(tr_r.param_names, mom_r))["fc1_weight"]
    assert mr.addressable_shards[0].data.shape == (32, 12)


def test_zero_composes_with_tp():
    """dp x tp mesh with ZeRO: momentum carries BOTH the tp sharding of
    its parameter and an extra dp-sharded dim."""
    spec = MeshSpec(make_mesh((2, 2), ("dp", "tp")))
    trainer = ShardedTrainer(_mlp(), spec, shard_optimizer_state=True)
    params, mom, aux = trainer.init_state(
        {"data": (8, 12), "softmax_label": (8,)})
    m = dict(zip(trainer.param_names, mom))["fc1_weight"]   # (32, 12)
    # tp shards dim0 (32→16), dp shards dim1 (12→6)
    assert m.addressable_shards[0].data.shape == (16, 6)
    p = dict(zip(trainer.param_names, params))["fc1_weight"]
    assert p.addressable_shards[0].data.shape == (16, 12)


def test_zero_grad_accum_parity():
    """ZeRO under gradient accumulation: the per-micro reduce-scatter +
    sharded f32 accumulator still match the replicated path bit-for-bit
    (up to fp roundoff) — the elastic-resize combination."""
    _, p_z, m_z, _ = _run(zero=True, grad_accum=2)
    tr, p_r, m_r, _ = _run(zero=False, grad_accum=2)
    for n, a, b in zip(tr.param_names, p_z, p_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=n)


def test_zero_hlo_reduce_scatter_replaces_grad_allreduce():
    """The wire contract: with the sharded update ON, the compiled step
    carries reduce-scatter (the fused all-reduce+partition-slice form
    XLA:CPU spells out) + weight all-gather, and the surviving plain
    all-reduce payload is noise (the non-finite verdict), NOT the grad
    payload.  The audited bytes reconcile with the analytic ZeRO model."""
    tr, params, mom, _ = _run(zero=True, steps=1)
    feed = {"data": jax.device_put(np.zeros((16, 12), np.float32),
                                   tr.spec.batch_sharding()),
            "softmax_label": jax.device_put(np.zeros((16,), np.float32),
                                            tr.spec.batch_sharding())}
    jitted = tr._build_step(donate=False)
    txt = jitted.lower(params, mom, (), feed, tr._keys(),
                       tr._guard_arrays()).compile().as_text()
    acct = audit.collective_accounting(txt, mesh=tr.spec.mesh)
    shardable, residual = tr._zero_split_bytes()
    model = audit.zero_update_model_bytes(shardable, residual, 8)
    assert acct["reduce-scatter"]["count"] >= 4          # one per param
    assert acct["reduce-scatter"]["fused_from_all_reduce"] >= 4
    # payloads match the model exactly on this bn-free MLP
    assert acct["reduce-scatter"]["bytes"] == model["reduce-scatter"]
    assert acct["all-gather"]["bytes"] == model["all-gather"]
    # the only plain all-reduces left are scalar-ish (verdict, loss)
    assert acct.get("all-reduce", {}).get("bytes", 0) < 0.01 * shardable
    # per-axis attribution: every byte is dp traffic on a pure-dp mesh
    assert set(acct["reduce-scatter"]["by_axis"]) == {"dp"}

    # the replicated control still all-reduces the full grad payload
    tr_r, p_r, m_r, _ = _run(zero=False, steps=1)
    txt_r = tr_r._build_step(donate=False).lower(
        p_r, m_r, (), feed, tr_r._keys(),
        tr_r._guard_arrays()).compile().as_text()
    acct_r = audit.collective_accounting(txt_r)
    assert "reduce-scatter" not in acct_r
    full = audit.grad_payload_bytes(p_r)
    assert abs(acct_r["all-reduce"]["bytes"] - full) / full < 0.10


def test_mom_sharding_picks_largest_divisible_dim():
    """Conv-shaped optimizer state (out, in, kh, kw): the dp shard must
    ride the LARGEST free divisible dim — the old first-fit could pick a
    tiny out-channel (or kernel) dim and strand per-shard memory in tile
    padding."""
    spec = MeshSpec(make_mesh((4, 2), ("dp", "tp")))
    trainer = ShardedTrainer(_mlp(), spec, shard_optimizer_state=True)
    # free dims after tp takes dim0: (64, 4, 4) — first-fit would grab
    # nothing before 64 here, so ALSO check the pure first-fit trap:
    # dim0 (8) divides dp=4 but dim1 (64) is the right choice
    def spec_of(s):
        dims = tuple(s.spec) + (None,) * (4 - len(s.spec))
        return dims

    s = trainer.mom_sharding("conv_weight", (8, 64, 4, 4))
    assert spec_of(s) == ("tp", "dp", None, None), spec_of(s)
    spec_dp = MeshSpec(make_mesh((4,), ("dp",)))
    tr_dp = ShardedTrainer(_mlp(), spec_dp, shard_optimizer_state=True)
    s = tr_dp.mom_sharding("conv_weight", (8, 64, 4, 4))
    assert spec_of(s) == (None, "dp", None, None), spec_of(s)
    # ties break to the earliest dim; no divisible dim -> unsharded
    s = tr_dp.mom_sharding("conv_weight", (8, 8, 3, 3))
    assert spec_of(s) == ("dp", None, None, None), spec_of(s)
    s = tr_dp.mom_sharding("odd", (7, 5, 3, 3))
    assert spec_of(s) == (None, None, None, None), spec_of(s)


def test_zero_env_knob(monkeypatch):
    """MXNET_TPU_ZERO=0 reverts shard_optimizer_state to storage-only
    sharding; =1 arms the full update without any ctor flag; the ctor
    arg wins over the env."""
    spec = MeshSpec(make_mesh((8,), ("dp",)))
    monkeypatch.setenv("MXNET_TPU_ZERO", "0")
    tr = ShardedTrainer(_mlp(), spec, shard_optimizer_state=True)
    assert tr.shard_optimizer_state and not tr.shard_weight_update
    monkeypatch.setenv("MXNET_TPU_ZERO", "1")
    tr = ShardedTrainer(_mlp(), spec)
    assert tr.shard_optimizer_state and tr.shard_weight_update
    tr = ShardedTrainer(_mlp(), spec, zero=False)
    assert not tr.shard_weight_update
    monkeypatch.delenv("MXNET_TPU_ZERO")
    tr = ShardedTrainer(_mlp(), spec, shard_optimizer_state=True)
    assert tr.zero and tr.shard_weight_update    # follows the state flag
    # dp=1: storage/update sharding degrade to no-ops, never an error
    tr1 = ShardedTrainer(_mlp(), MeshSpec(make_mesh((1,), ("dp",))),
                         zero=True)
    assert not tr1.shard_weight_update
