"""Interactive decode engine: paged KV cache, Pallas decode attention,
continuous token-level batching, quantized matmuls, tp serving
(mxnet_tpu/serving/decode.py + ops additions — ISSUE 15)."""
import json
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import pallas_kernels as pk
from mxnet_tpu.serving.decode import (DecodeConfig, DecodeEngine,
                                      DecodeProgram, PagePool,
                                      decode_retrace_report,
                                      decode_tp_model_bytes,
                                      init_decode_params)
from mxnet_tpu.serving.errors import (DeadlineExceeded, Overloaded,
                                      SwapFailed, TopologyMismatch)

VOCAB, T, L, H, HEADS = 29, 16, 2, 24, 2


@pytest.fixture(scope="module")
def toy():
    """One compiled toy program shared across the module — the decode
    step compiles ONCE, and every test riding this fixture doubles as a
    compile-once assertion (trace_count is checked at the end)."""
    cfg = DecodeConfig(VOCAB, L, H, HEADS, T, page_size=4, max_seqs=3)
    params = init_decode_params(cfg, seed=3)
    prog = DecodeProgram(params, cfg, name="toy")
    prog.ensure_compiled()
    return cfg, params, prog


def _contiguous_table(cfg, n=None):
    n = n or cfg.max_seqs
    pp = cfg.pages_per_seq
    table = np.zeros((cfg.max_seqs, pp), np.int32)
    for s in range(n):
        table[s] = 1 + s * pp + np.arange(pp)
    return table


def _first_logits(prog, toks=None):
    cfg = prog.config
    S = cfg.max_seqs
    kv = prog.fresh_cache()
    toks = (np.arange(S, dtype=np.int32) % cfg.vocab_size
            if toks is None else toks)
    pos = np.zeros(S, np.int32)
    table = _contiguous_table(cfg)
    _nxt, logits, _kv = prog.step(kv, toks, pos, pos + 1,
                                  table[:, 0].copy(),
                                  np.zeros(S, np.int32), table)
    return np.asarray(logits)


def test_page_pool_alloc_free_exhaustion():
    pool = PagePool(6)                  # page 0 = trash, 5 usable
    assert pool.available == 5
    a = pool.alloc(3)
    assert a is not None and 0 not in a
    assert pool.alloc(3) is None        # partial grants never happen
    assert pool.available == 2
    b = pool.alloc(2)
    pool.free(a)
    assert pool.available == 3
    pool.free(b)
    assert pool.available == 5


def test_quantize_weight_and_quant_matmul():
    rs = np.random.RandomState(0)
    w = rs.randn(24, 32).astype(np.float32)
    x = rs.randn(5, 32).astype(np.float32)
    ref = x @ w.T
    for bits, tol in ((8, 0.02), (4, 0.25)):
        qw, sc = pk.quantize_weight(w, bits)
        if bits == 4:
            assert qw.shape == (24, 16) and qw.dtype == np.uint8
        else:
            assert qw.dtype == np.int8
        ya = np.asarray(pk.quant_matmul(x, qw, sc, bits,
                                        use_pallas=False))
        yb = np.asarray(pk.quant_matmul(x, qw, sc, bits, use_pallas=True,
                                        block_n=8, block_k=16))
        # dequant-fused pallas kernel == XLA formulation to roundoff
        assert np.abs(ya - yb).max() < 1e-4
        # quantization error bounded relative to the result scale
        rel = np.abs(ya - ref).max() / np.abs(ref).max()
        assert rel < tol, (bits, rel)


def test_decode_attention_paged_matches_reference():
    rs = np.random.RandomState(0)
    S, nH, D, page, MP, P = 3, 2, 8, 4, 3, 10
    q = rs.randn(S, nH, D).astype(np.float32)
    kp = rs.randn(P, nH, page, D).astype(np.float32)
    vp = rs.randn(P, nH, page, D).astype(np.float32)
    pt = rs.randint(0, P, (S, MP)).astype(np.int32)
    lens = np.array([5, 12, 0], np.int32)   # partial page, full, inactive

    ref = np.zeros((S, nH, D), np.float32)
    for s in range(S):
        tl = int(lens[s])
        if tl == 0:
            continue
        ks = np.concatenate([kp[pt[s, j]] for j in range(MP)],
                            axis=1)[:, :tl]
        vs = np.concatenate([vp[pt[s, j]] for j in range(MP)],
                            axis=1)[:, :tl]
        sc = np.einsum("hd,htd->ht", q[s], ks) / np.sqrt(D)
        p = np.exp(sc - sc.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref[s] = np.einsum("ht,htd->hd", p, vs)

    for use_pallas in (False, True):
        out = np.asarray(pk.decode_attention(q, kp, vp, pt, lens,
                                             use_pallas=use_pallas))
        assert np.abs(out[:2] - ref[:2]).max() < 1e-5, use_pallas
        assert np.isfinite(out).all()    # inactive slot: garbage but finite


def test_decode_step_matches_training_forward(toy):
    """The weight-sharing golden test: teacher-forced decode through the
    paged cache reproduces the training graph's full-sequence logits at
    every position (same params, training names, via the
    models/transformer.get_decode_step entry point)."""
    from mxnet_tpu.models.transformer import get_decode_step, get_symbol
    cfg, params, _prog = toy
    net = get_symbol(vocab_size=VOCAB, seq_len=T, num_layers=L,
                     hidden=H, heads=HEADS)
    logits_sym = net.get_internals()["head_output"]
    N = cfg.max_seqs
    ex = logits_sym.simple_bind(mx.cpu(), data=(N, T),
                                head_weight=(VOCAB, H),
                                head_bias=(VOCAB,))
    for name, arr in ex.arg_dict.items():
        if name != "data":
            arr[:] = params[name]
    rs = np.random.RandomState(1)
    toks = rs.randint(0, VOCAB, (N, T)).astype(np.float32)
    ex.arg_dict["data"][:] = toks
    ref = ex.forward(is_train=False)[0].asnumpy()      # (N, T, V)

    prog = get_decode_step(params, vocab_size=VOCAB, seq_len=T,
                           num_layers=L, hidden=H, heads=HEADS,
                           page_size=cfg.page_size, max_seqs=N)
    kv = prog.fresh_cache()
    table = _contiguous_table(cfg)
    for t in range(T):
        pos = np.full(N, t, np.int32)
        _nxt, logits, kv = prog.step(
            kv, toks[:, t].astype(np.int32), pos, pos + 1,
            table[np.arange(N), t // cfg.page_size],
            np.full(N, t % cfg.page_size, np.int32), table)
        err = np.abs(np.asarray(logits) - ref[:, t]).max()
        assert err < 1e-4, (t, err)
    assert prog.trace_count == 1


def test_engine_continuous_batching_parity_and_compile_once(toy):
    """Mixed-length requests joining/leaving the batch mid-generation
    produce EXACTLY the tokens serial generation produces, with more
    requests than slots, and the step program never retraces."""
    from mxnet_tpu.telemetry import tracing
    cfg, _params, prog = toy
    traces_before = prog.trace_count
    seconds_before = tracing.compile_summary()["by_name"] \
        .get("decode_step", 0.0)
    assert seconds_before > 0          # the fixture's ONE visible compile
    with DecodeEngine(prog, default_deadline=60.0) as eng:
        rs = np.random.RandomState(0)
        prompts = [rs.randint(0, VOCAB, n) for n in (3, 7, 2, 5, 4)]
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        outs = [r.result(timeout=60)[0] for r in reqs]
        st = eng.stats()
    assert st["decode"]["tokens_decoded"] == 5 * 6
    assert st["decode"]["occupancy_mean"] > 0.5
    assert st["decode"]["pages_free"] == st["decode"]["pages_total"]
    # serial reference on the SAME program (no recompile)
    with DecodeEngine(prog) as eng2:
        for p, o in zip(prompts, outs):
            assert eng2.generate(p, max_new_tokens=6).tolist() \
                == o.tolist()
    assert prog.trace_count == traces_before  # zero retraces, any lengths
    # and from the compile/* span family: zero decode_step compile
    # seconds accrued while serving (the warmup compile is the only one)
    assert tracing.compile_summary()["by_name"] \
        .get("decode_step", 0.0) == seconds_before


def test_engine_deadline_and_eviction_no_late_ok(toy):
    cfg, _params, prog = toy
    with DecodeEngine(prog, default_deadline=60.0) as eng:
        # deadline expires MID-generation -> typed DeadlineExceeded,
        # pages freed, never a late OK
        doomed = eng.submit(np.array([1, 2], np.int32),
                            max_new_tokens=13, deadline=0.001)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=30)
        # slot + page pressure with priority: three low-prio sequences
        # saturate every slot and the whole pool; a high-prio arrival
        # evicts the cheapest running sequence
        long_reqs = [eng.submit(np.array([1, 2], np.int32),
                                max_new_tokens=12, priority=0)
                     for _ in range(3)]
        import time as _time
        deadline_at = _time.monotonic() + 10.0
        while (eng.stats()["decode"]["active_slots"] < 3
               and _time.monotonic() < deadline_at):
            _time.sleep(0.001)
        assert eng.stats()["decode"]["active_slots"] == 3
        vip = eng.submit(np.array([3] * 2, np.int32), max_new_tokens=13,
                         priority=5, deadline=30.0)
        assert vip.result(timeout=30)[0].size == 13
        evicted = 0
        for r in long_reqs:
            try:
                r.result(timeout=30)
            except (Overloaded, DeadlineExceeded):
                evicted += 1
        st = eng.stats()
    assert evicted >= 1        # page pressure evicted a cheaper sequence
    assert st["decode"]["pages_free"] == st["decode"]["pages_total"]
    # every settled OK was on time (the late-OK invariant)
    assert doomed.done and doomed.latency is not None


def test_quantized_engine_logit_kl_probe(toy):
    """int8/int4 weight-only quantization stays within the quality
    probe: bounded max-KL between f32 and quantized next-token
    distributions on the toy transformer."""
    cfg, params, prog = toy
    lf = _first_logits(prog)
    pf = np.exp(lf - lf.max(-1, keepdims=True))
    pf /= pf.sum(-1, keepdims=True)
    for q, bound in (("int8", 1e-3), ("int4", 0.1)):
        pq = DecodeProgram(params, cfg, quantize=q, name="toy-" + q)
        lq = _first_logits(pq)
        pqs = np.exp(lq - lq.max(-1, keepdims=True))
        pqs /= pqs.sum(-1, keepdims=True)
        kl = float((pf * (np.log(pf + 1e-12)
                          - np.log(pqs + 1e-12))).sum(-1).max())
        assert kl < bound, (q, kl)


def test_export_load_roundtrip_and_topology(toy, tmp_path):
    cfg, params, _prog = toy
    pq = DecodeProgram(params, cfg, quantize="int8", name="exp")
    path = str(tmp_path / "decode.mxt")
    pq.export(path)
    loaded = DecodeProgram.load(path)
    assert loaded.config.quantize == "int8"
    assert np.array_equal(_first_logits(loaded), _first_logits(pq))
    # a mesh this host cannot satisfy is refused typed, pre-deserialize
    with pytest.raises(TopologyMismatch):
        DecodeProgram.load(path, mesh={"tp": 4096})
    # refuse a non-decode container
    from mxnet_tpu.resilience.container import write_container
    bad = str(tmp_path / "bad.mxt")
    write_container(bad, arrays={}, meta={"magic": "nope"}, blobs={})
    with pytest.raises(mx.base.MXNetError):
        DecodeProgram.load(bad)


def test_gc307_clean_and_seeded(toy):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.analysis.graphcheck import check_decode_retrace
    cfg, _params, prog = toy
    # the paged step is clean: identical trace across positions AND
    # batch membership
    rep = decode_retrace_report(prog)
    assert not rep.findings, rep.pretty()

    # seeded: cache grown by concatenation -> shapes retrace per token
    D = 16
    W = np.random.RandomState(0).randn(D, D).astype(np.float32)

    def naive_grow(cache_k, x):
        k = x @ W
        cache = jnp.concatenate([cache_k, k[None]], axis=0)
        return cache, cache @ k
    a = (jnp.zeros((40, D), np.float32), jnp.zeros((D,), np.float32))
    b = (jnp.zeros((41, D), np.float32), jnp.zeros((D,), np.float32))
    rep = check_decode_retrace(naive_grow, a, b, target="grow")
    assert [f.rule for f in rep.findings] == ["GC307"]

    # seeded: position coerced to a host int -> static cache key
    def naive_pos(cache, k, pos):
        cache = jax.lax.dynamic_update_slice(cache, k[None],
                                             (int(pos), 0))
        return cache, cache @ k
    cache = jnp.zeros((64, D), np.float32)
    k = jnp.zeros((D,), np.float32)
    rep = check_decode_retrace(naive_pos, (cache, k, 3), (cache, k, 4),
                               target="baked")
    assert [f.rule for f in rep.findings] == ["GC307"]

    # a non-decode-shaped program passes silently (the rule can sit on
    # generic entry points)
    def plain(x):
        return (x @ W).sum()
    rep = check_decode_retrace(plain, (jnp.zeros((4, D), np.float32),),
                               (jnp.zeros((4, D), np.float32),))
    assert not rep.findings


def test_tp2_parity_and_collective_audit(toy):
    """Tensor-parallel serving: the tp2-sharded step matches the
    single-device logits, and its lowered HLO moves EXACTLY the
    analytic per-axis collective bytes (2 activation reductions per
    layer + one logits gather — nothing scales with weights or cache)."""
    import jax
    from mxnet_tpu.parallel.audit import collective_accounting
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    cfg, params, prog = toy
    # vocab 29 is not tp-divisible: the head degrades to replicated and
    # the model drops the gather — parity must still hold
    p2 = DecodeProgram(params, cfg, mesh={"tp": 2}, name="tp2")
    l1, l2 = _first_logits(prog), _first_logits(p2)
    assert np.abs(l1 - l2).max() < 1e-4
    acct = collective_accounting(p2.lowered_step_text(),
                                 mesh=p2.spec.mesh)
    model = decode_tp_model_bytes(cfg, 2)
    measured = {k: v["bytes"] for k, v in acct.items()}
    assert measured == model, (measured, model)
    # a tp-divisible vocab shards the head: the ONE logits all-gather
    # joins the model, still at exactly the analytic bytes, and every
    # byte is attributed to the tp axis
    cfg32 = DecodeConfig(32, L, H, HEADS, T, page_size=4, max_seqs=3)
    p32 = DecodeProgram(init_decode_params(cfg32, seed=3), cfg32,
                        mesh={"tp": 2}, name="tp2-v32")
    acct32 = collective_accounting(p32.lowered_step_text(),
                                   mesh=p32.spec.mesh)
    model32 = decode_tp_model_bytes(cfg32, 2)
    assert {k: v["bytes"] for k, v in acct32.items()} == model32
    for kind, info in acct32.items():
        assert set(info["by_axis"]) == {"tp"}, (kind, info)


def test_tp2_engine_kill_swap_drill(toy):
    """The serving drill on a tp2-served decode model: a model swap
    lands mid-generation without a failed or late request, and an
    executor kill burst (chaos exec_error) sheds typed with ZERO late
    OKs; the page pool drains clean."""
    import jax
    from mxnet_tpu.resilience import chaos
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    cfg, params, _prog = toy
    p_a = DecodeProgram(params, cfg, mesh={"tp": 2}, name="drill-a")
    p_b = DecodeProgram(init_decode_params(cfg, seed=9), cfg,
                        mesh={"tp": 2}, name="drill-b")
    deadline = 30.0
    with DecodeEngine(p_a, default_deadline=deadline,
                      breaker_threshold=100) as eng:
        rs = np.random.RandomState(0)
        reqs = [eng.submit(rs.randint(0, VOCAB, 2 + i % 3),
                           max_new_tokens=8) for i in range(6)]
        # rolling swap mid-generation: validated+compiled OFF the flip
        eng.swap(p_b)
        assert eng._program is p_b
        ok = late = 0
        for r in reqs:
            out = r.result(timeout=30)       # must ALL complete OK
            assert out[0].size == 8
            assert r.latency <= deadline
            ok += 1
        # kill burst: every step fails while armed -> typed ExecFailed,
        # never a late OK, pool freed
        with chaos.inject("exec_error", count=50):
            doomed = [eng.submit(rs.randint(0, VOCAB, 3),
                                 max_new_tokens=4, deadline=5.0)
                      for _ in range(3)]
            for r in doomed:
                with pytest.raises(Exception) as ei:
                    r.result(timeout=30)
                assert type(ei.value).__name__ in (
                    "ExecFailed", "DeadlineExceeded", "CircuitOpen")
        chaos.reset()
        st = eng.stats()
        assert st["decode"]["pages_free"] == st["decode"]["pages_total"]
        assert ok == 6 and late == 0
    # geometry mismatch is refused with the old model still serving
    cfg2 = DecodeConfig(VOCAB, L, H, HEADS, T * 2, page_size=4,
                        max_seqs=cfg.max_seqs)
    with DecodeEngine(p_b) as eng2:
        with pytest.raises(SwapFailed):
            eng2.swap(DecodeProgram(init_decode_params(cfg2), cfg2))


def test_kv_cache_memory_tag(toy, monkeypatch):
    from mxnet_tpu.telemetry import memory as tmem
    assert "kv_cache" in tmem.TAGS
    cfg, _params, prog = toy
    monkeypatch.setenv("MXNET_TPU_MEMWATCH", "1")
    tmem.reset()
    try:
        kv = prog.fresh_cache()
        assert tmem.live_bytes_by_tag().get("kv_cache", 0) \
            >= prog.cache_bytes
        del kv
    finally:
        monkeypatch.delenv("MXNET_TPU_MEMWATCH", raising=False)
        tmem.reset()


def test_decode_autotune_record_and_read(tmp_path, monkeypatch):
    from mxnet_tpu.ops import autotune
    monkeypatch.setenv("MXNET_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    autotune.invalidate()
    try:
        # no entry: platform default (xla on cpu)
        assert autotune.decode_backend(2, 2, 8, 4, "float32") == "xla"
        autotune.record("decode_attn", (2, 2, 8, 4, "float32"), "pallas",
                        0.5)
        assert autotune.decode_backend(2, 2, 8, 4, "float32") == "pallas"
        # the kernel wrapper consults the cache under auto
        monkeypatch.setenv("MXNET_TPU_PALLAS_DECODE", "auto")
        rs = np.random.RandomState(0)
        q = rs.randn(2, 2, 8).astype(np.float32)
        kp = rs.randn(5, 2, 4, 8).astype(np.float32)
        pt = np.zeros((2, 1), np.int32)
        lens = np.array([2, 1], np.int32)
        out = pk.decode_attention(q, kp, kp, pt, lens)   # pallas path
        assert np.isfinite(np.asarray(out)).all()
    finally:
        autotune.invalidate()


@pytest.mark.slow
def test_servebench_decode_smoke(capsys):
    # @slow per the PR-16 tier-1 re-profile: the continuous-vs-static
    # occupancy comparison depends on open-loop arrival timing, and on
    # the loaded 1-core rig arrivals bunch up enough for static batching
    # to tie (observed 0.671 vs 0.700 under a full-suite run); the
    # compile-once invariant it also guards stays in tier-1 via
    # test_engine_continuous_batching_parity_and_compile_once
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import servebench
    rc = servebench.main([
        "--decode", "--json", "--requests", "12",
        "--decode-prompts", "2,10", "--decode-new", "2,12",
        "--decode-layers", "1", "--decode-hidden", "32",
        "--decode-heads", "2", "--decode-vocab", "64",
        "--decode-seq", "32", "--decode-page", "8",
        "--decode-slots", "2", "--deadline", "0"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["compiles"] == 1
    cont, stat = report["continuous"], report["static"]
    assert cont["tokens"] == stat["tokens"] > 0
    assert not cont["errors"]
    # continuous batching refills freed slots: strictly better occupancy
    # on a mixed-length stream (throughput follows on real accelerators;
    # on a loaded CI box wall-clock is too noisy to gate hard)
    assert cont["occupancy_mean"] > stat["occupancy_mean"]
    assert report["continuous_vs_static"] > 0.7


@pytest.mark.slow
def test_bench_decode_emits_metric():
    import subprocess
    env = dict(os.environ, BENCH_MODEL="decode", BENCH_ITERS="5",
               BENCH_WARMUP="1", BENCH_DECODE_LAYERS="1",
               BENCH_DECODE_HIDDEN="64", BENCH_DECODE_HEADS="2",
               BENCH_DECODE_VOCAB="128", BENCH_DECODE_SEQ="32",
               BENCH_DECODE_SLOTS="2", BENCH_DECODE_PAGE="8",
               JAX_PLATFORMS="cpu")
    env.pop("BENCH_LEDGER", None)
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "bench.py")],
        env=env, capture_output=True, text=True, timeout=600)
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["metric"] == "decode_tokens_per_sec_per_chip"
    assert doc["value"] > 0
    assert "cpu" in doc["unit"]           # provenance in the unit string
    assert doc["decode"]["compiles"] == 1
