"""Module API tests (reference tests/python/unittest/test_module.py) +
small end-to-end convergence (reference tests/python/train/test_mlp.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp_sym():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _toy_data(n=256, dim=16, nclass=4, seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, nclass, n)
    X = rs.rand(n, dim).astype(np.float32) * 0.1
    for i in range(n):
        X[i, labels[i] * (dim // nclass):(labels[i] + 1) * (dim // nclass)] += 1
    return X, labels.astype(np.float32)


def test_module_bind_init_forward():
    net = _mlp_sym()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Xavier())
    batch = mx.io.DataBatch(data=[nd.ones((8, 16))],
                            label=[nd.zeros((8,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (8, 4)
    assert_almost_equal(out.asnumpy().sum(axis=1), np.ones(8), rtol=1e-4)


def test_module_fit_converges():
    X, y = _toy_data()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier(), num_epoch=5)
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=32), "acc")
    assert score[0][1] > 0.95, score


def test_module_multi_device():
    """Data-parallel over 2 virtual cpu devices (reference
    DataParallelExecutorGroup path)."""
    X, y = _toy_data()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier(), num_epoch=5, kvstore="local")
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=32), "acc")
    assert score[0][1] > 0.95, score


def test_module_checkpoint(tmp_path):
    X, y = _toy_data()
    train = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.init.Xavier(), num_epoch=2)
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
    ref = mod.score(mx.io.NDArrayIter(X, y, batch_size=32), "acc")[0][1]

    mod2 = mx.mod.Module.load(prefix, 2, context=mx.cpu())
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label, for_training=False)
    got = mod2.score(mx.io.NDArrayIter(X, y, batch_size=32), "acc")[0][1]
    assert got == ref


def test_module_predict():
    X, y = _toy_data(64)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (64, 4)


def test_module_input_grads():
    net = _mlp_sym()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))],
             for_training=True, inputs_need_grad=True)
    mod.init_params()
    batch = mx.io.DataBatch(data=[nd.ones((8, 16))], label=[nd.zeros((8,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    ig = mod.get_input_grads()[0]
    assert ig.shape == (8, 16)
    assert np.abs(ig.asnumpy()).sum() > 0


def test_module_reshape():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    batch = mx.io.DataBatch(data=[nd.ones((4, 16))], label=[nd.zeros((4,))])
    mod.forward(batch, is_train=False)  # auto-reshape
    assert mod.get_outputs()[0].shape == (4, 4)


def test_module_update_on_kvstore_paths():
    X, y = _toy_data()
    for kv in ["local", "device", None]:
        train = mx.io.NDArrayIter(X, y, batch_size=32)
        mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
        mod.fit(train, optimizer="sgd",
                optimizer_params={"learning_rate": 0.5},
                initializer=mx.init.Xavier(), num_epoch=3, kvstore=kv)
        score = mod.score(mx.io.NDArrayIter(X, y, batch_size=32), "acc")
        assert score[0][1] > 0.9, (kv, score)


def test_bucketing_module():
    """Variable-length 'sequences' via buckets sharing params."""
    def sym_gen(seq_len):
        # params independent of seq_len (like RNN/embedding models)
        data = sym.Variable("data")
        emb = sym.Embedding(data, input_dim=20, output_dim=8,
                            name="emb_shared")
        pooled = sym.mean(emb, axis=1)
        net = sym.FullyConnected(pooled, num_hidden=2, name="out_shared")
        net = sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    rs = np.random.RandomState(0)

    def make_batch(seq_len, bs=8):
        return mx.io.DataBatch(
            data=[nd.array(rs.randint(0, 20, (bs, seq_len)).astype(np.float32))],
            label=[nd.array(rs.randint(0, 2, bs).astype(np.float32))],
            bucket_key=seq_len,
            provide_data=[mx.io.DataDesc("data", (bs, seq_len))],
            provide_label=[mx.io.DataDesc("softmax_label", (bs,))])

    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
    for seq_len in [10, 5, 7, 10, 5]:
        batch = make_batch(seq_len)
        mod.forward_backward(batch)
        mod.update()
    # parameters are shared across buckets
    p5 = mod._buckets[5]._exec_group.execs[0].arg_dict["emb_shared_weight"]
    p10 = mod._buckets[10]._exec_group.execs[0].arg_dict["emb_shared_weight"]
    assert p5 is p10


def test_feedforward_api():
    X, y = _toy_data(256)
    model = mx.model.FeedForward(_mlp_sym(), ctx=mx.cpu(), num_epoch=8,
                                 learning_rate=0.5, numpy_batch_size=32)
    model.fit(X, y)
    preds = model.predict(X)
    acc = (preds.argmax(1) == y).mean()
    assert acc > 0.9, acc
