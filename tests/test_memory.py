"""Memory observability plane (ISSUE 7): tag bucketing, the sampler's
gauges/timeline/counter-track, the attribution report's memory section
(predicted vs compiled within 20% on the trainer + ring entry points),
the OOM drill (chaos ``oom`` fault -> post-mortem naming the top
consumer and the tripping program, rendered by tools/memwatch.py),
the leak watchdog, digest/fleet memory columns, the checkpoint-restore
double-residency fix, and the disarmed zero-cost gate.
"""
import glob
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import profiler, telemetry
from mxnet_tpu.telemetry import memory
from mxnet_tpu.resilience import chaos, watchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_MEMWATCH", raising=False)
    monkeypatch.delenv("MXNET_TPU_DEVICE_HBM_GB", raising=False)
    telemetry.reset()
    telemetry.disarm()
    chaos.reset()
    watchdog.reset()
    yield
    profiler.set_state("stop")
    telemetry.reset()
    telemetry.disarm()
    chaos.reset()
    watchdog.reset()


def _toy_trainer(n_dev=2, hidden=64):
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")
    spec = MeshSpec(make_mesh((min(n_dev, jax.device_count()),), ("dp",)))
    trainer = ShardedTrainer(net, spec, lr=0.1)
    shapes = {"data": (8, 32), "softmax_label": (8,)}
    return trainer, trainer.init_state(shapes), shapes


# ---------------------------------------------------------------------------
# tagging + live accounting
# ---------------------------------------------------------------------------

def test_tag_bucketing_roundtrip():
    telemetry.arm()
    a = jnp.ones((128, 128))            # 64 KB
    b = jnp.ones((64, 64))              # 16 KB
    memory.tag(a, "params", label="t.a")
    memory.tag({"x": [b]}, "optimizer", label="t.b")   # nested trees walk
    by_tag = memory.live_bytes_by_tag()
    assert by_tag["params"] == a.nbytes
    assert by_tag["optimizer"] == b.nbytes
    assert by_tag["total"] >= a.nbytes + b.nbytes
    rows = {r["label"]: r for r in memory.live_buffers() if r["label"]}
    assert rows["t.a"]["tag"] == "params"
    assert rows["t.a"]["shape"] == [128, 128]
    # tags are weak: a deleted buffer leaves the accounting
    a.delete()
    assert memory.live_bytes_by_tag().get("params", 0) == 0


def test_tagging_unwraps_ndarray_handles():
    telemetry.arm()
    nd = mx.nd.array(np.ones((32, 32), np.float32))
    memory.tag([nd], "batch", label="nd")
    assert memory.tagged_bytes("batch") >= nd._handle.nbytes


def test_disarmed_is_zero_cost_and_tracks_nothing():
    assert not memory.enabled()
    x = jnp.ones((16,))
    memory.tag(x, "params")
    memory.note_step(1)
    telemetry.arm()
    assert all(r["tag"] == "untagged" for r in memory.live_buffers()
               if r["shape"] == [16])
    telemetry.disarm()
    memory.reset()
    # per-call cost of the disarmed gates (tag + note_step + oom_guard):
    # the generous PR-5 bound — a live_arrays walk or a lock would blow it
    tree = {"data": None}
    n = 3000
    t0 = time.perf_counter()
    for i in range(n):
        with memory.oom_guard("t/hot", step=i):
            memory.tag(tree, "batch")
        memory.note_step(i)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 50e-6, "disarmed memory hooks cost %.1fus" % (
        per_call * 1e6)


def test_memwatch_env_gate_overrides_telemetry(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_MEMWATCH", "1")
    memory.reset()
    assert memory.enabled()             # armed without telemetry
    monkeypatch.setenv("MXNET_TPU_MEMWATCH", "0")
    memory.reset()
    telemetry.arm()
    assert not memory.enabled()         # explicit off beats telemetry


def test_sampler_gauges_timeline_and_counter_track(tmp_path):
    telemetry.arm()
    big = jnp.ones((256, 256))          # 256 KB
    memory.tag(big, "params", label="sampled")
    profiler.set_config(filename=str(tmp_path / "trace.json"))
    profiler.set_state("run")
    memory.sample_now()
    profiler.set_state("stop")
    assert telemetry.gauge("mem.live_bytes").value(
        tag="params") >= big.nbytes
    assert telemetry.gauge("mem.live_bytes_total").value() >= big.nbytes
    assert telemetry.gauge("mem.peak_live_bytes").value() >= big.nbytes
    win = memory.memory_window()
    assert win["samples"] and win["peak_live_bytes"] >= big.nbytes
    assert win["samples"][-1]["by_tag"]["params"] >= big.nbytes
    # the live-HBM counter track landed in the merged Perfetto trace
    path = profiler.dump_profile()
    events = json.load(open(path))["traceEvents"]
    counters = [e for e in events if e["ph"] == "C"
                and e["name"] == "memory/live_bytes"]
    assert counters, "no live-HBM counter track in the merged trace"
    assert counters[0]["args"]["params"] >= big.nbytes


def test_release_frees_and_reports_bytes():
    x = jnp.ones((64, 64))
    y = jnp.ones((32,))
    want = x.nbytes + y.nbytes
    freed = memory.release({"a": x, "b": (y,)})
    assert freed == want
    assert x.is_deleted() and y.is_deleted()
    assert memory.release(x) == 0       # idempotent


# ---------------------------------------------------------------------------
# attribution memory section (acceptance: trainer + ring within 20%)
# ---------------------------------------------------------------------------

def _memory_section_of(compiled, name):
    from mxnet_tpu.telemetry import perf
    return perf.attribute_compiled(compiled, name).to_dict()["memory"]


def test_attribution_memory_section_schema():
    x = jnp.ones((128, 128))
    compiled = jax.jit(lambda a: a @ a).lower(x).compile()
    mem = _memory_section_of(compiled, "toy_matmul")
    assert mem["predicted"]["argument_bytes"] == x.nbytes
    assert mem["predicted"]["output_bytes"] == x.nbytes
    comp = mem["compiled"]
    assert set(comp) >= {"argument_bytes", "output_bytes", "temp_bytes",
                         "alias_bytes", "peak_bytes"}
    assert 0.8 <= mem["predicted_vs_compiled"] <= 1.2
    # phases block surfaces the peak for bench artifacts
    from mxnet_tpu.telemetry import perf
    rep = perf.attribute_compiled(compiled, "toy_matmul")
    block = perf.phases_block(rep)
    assert block["peak_hbm_bytes"] == comp["peak_bytes"]


def test_trainer_step_memory_predicted_vs_compiled_within_20pct():
    trainer, (params, mom, aux), shapes = _toy_trainer()
    from mxnet_tpu.parallel.trainer import sgd_step_fn
    step = sgd_step_fn(trainer)
    inputs = {n: jnp.zeros(s, jnp.float32) for n, s in shapes.items()}
    keys = trainer._keys()
    guard = trainer._guard_arrays()

    def sds(t):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t)

    compiled = step.lower(*sds((params, mom, aux, inputs, keys,
                                guard))).compile()
    mem = _memory_section_of(compiled, "trainer_step")
    assert mem.get("compiled"), "no memory_analysis on this backend?"
    ratio = mem["predicted_vs_compiled"]
    assert ratio is not None and 0.8 <= ratio <= 1.2, ratio


def test_ring_memory_predicted_vs_compiled_within_20pct():
    from mxnet_tpu.parallel.mesh import make_mesh
    from mxnet_tpu.parallel.ring import local_ring_attention_fn
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    n = min(2, jax.device_count())
    mesh = make_mesh((n,), ("sp",))
    fn = local_ring_attention_fn("sp", causal=True, scale=1.0,
                                 num_devices=n)
    compat = {} if hasattr(jax.lax, "pvary") else {"check_rep": False}
    mapped = shard_map(fn, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                       out_specs=P(None, "sp"), **compat)
    blk = jnp.ones((1, 2 * n, 2, 4), jnp.float32)
    compiled = jax.jit(mapped).lower(blk, blk, blk).compile()
    mem = _memory_section_of(compiled, "ring_attention")
    assert mem.get("compiled"), "no memory_analysis on this backend?"
    ratio = mem["predicted_vs_compiled"]
    assert ratio is not None and 0.8 <= ratio <= 1.2, ratio


# ---------------------------------------------------------------------------
# OOM drill: chaos fault -> forensics -> memwatch --report
# ---------------------------------------------------------------------------

def test_oom_drill_postmortem_and_memwatch_report(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_WATCHDOG_DIR", str(tmp_path))
    telemetry.arm()
    trainer, (params, mom, aux), shapes = _toy_trainer(hidden=512)
    batch = {"data": np.random.rand(8, 32).astype(np.float32),
             "softmax_label": np.zeros(8, np.float32)}
    # a warm step so the armed plane has tags + a timeline sample
    params, mom, aux, loss = trainer.step(params, mom, aux, batch)
    with chaos.inject("oom", at_step=2):
        with pytest.raises(Exception) as ei:
            trainer.step(params, mom, aux, batch)
    assert memory.is_oom(ei.value)
    reports = glob.glob(str(tmp_path / "oom-postmortem-*.json"))
    assert len(reports) == 1
    doc = json.load(open(reports[0]))
    assert doc["kind"] == "oom_postmortem"
    assert doc["tag"] == "ShardedTrainer.step"
    assert "ShardedTrainer.step" in doc["program"]
    assert "RESOURCE_EXHAUSTED" in doc["error"]
    # the report names the top live consumers WITH their tags: the
    # trainer's fc1 weight (512x32 f32) must be in the table as params
    tagged = [r for r in doc["top_buffers"]
              if r["tag"] == "params" and r["nbytes"] >= 512 * 32 * 4]
    assert tagged, doc["top_buffers"][:5]
    assert doc["live_bytes_by_tag"]["params"] > 0
    assert doc["timeline"]["samples"], "no memory timeline in report"
    assert doc["hint"]
    assert telemetry.counter_total("mem.oom") == 1
    assert telemetry.counter_total("chaos.faults_injected") >= 1

    # tools/memwatch.py --report renders the forensics (stdlib only)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memwatch.py"),
         "--report", reports[0], "--top", "5"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "OOM POST-MORTEM" in out.stdout
    assert "params" in out.stdout
    assert "hint:" in out.stdout
    assert "RESOURCE_EXHAUSTED" in out.stdout


def test_oom_guard_passes_through_non_oom_errors(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_WATCHDOG_DIR", str(tmp_path))
    with pytest.raises(ValueError):
        with memory.oom_guard("t"):
            raise ValueError("not an oom")
    assert glob.glob(str(tmp_path / "oom-postmortem-*")) == []


# ---------------------------------------------------------------------------
# leak watchdog
# ---------------------------------------------------------------------------

def test_leak_watchdog_flags_synthetic_growing_cache():
    wd = memory.LeakWatchdog(window=12, min_samples=8,
                             threshold_bytes=1e6)
    for step in range(10):
        wd.observe(step, 10e6 + step * 0.5e6)     # +0.5 MB per step
    rep = wd.check()
    assert rep is not None
    assert rep["growth_bytes"] == pytest.approx(4.5e6)
    assert rep["kind"] == "leak_suspected"


def test_leak_watchdog_ignores_plateau_and_noise():
    wd = memory.LeakWatchdog(window=12, min_samples=8,
                             threshold_bytes=1e6)
    for step in range(10):                        # plateau after warmup
        wd.observe(step, 10e6 + min(step, 3) * 1e6)
    assert wd.check() is None
    wd.reset()
    for step in range(10):                        # sawtooth (GC'd cache)
        wd.observe(step, 10e6 + (step % 2) * 5e6)
    assert wd.check() is None


def test_leak_watchdog_end_to_end_via_note_step(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_MEMWATCH_LEAK_MB", "5")
    memory.reset()                                # re-reads the threshold
    telemetry.arm()
    cache = []                                    # the leak
    for step in range(10):
        cache.append(memory.tag(jnp.ones((256, 1024), jnp.float32),
                                "activations", label="leaky"))  # 1 MB each
        memory.note_step(step, min_interval=0.0)
    rep = memory.leak_report()
    assert rep is not None and rep["growth_bytes"] >= 8e6
    assert telemetry.counter_total("mem.leak_suspected") >= 1


# ---------------------------------------------------------------------------
# digests + fleet view memory columns
# ---------------------------------------------------------------------------

def test_digest_and_fleet_view_carry_memory_columns(monkeypatch):
    from tests.test_watchdog import FakeKVClient
    telemetry.arm()
    held = memory.tag(jnp.ones((512, 512)), "params", label="digest")
    assert held is not None               # keep the buffer live
    memory.sample_now()
    d = telemetry.rank_digest(step=7)
    assert d["mem_mb"]["live"] >= 1.0
    assert d["mem_mb"]["peak"] >= d["mem_mb"]["live"] - 0.1

    client = FakeKVClient()
    lane = watchdog.HeartbeatLane(client=client)
    monkeypatch.setattr(watchdog, "_LANE", lane)
    assert lane.beat(7, force=True)
    digests = lane.digests()
    assert digests[0]["mem_mb"]["live"] >= 1.0
    view = telemetry.fleet_view()
    assert view["ranks"]["0"]["digest"]["mem_mb"]["peak"] >= 1.0
    rendered = telemetry.render_fleet(view)
    assert "live_mb" in rendered and "peak_mb" in rendered


# ---------------------------------------------------------------------------
# checkpoint restore: the double-residency fix
# ---------------------------------------------------------------------------

def test_restore_trainer_releases_old_state_before_device_put(tmp_path):
    """The ~2x-peak fix: with ``old_state`` passed, every OLD device
    buffer is freed BEFORE the first device_put of the restored tree —
    peak residency stays ~1x model size (old is gone while new
    materializes) instead of old+new."""
    from mxnet_tpu.resilience.checkpoint import (CheckpointManager,
                                                 restore_trainer,
                                                 save_trainer)
    trainer, (params, mom, aux), shapes = _toy_trainer()
    mgr = CheckpointManager(str(tmp_path), keep=2)
    save_trainer(mgr, trainer, params, mom, aux, step=5)

    old_leaves = [x for x in (*params, *mom, *aux)]
    model_bytes = sum(x.nbytes for x in old_leaves)
    real_device_put = jax.device_put
    old_alive_at_put = []

    def spying_put(value, *a, **kw):
        old_alive_at_put.append(
            sum(x.nbytes for x in old_leaves if not x.is_deleted()))
        return real_device_put(value, *a, **kw)

    jax.device_put = spying_put
    try:
        out = restore_trainer(mgr, trainer,
                              old_state=(params, mom, aux))
    finally:
        jax.device_put = real_device_put
    assert out is not None
    new_params, new_mom, new_aux, step, _meta = out
    assert step == 5
    assert old_alive_at_put, "restore made no device_put calls?"
    # at EVERY materialization point the old residency was zero
    assert max(old_alive_at_put) == 0, (
        "old state still resident during restore: peak would be ~2x "
        "(%d of %d bytes live)" % (max(old_alive_at_put), model_bytes))
    assert all(x.is_deleted() for x in old_leaves)
    # the restored state is whole and usable
    batch = {"data": np.random.rand(8, 32).astype(np.float32),
             "softmax_label": np.zeros(8, np.float32)}
    _p, _m, _a, loss = trainer.step(new_params, new_mom, new_aux, batch)
    assert np.isfinite(float(loss))


def test_restore_trainer_without_old_state_keeps_legacy_behavior(tmp_path):
    from mxnet_tpu.resilience.checkpoint import (CheckpointManager,
                                                 restore_trainer,
                                                 save_trainer)
    trainer, (params, mom, aux), shapes = _toy_trainer()
    mgr = CheckpointManager(str(tmp_path), keep=2)
    save_trainer(mgr, trainer, params, mom, aux, step=3)
    out = restore_trainer(mgr, trainer)
    assert out is not None
    assert not params[0].is_deleted()   # caller's references untouched


# ---------------------------------------------------------------------------
# GC501 + capacity plumbing (memory side; graphcheck side in
# tests/test_analysis.py)
# ---------------------------------------------------------------------------

def test_device_capacity_env_override(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_DEVICE_HBM_GB", "32")
    assert memory.device_capacity_bytes() == 32e9


def test_predicted_peak_bytes_donation_accounting():
    from mxnet_tpu.analysis import costmodel
    assert costmodel.predicted_peak_bytes(100, 10, donated=True) == 110
    assert costmodel.predicted_peak_bytes(100, 10, donated=False) == 210
    assert costmodel.predicted_peak_bytes(100, 10, temp_bytes=5) == 115


# ---------------------------------------------------------------------------
# benchwatch: peak HBM recorded (extra block), never gated
# ---------------------------------------------------------------------------

def _load_benchwatch():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "benchwatch_t7", os.path.join(REPO, "tools", "benchwatch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_benchwatch_records_peak_hbm_as_ungated_extra(tmp_path):
    bw = _load_benchwatch()
    doc = {"metric": "resnet50_train_img_per_sec_per_chip", "value": 2000.0,
           "phases": {"bound": "hbm", "peak_hbm_bytes": 7_000_000_000},
           "transformer": {"metric": "transformer_train_tokens_per_sec"
                                     "_per_chip", "value": 90000.0,
                           "phases": {"peak_hbm_bytes": 5_000_000_000}}}
    assert bw.extract_extra(doc) == {
        "peak_hbm_bytes": 7_000_000_000,
        "transformer_peak_hbm_bytes": 5_000_000_000}
    ledger = str(tmp_path / "ledger.jsonl")
    bw.append_entry(ledger, bw.extract_metrics(doc), source="t",
                    extra=bw.extract_extra(doc))
    # a later round where throughput holds but peak HBM DROPS (an
    # improvement) must not read as a regression: extras are not gated
    doc2 = dict(doc, phases={"peak_hbm_bytes": 3_000_000_000})
    bw.append_entry(ledger, bw.extract_metrics(doc2), source="t",
                    extra=bw.extract_extra(doc2))
    entries = bw.read_ledger(ledger)
    assert entries[0]["extra"]["peak_hbm_bytes"] == 7_000_000_000
    assert entries[1]["extra"]["peak_hbm_bytes"] == 3_000_000_000
    ok, results = bw.check_ledger(entries)
    assert ok, results
    assert not any("hbm" in name for name in results)


# ---------------------------------------------------------------------------
# memwatch live-tail rendering (the gauge console)
# ---------------------------------------------------------------------------

def test_memwatch_tails_mem_gauges_from_jsonl(tmp_path):
    telemetry.arm()
    held = memory.tag(jnp.ones((512, 512)), "served", label="tail")
    assert held is not None               # keep the buffer live
    memory.sample_now()
    feed = str(tmp_path / "metrics.jsonl")
    telemetry.export_jsonl(feed)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memwatch.py"),
         feed], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "live" in out.stdout and "served" in out.stdout
    assert "MB" in out.stdout
