"""Symbol + Executor tests (reference test_symbol.py / test_executor.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.executor import Executor, infer_shapes
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_compose_and_lists():
    net = _mlp()
    assert net.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias", "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]
    assert net.name == "softmax"
    internals = net.get_internals()
    assert "fc1_output" in internals.list_outputs()
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]


def test_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(8, 100))
    assert arg_shapes[1] == (16, 100)
    assert arg_shapes[3] == (10, 16)
    assert out_shapes == [(8, 10)]
    a2, o2, _ = net.infer_shape(data=(32, 50))
    assert a2[1] == (16, 50) and o2 == [(32, 10)]


def test_infer_type_propagation():
    """Real dtype propagation (reference infer_graph_attr_pass.cc): Cast
    switches the downstream dtype; embedding tables stay float under
    integer indices."""
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fc1")
    net = sym.Cast(net, dtype="float16")
    net = sym.FullyConnected(net, num_hidden=2, name="fc2")
    arg_types, out_types, _ = net.infer_type(data="float32")
    by_name = dict(zip(net.list_arguments(), arg_types))
    assert by_name["fc1_weight"] == np.dtype("float32")
    assert by_name["fc2_weight"] == np.dtype("float16")
    assert out_types == [np.dtype("float16")]

    emb = sym.Embedding(sym.Variable("idx"), input_dim=10, output_dim=4)
    a, o, _ = emb.infer_type(idx="int32")
    by_name = dict(zip(emb.list_arguments(), a))
    assert by_name["idx"] == np.dtype("int32")
    assert by_name[emb.list_arguments()[1]] == np.dtype("float32")
    assert o == [np.dtype("float32")]

    # schema-default dtype attrs must NOT override propagation (topk
    # carries dtype='float32' by default but outputs the input dtype)
    t = sym.topk(sym.Variable("d"), k=2, ret_typ="value")
    assert t.infer_type(d="float16")[1] == [np.dtype("float16")]

    # positional None means "infer this arg"
    fc = sym.FullyConnected(sym.Variable("data"), num_hidden=4, name="fc")
    a, o, _ = fc.infer_type("float16", None, None)
    assert all(dt == np.dtype("float16") for dt in a)

    # BN params/aux pinned float32 under low-precision data (reference
    # batch_norm.cc type inference)
    b = sym.BatchNorm(sym.Cast(sym.Variable("data"), dtype="float16"),
                      name="bn")
    a, o, aux = b.infer_type(data="float32")
    by_name = dict(zip(b.list_arguments(), a))
    assert by_name["bn_gamma"] == np.dtype("float32")
    assert aux == [np.dtype("float32")] * 2
    assert o == [np.dtype("float16")]


def test_infer_shape_partial():
    data = sym.Variable("data")
    out = sym.FullyConnected(data, num_hidden=4, name="fc")
    arg_shapes, out_shapes, _ = out.infer_shape_partial()
    assert out_shapes[0] is None


def test_symbol_arithmetic_and_json():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a + b * 2) / (a - 1)
    js = c.tojson()
    c2 = sym.load_json(js)
    assert c2.list_arguments() == c.list_arguments()
    ex = c2.bind(mx.cpu(), {"a": nd.array([4.0]), "b": nd.array([3.0])})
    out = ex.forward()
    assert_almost_equal(out[0].asnumpy(), np.array([10.0 / 3]), rtol=1e-5)


def test_group_and_multiouts():
    a = sym.Variable("a")
    s1 = a * 2
    s2 = a + 1
    g = sym.Group([s1, s2])
    assert len(g.list_outputs()) == 2
    ex = g.bind(mx.cpu(), {"a": nd.array([1.0, 2])})
    outs = ex.forward()
    assert_almost_equal(outs[0].asnumpy(), [2, 4.0])
    assert_almost_equal(outs[1].asnumpy(), [2, 3.0])


def test_attr_scope_and_attrs():
    with mx.AttrScope(ctx_group="dev1"):
        a = sym.Variable("a")
    assert a.attr("ctx_group") == "dev1"
    b = sym.Variable("b", shape=(3, 4))
    arg_shapes, _, _ = (b * 2).infer_shape()
    assert arg_shapes[0] == (3, 4)


def test_executor_backward_grad_req():
    x = sym.Variable("x")
    y = sym.Variable("y")
    net = (x * y).sum()
    xv = nd.array(np.random.rand(3, 3).astype(np.float32))
    yv = nd.array(np.random.rand(3, 3).astype(np.float32))
    gx = nd.zeros((3, 3))
    ex = net.bind(mx.cpu(), {"x": xv, "y": yv},
                  args_grad={"x": gx},
                  grad_req={"x": "write", "y": "null"})
    ex.forward(is_train=True)
    ex.backward()
    assert_almost_equal(gx.asnumpy(), yv.asnumpy())
    # add req accumulates
    ex2 = net.bind(mx.cpu(), {"x": xv, "y": yv}, args_grad={"x": gx},
                   grad_req={"x": "add", "y": "null"})
    ex2.forward(is_train=True)
    ex2.backward()
    assert_almost_equal(gx.asnumpy(), 2 * yv.asnumpy(), rtol=1e-5)


def test_simple_bind_and_run_fwd_bwd():
    net = _mlp()
    ex = Executor.simple_bind(net, mx.cpu(), data=(4, 20), softmax_label=(4,))
    for name, arr in ex.arg_dict.items():
        if name.endswith("weight"):
            arr[:] = np.random.uniform(-0.1, 0.1, arr.shape)
    ex.arg_dict["data"][:] = np.random.rand(4, 20)
    ex.arg_dict["softmax_label"][:] = [0, 1, 2, 3]
    outs = ex.run_fwd_bwd(is_train=True)
    assert outs[0].shape == (4, 10)
    assert np.abs(ex.grad_dict["fc1_weight"].asnumpy()).sum() > 0


def test_executor_reshape():
    net = _mlp()
    ex = Executor.simple_bind(net, mx.cpu(), data=(4, 20), softmax_label=(4,))
    ex2 = ex.reshape(data=(8, 20), softmax_label=(8,))
    ex2.arg_dict["data"][:] = np.random.rand(8, 20)
    out = ex2.forward()
    assert out[0].shape == (8, 10)


def test_eval_shortcut():
    a = sym.Variable("a")
    out = (a * 3).eval(a=nd.array([1.0, 2]))
    assert_almost_equal(out[0].asnumpy(), [3, 6.0])


def test_save_load_file(tmp_path):
    net = _mlp()
    fname = str(tmp_path / "net.json")
    net.save(fname)
    net2 = mx.sym.load(fname)
    assert net2.tojson() == net.tojson()


def test_variable_init_attr():
    w = sym.Variable("w", lr_mult=2.0, wd_mult=0.5)
    assert w.attr("__lr_mult__") == "2.0"
    assert w.attr("__wd_mult__") == "0.5"


def test_backward_reuses_forward_rng():
    """backward() must reuse the dropout mask drawn by the preceding
    forward() (reference reuses forward state; ADVICE r1)."""
    import numpy as np
    data = mx.sym.Variable("data")
    out = mx.sym.Dropout(data, p=0.5)
    x = np.random.uniform(1.0, 2.0, (64, 64)).astype(np.float32)
    exe = out.simple_bind(mx.cpu(), data=x.shape)
    exe.arg_dict["data"][:] = x
    y = exe.forward(is_train=True)[0].asnumpy()
    exe.backward(mx.nd.ones((64, 64)))
    g = exe.grad_dict["data"].asnumpy()
    # for dropout, dy/dx == y/x elementwise iff the same mask was used
    np.testing.assert_allclose(g, y / x, rtol=1e-5)
