"""Mesh-parallel tests on the 8-device virtual CPU mesh: data-parallel
trainer, ring attention, pipeline parallelism, kvstore-over-mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.test_utils import assert_almost_equal


def test_sharded_trainer_matches_single_device():
    """dp=4 sharded step must produce the same params as one big batch on
    one device (synchronous SGD equivalence — the kvstore contract)."""
    from mxnet_tpu.models.mlp import get_symbol
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    sym = get_symbol(num_classes=4)
    rs = np.random.RandomState(0)
    data = rs.rand(16, 8).astype(np.float32)
    label = rs.randint(0, 4, 16).astype(np.float32)
    shapes = {"data": (16, 8), "softmax_label": (16,)}

    def run(n_dev):
        spec = MeshSpec(make_mesh((n_dev,), ("dp",)))
        tr = ShardedTrainer(sym, spec, lr=0.1, momentum=0.9, wd=0.0)
        params, mom, aux = tr.init_state(shapes, seed=3)
        for _ in range(3):
            params, mom, aux, loss = tr.step(
                params, mom, aux, {"data": data, "softmax_label": label})
        return [np.asarray(p) for p in params], float(loss)

    p1, l1 = run(1)
    p4, l4 = run(4)
    assert l1 == pytest.approx(l4, rel=1e-4)
    for a, b in zip(p1, p4):
        assert_almost_equal(a, b, rtol=1e-4, atol=1e-5)


def test_ring_attention_matches_reference():
    from mxnet_tpu.parallel.ring import reference_attention, ring_attention

    mesh = make_mesh((4,), ("sp",))
    rs = np.random.RandomState(0)
    B, T, H, D = 2, 16, 2, 8
    q = jnp.asarray(rs.rand(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rs.rand(B, T, H, D).astype(np.float32))
    v = jnp.asarray(rs.rand(B, T, H, D).astype(np.float32))
    ref = reference_attention(q, k, v)
    out = ring_attention(q, k, v, mesh, axis="sp")
    assert_almost_equal(np.asarray(out), np.asarray(ref), rtol=1e-4,
                        atol=1e-5)


def test_ring_attention_causal():
    from mxnet_tpu.parallel.ring import reference_attention, ring_attention

    mesh = make_mesh((4,), ("sp",))
    rs = np.random.RandomState(1)
    B, T, H, D = 1, 16, 2, 4
    q = jnp.asarray(rs.rand(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rs.rand(B, T, H, D).astype(np.float32))
    v = jnp.asarray(rs.rand(B, T, H, D).astype(np.float32))
    ref = reference_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, axis="sp", causal=True)
    assert_almost_equal(np.asarray(out), np.asarray(ref), rtol=1e-4,
                        atol=1e-5)


def test_pipeline_matches_sequential():
    from mxnet_tpu.parallel.pipeline import pipeline_apply

    mesh = make_mesh((4,), ("pp",))
    S, M, mb, d = 4, 8, 2, 6
    rs = np.random.RandomState(0)
    Ws = jnp.asarray(rs.rand(S, d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rs.rand(M, mb, d).astype(np.float32))

    def stage_fn(W, xb):
        return jnp.tanh(xb @ W)

    out = pipeline_apply(stage_fn, S, mesh, "pp", Ws, x)
    # sequential reference
    ref = x
    for s in range(S):
        ref = jnp.tanh(ref @ Ws[s])
    assert_almost_equal(np.asarray(out), np.asarray(ref), rtol=1e-4,
                        atol=1e-5)


def test_pipeline_grad():
    from mxnet_tpu.parallel.pipeline import PipelineRunner

    mesh = make_mesh((2,), ("pp",))
    S, M, mb, d = 2, 4, 2, 4
    rs = np.random.RandomState(0)
    Ws = jnp.asarray(rs.rand(S, d, d).astype(np.float32) * 0.3)
    x = jnp.asarray(rs.rand(M, mb, d).astype(np.float32))
    y = jnp.asarray(rs.rand(M, mb, d).astype(np.float32))

    runner = PipelineRunner(lambda W, xb: jnp.tanh(xb @ W), S, mesh)
    loss, grads = runner.loss_and_grad(
        lambda p, t: jnp.mean((p - t) ** 2), Ws, x, y)

    # reference grads without pipeline
    def ref_loss(Ws_):
        out = x
        for s in range(S):
            out = jnp.tanh(out @ Ws_[s])
        return jnp.mean((out - y) ** 2)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(Ws)
    assert float(loss) == pytest.approx(float(ref_l), rel=1e-4)
    assert_almost_equal(np.asarray(grads), np.asarray(ref_g), rtol=1e-3,
                        atol=1e-5)


def test_mesh_helpers():
    from mxnet_tpu.parallel import topology, barrier, allreduce_array
    topo = topology()
    assert topo.process_count == 1
    barrier()  # no-op single process
    x = jnp.ones((4,))
    assert (np.asarray(allreduce_array(x)) == 1).all()
    spec = MeshSpec(make_mesh((8,), ("dp",)))
    assert spec.dp_size == 8


@pytest.mark.slow   # ~70 s: full multichip dryrun; the trainer/mesh paths
                    # it rides stay covered by the rest of this file
def test_dryrun_entry():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 2
    ge.dryrun_multichip(4)
