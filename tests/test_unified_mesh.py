"""Unified GSPMD placement: ONE named-axis mesh with arbitrary axis dims
(MeshSpec.build), the shared ``__shard__`` grammar for params AND
activations (parallel/placement.py + the mxnet_tpu.placement façade),
3-axis composition through ShardedTrainer, the retained shard_map
kernels embedded in the same mesh, and the elastic reform of a
multi-axis mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.parallel import placement
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh, reform_mesh
from mxnet_tpu.parallel.trainer import ShardedTrainer


def _need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip("needs %d devices" % n)


# ---------------------------------------------------------------------------
# MeshSpec.build: arbitrary named-axis layouts with role inference
# ---------------------------------------------------------------------------

def test_meshspec_build_roles_and_sizes():
    _need_devices(8)
    spec = MeshSpec.build({"dp": 2, "tp": 2, "pp": 2})
    assert tuple(spec.mesh.axis_names) == ("dp", "tp", "pp")
    assert (spec.dp_axis, spec.tp_axis, spec.pp_axis) == ("dp", "tp", "pp")
    assert spec.ep_axis is None and spec.sp_axis is None
    assert spec.axis_size("dp") == 2 and spec.axis_size("missing") == 1
    assert spec.dp_size == 2
    assert spec.model_axes == ("tp", "pp")
    # trivial axes keep the name present but drop out of model_axes
    spec1 = MeshSpec.build({"dp": 8, "tp": 1})
    assert spec1.model_axes == () and spec1.dp_size == 8
    # custom axis names ride along, reachable via __shard__
    spec_c = MeshSpec.build([("dp", 2), ("banks", 4)])
    assert spec_c.mesh.shape["banks"] == 4 and spec_c.tp_axis is None
    with pytest.raises(ValueError):
        MeshSpec.build([("dp", 2), ("dp", 2)])


def test_reform_mesh_keeps_non_dp_axes_of_unified_mesh():
    _need_devices(8)
    spec = MeshSpec.build({"dp": 2, "tp": 2, "ep": 2}, generation=3)
    out = reform_mesh(spec)
    assert out.generation == 4
    assert dict(out.mesh.shape) == {"dp": 2, "tp": 2, "ep": 2}
    assert (out.tp_axis, out.ep_axis) == ("tp", "ep")


# ---------------------------------------------------------------------------
# the __shard__ grammar (one resolver for params and activations)
# ---------------------------------------------------------------------------

def test_resolve_spec_grammar():
    _need_devices(4)
    mesh = make_mesh((2, 2), ("dp", "tp"))
    assert placement.resolve_spec("tp,*", (8, 6), mesh) == P("tp", None)
    # trailing dims default to replicated
    assert placement.resolve_spec("tp", (8, 6, 4), mesh) == \
        P("tp", None, None)
    # non-divisible named dim downgrades to replicated, silently
    assert placement.resolve_spec("tp,dp", (7, 6), mesh) == P(None, "dp")
    with pytest.raises(ValueError):
        placement.resolve_spec("tp,dp,tp", (8, 6), mesh)     # arity
    with pytest.raises(ValueError):
        placement.resolve_spec("nope", (8, 6), mesh)         # unknown axis


def test_param_sharding_any_axis_annotation():
    """__shard__ may name ANY mesh axis — not just tp — which is what
    lets one annotated model run on every layout of the unified mesh."""
    _need_devices(8)
    spec = MeshSpec.build({"dp": 2, "tp": 2, "ep": 2})
    s = placement.param_sharding("w", (8, 6), spec.mesh, tp_axis="tp",
                                 ann="ep,*")
    assert tuple(s.spec) == ("ep", None)
    # no annotation + no tp: replicated over every axis
    s = placement.param_sharding("w", (8, 6), spec.mesh, tp_axis=None)
    assert tuple(s.spec) == ()


def test_activation_shard_constraint_applies_in_step():
    """An op-level __shard__ becomes a with_sharding_constraint on the
    op's outputs inside the trainer's traced step (the executor hook,
    armed by the trainer's current mesh) — and leaves numerics alone."""
    _need_devices(4)

    def net(annotate):
        data = mx.sym.Variable("data")
        attr = {"__shard__": "dp"} if annotate else None
        h = mx.sym.FullyConnected(data, name="fc1", num_hidden=16,
                                  attr=attr)
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, name="fc2", num_hidden=8)
        return mx.sym.SoftmaxOutput(h, name="softmax")

    spec = MeshSpec(make_mesh((4,), ("dp",)))
    rs = np.random.RandomState(0)
    feed = {"data": rs.rand(8, 12).astype(np.float32),
            "softmax_label": rs.randint(0, 8, 8).astype(np.float32)}
    outs = []
    for annotate in (True, False):
        tr = ShardedTrainer(net(annotate), spec, lr=0.1)
        assert bool(tr._act_shard_attrs) == annotate
        params, mom, aux = tr.init_state(
            {"data": (8, 12), "softmax_label": (8,)}, seed=1)
        params, mom, aux, loss = tr.step(params, mom, aux, feed)
        outs.append([np.asarray(p) for p in params])
    for a, b in zip(*outs):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    # the constraint really traced in: the jaxpr carries a
    # sharding_constraint over the annotated activation
    tr = ShardedTrainer(net(True), spec, lr=0.1)
    params, mom, aux = tr.init_state(
        {"data": (8, 12), "softmax_label": (8,)}, seed=1)
    tr._arm_mesh()
    sds = {n: jax.ShapeDtypeStruct(np.asarray(v).shape, jnp.float32)
           for n, v in feed.items()}
    jaxpr = jax.make_jaxpr(tr._make_step_fn())(
        params, mom, aux, sds, tr._keys(), tr._guard_arrays())
    assert "sharding_constraint" in str(jaxpr)


def test_activation_constraint_inert_without_mesh():
    """The executor hook is identity when no mesh is active — the
    single-device Module/Executor paths never pay for annotations."""
    from mxnet_tpu.parallel.mesh import set_current_mesh
    from mxnet_tpu.placement import activation_constraint
    set_current_mesh(None)
    x = (jnp.ones((4, 4)), jnp.float32(1.0))
    out = activation_constraint(x, "dp", "toy")
    assert out is x


def test_shard_annotations_facade_splits_vars_and_ops():
    from mxnet_tpu.executor import GraphProgram
    from mxnet_tpu.placement import shard_annotations
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w", attr={"__shard__": "tp"})
    h = mx.sym.FullyConnected(data, weight=w, name="fc", num_hidden=8,
                              attr={"__shard__": "dp"})
    net = mx.sym.SoftmaxOutput(h, name="softmax")
    var_anns, op_anns = shard_annotations(GraphProgram(net).nodes)
    assert var_anns == {"w": "tp"}
    assert op_anns == {"fc": "dp"}


# ---------------------------------------------------------------------------
# 3-axis composition through ShardedTrainer + embedded kernels
# ---------------------------------------------------------------------------

def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=8)
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _train(spec, steps=2, seed=4):
    tr = ShardedTrainer(_mlp(), spec, lr=0.1, momentum=0.9, wd=1e-4,
                        zero=True)
    params, mom, aux = tr.init_state(
        {"data": (8, 12), "softmax_label": (8,)}, seed=seed)
    rs = np.random.RandomState(1)
    for _ in range(steps):
        feed = {"data": rs.rand(8, 12).astype(np.float32),
                "softmax_label": rs.randint(0, 8, 8).astype(np.float32)}
        params, mom, aux, loss = tr.step(params, mom, aux, feed)
    return tr, [np.asarray(p) for p in params]


def test_three_axis_trainer_matches_single_axis():
    """dp2 x tp2 x pp2 through ShardedTrainer (ZeRO on) == dp8, the
    8-device composition the hand-rolled paths could never express."""
    _need_devices(8)
    tr8, p8 = _train(MeshSpec.build({"dp": 8}))
    tr3, p3 = _train(MeshSpec.build({"dp": 2, "tp": 2, "pp": 2}))
    assert tr3.tp_axis == "tp" and tr3.shard_weight_update
    for n, a, b in zip(tr3.param_names, p3, p8):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5, err_msg=n)


def test_shard_map_kernels_embed_in_unified_mesh():
    """ring attention / MoE dispatch / the GPipe schedule run on a mesh
    that ALSO carries dp and tp axes — manual only over their own axis,
    composing with the GSPMD-managed ones."""
    _need_devices(8)
    from mxnet_tpu.parallel.moe import moe_ffn, moe_ffn_dense
    from mxnet_tpu.parallel.pipeline import pipeline_apply
    from mxnet_tpu.parallel.ring import reference_attention, ring_attention
    rs = np.random.RandomState(0)

    spec = MeshSpec.build({"dp": 2, "tp": 2, "sp": 2})
    qkv = [jnp.asarray(rs.rand(2, 8, 2, 4).astype(np.float32))
           for _ in range(3)]
    out = ring_attention(*qkv, spec, axis="sp", causal=True)
    ref = reference_attention(*qkv, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)

    spec = MeshSpec.build({"dp": 2, "tp": 2, "ep": 2})
    E, d, hid = 2, 8, 16
    x = jnp.asarray(rs.rand(8, d).astype(np.float32))
    wg = jnp.asarray(rs.rand(d, E).astype(np.float32))
    w1 = jnp.asarray(rs.rand(E, d, hid).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rs.rand(E, hid, d).astype(np.float32) * 0.1)
    out, aux = moe_ffn(x, wg, w1, w2, spec, capacity_factor=4.0)
    ref, ref_aux = moe_ffn_dense(x, wg, w1, w2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)

    spec = MeshSpec.build({"dp": 2, "tp": 2, "pp": 2})
    Ws = jnp.asarray(rs.rand(2, 6, 6).astype(np.float32) * 0.2)
    xm = jnp.asarray(rs.rand(3, 2, 6).astype(np.float32))
    out = pipeline_apply(lambda W, x_: jnp.tanh(x_ @ W), 2, spec, "pp",
                         Ws, xm)
    ref = xm
    for i in range(2):
        ref = jnp.tanh(ref @ Ws[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
