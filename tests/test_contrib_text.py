"""contrib.text tests (reference tests/python/unittest/test_contrib_text.py
scenarios: counting, vocabulary indexing rules, embedding loading,
composite embeddings)."""
import collections

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text


def test_count_tokens_from_str():
    c = text.utils.count_tokens_from_str(" Life is great! \n life is good .\n")
    assert c["is"] == 2 and c["Life"] == 1 and c["life"] == 1
    c2 = text.utils.count_tokens_from_str("Life is great! \n life is good .",
                                          to_lower=True)
    assert c2["life"] == 2
    base = collections.Counter({"is": 10})
    c3 = text.utils.count_tokens_from_str("is it", counter_to_update=base)
    assert c3 is base and c3["is"] == 11 and c3["it"] == 1


def test_vocabulary_indexing_rules():
    counter = collections.Counter(
        {"a": 5, "b": 5, "c": 3, "d": 2, "rare": 1})
    v = text.Vocabulary(counter, most_freq_count=None, min_freq=2,
                        unknown_token="<unk>", reserved_tokens=["<pad>"])
    # unknown first, reserved next, then freq desc / token asc
    assert v.idx_to_token[:2] == ["<unk>", "<pad>"]
    assert v.idx_to_token[2:] == ["a", "b", "c", "d"]   # rare dropped
    assert v.to_indices("a") == 2
    assert v.to_indices(["zzz", "b"]) == [0, 3]
    assert v.to_tokens([0, 2]) == ["<unk>", "a"]
    with pytest.raises(ValueError):
        v.to_tokens(99)
    # most_freq_count caps counter tokens only; specials come on top
    v2 = text.Vocabulary(counter, most_freq_count=3, min_freq=1,
                         reserved_tokens=["<pad>"])
    assert len(v2) == 5
    assert v2.idx_to_token == ["<unk>", "<pad>", "a", "b", "c"]
    with pytest.raises(ValueError):
        text.Vocabulary(counter, reserved_tokens=["<unk>"])


@pytest.fixture()
def emb_file(tmp_path):
    p = tmp_path / "emb.txt"
    p.write_text("hello 1 2 3\nworld 4 5 6\nhello 9 9 9\n")
    return str(p)


def test_custom_embedding(emb_file):
    emb = text.embedding.CustomEmbedding(emb_file)
    assert emb.vec_len == 3
    assert len(emb) == 3   # <unk> + 2 tokens (duplicate 'hello' skipped)
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [1, 2, 3])
    got = emb.get_vecs_by_tokens(["world", "nope", "Hello"])
    np.testing.assert_allclose(got.asnumpy(),
                               [[4, 5, 6], [0, 0, 0], [0, 0, 0]])
    got = emb.get_vecs_by_tokens(["Hello"], lower_case_backup=True)
    np.testing.assert_allclose(got.asnumpy(), [[1, 2, 3]])
    emb.update_token_vectors("world", mx.nd.array([7.0, 7, 7]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("world").asnumpy(), [7, 7, 7])
    with pytest.raises(ValueError):
        emb.update_token_vectors("nope", mx.nd.array([1.0, 1, 1]))


def test_embedding_registry(emb_file):
    emb = text.embedding.create("customembedding",
                                pretrained_file_path=emb_file)
    assert emb.vec_len == 3
    names = text.embedding.get_pretrained_file_names()
    assert "glove" in names and any("840B" in n for n in names["glove"])
    with pytest.raises(RuntimeError):
        text.embedding.GloVe()   # no network: must demand a local path
    with pytest.raises(KeyError):
        text.embedding.create("nosuch")


def test_composite_embedding(tmp_path):
    p1 = tmp_path / "a.txt"
    p1.write_text("x 1 1\ny 2 2\n")
    p2 = tmp_path / "b.txt"
    p2.write_text("x 3\nz 4\n")
    e1 = text.embedding.CustomEmbedding(str(p1))
    e2 = text.embedding.CustomEmbedding(str(p2))
    vocab = text.Vocabulary(collections.Counter({"x": 2, "y": 1, "z": 1}))
    comp = text.embedding.CompositeEmbedding(vocab, [e1, e2])
    assert comp.vec_len == 3
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("x").asnumpy(), [1, 1, 3])
    # y only in e1, z only in e2 — the other half is the unknown vector
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("y").asnumpy(), [2, 2, 0])
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("z").asnumpy(), [0, 0, 4])


def test_fasttext_header_skipped(tmp_path):
    p = tmp_path / "ft.vec"
    p.write_text("2 3\ncat 1 2 3\ndog 4 5 6\n")
    emb = text.embedding.FastText(pretrained_file_path=str(p))
    assert emb.vec_len == 3 and len(emb) == 3
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("dog").asnumpy(), [4, 5, 6])


def test_contrib_autograd_legacy_api():
    """The OLD experimental autograd API (reference contrib/autograd.py):
    train_section + compute_gradient, and the grad/grad_and_loss
    decorators."""
    from mxnet_tpu.contrib import autograd as cag
    from mxnet_tpu import nd

    x = nd.array([1.0, 2.0, 3.0])
    gx = nd.zeros((3,))
    cag.mark_variables([x], [gx])
    with cag.train_section():
        y = x * x
        cag.compute_gradient([y])
    np.testing.assert_allclose(gx.asnumpy(), [2, 4, 6], rtol=1e-6)

    def f(a, b):
        return a * b + a

    g = cag.grad(f)
    ga, gb = g(nd.array([2.0]), nd.array([5.0]))
    np.testing.assert_allclose(ga.asnumpy(), [6.0])   # b + 1
    np.testing.assert_allclose(gb.asnumpy(), [2.0])   # a

    gl = cag.grad_and_loss(f, argnum=0)
    grads, loss = gl(nd.array([2.0]), nd.array([5.0]))
    np.testing.assert_allclose(grads[0].asnumpy(), [6.0])
    np.testing.assert_allclose(loss.asnumpy(), [12.0])


def test_engine_libinfo_log_modules():
    """Small top-level modules: engine bulk scopes (advisory under XLA
    fusion), libinfo lib location/version, log helpers (reference
    engine.py / libinfo.py / log.py)."""
    prev = mx.engine.current_bulk_size()
    with mx.engine.bulk(10):
        assert mx.engine.current_bulk_size() == 10
    assert mx.engine.current_bulk_size() == prev
    assert mx.__version__ == mx.libinfo.__version__
    lg = mx.log.get_logger("mxt_test_logger")
    mx.log.get_logger("mxt_test_logger")
    assert len(lg.handlers) == 1   # one handler regardless of call count
