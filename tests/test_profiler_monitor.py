"""Profiler op-event and per-node Monitor tests.

Reference analogs: src/engine/profiler.cc:147 (chrome trace with per-op
events) and src/executor/graph_executor.cc:121 (monitor callback invoked on
every node output — the tool for finding the exploding/NaN layer).
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu import profiler


@pytest.mark.slow   # ~25 s: exhaustive per-op trace; the fit-batch and
                    # monitor profiler tests below keep the subsystem covered
def test_profiler_records_op_and_executor_events(tmp_path):
    fname = str(tmp_path / "profile.json")
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    try:
        # imperative ops
        a = nd.ones((4, 4))
        b = (a * 2 + 1).asnumpy()
        # symbolic executor fwd + bwd
        x = sym.Variable("x")
        net = sym.FullyConnected(x, num_hidden=3, name="fc")
        ex = net.simple_bind(mx.cpu(), x=(2, 5))
        ex.forward(is_train=True)
        ex.backward(out_grads=nd.ones((2, 3)))
        # kvstore push/pull
        kv = mx.kv.create("local")
        kv.init(0, nd.zeros((3,)))
        kv.push(0, nd.ones((3,)))
        out = nd.zeros((3,))
        kv.pull(0, out=out)
    finally:
        profiler.set_state("stop")
    path = profiler.dump_profile()
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert events, "trace must not be empty"
    names = {e["name"] for e in events}
    cats = {e["cat"] for e in events}
    assert any("_mul_scalar" in n or "_plus_scalar" in n for n in names), names
    assert "fc_forward" in names or any(n.endswith("_forward") for n in names)
    assert any(n.endswith("_backward") for n in names)
    assert "kvstore_push" in names and "kvstore_pull" in names
    assert "operator" in cats and "symbolic" in cats
    for e in events:
        assert e["dur"] >= 0 and e["ph"] == "X"


def test_profiler_records_fit_batches(tmp_path):
    fname = str(tmp_path / "profile_fit.json")
    profiler.set_config(filename=fname)
    rng = np.random.RandomState(0)
    x = rng.rand(32, 8).astype(np.float32)
    y = rng.randint(0, 2, 32).astype(np.float32)
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=2)
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(x, y, batch_size=8, label_name="softmax_label")
    profiler.set_state("run")
    try:
        mod.fit(it, num_epoch=1, optimizer_params={"learning_rate": 0.1})
    finally:
        profiler.set_state("stop")
    with open(profiler.dump_profile()) as f:
        events = json.load(f)["traceEvents"]
    batch_events = [e for e in events if e["cat"] == "batch"]
    assert len(batch_events) == 4, [e["name"] for e in batch_events]


def test_monitor_all_taps_every_node_and_finds_nan():
    """fc1 produces negatives -> log() produces NaNs -> fc2 hides them in
    the final output magnitude.  Per-node monitoring must finger the log
    layer by name."""
    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=4, name="fc1")
    bad = sym.log(h, name="badlog")
    net = sym.FullyConnected(bad, num_hidden=2, name="fc2")

    def nan_stat(arr):
        return nd.array([float(np.isnan(arr.asnumpy()).any())])

    mon = mx.mon.Monitor(interval=1, stat_func=nan_stat, monitor_all=True)
    ex = net.simple_bind(mx.cpu(), data=(3, 5))
    rng = np.random.RandomState(0)
    for arr in ex.arg_arrays:
        arr[:] = nd.array(rng.normal(0, 1, arr.shape))
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=True)
    res = mon.toc()
    stats = {k: float(v) for _, k, v in
             [(n, k, s.strip().split("\t")[0]) for n, k, s in res]}
    assert "badlog_output" in stats, sorted(stats)
    assert "fc1_output" in stats and "fc2_output" in stats
    assert stats["badlog_output"] == 1.0   # NaN born here
    assert stats["fc1_output"] == 0.0      # clean before


def test_monitor_all_fires_on_fused_module_path():
    """Module.fit uses the fused run_fwd_bwd; monitor_all must still tap
    per-node outputs there."""
    rng = np.random.RandomState(0)
    x = rng.rand(16, 6).astype(np.float32)
    y = rng.randint(0, 2, 16).astype(np.float32)
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=3, name="fc1")
    net = sym.Activation(net, act_type="relu", name="act1")
    net = sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")
    seen = []

    def stat(arr):
        return nd.array([1.0])

    mon = mx.mon.Monitor(interval=1, stat_func=stat, monitor_all=True)
    orig_helper = mon.stat_helper

    def spy(name, arr):
        seen.append(name)
        orig_helper(name, arr)
    spy.monitor_active = orig_helper.monitor_active
    mon.stat_helper = spy

    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(x, y, batch_size=8, label_name="softmax_label")
    mod.fit(it, num_epoch=1, monitor=mon,
            optimizer_params={"learning_rate": 0.1})
    assert "act1_output" in seen and "fc1_output" in seen, sorted(set(seen))


def test_monitor_all_multi_output_names_match_list_outputs():
    """Multi-output nodes must tap under the same names list_outputs uses
    ("<name>_output0", "<name>_output1", ...)."""
    data = sym.Variable("data")
    net = sym.SliceChannel(data, num_outputs=2, name="split0")
    assert net.list_outputs() == ["split0_output0", "split0_output1"]
    mon = mx.mon.Monitor(interval=1, monitor_all=True)
    ex = net.simple_bind(mx.cpu(), data=(2, 4))
    ex.arg_arrays[0][:] = nd.ones((2, 4))
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=True)
    tapped = {k for _, k, _ in mon.toc()}
    assert {"split0_output0", "split0_output1"} <= tapped, sorted(tapped)


def test_monitor_outputs_only_default_unchanged():
    """monitor_all=False (default) keeps the outputs-only contract."""
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=2, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(2, 3))
    for arr in ex.arg_arrays:
        arr[:] = nd.ones(arr.shape)
    mon = mx.mon.Monitor(interval=1)
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=True)
    res = mon.toc()
    tapped = {k for _, k, _ in res}
    assert "fc_output" in tapped
