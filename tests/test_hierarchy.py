"""Two-tier hierarchical collectives (parallel/hierarchy.py): the
2-island x 4 dryrun of the ISSUE-19 acceptance bar — numerics match a
flat psum, the compiled program's per-tier payloads are attributed to
the right mesh axis and equal ``hierarchical_allreduce_model_bytes``
exactly, and the slow-tier wire bytes come out far below the flat-ring
baseline."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.analysis import graphcheck
from mxnet_tpu.parallel import audit, hierarchy
from mxnet_tpu.parallel.mesh import MeshSpec

ISLANDS, PER_ISLAND = 2, 4
WORLD = ISLANDS * PER_ISLAND


def _need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip("needs %d devices" % n)


def _mesh():
    return MeshSpec.build({"island": ISLANDS, "dp": PER_ISLAND}).mesh


def _stacked(n_elems, seed=0):
    rng = np.random.RandomState(seed)
    return rng.normal(size=(WORLD, n_elems)).astype(np.float32)


def test_hierarchical_matches_flat_psum():
    _need_devices(WORLD)
    mesh = _mesh()
    stacked = _stacked(64)
    hier = np.asarray(hierarchy.hierarchical_allreduce(
        jnp.asarray(stacked), mesh))
    flat = np.asarray(hierarchy.flat_allreduce(jnp.asarray(stacked), mesh))
    expect = stacked.sum(axis=0)
    assert np.allclose(hier, expect, atol=1e-5)
    assert np.allclose(hier, flat, atol=1e-5)
    # every row carries the same global sum
    assert np.allclose(hier, hier[0], atol=0)


def test_hierarchical_pads_non_divisible():
    _need_devices(WORLD)
    mesh = _mesh()
    stacked = _stacked(13, seed=3)    # 13 % 4 != 0 -> zero-pad path
    out = np.asarray(hierarchy.hierarchical_allreduce(
        jnp.asarray(stacked), mesh))
    assert out.shape == stacked.shape
    assert np.allclose(out, stacked.sum(axis=0), atol=1e-5)


def test_two_tier_payloads_match_model_per_axis():
    """The audit bar: compiled HLO must contain exactly one
    reduce-scatter on the fast axis, one all-reduce on the slow axis and
    one all-gather on the fast axis, each with the analytic payload."""
    _need_devices(WORLD)
    mesh = _mesh()
    n = 64
    f = jax.jit(functools.partial(hierarchy.hierarchical_allreduce,
                                  mesh=mesh))
    hlo = f.lower(jax.ShapeDtypeStruct((WORLD, n), jnp.float32)) \
        .compile().as_text()
    acct = audit.collective_accounting(hlo, mesh=mesh)
    model = audit.hierarchical_allreduce_model_bytes(
        n * 4, ISLANDS, PER_ISLAND)

    for kind, axis in (("reduce-scatter", "dp"), ("all-reduce", "island"),
                       ("all-gather", "dp")):
        assert kind in acct, (kind, sorted(acct))
        info = acct[kind]
        assert info["bytes"] == model[kind], (kind, info, model)
        # the whole payload of this kind is attributed to ONE tier
        assert set(info["by_axis"]) == {axis}, (kind, info["by_axis"])
        assert info["by_axis"][axis]["bytes"] == model[kind]


def test_slow_tier_wire_far_below_flat_ring():
    payload = 10 * 1024 * 1024
    model = audit.hierarchical_allreduce_model_bytes(
        payload, ISLANDS, PER_ISLAND)
    # slow tier moves a ring all-reduce of the 1/k shard over m islands
    assert model["slow_wire"] == audit.ring_allreduce_wire_bytes(
        payload // PER_ISLAND, ISLANDS)
    assert model["flat_wire"] == audit.ring_allreduce_wire_bytes(
        payload, WORLD)
    # the "<< flat ring" acceptance clause, with margin: 7x at 2x4
    assert model["flat_wire"] >= 4 * model["slow_wire"], model


def test_model_unit_values():
    m = audit.hierarchical_allreduce_model_bytes(256, 2, 4)
    assert m == {"reduce-scatter": 64, "all-reduce": 64,
                 "all-gather": 256,
                 "slow_wire": audit.ring_allreduce_wire_bytes(64, 2),
                 "flat_wire": audit.ring_allreduce_wire_bytes(256, 8)}
    # degenerate single-island mesh: nothing crosses a slow tier
    m1 = audit.hierarchical_allreduce_model_bytes(256, 1, 4)
    assert m1["slow_wire"] == 0
    # ceil-division when the payload does not divide the island
    mp = audit.hierarchical_allreduce_model_bytes(52, 2, 4)   # 13 f32
    assert mp["reduce-scatter"] == 16                          # 4-elem shard


def test_audit_report_hier_line():
    _need_devices(WORLD)
    mesh = _mesh()
    n = 64
    f = jax.jit(functools.partial(hierarchy.hierarchical_allreduce,
                                  mesh=mesh))
    hlo = f.lower(jax.ShapeDtypeStruct((WORLD, n), jnp.float32)) \
        .compile().as_text()
    model = audit.hierarchical_allreduce_model_bytes(
        n * 4, ISLANDS, PER_ISLAND)
    text, _ = audit.audit_report("hier-dryrun", hlo, WORLD,
                                 ring_n=ISLANDS, mesh=mesh,
                                 hier_model=model)
    assert "analytic 2-tier payload" in text
    assert "measured/model = 1.00" in text
    assert "by-axis" in text and "island" in text
    assert "flat ring" in text


def test_graphcheck_clean_and_worker_step_collective_free():
    _need_devices(WORLD)
    mesh = _mesh()

    def run(st):
        return hierarchy.hierarchical_allreduce(st, mesh)
    rep = graphcheck.check_fn(
        run, jax.ShapeDtypeStruct((WORLD, 16), jnp.float32), mesh=mesh,
        target="parallel.hierarchical_allreduce")
    assert rep.errors() == [], [f.to_dict() for f in rep.errors()]

    # the async worker step honours the collective-free contract...
    from mxnet_tpu.kvstore.worker import TOY_DIM, make_worker_step
    step = make_worker_step(TOY_DIM)
    w = jax.ShapeDtypeStruct((TOY_DIM,), jnp.float32)
    x = jax.ShapeDtypeStruct((16, TOY_DIM), jnp.float32)
    y = jax.ShapeDtypeStruct((16,), jnp.float32)
    rep = graphcheck.check_collective_free(step, w, x, y,
                                           target="kvstore.worker_step")
    assert rep.errors() == [], [f.to_dict() for f in rep.errors()]

    # ...and GC106 actually fires on a program that breaks it
    def sneaky(st):
        return hierarchy.flat_allreduce(st, mesh)
    rep = graphcheck.check_collective_free(
        sneaky, jax.ShapeDtypeStruct((WORLD, 16), jnp.float32),
        target="sneaky")
    assert any(f.rule == "GC106" for f in rep.errors()), \
        [f.to_dict() for f in rep.findings]


def test_grad_allreduce_tree():
    _need_devices(WORLD)
    mesh = _mesh()
    tree = {"a": jnp.asarray(_stacked(8, seed=1)),
            "b": jnp.asarray(_stacked(24, seed=2))}
    out = hierarchy.hierarchical_grad_allreduce(tree, mesh)
    for k in tree:
        assert np.allclose(np.asarray(out[k]),
                           np.asarray(tree[k]).sum(axis=0), atol=1e-5)
