"""IO + metric + recordio tests (reference test_io.py / test_metric.py /
test_recordio.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def test_ndarray_iter():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    labels = np.arange(10).astype(np.float32)
    it = mx.io.NDArrayIter(data, labels, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 4
    it2 = mx.io.NDArrayIter(data, labels, batch_size=5,
                            last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_ndarray_iter_dict_and_shuffle():
    data = {"a": np.random.rand(8, 2), "b": np.random.rand(8, 3)}
    it = mx.io.NDArrayIter(data, None, batch_size=4, shuffle=True)
    names = [d.name for d in it.provide_data]
    assert set(names) == {"a", "b"}
    batch = next(it)
    assert len(batch.data) == 2


def test_resize_iter():
    data = np.random.rand(10, 2).astype(np.float32)
    it = mx.io.ResizeIter(mx.io.NDArrayIter(data, batch_size=2), size=7)
    assert len(list(it)) == 7


def test_prefetching_iter():
    data = np.random.rand(16, 2).astype(np.float32)
    base = mx.io.NDArrayIter(data, np.zeros(16, np.float32), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    n = 0
    for batch in it:
        assert batch.data[0].shape == (4, 2)
        n += 1
    assert n == 4


def test_csv_iter(tmp_path):
    data = np.random.rand(10, 3)
    np.savetxt(tmp_path / "d.csv", data, delimiter=",")
    np.savetxt(tmp_path / "l.csv", np.arange(10), delimiter=",")
    it = mx.io.CSVIter(data_csv=str(tmp_path / "d.csv"), data_shape=(3,),
                       label_csv=str(tmp_path / "l.csv"), batch_size=5)
    batch = next(it)
    assert batch.data[0].shape == (5, 3)
    assert_almost_equal(batch.data[0].asnumpy(), data[:5], rtol=1e-5)


def test_libsvm_iter(tmp_path):
    with open(tmp_path / "d.svm", "w") as f:
        f.write("1 0:1.5 3:2.0\n0 1:1.0\n1 2:3.0 3:1.0\n0 0:0.5\n")
    it = mx.io.LibSVMIter(data_libsvm=str(tmp_path / "d.svm"),
                          data_shape=(4,), batch_size=2)
    batch = next(it)
    assert batch.data[0].stype == "csr"
    dense = batch.data[0].asnumpy()
    assert dense[0, 0] == 1.5 and dense[0, 3] == 2.0


def test_recordio_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "t.rec")
    rec = recordio.MXRecordIO(path, "w")
    for i in range(5):
        rec.write(b"record%d" % i)
    rec.close()
    rec = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert rec.read() == b"record%d" % i
    assert rec.read() is None
    rec.close()


def test_indexed_recordio(tmp_path):
    from mxnet_tpu import recordio
    path = str(tmp_path / "t.rec")
    idxp = str(tmp_path / "t.idx")
    rec = recordio.MXIndexedRecordIO(idxp, path, "w")
    for i in range(5):
        rec.write_idx(i, b"rec%d" % i)
    rec.close()
    rec = recordio.MXIndexedRecordIO(idxp, path, "r")
    assert rec.read_idx(3) == b"rec3"
    assert rec.read_idx(0) == b"rec0"
    assert rec.keys == list(range(5))


def test_recordio_pack_unpack():
    from mxnet_tpu import recordio
    header = recordio.IRHeader(0, 2.0, 7, 0)
    s = recordio.pack(header, b"payload")
    h2, data = recordio.unpack(s)
    assert h2.label == 2.0 and h2.id == 7 and data == b"payload"
    header = recordio.IRHeader(0, np.array([1.0, 2, 3], np.float32), 1, 0)
    s = recordio.pack(header, b"x")
    h2, data = recordio.unpack(s)
    assert (h2.label == [1, 2, 3]).all() and data == b"x"


def test_accuracy_metric():
    acc = mx.metric.create("acc")
    pred = nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = nd.array([1, 0, 0])
    acc.update([label], [pred])
    assert acc.get()[1] == pytest.approx(2.0 / 3)
    acc.reset()
    assert np.isnan(acc.get()[1])


def test_topk_f1_mse():
    topk = mx.metric.create("top_k_accuracy", top_k=2)
    pred = nd.array([[0.1, 0.5, 0.4], [0.8, 0.1, 0.1]])
    label = nd.array([2, 1])
    topk.update([label], [pred])
    assert topk.get()[1] == pytest.approx(0.5)

    mse = mx.metric.create("mse")
    mse.update([nd.array([1.0, 2])], [nd.array([1.5, 2.5])])
    assert mse.get()[1] == pytest.approx(0.25)

    f1 = mx.metric.F1()
    f1.update([nd.array([1, 0, 1, 1])],
              [nd.array([[0.2, 0.8], [0.9, 0.1], [0.3, 0.7], [0.6, 0.4]])])
    assert 0 < f1.get()[1] <= 1


def test_perplexity_crossentropy():
    pred = nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = nd.array([0, 0])
    ce = mx.metric.create("ce")
    ce.update([label], [pred])
    expected = -(np.log(0.5) + np.log(0.9)) / 2
    assert ce.get()[1] == pytest.approx(expected, rel=1e-4)
    ppl = mx.metric.Perplexity(ignore_label=None)
    ppl.update([label], [pred])
    assert ppl.get()[1] == pytest.approx(np.exp(expected), rel=1e-4)


def test_composite_and_custom():
    comp = mx.metric.create(["acc", "mse"])
    names, values = None, None
    comp.update([nd.array([1, 1])], [nd.array([[0.1, 0.9], [0.2, 0.8]])])
    out = dict(comp.get_name_value())
    assert "accuracy" in out and "mse" in out

    custom = mx.metric.np(lambda label, pred: float((label == 1).mean()))
    custom.update([nd.array([1, 0])], [nd.array([[1.0], [0.0]])])
    assert custom.get()[1] == pytest.approx(0.5)


def test_mnist_iter(tmp_path):
    # write tiny idx files
    import struct
    imgs = (np.random.rand(6, 28, 28) * 255).astype(np.uint8)
    labels = np.arange(6, dtype=np.uint8)
    with open(tmp_path / "img", "wb") as f:
        f.write(struct.pack(">I", 0x00000803))
        f.write(struct.pack(">III", 6, 28, 28))
        f.write(imgs.tobytes())
    with open(tmp_path / "lbl", "wb") as f:
        f.write(struct.pack(">I", 0x00000801))
        f.write(struct.pack(">I", 6))
        f.write(labels.tobytes())
    it = mx.io.MNISTIter(image=str(tmp_path / "img"),
                         label=str(tmp_path / "lbl"),
                         batch_size=2, shuffle=False)
    batch = next(it)
    assert batch.data[0].shape == (2, 1, 28, 28)
    assert batch.data[0].asnumpy().max() <= 1.0
