"""CustomOp tests — reference tests/python/unittest/test_operator.py
(test_custom_op) over python/mxnet/operator.py."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import operator as op_mod


@op_mod.register("pysoftmax")
class PySoftmaxProp(op_mod.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return PySoftmax()


class PySoftmax(op_mod.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        l = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy()
        y[np.arange(l.shape[0]), l] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y))


@op_mod.register("scalemul")
class ScaleMulProp(op_mod.CustomOpProp):
    """Exercises kwargs → prop constructor string marshalling."""

    def __init__(self, scale="1.0"):
        super().__init__(need_top_grad=True)
        self.scale = float(scale)

    def create_operator(self, ctx, shapes, dtypes):
        s = self.scale

        class _Op(op_mod.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0] * s)

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                self.assign(in_grad[0], req[0], out_grad[0] * s)

        return _Op()


def test_custom_nd_forward():
    x = mx.nd.array(np.random.rand(4, 10).astype(np.float32))
    lbl = mx.nd.array(np.zeros(4, np.float32))
    out = mx.nd.Custom(x, lbl, op_type="pysoftmax")
    xn = x.asnumpy()
    ref = np.exp(xn - xn.max(1, keepdims=True))
    ref /= ref.sum(1, keepdims=True)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)


def test_custom_kwargs_and_grad():
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="scalemul", scale=3.0)
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((2, 3), 3.0),
                               rtol=1e-6)


def test_custom_symbol_trains_via_module():
    rs = np.random.RandomState(0)
    X = rs.rand(64, 8).astype(np.float32)
    w_true = rs.rand(8, 3).astype(np.float32)
    y = (X @ w_true).argmax(axis=1).astype(np.float32)

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net = mx.sym.Custom(fc, label, op_type="pysoftmax", name="pysm")
    net = mx.sym.MakeLoss(net, name="out")

    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"], context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=16, shuffle=True,
                           label_name="softmax_label")
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    first_err = None
    for _ in range(12):
        it.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            probs = mod.get_outputs()[0].asnumpy()
            err = (probs.argmax(1) != batch.label[0].asnumpy()).mean()
            if first_err is None:
                first_err = err
            mod.backward()
            mod.update()
    assert err < first_err, (first_err, err)
    assert err < 0.2, err


def test_custom_symbol_infer_shape():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    net = mx.sym.Custom(data, label, op_type="pysoftmax")
    arg_shapes, out_shapes, _ = net.infer_shape(data=(5, 7), label=(5,))
    assert out_shapes[0] == (5, 7)
    assert net.list_arguments() == ["data", "label"]


def test_custom_unregistered_raises():
    x = mx.nd.array(np.ones((2, 2), np.float32))
    with pytest.raises(mx.MXNetError):
        mx.nd.Custom(x, op_type="no_such_op")


@op_mod.register("intgather")
class IntGatherProp(op_mod.CustomOpProp):
    """Integer second input (indices) — its grad must be float0-dropped,
    not returned as int zeros (custom_vjp contract)."""

    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data", "idx"]

    def infer_shape(self, in_shape):
        return in_shape, [(in_shape[1][0], in_shape[0][1])], []

    def create_operator(self, ctx, shapes, dtypes):
        class _Op(op_mod.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                i = in_data[1].asnumpy().astype(np.int64)
                self.assign(out_data[0], req[0], mx.nd.array(x[i]))

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                g = np.zeros(in_data[0].shape, np.float32)
                i = in_data[1].asnumpy().astype(np.int64)
                np.add.at(g, i, out_grad[0].asnumpy())
                self.assign(in_grad[0], req[0], mx.nd.array(g))
                self.assign(in_grad[1], req[1],
                            mx.nd.zeros(in_data[1].shape))

        return _Op()


def test_custom_op_integer_input_grad():
    x = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = mx.nd.array(np.array([1, 3], dtype=np.int64), dtype="int64")
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, idx, op_type="intgather")
        loss = y.sum()
    loss.backward()
    expect = np.zeros((4, 3), np.float32)
    expect[[1, 3]] = 1.0
    np.testing.assert_allclose(x.grad.asnumpy(), expect)


def test_custom_op_inside_ctx_group_scope():
    with mx.AttrScope(ctx_group="dev1"):
        sym = mx.sym.Custom(mx.sym.Variable("data"), op_type="scalemul",
                            scale="3.0")
    ex = sym.bind(mx.cpu(0), args={"data": mx.nd.ones((2, 2))})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               3 * np.ones((2, 2)))
