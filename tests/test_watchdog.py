"""Hang/straggler watchdog (mxnet_tpu/resilience/watchdog.py): deadline
arming, stack-dump + post-mortem forensics, the chaos `hang` fault, the
coordination-KV heartbeat lane, and the fixed KVStore.num_dead_node.

The multi-process end-to-end drill (watchdog fires on a hung rank, gang
fail-fasts, relaunch resumes from checkpoint) lives in
tests/test_dist.py::test_dist_hang_watchdog_4proc; these are the
single-process seams.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel import audit
from mxnet_tpu.resilience import chaos, watchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    chaos.reset()
    watchdog.reset()
    audit.clear_collective_log()
    yield
    chaos.reset()
    watchdog.reset()
    audit.clear_collective_log()


# ---------------------------------------------------------------------------
# deadline watchdog
# ---------------------------------------------------------------------------

def test_watch_is_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_WATCHDOG", raising=False)
    monkeypatch.delenv("MXNET_TPU_WATCHDOG_STEP_TIMEOUT", raising=False)
    assert not watchdog.enabled()
    with watchdog.watch("idle", step=1):
        pass   # no monitor thread, no deadline
    assert watchdog._INSTANCE is None


def test_env_master_switch(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_WATCHDOG_STEP_TIMEOUT", "120")
    watchdog.reset()
    assert watchdog.enabled()
    monkeypatch.setenv("MXNET_TPU_WATCHDOG", "0")
    watchdog.reset()
    assert not watchdog.enabled()


def test_deadline_fires_and_postmortem_names_stuck_frame(tmp_path):
    """The headline contract: a step that stalls past its deadline gets a
    stack dump + post-mortem that names the stuck frame and carries the
    last-completed collective from the audit trail."""
    fired = []
    watchdog.configure(step_timeout=0.25, action="wait",
                       report_dir=str(tmp_path), poll=0.05,
                       on_expire=fired.append)
    audit.record_collective("psum", "unit.grad_allreduce", step=41)

    def innocent_looking_stall():
        time.sleep(0.7)

    with watchdog.watch("unit.step", step=42):
        innocent_looking_stall()

    assert fired and fired[0] is not None
    rep = json.load(open(fired[0]))
    assert rep["kind"] == "watchdog_postmortem"
    assert rep["tag"] == "unit.step" and rep["step"] == 42
    assert rep["action"] == "wait"
    funcs = [f["function"] for f in rep["stuck_frames"]]
    assert "innocent_looking_stall" in funcs, funcs
    assert rep["last_collective"]["tag"] == "unit.grad_allreduce"
    assert rep["last_collective"]["step"] == 41
    # the faulthandler all-thread dump exists and names the frame too
    stack = open(rep["stack_dump"]).read()
    assert "innocent_looking_stall" in stack
    assert "mxt-watchdog" not in funcs   # stuck thread, not the monitor


def test_disarm_in_time_means_no_report(tmp_path):
    fired = []
    watchdog.configure(step_timeout=0.5, action="wait",
                       report_dir=str(tmp_path), poll=0.05,
                       on_expire=fired.append)
    for step in range(5):
        with watchdog.watch("fast.step", step=step):
            time.sleep(0.01)
    time.sleep(0.3)
    assert not fired
    assert not list(tmp_path.glob("watchdog-postmortem-*"))


def test_collective_timeout_is_independent(tmp_path):
    fired = []
    watchdog.configure(step_timeout=30.0, collective_timeout=0.2,
                       action="wait", report_dir=str(tmp_path), poll=0.05,
                       on_expire=fired.append)
    with watchdog.watch("slow.collective", kind="collective"):
        time.sleep(0.5)
    assert fired, "collective deadline must fire independently of step's"
    rep = json.load(open(fired[0]))
    assert rep["tag"] == "slow.collective"


def test_abort_action_fail_fasts_subprocess(tmp_path):
    """action=abort must end the process with the configured exit code
    (so the launcher's restart path sees a dead gang, not a hang) after
    writing the post-mortem."""
    code = (
        "from mxnet_tpu.resilience import watchdog\n"
        "import time\n"
        "watchdog.configure(step_timeout=0.3, action='abort',\n"
        "                   report_dir=%r, poll=0.05, exit_code=43)\n"
        "with watchdog.watch('sub.step', step=1):\n"
        "    time.sleep(30)\n"
        "print('UNREACHABLE')\n" % str(tmp_path))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=240, cwd=REPO)
    assert r.returncode == 43, (r.returncode, r.stdout, r.stderr)
    assert "UNREACHABLE" not in r.stdout
    reports = list(tmp_path.glob("watchdog-postmortem-*.json"))
    assert reports, "abort must still leave the post-mortem behind"
    assert json.load(open(reports[0]))["tag"] == "sub.step"


def test_chaos_hang_fault_is_caught_by_watchdog(tmp_path):
    """The chaos drill wiring: a `hang` fault sleeping inside the armed
    region trips the watchdog, and the report's stuck frame IS the chaos
    sleep — detection proven end to end, no shortcut flag."""
    fired = []
    watchdog.configure(step_timeout=0.25, action="wait",
                       report_dir=str(tmp_path), poll=0.05,
                       on_expire=fired.append)
    with chaos.inject("hang", at_step=3, seconds=0.8):
        for step in (1, 2, 3):
            with watchdog.watch("drill.step", step=step):
                chaos.maybe_hang(step)
    assert len(fired) == 1
    rep = json.load(open(fired[0]))
    assert rep["step"] == 3
    assert "maybe_hang" in [f["function"] for f in rep["stuck_frames"]]


def test_trainer_step_is_armed(tmp_path):
    """ShardedTrainer.step runs under the watchdog: a hang inside the
    step produces a post-mortem tagged with the trainer step."""
    from mxnet_tpu.models.mlp import get_symbol
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    tr = ShardedTrainer(get_symbol(num_classes=4),
                        MeshSpec(make_mesh((4,), ("dp",))), lr=0.1)
    params, mom, aux = tr.init_state({"data": (16, 8),
                                      "softmax_label": (16,)})
    rs = np.random.RandomState(0)
    batch = {"data": rs.rand(16, 8).astype(np.float32),
             "softmax_label": rs.randint(0, 4, 16).astype(np.float32)}
    fired = []
    watchdog.configure(step_timeout=1.0, action="wait",
                       report_dir=str(tmp_path), poll=0.05,
                       on_expire=fired.append)
    with chaos.inject("hang", at_step=2, seconds=2.0):
        for _ in range(2):
            params, mom, aux, _ = tr.step(params, mom, aux, batch)
    assert fired
    rep = json.load(open(fired[0]))
    assert rep["tag"] == "ShardedTrainer.step" and rep["step"] == 2
    # the step's gradient psum landed in the runtime collective trail
    last = audit.last_collective()
    assert last["kind"] == "psum" and "ShardedTrainer" in last["tag"]


# ---------------------------------------------------------------------------
# heartbeat lane + num_dead_node
# ---------------------------------------------------------------------------

class FakeKVClient:
    """In-memory stand-in for the jax coordination-service client."""

    def __init__(self):
        self.kv = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        if not allow_overwrite and key in self.kv:
            raise RuntimeError("key exists: " + key)
        self.kv[key] = value

    def key_value_delete(self, key):
        self.kv.pop(key, None)

    def key_value_dir_get(self, prefix):
        return [(k, v) for k, v in self.kv.items() if k.startswith(prefix)]

    def blocking_key_value_get(self, key, timeout_ms):
        if key not in self.kv:
            raise TimeoutError(key)
        return self.kv[key]


def test_heartbeat_lane_noop_without_distributed():
    assert watchdog.heartbeat(1) is False
    assert watchdog.lane().peers() == {}
    assert watchdog.lane().num_dead(1) == 0
    assert watchdog.lane().straggler_report() is None


def test_heartbeat_lane_overwrites_one_key_per_rank():
    client = FakeKVClient()
    lane = watchdog.HeartbeatLane(client=client)
    for step in range(5):
        assert lane.beat(step, force=True)
    keys = [k for k in client.kv if k.startswith(lane.PREFIX)]
    assert len(keys) == 1, "heartbeats must overwrite, not leak keys"
    assert lane.peers()[0]["step"] == 4


def test_straggler_report_and_num_dead():
    client = FakeKVClient()
    lane = watchdog.HeartbeatLane(client=client)
    now = time.time()
    client.kv["mxt_hb/0"] = "10:%f" % now
    client.kv["mxt_hb/1"] = "9:%f" % now
    client.kv["mxt_hb/2"] = "4:%f" % (now - 120)   # stalled 2 min ago
    rep = lane.straggler_report(stale_sec=60)
    assert rep["fastest_rank"] == 0 and rep["slowest_rank"] == 2
    assert rep["lag_steps"] == 6
    assert rep["stale_ranks"] == [2]
    assert lane.num_dead(timeout_sec=60) == 1
    assert lane.num_dead(timeout_sec=600) == 0


def test_heartbeat_throttling():
    client = FakeKVClient()
    lane = watchdog.HeartbeatLane(client=client)
    lane._interval = 10.0
    assert lane.beat(1) is True
    assert lane.beat(2) is False          # throttled
    assert lane.beat(3, force=True) is True
    assert lane.peers()[0]["step"] == 3


def test_num_dead_node_bounded_and_leak_free(monkeypatch):
    """The kvstore.py:338 fix: the probe honors timeout_sec, reuses ONE
    key, and deletes it afterwards; stale heartbeat peers are counted."""
    from mxnet_tpu import kvstore as kvstore_mod
    from mxnet_tpu.parallel import Topology

    client = FakeKVClient()
    monkeypatch.setattr(
        "jax._src.distributed.global_state.client", client, raising=False)
    kv = kvstore_mod.KVStoreTPUDist.__new__(kvstore_mod.KVStoreTPUDist)
    kvstore_mod.KVStore.__init__(kv, "dist_sync")
    kv._topo = Topology(0, 4, 1, 4)

    assert kv.num_dead_node(timeout_sec=5) == 0
    assert not [k for k in client.kv if k.startswith("mxt_dead_probe")], \
        "probe keys must be deleted, not leaked"
    # probe repeatedly: still zero leftover keys (the old code leaked one
    # per probe, forever)
    for _ in range(3):
        kv.num_dead_node(timeout_sec=5)
    assert not [k for k in client.kv if k.startswith("mxt_dead_probe")]

    # a peer with a stale heartbeat counts as dead
    client.kv["mxt_hb/3"] = "7:%f" % (time.time() - 999)
    client.kv["mxt_hb/0"] = "9:%f" % time.time()
    client.kv["mxt_hb/1"] = "9:%f" % time.time()
    client.kv["mxt_hb/2"] = "9:%f" % time.time()
    assert kv.num_dead_node(timeout_sec=60) == 1

    # an unreachable coordinator counts as one dead node and stays
    # within the timeout budget (blocking get raises, probe catches)
    class DeadClient(FakeKVClient):
        def key_value_set(self, *a, **k):
            raise RuntimeError("coordinator gone")

    monkeypatch.setattr("jax._src.distributed.global_state.client",
                        DeadClient(), raising=False)
    assert kv.num_dead_node(timeout_sec=1) >= 1


# ---------------------------------------------------------------------------
# runtime collective trail (parallel/audit.py)
# ---------------------------------------------------------------------------

def test_collective_trail_records_and_bounds():
    for i in range(200):
        audit.record_collective("psum", "step", step=i)
    last = audit.last_collective()
    assert last["step"] == 199
    log = audit.collective_log()
    assert len(log) == 128, "trail must stay bounded"
    assert audit.collective_log(5)[-1]["step"] == 199


def test_postmortem_tool_renders_report(tmp_path, capsys):
    """tools/postmortem.py digests a real report end to end."""
    fired = []
    watchdog.configure(step_timeout=0.2, action="wait",
                       report_dir=str(tmp_path), poll=0.05,
                       on_expire=fired.append)
    audit.record_collective("barrier", "epoch_end", step=12)
    with watchdog.watch("tool.step", step=13):
        time.sleep(0.5)
    assert fired
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import postmortem
        rc = postmortem.main([str(tmp_path)])
    finally:
        sys.path.pop(0)
    assert rc == 0
    out = capsys.readouterr().out
    assert "POST-MORTEM" in out
    assert "tool.step" in out
    assert "epoch_end" in out
    assert "STUCK FRAMES" in out
