"""Shape checks for every symbolic model family (reference keeps
example/image-classification/symbols/ working via the train scripts;
here each builder is pinned directly)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models


@pytest.mark.parametrize("family,kwargs", [
    ("resnet", dict(num_layers=50)),
    ("resnet_v1", dict(num_layers=18)),
    ("resnext", dict(num_layers=50, cardinality=4, bottleneck_width=4)),
    ("mobilenet", dict(multiplier=0.25)),
    ("googlenet", {}),
    ("inception_v4", {}),
    ("alexnet", {}),
    ("vgg", dict(num_layers=11)),
])
def test_symbol_family_output_shape(family, kwargs):
    net = getattr(models, family).get_symbol(num_classes=13, **kwargs)
    hw = 224
    _, out_shapes, _ = net.infer_shape(data=(2, 3, hw, hw),
                                       softmax_label=(2,))
    assert out_shapes[0] == (2, 13), (family, out_shapes)


def test_small_families_forward():
    """The cheap families also execute end-to-end."""
    for family, kwargs, hw in [("mobilenet", dict(multiplier=0.25), 64),
                               ("resnet_v1", dict(num_layers=18), 64)]:
        net = getattr(models, family).get_symbol(num_classes=5, **kwargs)
        ex = net.simple_bind(mx.cpu(), data=(2, 3, hw, hw),
                             softmax_label=(2,))
        for name, arr in ex.arg_dict.items():
            if name not in ("data", "softmax_label"):
                arr[:] = np.random.RandomState(0).normal(
                    0, 0.05, arr.shape).astype(np.float32)
        ex.arg_dict["data"][:] = np.random.rand(2, 3, hw, hw)
        ex.arg_dict["softmax_label"][:] = np.array([1.0, 3.0])
        out = ex.forward()[0].asnumpy()
        assert out.shape == (2, 5)
        np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)


def test_resnet_nhwc_layout_matches_nchw():
    """The channels-last op path (Convolution/Pooling layout=NHWC,
    BatchNorm axis=3) must reproduce the NCHW network exactly given
    transposed weights.  Compared at the PRE-softmax logits (softmax on
    randomly-scaled logits saturates to one-hot and would hide conv
    differences)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.models.resnet import get_symbol
    rs = np.random.RandomState(0)
    x = rs.rand(2, 3, 32, 32).astype(np.float32)
    logits_c = get_symbol(num_classes=10, num_layers=18,
                          image_shape="3,32,32") \
        .get_internals()["fc1_output"]
    logits_h = get_symbol(num_classes=10, num_layers=18,
                          image_shape="3,32,32", layout="NHWC") \
        .get_internals()["fc1_output"]
    ex_c = logits_c.simple_bind(mx.cpu(), data=(2, 3, 32, 32))
    ex_h = logits_h.simple_bind(mx.cpu(), data=(2, 32, 32, 3))
    for n, a in ex_c.arg_dict.items():
        a[:] = mx.nd.array(rs.normal(0, 0.05, a.shape).astype(np.float32))
    for n, a in ex_h.arg_dict.items():
        if n == "data":
            continue
        src = ex_c.arg_dict[n].asnumpy()
        # every 4-d arg is a conv weight: OIHW -> OHWI unconditionally
        # (shape equality is ambiguous for conv0's (64,3,3,3))
        if src.ndim == 4:
            src = src.transpose(0, 2, 3, 1)
        a[:] = mx.nd.array(src.reshape(a.shape))
    ex_c.arg_dict["data"][:] = mx.nd.array(x)
    ex_h.arg_dict["data"][:] = mx.nd.array(x.transpose(0, 2, 3, 1))
    ex_c.forward(is_train=False)
    ex_h.forward(is_train=False)
    a, b = ex_c.outputs[0].asnumpy(), ex_h.outputs[0].asnumpy()
    assert np.abs(a).max() > 1e-3, "logits degenerate; test would be vacuous"
    np.testing.assert_allclose(a, b, atol=2e-4)
