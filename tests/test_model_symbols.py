"""Shape checks for every symbolic model family (reference keeps
example/image-classification/symbols/ working via the train scripts;
here each builder is pinned directly)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models


@pytest.mark.parametrize("family,kwargs", [
    ("resnet", dict(num_layers=50)),
    ("resnet_v1", dict(num_layers=18)),
    ("resnext", dict(num_layers=50, cardinality=4, bottleneck_width=4)),
    ("mobilenet", dict(multiplier=0.25)),
    ("googlenet", {}),
    ("inception_v4", {}),
    ("alexnet", {}),
    ("vgg", dict(num_layers=11)),
])
def test_symbol_family_output_shape(family, kwargs):
    net = getattr(models, family).get_symbol(num_classes=13, **kwargs)
    hw = 224
    _, out_shapes, _ = net.infer_shape(data=(2, 3, hw, hw),
                                       softmax_label=(2,))
    assert out_shapes[0] == (2, 13), (family, out_shapes)


def test_small_families_forward():
    """The cheap families also execute end-to-end."""
    for family, kwargs, hw in [("mobilenet", dict(multiplier=0.25), 64),
                               ("resnet_v1", dict(num_layers=18), 64)]:
        net = getattr(models, family).get_symbol(num_classes=5, **kwargs)
        ex = net.simple_bind(mx.cpu(), data=(2, 3, hw, hw),
                             softmax_label=(2,))
        for name, arr in ex.arg_dict.items():
            if name not in ("data", "softmax_label"):
                arr[:] = np.random.RandomState(0).normal(
                    0, 0.05, arr.shape).astype(np.float32)
        ex.arg_dict["data"][:] = np.random.rand(2, 3, hw, hw)
        ex.arg_dict["softmax_label"][:] = np.array([1.0, 3.0])
        out = ex.forward()[0].asnumpy()
        assert out.shape == (2, 5)
        np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)
