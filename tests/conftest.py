"""Test config: force XLA:CPU with 8 virtual devices so multi-device and
mesh/sharding paths run without TPU hardware (SURVEY.md §4 — the analog of
the reference's local-multiprocess dist testing trick).

NOTE: in this environment the JAX_PLATFORMS env var is ignored (the axon
TPU plugin wins), so the platform is forced via jax.config before any
device is touched.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
