"""Test config: force XLA:CPU with 8 virtual devices so multi-device and
mesh/sharding paths run without TPU hardware (SURVEY.md §4 — the analog of
the reference's local-multiprocess dist testing trick).

NOTE: in this environment the JAX_PLATFORMS env var is ignored (the axon
TPU plugin wins), so the platform is forced via jax.config before any
device is touched.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Make skips LOUD: list every skipped test and its reason so a CI
    run records exactly which capabilities (toolchain, TPU-only paths)
    went unexercised (VERDICT r3 weak-item 7)."""
    skipped = terminalreporter.stats.get("skipped", [])
    if not skipped:
        return
    tr = terminalreporter
    tr.section("skipped capabilities (%d)" % len(skipped))
    seen = set()
    for rep in skipped:
        reason = rep.longrepr[-1] if isinstance(rep.longrepr, tuple) \
            else str(rep.longrepr)
        line = "%s — %s" % (rep.nodeid, reason)
        if line not in seen:
            seen.add(line)
            tr.write_line(line)
