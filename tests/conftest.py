"""Test config: force XLA:CPU with 8 virtual devices so multi-device and
mesh/sharding paths run without TPU hardware (SURVEY.md §4 — the analog of
the reference's local-multiprocess dist testing trick).

NOTE: in this environment the JAX_PLATFORMS env var is ignored (the axon
TPU plugin wins), so the platform is forced via jax.config before any
device is touched.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Older jax (< 0.4.34) has no jax_num_cpu_devices config option; there the
# virtual-device count must be forced through XLA_FLAGS BEFORE the backend
# initializes.  Set it pre-import, and on the old path immediately
# initialize the backend and RESTORE the env var — test_dist's worker
# subprocesses inherit os.environ, and 8 virtual devices per rank breaks
# the 4-rank gloo topology they self-configure.
_prev_xla_flags = os.environ.get("XLA_FLAGS")
if "--xla_force_host_platform_device_count" not in (_prev_xla_flags or ""):
    os.environ["XLA_FLAGS"] = ((_prev_xla_flags or "") +
                               " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:   # pre-0.4.34 jax: XLA_FLAGS above already did it
    jax.devices()        # force CPU client init while the flag is active
    if _prev_xla_flags is None:
        del os.environ["XLA_FLAGS"]
    else:
        os.environ["XLA_FLAGS"] = _prev_xla_flags


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Make skips LOUD: list every skipped test and its reason so a CI
    run records exactly which capabilities (toolchain, TPU-only paths)
    went unexercised (VERDICT r3 weak-item 7)."""
    skipped = terminalreporter.stats.get("skipped", [])
    if not skipped:
        return
    tr = terminalreporter
    tr.section("skipped capabilities (%d)" % len(skipped))
    seen = set()
    for rep in skipped:
        reason = rep.longrepr[-1] if isinstance(rep.longrepr, tuple) \
            else str(rep.longrepr)
        line = "%s — %s" % (rep.nodeid, reason)
        if line not in seen:
            seen.add(line)
            tr.write_line(line)
