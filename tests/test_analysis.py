"""Static-analysis subsystem tests (ISSUE 3): graphcheck jaxpr rules,
srclint fixture coverage, pre-flight wiring, CLI gating, and the repo
self-lint that keeps the shipped tree at zero gate-severity findings.
"""
import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

import mxnet_tpu as mx
from mxnet_tpu.analysis import (Finding, PreflightError, Report, graphcheck,
                                preflight, srclint)
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")

# pre-pvary jax cannot prove replication of some carries
_COMPAT = {} if hasattr(lax, "pvary") else {"check_rep": False}


def _mesh(n=2, axis="dp"):
    return make_mesh((n,), (axis,))


def _smap(fn, mesh, in_specs, out_specs):
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **_COMPAT)


def _rules(report):
    return sorted({f.rule for f in report})


# ---------------------------------------------------------------------------
# report model
# ---------------------------------------------------------------------------

def test_report_model_roundtrip(tmp_path):
    rep = Report("graphcheck", "unit")
    rep.add("GC102", "error", "boom", location="x:1", fix_hint="fix it")
    rep.add("GC201", "warning", "meh")
    rep.add("GC000", "info", "fyi")
    assert len(rep.errors()) == 1 and len(rep.warnings()) == 1
    assert [f.rule for f in rep.sorted()][0] == "GC102"
    assert len(rep.at_or_above("warning")) == 2
    path = rep.save(str(tmp_path / "r.json"))
    back = Report.load(path)
    assert back.counts() == rep.counts()
    assert back.findings[0].fix_hint == "fix it"
    text = rep.pretty()
    assert "GC102" in text and "ERROR" in text


def test_report_rejects_unknown_severity():
    with pytest.raises(ValueError):
        Finding("X", "fatal", "nope")


# ---------------------------------------------------------------------------
# graphcheck: collective-schedule extraction
# ---------------------------------------------------------------------------

def test_collect_collectives_scan_cond_nesting():
    mesh = _mesh()

    def nested(x):
        def body(c, t):
            c = lax.ppermute(c, "dp", [(0, 1), (1, 0)])
            c = lax.cond(t > 0,
                         lambda v: lax.psum(v, "dp"),
                         lambda v: lax.psum(v, "dp"), c)
            return c, t

        c, _ = lax.scan(body, x, jnp.arange(3))
        return c

    closed = jax.make_jaxpr(_smap(nested, mesh, P("dp"), P("dp")))(
        jnp.ones((4, 2)))
    events = graphcheck.collect_collectives(closed)
    assert [e.prim for e in events] == ["ppermute", "psum", "psum"]
    assert all(e.axes == ("dp",) for e in events)
    # paths name the nesting: shard_map -> scan body -> cond branches
    assert "scan" in events[0].path
    assert "branches[0]" in events[1].path
    assert "branches[1]" in events[2].path
    # symmetric cond: no divergence findings
    rep = graphcheck.check_jaxpr(closed, mesh=mesh)
    assert rep.errors() == []


def test_cond_divergent_schedule_is_flagged():
    """Acceptance criterion: the chaos-'hang'-style asymmetric program —
    a collective only SOME ranks reach — is rejected statically, where
    PR-2's watchdog could only catch the resulting live hang."""
    mesh = _mesh()

    def asymmetric(x):
        # data-dependent predicate: ranks can disagree, and then the
        # psum-taking branch blocks forever waiting for the others
        return lax.cond(x.sum() > 0,
                        lambda v: lax.psum(v, "dp"),
                        lambda v: v, x)

    rep = graphcheck.check_fn(_smap(asymmetric, mesh, P("dp"), P("dp")),
                              jnp.ones((4, 2)), mesh=mesh)
    errs = [f for f in rep.errors() if f.rule == "GC102"]
    assert len(errs) == 1
    assert "deadlock" in errs[0].message


def test_axis_name_mismatch_flagged():
    mesh = _mesh()

    def f(x):
        return lax.psum(x, "dp")

    closed = jax.make_jaxpr(_smap(f, mesh, P("dp"), P("dp")))(jnp.ones(4))
    # the program reduces over 'dp' but the deployment mesh only has 'tp'
    rep = graphcheck.check_jaxpr(closed, mesh={"tp": 2})
    assert [f.rule for f in rep.errors()] == ["GC101"]
    # and is clean against its own mesh
    assert graphcheck.check_jaxpr(closed, mesh=mesh).errors() == []


def test_ppermute_bad_perm_flagged():
    mesh = _mesh()

    def bad(x):
        return lax.ppermute(x, "dp", [(0, 0), (1, 0)])

    rep = graphcheck.check_fn(_smap(bad, mesh, P("dp"), P("dp")),
                              jnp.ones(4), mesh=mesh)
    assert [f.rule for f in rep.errors()] == ["GC104"]

    def good(x):
        return lax.ppermute(x, "dp", [(0, 1), (1, 0)])

    rep2 = graphcheck.check_fn(_smap(good, mesh, P("dp"), P("dp")),
                               jnp.ones(4), mesh=mesh)
    assert rep2.errors() == []


def test_ppermute_rank_out_of_range_flagged():
    mesh = _mesh()

    def oob(x):
        return lax.ppermute(x, "dp", [(0, 1), (1, 3)])

    rep = graphcheck.check_fn(_smap(oob, mesh, P("dp"), P("dp")),
                              jnp.ones(4), mesh=mesh)
    assert any(f.rule == "GC104" and "outside axis" in f.message
               for f in rep.errors())


def test_axis_groups_asymmetric_flagged():
    mesh = _mesh(4)

    def grouped(x):
        return lax.psum(x, "dp", axis_index_groups=[[0, 1], [2]])

    rep = graphcheck.check_fn(_smap(grouped, mesh, P("dp"), P("dp")),
                              jnp.ones(8), mesh=mesh)
    assert any(f.rule == "GC105" for f in rep.errors())


def test_while_loop_collective_warns():
    mesh = _mesh()

    def w(x):
        return lax.while_loop(lambda c: c.sum() < 10,
                              lambda c: lax.psum(c, "dp") + 1, x)

    rep = graphcheck.check_fn(_smap(w, mesh, P("dp"), P("dp")),
                              jnp.ones(4), mesh=mesh)
    assert [f.rule for f in rep.warnings()] == ["GC103"]
    assert rep.errors() == []


# ---------------------------------------------------------------------------
# graphcheck: dtype / sharding / recompile rules
# ---------------------------------------------------------------------------

def test_bf16_upcast_into_dot_flagged():
    def up(x):
        y = x.astype(jnp.float32)
        return y @ y.T

    rep = graphcheck.check_fn(up, jnp.ones((4, 4), jnp.bfloat16))
    assert any(f.rule == "GC301" for f in rep.warnings())

    def accum(x):
        # the INTENDED pattern: bf16 operands, f32 accumulation
        return jax.lax.dot(x, x.T, precision=None,
                           preferred_element_type=jnp.float32)

    rep2 = graphcheck.check_fn(accum, jnp.ones((4, 4), jnp.bfloat16))
    assert not any(f.rule == "GC301" for f in rep2)


def test_weak_type_input_flagged():
    rep = graphcheck.check_fn(lambda s, x: x * s, 1.0, jnp.ones(3))
    assert any(f.rule == "GC302" for f in rep.warnings())
    rep2 = graphcheck.check_fn(lambda s, x: x * s,
                               jnp.asarray(1.0, jnp.float32), jnp.ones(3))
    assert not any(f.rule == "GC302" for f in rep2)


def test_reshard_chain_flagged():
    mesh = _mesh()

    def rs(x):
        y = lax.with_sharding_constraint(x, NamedSharding(mesh, P("dp")))
        return lax.with_sharding_constraint(y, NamedSharding(mesh, P(None)))

    rep = graphcheck.check_fn(rs, jnp.ones(4))
    assert any(f.rule == "GC203" for f in rep.warnings())


def test_check_replication_flags_large_replicated_on_model_axis():
    mesh = make_mesh((2, 2), ("dp", "tp")) if jax.device_count() >= 4 \
        else make_mesh((1, 2), ("dp", "tp"))
    big = (2048, 2048)          # 16 MB f32 > default 8 MB threshold
    entries = [
        ("big_replicated", big, 4, NamedSharding(mesh, P())),
        ("big_sharded", big, 4, NamedSharding(mesh, P("tp", None))),
        ("small_replicated", (8, 8), 4, NamedSharding(mesh, P())),
    ]
    rep = graphcheck.check_replication(entries, mesh, model_axes=("tp",))
    assert [f.location for f in rep.warnings()] == ["big_replicated"]
    # pure-dp mesh: replication is the design, nothing fires
    rep2 = graphcheck.check_replication(entries, _mesh(), model_axes=())
    assert len(rep2) == 0


def test_check_donation():
    assert len(graphcheck.check_donation(True, "step")) == 0
    rep = graphcheck.check_donation(False, "step")
    assert [f.rule for f in rep.warnings()] == ["GC202"]


def test_check_registry_clean_and_seeded_gap():
    from mxnet_tpu.base import Param
    from mxnet_tpu.ops import registry as reg
    # the shipped registry is clean — every per-step param is dynamic
    assert len(graphcheck.check_registry()) == 0
    # seed a gap: an optimizer-style op whose lr is a static jit key
    name = "_ta_bad_update"

    @reg.register(name, inputs=("weight", "grad"),
                  params=dict(lr=Param(float, 0.1)))
    def _bad_update(attrs, w, g):
        return w - attrs.lr * g

    try:
        rep = graphcheck.check_registry()
        assert any(f.rule == "GC402" and name in f.message
                   for f in rep.warnings())
    finally:
        reg._REGISTRY.pop(name)


def test_check_symbol_static_float_attr_seeded():
    from mxnet_tpu.base import Param
    from mxnet_tpu.ops import registry as reg
    name = "_ta_bad_symop"

    @reg.register(name, inputs=("data",),
                  params=dict(lr=Param(float, 0.1)))
    def _bad_symop(attrs, x):
        return x * attrs.lr

    try:
        v = mx.sym.Variable("data")
        s = mx.sym.create(name, [v], {"lr": 0.05, "name": "badnode"})
        rep = graphcheck.check_symbol(s)
        assert any(f.rule == "GC401" for f in rep.warnings())
        # the shipped optimizer ops keep lr dynamic -> clean
        w = mx.sym.Variable("w")
        g = mx.sym.Variable("g")
        ok = mx.sym.create("sgd_update", [w, g], {"lr": 0.05})
        assert len(graphcheck.check_symbol(ok)) == 0
    finally:
        reg._REGISTRY.pop(name)


# ---------------------------------------------------------------------------
# pre-flight wiring
# ---------------------------------------------------------------------------

def _toy_trainer(n_dev=2):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    spec = MeshSpec(_mesh(n_dev))
    trainer = ShardedTrainer(net, spec, lr=0.1)
    shapes = {"data": (8, 32), "softmax_label": (8,)}
    return trainer, trainer.init_state(shapes)


def test_trainer_preflight_writes_report_and_passes(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT", "1")
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT_DIR", str(tmp_path))
    trainer, (params, mom, aux) = _toy_trainer()
    batch = {"data": np.random.rand(8, 32).astype(np.float32),
             "softmax_label": np.zeros(8, np.float32)}
    params, mom, aux, loss = trainer.step(params, mom, aux, batch)
    assert np.isfinite(float(loss))
    reports = [p for p in os.listdir(str(tmp_path))
               if p.startswith("preflight-trainer") and p.endswith(".json")]
    assert len(reports) == 1
    rep = Report.load(str(tmp_path / reports[0]))
    assert rep.errors() == []          # the shipped step program is clean
    assert "jaxpr" in rep.artifacts
    assert os.path.isfile(rep.artifacts["jaxpr"])
    # preflight runs ONCE per trainer
    trainer.step(params, mom, aux, batch)
    assert len([p for p in os.listdir(str(tmp_path))
                if p.endswith(".json")]) == 1


def test_trainer_preflight_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("MXNET_TPU_PREFLIGHT", raising=False)
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT_DIR", str(tmp_path))
    trainer, (params, mom, aux) = _toy_trainer()
    batch = {"data": np.zeros((8, 32), np.float32),
             "softmax_label": np.zeros(8, np.float32)}
    trainer.step(params, mom, aux, batch)
    assert os.listdir(str(tmp_path)) == []


def test_module_preflight_writes_report(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT", "1")
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT_DIR", str(tmp_path))
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    from mxnet_tpu.module import Module
    mod = Module(net, context=[mx.cpu()])
    mod.bind(data_shapes=[("data", (4, 16))],
             label_shapes=[("softmax_label", (4,))])
    reports = [p for p in os.listdir(str(tmp_path))
               if p.startswith("preflight-module") and p.endswith(".json")]
    assert len(reports) == 1
    assert Report.load(str(tmp_path / reports[0])).errors() == []


def test_preflight_aborts_on_error_findings(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT_DIR", str(tmp_path))
    monkeypatch.delenv("MXNET_TPU_PREFLIGHT_ACTION", raising=False)
    bad = Report("graphcheck", "seeded")
    bad.add("GC102", "error", "divergent schedule")
    with pytest.raises(PreflightError) as ei:
        preflight._finish(bad, "seeded")
    assert "GC102" in str(ei.value)
    assert ei.value.report is bad
    # the report is persisted even though we aborted
    assert any(p.endswith(".json") for p in os.listdir(str(tmp_path)))
    # action=warn downgrades to logging
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT_ACTION", "warn")
    preflight._finish(bad, "seeded2")


def test_preflight_catches_seeded_divergence_end_to_end(tmp_path,
                                                        monkeypatch):
    """Full loop: an asymmetric program goes through the same
    check+report+abort path the trainer pre-flight uses."""
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT_DIR", str(tmp_path))
    mesh = _mesh()

    def asymmetric(x):
        return lax.cond(x.sum() > 0,
                        lambda v: lax.psum(v, "dp"),
                        lambda v: v, x)

    rep = graphcheck.check_fn(_smap(asymmetric, mesh, P("dp"), P("dp")),
                              jnp.ones((4, 2)), mesh=mesh,
                              target="seeded-hang")
    with pytest.raises(PreflightError):
        preflight._finish(rep, "seeded-hang")


# ---------------------------------------------------------------------------
# srclint
# ---------------------------------------------------------------------------

def test_srclint_fixture_catches_every_rule():
    rep = srclint.lint_file(os.path.join(FIXTURES,
                                         "srclint_violations.py"),
                            in_library=False)
    by_rule = {}
    for f in rep:
        by_rule.setdefault(f.rule, []).append(f)
    assert set(by_rule) == {"SL101", "SL102", "SL103", "SL104", "SL105"}
    assert len(by_rule["SL101"]) == 2      # decorator + combinator paths
    assert len(by_rule["SL102"]) == 2      # decorator + collective-body
    assert len(by_rule["SL103"]) == 2      # .get + subscript
    assert len(by_rule["SL104"]) == 2      # random + np.random
    assert len(by_rule["SL105"]) == 1
    # the suppressed lambda produced nothing (checked by exact counts)


def test_srclint_library_rule_sl106():
    rep = srclint.lint_file(
        os.path.join(FIXTURES, "srclint_library_violations.py"),
        in_library=True)
    assert [f.rule for f in rep] == ["SL106"]
    assert rep.findings[0].extra["function"] == "unarmed_entry"
    # outside the library the rule stays quiet
    rep2 = srclint.lint_file(
        os.path.join(FIXTURES, "srclint_library_violations.py"),
        in_library=False)
    assert len(rep2) == 0


def test_srclint_sl107_manual_timing_in_library():
    """SL107 (info): a host-side library function hand-rolling start/stop
    timing should use a telemetry span; deadline arithmetic and
    span-based timing stay quiet."""
    src = (
        "import time\n"
        "from mxnet_tpu import telemetry\n"
        "def hand_rolled(work):\n"
        "    t0 = time.perf_counter()\n"
        "    work()\n"
        "    return time.perf_counter() - t0\n"
        "def deadline_math(budget):\n"
        "    deadline = time.monotonic() + budget\n"
        "    while time.monotonic() < deadline:\n"
        "        pass\n"
        "    return deadline - budget\n"
        "def span_based(work):\n"
        "    with telemetry.span('x', timed=True) as sp:\n"
        "        work()\n"
        "    return sp.duration\n"
    )
    rep = srclint.lint_source(src, "mxnet_tpu/inline_lib.py",
                              in_library=True)
    assert [f.rule for f in rep] == ["SL107"]
    assert rep.findings[0].extra["function"] == "hand_rolled"
    assert rep.findings[0].severity == "info"
    # host-only: app/tools code outside the library is not flagged
    assert len(srclint.lint_source(src, "tools/inline_app.py",
                                   in_library=False)) == 0
    # the instrumentation layer itself is exempt
    assert len(srclint.lint_source(
        src, "mxnet_tpu/telemetry/inline.py", in_library=True)) == 0
    # a TRACED function with the same pattern is SL102's territory
    traced = (
        "import time, jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    t0 = time.perf_counter()\n"
        "    return x * (time.perf_counter() - t0)\n"
    )
    rep2 = srclint.lint_source(traced, "mxnet_tpu/inline2.py",
                               in_library=True)
    assert set(f.rule for f in rep2) == {"SL102"}


def test_srclint_suppression_scopes():
    src = (
        "import time, jax\n"
        "@jax.jit\n"
        "def f(x):  # tpulint: disable=SL102\n"
        "    return x + time.time()\n"
        "@jax.jit\n"
        "def g(x):\n"
        "    return x + time.time()  # tpulint: disable=all\n"
        "@jax.jit\n"
        "def h(x):\n"
        "    return x + time.time()\n"
    )
    rep = srclint.lint_source(src, "inline.py")
    assert [f.extra["function"] for f in rep] == ["h"]
    filewide = "# tpulint: disable-file=SL102\n" + src
    assert len(srclint.lint_source(filewide, "inline2.py")) == 0


def test_srclint_sl108_sync_iter_fixture():
    """SL108 (warning): training loops iterating a synchronous DataIter
    directly are flagged; prefetch-wrapped, eval-only, and suppressed
    loops stay quiet."""
    rep = srclint.lint_file(os.path.join(FIXTURES, "srclint_sync_iter.py"),
                            in_library=False)
    assert [f.rule for f in rep] == ["SL108", "SL108"]
    assert sorted(f.extra["function"] for f in rep) == [
        "bad_module_loop", "bad_trainer_loop"]
    assert all(f.severity == "warning" for f in rep)
    assert "PrefetchingIter" in rep.findings[0].fix_hint


def test_srclint_sl108_module_scope_and_wrapping():
    """SL108 fires at module scope too, and any rebind through
    PrefetchingIter — even under a different name — clears the var."""
    src = (
        "from mxnet_tpu.io import NDArrayIter, PrefetchingIter\n"
        "it = NDArrayIter(x, y, batch_size=4)\n"
        "for batch in it:\n"
        "    trainer.step(state, batch)\n"
    )
    rep = srclint.lint_source(src, "inline_sync.py")
    assert [f.rule for f in rep] == ["SL108"]
    assert not rep.findings[0].extra.get("function")   # module scope
    wrapped = (
        "from mxnet_tpu.io import NDArrayIter, PrefetchingIter\n"
        "raw = NDArrayIter(x, y, batch_size=4)\n"
        "it = PrefetchingIter(raw)\n"
        "for batch in raw:\n"
        "    trainer.step(state, batch)\n"
    )
    # the raw handle was consumed by a prefetch wrapper: don't double-flag
    assert len(srclint.lint_source(wrapped, "inline_wrapped.py")) == 0


def test_srclint_host_helpers_not_false_flagged():
    """A helper CALLED from a traced fn runs at trace time with static
    args: np-on-param must not fire (SL101), but frozen clocks must
    (SL102)."""
    src = (
        "import time\n"
        "import numpy as np\n"
        "import jax\n"
        "def shape_helper(shape):\n"
        "    return int(np.prod(shape))\n"
        "def clock_helper():\n"
        "    return time.time()\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    n = shape_helper(x.shape)\n"
        "    return x.reshape(n) + clock_helper()\n"
    )
    rep = srclint.lint_source(src, "inline3.py")
    assert [f.rule for f in rep] == ["SL102"]
    assert rep.findings[0].extra["function"] == "clock_helper"


def test_repo_self_lint_zero_gate_findings():
    """The shipped tree must stay clean at the CI gate severity
    (warning+): new ERROR findings fail this test outright, and any new
    warning needs an explicit suppression with a justification."""
    rep = srclint.lint_paths([os.path.join(REPO, "mxnet_tpu"),
                              os.path.join(REPO, "example"),
                              os.path.join(REPO, "tools")])
    gated = rep.at_or_above("warning")
    assert gated == [], "repo self-lint regressions:\n%s" % "\n".join(
        "%s %s %s: %s" % (f.severity.upper(), f.rule, f.location,
                          f.message) for f in gated)


def test_repo_graphcheck_entry_points_clean():
    """Graph-level self-lint: the trainer step program traces clean."""
    trainer, (params, mom, aux) = _toy_trainer()
    inputs = {"data": jax.ShapeDtypeStruct((8, 32), jnp.float32),
              "softmax_label": jax.ShapeDtypeStruct((8,), jnp.float32)}
    rep, closed = graphcheck.check_trainer(trainer, params, mom, aux,
                                           inputs)
    assert rep.errors() == [], [f.message for f in rep.errors()]
    # the trace is real: the step program contains eqns
    assert len(closed.jaxpr.eqns) > 0


# ---------------------------------------------------------------------------
# CLI + hlo_diff integration
# ---------------------------------------------------------------------------

def test_tpulint_cli_json_gates_on_findings(tmp_path, capsys):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import tpulint
    finally:
        sys.path.pop(0)
    out = str(tmp_path / "report.json")
    rc = tpulint.main([os.path.join(FIXTURES, "srclint_violations.py"),
                       "--format", "json", "--out", out])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert data["counts"]["error"] >= 5
    assert os.path.isfile(out)
    # gate at error-severity only: fixture still fails (it has errors)
    assert tpulint.main([os.path.join(FIXTURES, "srclint_violations.py"),
                         "--format", "json", "--severity", "error"]) == 1
    capsys.readouterr()
    # the shipped tree passes the default gate
    rc_clean = tpulint.main([os.path.join(REPO, "mxnet_tpu"),
                             os.path.join(REPO, "example"),
                             "--format", "json"])
    capsys.readouterr()
    assert rc_clean == 0


def test_tpulint_predict_self_run(tmp_path, capsys, monkeypatch):
    """``tpulint --predict`` compiles the built-in entry points, prints a
    budget for every one, writes predict-*.json artifacts, and stays
    clean (rc 0) over a lint-clean target."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import tpulint
    finally:
        sys.path.pop(0)
    monkeypatch.setenv("MXNET_TPU_CALIBRATION_CACHE",
                       str(tmp_path / "calibration.json"))
    monkeypatch.setenv("MXNET_TPU_ATTRIBUTION_DIR", str(tmp_path / "rep"))
    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    rc = tpulint.main(["--predict", str(clean), "--format", "json"])
    out = capsys.readouterr().out
    assert rc == 0, out
    doc = json.loads(out)
    programs = {r["program"] for r in doc["predict"]}
    assert {"trainer", "ring", "moe", "pipeline", "recommender",
            "decode"} <= programs
    for r in doc["predict"]:
        assert r["budget"]["step_time_s"] > 0
        assert r["budget"]["peak_hbm_bytes"] > 0
        assert r["basis"]["achievable_fraction"] > 0
        assert not r["over_budget"]
    # the calibration store was fitted from the committed ledger
    assert os.path.isfile(str(tmp_path / "calibration.json"))
    written = [f for f in os.listdir(str(tmp_path / "rep"))
               if f.startswith("predict-")]
    assert len(written) >= 6


def test_hlo_diff_from_graphcheck_report(tmp_path, capsys, monkeypatch):
    hlo_a = tmp_path / "a.hlo.txt"
    hlo_a.write_text(
        "  %x = f32[4]{0} add(f32[4]{0} %a, f32[4]{0} %b)\n"
        "  %y = f32[4]{0} all-reduce(f32[4]{0} %x)\n")
    hlo_b = tmp_path / "b.hlo.txt"
    hlo_b.write_text("  %x = f32[4]{0} add(f32[4]{0} %a, f32[4]{0} %b)\n")
    rep = Report("graphcheck", "unit")
    rep.artifacts["hlo"] = str(hlo_a)
    rep_path = rep.save(str(tmp_path / "rep.json"))

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import hlo_diff
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(sys, "argv",
                        ["hlo_diff.py", "--from-graphcheck", rep_path,
                         "--against", str(hlo_b)])
    hlo_diff.main()
    out = capsys.readouterr().out
    assert "all-reduce" in out and "+1" in out
    # single-report mode prints the histogram
    monkeypatch.setattr(sys, "argv",
                        ["hlo_diff.py", "--from-graphcheck", rep_path])
    hlo_diff.main()
    assert "all-reduce" in capsys.readouterr().out
    # a report without an HLO artifact explains the knob
    bare = Report("graphcheck", "unit2").save(str(tmp_path / "bare.json"))
    monkeypatch.setattr(sys, "argv",
                        ["hlo_diff.py", "--from-graphcheck", bare])
    with pytest.raises(SystemExit) as ei:
        hlo_diff.main()
    assert "MXNET_TPU_PREFLIGHT_HLO" in str(ei.value)


# ---------------------------------------------------------------------------
# satellite regressions: the true positives the analyzer surfaced
# ---------------------------------------------------------------------------

def test_fused_sgd_momentum_buffers_are_donated():
    """GC202 true positive: the fused SGD whole-step update now donates
    the momentum buffers (update_batch rebinds them immediately), so the
    update no longer holds old+new momentum for the whole model live."""
    from mxnet_tpu.optimizer import _fused_sgd_program
    run = _fused_sgd_program(momentum_on=True, clip=0.0)
    ws = (jnp.ones(4),)
    gs = (jnp.ones(4),)
    ms = (jnp.zeros(4),)
    low = run.lower(ws, gs, ms, (0.1,), (0.0,), 1.0, 0.9).as_text()
    assert "tf.aliasing_output" in low, \
        "momentum donation regressed (GC202)"
    # math unchanged: one step of sgd_mom
    new_ws, new_ms = run(ws, gs, ms, (0.1,), (0.0,), 1.0, 0.9)
    np.testing.assert_allclose(np.asarray(new_ms[0]), -0.1 * np.ones(4),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_ws[0]), 0.9 * np.ones(4),
                               rtol=1e-6)


@pytest.mark.skipif(jax.device_count() < 2, reason="needs 2 devices")
def test_audit_trail_covers_every_collective_kind():
    """Audit-trail true positive: pipeline/moe record EVERY collective
    kind their traced schedule contains (graphcheck extraction is the
    oracle), so a hang post-mortem's 'last completed collective' cannot
    name a kind the program never finished."""
    from mxnet_tpu.parallel import audit
    from mxnet_tpu.parallel.pipeline import pipeline_apply

    audit.clear_collective_log()
    mesh = _mesh(2, "pp")
    params = jnp.stack([jnp.ones(3), 2 * jnp.ones(3)])
    x = jnp.ones((2, 1, 3))
    pipeline_apply(lambda p, v: v * p.sum(), 2, mesh, "pp", params, x)
    kinds = {e["kind"] for e in audit.collective_log()
             if "pipeline" in e["tag"]}
    assert kinds == {"collective-permute", "all-reduce"}

    audit.clear_collective_log()
    from mxnet_tpu.parallel.moe import moe_ffn
    ep = _mesh(2, "ep")
    T, d, E, h = 8, 4, 2, 8
    rng = np.random.RandomState(0)
    out, aux_loss = moe_ffn(
        jnp.asarray(rng.randn(T, d), jnp.float32),
        jnp.asarray(rng.randn(d, E), jnp.float32),
        jnp.asarray(rng.randn(E, d, h), jnp.float32),
        jnp.asarray(rng.randn(E, h, d), jnp.float32), ep)
    kinds = {e["kind"] for e in audit.collective_log()
             if "moe" in e["tag"]}
    assert kinds == {"all-to-all", "all-reduce"}


# ---------------------------------------------------------------------------
# GC501: pre-flight HBM capacity (the memory plane's graphcheck rule)
# ---------------------------------------------------------------------------

def test_gc501_capacity_exceeded_flagged():
    rep = graphcheck.check_capacity(32e9, capacity_bytes=16e9,
                                    target="seeded")
    assert _rules(rep) == ["GC501"]
    (f,) = rep.errors()
    assert "32.00 GB" in f.message and "16.00 GB" in f.message
    assert f.extra["predicted_bytes"] == 32_000_000_000


def test_gc501_clean_under_capacity_and_unknown_capacity(monkeypatch):
    assert len(graphcheck.check_capacity(8e9, capacity_bytes=16e9)) == 0
    # unknown capacity (CPU dev box, no env override): rule disables
    monkeypatch.delenv("MXNET_TPU_DEVICE_HBM_GB", raising=False)
    assert len(graphcheck.check_capacity(1e18)) == 0
    # env override supplies the capacity where the backend reports none
    monkeypatch.setenv("MXNET_TPU_DEVICE_HBM_GB", "16")
    from mxnet_tpu.telemetry import memory as _memory
    assert _memory.device_capacity_bytes() == 16e9
    assert _rules(graphcheck.check_capacity(32e9)) == ["GC501"]


def test_gc501_trainer_preflight_seeded_and_clean(tmp_path, monkeypatch):
    """End-to-end: a trainer whose state+batch cannot fit the (tiny,
    env-seeded) capacity is refused BEFORE dispatch with a GC501 ERROR;
    with a sane capacity the same trainer passes."""
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT", "1")
    monkeypatch.setenv("MXNET_TPU_PREFLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_TPU_DEVICE_HBM_GB", "0.000001")  # 1 kB
    trainer, (params, mom, aux) = _toy_trainer()
    batch = {"data": np.zeros((8, 32), np.float32),
             "softmax_label": np.zeros(8, np.float32)}
    with pytest.raises(PreflightError) as ei:
        trainer.step(params, mom, aux, batch)
    assert "GC501" in str(ei.value)
    assert "RESOURCE_EXHAUSTED" in str(ei.value)

    monkeypatch.setenv("MXNET_TPU_DEVICE_HBM_GB", "16")
    trainer2, (p2, m2, a2) = _toy_trainer()
    p2, m2, a2, loss = trainer2.step(p2, m2, a2, batch)
    assert np.isfinite(float(loss))
    reports = [p for p in os.listdir(str(tmp_path))
               if p.startswith("preflight-trainer") and p.endswith(".json")]
    clean = Report.load(str(tmp_path / sorted(reports)[-1]))
    assert not [f for f in clean if f.rule == "GC501"]


# ---------------------------------------------------------------------------
# GC304: collectives serialized against compute (round 6)
# ---------------------------------------------------------------------------

# 2 MB sync all-reduce on the critical path: its only neighbors are its
# producer (multiply) and consumer (add) — nothing to hide behind
_GC304_SERIAL_HLO = """
ENTRY %main (p0: f32[524288]) -> f32[524288] {
  %p0 = f32[524288]{0} parameter(0)
  %w = f32[524288]{0} multiply(f32[524288]{0} %p0, f32[524288]{0} %p0)
  %ar = f32[524288]{0} all-reduce(f32[524288]{0} %w), replica_groups={}
  ROOT %out = f32[524288]{0} add(f32[524288]{0} %ar, f32[524288]{0} %ar)
}
"""

# same payload, but an independent dot exists in the computation — a
# double-buffered schedule any async backend can hide the transfer in
_GC304_PIPELINED_HLO = """
ENTRY %main (p0: f32[524288], q0: f32[128,128]) -> f32[524288] {
  %p0 = f32[524288]{0} parameter(0)
  %q0 = f32[128,128]{1,0} parameter(1)
  %ar = f32[524288]{0} all-reduce(f32[524288]{0} %p0), replica_groups={}
  %mm = f32[128,128]{1,0} dot(f32[128,128]{1,0} %q0, f32[128,128]{1,0} %q0), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %out = f32[524288]{0} add(f32[524288]{0} %ar, f32[524288]{0} %ar)
}
"""


def test_gc304_seeded_all_sync_serial():
    rep = graphcheck.check_overlap(_GC304_SERIAL_HLO, target="toy")
    assert _rules(rep) == ["GC304"]
    (f,) = list(rep)
    assert f.severity == "warning"
    assert f.extra["sync_ops"] == 1 and f.extra["pipelined_ops"] == 0


def test_gc304_clean_when_overlap_exists():
    rep = graphcheck.check_overlap(_GC304_PIPELINED_HLO, target="toy")
    assert _rules(rep) == []


def test_gc304_tiny_payload_not_flagged():
    # the serial shape again, but 4 KB of payload: hiding a microsecond
    # transfer buys nothing — below MXNET_TPU_GC304_MIN_MB stays clean
    small = _GC304_SERIAL_HLO.replace("524288", "1024")
    assert _rules(graphcheck.check_overlap(small, target="toy")) == []
    # explicit floor override flags it again
    rep = graphcheck.check_overlap(small, target="toy", min_bytes=1)
    assert _rules(rep) == ["GC304"]


# ---------------------------------------------------------------------------
# GC305: pure-replica grad all-reduce while the ZeRO update is off
# ---------------------------------------------------------------------------

def test_gc305_seeded_replicated_update_at_payload():
    rep = graphcheck.check_zero_update(
        dp_size=8, update_sharded=False,
        grad_payload_bytes=45 << 20, target="toy")
    assert _rules(rep) == ["GC305"]
    (f,) = list(rep)
    assert f.severity == "warning"
    assert f.extra["dp_size"] == 8
    assert "MXNET_TPU_ZERO" in f.fix_hint


def test_gc305_clean_cases():
    # sharded update on -> clean at any payload
    rep = graphcheck.check_zero_update(8, True, 45 << 20, target="toy")
    assert _rules(rep) == []
    # dp=1: nothing is replicated, clean
    assert _rules(graphcheck.check_zero_update(1, False, 45 << 20)) == []
    # tiny payload under the default 8 MB floor: clean
    assert _rules(graphcheck.check_zero_update(8, False, 1 << 20)) == []
    # explicit floor override flags it again
    rep = graphcheck.check_zero_update(8, False, 1 << 20, min_bytes=1)
    assert _rules(rep) == ["GC305"]


def test_gc305_wired_into_check_trainer(monkeypatch):
    """check_trainer (the MXNET_TPU_PREFLIGHT=1 path) carries the rule:
    a dp trainer over a real payload warns unless the sharded update is
    on."""
    monkeypatch.setenv("MXNET_TPU_GC305_MIN_MB", "0.001")
    trainer, (params, mom, aux) = _toy_trainer()
    inputs = {"data": jax.ShapeDtypeStruct((8, 32), jnp.float32),
              "softmax_label": jax.ShapeDtypeStruct((8,), jnp.float32)}
    rep, _ = graphcheck.check_trainer(trainer, params, mom, aux, inputs)
    assert "GC305" in _rules(rep)
    from mxnet_tpu.parallel.trainer import ShardedTrainer
    trainer2 = ShardedTrainer(trainer.symbol, trainer.spec, lr=0.1,
                              zero=True)
    p2, m2, a2 = trainer2.init_state(
        {"data": (8, 32), "softmax_label": (8,)})
    rep2, _ = graphcheck.check_trainer(trainer2, p2, m2, a2, inputs)
    assert "GC305" not in _rules(rep2)


def test_gc304_clean_on_ring_attention_program():
    """The double-buffered ring schedule (r6) must never flag: every
    ppermute has the block's attention dots to hide behind — even with
    the payload floor removed."""
    from mxnet_tpu.parallel.ring import local_ring_attention_fn
    n = 2
    mesh = _mesh(n, "sp")
    fn = local_ring_attention_fn("sp", False, 0.25, n)
    spec = P(None, "sp", None, None)
    mapped = _smap(fn, mesh, (spec,) * 3, spec)
    x = jnp.ones((1, 4 * n, 2, 8), jnp.float32)
    txt = jax.jit(mapped).lower(x, x, x).compile().as_text()
    rep = graphcheck.check_overlap(txt, target="ring", min_bytes=0)
    assert _rules(rep) == [], [f.message for f in rep]
