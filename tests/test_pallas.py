"""Pallas kernel tests (interpret mode on the CPU backend; the same
pallas_call lowers to real TPU kernels on device)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.ops.pallas_kernels import (fused_attention,
                                          fused_attention_bwd,
                                          fused_attention_fwd,
                                          two_bit_compress)


@pytest.mark.parametrize("use_pallas", [False, True],
                         ids=["xla", "pallas-kernel"])
def test_two_bit_compress_matches_formula(use_pallas):
    rs = np.random.RandomState(0)
    for shape in [(7,), (33, 5), (2, 3, 4)]:
        g = jnp.asarray(rs.normal(0, 1, shape).astype(np.float32))
        r = jnp.asarray(rs.normal(0, 0.3, shape).astype(np.float32))
        q, nr = two_bit_compress(g, r, threshold=0.5,
                                 use_pallas=use_pallas)
        comp = np.asarray(g) + np.asarray(r)
        want_q = np.where(comp >= 0.5, 0.5, np.where(comp <= -0.5, -0.5, 0.0))
        np.testing.assert_allclose(np.asarray(q), want_q, atol=1e-6)
        np.testing.assert_allclose(np.asarray(nr), comp - want_q, atol=1e-6)
        assert q.shape == shape and nr.shape == shape


def test_two_bit_error_feedback_accumulates():
    """Small gradients below threshold must eventually fire via the
    residual (the whole point of error feedback)."""
    g = jnp.full((16,), 0.2, jnp.float32)
    r = jnp.zeros((16,), jnp.float32)
    fired = 0.0
    for _ in range(5):
        q, r = two_bit_compress(g, r, threshold=0.5)
        fired += float(np.asarray(q).sum())
    # 5 steps x 0.2 = 1.0 per element; quantized emissions must track it
    assert fired > 0
    total = fired + float(np.asarray(r).sum())
    np.testing.assert_allclose(total, 16 * 1.0, rtol=1e-5)


def test_kvstore_compression_uses_fused_kernel():
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", nd.zeros((8,)))
    kv.push("w", nd.array(np.full(8, 0.6, np.float32)))
    out = nd.zeros((8,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(8, 0.5), atol=1e-6)


def _naive_attention(q, k, v, causal=False, scale=None):
    B, T, H, D = q.shape
    if scale is None:
        scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = np.tril(np.ones((T, k.shape[1]), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_k", [512, 8],
                         ids=["one-k-block", "multi-k-block"])
def test_fused_attention_matches_naive(causal, block_k):
    """block_k=8 forces nk=4: the online-softmax carry (running max/sum
    renormalization across k blocks, causal block skipping) is on the
    line, not just the single-block degenerate path."""
    rs = np.random.RandomState(1)
    B, T, H, D = 2, 32, 2, 16
    q = jnp.asarray(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    v = jnp.asarray(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    out = fused_attention(q, k, v, causal=causal, block_q=16,
                          block_k=block_k)
    want = _naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_fused_attention_single_block():
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.normal(0, 1, (1, 8, 1, 8)).astype(np.float32))
    out = fused_attention(q, q, q, block_q=128)  # bq clamps to T
    want = _naive_attention(q, q, q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_fused_attention_op_flash_min_seq_attr():
    """Op-level flash dispatch: flash_min_seq=1 forces the Pallas flash
    forward + fused flash backward THROUGH the operator even at tiny T
    (the env default would route this to the plain einsum path).
    Covers the attr half of the MXNET_FLASH_MIN_SEQ resolution — the env
    half is frozen at import so it cannot silently change post-trace."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    rs = np.random.RandomState(3)
    B, T, H, D = 2, 16, 2, 8
    qh = rs.normal(0, 1, (B, T, H, D)).astype(np.float32)
    kh = rs.normal(0, 1, (B, T, H, D)).astype(np.float32)
    vh = rs.normal(0, 1, (B, T, H, D)).astype(np.float32)
    q, k, v = nd.array(qh), nd.array(kh), nd.array(vh)

    out = nd.contrib.fused_attention(q, k, v, flash_min_seq=1,
                                     block_q=8).asnumpy()
    want = np.asarray(_naive_attention(
        jnp.asarray(qh), jnp.asarray(kh), jnp.asarray(vh)))
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    # backward rides the rematerializing custom vjp
    gq = nd.zeros((B, T, H, D))
    mx.autograd.mark_variables([q], [gq])
    with mx.autograd.record():
        o = nd.contrib.fused_attention(q, k, v, flash_min_seq=1, block_q=8)
        mx.autograd.backward([o])
    g = gq.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


# ---------------------------------------------------------------------------
# flash backward (round 6): recompute-free dQ/dK/dV from the saved lse
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blocks", [(16, 16), (16, 8), (8, 16)],
                         ids=["sym", "multi-k", "multi-q"])
def test_flash_backward_matches_einsum_vjp(causal, blocks):
    """The flash dQ/dK/dV kernels against jax.vjp of the einsum
    formulation, across block shapes that force the online accumulators
    (multi-k: several score tiles per dQ row; multi-q: several per
    dK/dV column) and the causal block-skipping."""
    bq, bk = blocks
    rs = np.random.RandomState(7)
    B, T, H, D = 2, 32, 2, 16
    q = jnp.asarray(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    v = jnp.asarray(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    g = jnp.asarray(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    scale = float(1.0 / np.sqrt(D))

    out, lse = fused_attention_fwd(q, k, v, causal=causal,
                                   block_q=bq, block_k=bk)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(_naive_attention(q, k, v, causal=causal)),
        rtol=1e-4, atol=1e-5)
    dq, dk, dv = fused_attention_bwd(q, k, v, out, lse, g, causal=causal,
                                     block_q=bq, block_k=bk)
    _, vjp = jax.vjp(
        lambda a, b, c: _naive_attention(a, b, c, causal=causal,
                                         scale=scale), q, k, v)
    wq, wk, wv = vjp(g)
    for got, want in ((dq, wq), (dk, wk), (dv, wv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


def test_flash_fwd_lse_is_row_logsumexp():
    """The residual really is logsumexp of the scaled (masked) logits —
    the invariant the backward rebuilds p from."""
    rs = np.random.RandomState(8)
    B, T, H, D = 1, 32, 1, 8
    q = jnp.asarray(rs.normal(0, 1, (B, T, H, D)).astype(np.float32))
    scale = float(1.0 / np.sqrt(D))
    _, lse = fused_attention_fwd(q, q, q, causal=True, block_q=16,
                                 block_k=8)
    s = np.einsum("bqhd,bkhd->bhqk", np.asarray(q), np.asarray(q)) * scale
    s = np.where(np.tril(np.ones((T, T), bool)), s, -np.inf)
    want = np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) \
        + s.max(-1)                                   # (B,H,T)
    got = np.asarray(lse)[:, :, 0].reshape(B, H, T)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # every lane carries the same broadcast value
    assert np.all(np.asarray(lse) == np.asarray(lse)[:, :, :1])


def test_flash_bwd_bf16_tolerance():
    rs = np.random.RandomState(9)
    B, T, H, D = 1, 32, 2, 16
    mk = lambda: jnp.asarray(
        rs.normal(0, 1, (B, T, H, D)).astype(np.float32)).astype(
        jnp.bfloat16)
    q, k, v, g = mk(), mk(), mk(), mk()
    out, lse = fused_attention_fwd(q, k, v, causal=True, block_q=16,
                                   block_k=16)
    dq, dk, dv = fused_attention_bwd(q, k, v, out, lse, g, causal=True,
                                     block_q=16, block_k=16)
    scale = float(1.0 / np.sqrt(D))
    f32 = lambda x: jnp.asarray(np.asarray(x, np.float32))
    _, vjp = jax.vjp(
        lambda a, b, c: _naive_attention(a, b, c, causal=True,
                                         scale=scale),
        f32(q), f32(k), f32(v))
    for got, want in zip((dq, dk, dv), vjp(f32(g))):
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), rtol=0.1, atol=0.05)
