"""Async snapshot-then-write checkpointing (round 6).

Semantics under test (resilience/checkpoint.py):

* ``save`` returns after snapshot+enqueue; the CRC+fsync+rename happens
  on the background writer and the ``checkpoint/save`` span's
  host-blocking time is a small fraction of ``checkpoint/write``.
* A crash between snapshot and write loses only that snapshot — the
  previous checkpoint on disk stays valid, quarantine/fallback
  untouched.
* Reads through the manager (steps/restore/latest) barrier on in-flight
  writes, so concurrent save+restore can never observe a partial state.
* Writer failures surface on the next ``save``/``wait`` — never silent.
"""
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.resilience import chaos
from mxnet_tpu.resilience.checkpoint import CheckpointManager
from mxnet_tpu.resilience import checkpoint as ckpt_mod


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    telemetry.disarm()
    yield
    telemetry.reset()
    telemetry.disarm()


def _arrays(step):
    return {"w": np.full(1024, step, np.float32)}


def test_async_save_returns_before_write_lands(tmp_path, monkeypatch):
    """save() must not wait for the disk: with the writer slowed, save
    returns immediately and the file appears only after wait()."""
    gate = threading.Event()
    real_write = ckpt_mod.write_container

    def slow_write(path, arrays=None, meta=None, blobs=None):
        gate.wait(timeout=10)
        return real_write(path, arrays, meta, blobs)

    monkeypatch.setattr(ckpt_mod, "write_container", slow_write)
    mgr = CheckpointManager(str(tmp_path), async_write=True)
    t0 = time.perf_counter()
    path = mgr.save(1, _arrays(1))
    assert time.perf_counter() - t0 < 0.5, "save blocked on the write"
    assert not os.path.exists(path)
    assert mgr.pending() == 1
    gate.set()
    assert mgr.wait(timeout=10)
    assert os.path.exists(path)
    ck = mgr.latest()
    assert ck.step == 1
    np.testing.assert_array_equal(ck.arrays["w"], _arrays(1)["w"])


def test_crash_between_snapshot_and_write_keeps_previous(tmp_path):
    """A process that dies with a snapshot still queued leaves the
    previous checkpoint as the newest valid one (simulated by a manager
    whose writer never runs — exactly what a crash looks like on
    disk)."""
    mgr = CheckpointManager(str(tmp_path), async_write=True)
    mgr.save(1, _arrays(1))
    assert mgr.wait(timeout=10)

    # "crashing" manager: snapshot accepted, writer never scheduled
    mgr2 = CheckpointManager(str(tmp_path), async_write=True)
    mgr2._ensure_writer = lambda: None
    mgr2.save(2, _arrays(2))
    assert not os.path.exists(mgr2.path_for(2))

    # recovery process: fresh manager over the same directory
    mgr3 = CheckpointManager(str(tmp_path))
    ck = mgr3.latest()
    assert ck is not None and ck.step == 1
    np.testing.assert_array_equal(ck.arrays["w"], _arrays(1)["w"])


def test_crash_mid_write_quarantine_fallback_unchanged(tmp_path):
    """Corruption semantics are untouched by the async path: corrupt the
    newest LANDED checkpoint — restore quarantines it and falls back."""
    mgr = CheckpointManager(str(tmp_path), keep=3, async_write=True)
    for s in (1, 2):
        mgr.save(s, _arrays(s))
    mgr.wait(timeout=10)
    assert chaos.corrupt_latest(str(tmp_path)) is not None
    ck = mgr.latest()
    assert ck.step == 1
    assert any(n.endswith(".corrupt") for n in os.listdir(str(tmp_path)))


def test_concurrent_save_and_restore_safe(tmp_path):
    """Hammer save on one thread and restore on another: every restore
    must return a fully-validated checkpoint whose arrays match its
    step (the manager barriers; the container CRC-checks)."""
    mgr = CheckpointManager(str(tmp_path), keep=4, async_write=True)
    errs = []
    done = threading.Event()

    def saver():
        try:
            for s in range(1, 21):
                mgr.save(s, _arrays(s))
        except Exception as e:       # pragma: no cover - failure path
            errs.append(e)
        finally:
            done.set()

    t = threading.Thread(target=saver)
    t.start()
    seen = 0
    while not done.is_set() or seen == 0:
        ck = mgr.restore()
        if ck is None:
            continue
        np.testing.assert_array_equal(ck.arrays["w"], _arrays(ck.step)["w"])
        seen += 1
        if done.is_set():
            break
    t.join()
    mgr.wait(timeout=10)
    assert not errs
    assert mgr.latest().step == 20


def test_writer_error_surfaces_on_next_save(tmp_path, monkeypatch):
    calls = {"n": 0}
    real_write = ckpt_mod.write_container

    def failing_write(path, arrays=None, meta=None, blobs=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("disk full")
        return real_write(path, arrays, meta, blobs)

    monkeypatch.setattr(ckpt_mod, "write_container", failing_write)
    mgr = CheckpointManager(str(tmp_path), async_write=True)
    mgr.save(1, _arrays(1))
    with mgr._cv:
        mgr._cv.wait_for(lambda: mgr._inflight == 0, timeout=10)
    with pytest.raises(MXNetError, match="background checkpoint write"):
        mgr.save(2, _arrays(2))
    # the failure is consumed once surfaced; later saves work again
    mgr.save(3, _arrays(3))
    assert mgr.wait(timeout=10)
    assert 3 in mgr.steps()


def test_sync_mode_writes_inline(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_write=False)
    path = mgr.save(1, _arrays(1))
    assert os.path.exists(path), "sync save must be durable on return"
    assert mgr.pending() == 0


def test_retention_applies_on_writer_thread(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2, async_write=True)
    for s in (1, 2, 3, 4):
        mgr.save(s, _arrays(s))
    assert mgr.steps() == [3, 4]     # steps() barriers first


def test_save_span_off_critical_path(tmp_path, monkeypatch):
    """The acceptance criterion: with telemetry armed and a deliberately
    slow disk, the ``checkpoint/save`` span (host-blocking) stays an
    order of magnitude under ``checkpoint/write`` (the disk)."""
    real_write = ckpt_mod.write_container

    def slow_write(path, arrays=None, meta=None, blobs=None):
        time.sleep(0.25)
        return real_write(path, arrays, meta, blobs)

    monkeypatch.setattr(ckpt_mod, "write_container", slow_write)
    telemetry.arm()
    mgr = CheckpointManager(str(tmp_path), async_write=True)
    for s in (1, 2, 3):
        mgr.save(s, _arrays(s))
    assert mgr.wait(timeout=30)
    save_p = telemetry.histogram("checkpoint.save_seconds").percentiles(
        (0.5,))[0.5]
    write_p = telemetry.histogram("checkpoint.write_seconds").percentiles(
        (0.5,))[0.5]
    assert write_p >= 0.25
    assert save_p < write_p / 10, (
        "host-blocking save time %.4fs is not << write time %.4fs"
        % (save_p, write_p))
