"""Tensor parallelism via GSPMD sharding annotations (SURVEY §2.3: "provide
via pjit/GSPMD sharding annotations").

ShardedTrainer shards FC/Conv output channels and embedding vocab rows over
the 'tp' mesh axis; XLA propagates activation shardings and inserts the
collectives.  A dp×tp mesh must match single-device numerics, parameters
must REALLY live sharded (per-device bytes drop), and per-variable
``__shard__`` Symbol attrs override the default recipe.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
from mxnet_tpu.parallel.trainer import ShardedTrainer


def _mlp(num_hidden=16, num_classes=8):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=num_hidden)
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=num_classes)
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _train(sym, mesh_shape, axes, steps=3, batch=8, feat=12, classes=8,
           seed=11):
    spec = MeshSpec(make_mesh(mesh_shape, axes))
    trainer = ShardedTrainer(sym, spec, lr=0.1, momentum=0.9, wd=1e-4)
    shapes = {"data": (batch, feat), "softmax_label": (batch,)}
    params, mom, aux = trainer.init_state(shapes, seed=seed)
    rs = np.random.RandomState(0)
    for i in range(steps):
        data = rs.rand(batch, feat).astype(np.float32)
        label = rs.randint(0, classes, batch).astype(np.float32)
        params, mom, aux, loss = trainer.step(
            params, mom, aux, {"data": data, "softmax_label": label})
    out = {n: np.asarray(p) for n, p in zip(trainer.param_names, params)}
    return trainer, out, float(loss)


def test_tp_matches_single_device():
    """dp=2 x tp=4 training == single-device training, numerically."""
    tr_tp, p_tp, loss_tp = _train(_mlp(), (2, 4), ("dp", "tp"))
    assert tr_tp.tp_axis == "tp"
    tr_1, p_1, loss_1 = _train(_mlp(), (1,), ("dp",))
    assert abs(loss_tp - loss_1) < 1e-3
    for n in p_1:
        np.testing.assert_allclose(p_tp[n], p_1[n], rtol=2e-4, atol=2e-5)


def test_tp_params_really_sharded():
    """FC weights must be placed sharded: per-device shard is 1/tp of the
    rows, so per-chip parameter memory actually scales down."""
    tr, _, _ = _train(_mlp(num_hidden=16), (1, 4), ("dp", "tp"), steps=1)
    spec = MeshSpec(make_mesh((1, 4), ("dp", "tp")))
    trainer = ShardedTrainer(_mlp(num_hidden=16), spec)
    shapes = {"data": (8, 12), "softmax_label": (8,)}
    params, mom, aux = trainer.init_state(shapes)
    by_name = dict(zip(trainer.param_names, params))
    w1 = by_name["fc1_weight"]          # (16, 12) sharded (tp, None)
    shard = w1.addressable_shards[0].data
    assert shard.shape == (4, 12), shard.shape
    m1 = dict(zip(trainer.param_names, mom))["fc1_weight"]
    assert m1.addressable_shards[0].data.shape == (4, 12)
    # bias (16,) is not name-matched *_weight → replicated
    b1 = by_name["fc1_bias"]
    assert b1.addressable_shards[0].data.shape == (16,)


def test_shard_attr_override():
    """__shard__ Symbol attr overrides the default tp recipe (the
    ctx_group-style per-layer annotation)."""
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("myw", attr={"__shard__": "*,tp"})
    h = mx.sym.FullyConnected(data, weight=w, name="fc1", num_hidden=16)
    net = mx.sym.SoftmaxOutput(h, name="softmax")
    spec = MeshSpec(make_mesh((1, 4), ("dp", "tp")))
    trainer = ShardedTrainer(net, spec)
    params, mom, aux = trainer.init_state(
        {"data": (8, 12), "softmax_label": (8,)})
    by_name = dict(zip(trainer.param_names, params))
    shard = by_name["myw"].addressable_shards[0].data
    assert shard.shape == (16, 3), shard.shape   # dim 1 sharded over tp=4

    # annotation on a non-divisible dim falls back to replicated
    w2 = mx.sym.Variable("oddw", attr={"__shard__": "tp"})
    h2 = mx.sym.FullyConnected(mx.sym.Variable("data"), weight=w2,
                               name="fcodd", num_hidden=15)
    net2 = mx.sym.SoftmaxOutput(h2, name="softmax")
    tr2 = ShardedTrainer(net2, spec)
    p2, _, _ = tr2.init_state({"data": (8, 12), "softmax_label": (8,)})
    odd = dict(zip(tr2.param_names, p2))["oddw"]
    assert odd.addressable_shards[0].data.shape == (15, 12)


def test_tp_embedding_vocab_sharded():
    """Embedding weight (vocab, dim) rows shard over tp; training still
    matches the single-device run."""
    def net():
        data = mx.sym.Variable("data")
        e = mx.sym.Embedding(data, name="emb", input_dim=16, output_dim=8)
        h = mx.sym.Flatten(e)
        h = mx.sym.FullyConnected(h, name="fc", num_hidden=4)
        return mx.sym.SoftmaxOutput(h, name="softmax")

    spec = MeshSpec(make_mesh((2, 2), ("dp", "tp")))
    trainer = ShardedTrainer(net(), spec)
    shapes = {"data": (4, 5), "softmax_label": (4,)}
    params, mom, aux = trainer.init_state(shapes, seed=3)
    emb = dict(zip(trainer.param_names, params))["emb_weight"]
    assert emb.addressable_shards[0].data.shape == (8, 8)   # 16/2 rows

    rs = np.random.RandomState(1)
    data = rs.randint(0, 16, (4, 5)).astype(np.float32)
    label = rs.randint(0, 4, (4,)).astype(np.float32)
    params, mom, aux, loss = trainer.step(
        params, mom, aux, {"data": data, "softmax_label": label})

    tr1 = ShardedTrainer(net(), MeshSpec(make_mesh((1,), ("dp",))))
    p1, m1, a1 = tr1.init_state(shapes, seed=3)
    p1, m1, a1, loss1 = tr1.step(
        p1, m1, a1, {"data": data, "softmax_label": label})
    assert abs(float(loss) - float(loss1)) < 1e-3
    for n, a, b in zip(trainer.param_names, params, p1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
