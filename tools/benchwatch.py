#!/usr/bin/env python3
"""Bench-trajectory ledger + statistical regression gate.

The bench numbers of record (bench.py's JSON line, the driver's
BENCH_r*.json artifacts) accumulate into ONE append-only ledger —
``PERF_LEDGER.jsonl``, one JSON object per bench round — and ``check``
gates new rounds against the trajectory: a drop beyond the noise the
history itself exhibits exits nonzero, so a perf regression fails CI
the same run it lands instead of being noticed three rounds later
(exactly how the r01→r05 plateau went unflagged).

Usage:
    python tools/benchwatch.py append --from-bench BENCH_r05.json
    python tools/benchwatch.py append --metric transformer_mfu=0.41
    python tools/benchwatch.py check [--json]       # or: --check
    python tools/benchwatch.py show

    --ledger PATH   ledger file (default: PERF_LEDGER.jsonl next to the
                    repo root)
    --sigma N       regression threshold in noise sigmas (default 4)
    --floor F       minimum relative drop to flag regardless of sigma
                    (default 0.05 = 5%: sub-noise-floor trajectories
                    would otherwise flag measurement jitter)

Gate semantics (per metric):  the latest entry is compared against the
best-known value in the history; the noise scale is the sigma of
historical excursions past the running best (drawdowns below the
running max for higher-is-better metrics — improvements are signal,
not noise, and must not widen the band).  A move beyond
``max(sigma * noise, floor)`` in the WRONG direction is a regression.
Most metrics (img/s, tok/s, MFU) are higher-is-better;
``compile_seconds`` (and its ``transformer_`` twin) is gated
LOWER-is-better — a compile-time improvement (a drop) can never read
as a regression, a compile-time blow-up does.  ``append`` accepts
bench.py's raw JSON line or the driver's BENCH_r*.json wrapper
(``{"parsed": {...}}``); bench.py appends automatically when
``BENCH_LEDGER`` names a ledger path.

Ledger entry schema: ``{"t", "source", "metrics": {...}}`` plus an
optional ``"extra"`` block for recorded-but-not-gated fields — today
the memory plane's per-benchmark ``peak_hbm_bytes`` (and
``transformer_peak_hbm_bytes``) lifted from the bench ``phases``
block.  Extras never enter the gate: metrics are higher-is-better, and
a peak-HBM improvement (a drop) must not read as a regression.

Exit status: check → 0 clean, 1 regression(s), 2 unreadable ledger.
"""
import argparse
import json
import os
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_LEDGER = os.path.join(_REPO, "PERF_LEDGER.jsonl")

SIGMA_MULT = 4.0
FLOOR = 0.05


def lower_is_better(name):
    """Metrics gated in the inverted direction (a DROP is the
    improvement): today the compile-time plane's ``compile_seconds``
    (promoted from an ungated extra once the compile cache landed —
    recovery-without-recompilation is a gated property now)."""
    return name.endswith("compile_seconds")


# ---------------------------------------------------------------------------
# ledger I/O
# ---------------------------------------------------------------------------

def extract_metrics(doc):
    """Flat {metric_name: value} from a bench document: bench.py's JSON
    line, or the driver's BENCH_r*.json wrapper carrying it under
    'parsed'."""
    if not isinstance(doc, dict):
        return {}
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    out = {}
    name = doc.get("metric")
    if name and isinstance(doc.get("value"), (int, float)):
        out[name] = float(doc["value"])
    if isinstance(doc.get("mfu"), (int, float)):
        out[(name or "bench") + "_mfu"] = float(doc["mfu"])
    # compile time is a GATED metric since the compile-cache round
    # (lower-is-better: see lower_is_better()); it was an ungated extra
    # before — metric_series() still folds those legacy extras into the
    # same history
    phases = doc.get("phases")
    if isinstance(phases, dict) and \
            isinstance(phases.get("compile_seconds"), (int, float)):
        out["compile_seconds"] = round(float(phases["compile_seconds"]), 6)
    sub = doc.get("transformer")
    if isinstance(sub, dict):
        for k, v in extract_metrics(sub).items():
            out["transformer_" + k if k == "compile_seconds" else k] = v
    return out


def extract_extra(doc):
    """Recorded-but-not-gated fields from a bench document — the memory
    plane's peak HBM and the collective plane's per-step wire bytes
    (phases.peak_hbm_bytes / phases.collective_bytes_per_step).  These
    land in the ledger entry's ``extra`` block, NOT ``metrics``: the
    gate treats every metric as higher-is-better, and a peak-HBM or
    wire-bytes *improvement* (a drop) must never read as a regression."""
    if not isinstance(doc, dict):
        return {}
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    out = {}
    phases = doc.get("phases")
    if isinstance(phases, dict):
        for field in ("peak_hbm_bytes", "collective_bytes_per_step"):
            if isinstance(phases.get(field), (int, float)):
                out[field] = int(phases[field])
        # measured/predicted step ratio from the conformance pass:
        # ungated for the same reason — drift toward 1.0 (a better
        # calibration) must never read as a regression
        if isinstance(phases.get("conformance_step_ratio"),
                      (int, float)):
            out["conformance_step_ratio"] = round(
                float(phases["conformance_step_ratio"]), 4)
        # compile_seconds moved from here into extract_metrics when it
        # was promoted to a (lower-is-better) gated metric
    sub = doc.get("transformer")
    if isinstance(sub, dict):
        for k, v in extract_extra(sub).items():
            out["transformer_" + k] = v
    return out


def append_entry(ledger_path, metrics, source="", t=None, extra=None):
    """Append one round to the ledger (plain append: the ledger is an
    event log, each line self-contained).  A round may carry only
    ``extra`` (ungated) fields — audit-level artifacts like the
    MULTICHIP dryrun publish wire-bytes/overlap facts without any
    throughput metric to gate."""
    if not metrics and not extra:
        raise ValueError("no metrics or extras to append")
    entry = {"t": time.time() if t is None else t, "source": source,
             "metrics": {k: float(v) for k, v in metrics.items()}}
    if extra:
        entry["extra"] = extra
    with open(ledger_path, "a") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")
    return entry


def read_ledger(path):
    entries = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                raise ValueError("ledger %s line %d is not JSON"
                                 % (path, i + 1))
            if isinstance(e, dict) and isinstance(e.get("metrics"), dict):
                entries.append(e)
    return entries


def metric_series(entries):
    """{metric: [values in ledger order]} (rounds missing a metric are
    simply absent from that series).  Lower-is-better metrics that
    older rounds recorded in the ungated ``extra`` block (compile
    seconds before its promotion) are folded into the same series, so
    the gate has its full history from day one."""
    out = {}
    for e in entries:
        merged = dict(e["metrics"])
        for k, v in (e.get("extra") or {}).items():
            if lower_is_better(k) and k not in merged:
                merged[k] = v
        for k, v in merged.items():
            if isinstance(v, (int, float)):
                out.setdefault(k, []).append(float(v))
    return out


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def drawdown_sigma(history):
    """Noise scale of a higher-is-better series: the sigma of relative
    drawdowns below the running max.  Improvements are signal and do not
    widen the band; a flat-with-jitter series yields its jitter."""
    if len(history) < 2:
        return 0.0
    run_max = history[0]
    draws = []
    for v in history[1:]:
        run_max = max(run_max, v)
        draws.append((run_max - v) / run_max if run_max > 0 else 0.0)
    if len(draws) < 2:
        # one excursion is a data point, not a noise scale — returning
        # it as sigma let a single bad historical round widen the band
        # 4x; report zero and let the caller's floor take over
        return 0.0
    return statistics.stdev(draws)


def rise_sigma(history):
    """Noise scale of a LOWER-is-better series: the sigma of relative
    rises above the running min — mirror image of drawdown_sigma
    (improvements, i.e. drops, are signal and never widen the band)."""
    if len(history) < 2:
        return 0.0
    run_min = history[0]
    rises = []
    for v in history[1:]:
        run_min = min(run_min, v)
        rises.append((v - run_min) / run_min if run_min > 0 else 0.0)
    if len(rises) < 2:
        # mirror of drawdown_sigma: a lone rise is not a noise scale
        return 0.0
    return statistics.stdev(rises)


def check_series(values, sigma_mult=SIGMA_MULT, floor=FLOOR, lower=False):
    """Gate one metric's trajectory: is the LATEST value a regression
    against the best-known, beyond the history's own noise?  ``lower``
    inverts the direction (best = running MIN, a rise regresses) — so a
    compile-time improvement can never read as a regression and a
    blow-up cannot hide.

    Returns {"checked", "regression", "latest", "best", "drop",
    "threshold", "noise_sigma", "band_basis", "direction"}.
    ``band_basis`` says which side of ``max(sigma*noise, floor)`` won:
    a single-row history has no sigma at all (noise 0.0) and gates on
    the explicit 5% floor — the calibration store reads these series,
    so the one-row edge case is load-bearing, not cosmetic."""
    if len(values) < 2:
        return {"checked": False, "regression": False,
                "n": len(values)}
    history, latest = values[:-1], values[-1]
    if lower:
        best = min(history)
        move = (latest - best) / best if best > 0 else 0.0
        noise = rise_sigma(history)
    else:
        best = max(history)
        move = (best - latest) / best if best > 0 else 0.0
        noise = drawdown_sigma(history)
    threshold = max(sigma_mult * noise, floor)
    return {"checked": True,
            "regression": move > threshold,
            "latest": latest, "best": best,
            "drop": round(move, 4), "threshold": round(threshold, 4),
            "noise_sigma": round(noise, 4), "n": len(values),
            "band_basis": "sigma" if sigma_mult * noise > floor
            else "floor",
            "direction": "lower" if lower else "higher"}


def check_ledger(entries, sigma_mult=SIGMA_MULT, floor=FLOOR):
    """(ok, {metric: verdict}) over every metric series in the ledger."""
    results = {}
    ok = True
    for name, values in sorted(metric_series(entries).items()):
        r = check_series(values, sigma_mult=sigma_mult, floor=floor,
                         lower=lower_is_better(name))
        results[name] = r
        if r["regression"]:
            ok = False
    return ok, results


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _cmd_append(args):
    metrics = {}
    extra = {}
    sources = []
    for path in args.from_bench or []:
        with open(path) as f:
            doc = json.load(f)
        metrics.update(extract_metrics(doc))
        extra.update(extract_extra(doc))
        sources.append(os.path.basename(path))
    for kv in args.metric or []:
        k, _, v = kv.partition("=")
        metrics[k] = float(v)
    for kv in args.extra or []:
        k, _, v = kv.partition("=")
        extra[k] = float(v)
    entry = append_entry(args.ledger, metrics,
                         source=args.source or ",".join(sources),
                         extra=extra or None)
    print(json.dumps(entry, sort_keys=True))
    return 0


def _cmd_check(args):
    try:
        entries = read_ledger(args.ledger)
    except (OSError, ValueError) as e:
        print("benchwatch: %s" % e, file=sys.stderr)
        return 2
    ok, results = check_ledger(entries, sigma_mult=args.sigma,
                               floor=args.floor)
    if args.json:
        print(json.dumps({"ok": ok, "rounds": len(entries),
                          "metrics": results}, indent=2, sort_keys=True))
    else:
        print("benchwatch: %d rounds in %s" % (len(entries), args.ledger))
        for name, r in results.items():
            if not r["checked"]:
                print("  %-48s %d point(s), not gated" % (name, r["n"]))
                continue
            verdict = "REGRESSION" if r["regression"] else "ok"
            word = ("rise" if r.get("direction") == "lower" else "drop")
            print("  %-48s latest %.4g vs best %.4g  %s %.1f%% "
                  "(threshold %.1f%%, noise sigma %.2f%%)  %s"
                  % (name, r["latest"], r["best"], word, 100 * r["drop"],
                     100 * r["threshold"], 100 * r["noise_sigma"],
                     verdict))
        if not ok:
            print("benchwatch: REGRESSION beyond noise — investigate "
                  "before merging (PERF.md workflow)")
    return 0 if ok else 1


def _cmd_show(args):
    try:
        entries = read_ledger(args.ledger)
    except (OSError, ValueError) as e:
        print("benchwatch: %s" % e, file=sys.stderr)
        return 2
    for i, e in enumerate(entries):
        when = time.strftime("%Y-%m-%d %H:%M",
                             time.localtime(e["t"])) if e.get("t") else "-"
        ms = "  ".join("%s=%.4g" % kv for kv in
                       sorted(e["metrics"].items()))
        ex = e.get("extra") or {}
        if ex:
            ms += "  [" + "  ".join("%s=%.4g" % kv
                                    for kv in sorted(ex.items())) + "]"
        print("%3d  %s  %-14s %s" % (i + 1, when, e.get("source") or "-",
                                     ms))
    return 0


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # `--check` as the first token is an alias for the check command
    if argv and argv[0] == "--check":
        argv[0] = "check"
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("command", choices=["append", "check", "show"])
    ap.add_argument("--ledger", default=DEFAULT_LEDGER)
    ap.add_argument("--from-bench", action="append", default=[],
                    metavar="JSON")
    ap.add_argument("--metric", action="append", default=[],
                    metavar="NAME=VALUE")
    ap.add_argument("--extra", action="append", default=[],
                    metavar="NAME=VALUE",
                    help="recorded-but-not-gated fields (see the extra "
                         "block note in the module docstring)")
    ap.add_argument("--source", default="")
    ap.add_argument("--sigma", type=float, default=SIGMA_MULT)
    ap.add_argument("--floor", type=float, default=FLOOR)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    return {"append": _cmd_append, "check": _cmd_check,
            "show": _cmd_show}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
