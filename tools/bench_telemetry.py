#!/usr/bin/env python3
"""Telemetry overhead microbenchmark (acceptance gate for ISSUE 5).

Two measurements:

1. **Disarmed per-call cost** — the span/count/window_tick gates on the
   instrumented hot paths, measured in isolation (this is the only cost
   the telemetry layer adds to a step when nothing is armed).
2. **ShardedTrainer.step A/B** — a toy sharded train step timed with
   telemetry disarmed vs armed.  The disarmed column IS the pre-PR hot
   path plus the disarmed gates from (1); the printed overhead fraction
   (disarmed gate cost / median step time) must sit inside noise (<2%).

Usage:
    JAX_PLATFORMS=cpu python tools/bench_telemetry.py [--steps N]
"""
import argparse
import os
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def bench_disarmed_gates(n=20000):
    """Per-step disarmed telemetry cost: the 3 spans + 1 counter + 1
    window tick ShardedTrainer.step issues, PLUS the memory-plane hooks
    it gained in ISSUE 7 (oom_guard frame, batch tag, note_step) and the
    tracing-plane gates from ISSUE 12 (context mint + request-lane
    emission, both no-ops while MXNET_TPU_TRACE is off) — the gate
    bound covers the whole instrumented surface."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import memory, tracing
    telemetry.disarm()
    tracing.disarm()
    memory.reset()
    fake_batch = {"data": None, "softmax_label": None}
    req = _settled_request()
    t0 = time.perf_counter()
    for i in range(n):
        with memory.oom_guard("bench/step", step=i), \
                telemetry.span("bench/step", cat="train",
                               metric="train.step_seconds", step=i):
            with telemetry.span("bench/enqueue", cat="train"):
                memory.tag(fake_batch, "batch")
            with telemetry.span("bench/wait", cat="train"):
                pass
        memory.note_step(i)
        telemetry.count("train.steps")
        telemetry.window_tick()
        tracing.new_context()                  # router-side disarmed gate
        tracing.record_served_request(req)     # replica-side disarmed gate
    per_step = (time.perf_counter() - t0) / n
    return per_step


def _settled_request():
    """A pre-settled serving Request (no runtime, no device) — the shape
    the replica's trace emission walks."""
    from mxnet_tpu.serving.request import Request
    req = Request({"data": None}, 1, priority=0,
                  deadline=time.monotonic() + 60.0)
    now = time.monotonic()
    req.t_popped = now
    req.t_dispatched = now
    req.t_exec_done = now
    req.batch_seq = 1
    req._outputs = []
    req._done_at = now
    req._event.set()
    return req


def bench_tracing_armed(n=2000):
    """Armed-with-sampling per-request tracing cost: the router's mint +
    wire round trip + dispatch/root span records plus the replica's
    request-lane emission (six line-buffered sink appends total) — the
    FULL tracing work one fleet request causes, measured end to end
    against a real tmp-dir sink."""
    import tempfile
    from mxnet_tpu.telemetry import tracing
    tracing.reset()
    tracing.arm(sample=1.0)
    tracing.set_sink_dir(tempfile.mkdtemp(prefix="bench-trace-"))
    req = _settled_request()
    t0 = time.perf_counter()
    for _ in range(n):
        ctx = tracing.new_context()
        dctx = ctx.child()
        req.trace = tracing.from_wire(dctx.to_wire())
        tracing.record_served_request(req)
        tracing.record("fleet/dispatch", dctx, time.time(), 1e-3,
                       outcome="ok", replica=0)
        tracing.record("fleet/request", ctx, time.time(), 1e-3,
                       outcome="ok", tenant="bench")
    per_req = (time.perf_counter() - t0) / n
    tracing.reset()
    return per_req


def bench_request_latency(n=150):
    """Median in-process serving request latency (synthetic 2 ms
    executor — servebench's default) as the denominator the tracing
    overhead is judged against; a real fleet request costs MORE (two
    wire hops), so this is the conservative bound."""
    import numpy as np
    from mxnet_tpu.serving import ServingRuntime

    class _Prog:
        input_names = ["data"]
        input_shapes = {"data": (8, 16)}
        input_dtypes = {"data": np.dtype(np.float32)}

        def forward(self, data):
            time.sleep(0.002)
            return [data]

    lat = []
    with ServingRuntime(_Prog(), name="bench-trace") as rt:
        x = np.zeros((16,), np.float32)
        for _ in range(n):
            t0 = time.perf_counter()
            rt.predict({"data": x}, deadline=5.0)
            lat.append(time.perf_counter() - t0)
    return statistics.median(lat)


def bench_trainer_step(steps=30, armed=False):
    import numpy as np
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.parallel.mesh import MeshSpec, make_mesh
    from mxnet_tpu.parallel.trainer import ShardedTrainer

    (telemetry.arm if armed else telemetry.disarm)()
    n = min(2, jax.device_count())
    mesh = make_mesh((n,), ("dp",))
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=10, name="fc2")
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")
    trainer = ShardedTrainer(net, MeshSpec(mesh))
    shapes = {"data": (8 * n, 32), "softmax_label": (8 * n,)}
    params, mom, aux = trainer.init_state(shapes)
    rs = np.random.RandomState(0)
    batch = {"data": rs.rand(*shapes["data"]).astype(np.float32),
             "softmax_label": rs.randint(
                 0, 10, shapes["softmax_label"]).astype(np.float32)}
    # warm-up compiles
    for _ in range(3):
        params, mom, aux, loss = trainer.step(params, mom, aux, batch)
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        params, mom, aux, loss = trainer.step(params, mom, aux, batch)
        jax.block_until_ready(loss)
        times.append(time.perf_counter() - t0)
    telemetry.disarm()
    return statistics.median(times)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args(argv)

    gate = bench_disarmed_gates()
    print("disarmed telemetry gates: %.2f us / step" % (gate * 1e6))

    trace_cost = bench_tracing_armed()
    req_lat = bench_request_latency()
    trace_frac = trace_cost / req_lat
    print("tracing armed (sample=1.0): %.2f us / request, vs %.3f ms "
          "request -> %.4f%% (gate < 2%%: %s)"
          % (trace_cost * 1e6, req_lat * 1e3, 100 * trace_frac,
             "PASS" if trace_frac < 0.02 else "FAIL"))

    disarmed = bench_trainer_step(args.steps, armed=False)
    armed = bench_trainer_step(args.steps, armed=True)
    frac = gate / disarmed
    print("ShardedTrainer.step median: disarmed %.3f ms, armed %.3f ms"
          % (disarmed * 1e3, armed * 1e3))
    print("disarmed gate overhead: %.4f%% of step time (gate < 2%%: %s)"
          % (100 * frac, "PASS" if frac < 0.02 else "FAIL"))
    ok = frac < 0.02 and trace_frac < 0.02
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
