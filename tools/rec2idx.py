#!/usr/bin/env python
"""Rebuild the .idx file for an existing RecordIO .rec file.

Reference: tools/rec2idx.py (IndexCreator over MXRecordIO).  The index
maps record key -> byte offset so MXIndexedRecordIO can random-access and
shuffle; losing the .idx previously meant re-running im2rec.

Usage:  python tools/rec2idx.py data.rec [data.idx]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio


def build_index(rec_path: str, idx_path: str) -> int:
    """Scan every record, emitting `key\\toffset` lines keyed 0..N-1 (the
    im2rec convention).  Returns the record count."""
    reader = recordio.MXRecordIO(rec_path, "r")
    n = 0
    with open(idx_path, "w") as out:
        while True:
            offset = reader.tell()
            if reader.read() is None:
                break
            out.write("%d\t%d\n" % (n, offset))
            n += 1
    reader.close()
    return n


def main():
    ap = argparse.ArgumentParser(
        description="recreate the .idx for a RecordIO file")
    ap.add_argument("record", help="path to the .rec file")
    ap.add_argument("index", nargs="?", default=None,
                    help="output .idx path (default: alongside the .rec)")
    args = ap.parse_args()
    idx = args.index or os.path.splitext(args.record)[0] + ".idx"
    n = build_index(args.record, idx)
    print("wrote %s: %d records" % (idx, n))


if __name__ == "__main__":
    main()
