#!/usr/bin/env python
"""Measure Gluon DataLoader worker modes on a decode-bound dataset.

Synthesizes JPEGs, then times one epoch of batches through:
  workers=0 (sync), threads (thread_workers=True), processes (default).
Prints one JSON line per mode.  This is the evidence for the
multiprocess worker plane (reference gluon/data/dataloader.py:23 forks
for the same reason: Python-level decode does not scale under the GIL).

Usage: python tools/bench_dataloader.py [n_images] [num_workers]
"""
import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np
from PIL import Image

from mxnet_tpu.gluon.data import DataLoader, Dataset


class JpegDataset(Dataset):
    """Decode-bound: every __getitem__ decodes + augments one JPEG."""

    def __init__(self, n, hw=224):
        rs = np.random.RandomState(0)
        self._blobs = []
        for _ in range(min(n, 64)):
            arr = rs.randint(0, 256, (hw, hw, 3), dtype=np.uint8)
            b = io.BytesIO()
            Image.fromarray(arr).save(b, format="JPEG", quality=90)
            self._blobs.append(b.getvalue())
        self._n = n

    def __len__(self):
        return self._n

    def __getitem__(self, idx):
        img = np.asarray(Image.open(io.BytesIO(
            self._blobs[idx % len(self._blobs)])), dtype=np.float32)
        img = (img - 128.0) / 64.0          # numpy augment tail
        return img.transpose(2, 0, 1), np.float32(idx % 10)


def run(loader, label, n):
    t0 = time.perf_counter()
    seen = 0
    for batch in loader:
        seen += batch[0].shape[0]
    dt = time.perf_counter() - t0
    print(json.dumps({"metric": "dataloader_img_per_sec", "mode": label,
                      "value": round(seen / dt, 1), "images": seen}))
    return seen / dt


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    workers = int(sys.argv[2]) if len(sys.argv) > 2 else (os.cpu_count() or 4)
    ds = JpegDataset(n)
    batch = 32
    run(DataLoader(ds, batch), "sync", n)
    run(DataLoader(ds, batch, num_workers=workers, thread_workers=True),
        "threads[%d]" % workers, n)
    run(DataLoader(ds, batch, num_workers=workers),
        "processes[%d]" % workers, n)


if __name__ == "__main__":
    main()
