#!/usr/bin/env python
"""Hand-written pure-JAX ResNet-50 train step — the "ideal program"
yardstick for bench.py (PERF.md).  No framework code: raw jax.numpy +
lax convs in NHWC, bf16 params/activations with fp32 BN stats, fused
fwd+bwd+SGD(momentum+wd) step with full buffer donation.  Methodology
matches bench.py exactly: warmup, 100-iter chain, float(loss) sync.

BENCH_ARCH=v2 (default) mirrors the framework bench's architecture
EXACTLY (models/resnet.py: pre-activation v2, data-BN stem, eps=2e-5)
so framework-vs-ideal deltas measure the framework, not the model;
BENCH_ARCH=v1 keeps the classic post-activation network.

Usage: python tools/bench_ideal.py            # bs32 bf16
       BENCH_BATCH=128 python tools/bench_ideal.py
Prints one JSON line {"metric": "resnet50_ideal_img_per_sec", ...}.
BENCH_DUMP_HLO=/path.txt additionally dumps the optimized HLO.
"""
import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

BOTTLENECK = [3, 4, 6, 3]
WIDTHS = [256, 512, 1024, 2048]
ARCH = os.environ.get("BENCH_ARCH", "v2")
EPS = 2e-5 if ARCH == "v2" else 1e-5


def conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bn(x, scale, bias, mean, var, momentum=0.9, eps=EPS, train=True):
    """Returns (y, new_mean, new_var); stats in fp32."""
    if train:
        m = jnp.mean(x.astype(jnp.float32), axis=(0, 1, 2))
        v = jnp.var(x.astype(jnp.float32), axis=(0, 1, 2))
        new_mean = momentum * mean + (1 - momentum) * m
        new_var = momentum * var + (1 - momentum) * v
    else:
        m, v, new_mean, new_var = mean, var, mean, var
    inv = lax.rsqrt(v + eps) * scale
    y = (x.astype(jnp.float32) - m) * inv + bias
    return y.astype(x.dtype), new_mean, new_var


def init_params(key, dtype=jnp.bfloat16):
    params, stats = {}, {}
    rngs = iter(jax.random.split(key, 200))

    def conv_p(name, kh, kw, cin, cout):
        fan = kh * kw * cin
        params[name] = (jax.random.normal(next(rngs), (kh, kw, cin, cout),
                                          jnp.float32)
                        * np.sqrt(2.0 / fan)).astype(dtype)

    def bn_p(name, c):
        params[name + "_g"] = jnp.ones((c,), jnp.float32)
        params[name + "_b"] = jnp.zeros((c,), jnp.float32)
        stats[name + "_m"] = jnp.zeros((c,), jnp.float32)
        stats[name + "_v"] = jnp.ones((c,), jnp.float32)

    if ARCH == "v2":
        bn_p("bn_data", 3)
        conv_p("stem", 7, 7, 3, 64)
        bn_p("bn0", 64)
        cin = 64
        for s, (n, w) in enumerate(zip(BOTTLENECK, WIDTHS)):
            for u in range(n):
                pre = "s%du%d" % (s, u)
                mid = w // 4
                bn_p(pre + "_bn1", cin)
                conv_p(pre + "_c1", 1, 1, cin, mid)
                bn_p(pre + "_bn2", mid)
                conv_p(pre + "_c2", 3, 3, mid, mid)
                bn_p(pre + "_bn3", mid)
                conv_p(pre + "_c3", 1, 1, mid, w)
                if u == 0:
                    conv_p(pre + "_sc", 1, 1, cin, w)
                cin = w
        bn_p("bn1", 2048)
    else:
        conv_p("stem", 7, 7, 3, 64)
        bn_p("stem_bn", 64)
        cin = 64
        for s, (n, w) in enumerate(zip(BOTTLENECK, WIDTHS)):
            for u in range(n):
                pre = "s%du%d" % (s, u)
                mid = w // 4
                conv_p(pre + "_c1", 1, 1, cin, mid)
                bn_p(pre + "_bn1", mid)
                conv_p(pre + "_c2", 3, 3, mid, mid)
                bn_p(pre + "_bn2", mid)
                conv_p(pre + "_c3", 1, 1, mid, w)
                bn_p(pre + "_bn3", w)
                if u == 0:
                    conv_p(pre + "_sc", 1, 1, cin, w)
                    bn_p(pre + "_scbn", w)
                cin = w
    params["fc_w"] = (jax.random.normal(next(rngs), (2048, 1000), jnp.float32)
                      * 0.01).astype(dtype)
    params["fc_b"] = jnp.zeros((1000,), jnp.float32)
    return params, stats


def forward(params, stats, x, train=True):
    new_stats = {}

    def run_bn(name, x, fix_gamma=False):
        g = (jnp.ones_like(params[name + "_g"]) if fix_gamma
             else params[name + "_g"])
        y, m, v = bn(x, g, params[name + "_b"],
                     stats[name + "_m"], stats[name + "_v"], train=train)
        new_stats[name + "_m"], new_stats[name + "_v"] = m, v
        return y

    if ARCH == "v2":
        # mirror models/resnet.py resnet(): Cast(bf16) then pre-act v2
        x = x.astype(jnp.bfloat16)
        x = run_bn("bn_data", x, fix_gamma=True)
        x = conv(x, params["stem"], 2)
        x = jax.nn.relu(run_bn("bn0", x))
        x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
        for s, (n, w) in enumerate(zip(BOTTLENECK, WIDTHS)):
            for u in range(n):
                pre = "s%du%d" % (s, u)
                stride = 2 if (u == 0 and s > 0) else 1
                act1 = jax.nn.relu(run_bn(pre + "_bn1", x))
                y = conv(act1, params[pre + "_c1"])
                y = jax.nn.relu(run_bn(pre + "_bn2", y))
                y = conv(y, params[pre + "_c2"], stride)
                y = jax.nn.relu(run_bn(pre + "_bn3", y))
                y = conv(y, params[pre + "_c3"])
                sc = x if u != 0 else conv(act1, params[pre + "_sc"], stride)
                x = y + sc
        x = jax.nn.relu(run_bn("bn1", x))
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        logits = x @ params["fc_w"].astype(jnp.float32) + params["fc_b"]
        return logits, new_stats

    x = conv(x, params["stem"], 2)
    x = jax.nn.relu(run_bn("stem_bn", x))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")
    cin = 64
    for s, (n, w) in enumerate(zip(BOTTLENECK, WIDTHS)):
        for u in range(n):
            pre = "s%du%d" % (s, u)
            stride = 2 if (u == 0 and s > 0) else 1
            y = jax.nn.relu(run_bn(pre + "_bn1",
                                   conv(x, params[pre + "_c1"], stride)))
            y = jax.nn.relu(run_bn(pre + "_bn2", conv(y, params[pre + "_c2"])))
            y = run_bn(pre + "_bn3", conv(y, params[pre + "_c3"]))
            if u == 0:
                x = run_bn(pre + "_scbn", conv(x, params[pre + "_sc"], stride))
            x = jax.nn.relu(x + y)
            cin = w
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["fc_w"].astype(jnp.float32) + params["fc_b"]
    return logits, new_stats


def loss_fn(params, stats, x, labels):
    logits, new_stats = forward(params, stats, x)
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
    return loss, new_stats


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def train_step(params, mom, stats, x, labels):
    (loss, new_stats), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params, stats, x, labels)
    lr, mu, wd = 0.1, 0.9, 1e-4
    new_p, new_m = {}, {}
    for k, p in params.items():
        g = grads[k].astype(jnp.float32) + wd * p.astype(jnp.float32)
        m = mu * mom[k] + g
        new_m[k] = m
        new_p[k] = (p.astype(jnp.float32) - lr * m).astype(p.dtype)
    return new_p, new_m, new_stats, loss


def transformer_flops_per_step(batch, seq, layers, hidden, vocab):
    """Model FLOPs for one fused train step (fwd+bwd = 3x fwd matmuls).

    Matmul counting (dense 2mnk): qkv+out projections 4*D^2/tok/layer,
    FFN 8*D^2/tok/layer, vocab head D*V/tok; attention scores+values
    4*T*D/tok/layer counted over the FULL score matrix (both the ideal
    and the flash kernel do the causal work, so full-matrix counting is
    the consistent convention; halve for the causal-skip convention).
    """
    tokens = batch * seq
    proj = 2 * tokens * (layers * 12 * hidden * hidden + hidden * vocab)
    attn = 2 * tokens * layers * 2 * (2 * seq * hidden)
    return 3 * (proj + attn)


def _t_init(key, vocab, seq, layers, hidden, dtype=jnp.bfloat16):
    """GPT-2-small-geometry decoder LM params, bf16 weights + f32 norms."""
    rngs = iter(jax.random.split(key, 8 * layers + 8))
    p = {}

    def dense(name, fan_in, fan_out):
        p[name + "_w"] = (jax.random.normal(next(rngs), (fan_in, fan_out),
                                            jnp.float32)
                          * np.sqrt(1.0 / fan_in)).astype(dtype)
        p[name + "_b"] = jnp.zeros((fan_out,), dtype)

    def norm(name):
        p[name + "_g"] = jnp.ones((hidden,), jnp.float32)
        p[name + "_b"] = jnp.zeros((hidden,), jnp.float32)

    p["tok"] = (jax.random.normal(next(rngs), (vocab, hidden), jnp.float32)
                * 0.02).astype(dtype)
    p["pos"] = (jax.random.normal(next(rngs), (seq, hidden), jnp.float32)
                * 0.02).astype(dtype)
    for i in range(layers):
        pre = "l%d_" % i
        norm(pre + "ln1")
        dense(pre + "q", hidden, hidden)
        dense(pre + "k", hidden, hidden)
        dense(pre + "v", hidden, hidden)
        dense(pre + "proj", hidden, hidden)
        norm(pre + "ln2")
        dense(pre + "ff1", hidden, 4 * hidden)
        dense(pre + "ff2", 4 * hidden, hidden)
    norm("ln_f")
    dense("head", hidden, vocab)
    return p


def _t_forward(p, ids, layers, heads):
    """Pre-LN causal decoder matching models/transformer.py op-for-op."""
    hidden = p["tok"].shape[1]
    hd = hidden // heads

    def ln(name, x):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.var(x32, -1, keepdims=True)
        y = (x32 - mu) * lax.rsqrt(var + 1e-5)
        return (y * p[name + "_g"] + p[name + "_b"]).astype(x.dtype)

    def dense(name, x):
        return x @ p[name + "_w"] + p[name + "_b"]

    x = p["tok"][ids] + p["pos"][None, :, :]
    B, T = ids.shape
    causal = jnp.tril(jnp.ones((T, T), jnp.bool_))
    for i in range(layers):
        pre = "l%d_" % i
        a = ln(pre + "ln1", x)
        q = dense(pre + "q", a).reshape(B, T, heads, hd)
        k = dense(pre + "k", a).reshape(B, T, heads, hd)
        v = dense(pre + "v", a).reshape(B, T, heads, hd)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        scores = scores / np.sqrt(hd)
        scores = jnp.where(causal, scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        att = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, hidden)
        x = x + dense(pre + "proj", att)
        f = ln(pre + "ln2", x)
        f = jax.nn.gelu(dense(pre + "ff1", f))
        x = x + dense(pre + "ff2", f)
    x = ln("ln_f", x)
    return dense("head", x).astype(jnp.float32)


def _transformer_main():
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    layers = int(os.environ.get("BENCH_LAYERS", "12"))
    hidden = int(os.environ.get("BENCH_HIDDEN", "768"))
    heads = int(os.environ.get("BENCH_HEADS", "12"))
    vocab = int(os.environ.get("BENCH_VOCAB", "32768"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    iters = int(os.environ.get("BENCH_ITERS", "30"))
    peak = float(os.environ.get("BENCH_PEAK_TFLOPS", "197")) * 1e12

    key = jax.random.PRNGKey(0)
    params = _t_init(key, vocab, seq, layers, hidden)
    mom = {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()}
    ids = jax.random.randint(key, (batch, seq), 0, vocab)
    labels = jax.random.randint(key, (batch, seq), 0, vocab)

    def loss_fn(p, ids, labels):
        logits = _t_forward(p, ids, layers, heads)
        logp = jax.nn.log_softmax(logits)
        picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)
        return -jnp.mean(picked)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(p, mom, ids, labels):
        loss, grads = jax.value_and_grad(loss_fn)(p, ids, labels)
        lr, mu = 1e-4, 0.9
        new_p, new_m = {}, {}
        for k, w in p.items():
            m = mu * mom[k] + grads[k].astype(jnp.float32)
            new_m[k] = m
            new_p[k] = (w.astype(jnp.float32) - lr * m).astype(w.dtype)
        return new_p, new_m, loss

    dump = os.environ.get("BENCH_DUMP_HLO")
    if dump:
        open(dump, "w").write(
            step.lower(params, mom, ids, labels).compile().as_text())

    for _ in range(warmup):
        params, mom, loss = step(params, mom, ids, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, mom, loss = step(params, mom, ids, labels)
    float(loss)
    dt = time.perf_counter() - t0
    tok_s = batch * seq * iters / dt
    mfu = transformer_flops_per_step(batch, seq, layers, hidden,
                                     vocab) * iters / dt / peak
    print(json.dumps({
        "metric": "transformer_ideal_tokens_per_sec",
        "value": round(tok_s, 2),
        "mfu": round(mfu, 4),
        "unit": "tokens/sec (L%d H%d T%d bs%d, bf16, pure-JAX)"
                % (layers, hidden, seq, batch)}))


def main():
    if os.environ.get("BENCH_MODEL", "resnet50") == "transformer":
        _transformer_main()
        return
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    iters = int(os.environ.get("BENCH_ITERS", "100"))
    key = jax.random.PRNGKey(0)
    params, stats = init_params(key)
    mom = {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()}
    # v2 parity: the framework feeds f32 and casts in-graph
    x_dtype = jnp.float32 if ARCH == "v2" else jnp.bfloat16
    x = jax.random.uniform(key, (batch, 224, 224, 3), x_dtype)
    labels = jax.random.randint(key, (batch,), 0, 1000)

    dump = os.environ.get("BENCH_DUMP_HLO")
    if dump:
        txt = train_step.lower(params, mom, stats, x, labels) \
            .compile().as_text()
        open(dump, "w").write(txt)

    for _ in range(warmup):
        params, mom, stats, loss = train_step(params, mom, stats, x, labels)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, mom, stats, loss = train_step(params, mom, stats, x, labels)
    float(loss)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "resnet50_ideal_img_per_sec",
        "value": round(batch * iters / dt, 2),
        "unit": "images/sec (bs%d, bf16, pure-JAX NHWC, arch=%s)"
                % (batch, ARCH)}))


if __name__ == "__main__":
    main()
